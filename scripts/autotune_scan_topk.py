#!/usr/bin/env python
"""Offline fused-kernel tile autotuner (docs/kernels.md "Autotuned
tiles").

Times candidate ``bm`` tiles for the fused scan-top-k kernel
(``kernels/scan_topk.py``) on THIS process's backend over a
``(variant, dim, dtype, k)`` grid and persists the winners into the
versioned JSON table ``kernels/autotune.py`` consults — the static
VMEM-footprint model stays the fallback for every shape the table does
not cover.  The table is additive: entries for other device kinds and
shapes are preserved, the tuned grid's keys are overwritten.

    # tune the serve-shaped defaults on the current backend
    python scripts/autotune_scan_topk.py

    # a custom grid, somewhere else
    python scripts/autotune_scan_topk.py --dims 16,32 --ks 10,128 \
        --dtypes float32,bfloat16 --variants slab --rows 100000 \
        --out /tmp/tiles.json

Run on the deployment backend: a table tuned on the CPU twin says
nothing about a TPU (entries are keyed by device kind, so a foreign
table simply never matches — the fallback rule).
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as a plain script from anywhere (the package is not installed)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _ints(s: str) -> list[int]:
    return [int(t) for t in s.split(",") if t.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="autotune_scan_topk",
        description="Tune fused scan-top-k tile sizes on the current "
                    "backend and persist the table.")
    ap.add_argument("--dims", default="16,32,64",
                    help="comma list of feature dims to tune")
    ap.add_argument("--ks", default="10,100,256",
                    help="comma list of k values to tune (256 = the "
                         "engine's worst-case sizing key)")
    ap.add_argument("--dtypes", default="float32,bfloat16",
                    help="comma list of table dtypes")
    ap.add_argument("--variants", default="slab,cand",
                    help="comma list of kernel variants (slab,cand)")
    ap.add_argument("--rows", type=int, default=65_536,
                    help="synthetic table rows per timing run")
    ap.add_argument("--batch", type=int, default=256,
                    help="query batch per timing run")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timing repeats per candidate (min wins)")
    ap.add_argument("--out", default=None,
                    help="table path (default: the consulted table — "
                         "HYPERSPACE_AUTOTUNE_TABLE or "
                         "configs/scan_topk_tiles.json)")
    ap.add_argument("--dry-run", action="store_true",
                    help="walk the grid and emit a schema-complete table "
                         "WITHOUT timing anything on a device: each entry "
                         "takes the static model's tile cap, ms=0.0, and "
                         "device_kind='dry-run' (inert — real lookups are "
                         "keyed by the actual device kind, so a dry table "
                         "never matches).  Prints to stdout unless --out "
                         "is given, so it can never clobber a real table.")
    args = ap.parse_args(argv)

    from hyperspace_tpu.kernels import autotune

    out = args.out or autotune.table_path() or autotune.default_table_path()
    variants = tuple(v.strip() for v in args.variants.split(",") if v.strip())
    for v in variants:
        if v not in autotune.VARIANTS:
            raise SystemExit(
                f"--variants {v!r}: want a subset of {autotune.VARIANTS}")
    try:
        dims, ks = _ints(args.dims), _ints(args.ks)
    except ValueError as e:
        raise SystemExit(f"bad grid list: {e}") from None
    dtypes = [t.strip() for t in args.dtypes.split(",") if t.strip()]

    if args.dry_run:
        from hyperspace_tpu.kernels import scan_topk as K

        entries = autotune.load_table(args.out) if args.out else {}
        for variant in variants:
            for dim in dims:
                for dtype in dtypes:
                    for k in ks:
                        # the static footprint cap — the largest tile a
                        # real tune would be allowed to time
                        cap = (K.fused_tile_rows(dim, dtype, k,
                                                 allow_tuned=False)
                               if variant == "slab"
                               else K.fused_cand_tile_rows(
                                   dim, dtype, k, allow_tuned=False))
                        key = autotune.entry_key(variant, dim, dtype, k,
                                                 "dry-run")
                        entries[key] = {
                            "variant": variant, "dim": int(dim),
                            "dtype": dtype, "k": int(k),
                            "device_kind": "dry-run", "bm": int(cap),
                            "ms": 0.0, "timings": {},
                        }
        doc = {"version": autotune.TABLE_VERSION, "entries": entries}
        if args.out:
            autotune.save_table(entries, args.out)
            print(f"[autotune] dry-run: {len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'} -> {args.out}")
        else:
            import json

            print(json.dumps(doc, indent=2, sort_keys=True))
        return 0

    entries = autotune.autotune(
        dims, dtypes, ks, variants=variants, rows=args.rows,
        batch=args.batch, repeats=args.repeats,
        base_entries=autotune.load_table(out))
    autotune.save_table(entries, out)
    autotune.reset_cache()  # this process sees its own fresh answers
    print(f"[autotune] {len(entries)} entr{'y' if len(entries) == 1 else 'ies'} "
          f"-> {out} (device_kind={autotune.device_kind()!r})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
