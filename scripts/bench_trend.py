#!/usr/bin/env python
"""Cross-round bench trend reports + a regression gate.

Every driver round leaves a ``BENCH_r<N>.json`` wrapper in the repo
root ({n, cmd, rc, tail, parsed}) and every local ``bench.py`` run
rewrites ``bench_full.json`` (the bare result record).  Until now the
only consumer of that trajectory was a human re-reading JSON — which is
how BENCH_r04 (rc=0, ``parsed: null``) and BENCH_r05 (rc=124) went from
"lost artifact" to "lesson" only after the fact.  This script is the
first tool that reads the trajectory:

- **trend table** (markdown to stdout by default; ``--json`` for the
  machine-readable form; ``--out-json``/``--out-md`` write files):
  per-round status (rc, parseable), the headline metric series with
  best-so-far, and every numeric detail key seen in ≥2 parseable
  rounds;
- **regression gate** (``--gate``): exits nonzero when the LATEST
  parseable value of any headline metric is more than ``--threshold``
  (default 10%) worse than the best parseable round's — the check a
  perf PR runs before shipping, instead of eyeballing.

Unparseable rounds (r04's null, r05's rc=124) are listed, never fatal:
a lost artifact must not hide the rounds around it.  Sentinel records
(``metric`` of ``error`` / ``budget_exhausted``) appear in the rounds
table but are excluded from series and gate — a watchdog's value=0 is
an incident marker, not a measurement.  The same holds for a
**budget-exhausted primary**: a record whose metric is real but whose
``detail.budget_exhausted`` is set was cut short by the watchdog (the
checked-in 1-second-budget ``bench_full.json`` test artifact is the
standing example) — its numbers are partial, so it is a rounds row but
never a series point or gate candidate.

Better/worse per metric is inferred from the name (queries/s and
samples/s up, seconds and milliseconds down — ``direction()``);
unrecognized metrics are reported but never gated.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Optional

DEFAULT_THRESHOLD = 0.10

# sentinel records a failed/overran run emits in place of a measurement
SENTINEL_METRICS = {"error", "budget_exhausted"}

# detail subtrees that are not cross-round comparable: telemetry is
# process-cumulative (warmup-diluted, run-order dependent), tracebacks
# are text
_SKIP_DETAIL_KEYS = {"telemetry", "traceback"}

_HIGHER_TOKENS = ("per_s", "per_sec", "qps", "samples", "speedup",
                  "recall", "rate", "auc", "frac", "roofline", "ratio",
                  # the r19 pod-scaling leg: scaling_efficiency (fleet
                  # throughput over N× single-process) — closer to
                  # linear is better; its multihost_ok verdict is a
                  # JSON bool and therefore never a gated series at all
                  "scaling", "efficiency")
_LOWER_TOKENS = ("time", "stall", "waste", "recompile", "epoch_s",
                 "compile", "latency", "ttfq")
# lower-better tokens that outrank the higher-better list: "ratio" is
# generically higher-better (fused/unfused speedup ratios), but a
# waste ratio is still waste; "rate" is generically higher-better
# (cache_hit_rate, qps_at_recall...), but the r13 HTTP front door's
# shed_rate / deadline_rate are failure fractions — shedding MORE is
# never an improvement (latency itself — http_p99_ms and every
# latency_ms leaf — is already lower-better via the _ms suffix);
# "overhead" likewise (the r16 observability overhead_ratio is a cost
# fraction — a bigger ratio is a slower instrumented server); the r18
# live-index freshness/staleness family is a cost too — time-to-visible
# (``upsert_visible_ms``), stale answers served (``stale_results``) —
# growing fresher-slower or staler is never an improvement; the r20
# multi-tenant leg's ``tenant_fairness`` (starved p99 over solo p99 —
# a contention-damage RATIO, so it must outrank the generic ratio
# token) and the ``starved_p99_ms`` reading behind it are both costs —
# a tenant getting more starved is never an improvement
_LOWER_PRIORITY_TOKENS = ("waste", "shed", "deadline", "overhead",
                          "fresh", "stale", "visible", "fairness",
                          "starved")
# size tokens, matched per dotted-path SEGMENT (word-boundary style: the
# segment is the token, or carries it as a ``_``-separated word) so the
# r15 big-table leg's capacity metrics — ``table_mb.int8``,
# ``table_bytes``, ``hbm_gb`` — gate lower-is-better: a table growing
# is never an improvement.  Segment matching keeps substrings inert
# ("poincare_embed..." contains "mb" but carries no ``mb`` word; plain
# substring matching would have re-directioned every *embed* metric).
# Checked AFTER the higher-better tokens: a size word does not demote a
# metric that is explicitly a quality/throughput reading — the roofline
# FRACTION ``frac_hbm_roofline`` carries the hbm word but measures how
# close to the hbm roofline the step runs (higher is better)
_LOWER_SIZE_TOKENS = ("bytes", "mb", "hbm")
_LOWER_SUFFIXES = ("_s", "_ms", "_bytes")
# leaves that are the size of a measurement's basis, not a measurement
# — fewer samples is not an improvement
_NEUTRAL_LEAVES = {"n", "count"}
# workload-shape/config leaves: constants of the run, not measurements
# — a series that can never trend is table noise, dropped entirely
_CONFIG_LEAVES = {"devices", "num_nodes", "num_edges", "num_edges_padded",
                  "num_pairs", "batch_size", "steps", "steps_per_epoch",
                  "dim", "k"}


def _size_token(key: str) -> bool:
    """True when any dotted segment carries a ``_LOWER_SIZE_TOKENS``
    word: the segment IS the token, or holds it as an underscore-
    separated word (``table_mb``, ``hbm_gb``, ``bytes_f32``)."""
    for seg in key.split("."):
        words = seg.split("_")
        if any(t in words for t in _LOWER_SIZE_TOKENS):
            return True
    return False


def direction(key: str) -> Optional[str]:
    """'higher' / 'lower' = which way is better; None = unknown (shown,
    never gated).  Higher-better tokens win first: ``samples_per_s``
    ends in ``_s`` but is a throughput.  Suffixes are matched per
    dotted segment so nested detail paths keep their unit's direction
    (``detail.latency_ms.b8.p99`` is a millisecond metric even though
    the full path ends in ``.p99``) — except sample-count leaves
    (``...latency_ms.b8.n``), which have no better direction at all."""
    k = key.lower()
    if k.rsplit(".", 1)[-1] in _NEUTRAL_LEAVES:
        return None
    if "during_rollover" in k:
        # a ``*_during_rollover`` reading inherits its base metric's
        # direction (r18 live-index leg): ``p99_during_rollover_ms``
        # is still a latency, a ``qps_during_rollover`` would still be
        # a throughput — the window qualifier carries no direction
        return direction(re.sub(r"_?during_rollover", "", k))
    if any(t in k for t in _LOWER_PRIORITY_TOKENS):
        return "lower"
    if any(t in k for t in _HIGHER_TOKENS):
        return "higher"
    if _size_token(k):
        # table-capacity metrics (the r15 beyond-HBM leg): bytes / mb /
        # hbm gate lower-is-better — ``table_mb`` growing can never
        # read as an improvement
        return "lower"
    if (any(seg.endswith(_LOWER_SUFFIXES) for seg in k.split("."))
            or any(t in k for t in _LOWER_TOKENS)):
        return "lower"
    return None


def _round_sort_key(label: str) -> tuple:
    m = re.search(r"(\d+)", label)
    # numbered driver rounds first in order; 'full' (the working-copy
    # bench_full.json) sorts last = most recent
    return (0, int(m.group(1))) if m else (1, 0)


def load_rounds(root: str) -> list[dict]:
    """One row per artifact: round label, rc, whether it parsed, and
    the parsed result record (None for the lost rounds)."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        label = os.path.basename(path)[len("BENCH_"):-len(".json")]
        row = {"round": label, "path": os.path.basename(path),
               "rc": None, "parsed": False, "record": None}
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            row["error"] = f"{type(e).__name__}: {e}"
            rounds.append(row)
            continue
        if not isinstance(doc, dict):
            row["error"] = "not a wrapper object"
            rounds.append(row)
            continue
        row["rc"] = doc.get("rc")
        rec = doc.get("parsed")
        if isinstance(rec, dict) and "metric" in rec:
            row["parsed"] = True
            row["record"] = rec
        rounds.append(row)
    full = os.path.join(root, "bench_full.json")
    if os.path.exists(full):
        row = {"round": "full", "path": "bench_full.json", "rc": None,
               "parsed": False, "record": None}
        try:
            with open(full, encoding="utf-8") as f:
                rec = json.load(f)
            if isinstance(rec, dict) and "metric" in rec:
                row["parsed"] = True
                row["record"] = rec
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            row["error"] = f"{type(e).__name__}: {e}"
        rounds.append(row)
    rounds.sort(key=lambda r: _round_sort_key(r["round"]))
    return rounds


def _flatten_numeric(tree, prefix: str = "", depth: int = 0) -> dict:
    """{dotted.path: number} over a detail dict's numeric scalar leaves
    (bools excluded — flags are config, not measurements)."""
    out: dict = {}
    if depth > 4 or not isinstance(tree, dict):
        return out
    for k, v in tree.items():
        if k in _SKIP_DETAIL_KEYS:
            continue
        path = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            if k in _CONFIG_LEAVES:
                continue
            out[path] = v
        elif isinstance(v, dict):
            out.update(_flatten_numeric(v, path + ".", depth + 1))
    return out


def build_series(rounds: list[dict]) -> dict:
    """Per-metric series over the parseable rounds.

    Headline series are keyed by the metric name itself; detail leaves
    by ``detail.<dotted.path>``.  Detail series need ≥2 points to be a
    trend; headline series are kept even as single points (the gate
    just has nothing to compare them to)."""
    headline: dict[str, list] = {}
    detail: dict[str, list] = {}
    for row in rounds:
        rec = row["record"]
        if not rec:
            continue
        metric = rec.get("metric")
        if metric in SENTINEL_METRICS or not metric:
            continue
        det = rec.get("detail")
        if isinstance(det, dict) and det.get("budget_exhausted"):
            # a watchdog-cut partial artifact (real metric, truncated
            # legs): a rounds-table row, never a series point — it must
            # not gate as the 'full' round nor set a phantom best
            continue
        value = rec.get("value")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            headline.setdefault(metric, []).append(
                {"round": row["round"], "value": value,
                 "unit": rec.get("unit", "")})
        for path, v in _flatten_numeric(rec.get("detail") or {},
                                        "detail.").items():
            detail.setdefault(path, []).append(
                {"round": row["round"], "value": v})
    series = {}
    for key, pts in headline.items():
        series[key] = _summarize(key, pts, headline=True)
    for key, pts in detail.items():
        if len(pts) >= 2:
            series[key] = _summarize(key, pts, headline=False)
    return series


def _summarize(key: str, pts: list[dict], *, headline: bool) -> dict:
    d = direction(key)
    best = None
    if d is not None:
        pick = max if d == "higher" else min
        best = pick(pts, key=lambda p: p["value"])
    latest = pts[-1]
    out = {"direction": d, "points": pts, "latest": latest,
           "headline": headline}
    if best is not None:
        out["best"] = best
        if best["value"]:
            delta = (latest["value"] - best["value"]) / abs(best["value"])
            # signed relative move of latest vs best; for lower-better
            # metrics a POSITIVE delta is the regression direction
            out["latest_vs_best_pct"] = round(delta * 100, 2)
    return out


def gate(series: dict, threshold: float) -> dict:
    """Regressions among the HEADLINE series: latest parseable value
    more than ``threshold`` worse than best-so-far."""
    regressions = []
    for key, s in series.items():
        if not s.get("headline") or "best" not in s:
            continue
        best, latest = s["best"], s["latest"]
        if latest["round"] == best["round"]:
            continue
        if best["value"]:
            rel = (latest["value"] - best["value"]) / abs(best["value"])
            worse = -rel if s["direction"] == "higher" else rel
            pct = round(worse * 100, 2)
            tripped = worse > threshold
        else:
            # best == 0: the relative move is unbounded, so ANY step in
            # the regression direction trips the gate (pct unreportable)
            diff = latest["value"] - best["value"]
            worse = -diff if s["direction"] == "higher" else diff
            pct = None
            tripped = worse > 0
        if tripped:
            regressions.append({
                "metric": key,
                "best": best, "latest": latest,
                "regression_pct": pct,
            })
    return {"threshold_pct": round(threshold * 100, 2),
            "regressions": regressions, "ok": not regressions}


def build_report(root: str, threshold: float) -> dict:
    rounds = load_rounds(root)
    series = build_series(rounds)
    def _cut_short(rec) -> bool:
        det = (rec or {}).get("detail")
        return bool(isinstance(det, dict) and det.get("budget_exhausted"))

    public_rounds = [{k: v for k, v in r.items() if k != "record"}
                     | {"metric": (r["record"] or {}).get("metric"),
                        "value": (r["record"] or {}).get("value"),
                        "budget_exhausted": _cut_short(r["record"])}
                     for r in rounds]
    return {"rounds": public_rounds, "series": series,
            "gate": gate(series, threshold)}


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def _pct(p) -> str:
    # None = regression from a zero best: relative move is unbounded
    return "inf" if p is None else f"{p:g}"


def to_markdown(report: dict) -> str:
    lines = ["# Bench trend", "", "## Rounds", "",
             "| round | rc | parsed | metric | value |",
             "|---|---|---|---|---|"]
    for r in report["rounds"]:
        lines.append(
            f"| {r['round']} | {_fmt(r['rc'])} | "
            f"{'yes' if r['parsed'] else 'NO'} | "
            f"{_fmt(r.get('metric'))} | {_fmt(r.get('value'))} |")
    head = {k: s for k, s in report["series"].items() if s["headline"]}
    lines += ["", "## Headline metrics", "",
              "| metric | better | best (round) | latest (round) "
              "| latest vs best |", "|---|---|---|---|---|"]
    for key in sorted(head):
        s = head[key]
        best = s.get("best")
        pct = s.get("latest_vs_best_pct")
        lines.append(
            f"| {key} | {_fmt(s['direction'])} | "
            + (f"{_fmt(best['value'])} ({best['round']})"
               if best else "—")
            + f" | {_fmt(s['latest']['value'])} ({s['latest']['round']})"
            + f" | {'—' if pct is None else f'{pct:+g}%'} |")
    tail = {k: s for k, s in report["series"].items()
            if not s["headline"]}
    if tail:
        lines += ["", "## Detail series (≥2 rounds)", "",
                  "| key | better | best (round) | latest (round) |",
                  "|---|---|---|---|"]
        for key in sorted(tail):
            s = tail[key]
            best = s.get("best")
            lines.append(
                f"| {key} | {_fmt(s['direction'])} | "
                + (f"{_fmt(best['value'])} ({best['round']})"
                   if best else "—")
                + f" | {_fmt(s['latest']['value'])}"
                  f" ({s['latest']['round']}) |")
    g = report["gate"]
    lines += ["", "## Gate", ""]
    if g["regressions"]:
        lines.append(f"**{len(g['regressions'])} regression(s) past "
                     f"{g['threshold_pct']:g}%:**")
        for r in g["regressions"]:
            lines.append(
                f"- `{r['metric']}`: {_fmt(r['latest']['value'])} "
                f"({r['latest']['round']}) is {_pct(r['regression_pct'])}% "
                f"worse than best {_fmt(r['best']['value'])} "
                f"({r['best']['round']})")
    else:
        lines.append(f"No headline regression past "
                     f"{g['threshold_pct']:g}% vs best-so-far.")
    return "\n".join(lines) + "\n"


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_trend",
        description="Aggregate BENCH_r*.json + bench_full.json into a "
                    "per-metric trend table; --gate fails on a "
                    "regression vs the best parseable round.")
    ap.add_argument("--dir", default=None,
                    help="repo root holding the artifacts "
                         "(default: this script's parent repo)")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report to stdout instead of "
                         "markdown")
    ap.add_argument("--out-json", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--out-md", default=None,
                    help="also write the markdown report to this path")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any headline metric's latest "
                         "parseable value is > threshold worse than the "
                         "best round's")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="gate threshold as a fraction (default 0.10)")
    args = ap.parse_args(argv)

    root = args.dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    report = build_report(root, args.threshold)
    if not report["rounds"]:
        print(f"no BENCH_r*.json / bench_full.json under {root}",
              file=sys.stderr)
        return 2

    if args.out_json:
        with open(args.out_json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    if args.out_md:
        with open(args.out_md, "w", encoding="utf-8") as f:
            f.write(to_markdown(report))
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(to_markdown(report), end="")
    if args.gate:
        g = report["gate"]
        for r in g["regressions"]:
            print(f"GATE: {r['metric']} regressed "
                  f"{_pct(r['regression_pct'])}% vs {r['best']['round']}",
                  file=sys.stderr)
        if not g["ok"]:
            return 1
        print(f"GATE: ok ({g['threshold_pct']:g}% threshold)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
