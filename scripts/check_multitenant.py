#!/usr/bin/env python
"""Smoke lint: the multi-tenant front door over the wire, as a subprocess.

Two artifacts → ONE ``serve-http`` process (``tenants=`` roster) →
route by tenant name AND by artifact fingerprint → answers bitwise
against solo engines built from the same artifacts → unknown tenants
answer the typed 404 → then a SECOND launch under a device budget that
cannot hold both engines proves the paging round trip (admissions +
evictions observed via /healthz, answers still bitwise) → SIGTERM
drain exits 0.  Asserted (exit 1 on any miss):

- ``/healthz`` lists both tenants with DISTINCT fingerprints; the
  first roster entry is the default route;
- ``POST /v1/topk`` with ``"tenant": <name>`` and with ``"tenant":
  <fingerprint>`` both route to the right engine — results bitwise
  equal (``.view(uint32)``) to a solo engine over the same artifact,
  and the no-field request answers exactly the default tenant's rows
  (cross-tenant isolation is structural: fingerprint-keyed caches,
  signature-keyed programs);
- an unregistered tenant answers ``404`` + ``error.kind =
  "unknown_tenant"`` (docs/serving.md "Error taxonomy");
- ``/v1/stats?tenant=`` answers that tenant's block;
- recompiles stay FLAT across repeated same-bucket traffic to BOTH
  resident tenants (steady state compiles nothing);
- under ``device_budget_mb=`` paging: alternating tenants records
  admissions AND evictions in the healthz summaries, and every answer
  stays bitwise-correct across the round trips;
- SIGTERM drains rc=0 with the drain notice.

Run by ``tests/serve/test_check_multitenant_script.py`` inside the
suite, mirroring ``check_serve_http.py``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from scripts.check_serve_http import (  # noqa: E402
    _StderrPump,
    _get,
    _post,
    _wait_for_port,
)

D = 16
K = 5
TENANTS = (("alpha", 600, 1.1, 3), ("beta", 600, 1.4, 7))
QUERY_IDS = [0, 3, 11, 29]


def build_table(n: int, c: float, seed: int):
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.4 * jax.random.normal(jax.random.PRNGKey(seed), (n, D),
                                jnp.float32)
    return PoincareBall(c).expmap0(v)


def _bitwise_equal(a, b) -> bool:
    import numpy as np

    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return (a.shape == b.shape
            and bool((a.view(np.uint32) == b.view(np.uint32)).all()))


def _launch(roster_path: str, budget_mb: float):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperspace_tpu.cli.serve", "serve-http",
         f"tenants={roster_path}", "port=0", "host=127.0.0.1",
         "max_wait_us=1000", "telemetry=1", "prewarm=1", f"k={K}",
         "min_bucket=8", "max_bucket=16",
         f"device_budget_mb={budget_mb}"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    pump = _StderrPump(proc)
    host, port = _wait_for_port(proc, pump)
    return proc, pump, host, port


def _drain(proc, pump) -> int:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        print("DRAIN HUNG: SIGTERM did not stop the server in 60 s")
        return 1
    err = pump.text()
    if proc.returncode != 0:
        print(f"DRAIN EXIT CODE {proc.returncode}; stderr:\n{err}")
        return 1
    if "drained" not in err:
        print(f"DRAIN NOTICE missing; stderr:\n{err}")
        return 1
    return 0


def main(out_dir: str | None = None) -> int:
    import numpy as np

    from hyperspace_tpu.serve import QueryEngine, export_artifact, \
        load_artifact

    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = tmp.name
    os.makedirs(out_dir, exist_ok=True)
    procs = []
    try:
        # --- two artifacts + in-process solo reference engines -------
        arts, solo = {}, {}
        for name, n, c, seed in TENANTS:
            path = os.path.join(out_dir, name)
            table = np.asarray(build_table(n, c, seed))
            export_artifact(path, table, ("poincare", c),
                            model_config={"c": c}, overwrite=True)
            arts[name] = path
            solo[name] = QueryEngine.from_artifact(load_artifact(path))
        expect = {name: solo[name].topk_neighbors(QUERY_IDS, K)
                  for name in solo}
        fps = {name: solo[name].fingerprint for name in solo}
        if fps["alpha"] == fps["beta"]:
            print("TEST SETUP BROKEN: both artifacts share a fingerprint")
            return 1
        roster_path = os.path.join(out_dir, "tenants.json")
        with open(roster_path, "w", encoding="utf-8") as f:
            json.dump([{"name": "alpha", "artifact": arts["alpha"],
                        "weight": 2.0, "queue_max": 64},
                       {"name": "beta", "artifact": arts["beta"],
                        "weight": 1.0}], f)

        def check_topk(host, port, payload, name, label) -> int:
            status, q = _post(host, port, "/v1/topk",
                              {**payload, "ids": QUERY_IDS, "k": K})
            if status != 200:
                print(f"{label}: topk FAILED: {status} {q}")
                return 1
            idx, dist = expect[name]
            if q["neighbors"] != np.asarray(idx).tolist():
                print(f"{label}: WRONG NEIGHBORS (cross-tenant "
                      f"leak?): {q['neighbors']} want "
                      f"{np.asarray(idx).tolist()}")
                return 1
            if not _bitwise_equal(q["dists"], dist):
                print(f"{label}: dists NOT BITWISE vs the solo engine")
                return 1
            return 0

        # ============ launch 1: unlimited budget (routing) ============
        proc, pump, host, port = _launch(roster_path, 0.0)
        procs.append(proc)
        status, health = _get(host, port, "/healthz")
        if status != 200 or health.get("ok") is not True:
            print(f"HEALTHZ BROKEN: {status} {health}")
            return 1
        summaries = {t["tenant"]: t for t in health.get("tenants", [])}
        if set(summaries) != {"alpha", "beta"}:
            print(f"HEALTHZ TENANTS wrong: {sorted(summaries)}")
            return 1
        if health.get("tenant") != "alpha":
            print(f"DEFAULT TENANT should be the first roster entry "
                  f"(alpha); got {health.get('tenant')!r}")
            return 1
        for name in summaries:
            if summaries[name].get("fingerprint") != fps[name]:
                print(f"FINGERPRINT MISMATCH for {name}: "
                      f"{summaries[name].get('fingerprint')!r}")
                return 1

        # route by name, by fingerprint, and by default — all bitwise
        for payload, name, label in (
                ({"tenant": "alpha"}, "alpha", "by-name alpha"),
                ({"tenant": "beta"}, "beta", "by-name beta"),
                ({"tenant": fps["beta"]}, "beta", "by-fingerprint beta"),
                ({}, "alpha", "default route")):
            if check_topk(host, port, payload, name, label):
                return 1

        status, r = _post(host, port, "/v1/topk",
                          {"tenant": "nobody", "ids": QUERY_IDS, "k": K})
        if status != 404 or r.get("error", {}).get("kind") != \
                "unknown_tenant":
            print(f"UNKNOWN TENANT should answer 404/unknown_tenant: "
                  f"{status} {r}")
            return 1

        status, st = _get(host, port, "/v1/stats?tenant=beta")
        if status != 200 or st.get("registry", {}).get("tenant") != "beta":
            print(f"PER-TENANT STATS broken: {status} "
                  f"{st.get('registry')}")
            return 1

        # steady state: repeated same-bucket traffic to both resident
        # tenants compiles nothing
        status, st0 = _post(host, port, "/v1/stats", {})
        for _ in range(3):
            for payload, name in (({"tenant": "alpha"}, "alpha"),
                                  ({"tenant": "beta"}, "beta")):
                if check_topk(host, port, payload, name,
                              f"steady {name}"):
                    return 1
        status, st1 = _post(host, port, "/v1/stats", {})
        if st1["recompiles"] != st0["recompiles"]:
            print(f"RECOMPILES NOT FLAT in steady state: "
                  f"{st0['recompiles']} -> {st1['recompiles']}")
            return 1
        if _drain(proc, pump):
            return 1

        # ============ launch 2: budget forces engine paging ===========
        # each table is 600×16 f32 = 37.5 KiB, so 0.05 MiB (51.2 KiB)
        # holds one engine but never both — alternating tenants must
        # page (the artifact stays the host master; answers stay bitwise)
        proc, pump, host, port = _launch(roster_path, 0.05)
        procs.append(proc)
        for round_i in range(2):
            for payload, name in (({"tenant": "alpha"}, "alpha"),
                                  ({"tenant": "beta"}, "beta")):
                if check_topk(host, port, payload, name,
                              f"paged round {round_i} {name}"):
                    return 1
        status, health = _get(host, port, "/healthz")
        summaries = {t["tenant"]: t for t in health.get("tenants", [])}
        admits = sum(t.get("admissions", 0) for t in summaries.values())
        evicts = sum(t.get("evictions", 0) for t in summaries.values())
        if not (admits > 0 and evicts > 0):
            print(f"PAGING NEVER HAPPENED under the budget: "
                  f"admissions={admits} evictions={evicts} {summaries}")
            return 1
        if _drain(proc, pump):
            return 1
        print(f"multi-tenant front door OK: routed by name+fingerprint "
              f"(bitwise vs solo), unknown tenant 404, recompiles flat "
              f"steady, paging round trip ({admits} admits / {evicts} "
              f"evicts) bitwise, drained clean x2")
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
