"""On-chip Pallas kernel smoke: every N1-N7 kernel lowered through Mosaic on
the real TPU, compared against its pure-JAX twin on identical inputs.

Prints one JSON line per kernel: {"kernel", "max_err", "ok"}.  Run with the
default (axon/TPU) backend:

    PYTHONPATH="/root/repo:$PYTHONPATH" python scripts/tpu_kernel_smoke.py

The kernel/twin switch is the HYPERSPACE_KERNELS env var read at trace time
(kernels/_support.mode), so each op is evaluated eagerly twice — once forced
'pallas', once forced 'xla' — inside one process.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np


def run(name, fn, tol=5e-4):
    """Mixed abs/rel check: |pallas - xla| / max(|xla|, 1) < tol.

    Relative for amplified quantities (MLR logits reach O(100) for points
    near the boundary; TPU transcendental precision gives ~1e-4 relative),
    absolute for O(1) outputs — one formula covers both.
    """
    os.environ["HYPERSPACE_KERNELS"] = "pallas"
    out_p = np.asarray(jax.device_get(fn()), np.float64)
    os.environ["HYPERSPACE_KERNELS"] = "xla"
    out_x = np.asarray(jax.device_get(fn()), np.float64)
    err = float(np.max(np.abs(out_p - out_x) / np.maximum(np.abs(out_x), 1.0)))
    ok = bool(err < tol and np.isfinite(out_p).all())
    print(json.dumps({"kernel": name, "max_err": err, "ok": ok}), flush=True)
    return ok


def main():
    from hyperspace_tpu import kernels as K
    from hyperspace_tpu.kernels.segment import build_csr_plan, csr_segment_sum
    from hyperspace_tpu.manifolds import Lorentz, PoincareBall

    assert jax.default_backend() != "cpu", "smoke needs the TPU backend"
    key = jax.random.PRNGKey(0)
    ks = list(jax.random.split(key, 16))
    ball, lor = PoincareBall(1.0), Lorentz(1.0)
    c = 1.0
    B, D = 256, 48

    x = ball.random_normal(ks[0], (B, D), jnp.float32, std=0.3)
    y = ball.random_normal(ks[1], (B, D), jnp.float32, std=0.3)
    v = 0.3 * jax.random.normal(ks[2], (B, D), jnp.float32)
    r = 0.7  # kernel N2 takes a scalar multiplier

    oks = [
        run("mobius_add", lambda: K.mobius_add(x, y, c)),
        run("mobius_scalar_mul", lambda: K.mobius_scalar_mul(r, x, c)),
        run("expmap", lambda: K.expmap(x, v, c)),
        run("logmap", lambda: K.logmap(x, y, c)),
        run("expmap0", lambda: K.expmap0(v, c)),
        run("logmap0", lambda: K.logmap0(y, c)),
        run("ptransp", lambda: K.ptransp(x, y, v, c)),
        run("poincare_pdist", lambda: K.poincare_pdist(x, y, c)),
    ]

    lx = lor.random_normal(ks[4], (B, D + 1), jnp.float32, std=0.3)
    ly = lor.random_normal(ks[5], (B, D + 1), jnp.float32, std=0.3)
    oks.append(run("lorentz_pdist", lambda: K.lorentz_pdist(lx, ly, c)))

    m = 0.2 * jax.random.normal(ks[6], (D, 32), jnp.float32)
    b = ball.random_normal(ks[7], (32,), jnp.float32, std=0.1)
    oks.append(run("hyp_linear", lambda: K.hyp_linear(x, m, b, c)))

    p = ball.random_normal(ks[8], (16, D), jnp.float32, std=0.2)
    a = 0.3 * jax.random.normal(ks[9], (16, D), jnp.float32)
    oks.append(run("hyp_mlr", lambda: K.hyp_mlr(x, p, a, c)))

    q = lor.random_normal(ks[10], (2, 128, 17), jnp.float32, std=0.3)
    kk = lor.random_normal(ks[11], (2, 128, 17), jnp.float32, std=0.3)
    oks.append(run("flash_attention",
                   lambda: K.flash_attention(q, kk, kk, c)))

    # large batch×heads: regression for the β/τ SMEM windowing (a whole
    # [B, 1] SMEM block overflowed the 1 MB budget at B ≈ 1k)
    qb = lor.random_normal(ks[12], (1024, 32, 17), jnp.float32, std=0.3)
    kb = lor.random_normal(ks[13], (1024, 32, 17), jnp.float32, std=0.3)
    oks.append(run("flash_attention_B1024",
                   lambda: K.flash_attention(qb, kb, kb, c)))

    rng = np.random.default_rng(0)
    recv = np.sort(rng.integers(0, 200, 1024)).astype(np.int32)
    vals = jnp.asarray(rng.normal(size=(1024, 64)).astype(np.float32))
    plan = tuple(jnp.asarray(a_) for a_ in build_csr_plan(recv, 200))
    recv_d = jnp.asarray(recv)
    oks.append(run("csr_segment_sum",
                   lambda: csr_segment_sum(vals, recv_d, plan, 200)))

    # scalar CSR reductions: the lane-partial accumulator layout is exactly
    # what interpret mode can't exercise — real-chip parity matters here
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    svals = jnp.asarray(rng.normal(size=(1024,)).astype(np.float32))
    oks.append(run("csr_segment_reduce_1d_sum",
                   lambda: csr_segment_reduce_1d(svals, recv_d, plan, 200,
                                                 op="sum")))
    # empty segments: the kernel's contract is the finite NEG_FILL
    # sentinel where XLA's segment_max gives -inf — clamp both so the
    # comparison tests the real values, not the sentinel encodings
    from hyperspace_tpu.kernels.segment import NEG_FILL

    oks.append(run("csr_segment_reduce_1d_max",
                   lambda: jnp.maximum(
                       csr_segment_reduce_1d(svals, recv_d, plan, 200,
                                             op="max"), NEG_FILL)))

    # cluster-pair SpMM kernel (r03): one-hot matmuls over VMEM tiles,
    # f32 and the fast single-pass bf16 mode
    from hyperspace_tpu.kernels.cluster import (
        build_cluster_plan,
        cluster_aggregate,
    )

    n_cl = 700
    r_cl = rng.integers(0, n_cl, 4096).astype(np.int32)
    s_cl = rng.integers(0, n_cl, 4096).astype(np.int32)
    from hyperspace_tpu.kernels import cluster as CL

    key_cl = ((r_cl // CL._BN).astype(np.int64) * (n_cl // CL._BS + 1)
              + s_cl // CL._BS)
    o_cl = np.argsort(key_cl, kind="stable")
    r_cl, s_cl = r_cl[o_cl], s_cl[o_cl]
    w_cl = jnp.asarray(rng.random(4096).astype(np.float32))
    h_cl = jnp.asarray(rng.normal(size=(n_cl, 64)).astype(np.float32))
    cplan = tuple(jnp.asarray(a_)
                  for a_ in build_cluster_plan(r_cl, s_cl, n_cl))
    r_cld, s_cld = jnp.asarray(r_cl), jnp.asarray(s_cl)
    oks.append(run("cluster_aggregate_f32",
                   lambda: cluster_aggregate(h_cl, w_cl, r_cld, s_cld,
                                             cplan, n_cl)))
    h_bf = h_cl.astype(jnp.bfloat16)
    oks.append(run("cluster_aggregate_bf16",
                   lambda: cluster_aggregate(h_bf, w_cl, r_cld, s_cld,
                                             cplan, n_cl), tol=2e-2))

    # fused scan-top-k (r12): the twin is bitwise by construction on
    # CPU-interpret — the chip run is the Mosaic-lowering check the
    # interpreter can't give (docs/kernels.md "Twin contract").  Compare
    # distances (f32 contract, tol covers transcendental drift); the
    # int ids ride along in the distance comparison (a rank flip would
    # change a distance by a visible gap on this point scale).
    from hyperspace_tpu.kernels import scan_topk as ST

    st_tab = ball.random_normal(ks[14], (1024, 16), jnp.float32, std=0.3)
    st_qi = jnp.arange(64, dtype=jnp.int32)
    st_q = st_tab[st_qi]
    oks.append(run("scan_topk",
                   lambda: ST.scan_topk(st_tab, st_q, st_qi, 0,
                                        spec=("poincare", 1.0), k=10,
                                        n=1024, exclude_self=True,
                                        tile_rows=512)[0]))
    st_cand = jnp.asarray(rng.integers(0, 1024, (64, 256)).astype(np.int32))
    oks.append(run("scan_topk_cand",
                   lambda: ST.scan_topk_cand(st_tab, st_cand, st_q, st_qi,
                                             spec=("poincare", 1.0),
                                             k=5)[0]))

    print(json.dumps({"all_ok": all(oks), "backend": jax.default_backend()}),
          flush=True)
    sys.exit(0 if all(oks) else 1)


if __name__ == "__main__":
    main()
