"""Generate golden constants for tests/manifolds/test_golden.py.

Implements the *published* closed forms (Ganea et al. 2018; Nickel &
Kiela 2018) directly in mpmath at 50 digits — deliberately independent of
hyperspace_tpu, so the goldens catch silent formula drift in the library
(SURVEY.md §4.3).  Run and paste the printed block into the test.

    python scripts/gen_golden.py
"""

from mpmath import (acos, acosh, asinh, atanh, cos, cosh, mp, mpf, sin,
                    sinh, sqrt, tanh)

mp.dps = 50


def dot(a, b):
    return sum(x * y for x, y in zip(a, b))


def nrm(a):
    return sqrt(dot(a, a))


def mobius_add(x, y, c):
    """(x ⊕_c y) — Ganea et al. 2018 eq. (1)."""
    xy, x2, y2 = dot(x, y), dot(x, x), dot(y, y)
    den = 1 + 2 * c * xy + c * c * x2 * y2
    cx = (1 + 2 * c * xy + c * y2) / den
    cy = (1 - c * x2) / den
    return [cx * xi + cy * yi for xi, yi in zip(x, y)]


def poincare_dist(x, y, c):
    """d_c(x,y) = (2/√c)·artanh(√c‖(−x)⊕_c y‖) — Ganea eq. (2)."""
    z = mobius_add([-xi for xi in x], y, c)
    return (2 / sqrt(c)) * atanh(sqrt(c) * nrm(z))


def poincare_expmap(x, v, c):
    """exp_x(v) = x ⊕_c (tanh(√c·λ_x‖v‖/2)·v/(√c‖v‖)) — Ganea eq. (8)."""
    lam = 2 / (1 - c * dot(x, x))
    nv = nrm(v)
    t = tanh(sqrt(c) * lam * nv / 2) / (sqrt(c) * nv)
    return mobius_add(x, [t * vi for vi in v], c)


def gyration(a, b, v, c):
    """gyr[a,b]v = −(a⊕b) ⊕ (a ⊕ (b ⊕ v)) (Ungar)."""
    ab = mobius_add(a, b, c)
    inner = mobius_add(a, mobius_add(b, v, c), c)
    return mobius_add([-t for t in ab], inner, c)


def poincare_ptransp(x, y, v, c):
    """P_{x→y}(v) = (λ_x/λ_y)·gyr[y, −x]v — Ganea eq. (after 10)."""
    lx = 2 / (1 - c * dot(x, x))
    ly = 2 / (1 - c * dot(y, y))
    g = gyration(y, [-t for t in x], v, c)
    return [(lx / ly) * t for t in g]


def mlr_logit(x, p, a, c):
    """Ganea et al. 2018 eq. (25)."""
    z = mobius_add([-t for t in p], x, c)
    lam_p = 2 / (1 - c * dot(p, p))
    na = nrm(a)
    arg = 2 * sqrt(c) * dot(z, a) / ((1 - c * dot(z, z)) * na)
    return (lam_p * na / sqrt(c)) * asinh(arg)


def ldot(x, y):
    """Minkowski inner product, time coordinate first."""
    return -x[0] * y[0] + dot(x[1:], y[1:])


def lorentz_point(space, c):
    """Lift a space vector onto {⟨x,x⟩_L = −1/c}, time first."""
    t = sqrt(1 / c + dot(space, space))
    return [t] + list(space)


def lorentz_dist(x, y, c):
    """d = (1/√c)·arcosh(−c⟨x,y⟩_L) — Nickel & Kiela 2018."""
    return acosh(-c * ldot(x, y)) / sqrt(c)


def lorentz_expmap(x, v, c):
    """exp_x(v) = cosh(√c‖v‖_L)x + sinh(√c‖v‖_L)v/(√c‖v‖_L)."""
    nv = sqrt(ldot(v, v))
    s = sqrt(c) * nv
    return [cosh(s) * xi + sinh(s) * vi / s for xi, vi in zip(x, v)]


def sphere_point(theta, phi, c):
    """Spherical coordinates on the radius-1/√c sphere in R³."""
    r = 1 / sqrt(c)
    return [r * sin(theta) * cos(phi), r * sin(theta) * sin(phi),
            r * cos(theta)]


def sphere_dist(x, y, c):
    """Great-circle distance: r·angle = arccos(c⟨x,y⟩)/√c."""
    return acos(c * dot(x, y)) / sqrt(c)


def fmt(v):
    if isinstance(v, list):
        return "[" + ", ".join(fmt(t) for t in v) + "]"
    return mp.nstr(v, 20)


if __name__ == "__main__":
    c1, c2 = mpf(1), mpf("0.7")
    x = [mpf("0.3"), mpf("-0.2"), mpf("0.1")]
    y = [mpf("-0.5"), mpf("0.1"), mpf("0.4")]
    v = [mpf("0.25"), mpf("0.4"), mpf("-0.1")]
    p = [mpf("0.1"), mpf("0.2"), mpf("-0.3")]
    a = [mpf("0.8"), mpf("-0.5"), mpf("0.2")]

    print("POINCARE_DIST_C1  =", fmt(poincare_dist(x, y, c1)))
    print("POINCARE_DIST_C07 =", fmt(poincare_dist(x, y, c2)))
    print("POINCARE_EXPMAP_C1  =", fmt(poincare_expmap(x, v, c1)))
    print("POINCARE_EXPMAP_C07 =", fmt(poincare_expmap(x, v, c2)))
    print("POINCARE_PTRANSP_C1 =", fmt(poincare_ptransp(x, y, v, c1)))
    print("MLR_LOGIT_C1  =", fmt(mlr_logit(x, p, a, c1)))
    print("MLR_LOGIT_C07 =", fmt(mlr_logit(x, p, a, c2)))

    lx = lorentz_point(x, c1)
    ly = lorentz_point(y, c1)
    print("LORENTZ_X_C1 =", fmt(lx))
    print("LORENTZ_Y_C1 =", fmt(ly))
    print("LORENTZ_DIST_C1 =", fmt(lorentz_dist(lx, ly, c1)))
    lx2 = lorentz_point(x, c2)
    ly2 = lorentz_point(y, c2)
    print("LORENTZ_DIST_C07 =", fmt(lorentz_dist(lx2, ly2, c2)))
    # tangent at lx: project v' = v - <x,v>_L / <x,x>_L x  (time-first)
    v4 = [mpf(0)] + v
    coef = ldot(lx, v4) * c1  # <x,x>_L = -1/c ⇒ proj = v + c<x,v> x
    tv = [vi + coef * xi for vi, xi in zip(v4, lx)]
    print("LORENTZ_TANGENT_C1 =", fmt(tv))
    print("LORENTZ_EXPMAP_C1 =", fmt(lorentz_expmap(lx, tv, c1)))

    sx = sphere_point(mpf("0.4"), mpf("1.1"), c2)
    sy = sphere_point(mpf("1.3"), mpf("-0.5"), c2)
    print("SPHERE_X_C07 =", fmt(sx))
    print("SPHERE_Y_C07 =", fmt(sy))
    print("SPHERE_DIST_C07 =", fmt(sphere_dist(sx, sy, c2)))
    # same points rescaled onto the unit sphere
    s = sqrt(c2)
    print("SPHERE_DIST_C1 =", fmt(sphere_dist(
        [v * s for v in sx], [v * s for v in sy], c1)))
