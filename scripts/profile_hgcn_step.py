"""Decompose the HGCN LP train-step time on the live backend.

Times (min over repeats, 10 chained calls per repeat, scalar-fetch
barrier): encoder forward, full forward (encoder + decoder), loss+grad,
and the full train step — the differences isolate decoder, backward, and
optimizer cost.  One JSON line per probe.
"""

from __future__ import annotations

import json
import time


def timed(fn, *args, steps=10, repeats=3):
    import jax

    out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best / steps


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    num_nodes = HB.ARXIV_NODES
    split, x = HB.arxiv_scale_split(num_nodes)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(128, 32),
                          kind="lorentz")
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    n_pairs = train_pos.shape[0]
    pairs2 = jnp.concatenate([train_pos, train_pos], axis=0)

    enc = jax.jit(lambda p, g: hgcn.HGCNEncoder(cfg).apply(  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
        {"params": p["encoder"]}, g)[0].sum())
    fwd = jax.jit(lambda p, g, pr: model.apply({"params": p}, g, pr).sum())  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process

    def loss_fn(p, g, pr):
        logits = model.apply({"params": p}, g, pr)
        labels = jnp.concatenate(
            [jnp.ones(n_pairs), jnp.zeros(n_pairs)]).astype(logits.dtype)
        return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))

    @jax.jit  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
    def grad(p, g, pr):
        # return a scalar depending on every grad leaf so nothing is DCE'd
        l, gr = jax.value_and_grad(loss_fn)(p, g, pr)
        return l + sum(jnp.sum(x) for x in jax.tree_util.tree_leaves(gr))

    from hyperspace_tpu.nn.scatter import sym_segment_aggregate

    h0 = jnp.zeros((num_nodes, 128), jnp.float32)
    w0 = ga.edge_mask.astype(jnp.float32)
    pb, pc, pf = ga.plan

    @jax.jit  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
    def agg_fwd_bwd(h):
        def f(hh):
            out = sym_segment_aggregate(hh, w0, ga.senders, ga.receivers,
                                        ga.rev_perm, pb, pc, pf, num_nodes,
                                        False)
            return jnp.sum(out * out)
        l, g_ = jax.value_and_grad(f)(h)
        return l + jnp.sum(g_)

    probes = {
        "encoder_fwd": lambda: enc(state.params, ga),
        "full_fwd": lambda: fwd(state.params, ga, pairs2),
        "loss_grad": lambda: grad(state.params, ga, pairs2),
        "one_agg_fwd_bwd": lambda: agg_fwd_bwd(h0),
    }
    for name, fn in probes.items():
        t = timed(fn)
        print(json.dumps({"probe": name, "time_s": round(t, 5)}), flush=True)

    def step(st):
        return hgcn.train_step_lp(model, opt, num_nodes, st, ga, train_pos)

    st, loss = step(state)
    jax.device_get(loss)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            st, loss = step(st)
        jax.device_get(loss)
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({"probe": "train_step", "time_s": round(best / 10, 5)}),
          flush=True)

    try:
        from hyperspace_tpu.train.profiling import cost_analysis_dict

        cost = cost_analysis_dict(
            jax.jit(lambda st: step(st)).lower(st).compile())
        print(json.dumps({"probe": "xla_cost",
                          "flops": cost.get("flops"),
                          "bytes": cost.get("bytes accessed")}), flush=True)
    except Exception as e:
        print(json.dumps({"probe": "xla_cost", "error": repr(e)}), flush=True)


if __name__ == "__main__":
    main()
