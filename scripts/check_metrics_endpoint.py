#!/usr/bin/env python
"""Smoke lint: the live observability plane, as a real subprocess.

export → ``serve-http`` with ``access_log=`` + ``window_s=`` on an
ephemeral port → scrape ``GET /metrics`` twice around real traffic →
SIGTERM drain.  Asserted (exit 1 on any miss):

- the exposition parses as Prometheus text (v0.0.4): HELP/TYPE per
  family, every sample labeled with ``process_index``;
- **catalog round trip, both directions**: every family's HELP line
  carries the ORIGINAL registry name, which must be a backticked token
  in docs/observability.md's catalogs (an exposed-but-undocumented
  metric is exactly what the telemetry-catalog lint exists to stop),
  and re-sanitizing that original reproduces the family name (no
  collisions across families);
- **counters are monotone** between the two scrapes;
- a topk request carrying ``X-Request-Id`` gets the SAME id echoed in
  the response header, ``/v1/stats`` reports the windowed SLO block
  with a populated distribution, and after drain the access log holds
  one line for that id with its route, flush id, and e2e latency —
  the Dapper-style join this plane exists for.

Run by ``tests/serve/test_check_metrics_script.py`` inside tier-1,
mirroring ``check_serve_http.py``, so an observability regression
fails the build.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

N, D, C = 123, 8, 1.1
K = 5
LISTEN_DEADLINE_S = 120.0
_SAMPLE_RX = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def build_table():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.5 * jax.random.normal(jax.random.PRNGKey(7), (N, D), jnp.float32)
    return PoincareBall(C).expmap0(v)


def parse_exposition(text: str) -> dict:
    """{family: {"help": str, "type": str, "samples": {(name, labels):
    float}}} — a minimal, order-free parser of the text format."""
    fams: dict = {}
    cur = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            cur = fams.setdefault(name, {"help": None, "type": None,
                                         "samples": {}})
            cur["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            fams.setdefault(name, {"help": None, "type": None,
                                   "samples": {}})["type"] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RX.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {lineno}: "
                             f"{line!r}")
        sample, labels, value = m.group(1), m.group(2) or "", m.group(3)
        # histogram samples (_bucket/_sum/_count) attach to their family
        fam = sample
        for suffix in ("_bucket", "_sum", "_count"):
            if sample.endswith(suffix) and sample[: -len(suffix)] in fams:
                fam = sample[: -len(suffix)]
                break
        if fam not in fams:
            raise ValueError(
                f"sample {sample!r} before any HELP/TYPE (line {lineno})")
        fams[fam]["samples"][(sample, labels)] = float(value)
    return fams


def _get(host, port, path, headers=None):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read().decode(), dict(resp.getheaders())
    finally:
        conn.close()


def _post(host, port, path, payload, headers=None):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        body = json.dumps(payload).encode()
        hs = {"Content-Type": "application/json"}
        hs.update(headers or {})
        conn.request("POST", path, body=body, headers=hs)
        resp = conn.getresponse()
        return (resp.status, json.loads(resp.read().decode()),
                dict(resp.getheaders()))
    finally:
        conn.close()


def _wait_for_port(proc, err_path: str) -> tuple[str, int]:
    """Poll the file-backed stderr for the 'listening on HOST:PORT'
    line, HARD-bounded — file-backed (not a pipe) so a wedged-but-
    silent server can neither block a readline nor deadlock the drain
    wait on a full pipe (the check_serve_http pump, without the
    thread)."""
    deadline = time.monotonic() + LISTEN_DEADLINE_S
    while time.monotonic() < deadline:
        with open(err_path, encoding="utf-8") as f:
            for line in f:
                if "listening on" in line:
                    hostport = line.strip().rsplit(" ", 1)[-1]
                    host, _, port = hostport.rpartition(":")
                    return host, int(port)
        if proc.poll() is not None:
            with open(err_path, encoding="utf-8") as f:
                tail = f.read()[-800:]
            raise RuntimeError(
                f"server died rc={proc.returncode} before listening:\n"
                f"{tail}")
        time.sleep(0.25)
    raise RuntimeError("no listening line within the deadline")


def main(out_dir: str | None = None) -> int:
    from hyperspace_tpu.serve import export_artifact
    from hyperspace_tpu.telemetry.exposition import sanitize_name

    with open(os.path.join(ROOT, "docs", "observability.md"),
              encoding="utf-8") as f:
        documented = set(re.findall(r"`([^`\s]+)`", f.read()))

    table = build_table()
    import numpy as np

    table = np.asarray(table)
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = tmp.name
    art_dir = os.path.join(out_dir, "artifact")
    access_path = os.path.join(out_dir, "access.jsonl")
    proc = None
    try:
        export_artifact(art_dir, table, ("poincare", C),
                        model_config={"c": C}, overwrite=True)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        err_path = os.path.join(out_dir, "server.stderr")
        with open(err_path, "w", encoding="utf-8") as errf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "hyperspace_tpu.cli.serve",
                 "serve-http", f"artifact={art_dir}", "port=0",
                 "host=127.0.0.1", "max_wait_us=1000", "prewarm=1",
                 f"access_log={access_path}", "window_s=30", f"k={K}"],
                cwd=ROOT, env=env, stdout=subprocess.DEVNULL,
                stderr=errf, text=True)
        host, port = _wait_for_port(proc, err_path)

        # --- scrape 1: before traffic ---------------------------------
        status, text1, _hdr = _get(host, port, "/metrics")
        if status != 200:
            print(f"/metrics SCRAPE 1 FAILED: {status}")
            return 1
        fams1 = parse_exposition(text1)
        if not fams1:
            print("/metrics EMPTY on scrape 1")
            return 1

        # --- request with a traced id ---------------------------------
        rid = "smoke-req-0001"
        status, resp, hdrs = _post(host, port, "/v1/topk",
                                   {"ids": [0, 1, 2], "k": K},
                                   headers={"X-Request-Id": rid})
        if status != 200 or len(resp.get("neighbors", [])) != 3:
            print(f"TOPK FAILED: {status} {resp}")
            return 1
        echoed = {k.lower(): v for k, v in hdrs.items()}.get(
            "x-request-id")
        if echoed != rid:
            print(f"REQUEST ID NOT ECHOED: sent {rid!r}, got {echoed!r}")
            return 1
        # an anonymous request still gets a generated id echoed
        status, _resp, hdrs = _post(host, port, "/v1/topk",
                                    {"ids": [5, 6], "k": K})
        gen = {k.lower(): v for k, v in hdrs.items()}.get("x-request-id")
        if status != 200 or not gen:
            print(f"GENERATED ID MISSING: {status} {gen!r}")
            return 1

        # --- windowed SLO block in stats ------------------------------
        status, stats, _ = _post(host, port, "/v1/stats", {})
        win = stats.get("window")
        if status != 200 or not isinstance(win, dict):
            print(f"NO WINDOW BLOCK in stats: {status} {win}")
            return 1
        e2e = win.get("e2e_ms")
        if not e2e or e2e.get("count", 0) < 1 or not e2e.get("p99"):
            print(f"WINDOW DISTRIBUTION EMPTY after traffic: {win}")
            return 1

        # --- scrape 2: after traffic ----------------------------------
        status, text2, _ = _get(host, port, "/metrics")
        if status != 200:
            print(f"/metrics SCRAPE 2 FAILED: {status}")
            return 1
        fams2 = parse_exposition(text2)

        # catalog round trip, both directions
        seen_original = {}
        for fam, info in fams2.items():
            original = info["help"]
            if not original:
                print(f"FAMILY {fam} HAS NO HELP LINE")
                return 1
            if original not in documented:
                print(f"EXPOSED-BUT-UNDOCUMENTED metric: {fam} "
                      f"(registry name {original!r} has no backticked "
                      "row in docs/observability.md)")
                return 1
            if sanitize_name(original) != fam:
                print(f"SANITIZE ROUND TRIP BROKEN: {original!r} -> "
                      f"{sanitize_name(original)!r} != {fam!r}")
                return 1
            if original in seen_original:
                print(f"FAMILY COLLISION: {original!r} renders as both "
                      f"{seen_original[original]!r} and {fam!r}")
                return 1
            seen_original[original] = fam
        # counters monotone between scrapes
        for fam, info in fams1.items():
            if info["type"] != "counter":
                continue
            for key, v1 in info["samples"].items():
                v2 = fams2.get(fam, {}).get("samples", {}).get(key)
                if v2 is not None and v2 < v1:
                    print(f"COUNTER WENT BACKWARDS: {key} {v1} -> {v2}")
                    return 1
        # the serve traffic must be visible in the delta
        req_fam = sanitize_name("serve/requests")
        n1 = sum(fams1.get(req_fam, {}).get("samples", {}).values())
        n2 = sum(fams2.get(req_fam, {}).get("samples", {}).values())
        if not n2 > n1:
            print(f"serve/requests NOT MONOTONE-INCREASING: {n1} -> {n2}")
            return 1
        # the e2e histogram must expose cumulative buckets
        e2e_fam = sanitize_name("serve/e2e_ms")
        f2 = fams2.get(e2e_fam)
        if (f2 is None or f2["type"] != "histogram"
                or not any(s.endswith("_bucket")
                           for s, _l in f2["samples"])):
            print(f"serve/e2e_ms NOT EXPOSED AS HISTOGRAM: {f2}")
            return 1

        # --- drain, then join the access log --------------------------
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            print("DRAIN HUNG")
            return 1
        if proc.returncode != 0:
            with open(err_path, encoding="utf-8") as f:
                tail = f.read()[-800:]
            print(f"DRAIN EXIT CODE {proc.returncode}:\n{tail}")
            return 1
        with open(access_path, encoding="utf-8") as f:
            records = [json.loads(line) for line in f if line.strip()]
        mine = [r for r in records if r.get("request_id") == rid]
        if len(mine) != 1:
            print(f"ACCESS LOG LINES for {rid!r}: {len(mine)} (want 1); "
                  f"log holds {len(records)} records")
            return 1
        rec = mine[0]
        bad = [field for field in ("route", "outcome", "e2e_ms",
                                   "queue_wait_ms", "bucket",
                                   "cache_hits", "cache_misses",
                                   "degrade_level")
               if field not in rec]
        if bad or rec["route"] != "topk" or rec["outcome"] != "ok":
            print(f"ACCESS RECORD MALFORMED (missing {bad}): {rec}")
            return 1
        if rec.get("flush_id") is None:
            print(f"ACCESS RECORD HAS NO FLUSH ID (cold topk must ride "
                  f"a collator flush): {rec}")
            return 1
        print(f"metrics endpoint OK: {len(fams2)} families, "
              f"{len(records)} access record(s), request {rid} joined "
              f"to flush {rec['flush_id']} at e2e {rec['e2e_ms']} ms, "
              f"windowed p99 {e2e['p99']} ms")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
