"""Full-scale quality anchor for the neighbor-sampled trainer.

Trains on the arxiv-density synthetic graph (169 343 nodes) two ways —
the full-graph step and the neighbor-sampled minibatch step —
evaluating BOTH with the full-graph model (the param trees are
identical), and records (wall seconds, quality) curves.  This answers
the question the throughput number alone cannot: does sampled training
reach the same operating point, and how fast in wall-clock?

``--task nc`` (default) anchors node classification (val/test acc);
``--task lp`` anchors the north-star link-prediction task (val/test
ROC-AUC) — VERDICT r4 #7.

Writes JSONL records to --out (default docs/data/sampled_quality_r03.jsonl;
use docs/data/sampled_quality_lp_r05.jsonl for the LP run) and prints a
final summary line per arm.  Run on the TPU chip.
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="default derives from --task so an LP run can "
                         "never truncate the committed NC artifact")
    ap.add_argument("--task", choices=["nc", "lp"], default="nc")
    ap.add_argument("--num-nodes", type=int, default=169_343)
    ap.add_argument("--full-steps", type=int, default=800)
    ap.add_argument("--sampled-epochs", type=int, default=24)
    ap.add_argument("--plan-steps", type=int, default=512)
    # minibatch gradients are noisier than the full-batch gradient: at
    # the shared default lr=1e-2 the sampled arm oscillates without
    # converging (measured: val acc 0.3-0.76 swings); 3e-3 and 1e-3 both
    # reach the full-graph arm's plateau exactly
    ap.add_argument("--sampled-lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.out is None:
        args.out = ("docs/data/sampled_quality_r03.jsonl"
                    if args.task == "nc"
                    else "docs/data/sampled_quality_lp_r05.jsonl")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.benchmarks.hgcn_bench import arxiv_scale_graph
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn
    from hyperspace_tpu.models import hgcn_sampled as HS

    n = args.num_nodes
    edges, x, labels, ncls = arxiv_scale_graph(n, seed=args.seed)
    tr, va, te = G.node_split_masks(n, seed=args.seed)
    base = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(128, 32),
                           num_classes=ncls if args.task == "nc" else 0)
    out = open(args.out, "w")  # one run = one file; re-runs replace, not
    # append — the committed docs/data artifact must match one run

    def emit(rec):
        rec["ts"] = time.time()
        out.write(json.dumps(rec) + "\n")
        out.flush()
        print(json.dumps(rec))

    if args.task == "lp":
        _run_lp(args, emit, edges, x, n, base, hgcn, HS, G, jax, jnp, np)
        return

    g = G.prepare(edges, n, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    ga = G.to_device(g)
    full_eval_model = hgcn.HGCNNodeClf(base)

    # --- arm 1: full-graph step -------------------------------------------
    model, opt, state = hgcn.init_nc(base, g, seed=args.seed)
    lab = jnp.asarray(g.labels)
    mask = jnp.asarray(g.train_mask)
    state, loss = hgcn.train_step_nc(model, opt, state, ga, lab, mask)
    jax.device_get(loss)  # compile outside the timed region
    train_wall, seg0 = 0.0, time.perf_counter()
    for step in range(args.full_steps):
        state, loss = hgcn.train_step_nc(model, opt, state, ga, lab, mask)
        # always emit the final step, whether or not it lands on the
        # 100-step cadence — trailing steps must be timed and evaluated
        if (step + 1) % 100 == 0 or step + 1 == args.full_steps:
            jax.device_get(loss)
            train_wall += time.perf_counter() - seg0  # eval time excluded
            m = hgcn.evaluate_nc(full_eval_model, state.params, g, ga=ga)
            emit({"arm": "full_graph", "step": step + 1,
                  "wall_s": round(train_wall, 2), "loss": float(loss), **m})
            seg0 = time.perf_counter()

    # --- arm 2: sampled minibatch step ------------------------------------
    import dataclasses

    sbase = dataclasses.replace(base, lr=args.sampled_lr)
    scfg = HS.SampledConfig(base=sbase, fanouts=(10, 10), batch_size=512)
    smodel, sopt, sstate = HS.init_sampled_nc(
        scfg, feat_dim=x.shape[1], seed=args.seed)
    batches, deg = HS.plan_batches(scfg, edges, labels, tr, n,
                                   steps=args.plan_steps, seed=args.seed)
    xt = jnp.asarray(np.asarray(x, np.float32))
    sstate, losses = HS.train_epoch_sampled_nc(smodel, sopt, sstate, xt,
                                               deg, batches)
    jax.device_get(losses[-1])  # compile
    # fresh state so the compile pass doesn't count as training
    _, _, sstate = HS.init_sampled_nc(scfg, feat_dim=x.shape[1],
                                      seed=args.seed)
    train_wall, seg0 = 0.0, time.perf_counter()
    for ep in range(args.sampled_epochs):
        sstate, losses = HS.train_epoch_sampled_nc(smodel, sopt, sstate, xt,
                                                   deg, batches)
        jax.device_get(losses[-1])
        train_wall += time.perf_counter() - seg0  # eval time excluded
        m = hgcn.evaluate_nc(full_eval_model, sstate.params, g, ga=ga)
        emit({"arm": "sampled", "step": (ep + 1) * args.plan_steps,
              "wall_s": round(train_wall, 2), "loss": float(losses[-1]), **m})
        seg0 = time.perf_counter()


def _run_lp(args, emit, edges, x, n, base, hgcn, HS, G, jax, jnp, np):
    """LP twin of the NC anchor (VERDICT r4 #7): full-graph LP vs
    sampled-LP to the same ROC-AUC plateau, wall-clock per eval point.
    Both arms evaluate through the full-graph HGCNLinkPred on identical
    param trees."""
    import dataclasses

    split = G.split_edges(edges, n, x, seed=args.seed, pad_multiple=65536)
    ga = hgcn._device_graph(split.graph)
    full_model = hgcn.HGCNLinkPred(base)

    def auc(params, which):
        return hgcn.evaluate_lp(full_model, params, split, which,
                                ga=ga)["roc_auc"]

    # --- arm 1: full-graph LP step ---------------------------------------
    model, opt, state = hgcn.init_lp(base, split.graph, seed=args.seed)
    train_pos = jnp.asarray(split.train_pos)
    state, loss = hgcn.train_step_lp(model, opt, n, state, ga, train_pos)
    jax.device_get(loss)  # compile outside the timed region
    train_wall, seg0 = 0.0, time.perf_counter()
    for step in range(args.full_steps):
        state, loss = hgcn.train_step_lp(model, opt, n, state, ga,
                                         train_pos)
        if (step + 1) % 100 == 0 or step + 1 == args.full_steps:
            jax.device_get(loss)
            train_wall += time.perf_counter() - seg0  # eval excluded
            emit({"arm": "full_graph", "task": "lp", "step": step + 1,
                  "wall_s": round(train_wall, 2), "loss": float(loss),
                  "val_auc": round(auc(state.params, "val"), 4),
                  "test_auc": round(auc(state.params, "test"), 4)})
            seg0 = time.perf_counter()

    # --- arm 2: sampled-LP minibatch step --------------------------------
    sbase = dataclasses.replace(base, lr=args.sampled_lr)
    scfg = HS.SampledConfig(base=sbase, fanouts=(10, 10), batch_size=512)
    smodel, sopt, sstate = HS.init_sampled_lp(
        scfg, feat_dim=x.shape[1], seed=args.seed)
    lb, ldeg = HS.plan_lp_batches(scfg, split.train_pos, n,
                                  steps=args.plan_steps, seed=args.seed)
    xt = jnp.asarray(np.asarray(x, np.float32))
    sstate, losses = HS.train_epoch_sampled_lp(smodel, sopt, sstate, xt,
                                               ldeg, lb)
    jax.device_get(losses[-1])  # compile
    _, _, sstate = HS.init_sampled_lp(scfg, feat_dim=x.shape[1],
                                      seed=args.seed)
    train_wall, seg0 = 0.0, time.perf_counter()
    for ep in range(args.sampled_epochs):
        sstate, losses = HS.train_epoch_sampled_lp(smodel, sopt, sstate,
                                                   xt, ldeg, lb)
        jax.device_get(losses[-1])
        train_wall += time.perf_counter() - seg0  # eval excluded
        emit({"arm": "sampled", "task": "lp",
              "step": (ep + 1) * args.plan_steps,
              "wall_s": round(train_wall, 2), "loss": float(losses[-1]),
              "val_auc": round(auc(sstate.params, "val"), 4),
              "test_auc": round(auc(sstate.params, "test"), 4)})
        seg0 = time.perf_counter()


if __name__ == "__main__":
    main()
