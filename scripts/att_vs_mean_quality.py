"""Does mean aggregation match attention aggregation's quality? (VERDICT r1 #5)

The bench reports the mean-aggregation HGCN (797 k samples/s/chip); the
attention path — closest to Chami et al.'s config — runs at ~321 k.  The
honest options are (a) bench attention, or (b) show mean-agg reaches the
same converged quality on the eval fixtures.  This script measures (b):
same split, use_att False vs True, several seeds, converged test ROC-AUC
on hierarchy graphs (LP) plus NC accuracy.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/att_vs_mean_quality.py --nodes 4096 --steps 400
"""

from __future__ import annotations

import argparse
import json


def run_lp(use_att: bool, nodes: int, steps: int, seed: int):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=nodes, feat_dim=16, ancestor_hops=4, seed=seed)
    split = G.split_edges(edges, nodes, x, seed=seed)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(64, 16),
                          kind="lorentz", use_att=use_att)
    model, params, _ = hgcn.train_lp(cfg, split, steps=steps, seed=seed)
    ev = hgcn.evaluate_lp(model, params, split, "test")
    return {"task": "lp", "use_att": use_att, "seed": seed,
            "test_roc_auc": round(ev["roc_auc"], 4)}


def run_nc(use_att: bool, nodes: int, steps: int, seed: int):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=nodes, feat_dim=16, ancestor_hops=4, seed=seed)
    tr, va, te = G.node_split_masks(nodes, seed=seed)
    g = G.prepare(edges, nodes, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(64, 16),
                          kind="lorentz", use_att=use_att,
                          num_classes=ncls)
    model, params, res = hgcn.train_nc(cfg, g, steps=steps, seed=seed)
    return {"task": "nc", "use_att": use_att, "seed": seed,
            "test_acc": round(res["test_acc"], 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--seeds", type=int, default=3)
    args = ap.parse_args()

    import numpy as np

    acc = {("lp", False): [], ("lp", True): [], ("nc", False): [],
           ("nc", True): []}
    for seed in range(args.seeds):
        for use_att in (False, True):
            r = run_lp(use_att, args.nodes, args.steps, seed)
            acc[("lp", use_att)].append(r["test_roc_auc"])
            print(json.dumps(r), flush=True)
            r = run_nc(use_att, args.nodes, args.steps, seed)
            acc[("nc", use_att)].append(r["test_acc"])
            print(json.dumps(r), flush=True)
    summary = {f"{t}_{'att' if a else 'mean'}":
               round(float(np.mean(v)), 4) for (t, a), v in acc.items()}
    print(json.dumps({"summary": summary}), flush=True)


if __name__ == "__main__":
    main()
