#!/usr/bin/env python
"""Lint shim: every telemetry counter/gauge in code is documented.

The implementation moved to the AST rule ``telemetry-catalog`` in
``hyperspace_tpu/analysis/rules/catalog.py`` (docs/static-analysis.md)
— structural matching of ``inc``/``set_gauge`` writes and namespaced
``get("ns/name")`` reads, plus the ``# telemetry-catalog: name`` escape
for dynamic names.  This script keeps the original CLI contract (same
scan set — the package plus the repo-root ``bench.py`` — same exit
codes, same helper functions) for ``tests/telemetry/test_catalog.py``
and any callers of the old path; ``python -m hyperspace_tpu.analysis
--rules telemetry-catalog`` is the first-class entry point.
"""

from __future__ import annotations

import os
import sys


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


if repo_root() not in sys.path:  # standalone `python scripts/...` runs
    sys.path.insert(0, repo_root())

from hyperspace_tpu.analysis.rules.catalog import (  # noqa: E402,F401
    counters_in_code,
    documented_names as _documented_in_text,
)


def documented_names(doc_path: str) -> set[str]:
    """Names carried in the catalog doc (any backticked token)."""
    with open(doc_path, encoding="utf-8") as f:
        return _documented_in_text(f.read())


def main() -> int:
    root = repo_root()
    pkg = os.path.join(root, "hyperspace_tpu")
    doc = os.path.join(root, "docs", "observability.md")
    if not os.path.exists(doc):
        print(f"missing catalog doc: {doc}")
        return 1
    found = counters_in_code(pkg)
    documented = documented_names(doc)
    missing = {k: v for k, v in found.items() if k not in documented}
    if missing:
        print("telemetry counters incremented in code but missing from "
              "docs/observability.md's catalog:")
        for name in sorted(missing):
            sites = ", ".join(missing[name][:3])
            print(f"  {name}  ({sites})")
        return 1
    print(f"telemetry catalog OK: {len(found)} names, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
