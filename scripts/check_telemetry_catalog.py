#!/usr/bin/env python
"""Lint: every telemetry counter/gauge incremented in code is documented.

The counter catalog in docs/observability.md is the contract consumers
(dashboards, the bench, humans reading a JSONL) rely on; an undocumented
counter is invisible telemetry.  This script scans every ``.py`` under
``hyperspace_tpu/`` — plus the repo-root ``bench.py``, which reads
registry names of its own (the ``serve_qps`` leg) — for literal
``inc("name")`` / ``set_gauge("name")`` calls AND namespaced
``get("ns/name")`` reads, and fails (exit 1, listing offenders) unless
each name appears in the catalog doc — so a consumer reading a typo'd
counter (which silently returns 0) fails the lint too.  Run by
``tests/telemetry/test_catalog.py`` inside the suite, so adding a
counter without its doc row fails the build.

Dynamically-built names can't be scanned; keep registry names literal
(they are today) or add the doc row and a ``# telemetry-catalog: name``
comment the scanner also picks up.
"""

from __future__ import annotations

import os
import re
import sys

_CALL = re.compile(r"""\b(?:inc|set_gauge)\(\s*["']([^"']+)["']""")
# registry READS too: get("ns/name") / snapshot-dict .get("ns/name").
# Requiring a "/" keeps ordinary dict .get("key") calls out — every
# registry name is namespaced, plain dict keys are not — so a consumer
# reading a typo'd (hence undocumented) counter name fails the lint.
_READ = re.compile(r"""\bget\(\s*["']([^"'\s]+/[^"'\s]+)["']""")
_ANNOT = re.compile(r"#\s*telemetry-catalog:\s*(\S+)")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scan_file(path: str, rel: str, found: dict[str, list[str]]) -> None:
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            for rx in (_CALL, _READ, _ANNOT):
                for m in rx.finditer(line):
                    found.setdefault(m.group(1), []).append(f"{rel}:{lineno}")


def counters_in_code(pkg_dir: str) -> dict[str, list[str]]:
    """{counter name: [file:line, ...]} for every literal registry call
    under the package, plus the repo-root ``bench.py`` (its serve leg
    participates in the same registry)."""
    found: dict[str, list[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            _scan_file(path, os.path.relpath(path, os.path.dirname(pkg_dir)),
                       found)
    bench = os.path.join(os.path.dirname(pkg_dir), "bench.py")
    if os.path.exists(bench):
        _scan_file(bench, "bench.py", found)
    return found


def documented_names(doc_path: str) -> set[str]:
    """Names carried in the catalog doc (any backticked token)."""
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    return set(re.findall(r"`([^`\s]+)`", text))


def main() -> int:
    root = repo_root()
    pkg = os.path.join(root, "hyperspace_tpu")
    doc = os.path.join(root, "docs", "observability.md")
    if not os.path.exists(doc):
        print(f"missing catalog doc: {doc}")
        return 1
    found = counters_in_code(pkg)
    documented = documented_names(doc)
    missing = {k: v for k, v in found.items() if k not in documented}
    if missing:
        print("telemetry counters incremented in code but missing from "
              "docs/observability.md's catalog:")
        for name in sorted(missing):
            sites = ", ".join(missing[name][:3])
            print(f"  {name}  ({sites})")
        return 1
    print(f"telemetry catalog OK: {len(found)} names, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
