#!/usr/bin/env python
"""Lint: every telemetry counter/gauge incremented in code is documented.

The counter catalog in docs/observability.md is the contract consumers
(dashboards, the bench, humans reading a JSONL) rely on; an undocumented
counter is invisible telemetry.  This script scans every ``.py`` under
``hyperspace_tpu/`` for literal ``inc("name")`` / ``set_gauge("name")``
calls and fails (exit 1, listing offenders) unless each name appears in
the catalog doc.  Run by ``tests/telemetry/test_catalog.py`` inside the
suite, so adding a counter without its doc row fails the build.

Dynamically-built names can't be scanned; keep registry names literal
(they are today) or add the doc row and a ``# telemetry-catalog: name``
comment the scanner also picks up.
"""

from __future__ import annotations

import os
import re
import sys

_CALL = re.compile(r"""\b(?:inc|set_gauge)\(\s*["']([^"']+)["']""")
_ANNOT = re.compile(r"#\s*telemetry-catalog:\s*(\S+)")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def counters_in_code(pkg_dir: str) -> dict[str, list[str]]:
    """{counter name: [file:line, ...]} for every literal registry call."""
    found: dict[str, list[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    for rx in (_CALL, _ANNOT):
                        for m in rx.finditer(line):
                            found.setdefault(m.group(1), []).append(
                                f"{rel}:{lineno}")
    return found


def documented_names(doc_path: str) -> set[str]:
    """Names carried in the catalog doc (any backticked token)."""
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    return set(re.findall(r"`([^`\s]+)`", text))


def main() -> int:
    root = repo_root()
    pkg = os.path.join(root, "hyperspace_tpu")
    doc = os.path.join(root, "docs", "observability.md")
    if not os.path.exists(doc):
        print(f"missing catalog doc: {doc}")
        return 1
    found = counters_in_code(pkg)
    documented = documented_names(doc)
    missing = {k: v for k, v in found.items() if k not in documented}
    if missing:
        print("telemetry counters incremented in code but missing from "
              "docs/observability.md's catalog:")
        for name in sorted(missing):
            sites = ", ".join(missing[name][:3])
            print(f"  {name}  ({sites})")
        return 1
    print(f"telemetry catalog OK: {len(found)} names, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
