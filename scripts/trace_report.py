#!/usr/bin/env python
"""Summarize a span/access-log JSONL into per-stage latency tables.

Input: a JSONL written by the serve access log (``access_log=``), the
slow-query log (``slow_log=``), or a flight-recorder incident dump —
any mix of records is fine; lines without the relevant fields are
skipped (a trace report must summarize whatever evidence exists, not
demand a pristine capture).

Two views:

1. **Stage table** — every record's ``stages`` dict (the batcher's
   boundary decomposition: queue_wait / collate_wait / dispatch /
   serialize, which sum to ``e2e_ms`` exactly) aggregated into one row
   per stage: count, mean, p99, and share of total time.  This is the
   "where does the latency GO" answer over a whole capture.

2. **Span rollup** — every record's ``span`` tree (attached to failed/
   slow requests and incident dumps when ``trace=1``) walked
   depth-first into a flamegraph-style indented table: one row per
   span PATH (``request/dispatch/device_compute``), with count and
   total/mean self-time — nested stages (device_compute, rescore
   inside dispatch) show up here even though the boundary table can't
   carry them.

Usage::

    python scripts/trace_report.py runs/access.jsonl [more.jsonl ...]

Exit codes: 0 with at least one summarizable record, 1 when the input
held none (a report silently rendered from nothing would read as "no
latency anywhere").
"""

from __future__ import annotations

import json
import sys


def read_records(paths: list) -> list:
    """Every JSON object line across the inputs; non-JSON lines skip
    (incident dumps open with a header line — that header is itself
    JSON and simply carries no stages, so it falls through later)."""
    records = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict):
                    records.append(obj)
    return records


def _p99(values: list) -> float:
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(0.99 * (len(vs) - 1) + 0.999999))]


def stage_table(records: list) -> list:
    """[(stage, count, mean_ms, p99_ms, share), ...] from the records'
    ``stages`` dicts, in pipeline order (unknown stages append in
    first-seen order — forward-compatible with new stages)."""
    order = ["queue_wait", "collate_wait", "dispatch", "serialize"]
    per: dict = {}
    for rec in records:
        st = rec.get("stages")
        if not isinstance(st, dict):
            continue
        for name, ms in st.items():
            if isinstance(ms, (int, float)) and not isinstance(ms, bool):
                per.setdefault(name, []).append(float(ms))
                if name not in order:
                    order.append(name)
    total = sum(sum(v) for v in per.values()) or 1.0
    out = []
    for name in order:
        vs = per.get(name)
        if not vs:
            continue
        out.append((name, len(vs), sum(vs) / len(vs), _p99(vs),
                    sum(vs) / total))
    return out


def _walk(span: dict, prefix: str, acc: dict) -> None:
    name = span.get("name", "?")
    path = f"{prefix}/{name}" if prefix else name
    dur = span.get("dur_ms")
    kids = span.get("children") or []
    child_ms = sum(k.get("dur_ms") or 0.0 for k in kids)
    if isinstance(dur, (int, float)):
        # self time: the span minus its children — a flamegraph's
        # "where is the time actually spent" column (floored at 0: a
        # thread-adopted child can straddle its parent's close)
        acc.setdefault(path, []).append(max(0.0, float(dur) - child_ms))
    for k in kids:
        if isinstance(k, dict):
            _walk(k, path, acc)


def span_rollup(records: list) -> list:
    """[(path, depth, count, total_self_ms, mean_self_ms), ...] over
    every ``span`` tree in the records, paths in depth-first order of
    first appearance."""
    acc: dict = {}
    for rec in records:
        span = rec.get("span") or rec.get("trigger_span")
        if isinstance(span, dict):
            _walk(span, "", acc)
    out = []
    for path in acc:
        vs = acc[path]
        depth = path.count("/")
        out.append((path, depth, len(vs), sum(vs), sum(vs) / len(vs)))
    return out


def render(records: list) -> str:
    lines = []
    stages = stage_table(records)
    if stages:
        lines.append(f"stage breakdown over {max(n for _, n, *_ in stages)}"
                     " record(s):")
        lines.append(f"  {'stage':<16} {'count':>7} {'mean_ms':>10} "
                     f"{'p99_ms':>10} {'share':>7}")
        for name, n, mean, p99, share in stages:
            lines.append(f"  {name:<16} {n:>7} {mean:>10.3f} "
                         f"{p99:>10.3f} {share:>6.1%}")
    rollup = span_rollup(records)
    if rollup:
        if lines:
            lines.append("")
        lines.append(f"span rollup over "
                     f"{sum(1 for r in records if r.get('span') or r.get('trigger_span'))}"
                     " tree(s) (self time):")
        lines.append(f"  {'span':<40} {'count':>7} {'total_ms':>10} "
                     f"{'mean_ms':>10}")
        for path, depth, n, total, mean in rollup:
            label = "  " * depth + path.rsplit("/", 1)[-1]
            lines.append(f"  {label:<40} {n:>7} {total:>10.3f} "
                         f"{mean:>10.3f}")
    return "\n".join(lines)


def main(argv: list) -> int:
    if not argv:
        print("usage: trace_report.py ACCESS_OR_SLOW_OR_INCIDENT.jsonl "
              "[...]", file=sys.stderr)
        return 1
    records = read_records(argv)
    text = render(records)
    if not text:
        print("no stage/span records found in "
              + ", ".join(argv), file=sys.stderr)
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
