#!/usr/bin/env python
"""Lint: serving-artifact export → load is the identity, bit for bit.

The serving contract (docs/serving.md) is that an exported artifact
answers queries EXACTLY like the live params it froze — same bytes in,
same executable, same bits out.  This script builds a deterministic
Poincaré table, exports it, loads it back, and runs 10 top-k queries
(varying batch sizes and k) through engines on the live table and on
the loaded artifact; any bit difference in neighbors or distances — or
a fingerprint drift — fails (exit 1).  A second artifact ships an IVF
index (serve/index.py) and must reproduce its fingerprints, keep
assignment totality, and answer ``nprobe=ncells`` (the degenerate
probe) bitwise-identically to the exact engine.  Run by
``tests/serve/test_check_script.py`` inside the suite, mirroring the
telemetry-catalog lint, so a serialization regression fails the build.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N, D, C = 97, 8, 1.3
QUERIES = [  # (q_ids, k) — 10 queries over several buckets and ks
    ([0, 1, 2], 5),
    ([3], 1),
    ([10, 20, 30, 40, 50], 5),
    ([7, 7, 9], 3),
    (list(range(16)), 5),
    ([96, 95], 8),
    ([11], 5),
    ([42, 13, 77, 5], 5),
    (list(range(30, 60)), 2),
    ([64, 32, 16, 8, 4, 2, 1], 7),
]


def build_table():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.5 * jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)
    return PoincareBall(C).expmap0(v)


def _check_index_round_trip(table, spec, out_dir: str, live) -> int:
    """Export-with-index → load → degenerate-probe identity.

    Builds a small IVF index, ships it inside a second artifact, loads
    it back, and verifies (a) assignment totality survived the round
    trip (every row id appears in exactly one cell), (b) the index and
    artifact fingerprints reproduce, and (c) top-k at ``nprobe=ncells``
    is BITWISE-identical to the exact engine — probing every cell
    covers every row, so the engine serves the degenerate probe through
    the exact executable by design (docs/serving.md "Approximate
    retrieval"); the identity is the cheapest end-to-end check that the
    index loads, validates against the table, and plugs into the query
    path.
    """
    import numpy as np

    from hyperspace_tpu.serve import (QueryEngine, build_index,
                                      export_artifact, load_artifact)

    idx = build_index(table, spec, 8, iters=4, seed=0)
    exported = export_artifact(out_dir, table, spec, index=idx,
                               overwrite=True)
    loaded = load_artifact(out_dir)
    if loaded.index is None or loaded.index.fingerprint != idx.fingerprint:
        print("INDEX DRIFT: loaded index fingerprint != built index")
        return 1
    if loaded.fingerprint != exported.fingerprint:
        print("FINGERPRINT DRIFT: exported-with-index != loaded")
        return 1
    if loaded.fingerprint == live.fingerprint:
        print("FINGERPRINT BUG: index artifact hashes like the bare table")
        return 1
    cell_ids = np.sort(loaded.index.cells[loaded.index.cells >= 0])
    if not np.array_equal(cell_ids, np.arange(table.shape[0])):
        print("INDEX TOTALITY BROKEN: cells do not cover each row once")
        return 1
    probed = QueryEngine.from_artifact(loaded, nprobe=loaded.index.ncells)
    if probed.scan_strategy != "exact":
        print("DEGENERATE PROBE not routed to the exact program")
        return 1
    for qi, (ids, k) in enumerate(QUERIES):
        q = np.asarray(ids, np.int32)
        li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
        pi, pd = (np.asarray(a) for a in probed.topk_neighbors(q, k))
        if not np.array_equal(li, pi) or not np.array_equal(
                ld.view(np.uint32), pd.view(np.uint32)):
            print(f"index query {qi}: nprobe=ncells differs from exact")
            return 1
    return 0


def main(out_dir: str | None = None) -> int:
    import numpy as np

    from hyperspace_tpu.serve import (QueryEngine, export_artifact,
                                      load_artifact)

    table = np.asarray(build_table())
    spec = ("poincare", C)
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = os.path.join(tmp.name, "artifact")
    try:
        exported = export_artifact(out_dir, table, spec,
                                   model_config={"c": C}, overwrite=True)
        loaded = load_artifact(out_dir)
        if loaded.fingerprint != exported.fingerprint:
            print(f"FINGERPRINT DRIFT: exported {exported.fingerprint} "
                  f"!= loaded {loaded.fingerprint}")
            return 1
        live = QueryEngine(table, spec)
        served = QueryEngine.from_artifact(loaded)
        if live.fingerprint != served.fingerprint:
            print("FINGERPRINT DRIFT: live engine != loaded engine")
            return 1
        for qi, (ids, k) in enumerate(QUERIES):
            q = np.asarray(ids, np.int32)
            li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
            si, sd = (np.asarray(a) for a in served.topk_neighbors(q, k))
            if not np.array_equal(li, si):
                print(f"query {qi}: neighbor indices differ\n{li}\nvs\n{si}")
                return 1
            if not np.array_equal(ld.view(np.uint32), sd.view(np.uint32)):
                print(f"query {qi}: distances differ bitwise\n{ld}\nvs\n{sd}")
                return 1
        rc = _check_index_round_trip(table, spec, out_dir + ".ivf", live)
        if rc:
            return rc
        print(f"serve artifact round-trip OK: {len(QUERIES)} queries "
              f"bit-identical (N={N}, D={D}, fingerprint "
              f"{loaded.fingerprint[:12]}…)")
        return 0
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
