#!/usr/bin/env python
"""Lint: serving-artifact export → load is the identity, bit for bit.

The serving contract (docs/serving.md) is that an exported artifact
answers queries EXACTLY like the live params it froze — same bytes in,
same executable, same bits out.  This script builds a deterministic
Poincaré table, exports it, loads it back, and runs 10 top-k queries
(varying batch sizes and k) through engines on the live table and on
the loaded artifact; any bit difference in neighbors or distances — or
a fingerprint drift — fails (exit 1).  A second artifact ships an IVF
index (serve/index.py) and must reproduce its fingerprints, keep
assignment totality, and answer ``nprobe=ncells`` (the degenerate
probe) bitwise-identically to the exact engine.  A third pair of
artifacts ship the sub-int8 quant payloads (int4 packed nibbles, PQ
codes + codebooks — serve/quant.py): each must reproduce its payload
and artifact fingerprints, rank queries exactly like the live f32
engine through the over-fetch + f32-rescore contract, and REJECT a
tampered codebook/scale byte at load.  A fourth leg exercises the
MUTABLE round trip (serve/delta.py): load → upsert + delete → compact,
recall vs a rebuilt-from-scratch oracle, plus the cache-isolation
proof — a result cached before a mutation must be unreachable after
it, and the pre-compaction fingerprint must no longer answer.  Run by
``tests/serve/test_check_script.py`` inside the suite, mirroring the
telemetry-catalog lint, so a serialization regression fails the build.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N, D, C = 97, 8, 1.3
QUERIES = [  # (q_ids, k) — 10 queries over several buckets and ks
    ([0, 1, 2], 5),
    ([3], 1),
    ([10, 20, 30, 40, 50], 5),
    ([7, 7, 9], 3),
    (list(range(16)), 5),
    ([96, 95], 8),
    ([11], 5),
    ([42, 13, 77, 5], 5),
    (list(range(30, 60)), 2),
    ([64, 32, 16, 8, 4, 2, 1], 7),
]


def build_table():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.5 * jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)
    return PoincareBall(C).expmap0(v)


def _check_index_round_trip(table, spec, out_dir: str, live) -> int:
    """Export-with-index → load → degenerate-probe identity.

    Builds a small IVF index, ships it inside a second artifact, loads
    it back, and verifies (a) assignment totality survived the round
    trip (every row id appears in exactly one cell), (b) the index and
    artifact fingerprints reproduce, and (c) top-k at ``nprobe=ncells``
    is BITWISE-identical to the exact engine — probing every cell
    covers every row, so the engine serves the degenerate probe through
    the exact executable by design (docs/serving.md "Approximate
    retrieval"); the identity is the cheapest end-to-end check that the
    index loads, validates against the table, and plugs into the query
    path.
    """
    import numpy as np

    from hyperspace_tpu.serve import (QueryEngine, build_index,
                                      export_artifact, load_artifact)

    idx = build_index(table, spec, 8, iters=4, seed=0)
    exported = export_artifact(out_dir, table, spec, index=idx,
                               overwrite=True)
    loaded = load_artifact(out_dir)
    if loaded.index is None or loaded.index.fingerprint != idx.fingerprint:
        print("INDEX DRIFT: loaded index fingerprint != built index")
        return 1
    if loaded.fingerprint != exported.fingerprint:
        print("FINGERPRINT DRIFT: exported-with-index != loaded")
        return 1
    if loaded.fingerprint == live.fingerprint:
        print("FINGERPRINT BUG: index artifact hashes like the bare table")
        return 1
    cell_ids = np.sort(loaded.index.cells[loaded.index.cells >= 0])
    if not np.array_equal(cell_ids, np.arange(table.shape[0])):
        print("INDEX TOTALITY BROKEN: cells do not cover each row once")
        return 1
    probed = QueryEngine.from_artifact(loaded, nprobe=loaded.index.ncells)
    if probed.scan_strategy != "exact":
        print("DEGENERATE PROBE not routed to the exact program")
        return 1
    for qi, (ids, k) in enumerate(QUERIES):
        q = np.asarray(ids, np.int32)
        li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
        pi, pd = (np.asarray(a) for a in probed.topk_neighbors(q, k))
        if not np.array_equal(li, pi) or not np.array_equal(
                ld.view(np.uint32), pd.view(np.uint32)):
            print(f"index query {qi}: nprobe=ncells differs from exact")
            return 1
    return 0


def _check_quant_round_trip(table, spec, out_dir: str, live) -> int:
    """Export-with-quant-payload → load → serve-lane rank agreement.

    For each sub-int8 lane (``int4``, ``pq``): build the payload, ship
    it inside an artifact, load it back, and verify (a) the payload and
    artifact fingerprints reproduce (and differ from the bare table's),
    (b) an engine served from the loaded payload returns EXACTLY the
    live f32 engine's neighbor ids — at this table size every lane's
    over-fetch window absorbs the coarse pass's quantization error, and
    the distances come from the f32 rescore, equal to the exact scan up
    to a few ULPs — and (c) a flipped codebook/scale byte is rejected
    at load (the payload hash covers the array bytes: docs/serving.md
    "Sub-int8 lanes").
    """
    import numpy as np

    from hyperspace_tpu.serve import (QueryEngine, build_quant_payload,
                                      export_artifact, load_artifact)
    from hyperspace_tpu.serve.artifact import QUANT_FILE

    for lane in ("int4", "pq"):
        d = f"{out_dir}.{lane}"
        payload = build_quant_payload(np.asarray(table), spec, lane)
        exported = export_artifact(d, table, spec, quant=payload,
                                   overwrite=True)
        loaded = load_artifact(d)
        if loaded.quant is None or \
                loaded.quant.fingerprint != payload.fingerprint:
            print(f"{lane}: QUANT DRIFT: loaded payload fingerprint "
                  f"!= built payload")
            return 1
        if loaded.fingerprint != exported.fingerprint:
            print(f"{lane}: FINGERPRINT DRIFT: exported-with-quant != loaded")
            return 1
        if loaded.fingerprint == live.fingerprint:
            print(f"{lane}: FINGERPRINT BUG: quant artifact hashes like "
                  f"the bare table")
            return 1
        served = QueryEngine.from_artifact(loaded, precision=lane)
        if served.precision != lane:
            print(f"{lane}: loaded engine serves {served.precision!r}")
            return 1
        for qi, (ids, k) in enumerate(QUERIES):
            q = np.asarray(ids, np.int32)
            li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
            si, sd = (np.asarray(a) for a in served.topk_neighbors(q, k))
            if not np.array_equal(li, si):
                print(f"{lane} query {qi}: neighbor ranks differ from "
                      f"the live f32 engine\n{li}\nvs\n{si}")
                return 1
            if not np.allclose(ld, sd, rtol=5e-6, atol=1e-8):
                print(f"{lane} query {qi}: rescored distances drift "
                      f"beyond ULP noise\n{ld}\nvs\n{sd}")
                return 1
        # tamper detection: flip one byte of the trained arrays on disk
        qpath = os.path.join(d, QUANT_FILE)
        with np.load(qpath) as z:
            arrays = {name: np.array(z[name]) for name in z.files}
        key = "codebooks" if lane == "pq" else "scale"
        raw = arrays[key].view(np.uint8).reshape(-1).copy()
        raw[0] ^= 0xFF
        arrays[key] = raw.view(arrays[key].dtype).reshape(
            arrays[key].shape)
        np.savez(qpath, **arrays)
        try:
            load_artifact(d)
        except ValueError:
            pass
        else:
            print(f"{lane}: TAMPER NOT DETECTED: a flipped {key} byte "
                  f"loaded cleanly")
            return 1
    return 0


def _check_mutable_round_trip(table, spec, out_dir: str) -> int:
    """Export → load → live mutations → compact → oracle agreement.

    Loads the exported artifact into a :class:`LiveQueryEngine`
    (serve/delta.py), applies upserts (new contiguous rows near known
    anchors + in-place updates) and deletes, compacts, and verifies:
    (a) recall@k against an oracle engine REBUILT FROM SCRATCH over the
    final master table (deleted ids host-filtered from an overfetched
    frozen top-k) is exact, before and after compaction; (b) the
    pre-mutation result cache can no longer answer — the generation-
    folded scan signature keys every mutation into a fresh cache row,
    so a batcher primed before the upsert MUST miss after it
    (cache-isolation proof), and the pre-compaction fingerprint is gone
    from the engine's identity after the swap; (c) tombstoned ids are
    rejected as query anchors and never returned as neighbors.
    """
    import numpy as np

    from hyperspace_tpu.parallel.host_table import HostEmbedTable
    from hyperspace_tpu.serve import (LiveQueryEngine, QueryEngine,
                                      RequestBatcher, export_artifact,
                                      load_artifact)
    from hyperspace_tpu.telemetry import registry as telem

    export_artifact(out_dir, table, spec, overwrite=True)
    loaded = load_artifact(out_dir)
    arr0 = np.array(loaded.table, np.float32)
    live = LiveQueryEngine(QueryEngine.from_artifact(loaded),
                           HostEmbedTable.from_array(np.array(arr0)),
                           capacity=64, auto_compact=False)
    k, rng = 5, np.random.default_rng(3)

    def oracle_recall(eng, deleted) -> float:
        """recall@k of ``eng`` vs a frozen engine rebuilt from the
        final master (overfetch + host-side tombstone filter)."""
        probe = np.asarray(
            [i for i in range(eng.num_nodes) if i not in deleted][:32],
            np.int64)
        oracle = QueryEngine(np.array(eng.master.to_array()), spec)
        li, _ = eng.topk_neighbors(probe, k)
        oi, _ = oracle.topk_neighbors(probe, k + len(deleted))
        hits = 0
        for r in range(probe.size):
            want = [j for j in np.asarray(oi)[r].tolist()
                    if j not in deleted][:k]
            hits += len(set(np.asarray(li)[r].tolist()) & set(want))
        return hits / (probe.size * k)

    # --- cache-isolation proof: prime, mutate, MUST miss --------------
    bat = RequestBatcher(live, cache_size=256)
    reg = telem.default_registry()
    bat.topk([3], k)                      # prime
    h0 = reg.get("serve/cache_hit")
    bat.topk([3], k)                      # same key: a hit
    if reg.get("serve/cache_hit") != h0 + 1:
        print("MUTABLE: cache prime did not hit on the unchanged engine")
        return 1
    anchor = 7
    vec = arr0[anchor] + 1e-4 * rng.standard_normal(D).astype(np.float32)
    live.upsert([N], vec[None, :])        # first insert: generation bump
    h1, m1 = reg.get("serve/cache_hit"), reg.get("serve/cache_miss")
    ni, _ = bat.topk([3], k)              # same request, NEW generation
    if reg.get("serve/cache_hit") != h1 or \
            reg.get("serve/cache_miss") <= m1:
        print("MUTABLE: STALE CACHE — a pre-mutation result answered "
              "after the upsert (scan_signature must fold the segment "
              "generation)")
        return 1
    qi, _ = bat.topk([anchor], k)
    if int(np.asarray(qi)[0, 0]) != N:
        print("MUTABLE: the anchor's near-duplicate insert is not its "
              "top-1 — upsert not visible through the batcher")
        return 1

    # --- upsert N + delete M, recall vs oracle, compact, again --------
    new_ids = list(range(N + 1, N + 9))
    anchors = list(range(20, 20 + len(new_ids)))
    rows = np.stack([arr0[a] for a in anchors]) \
        + 1e-4 * rng.standard_normal((len(new_ids), D)).astype(np.float32)
    live.upsert(new_ids, rows)
    live.upsert([11, 13], np.stack([arr0[50], arr0[51]])
                + np.float32(1e-4))      # in-place updates write through
    deleted = {new_ids[0], new_ids[1], 13}
    live.delete(sorted(deleted))
    r_pre = oracle_recall(live, deleted)
    if r_pre < 0.999:
        print(f"MUTABLE: pre-compaction recall vs rebuilt oracle "
              f"{r_pre:.4f} < 1.0")
        return 1
    fp_pre, gen_pre = live.fingerprint, live.generation
    live.compact()
    if live.fingerprint == fp_pre or live.generation <= gen_pre:
        print("MUTABLE: compaction kept the pre-compaction fingerprint/"
              "generation — stale cache rows would stay addressable")
        return 1
    if live.segment_rows != 0:
        print(f"MUTABLE: {live.segment_rows} delta rows survived "
              f"compaction")
        return 1
    r_post = oracle_recall(live, deleted)
    if r_post < 0.999:
        print(f"MUTABLE: post-compaction recall vs rebuilt oracle "
              f"{r_post:.4f} < 1.0")
        return 1
    # the old fingerprint no longer answers: the batcher's plan keys on
    # the live engine identity, and the swapped-in base reports the new
    # one everywhere a cache key could be built from
    if fp_pre in (live.fingerprint, live.base.fingerprint):
        print("MUTABLE: pre-compaction fingerprint still answers")
        return 1
    # tombstones: rejected as anchors, never returned as neighbors
    try:
        live.topk_neighbors([13], k)
    except ValueError:
        pass
    else:
        print("MUTABLE: querying a tombstoned id did not raise")
        return 1
    ti, _ = live.topk_neighbors([20], k)
    if deleted & set(np.asarray(ti)[0].tolist()):
        print("MUTABLE: a tombstoned id came back as a neighbor")
        return 1
    return 0


def main(out_dir: str | None = None) -> int:
    import numpy as np

    from hyperspace_tpu.serve import (QueryEngine, export_artifact,
                                      load_artifact)

    table = np.asarray(build_table())
    spec = ("poincare", C)
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = os.path.join(tmp.name, "artifact")
    try:
        exported = export_artifact(out_dir, table, spec,
                                   model_config={"c": C}, overwrite=True)
        loaded = load_artifact(out_dir)
        if loaded.fingerprint != exported.fingerprint:
            print(f"FINGERPRINT DRIFT: exported {exported.fingerprint} "
                  f"!= loaded {loaded.fingerprint}")
            return 1
        live = QueryEngine(table, spec)
        served = QueryEngine.from_artifact(loaded)
        if live.fingerprint != served.fingerprint:
            print("FINGERPRINT DRIFT: live engine != loaded engine")
            return 1
        for qi, (ids, k) in enumerate(QUERIES):
            q = np.asarray(ids, np.int32)
            li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
            si, sd = (np.asarray(a) for a in served.topk_neighbors(q, k))
            if not np.array_equal(li, si):
                print(f"query {qi}: neighbor indices differ\n{li}\nvs\n{si}")
                return 1
            if not np.array_equal(ld.view(np.uint32), sd.view(np.uint32)):
                print(f"query {qi}: distances differ bitwise\n{ld}\nvs\n{sd}")
                return 1
        rc = _check_index_round_trip(table, spec, out_dir + ".ivf", live)
        if rc:
            return rc
        rc = _check_quant_round_trip(table, spec, out_dir + ".q", live)
        if rc:
            return rc
        rc = _check_mutable_round_trip(table, spec, out_dir + ".live")
        if rc:
            return rc
        print(f"serve artifact round-trip OK: {len(QUERIES)} queries "
              f"bit-identical (N={N}, D={D}, fingerprint "
              f"{loaded.fingerprint[:12]}…)")
        return 0
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
