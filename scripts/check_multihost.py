#!/usr/bin/env python
"""Smoke lint: the pod train→checkpoint→restore→export→serve loop,
with a REAL 2-process ``jax.distributed`` group over loopback.

Launches ``hyperspace_tpu.benchmarks.mh_worker --task pipeline`` as a
2-process × 2-virtual-device group (the per-host data plane, the
digest-exchange replica consistency check, the per-host-owned table
checkpoint and the process-0-gated export all run inside the workers),
then closes the elastic loop in THIS single process.  Asserted (exit 1
on any miss):

- both workers exit 0 and process 0 prints one parseable RESULT line
  with finite, descending losses;
- the 2-host checkpoint (one ``.npy`` shard per host + process-0
  manifest) restores here at 1 process, bit-identical to the table the
  fleet trained (``table_sha`` match) — restore across a DIFFERENT
  process count than wrote it;
- ``load_rows`` of process 0's owned range matches the restored slice
  (the per-host partial-read path);
- the exported artifact is committed, loads here, and its fingerprint
  matches what every worker verified;
- re-exporting the RESTORED table from this single process reproduces
  the SAME fingerprint — a pod run and a single-host run yield
  interchangeable serving artifacts;
- ``QueryEngine.from_artifact`` answers a top-k query over it.

Run by ``tests/parallel/test_check_multihost_script.py`` inside the
suite (mirroring ``check_serve_artifact.py``), so a pod-loop
regression fails the build.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_WORKER_MOD = "hyperspace_tpu.benchmarks.mh_worker"
NPROCS = 2
STEPS = 3
K = 5


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    extra = env.get("PYTHONPATH")  # no empty entry (= cwd) when unset
    env["PYTHONPATH"] = os.pathsep.join(
        [ROOT] + (extra.split(os.pathsep) if extra else []))
    return env


def run_group(workdir: str, *extra: str, nprocs: int = NPROCS,
              timeout: int = 180):
    """Run an nprocs worker group to completion; return (rc_fail_text,
    RESULT dict) — exactly one of the two is None."""
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-m", _WORKER_MOD, "--pid", str(p),
         "--nprocs", str(nprocs), "--port", str(port),
         "--workdir", workdir, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env()) for p in range(nprocs)]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
            pr.wait()
        return "GROUP TIMED OUT\n" + "\n".join(outs), None
    for pr, out in zip(procs, outs):
        if pr.returncode != 0:
            return (f"WORKER rc={pr.returncode}:\n{out}", None)
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                return None, json.loads(line[len("RESULT "):])
    return "NO RESULT LINE\n" + "\n".join(outs), None


def _sha(a) -> str:
    import numpy as np

    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def main(out_dir: str | None = None) -> int:
    import numpy as np

    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = tmp.name
    try:
        fail, res = run_group(out_dir, "--task", "pipeline",
                              "--steps", str(STEPS))
        if fail is not None:
            print(fail)
            return 1
        losses = res["losses"]
        if (res["processes"] != NPROCS or len(losses) != STEPS
                or not np.all(np.isfinite(losses))
                or not losses[-1] < losses[0]):
            print(f"FLEET DID NOT TRAIN: {res}")
            return 1

        from hyperspace_tpu.parallel import host_table as HT

        # elastic restore: the 2-host checkpoint, read at 1 process
        t = HT.HostEmbedTable.load_sharded(res["ckpt_dir"], shards=1)
        arr = t.to_array()
        if _sha(arr) != res["table_sha"]:
            print(f"RESTORE NOT BITWISE: restored sha {_sha(arr)} != "
                  f"fleet table sha {res['table_sha']}")
            return 1
        lo, hi = res["owned_rows_p0"]
        rows = HT.load_rows(res["ckpt_dir"], lo, hi)
        if rows.tobytes() != arr[lo:hi].tobytes():
            print(f"PER-HOST READ PATH DIVERGES on rows [{lo}, {hi})")
            return 1

        from hyperspace_tpu.serve import QueryEngine
        from hyperspace_tpu.serve.artifact import (export_artifact,
                                                   is_committed,
                                                   load_artifact)

        if not is_committed(res["export_dir"]):
            print(f"EXPORT NOT COMMITTED: {res['export_dir']}")
            return 1
        art = load_artifact(res["export_dir"])
        if art.fingerprint != res["fingerprint"]:
            print(f"ARTIFACT FINGERPRINT {art.fingerprint} != fleet's "
                  f"{res['fingerprint']}")
            return 1

        # export parity: the restored table, exported HERE at 1
        # process, must fingerprint identically to the pod's export
        solo_dir = os.path.join(out_dir, "artifact_solo")
        solo = export_artifact(solo_dir, arr, art.manifold_spec,
                               model_config=art.model_config,
                               overwrite=True)
        if solo.fingerprint != art.fingerprint:
            print(f"EXPORT PARITY BROKEN: single-process re-export "
                  f"fingerprint {solo.fingerprint} != pod export "
                  f"{art.fingerprint}")
            return 1

        eng = QueryEngine.from_artifact(art)
        ids, dists = (np.asarray(a) for a in
                      eng.topk_neighbors([0, 1], K))
        if ids.shape != (2, K) or not np.all(np.isfinite(dists)):
            print(f"SERVE QUERY BROKEN: ids {ids.shape}, dists "
                  f"finite={np.all(np.isfinite(dists))}")
            return 1

        print(f"check_multihost OK: {NPROCS} processes trained "
              f"{STEPS} steps (loss {losses[0]:.4f} -> "
              f"{losses[-1]:.4f}), 2-host checkpoint restored at 1 "
              f"process bitwise, export parity "
              f"{art.fingerprint[:12]}, top-{K} query served")
        return 0
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
