"""Per-device compiled-cost scaling of the node-sharded HGCN step.

The BASELINE north star is "HGCN on v5e-16"; real 16-chip hardware is not
available in this environment, so the scaling evidence is compiled-cost
analysis on a virtual CPU mesh (the same probe the r2 verdict used to show
the pair-sharded step did NOT scale).  This script forces ``--ndev``
virtual devices, compiles the node-sharded LP step at each data-parallel
degree in ``--dp-list``, and prints one JSON line with per-device FLOPs
and HBM-bytes ratios relative to the compiled single-device step.

Run standalone::

    python scripts/cost_scaling_probe.py --ndev 16

or via the drill in tests/parallel/test_node_sharded.py (marked slow),
which asserts dp=16 leaves <=20% of single-device FLOPs per device.
"""

from __future__ import annotations

import argparse
import json
import os


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ndev", type=int, default=16)
    ap.add_argument("--num-nodes", type=int, default=2048)
    ap.add_argument("--dp-list", type=str, default="1,4,8,16")
    ap.add_argument("--reorder", choices=["none", "bfs", "community"],
                    default="none",
                    help="locality relabeling before sharding: under "
                         "'community' the halo exchange replaces the "
                         "all-gather wherever its static volume wins")
    args = ap.parse_args()

    # virtual CPU devices must be configured before jax import; an
    # inherited device-count flag (e.g. the test conftest's 8) must be
    # REPLACED, not kept, or dp > 8 has too few devices
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    os.environ["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={args.ndev}"])

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn
    from hyperspace_tpu.parallel.mesh import make_mesh

    n = args.num_nodes
    edges, x, _, _ = G.synthetic_hierarchy(num_nodes=n, feat_dim=16, seed=0)
    if args.reorder != "none":
        edges, x, _, _ = G.apply_locality_order(edges, x, None,
                                                method=args.reorder)
    split = G.split_edges(edges, n, x, seed=0, pad_multiple=256)
    cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 8))

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    pairs = jnp.asarray(split.train_pos[:256])
    from hyperspace_tpu.train.profiling import cost_analysis_dict

    single = cost_analysis_dict(jax.jit(
        lambda st, g, p: hgcn._lp_step_impl(model, opt, n, st, g, p)
    ).lower(state, ga, pairs).compile())

    out = {"ndev": args.ndev, "num_nodes": n, "reorder": args.reorder,
           "single_flops": single["flops"],
           "single_bytes": single["bytes accessed"], "dp": {}}
    for dp in (int(d) for d in args.dp_list.split(",")):
        if dp > args.ndev or n % dp:
            continue
        mesh = make_mesh({"data": dp}, devices=jax.devices()[:dp])
        model_k, opt_k, state_k = hgcn.init_lp(cfg, split.graph, seed=0)
        tp = jnp.asarray(hgcn.round_up_pairs(split.train_pos[:256], mesh))
        step, state_k, nsg = hgcn.make_node_sharded_step_lp(
            model_k, opt_k, n, mesh, state_k, split)
        cost = cost_analysis_dict(step.lower(state_k, nsg, tp).compile())
        out["dp"][str(dp)] = {
            "halo": bool(nsg.halo),
            "flops_ratio": round(cost["flops"] / single["flops"], 4),
            "bytes_ratio": round(
                cost["bytes accessed"] / single["bytes accessed"], 4),
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
