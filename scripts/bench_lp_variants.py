"""Compare HGCN LP train-step variants on the live backend (TPU or CPU).

Variants:
  unplanned  — train_step_lp: fresh (u, v) negatives, XLA scatter decoder grads
  planned    — train_step_lp_planned: graph-edge positives + corrupt-one-side
               negatives, every decoder gradient scatter CSR-planned
  bf16       — the faster variant re-run in bfloat16

Prints one JSON line per variant.  Run under nohup; compiles go through the
remote helper (~1-3 min each).
"""

from __future__ import annotations

import json
import time


def timed(step, state, *args, steps=10, repeats=3):
    import jax

    state, loss = step(state, *args)  # compile + warmup
    jax.device_get(loss)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, *args)
        jax.device_get(loss)
        best = min(best, time.perf_counter() - t0)
    return best / steps, state


def main():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    num_nodes = HB.ARXIV_NODES
    split, x = HB.arxiv_scale_split(num_nodes)

    for name, dtype in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(128, 32),
                              kind="lorentz", dtype=dtype)
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        ga = hgcn._device_graph(split.graph)

        # unplanned
        train_pos = jnp.asarray(split.train_pos)
        t, _ = timed(
            lambda st, g, tp: hgcn.train_step_lp(model, opt, num_nodes, st, g, tp),
            state, ga, train_pos)
        print(json.dumps({"variant": f"unplanned_{name}",
                          "step_s": round(t, 5),
                          "samples_per_s": round(num_nodes / t, 1)}), flush=True)

        # planned
        model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
        n_neg = int(split.graph.senders.shape[0])
        neg_u, neg_plan = hgcn.make_static_negatives(num_nodes, n_neg, seed=0)
        t, _ = timed(
            lambda st, g, nu, npl: hgcn.train_step_lp_planned(
                model2, opt2, num_nodes, st, g, nu, npl),
            state2, ga, neg_u, neg_plan)
        print(json.dumps({"variant": f"planned_{name}",
                          "step_s": round(t, 5),
                          "samples_per_s": round(num_nodes / t, 1)}), flush=True)


if __name__ == "__main__":
    main()
