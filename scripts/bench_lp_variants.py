"""Compare HGCN LP train-step variants on the live backend (TPU or CPU).

Variants:
  unplanned  — train_step_lp: fresh (u, v) negatives, XLA scatter decoder grads
  planned    — train_step_lp_planned: graph-edge positives + corrupt-one-side
               negatives, every decoder gradient scatter CSR-planned
  pairs      — train_step_lp_pairs: exactly the train positives with BOTH
               decoder scatters planned + corrupt-v negatives (u planned);
               same pair count as unplanned, same scatter story as planned
  bf16       — each variant re-run in bfloat16 / with bf16 edge messages

Prints one JSON line per variant.  Run under nohup; compiles go through the
remote helper (~1-3 min each).
"""

from __future__ import annotations

import json
import time


def timed(step, state, *args, steps=10, repeats=3):
    import jax

    state, loss = step(state, *args)  # compile + warmup
    jax.device_get(loss)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, *args)
        jax.device_get(loss)
        best = min(best, time.perf_counter() - t0)
    return best / steps, state


def main():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    num_nodes = HB.ARXIV_NODES
    split, x = HB.arxiv_scale_split(num_nodes)

    # one-time host-side prep shared by every combo
    n_neg_edges = int(split.graph.senders.shape[0])
    neg_u, neg_plan = hgcn.make_static_negatives(num_nodes, n_neg_edges, seed=0)
    pos = hgcn.make_planned_pairs(split.train_pos, num_nodes)
    neg_u3, neg_plan3 = hgcn.make_static_negatives(
        num_nodes, int(pos.u.shape[0]), seed=0)

    combos = (
        ("f32", jnp.float32, None, None),
        ("f32_aggbf16", jnp.float32, jnp.bfloat16, None),
        # bench default (pairs row): + bf16 decoder pass
        ("f32_aggbf16_decbf16", jnp.float32, jnp.bfloat16, jnp.bfloat16),
        ("bf16", jnp.bfloat16, None, None),
    )
    for name, dtype, agg_dtype, decoder_dtype in combos:
        cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(128, 32),
                              kind="lorentz", dtype=dtype,
                              agg_dtype=agg_dtype,
                              decoder_dtype=decoder_dtype)
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        ga = hgcn._device_graph(split.graph)

        # unplanned
        train_pos = jnp.asarray(split.train_pos)
        t, _ = timed(
            lambda st, g, tp: hgcn.train_step_lp(model, opt, num_nodes, st, g, tp),
            state, ga, train_pos)
        print(json.dumps({"variant": f"unplanned_{name}",
                          "step_s": round(t, 5),
                          "samples_per_s": round(num_nodes / t, 1)}), flush=True)

        # planned
        model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
        t, _ = timed(
            lambda st, g, nu, npl: hgcn.train_step_lp_planned(
                model2, opt2, num_nodes, st, g, nu, npl),
            state2, ga, neg_u, neg_plan)
        print(json.dumps({"variant": f"planned_{name}",
                          "step_s": round(t, 5),
                          "samples_per_s": round(num_nodes / t, 1)}), flush=True)

        # pairs (fully-planned decoder on the actual train positives)
        model3, opt3, state3 = hgcn.init_lp(cfg, split.graph, seed=0)
        t, _ = timed(
            lambda st, g, p, nu, npl: hgcn.train_step_lp_pairs(
                model3, opt3, num_nodes, st, g, p, nu, npl),
            state3, ga, pos, neg_u3, neg_plan3)
        print(json.dumps({"variant": f"pairs_{name}",
                          "step_s": round(t, 5),
                          "samples_per_s": round(num_nodes / t, 1)}), flush=True)


if __name__ == "__main__":
    main()
