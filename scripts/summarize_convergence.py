"""Summarize convergence_runs.py JSONL logs into the docs tables.

    python scripts/summarize_convergence.py docs/data/convergence_r03.jsonl ...

Prints (a) a final-AUC table (mean ± spread over seeds per arm) and (b) a
compact val-AUC-vs-step curve per arm (seed mean), ready to paste into
docs/benchmarks.md.
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict

import numpy as np


def main(paths):
    finals = defaultdict(list)
    curves = defaultdict(lambda: defaultdict(list))  # arm -> step -> [auc]
    for path in paths:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("phase") == "final":
                    finals[rec["config"]].append(rec)
                elif rec.get("phase") == "curve":
                    curves[rec["config"]][rec["step"]].append(rec["val_auc"])

    print("| arm | seeds | steps | test AUC mean | spread | val AUC mean |")
    print("|---|---|---|---|---|---|")
    for arm, recs in finals.items():
        t = [r["test_auc"] for r in recs]
        v = [r["val_auc"] for r in recs]
        print(f"| {arm} | {len(recs)} | {recs[0]['steps']} "
              f"| {np.mean(t):.4f} | ±{(max(t) - min(t)) / 2:.4f} "
              f"| {np.mean(v):.4f} |")

    print()
    for arm, by_step in curves.items():
        steps = sorted(by_step)
        vals = [f"{np.mean(by_step[s]):.3f}" for s in steps]
        print(f"{arm}: steps {steps[0]}..{steps[-1]}")
        print("  val AUC: " + " ".join(vals))


if __name__ == "__main__":
    main(sys.argv[1:] or ["docs/data/convergence_r03.jsonl"])
