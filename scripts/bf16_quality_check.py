"""bf16-vs-f32 ROC-AUC parity for HGCN LP at arxiv density.

The north-star metric couples throughput to matching test ROC-AUC
(SURVEY.md §6); bf16 is ~11% faster per step, so this measures what it
costs in quality.  Trains the same split with each dtype config over
several seeds and prints one JSON line per run.

Defaults run the quality phase at the FULL 169 k-node bench scale over 3
seeds (VERDICT r1 #4c: the bench default's quality-neutrality must be
measured at the scale it is reported at, not extrapolated from 32 k):

    python scripts/bf16_quality_check.py                   # full scale, TPU
    python scripts/bf16_quality_check.py --quality-nodes 32768 --seeds 1
"""

from __future__ import annotations

import argparse
import json


def configs(hgcn, jnp, feat_dim, which="all"):
    """(name, cfg, step) triples; step "lp" = train_step_lp (fresh uv
    negatives), "pairs" = train_step_lp_pairs (fully-planned decoder,
    corrupt-v negatives)."""
    base = dict(feat_dim=feat_dim, hidden_dims=(128, 32), kind="lorentz")
    all_ = [
        ("f32", hgcn.HGCNConfig(**base), "lp"),
        ("f32_aggbf16", hgcn.HGCNConfig(**base, agg_dtype=jnp.bfloat16),
         "lp"),
        ("bf16", hgcn.HGCNConfig(**base, dtype=jnp.bfloat16), "lp"),
        # the r02 bench candidate: f32 encoder, bf16 messages, bf16
        # decoder pass, fully-planned pairs step (987 k samples/s/chip)
        ("pairs_f32_aggbf16_decbf16",
         hgcn.HGCNConfig(**base, agg_dtype=jnp.bfloat16,
                         decoder_dtype=jnp.bfloat16), "pairs"),
        # its f32 control through the same step/negative sampler, so the
        # dtype effect is isolated from the sampler change
        ("pairs_f32", hgcn.HGCNConfig(**base), "pairs"),
    ]
    if which == "all":
        return all_
    names = {t[0] for t in all_}
    sel = which.split(",")
    unknown = [s for s in sel if s not in names]
    if unknown:  # fail fast — a typo must not silently run nothing
        raise SystemExit(
            f"unknown config(s) {unknown}; known: {sorted(names)}")
    return [t for t in all_ if t[0] in sel]


def make_split(num_nodes):
    from hyperspace_tpu.benchmarks import hgcn_bench as HB

    return HB.arxiv_scale_split(num_nodes)


def time_phase(which: str = "all"):
    """Step time per config at full arxiv scale."""
    import time

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    split, x = make_split(HB.ARXIV_NODES)
    n = HB.ARXIV_NODES
    ga = hgcn._device_graph(split.graph)
    sel = configs(hgcn, jnp, x.shape[1], which)
    steppers = _steppers(hgcn, split, n, {k for _, _, k in sel})
    for name, cfg, kind in sel:
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        step = steppers[kind]
        state, loss = step(model, opt, state, ga)
        jax.device_get(loss)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                state, loss = step(model, opt, state, ga)
            jax.device_get(loss)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({"phase": "time", "config": name,
                          "step_s": round(best / 10, 5),
                          "samples_per_s": round(n / (best / 10), 1)}),
              flush=True)


def _steppers(hgcn, split, n, kinds):
    """step(model, opt, state, ga) closures, built only for ``kinds``
    (the pairs prep sorts millions of host-side indices — skip it when no
    selected config needs it)."""
    import jax.numpy as jnp

    out = {}
    if "lp" in kinds:
        train_pos = jnp.asarray(split.train_pos)
        out["lp"] = lambda m, o, st, g: hgcn.train_step_lp(
            m, o, n, st, g, train_pos)
    if "pairs" in kinds:
        pos = hgcn.make_planned_pairs(split.train_pos, n)
        neg_u, neg_plan = hgcn.make_static_negatives(
            n, int(pos.u.shape[0]), seed=0)
        out["pairs"] = lambda m, o, st, g: hgcn.train_step_lp_pairs(
            m, o, n, st, g, pos, neg_u, neg_plan)
    return out


def quality_phase(quality_nodes: int, steps: int, seeds: int,
                  which: str = "all"):
    """Converged test ROC-AUC per config per seed at the requested scale."""
    import jax.numpy as jnp

    from hyperspace_tpu.models import hgcn

    split, x = make_split(quality_nodes)
    n = quality_nodes
    ga = hgcn._device_graph(split.graph)
    sel = configs(hgcn, jnp, x.shape[1], which)
    steppers = _steppers(hgcn, split, n, {k for _, _, k in sel})
    for name, cfg, kind in sel:
        step = steppers[kind]
        for seed in range(seeds):
            model, opt, state = hgcn.init_lp(cfg, split.graph, seed=seed)
            for _ in range(steps):
                state, loss = step(model, opt, state, ga)
            res = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
            print(json.dumps({"phase": "quality", "config": name,
                              "seed": seed, "nodes": n, "steps": steps,
                              "loss": float(loss), **res}), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quality-nodes", type=int, default=None,
                    help="default: the full bench scale (ARXIV_NODES)")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--skip-timing", action="store_true")
    ap.add_argument("--configs", default="all",
                    help='comma-separated config names, or "all"')
    args = ap.parse_args()
    if args.quality_nodes is None:
        from hyperspace_tpu.benchmarks import hgcn_bench as HB

        args.quality_nodes = HB.ARXIV_NODES
    if not args.skip_timing:
        time_phase(args.configs)
    quality_phase(args.quality_nodes, args.steps, args.seeds, args.configs)
