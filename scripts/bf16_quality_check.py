"""bf16-vs-f32 ROC-AUC parity for HGCN LP at arxiv density.

The north-star metric couples throughput to matching test ROC-AUC
(SURVEY.md §6); bf16 is ~11% faster per step, so this measures what it
costs in quality.  Trains the same split with both dtypes and prints one
JSON line per run.
"""

from __future__ import annotations

import json


def configs(hgcn, jnp, feat_dim):
    base = dict(feat_dim=feat_dim, hidden_dims=(128, 32), kind="lorentz")
    return [
        ("f32", hgcn.HGCNConfig(**base)),
        ("f32_aggbf16", hgcn.HGCNConfig(**base, agg_dtype=jnp.bfloat16)),
        ("bf16", hgcn.HGCNConfig(**base, dtype=jnp.bfloat16)),
    ]


def make_split(num_nodes):
    from hyperspace_tpu.benchmarks import hgcn_bench as HB

    return HB.arxiv_scale_split(num_nodes)


def main(quality_nodes=32768, steps=400):
    import time

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    # phase A: step time at full arxiv scale
    split, x = make_split(HB.ARXIV_NODES)
    n = HB.ARXIV_NODES
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    for name, cfg in configs(hgcn, jnp, x.shape[1]):
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        state, loss = hgcn.train_step_lp(model, opt, n, state, ga, train_pos)
        jax.device_get(loss)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                state, loss = hgcn.train_step_lp(model, opt, n, state, ga,
                                                 train_pos)
            jax.device_get(loss)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({"phase": "time", "config": name,
                          "step_s": round(best / 10, 5),
                          "samples_per_s": round(n / (best / 10), 1)}),
              flush=True)

    # phase B: ROC-AUC parity at reduced scale
    split, x = make_split(quality_nodes)
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    for name, cfg in configs(hgcn, jnp, x.shape[1]):
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        for _ in range(steps):
            state, loss = hgcn.train_step_lp(model, opt, quality_nodes, state,
                                             ga, train_pos)
        res = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
        print(json.dumps({"phase": "quality", "config": name, "steps": steps,
                          "loss": float(loss), **res}), flush=True)


if __name__ == "__main__":
    main()
