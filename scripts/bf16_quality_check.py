"""bf16-vs-f32 ROC-AUC parity for HGCN LP at arxiv density.

The north-star metric couples throughput to matching test ROC-AUC
(SURVEY.md §6); bf16 is ~11% faster per step, so this measures what it
costs in quality.  Trains the same split with each dtype config over
several seeds and prints one JSON line per run.

Defaults run the quality phase at the FULL 169 k-node bench scale over 3
seeds (VERDICT r1 #4c: the bench default's quality-neutrality must be
measured at the scale it is reported at, not extrapolated from 32 k):

    python scripts/bf16_quality_check.py                   # full scale, TPU
    python scripts/bf16_quality_check.py --quality-nodes 32768 --seeds 1
"""

from __future__ import annotations

import argparse
import json


def configs(hgcn, jnp, feat_dim):
    base = dict(feat_dim=feat_dim, hidden_dims=(128, 32), kind="lorentz")
    return [
        ("f32", hgcn.HGCNConfig(**base)),
        ("f32_aggbf16", hgcn.HGCNConfig(**base, agg_dtype=jnp.bfloat16)),
        ("bf16", hgcn.HGCNConfig(**base, dtype=jnp.bfloat16)),
    ]


def make_split(num_nodes):
    from hyperspace_tpu.benchmarks import hgcn_bench as HB

    return HB.arxiv_scale_split(num_nodes)


def time_phase():
    """Step time per config at full arxiv scale."""
    import time

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    split, x = make_split(HB.ARXIV_NODES)
    n = HB.ARXIV_NODES
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    for name, cfg in configs(hgcn, jnp, x.shape[1]):
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        state, loss = hgcn.train_step_lp(model, opt, n, state, ga, train_pos)
        jax.device_get(loss)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                state, loss = hgcn.train_step_lp(model, opt, n, state, ga,
                                                 train_pos)
            jax.device_get(loss)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({"phase": "time", "config": name,
                          "step_s": round(best / 10, 5),
                          "samples_per_s": round(n / (best / 10), 1)}),
              flush=True)


def quality_phase(quality_nodes: int, steps: int, seeds: int):
    """Converged test ROC-AUC per config per seed at the requested scale."""
    import jax.numpy as jnp

    from hyperspace_tpu.models import hgcn

    split, x = make_split(quality_nodes)
    n = quality_nodes
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    for name, cfg in configs(hgcn, jnp, x.shape[1]):
        for seed in range(seeds):
            model, opt, state = hgcn.init_lp(cfg, split.graph, seed=seed)
            for _ in range(steps):
                state, loss = hgcn.train_step_lp(model, opt, n, state, ga,
                                                 train_pos)
            res = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
            print(json.dumps({"phase": "quality", "config": name,
                              "seed": seed, "nodes": n, "steps": steps,
                              "loss": float(loss), **res}), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quality-nodes", type=int, default=None,
                    help="default: the full bench scale (ARXIV_NODES)")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--skip-timing", action="store_true")
    args = ap.parse_args()
    if args.quality_nodes is None:
        from hyperspace_tpu.benchmarks import hgcn_bench as HB

        args.quality_nodes = HB.ARXIV_NODES
    if not args.skip_timing:
        time_phase()
    quality_phase(args.quality_nodes, args.steps, args.seeds)
