#!/usr/bin/env python
"""Lint: no ad-hoc bf16 casts outside the precision policy.

``hyperspace_tpu/precision.py`` is the ONE place the package is allowed
to name bf16 (docs/precision.md): consumers take a ``Policy`` and use
its cast helpers, so every half-precision decision is visible in one
module and the boundary-sensitive hyperbolic math can't be silently
downcast by a stray ``astype``.  This script scans every ``.py`` under
``hyperspace_tpu/`` for bf16 literals in CODE (comments stripped;
docstrings may *discuss* bf16 freely — only the dtype tokens below
trigger):

- ``jnp.bfloat16`` / ``jax.numpy.bfloat16`` / ``np.bfloat16``
- a quoted ``"bfloat16"`` dtype string
- ``astype(jnp.bfloat16)`` is just the composition of the above

Allowed locations:

- ``hyperspace_tpu/precision.py`` — the policy itself;
- ``hyperspace_tpu/kernels/`` — the Pallas fast paths (e.g.
  ``cluster.py``'s single-pass bf16 MXU body) pick dtypes from their
  INPUT dtype, which the policy already controls upstream;
- any line carrying a ``# precision-policy: ok`` annotation (use it for
  CLI dtype-flag *names*, with a reason).

Run by ``tests/test_precision_policy.py`` inside the suite, so an
ad-hoc cast can't merge.  Exit 0 = clean, 1 = offenders listed.
"""

from __future__ import annotations

import os
import re
import sys

_BF16 = re.compile(
    r"(?:\bjnp\.bfloat16\b|\bjax\.numpy\.bfloat16\b|\bnp\.bfloat16\b"
    r"|[\"']bfloat16[\"'])")
_ALLOW_ANNOT = "precision-policy: ok"
_ALLOWED_FILES = ("precision.py",)
_ALLOWED_DIRS = (os.path.join("hyperspace_tpu", "kernels"),)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment (string-aware enough for this
    codebase: a ``#`` inside quotes would need a quoted bf16 token ON
    the same line to matter, which the annotation escape covers)."""
    i = line.find("#")
    return line if i < 0 else line[:i]


def violations_in_text(text: str, rel: str) -> list[str]:
    """``["path:lineno: line", ...]`` for bf16 literals in code lines."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if _ALLOW_ANNOT in line:
            continue
        if _BF16.search(_strip_comment(line)):
            out.append(f"{rel}:{lineno}: {line.strip()}")
    return out


def _allowed(rel: str) -> bool:
    if os.path.basename(rel) in _ALLOWED_FILES:
        return True
    return any(rel.startswith(d + os.sep) for d in _ALLOWED_DIRS)


def scan_package(pkg_dir: str) -> list[str]:
    root = os.path.dirname(pkg_dir)
    offenders: list[str] = []
    for dirpath, _dirs, files in os.walk(pkg_dir):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            if _allowed(rel):
                continue
            with open(path, encoding="utf-8") as f:
                offenders += violations_in_text(f.read(), rel)
    return offenders


def main() -> int:
    pkg = os.path.join(repo_root(), "hyperspace_tpu")
    offenders = scan_package(pkg)
    if offenders:
        print("ad-hoc bf16 literals outside the precision policy "
              "(route them through hyperspace_tpu/precision.py, or "
              f"annotate a flag-name line with `# {_ALLOW_ANNOT} "
              "(reason)`):")
        for line in offenders:
            print(f"  {line}")
        return 1
    print("precision policy OK: no ad-hoc bf16 literals outside "
          "precision.py / kernels/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
