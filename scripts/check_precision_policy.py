#!/usr/bin/env python
"""Lint shim: no ad-hoc bf16 casts outside the precision policy.

The implementation moved to the AST rule ``precision-literal`` in
``hyperspace_tpu/analysis/rules/precision.py`` (docs/static-analysis.md)
— structural matching catches aliased imports and ``from jax.numpy
import bfloat16``, and docstrings can discuss bf16 freely.  This script
keeps the original CLI contract (same args, exit 0 = clean / 1 =
offenders listed, same helper functions) for
``tests/test_precision_policy.py`` and any callers of the old path;
``python -m hyperspace_tpu.analysis --rules precision-literal`` is the
first-class entry point.

Allowed locations (unchanged — docs/precision.md): ``precision.py``
itself, ``hyperspace_tpu/kernels/``, and any line annotated
``# precision-policy: ok (reason)``.
"""

from __future__ import annotations

import os
import sys


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


if repo_root() not in sys.path:  # standalone `python scripts/...` runs
    sys.path.insert(0, repo_root())

from hyperspace_tpu.analysis.rules.precision import (  # noqa: E402,F401
    LEGACY_ANNOT as _ALLOW_ANNOT,
    scan_package,
    violations_in_text,
)


def main() -> int:
    pkg = os.path.join(repo_root(), "hyperspace_tpu")
    offenders = scan_package(pkg)
    if offenders:
        print("ad-hoc bf16 literals outside the precision policy "
              "(route them through hyperspace_tpu/precision.py, or "
              f"annotate a flag-name line with `# {_ALLOW_ANNOT} "
              "(reason)`):")
        for line in offenders:
            print(f"  {line}")
        return 1
    print("precision policy OK: no ad-hoc bf16 literals outside "
          "precision.py / kernels/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
