"""Sweep the cluster-split threshold on the bench-scale HGCN step.

Each (receiver-block x sender-block) pair above the threshold runs the
cluster-pair SpMM kernel; below it, the gather+CSR path.  Lower
thresholds cluster more edges but waste h-tile loads on thin pairs.
Prints one JSON line per config: step time + clustered fraction.

    python scripts/bench_cluster_sweep.py --thresholds 64,128,256
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--thresholds", default="64,128,256")
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.kernels.cluster import build_cluster_split
    from hyperspace_tpu.models import hgcn

    n = args.nodes or HB.ARXIV_NODES
    split, x = HB.arxiv_scale_split(n)
    g = split.graph
    cfg = hgcn.HGCNConfig(
        feat_dim=x.shape[1], hidden_dims=(128, 32), kind="lorentz",
        agg_dtype=jnp.bfloat16, decoder_dtype=jnp.bfloat16)
    pos = hgcn.make_planned_pairs(split.train_pos, n)
    neg_u, neg_plan = hgcn.make_static_negatives(n, int(pos.u.shape[0]), seed=0)

    configs = [None] + [int(t) for t in args.thresholds.split(",")]
    for thr in configs:
        if thr is None:
            g.cluster_split = None  # the r02 gather+CSR-only baseline
            frac = 0.0
        else:
            g.cluster_split = build_cluster_split(
                g.senders, g.receivers, g.edge_mask, g.deg, n,
                min_pair_edges=thr)
            frac = g.cluster_split.frac_clustered
        ga = hgcn._device_graph(g)
        model, opt, state = hgcn.init_lp(cfg, g, seed=0)
        stepper = lambda st: hgcn.train_step_lp_pairs(
            model, opt, n, st, ga, pos, neg_u, neg_plan)
        state, loss = stepper(state)
        jax.device_get(loss)
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, loss = stepper(state)
            jax.device_get(loss)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({
            "min_pair_edges": thr, "frac_clustered": round(frac, 3),
            "step_s": round(best / args.steps, 5),
            "samples_per_s": round(n / (best / args.steps), 1),
        }), flush=True)


if __name__ == "__main__":
    main()
