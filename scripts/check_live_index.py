#!/usr/bin/env python
"""Smoke lint: the live mutable index over the wire, as a subprocess.

export → ``serve-http`` with ``live=1`` on an ephemeral port → healthz
reports a generation → upsert a new row through the socket → an
immediate query BY THE NEW ID sees it (and ranks its planted anchor
top-1) → delete it → the tombstone is refused as a query anchor and
never returned as a neighbor → the generation advanced once per
mutation → SIGTERM drain exits 0 with the drain notice.  Asserted
(exit 1 on any miss):

- ``/healthz`` carries ``generation`` (a live engine identity, not the
  frozen ``null``) and folds it into ``scan_signature``;
- ``POST /v1/upsert`` answers ``{"inserted": 1}`` and the row is
  queryable the moment the response lands (write-through visibility —
  docs/serving.md "Live index and rollover");
- ``POST /v1/delete`` tombstones it: querying the deleted id answers a
  typed 400 validation error, and the anchor's top-k no longer
  contains it;
- recompiles stay FLAT across the mutations (the delta scan and the
  tombstone mask are traced operands, never fresh executables);
- SIGTERM drains rc=0 — mutations never break the drain contract.

Run by ``tests/serve/test_check_live_script.py`` inside the suite,
mirroring ``check_serve_http.py``, so a live-index regression fails
the build.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from scripts.check_serve_http import (  # noqa: E402
    _StderrPump,
    _get,
    _post,
    _wait_for_port,
)

N, D, C = 101, 8, 1.2
K = 5


def build_table():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.5 * jax.random.normal(jax.random.PRNGKey(11), (N, D), jnp.float32)
    return PoincareBall(C).expmap0(v)


def main(out_dir: str | None = None) -> int:
    import numpy as np

    from hyperspace_tpu.serve import export_artifact

    table = np.asarray(build_table())
    spec = ("poincare", C)
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = os.path.join(tmp.name, "artifact")
    proc = None
    try:
        export_artifact(out_dir, table, spec, model_config={"c": C},
                        overwrite=True)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "hyperspace_tpu.cli.serve",
             "serve-http", f"artifact={out_dir}", "port=0",
             "host=127.0.0.1", "max_wait_us=1000", "telemetry=1",
             "prewarm=1", f"k={K}", "live=1", "delta_cap=32"],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        pump = _StderrPump(proc)
        host, port = _wait_for_port(proc, pump)

        status, health = _get(host, port, "/healthz")
        if status != 200 or health.get("ok") is not True:
            print(f"HEALTHZ BROKEN: {status} {health}")
            return 1
        if health.get("generation") != 0:
            print(f"LIVE ENGINE NOT ARMED: live=1 but /healthz "
                  f"generation is {health.get('generation')!r}")
            return 1
        if "gen" not in health.get("scan_signature", []):
            print(f"SCAN SIGNATURE does not fold the generation: "
                  f"{health.get('scan_signature')}")
            return 1

        status, stats0 = _post(host, port, "/v1/stats", {})
        if status != 200:
            print(f"STATS FAILED: {status} {stats0}")
            return 1

        # upsert one new row, a near-duplicate of a known anchor: the
        # response landing means the write is applied (synchronous
        # write-through), so the very next query must see it
        anchor, new_id = 17, N
        vec = (table[anchor]
               + 1e-4 * np.random.default_rng(0).standard_normal(D))
        status, r = _post(host, port, "/v1/upsert",
                          {"ids": [new_id], "rows": [vec.tolist()]})
        if status != 200 or r.get("inserted") != 1:
            print(f"UPSERT FAILED: {status} {r}")
            return 1
        status, q = _post(host, port, "/v1/topk",
                          {"ids": [new_id], "k": K})
        if status != 200:
            print(f"QUERY BY THE NEW ID FAILED: {status} {q}")
            return 1
        if q["neighbors"][0][0] != anchor:
            print(f"UPSERT NOT VISIBLE: the new row's top-1 should be "
                  f"its anchor {anchor}; got {q['neighbors'][0]}")
            return 1

        status, r = _post(host, port, "/v1/delete", {"ids": [new_id]})
        if status != 200 or r.get("deleted") != 1:
            print(f"DELETE FAILED: {status} {r}")
            return 1
        status, r = _post(host, port, "/v1/topk",
                          {"ids": [new_id], "k": K})
        if status != 400 or r["error"]["kind"] != "validation":
            print(f"TOMBSTONE STILL QUERYABLE: {status} {r}")
            return 1
        status, q = _post(host, port, "/v1/topk",
                          {"ids": [anchor], "k": K})
        if status != 200 or new_id in q["neighbors"][0]:
            print(f"TOMBSTONE RETURNED AS NEIGHBOR: {status} "
                  f"{q.get('neighbors')}")
            return 1

        status, health2 = _get(host, port, "/healthz")
        if status != 200 or health2.get("generation") != 2:
            print(f"GENERATION DID NOT ADVANCE once per mutation: "
                  f"{health2.get('generation')!r} (want 2)")
            return 1
        status, stats1 = _post(host, port, "/v1/stats", {})
        if status != 200 or stats1["recompiles"] != stats0["recompiles"]:
            print(f"RECOMPILES NOT FLAT across mutations: "
                  f"{stats0.get('recompiles')} -> "
                  f"{stats1.get('recompiles')}")
            return 1

        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            print("DRAIN HUNG: SIGTERM did not stop the server in 60 s")
            return 1
        err = pump.text()
        if proc.returncode != 0:
            print(f"DRAIN EXIT CODE {proc.returncode}; stderr:\n{err}")
            return 1
        if "drained" not in err:
            print(f"DRAIN NOTICE missing; stderr:\n{err}")
            return 1
        print(f"live index round trip OK: upsert visible, tombstone "
              f"refused, generation {health2['generation']}, recompiles "
              f"flat at {stats1['recompiles']}, drained clean")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
