"""Decompose the ATTENTION HGCN LP train-step time on the live backend.

VERDICT r3 #1: the attention arm (best quality, AUC 0.633) runs ~4x the
mean step.  This probe isolates where the extra time lives: the logits
pipeline ([E] scalar picks + CSR segment max/sum), the weighted [E, F]
aggregation forward, its dh backward, and the dw backward (two [E, F]
gathers in the current path).  One JSON line per probe.
"""

from __future__ import annotations

import json
import time


def timed(fn, *args, steps=10, repeats=3):
    import jax

    out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best / steps


def main():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    num_nodes = HB.ARXIV_NODES
    split, x = HB.arxiv_scale_split(num_nodes)
    g = split.graph
    print(json.dumps({
        "probe": "graph",
        "edges_padded": int(g.senders.shape[0]),
        "frac_clustered": (None if g.cluster_split is None
                           else round(g.cluster_split.frac_clustered, 4)),
    }), flush=True)

    for use_att in (False, True):
        cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(128, 32),
                              kind="lorentz", use_att=use_att,
                              agg_dtype=jnp.bfloat16,
                              decoder_dtype=jnp.bfloat16)
        model, opt, state = hgcn.init_lp(cfg, g, seed=0)
        ga = hgcn._device_graph(g)
        pos = hgcn.make_planned_pairs(split.train_pos, num_nodes)
        neg_u, neg_plan = hgcn.make_static_negatives(
            num_nodes, int(pos.u.shape[0]), seed=0)
        step = lambda st: hgcn.train_step_lp_pairs(
            model, opt, num_nodes, st, ga, pos, neg_u, neg_plan)
        st, loss = step(state)
        jax.device_get(loss)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                st, loss = step(st)
            jax.device_get(loss)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({"probe": f"train_step att={use_att}",
                          "time_s": round(best / 10, 5)}), flush=True)

        enc = jax.jit(lambda p, gg: hgcn.HGCNEncoder(cfg).apply(  # hyperlint: disable=recompile-hazard,jit-cache-defeat — config sweep: each use_att arm IS its own program, by design
            {"params": p["encoder"]}, gg)[0].sum())
        t = timed(enc, st.params, ga)
        print(json.dumps({"probe": f"encoder_fwd att={use_att}",
                          "time_s": round(t, 5)}), flush=True)

        @jax.jit  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
        def enc_grad(p, gg):
            def f(pp):
                out, _ = hgcn.HGCNEncoder(cfg).apply(
                    {"params": pp["encoder"]}, gg)
                return jnp.sum(out * out)
            l, gr = jax.value_and_grad(f)(p)
            return l + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(gr))

        t = timed(enc_grad, st.params, ga)
        print(json.dumps({"probe": f"encoder_fwd_bwd att={use_att}",
                          "time_s": round(t, 5)}), flush=True)

    # isolated weighted aggregation: dw on vs off isolates the two
    # [E, F] gathers of the dw backward
    from hyperspace_tpu.nn.scatter import sym_segment_aggregate

    ga = hgcn._device_graph(g)
    pb, pc, pf = ga.plan
    h0 = jnp.zeros((num_nodes, 128), jnp.bfloat16)
    w0 = ga.edge_mask.astype(jnp.bfloat16)

    for with_dw in (False, True):
        @jax.jit  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
        def agg_fb(h, w):
            def f(hh, ww):
                out = sym_segment_aggregate(hh, ww, ga.senders, ga.receivers,
                                            ga.rev_perm, pb, pc, pf,
                                            num_nodes, with_dw)
                return jnp.sum(out.astype(jnp.float32) ** 2)
            l, (gh, gw) = jax.value_and_grad(f, argnums=(0, 1))(h, w)
            return l + jnp.sum(gh.astype(jnp.float32)) + jnp.sum(
                gw.astype(jnp.float32))

        t = timed(agg_fb, h0, w0)
        print(json.dumps({"probe": f"one_agg_fwd_bwd dw={with_dw}",
                          "time_s": round(t, 5)}), flush=True)

    # the logits pipeline alone (picks + segmax + exp + densum), fwd+bwd
    from hyperspace_tpu.nn.scatter import (
        pick_receivers,
        pick_senders,
        planned_segment_max_1d,
        planned_segment_sum_1d,
    )
    from hyperspace_tpu.kernels.segment import NEG_FILL as _NEG

    a0 = jnp.ones((num_nodes,), jnp.float32)
    maskf = ga.edge_mask.astype(jnp.float32)

    @jax.jit  # hyperlint: disable=jit-cache-defeat — one-shot profiler: main runs once per process
    def logits_fb(a_s, a_r):
        def f(as_, ar_):
            logits = (pick_senders(as_, ga.senders, ga.receivers,
                                   ga.rev_perm, pb, pc, pf, num_nodes)
                      + pick_receivers(ar_, ga.receivers, pb, pc, pf,
                                       num_nodes))
            lm = jnp.where(maskf > 0, logits, _NEG)
            seg_max = planned_segment_max_1d(lm, ga.receivers, pb, pc, pf,
                                             num_nodes)
            seg_max = jnp.where(seg_max > 0.5 * _NEG, seg_max, 0.0)
            w = jnp.exp(lm - seg_max[ga.receivers]) * maskf
            den = planned_segment_sum_1d(w, ga.receivers, pb, pc, pf,
                                         num_nodes)
            return jnp.sum(w) + jnp.sum(den)
        l, (g1, g2) = jax.value_and_grad(f, argnums=(0, 1))(a_s, a_r)
        return l + jnp.sum(g1) + jnp.sum(g2)

    t = timed(logits_fb, a0, a0)
    print(json.dumps({"probe": "logits_pipeline_fwd_bwd",
                      "time_s": round(t, 5)}), flush=True)


if __name__ == "__main__":
    main()
