"""Measure the host→device transfer floor behind the sampled pipeline.

VERDICT r4 #8 asks for a direct measurement backing the claim that the
sampling-inclusive throughput gap is the remote-attach tunnel, not the
pipeline: this probe times raw ``jax.device_put`` of (a) a buffer the
size of one ``SampledBatchStream`` chunk (~14.7 MB) and (b) a small
control, reports MB/s, and converts the chunk time into the per-step
overhead it implies at ``chunk_steps = 64`` — directly comparable to
the measured device-only vs sampling-inclusive step gap in
``bench.py``'s ``hgcn_sampled`` detail.

On a directly attached host (or CPU backend) the same probe measures
GB/s and the implied overhead vanishes — run it both ways to separate
environment from pipeline.  One JSON line.
"""

from __future__ import annotations

import argparse
import json
import time


def _put_time(arrs, repeats):
    import jax

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = [jax.device_put(a) for a in arrs]
        for o in out:
            jax.device_get(o.ravel()[-1])   # tunnel-safe completion barrier
        best = min(best, time.perf_counter() - t0)
        for o in out:
            o.delete()
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--chunk-steps", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=512)
    args = ap.parse_args()

    import jax
    import numpy as np

    s, b = args.chunk_steps, args.batch_size
    # the exact shapes SampledBatchStream ships per NC chunk at the
    # bench config (fanouts (10, 10)): seeds, two pyramid levels, labels
    chunk = [np.random.randint(0, 169_343, (s, b), dtype=np.int32),
             np.random.randint(0, 169_343, (s, b, 10), dtype=np.int32),
             np.random.randint(0, 169_343, (s, b, 10, 10), dtype=np.int32),
             np.random.randint(0, 40, (s, b), dtype=np.int32)]
    nbytes = sum(a.nbytes for a in chunk)
    t_chunk = _put_time(chunk, args.repeats)
    small = [np.zeros((8, 128), np.float32)]
    t_small = _put_time(small, args.repeats)

    per_step_ms = t_chunk / s * 1e3
    print(json.dumps({
        "backend": jax.default_backend(),
        "chunk_mb": round(nbytes / 1e6, 2),
        "chunk_put_s": round(t_chunk, 4),
        "mb_per_s": round(nbytes / 1e6 / t_chunk, 1),
        "small_put_ms": round(t_small * 1e3, 3),
        "implied_overhead_ms_per_step": round(per_step_ms, 3),
        "implied_inclusive_samples_per_s_at_2p1ms_device": round(
            b / (2.1e-3 + per_step_ms / 1e3), 1),
    }))


if __name__ == "__main__":
    main()
