"""Hyperbolic-vs-Euclidean quality control for HGCN (VERDICT r1 #4a).

Trains the *same* architecture (HGCConv stack + Fermi–Dirac LP decoder,
one shared codepath) with kind="lorentz" vs kind="euclidean" (flat GCN
control) on hierarchy graphs, several seeds each, and prints one JSON
line per run plus a summary.  The point: on hierarchical data the
hyperbolic model must beat the flat control, anchoring the "matching
ROC-AUC" claim to a falsifiable comparison while the real reference
datasets are unavailable.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/euclidean_control.py --nodes 4096 --steps 400
"""

from __future__ import annotations

import argparse
import json


def run_one(kind: str, nodes: int, steps: int, seed: int,
            feat_dim: int = 16, ancestor_hops: int = 4):
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=nodes, feat_dim=feat_dim, ancestor_hops=ancestor_hops,
        seed=seed)
    split = G.split_edges(edges, nodes, x, seed=seed)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(64, 16),
                          kind=kind)
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=seed)
    ga = hgcn._device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    for _ in range(steps):
        state, loss = hgcn.train_step_lp(model, opt, nodes, state, ga,
                                         train_pos)
    ev = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
    return {"kind": kind, "seed": seed, "nodes": nodes, "steps": steps,
            "loss": round(float(loss), 4),
            "test_roc_auc": round(ev["roc_auc"], 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--seeds", type=int, default=3)
    args = ap.parse_args()

    import numpy as np

    results = {"lorentz": [], "euclidean": []}
    for seed in range(args.seeds):
        for kind in ("lorentz", "euclidean"):
            r = run_one(kind, args.nodes, args.steps, seed)
            results[kind].append(r["test_roc_auc"])
            print(json.dumps(r), flush=True)
    summary = {
        "lorentz_auc_mean": round(float(np.mean(results["lorentz"])), 4),
        "euclidean_auc_mean": round(float(np.mean(results["euclidean"])), 4),
        "delta": round(float(np.mean(results["lorentz"])
                             - np.mean(results["euclidean"])), 4),
    }
    print(json.dumps({"summary": summary}), flush=True)


if __name__ == "__main__":
    main()
