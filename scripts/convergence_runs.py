"""Full-scale HGCN LP convergence runs (VERDICT r2 next #3).

Trains the bench-scale (169 k-node) graph to AUC plateau for three arms —
the f32 control, the bf16 bench default, and attention aggregation with
the same dtype policy — 3 seeds each, logging a val-AUC curve every
``--eval-every`` steps and the final test AUC.  One JSON line per event;
tee stdout into docs/data/ and summarize in docs/benchmarks.md.

Seed-major order: after one seed's worth of wall-clock every arm has a
complete curve, so a truncated session still yields a comparable table.

    python scripts/convergence_runs.py --steps 6000 --eval-every 500
"""

from __future__ import annotations

import argparse
import json
import time


def arms(hgcn, jnp, feat_dim, which="all"):
    base = dict(feat_dim=feat_dim, hidden_dims=(128, 32), kind="lorentz")
    all_ = [
        # f32 control through the same planned-pairs step as the bench
        ("pairs_f32", hgcn.HGCNConfig(**base)),
        # the bench default: f32 compute, bf16 edge messages + decoder pass
        ("pairs_f32_aggbf16_decbf16",
         hgcn.HGCNConfig(**base, agg_dtype=jnp.bfloat16,
                         decoder_dtype=jnp.bfloat16)),
        # attention aggregation under the identical dtype policy — the
        # mean-vs-att quality comparison at bench scale, 3 seeds
        ("pairs_att_aggbf16_decbf16",
         hgcn.HGCNConfig(**base, use_att=True, agg_dtype=jnp.bfloat16,
                         decoder_dtype=jnp.bfloat16)),
        # stabilized attention arms (seed-0 att at lr=1e-2 trained to
        # val-AUC 0.596 by step 500 then diverged to chance by 1000):
        # lower lr with the bench dtype policy, and an f32-message control
        # to separate the lr effect from bf16-gradient noise
        ("pairs_att_lr3e3_aggbf16_decbf16",
         hgcn.HGCNConfig(**{**base, "lr": 3e-3}, use_att=True,
                         agg_dtype=jnp.bfloat16, decoder_dtype=jnp.bfloat16)),
        ("pairs_att_lr3e3_f32",
         hgcn.HGCNConfig(**{**base, "lr": 3e-3}, use_att=True)),
        # r04 shipped attention defaults: lr 3e-3 + grad clip 1.0 (what
        # `use_att=true` now builds via cli.train.hgcn_mode_defaults),
        # on the bounded-logit softmax + fused planned aggregation path
        ("pairs_att_stab",
         hgcn.HGCNConfig(**{**base, "lr": 3e-3, "clip_norm": 1.0},
                         use_att=True, agg_dtype=jnp.bfloat16,
                         decoder_dtype=jnp.bfloat16)),
    ]
    if which == "all":
        return all_
    sel = which.split(",")
    unknown = [s for s in sel if s not in {n for n, _ in all_}]
    if unknown:
        raise SystemExit(f"unknown arm(s) {unknown}")
    return [t for t in all_ if t[0] in sel]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=None,
                    help="default: full bench scale (ARXIV_NODES)")
    ap.add_argument("--steps", type=int, default=6000)
    ap.add_argument("--eval-every", type=int, default=500)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--seed-start", type=int, default=0,
                    help="resume a truncated session at this seed")
    ap.add_argument("--arms", default="all")
    ap.add_argument("--dataset", choices=["synthetic", "realistic"],
                    default="synthetic",
                    help="realistic = the DC-SBM disk dataset through the "
                         "full disk -> loader -> community-reorder -> "
                         "split path (VERDICT r4 #3); hub-skewed degree "
                         "distribution, ~30%% clusterable")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.benchmarks import hgcn_bench as HB
    from hyperspace_tpu.models import hgcn

    if args.dataset == "realistic":
        from hyperspace_tpu.data import graphs as G

        if args.nodes is not None:
            raise SystemExit(
                "--nodes only applies to the synthetic dataset; the "
                "realistic disk graph has a fixed node count")
        root = HB.ensure_disk_dataset()
        edges, x, labels, ncls, source = G.load_graph("ogbn-arxiv", root)
        edges, x, labels, _ = G.apply_locality_order(edges, x, labels,
                                                     method="community")
        n = x.shape[0]
        split = G.split_edges(edges, n, x, val_frac=0.02, test_frac=0.02,
                              seed=0, pad_multiple=65536)
        print(json.dumps({
            "phase": "dataset", "dataset": "realistic", "source": source,
            "num_nodes": n,
            "frac_clustered": (
                None if split.graph.cluster_split is None else
                round(split.graph.cluster_split.frac_clustered, 4)),
        }), flush=True)
    else:
        n = args.nodes or HB.ARXIV_NODES
        split, x = HB.arxiv_scale_split(n)
    ga = hgcn._device_graph(split.graph)
    pos = hgcn.make_planned_pairs(split.train_pos, n)
    neg_u, neg_plan = hgcn.make_static_negatives(n, int(pos.u.shape[0]), seed=0)
    sel = arms(hgcn, jnp, x.shape[1], args.arms)

    for seed in range(args.seed_start, args.seeds):
        for name, cfg in sel:
            model, opt, state = hgcn.init_lp(cfg, split.graph, seed=seed)
            t0 = time.perf_counter()
            for i in range(args.steps):
                state, loss = hgcn.train_step_lp_pairs(
                    model, opt, n, state, ga, pos, neg_u, neg_plan)
                if (i + 1) % args.eval_every == 0:
                    ev = hgcn.evaluate_lp(model, state.params, split, "val",
                                          ga=ga)
                    print(json.dumps({
                        "phase": "curve", "config": name, "seed": seed,
                        "step": i + 1, "loss": float(loss),
                        "val_auc": round(ev["roc_auc"], 4),
                        "elapsed_s": round(time.perf_counter() - t0, 1),
                    }), flush=True)
            test = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
            val = hgcn.evaluate_lp(model, state.params, split, "val", ga=ga)
            print(json.dumps({
                "phase": "final", "config": name, "seed": seed,
                "nodes": n, "steps": args.steps, "loss": float(loss),
                "test_auc": round(test["roc_auc"], 4),
                "val_auc": round(val["roc_auc"], 4),
                "train_s": round(time.perf_counter() - t0, 1),
            }), flush=True)


if __name__ == "__main__":
    main()
