#!/usr/bin/env python
"""Smoke lint: the HTTP front door round trip, as a real subprocess.

export → ``serve-http`` with ``prewarm=1`` on an ephemeral port →
healthz → stats → same-bucket queries → stats → score → a malformed
request → SIGTERM drain.  Asserted (exit 1 on any miss):

- exactly one response per request (none dropped, none duplicated);
- with ``prewarm=1`` the bucket ladder is compiled BEFORE the
  listeners open, so ``jax/recompiles`` is FLAT from the **first**
  request — the stats endpoint is read before any topk, and again
  after them (docs/serving.md "Warm starts"; before PR 13 this script
  could only assert flatness across same-bucket repeats AFTER a
  warmup request);
- the served top-k matches a live engine on the same table bit-for-bit;
- a malformed request answers 400 with a typed kind and the server
  keeps serving;
- SIGTERM exits 0 with the drain notice + latency summary on stderr —
  the stdin loop's drain contract, through the socket path.

Run by ``tests/serve/test_check_http_script.py`` inside the suite,
mirroring ``check_serve_artifact.py``, so a front-door regression fails
the build.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as a plain script from anywhere (the package is not installed)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

N, D, C = 123, 8, 1.1
LISTEN_DEADLINE_S = 120.0  # first-launch jax import dominates
K = 5


def build_table():
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall

    v = 0.5 * jax.random.normal(jax.random.PRNGKey(7), (N, D), jnp.float32)
    return PoincareBall(C).expmap0(v)


class _StderrPump:
    """Drain the server's stderr on a thread so (a) the LISTEN
    deadline is actually enforced — a blocking ``readline`` on a
    wedged-but-silent server would wait forever, the exact unbounded
    shape the dryrun satellite exists to kill — and (b) the full
    stream stays collectable for the drain-notice assertions after the
    process exits."""

    def __init__(self, proc):
        self._q: queue.Queue = queue.Queue()
        self.lines: list[str] = []
        self._t = threading.Thread(target=self._pump, args=(proc,),
                                   daemon=True)
        self._t.start()

    def _pump(self, proc) -> None:
        for line in proc.stderr:
            self.lines.append(line)
            self._q.put(line)

    def next_line(self, timeout: float):
        """The next stderr line, or None after ``timeout`` seconds."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def text(self) -> str:
        self._t.join(timeout=10)
        return "".join(self.lines)


def _wait_for_port(proc, pump: _StderrPump) -> tuple[str, int]:
    """Parse the '[serve-http] listening on HOST:PORT' stderr line,
    HARD-bounded at LISTEN_DEADLINE_S — a server that wedges before
    announcing fails loudly instead of hanging the suite."""
    deadline = time.monotonic() + LISTEN_DEADLINE_S
    while time.monotonic() < deadline:
        line = pump.next_line(timeout=0.25)
        if line is None:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server died rc={proc.returncode} before "
                    f"listening:\n{pump.text()[-800:]}")
            continue
        line = line.strip()
        if "listening on" in line:
            hostport = line.rsplit(" ", 1)[-1]
            host, _, port = hostport.rpartition(":")
            return host, int(port)
    raise RuntimeError("no listening line within the deadline")


def _post(host: str, port: int, path: str, payload,
          raw: bytes | None = None):
    """(status, parsed body) over one fresh connection."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        body = raw if raw is not None else json.dumps(payload).encode()
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def _get(host: str, port: int, path: str):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def main(out_dir: str | None = None) -> int:
    import numpy as np

    from hyperspace_tpu.serve import QueryEngine, export_artifact

    table = np.asarray(build_table())
    spec = ("poincare", C)
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = os.path.join(tmp.name, "artifact")
    proc = None
    try:
        export_artifact(out_dir, table, spec, model_config={"c": C},
                        overwrite=True)
        live = QueryEngine(table, spec)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "hyperspace_tpu.cli.serve",
             "serve-http", f"artifact={out_dir}", "port=0",
             "host=127.0.0.1", "max_wait_us=1000", "telemetry=1",
             "prewarm=1", f"k={K}"],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        pump = _StderrPump(proc)
        host, port = _wait_for_port(proc, pump)

        sent = answered = 0

        status, health = _get(host, port, "/healthz")
        sent += 1
        answered += 1
        if status != 200 or health.get("ok") is not True:
            print(f"HEALTHZ BROKEN: {status} {health}")
            return 1

        # recompile count BEFORE any topk: prewarm=1 compiled the whole
        # ladder before the listener opened, so the FIRST real request
        # must find its executable warm (stats itself compiles nothing)
        status, stats0 = _post(host, port, "/v1/stats", {})
        sent += 1
        answered += 1
        if status != 200 or stats0.get("prewarmed", 0) <= 0:
            print(f"PREWARM DID NOT RUN: {status} {stats0.get('prewarmed')}")
            return 1

        ids0 = [0, 1, 2]
        status, first = _post(host, port, "/v1/topk",
                              {"ids": ids0, "k": K})
        sent += 1
        answered += 1
        if status != 200:
            print(f"FIRST QUERY FAILED: {status} {first}")
            return 1
        li, ld = (np.asarray(a) for a in live.topk_neighbors(
            np.asarray(ids0, np.int32), K))
        if not np.array_equal(li, np.asarray(first["neighbors"])):
            print(f"SERVED NEIGHBORS DIFFER from live engine:\n"
                  f"{li}\nvs\n{first['neighbors']}")
            return 1
        if not np.array_equal(
                ld.astype(np.float32).view(np.uint32),
                np.asarray(first["dists"],
                           np.float32).view(np.uint32)):
            print("SERVED DISTANCES not bit-identical to live engine")
            return 1

        status, stats1 = _post(host, port, "/v1/stats", {})
        sent += 1
        answered += 1
        for qids in ([3, 4, 5], [10, 11, 12], [20, 21, 22]):
            status, r = _post(host, port, "/v1/topk",
                              {"ids": qids, "k": K})
            sent += 1
            answered += 1
            if status != 200 or len(r["neighbors"]) != len(qids):
                print(f"QUERY {qids} FAILED: {status} {r}")
                return 1
        status, stats2 = _post(host, port, "/v1/stats", {})
        sent += 1
        answered += 1
        if stats2["recompiles"] != stats1["recompiles"]:
            print(f"RECOMPILES NOT FLAT across same-bucket requests: "
                  f"{stats1['recompiles']} -> {stats2['recompiles']}")
            return 1
        # the prewarm contract: flat from the FIRST request, not merely
        # across repeats after a warmup — the pre-first-query reading
        # equals the post-queries reading
        if stats2["recompiles"] != stats0["recompiles"]:
            print(f"RECOMPILES NOT FLAT FROM THE FIRST REQUEST despite "
                  f"prewarm=1: {stats0['recompiles']} -> "
                  f"{stats2['recompiles']}")
            return 1

        status, r = _post(host, port, "/v1/score",
                          {"u": [0, 1], "v": [2, 3]})
        sent += 1
        answered += 1
        if status != 200 or len(r["scores"]) != 2:
            print(f"SCORE FAILED: {status} {r}")
            return 1

        # a malformed request answers a typed 400 and the server lives
        status, r = _post(host, port, "/v1/topk", None,
                          raw=b"this is not json")
        sent += 1
        answered += 1
        if status != 400 or r["error"]["kind"] != "parse":
            print(f"MALFORMED REQUEST mishandled: {status} {r}")
            return 1
        status, r = _post(host, port, "/v1/topk", {"ids": [0], "k": K})
        sent += 1
        answered += 1
        if status != 200:
            print(f"SERVER DID NOT SURVIVE a malformed request: {status}")
            return 1

        if sent != answered:
            print(f"RESPONSE COUNT DRIFT: sent {sent}, answered "
                  f"{answered}")
            return 1

        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            print("DRAIN HUNG: SIGTERM did not stop the server in 60 s")
            return 1
        err = pump.text()
        if proc.returncode != 0:
            print(f"DRAIN EXIT CODE {proc.returncode}; stderr:\n{err}")
            return 1
        if "drained" not in err or "latency e2e_ms" not in err:
            print(f"DRAIN NOTICE / latency summary missing; stderr:\n"
                  f"{err}")
            return 1
        print(f"serve-http round trip OK: {sent} requests, {answered} "
              f"responses, recompiles flat at {stats2['recompiles']}, "
              "drained clean")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
