"""hyperlint framework contracts: suppressions, output formats, CLI.

The per-rule good/bad fixtures live in test_rules.py; this file covers
the machinery every rule rides on (hyperspace_tpu/analysis/core.py).
"""

import json
import os

import pytest

from hyperspace_tpu.analysis import __main__ as cli
from hyperspace_tpu.analysis.core import (Finding, lint_file, lint_paths,
                                          make_context)
from hyperspace_tpu.analysis.rules import ALL_RULES, RULES_BY_ID
from hyperspace_tpu.analysis.rules.exceptions import SwallowBaseExceptionRule

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")

BAD = """\
def f(x):
    try:
        return x()
    except BaseException:
        pass
"""


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_suppression_silences_exactly_the_named_rule(tmp_path):
    rule = [SwallowBaseExceptionRule()]
    path = _write(tmp_path, "bad.py", BAD)
    assert lint_file(path, rules=rule).findings, "fixture must fire"
    suppressed = BAD.replace(
        "except BaseException:",
        "except BaseException:  # hyperlint: disable=swallow-base-exception"
        " — fixture reason")
    path = _write(tmp_path, "ok.py", suppressed)
    assert lint_file(path, rules=rule).findings == []
    # a DIFFERENT rule id on the line does not silence this rule
    wrong = BAD.replace(
        "except BaseException:",
        "except BaseException:  # hyperlint: disable=tracer-leak")
    path = _write(tmp_path, "wrong.py", wrong)
    assert lint_file(path, rules=rule).findings


def test_suppression_takes_comma_separated_ids(tmp_path):
    rule = [SwallowBaseExceptionRule()]
    both = BAD.replace(
        "except BaseException:",
        "except BaseException:  "
        "# hyperlint: disable=tracer-leak,swallow-base-exception")
    path = _write(tmp_path, "both.py", both)
    assert lint_file(path, rules=rule).findings == []


def test_report_json_artifact_shape(tmp_path):
    path = _write(tmp_path, "bad.py", BAD)
    report = lint_file(path, rules=[SwallowBaseExceptionRule()])
    doc = report.to_json()
    assert doc["version"] == 1 and doc["clean"] is False
    (f,) = doc["findings"]
    assert set(f) == {"rule", "severity", "path", "line", "col", "message"}
    assert f["rule"] == "swallow-base-exception" and f["line"] == 4
    assert doc["counts"] == {"swallow-base-exception": 1}
    assert report.exit_code() == 1


def test_parse_error_is_reported_not_raised(tmp_path):
    path = _write(tmp_path, "broken.py", "def f(:\n")
    report = lint_paths([path], root=str(tmp_path))
    assert report.findings == [] and len(report.parse_errors) == 1
    assert report.exit_code() == 1


def test_single_parse_alias_resolution(tmp_path):
    path = _write(tmp_path, "m.py",
                  "import jax.numpy as q\nimport numpy\n"
                  "from jax import lax as L\n\n\nx = q.zeros(3)\n")
    ctx = make_context(path, rel="m.py", root=str(tmp_path))
    assert ctx.aliases["q"] == "jax.numpy"
    assert ctx.aliases["L"] == "jax.lax"
    call = ctx.tree.body[-1].value
    assert ctx.resolve(call.func) == "jax.numpy.zeros"


def test_every_rule_is_registered_with_id_and_summary():
    assert len(ALL_RULES) >= 8
    for cls in ALL_RULES:
        assert cls.id and cls.summary and cls.severity in (
            "error", "warning", "note")
    assert len(RULES_BY_ID) == len(ALL_RULES)  # ids are unique


def test_cli_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_RULES:
        assert cls.id in out


def test_cli_bad_path_and_bad_rule_are_usage_errors(tmp_path):
    with pytest.raises(SystemExit):
        cli.main([str(tmp_path / "nope.py")])
    with pytest.raises(SystemExit):
        cli.main(["--rules", "not-a-rule", str(tmp_path)])


def test_cli_json_on_bad_fixture(capsys):
    bad = os.path.join(FIXTURES, "bad_exceptions.py")
    rc = cli.main(["--json", "--rules", "swallow-base-exception", bad])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["clean"] is False
    assert {f["rule"] for f in doc["findings"]} == {"swallow-base-exception"}
    assert all(f["path"].startswith("tests/analysis/fixtures/")
               for f in doc["findings"])


def test_cli_human_output_and_exit_zero_on_clean(tmp_path, capsys):
    path = _write(tmp_path, "fine.py", "x = 1\n")
    rc = cli.main(["--root", str(tmp_path), str(path)])
    out = capsys.readouterr().out
    assert rc == 0 and "hyperlint OK" in out


def test_finding_render_is_clickable():
    f = Finding(rule="r", severity="error", path="a/b.py", line=3, col=7,
                message="m")
    assert f.render() == "a/b.py:3:7: [r/error] m"


# --- review regressions ------------------------------------------------------


def test_overlapping_input_paths_scan_each_file_once(tmp_path):
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    bad = sub / "bad.py"
    bad.write_text(BAD)
    report = lint_paths([str(pkg), str(sub), str(bad)],
                        root=str(tmp_path),
                        rules=[SwallowBaseExceptionRule()])
    assert report.files_scanned == 1
    assert len(report.findings) == 1
    assert report.to_json()["counts"] == {"swallow-base-exception": 1}


def test_directive_inside_string_literal_is_not_a_suppression(tmp_path):
    """The grammar lives in comments only — help text or a test string
    QUOTING a disable directive must not silence a finding on its
    line."""
    src = BAD.replace(
        "except BaseException:\n        pass",
        "except BaseException:"
        ' x = "# hyperlint: disable=swallow-base-exception"')
    path = _write(tmp_path, "quoted.py", src)
    report = lint_file(path, rules=[SwallowBaseExceptionRule()])
    assert report.findings, "string-literal directive must not suppress"
    assert {f.line for f in report.findings} == {4}  # directive's own line
    # and the real comment form still works
    real = BAD.replace(
        "except BaseException:",
        "except BaseException:  # hyperlint: disable="
        "swallow-base-exception — reason")
    path = _write(tmp_path, "real.py", real)
    assert lint_file(path, rules=[SwallowBaseExceptionRule()]).findings == []
