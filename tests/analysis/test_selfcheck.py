"""The CI gate: the shipped tree lints clean under the full rule set.

This is the acceptance contract — ``python -m hyperspace_tpu.analysis
hyperspace_tpu bench.py scripts`` exits 0 on the final tree — run
in-process (no subprocess, no jax work) so it rides in tier-1.  Every
accepted hazard in the tree carries a ``# hyperlint: disable=<rule> —
reason`` annotation; a new unannotated one fails here.
"""

import os

from hyperspace_tpu.analysis.core import lint_paths, repo_root

TARGETS = ("hyperspace_tpu", "bench.py", "scripts")


def test_tree_lints_clean():
    root = repo_root()
    report = lint_paths([os.path.join(root, t) for t in TARGETS],
                        root=root)
    assert report.parse_errors == [], report.parse_errors
    assert report.findings == [], "\n" + "\n".join(
        f.render() for f in report.findings)
    # sanity: the run actually covered the tree
    assert report.files_scanned > 80


def test_script_shims_preserve_exit_codes(capsys):
    """The migrated lint scripts keep their CLI contract (exit 0 clean)
    — the old tests cover their module APIs; this pins main()."""
    import importlib.util

    root = repo_root()
    for name in ("check_precision_policy", "check_telemetry_catalog"):
        path = os.path.join(root, "scripts", f"{name}.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0, capsys.readouterr().out
