"""Bad fixture: wall-clock durations in latency-bearing code (linted
under a pretend hyperspace_tpu/serve/ rel path; never imported)."""
import time
from time import time as now


def e2e_latency(t_enq):
    return (time.time() - t_enq) * 1e3  # direct call as left operand


def remaining(deadline):
    return deadline - time.time()  # direct call as right operand


def stage():
    t0 = time.time()  # the taint source — fires at the subtraction
    do_work()
    return time.perf_counter() - t0  # tainted name as operand


def aliased():
    start = now()  # from-import alias resolves to time.time
    do_work()
    return now() - start


def augmented(total):
    total -= time.time()  # AugAssign subtraction
    return total


def do_work():
    pass
