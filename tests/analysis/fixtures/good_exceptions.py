"""Good fixture: the legitimate broad-handler shapes."""
import queue
import shutil


def cleanup(fn, staging):
    try:
        fn()
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise  # cleanup-and-reraise: the alarm still lands


def best_effort(fn, log):
    try:
        fn()
    except Exception as e:
        log.warning(repr(e))  # handled: the failure is visible


def narrow(q):
    try:
        q.get_nowait()
    except queue.Empty:  # narrow type: out of this rule's scope
        pass
