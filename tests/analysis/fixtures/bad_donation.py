"""Bad fixture: donated buffers read after dispatch (never imported)."""
import jax


def train(state, pairs):
    step = jax.jit(lambda s, p: s, donate_argnums=(0,))
    out = step(state, pairs)
    return state.table, out  # reads donated `state` after the dispatch


def train_direct(state):
    out = jax.jit(lambda s: s, donate_argnums=(0,))(state)
    print(state)  # donated buffers already invalidated
    return out
