"""Good fixture: monotonic-clock durations and legitimate wall-clock
TIMESTAMPS — none of these are findings."""
import time


def duration():
    t0 = time.perf_counter()
    do_work()
    return (time.perf_counter() - t0) * 1e3  # the fix


def cadence(next_at):
    return time.monotonic() - next_at  # monotonic math is fine


def stamp_record():
    return {"ts": time.time()}  # a timestamp, never subtracted


def expired(deadline_epoch):
    return time.time() > deadline_epoch  # comparison, not arithmetic


def window_start():
    ts = time.time()  # stored as a stamp; no subtraction uses it
    return ts + 60.0  # addition (epoch deadline math) is not a duration


def do_work():
    pass
