"""Good fixture: every write is process-0-gated or per-host-pathed —
the two sanctioned shapes (docs/multihost.md), plus reads, which never
fire."""

import json
import os

import jax

from hyperspace_tpu.parallel import multihost as mh


def save_manifest(directory, meta):
    if jax.process_index() == 0:  # ONE writer commits shared state
        with open(os.path.join(directory, "MANIFEST.json"), "w") as f:
            json.dump(meta, f)


def export(directory, payload):
    if mh.is_primary():
        with open(os.path.join(directory, "artifact.json"), "w") as f:
            f.write(payload)


def save_shard(directory, block):
    pi = jax.process_index()
    path = os.path.join(directory, f"shard_{pi:05d}.npy")  # per-host path
    tmp = f"{path}.tmp.{pi}"
    with open(tmp, "wb") as f:
        f.write(block)
    os.replace(tmp, path)  # target resolves to the per-host path


def append_trend(path, row):
    if jax.process_index() != 0:
        return  # early-exit gate: only process 0 reaches the write
    with open(path, "a") as f:
        f.write(row)


def read_config(path):
    with open(path) as f:  # reads are always safe
        return json.load(f)
