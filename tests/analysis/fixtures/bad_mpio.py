"""Bad fixture: unguarded filesystem writes in a multihost-reachable
module — every process on a pod would race these against one shared
filesystem (linted under a pretend hyperspace_tpu/parallel/ rel path)."""

import json
import os
import shutil


def save_manifest(directory, meta):
    # no process gate, shared path: N writers race the manifest
    with open(os.path.join(directory, "MANIFEST.json"), "w") as f:
        json.dump(meta, f)


def commit(tmp_path, final_path):
    os.replace(tmp_path, final_path)  # racing atomic commits


def publish(src, dst):
    shutil.move(src, dst)


def note(path):
    path.write_text("done")


def append_row(path, row):
    with open(path, mode="a") as f:
        f.write(row)
