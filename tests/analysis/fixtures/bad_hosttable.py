"""Fixture: full-table-materialization MUST fire on every pattern here."""
import jax
import jax.numpy as jnp

from hyperspace_tpu.parallel.host_table import HostEmbedTable


def whole_master_to_device(arr):
    master = HostEmbedTable.from_array(arr, shards=4)
    return jnp.asarray(master)           # the master object itself


def to_array_then_transfer(master):
    full = master.to_array()             # sanctioned host materializer…
    return jax.device_put(full)          # …shipped whole to device


def direct_to_array_transfer(master):
    return jnp.asarray(master.to_array())


def constructed_then_put(shards):
    t = HostEmbedTable(shards)
    return jax.device_put(t)


def loaded_then_transfer(path):
    t = HostEmbedTable.load_sharded(path, shards=2)
    return jnp.asarray(t)
