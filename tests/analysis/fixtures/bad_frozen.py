"""Fixture: frozen-table-mutation MUST fire on every pattern here."""
import numpy as np


def poke_embedding_row(eng, row):
    eng.table[17] = row                  # in-place write to the table


def scale_a_lane_in_place(eng):
    eng.scan_scale[3] *= 2.0             # aug-assign subscript write


def patch_quant_codes(payload, new_codes):
    payload.codes[0:4] = new_codes       # slice write, same poke


def clobber_a_centroid(index, c):
    index.centroids[c] = np.zeros(8)     # coarse index mutated in place


def grow_a_cell(index, c):
    index.cells[c] += 1                  # postings mutated in place


def reach_into_delta_internals(live, slot):
    live._pen[slot] = float("inf")       # delta internals from outside


def tuple_target_hides_the_poke(eng, row):
    i, eng.table[5] = 0, row             # write hidden in an unpacking


def swap_a_lane_on_a_foreign_engine(eng, lane):
    eng.scan_table = lane                # rebind out from under the
    return eng                           # engine's fingerprint


def requantize_someone_elses_codebooks(quantizer, cb):
    quantizer.codebooks = cb             # foreign rebind, same hazard
