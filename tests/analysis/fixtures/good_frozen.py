"""Fixture: frozen-table-mutation must stay SILENT on all of this."""
import numpy as np


class OwnsItsArrays:
    def __init__(self, table, centroids):
        # a class initializing its OWN slots is construction, not
        # mutation of a foreign engine
        self.table = table
        self.centroids = centroids
        self.cells = [[] for _ in range(8)]

    def rebuild(self, table):
        self.table = table               # self-rebind stays sanctioned
        self.scan_scale = np.abs(table).max(axis=0)


def reads_are_fine(eng, i):
    row = eng.table[i]                   # subscript READ, not a write
    return row + eng.scan_scale[0]


def local_names_merely_shadow(rows):
    table = np.asarray(rows)
    table[0] = table[1]                  # a local array named "table"
    cells = {0: []}
    cells[0] = [1, 2]                    # plain dict, no attribute base
    return table, cells


def sanctioned_api_calls(live, ids, rows):
    live.upsert(ids, rows)               # the blessed mutation path
    live.delete(ids[:1])
    return live.master.write_back(ids, rows)


def unrelated_attributes_are_untouched(eng, stats):
    eng.generation_hint = 3              # not a frozen array attr
    stats["table"] = 1                   # dict key sharing the name
    return eng
