"""Fixture: blocking calls inside async defs (each must fire)."""

import io
import socket
import subprocess
import time


async def sleepy_handler(request):
    time.sleep(0.05)  # parks the whole event loop
    return request


async def raw_socket_probe(host, port):
    s = socket.create_connection((host, port))
    s.close()


async def sync_read(path):
    with open(path) as f:  # sync file I/O on the loop
        return f.read()


async def sync_io_open(path):
    return io.open(path).read()


async def pathlib_write(p, text):
    p.write_text(text)


async def shell_out(cmd):
    return subprocess.run(cmd)


async def outer_async():
    async def inner(p):
        # nested ASYNC def: still event-loop code, still fires
        time.sleep(0.01)

    await inner(None)
