"""Fixture: async shapes the blocking-call-in-async rule must pass."""

import asyncio
import functools
import time


async def proper_sleep(ms):
    await asyncio.sleep(ms / 1e3)  # the non-blocking analog


async def stream_client(host, port, payload):
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(payload)
    await writer.drain()
    data = await reader.read()
    writer.close()
    return data


def read_blob(path):
    # sync helper OUTSIDE any async def: runs wherever it is called
    with open(path) as f:
        return f.read()


async def offloaded_read(path):
    # the sanctioned route: blocking work rides an executor
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None,
                                      functools.partial(read_blob, path))


async def nested_sync_helper(items):
    def prep(batch):
        # nearest enclosing function is a SYNC def — out of scope (the
        # helper is handed to an executor by its caller)
        time.sleep(0.001)
        return sorted(batch)

    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, prep, items)


async def annotated_startup_read(path):
    # the escape hatch: visible, per-line, with a reason
    with open(path) as f:  # hyperlint: disable=blocking-call-in-async — startup-only config read, loop not serving yet
        return f.read()
