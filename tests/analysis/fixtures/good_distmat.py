"""Fixture: materialized-distmat must stay CLEAN on the streamed forms."""
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.distmat import pdist


def topk_chunked(q, table, k, chunk):
    """Per-chunk top-k over the tile only — the engine's two-stage
    shape: the ranked operand comes from a tile closure (the engine's
    ``masked_tile``), not from a distmat-producer binding."""
    def masked_tile(i):
        rows = jax.lax.dynamic_slice_in_dim(table, i * chunk, chunk)
        return pdist(q, rows, 1.0, manifold="poincare")  # one tile

    def body(carry, i):
        d = masked_tile(i)
        top, sel = jax.lax.top_k(-d, min(k, chunk))
        return carry, (top, sel)

    _, out = jax.lax.scan(body, None,
                          jnp.arange(table.shape[0] // chunk))
    return out


def distmat_without_sort(q, table):
    """Materializing a distmat for something OTHER than top-k (eval
    metrics) is not this rule's hazard."""
    return pdist(q, table, 1.0, manifold="poincare").mean()


def topk_of_scores(scores, k):
    """top_k over non-distance data stays clean."""
    d = scores * 2.0
    return jax.lax.top_k(d, k)


def rebound_name_goes_clean(q, table, k):
    d = pdist(q, table, 1.0, manifold="poincare")
    d = jnp.zeros((4, 4))  # rebound: no longer the distmat
    return jax.lax.top_k(d, k)
