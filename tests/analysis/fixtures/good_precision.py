"""Good fixture: bfloat16 discussed in prose (this docstring — even
jnp.bfloat16 spelled out) never fires; code goes through the policy."""

flag: str = "bfloat16"  # precision-policy: ok (CLI flag name)


def cast(x, policy):
    return policy.cast_compute(x)  # the sanctioned path
