"""Bad fixture: Python control flow on traced values (never imported)."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("k",))
def select(x, k):
    if jnp.any(x > 0):  # traced value in Python control flow
        return jax.lax.top_k(x, k)
    return x, None


@jax.jit
def count(x):
    return int(jnp.sum(x > 0))  # host cast forces the tracer concrete


def scanned(state, xs):
    def body(carry, x):
        while jnp.all(carry > 0):  # traced loop condition
            carry = carry - x
        return carry, x

    return jax.lax.scan(body, state, xs)
