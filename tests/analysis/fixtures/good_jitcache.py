"""jit-cache-defeat clean shapes: module binds, factories, attribute
binds, AOT lowering (parsed by tests, never imported)."""
import jax

double = jax.jit(lambda v: v * 2)  # module scope: bound once


def make_step(opt):
    def step(s):
        return s - opt

    return jax.jit(step)  # factory: built once, handed to the loop


def make_pair(opt):
    def step(s):
        return s * opt

    step_j = jax.jit(step)
    return step_j, opt  # escapes via the return tuple: factory


class Engine:
    def __init__(self, table):
        def scan(q):
            return q @ table

        self._scan = jax.jit(scan)  # once per object construction


def probe_cost(state):
    def step(s):
        return s + 1

    return jax.jit(step).lower(state).compile()  # AOT: no dispatch cache
