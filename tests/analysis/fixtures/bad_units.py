"""metric-unit-suffix BAD fixture: unit-smelling names, no unit suffix.

Never imported — parsed by the lint only.
"""

from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.registry import inc, observe, set_gauge


def durations():
    observe("serve/dispatch_latency", 1.2)   # duration token, no suffix
    inc("ckpt/save_time", 0.5)               # "time" smells duration
    telem.inc("train/step_seconds", 1.0)     # seconds spelled out


def sizes():
    set_gauge("cache/resident_mb", 12)       # size token, wrong suffix
    telem.set_gauge("table/upload_byte", 4)  # singular "byte"
