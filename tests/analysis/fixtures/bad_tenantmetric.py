"""Bad fixture: registry-scoped serve code writing unlabeled metrics.

Linted under a pretend ``hyperspace_tpu/serve/registry.py`` rel path
(the rule is file-scoped); never imported.
"""

from hyperspace_tpu.telemetry import registry as telem


def admit(stack):
    # aggregate-only counter: every tenant's paging folds into one
    # series and a thrashing cold tenant vanishes in the average
    telem.inc("serve/tenant_admissions")
    telem.observe("serve/tenant_admit_s", 0.25)


def residency(level):
    telem.set_gauge("serve/tenants_resident", level)
