"""Good fixture: the rebind idiom — donation leaves no stale name."""
import jax


def train(state, steps):
    step = jax.jit(lambda s: s, donate_argnums=(0,))
    for _ in range(steps):
        state = step(state)  # rebinds: the old buffers are never read
    return state


def no_donation(state, fn):
    out = jax.jit(fn)(state)  # hyperlint: disable=recompile-hazard — fixture: no donation, read-after is fine
    return state, out
