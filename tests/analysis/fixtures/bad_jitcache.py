"""jit-cache-defeat shapes: fresh function objects reaching jax.jit
per call — every call retraces (parsed by tests, never imported)."""
import jax


def serve_request(q):
    score = jax.jit(lambda v: v * 2)  # lambda: fresh object per call
    return score(q)


def dispatch(state):
    def step(s):
        return s + 1

    run = jax.jit(step)  # nested def, used locally: rebuilt per call
    return run(state)


def answer(x):
    return jax.jit(lambda v: v - 1)(x)  # returned INVOCATION, not the fn


def outer(x):
    @jax.jit
    def inner(v):  # decorated nested def: fresh jitted per outer() call
        return v + 1

    return inner(x)
