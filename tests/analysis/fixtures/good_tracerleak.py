"""Good fixture: static introspection and device-side branching."""
import jax
import jax.numpy as jnp


@jax.jit
def select(x):
    if jnp.ndim(x) == 1:  # shape introspection is static under trace
        x = x[None, :]
    return jnp.where(x > 0, x, 0.0)  # device-side branch


@jax.jit
def clipped(x, mode="soft"):
    if mode == "soft":  # Python branch on a static python value
        return jnp.tanh(x)
    return jnp.clip(x, -1.0, 1.0)
