"""Fixture: unbounded sleep-and-retry loops (each must fire)."""

import itertools
import time


def retry_forever(op):
    while True:  # no attempt cap, no deadline: hangs on a hard failure
        try:
            return op()
        except IOError:
            time.sleep(0.1)


def poll_forever(ready):
    for _ in itertools.count():
        if ready():
            break
        time.sleep(1.0)


def spin_forever(flaky):
    while 1:
        if flaky():
            return True
        time.sleep(0.01)
