"""Good fixture: the sanctioned jit idioms (never imported)."""
from functools import partial

import jax

double = jax.jit(lambda v: v * 2)  # module scope: compiled once


@partial(jax.jit, static_argnames=("k",))
def topk(x, k=8):  # hashable static default
    return jax.lax.top_k(x, k)


def make_step(cfg):
    """Factory: builds the jitted step ONCE and returns it."""

    def body(state):
        return state

    return jax.jit(body, donate_argnums=(0,))


def caller(state):
    return topk(state, k=4)  # hashable static value at the call site
