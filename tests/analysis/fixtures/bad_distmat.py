"""Fixture: materialized-distmat MUST fire on every pattern here."""
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.distmat import pdist


def topk_via_full_distmat(q, table, k):
    d = pdist(q, table, 1.0, manifold="poincare")   # [B, N] in HBM
    vals, idx = jax.lax.top_k(-d, k)
    return idx, -vals


def topk_direct(q, table, k):
    return jax.lax.top_k(-pdist(q, table, 1.0, manifold="lorentz"), k)


def topk_broadcast_dist(man, q, table, k):
    d = man.dist(q[:, None, :], table[None, :, :])   # O(N²) broadcast
    return jax.lax.top_k(-d, k)


def taint_survives_a_later_nested_rebind(q, table, k):
    d = pdist(q, table, 1.0, manifold="poincare")
    out = jax.lax.top_k(-d, k)  # must fire: the rebind below is LATER

    def helper():
        d = jnp.zeros((2, 2))  # source-order taint: this clears d only
        return d               # for sites after this line

    return out, helper
