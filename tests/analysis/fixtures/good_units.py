"""metric-unit-suffix GOOD fixture: proper suffixes, unitless names,
and shapes the rule must not touch.  Never imported — parsed only."""

from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.registry import inc, observe, set_gauge


def suffixed():
    observe("serve/e2e_ms", 1.2)        # milliseconds, suffixed
    inc("jax/compile_s", 0.5)           # seconds, suffixed
    set_gauge("ckpt/bytes", 100)        # bare unit as final segment
    telem.inc("host_table/upload_rows", 8)
    telem.observe("serve/queue_wait_ms", 0.1)  # "wait" token + suffix


def unitless():
    inc("serve/requests")               # a count: no unit to name
    set_gauge("prefetch/queue_depth", 3)
    telem.inc("serve/cache_hit")


def out_of_scope():
    h = object()
    # instance observe with a NUMBER first arg (the histogram kind's
    # value call) has no name literal — never scanned
    getattr(h, "observe", lambda v: None)(1.25)
    name = "serve/" + "dispatch_latency"
    # dynamically-built names cannot be judged — out of scope
    telem.inc(name)
