"""Fixture: full-table-materialization stays CLEAN on the bounded forms."""
import jax.numpy as jnp

from hyperspace_tpu.parallel.host_table import DeviceHotCache, HostEmbedTable


def streamed_build(master, chunk):
    """iter_chunks blocks are bounded by construction — the streamed
    index builder's read path."""
    total = 0.0
    for _start, blk in master.iter_chunks(chunk):
        total += float(jnp.asarray(blk).sum())
    return total


def gathered_rows(master, ids):
    """A gathered row BATCH is the hot-row protocol's working set, not
    the table."""
    rows = master.gather(ids)
    return jnp.asarray(rows)


def through_the_cache(master, ids):
    cache = DeviceHotCache(master, 1024)
    return cache.ensure(ids)


def rebind_clears_taint(arr):
    t = HostEmbedTable.from_array(arr)
    t = t.gather([0, 1, 2])              # rebound to a bounded batch
    return jnp.asarray(t)


def host_only_round_trip(arr, path):
    t = HostEmbedTable.from_array(arr, shards=4)
    t.save_sharded(path, shards=2)       # host I/O never touches device
    return HostEmbedTable.load_sharded(path).num_rows
