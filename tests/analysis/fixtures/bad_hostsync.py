"""Bad fixture: host syncs inside the hot regions (never imported)."""
import jax
import numpy as np

from hyperspace_tpu.telemetry.trace import span


def chunk(state, xs):
    def body(carry, x):
        loss = float(carry.sum())  # host sync inside the scan body
        arr = np.asarray(x)  # concretization inside the scan body
        return carry, loss + arr.mean()

    return jax.lax.scan(body, state, xs)


def dispatch(stepper, state):
    with span("dispatch"):
        state, loss = stepper(state)
        host = loss.item()  # sync inside the dispatch span
        fetched = jax.device_get(state)  # and a bulk device fetch
    return state, host, fetched
