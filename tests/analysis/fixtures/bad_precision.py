"""Bad fixture: bf16 literals the old regex lint missed (never
imported; linted under a pretend hyperspace_tpu/ rel path)."""
import jax.numpy as q
from jax.numpy import bfloat16


def cast(x, h):
    y = x.astype(q.bfloat16)  # aliased import — the regex blind spot
    z = h.astype("bfloat16")  # dtype string
    return y.astype(bfloat16), z  # the from-imported name
