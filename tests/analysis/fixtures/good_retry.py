"""Fixture: bounded retry shapes the unbounded-retry rule must pass."""

import time

MAX_ATTEMPTS = 5


def bounded_backoff(op, attempts=3):
    # iteration IS the budget: the checkpoint-save retry pattern
    for attempt in range(attempts + 1):
        try:
            return op()
        except IOError:
            if attempt >= attempts:
                raise
            time.sleep(0.05 * (2 ** attempt))


def deadline_poll(ready):
    deadline = time.monotonic() + 5.0
    while True:
        if ready():
            return True
        if time.monotonic() > deadline:
            raise TimeoutError("gave up")
        time.sleep(0.05)


def counted_spin(flaky):
    n = 0
    while True:
        n += 1
        if n > MAX_ATTEMPTS:
            raise RuntimeError("exhausted")
        if flaky():
            return True
        time.sleep(0.01)


def condition_driven(stop_event):
    # condition-driven while loops never fire: something external can
    # end them (the HostPrefetcher worker's shape)
    while not stop_event.is_set():
        time.sleep(0.2)
