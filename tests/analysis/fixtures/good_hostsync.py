"""Good fixture: fetches batched at boundaries, scans stay on device."""
import jax
import jax.numpy as jnp

from hyperspace_tpu.telemetry.trace import span


def chunk(state, xs):
    def body(carry, x):
        return carry + x, jnp.mean(x)  # everything stays on device

    return jax.lax.scan(body, state, xs)


def dispatch(stepper, state):
    with span("dispatch"):
        state, loss = stepper(state)  # async enqueue, no host wait
    return state, loss


def boundary_flush(log, loss):
    log.log(loss=float(loss))  # outside any hot region: fine
