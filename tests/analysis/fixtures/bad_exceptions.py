"""Bad fixture: alarm-swallowing handlers (never imported)."""


def watchdog(fn):
    try:
        fn()
    except BaseException:  # swallows KeyboardInterrupt / the alarm
        pass


def leg(fn, detail):
    try:
        detail["x"] = fn()
    except Exception:  # silent: the failure vanishes without a trace
        pass


def worst(fn):
    try:
        fn()
    except:  # noqa: E722 — bare
        pass
