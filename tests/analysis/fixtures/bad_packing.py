"""Bad fixture: hand-rolled nibble pack/unpack outside the int4 packing
boundary (never imported; linted under a pretend hyperspace_tpu/ rel
path)."""
import numpy as np


def unpack(packed):
    lo = packed & 0xF          # nibble mask (hex spelling)
    hi = packed >> 4           # nibble shift, non-constant operand
    lo2 = packed & 15          # nibble mask (decimal spelling)
    return np.concatenate([lo, hi, lo2], axis=-1)


def pack(lo, hi):
    top = hi << 4              # nibble shift (pack direction)
    top = top & 0xF0           # high-nibble mask
    return top | lo
