"""Good fixture: byte masks, constant arithmetic, and non-4 shifts are
all legitimate — only the NIBBLE idiom is fenced."""


def header(magic):
    ndim = magic & 0xFF        # byte mask — data/mnist.py's IDX header
    dtype_code = (magic >> 8) & 0xFF
    return ndim, dtype_code


SIXTEEN = 1 << 4               # pure constant arithmetic never fires
PAGE = 1024 >> 4


def halve(n):
    return n >> 1              # shift by non-4 constant
