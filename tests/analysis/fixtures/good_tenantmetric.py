"""Good fixture: the double-write convention plus a suppressed global.

Linted under a pretend ``hyperspace_tpu/serve/registry.py`` rel path;
never imported.
"""

from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.exposition import tenant_metric


def admit(stack):
    # the convention: aggregate + labeled twin, both through the
    # dynamic-name path (non-literal first args never fire)
    for name in ("serve/tenant_admissions",):
        telem.inc(name)
        telem.inc(tenant_metric(name, stack.name))
    telem.observe(tenant_metric("serve/tenant_admit_s", stack.name),
                  0.25)


def residency(level):
    # genuinely registry-global: a device-wide residency level, not one
    # tenant's load — accepted hazard, visible at the line
    telem.set_gauge(  # hyperlint: disable=tenant-unlabeled-metric — device-wide residency level, not per-tenant load
        "serve/tenants_resident", level)
