"""Bad fixture: every recompile-hazard shape fires (never imported)."""
from functools import partial

import jax


def hot_loop(xs):
    out = []
    for x in xs:
        f = jax.jit(lambda v: v * 2)  # jit built fresh per iteration
        out.append(f(x))
    return out


def per_call(x):
    return jax.jit(lambda v: v + 1)(x)  # build-and-discard wrapper


@partial(jax.jit, static_argnames=("cfg",))
def step(state, cfg={}):  # unhashable default on a static arg
    return state


def call_site(state):
    return step(state, cfg={"lr": 1e-2})  # dict passed for a static arg
