"""Per-rule contracts: each bad fixture fires (nonzero exit), each good
fixture is clean, and each rule's suppression works on its own line.

Fixture files live in ``fixtures/`` (never imported — parsed only).
The precision fixtures lint under a pretend ``hyperspace_tpu/`` rel
path because that rule is package-scoped.
"""

import os
import textwrap

import pytest

from hyperspace_tpu.analysis.core import lint_file, lint_paths
from hyperspace_tpu.analysis.rules.asyncblock import BlockingCallInAsyncRule
from hyperspace_tpu.analysis.rules.catalog import TelemetryCatalogRule
from hyperspace_tpu.analysis.rules.distmat import MaterializedDistmatRule
from hyperspace_tpu.analysis.rules.donation import DonationHazardRule
from hyperspace_tpu.analysis.rules.exceptions import SwallowBaseExceptionRule
from hyperspace_tpu.analysis.rules.flags import FlagDocDriftRule
from hyperspace_tpu.analysis.rules.frozen import FrozenTableMutationRule
from hyperspace_tpu.analysis.rules.hostsync import HostSyncRule
from hyperspace_tpu.analysis.rules.hosttable import (
    FullTableMaterializationRule)
from hyperspace_tpu.analysis.rules.jitcache import JitCacheDefeatRule
from hyperspace_tpu.analysis.rules.monoclock import MonotonicClockRule
from hyperspace_tpu.analysis.rules.mpio import MultiprocessUnsafeIORule
from hyperspace_tpu.analysis.rules.packing import PackingLiteralRule
from hyperspace_tpu.analysis.rules.precision import PrecisionLiteralRule
from hyperspace_tpu.analysis.rules.recompile import RecompileHazardRule
from hyperspace_tpu.analysis.rules.retry import UnboundedRetryRule
from hyperspace_tpu.analysis.rules.tenantmetric import (
    TenantUnlabeledMetricRule)
from hyperspace_tpu.analysis.rules.tracerleak import TracerLeakRule
from hyperspace_tpu.analysis.rules.units import MetricUnitSuffixRule

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def _lint(name, rule, rel=None):
    return lint_file(os.path.join(FIXTURES, name), rel=rel, rules=[rule()])


# --- suppression works for EVERY per-file rule -------------------------------

_PER_FILE = [
    ("bad_recompile.py", RecompileHazardRule, None),
    ("bad_jitcache.py", JitCacheDefeatRule, None),
    ("bad_donation.py", DonationHazardRule, None),
    ("bad_hostsync.py", HostSyncRule, None),
    ("bad_tracerleak.py", TracerLeakRule, None),
    ("bad_exceptions.py", SwallowBaseExceptionRule, None),
    ("bad_retry.py", UnboundedRetryRule, None),
    ("bad_asyncblock.py", BlockingCallInAsyncRule, None),
    ("bad_distmat.py", MaterializedDistmatRule, None),
    ("bad_hosttable.py", FullTableMaterializationRule, None),
    ("bad_frozen.py", FrozenTableMutationRule, None),
    ("bad_precision.py", PrecisionLiteralRule,
     "hyperspace_tpu/models/bad_precision.py"),
    ("bad_packing.py", PackingLiteralRule,
     "hyperspace_tpu/serve/bad_packing.py"),
    ("bad_units.py", MetricUnitSuffixRule, None),
    ("bad_tenantmetric.py", TenantUnlabeledMetricRule,
     "hyperspace_tpu/serve/registry.py"),
    ("bad_monoclock.py", MonotonicClockRule,
     "hyperspace_tpu/serve/bad_monoclock.py"),
    ("bad_mpio.py", MultiprocessUnsafeIORule,
     "hyperspace_tpu/parallel/bad_mpio.py"),
]


@pytest.mark.parametrize("name,rule,rel", _PER_FILE,
                         ids=[r[1].id for r in _PER_FILE])
def test_suppressing_every_finding_line_goes_clean(tmp_path, name, rule,
                                                   rel):
    """Append `# hyperlint: disable=<rule> — reason` to each finding's
    line of the bad fixture: the re-lint must be clean."""
    report = _lint(name, rule, rel=rel)
    assert report.findings, "the bad fixture must fire to prove anything"
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        lines = f.read().splitlines()
    for fnd in report.findings:
        lines[fnd.line - 1] += (f"  # hyperlint: disable={fnd.rule} "
                                "— fixture: suppression contract")
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    assert lint_file(str(p), rel=rel, rules=[rule()]).findings == []


# --- recompile-hazard ---------------------------------------------------------


def test_recompile_bad_fixture_fires_every_shape():
    report = _lint("bad_recompile.py", RecompileHazardRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 4
    assert any("inside a loop" in m for m in msgs)
    assert any("builds and discards" in m for m in msgs)
    assert any("defaults to a dict" in m for m in msgs)
    assert any("dict passed for static arg 'cfg'" in m for m in msgs)


def test_recompile_good_fixture_is_clean():
    assert _lint("good_recompile.py", RecompileHazardRule).findings == []


# --- jit-cache-defeat ---------------------------------------------------------


def test_jitcache_bad_fixture_fires_every_shape():
    report = _lint("bad_jitcache.py", JitCacheDefeatRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 4
    assert sum("a lambda" in m for m in msgs) == 2
    assert any("nested function 'step'" in m for m in msgs)
    assert any("@jax.jit on 'inner'" in m for m in msgs)


def test_jitcache_good_fixture_is_clean():
    """Module binds, factories (direct return AND assigned-then-
    returned tuple), attribute binds, and AOT `.lower` pipelines are
    all exempt."""
    assert _lint("good_jitcache.py", JitCacheDefeatRule).findings == []


def test_jitcache_returned_invocation_still_fires(tmp_path):
    """`return jax.jit(fn)(x)` returns the RESULT, not the wrapper —
    the per-call rebuild is intact and must fire (the Return exemption
    covers only an escaping callable)."""
    src = textwrap.dedent("""\
        import jax


        def answer(x):
            def fn(v):
                return v

            return jax.jit(fn)(x)
    """)
    p = tmp_path / "j.py"
    p.write_text(src)
    report = lint_file(str(p), rules=[JitCacheDefeatRule()])
    assert len(report.findings) == 1


# --- donation-hazard ----------------------------------------------------------


def test_donation_bad_fixture_fires():
    report = _lint("bad_donation.py", DonationHazardRule)
    assert report.exit_code() == 1 and len(report.findings) == 2
    assert all("'state'" in f.message for f in report.findings)


def test_donation_good_fixture_is_clean():
    assert _lint("good_donation.py", DonationHazardRule).findings == []


def test_donation_suppression(tmp_path):
    src = textwrap.dedent("""\
        import jax


        def t(state):
            step = jax.jit(lambda s: s, donate_argnums=(0,))
            out = step(state)
            return state, out  # hyperlint: disable=donation-hazard — fixture
    """)
    p = tmp_path / "d.py"
    p.write_text(src)
    assert lint_file(str(p), rules=[DonationHazardRule()]).findings == []


# --- host-sync-in-hot-path ----------------------------------------------------


def test_hostsync_bad_fixture_fires():
    report = _lint("bad_hostsync.py", HostSyncRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 4
    assert any("float(...)" in m and "lax.scan body" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any(".item()" in m and "span('dispatch')" in m for m in msgs)
    assert any("jax.device_get" in m for m in msgs)


def test_hostsync_good_fixture_is_clean():
    assert _lint("good_hostsync.py", HostSyncRule).findings == []


# --- tracer-leak --------------------------------------------------------------


def test_tracerleak_bad_fixture_fires():
    report = _lint("bad_tracerleak.py", TracerLeakRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 3
    assert any("`if`" in m for m in msgs)
    assert any("`while`" in m for m in msgs)
    assert any("int(...)" in m for m in msgs)
    assert all(f.severity == "note" for f in report.findings)


def test_tracerleak_good_fixture_is_clean():
    assert _lint("good_tracerleak.py", TracerLeakRule).findings == []


# --- swallow-base-exception ---------------------------------------------------


def test_exceptions_bad_fixture_fires():
    report = _lint("bad_exceptions.py", SwallowBaseExceptionRule)
    assert report.exit_code() == 1 and len(report.findings) == 3
    sevs = sorted(f.severity for f in report.findings)
    assert sevs == ["error", "error", "warning"]  # 2 broadest + 1 silent


def test_exceptions_good_fixture_is_clean():
    assert _lint("good_exceptions.py", SwallowBaseExceptionRule
                 ).findings == []


# --- unbounded-retry ----------------------------------------------------------


def test_retry_bad_fixture_fires_every_shape():
    report = _lint("bad_retry.py", UnboundedRetryRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 3
    assert sum("while True" in m for m in msgs) == 2  # while True + while 1
    assert any("itertools.count" in m for m in msgs)


def test_retry_good_fixture_is_clean():
    """range-bounded retries, deadline checks, attempt counters and
    condition-driven polls all pass."""
    assert _lint("good_retry.py", UnboundedRetryRule).findings == []


def test_retry_sleepless_while_true_is_fine(tmp_path):
    """A while-True with no sleep is a different shape (event loops,
    generators) — out of this rule's scope."""
    p = tmp_path / "loop.py"
    p.write_text("def f(q):\n    while True:\n        q.get()\n")
    assert lint_file(str(p), rules=[UnboundedRetryRule()]).findings == []


# --- monotonic-clock ----------------------------------------------------------


def test_monoclock_bad_fixture_fires_every_shape():
    report = _lint("bad_monoclock.py", MonotonicClockRule,
                   rel="hyperspace_tpu/serve/bad_monoclock.py")
    assert report.exit_code() == 1 and len(report.findings) == 5
    lines = {f.line for f in report.findings}
    texts = [_fixture_line("bad_monoclock.py", ln) for ln in sorted(lines)]
    # both operand positions, the tainted-name flow, the from-import
    # alias, and the AugAssign shape each land on their own line
    assert any("time.time() - t_enq" in t for t in texts)
    assert any("deadline - time.time()" in t for t in texts)
    assert any("time.perf_counter() - t0" in t for t in texts)
    assert any("now() - start" in t for t in texts)
    assert any("total -= time.time()" in t for t in texts)


def _fixture_line(name, lineno):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read().splitlines()[lineno - 1]


def test_monoclock_good_fixture_is_clean():
    report = _lint("good_monoclock.py", MonotonicClockRule,
                   rel="hyperspace_tpu/telemetry/good_monoclock.py")
    assert report.findings == []


@pytest.mark.parametrize("rel", [
    "hyperspace_tpu/serve/x.py",
    "hyperspace_tpu/telemetry/x.py",
    "hyperspace_tpu/train/x.py",
])
def test_monoclock_fires_in_every_latency_plane(rel):
    report = _lint("bad_monoclock.py", MonotonicClockRule, rel=rel)
    assert report.findings


@pytest.mark.parametrize("rel", [
    "hyperspace_tpu/parallel/bad_monoclock.py",  # outside latency planes
    "scripts/bad_monoclock.py",                  # outside the package
    "bench.py",
])
def test_monoclock_out_of_scope_is_clean(rel):
    report = _lint("bad_monoclock.py", MonotonicClockRule, rel=rel)
    assert report.findings == []


# --- metric-unit-suffix -------------------------------------------------------


def test_units_bad_fixture_fires_every_shape():
    report = _lint("bad_units.py", MetricUnitSuffixRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 5
    assert sum("duration token" in m for m in msgs) == 3
    assert sum("size token" in m for m in msgs) == 2
    assert any("'serve/dispatch_latency'" in m for m in msgs)
    assert any("'cache/resident_mb'" in m for m in msgs)


def test_units_good_fixture_is_clean():
    """Suffixed names, bare-unit final segments (ckpt/bytes), unitless
    counts, instance observes, and dynamic names all pass."""
    assert _lint("good_units.py", MetricUnitSuffixRule).findings == []


def test_units_severity_is_warning():
    report = _lint("bad_units.py", MetricUnitSuffixRule)
    assert all(f.severity == "warning" for f in report.findings)


# --- tenant-unlabeled-metric --------------------------------------------------

_REGISTRY_REL = "hyperspace_tpu/serve/registry.py"


def test_tenantmetric_bad_fixture_fires_every_shape():
    """Unlabeled inc / observe / set_gauge literals in registry-scoped
    serve code each fire."""
    report = _lint("bad_tenantmetric.py", TenantUnlabeledMetricRule,
                   rel=_REGISTRY_REL)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 3
    assert any("'serve/tenant_admissions'" in m for m in msgs)
    assert any("'serve/tenant_admit_s'" in m for m in msgs)
    assert any("'serve/tenants_resident'" in m for m in msgs)
    assert all("tenant label" in m for m in msgs)


def test_tenantmetric_good_fixture_is_clean():
    """tenant_metric twins, dynamic names, and a suppressed genuinely-
    global gauge all pass."""
    assert _lint("good_tenantmetric.py", TenantUnlabeledMetricRule,
                 rel=_REGISTRY_REL).findings == []


def test_tenantmetric_out_of_scope_is_clean():
    """The same writes outside registry-scoped serve code never fire —
    the batcher's lifecycle double-writes are already labeled and the
    rest of the package predates tenancy."""
    for rel in ("hyperspace_tpu/serve/batcher.py",
                "hyperspace_tpu/telemetry/registry.py", None):
        report = _lint("bad_tenantmetric.py", TenantUnlabeledMetricRule,
                       rel=rel)
        assert report.findings == [], rel


def test_tenantmetric_severity_is_warning():
    report = _lint("bad_tenantmetric.py", TenantUnlabeledMetricRule,
                   rel=_REGISTRY_REL)
    assert all(f.severity == "warning" for f in report.findings)


# --- blocking-call-in-async ---------------------------------------------------


def test_asyncblock_bad_fixture_fires_every_shape():
    """time.sleep, a socket-module call, builtin open, io.open,
    pathlib-style write_text, subprocess.run, and a NESTED async def's
    sleep all fire."""
    report = _lint("bad_asyncblock.py", BlockingCallInAsyncRule)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 7
    assert any("asyncio.sleep" in m for m in msgs)
    assert any("socket.create_connection" in m for m in msgs)
    assert any("write_text" in m for m in msgs)
    assert any("subprocess" in m for m in msgs)


def test_asyncblock_good_fixture_is_clean():
    """await asyncio.sleep, asyncio streams, executor offload, a sync
    helper nested in an async def, sync module-level I/O, and the
    annotated escape hatch all pass."""
    assert _lint("good_asyncblock.py", BlockingCallInAsyncRule
                 ).findings == []


def test_asyncblock_sync_def_is_out_of_scope(tmp_path):
    """The same calls in a plain def never fire — the rule is about the
    event loop, not about sleeping in general."""
    p = tmp_path / "sync.py"
    p.write_text("import time\n"
                 "def f(path):\n"
                 "    time.sleep(0.1)\n"
                 "    return open(path).read()\n")
    assert lint_file(str(p),
                     rules=[BlockingCallInAsyncRule()]).findings == []


def test_asyncblock_aliased_import_resolves(tmp_path):
    """`import time as t; t.sleep(...)` inside an async def still fires
    (the alias-resolution contract every resolved-name rule shares)."""
    p = tmp_path / "alias.py"
    p.write_text("import time as t\n"
                 "async def f():\n"
                 "    t.sleep(0.1)\n")
    report = lint_file(str(p), rules=[BlockingCallInAsyncRule()])
    assert len(report.findings) == 1


# --- materialized-distmat -----------------------------------------------------


def test_distmat_bad_fixture_fires_every_shape():
    """pdist-via-name, pdist-direct, the broadcast .dist idiom, and a
    taint that survives a LATER nested-scope rebind (source-order
    tracking, not ast.walk order) all fire."""
    report = _lint("bad_distmat.py", MaterializedDistmatRule)
    assert report.exit_code() == 1 and len(report.findings) == 4


def test_distmat_good_fixture_is_clean():
    """Tile-closure chunked scans, unsorted distmats, non-distance
    top_k and rebound names all pass."""
    assert _lint("good_distmat.py", MaterializedDistmatRule).findings == []


def test_distmat_kernels_dir_is_out_of_scope(tmp_path):
    """kernels/ is the sanctioned home of tile-level sorting — the same
    source that fires elsewhere is clean under a kernels/ rel path."""
    src = ("import jax\nfrom hyperspace_tpu.kernels.distmat import pdist\n"
           "def f(q, t, k):\n"
           "    d = pdist(q, t, 1.0, manifold='poincare')\n"
           "    return jax.lax.top_k(-d, k)\n")
    p = tmp_path / "x.py"
    p.write_text(src)
    assert lint_file(str(p), rel="hyperspace_tpu/serve/x.py",
                     rules=[MaterializedDistmatRule()]).findings
    assert lint_file(str(p), rel="hyperspace_tpu/kernels/x.py",
                     rules=[MaterializedDistmatRule()]).findings == []
    assert lint_file(str(p), rel="hyperspace_tpu/kernels/deep/x.py",
                     rules=[MaterializedDistmatRule()]).findings == []


# --- full-table-materialization ----------------------------------------------


def test_hosttable_bad_fixture_fires_on_every_pattern():
    """Master-object transfer, to_array-then-put (named and direct),
    constructor-then-put, and load_sharded-then-asarray all fire."""
    report = _lint("bad_hosttable.py", FullTableMaterializationRule)
    assert report.exit_code() == 1 and len(report.findings) == 5


def test_hosttable_good_fixture_is_clean():
    """Streamed iter_chunks blocks, gathered row batches, the hot-row
    cache, rebound names and host-only save/load all pass."""
    assert _lint("good_hosttable.py",
                 FullTableMaterializationRule).findings == []


def test_hosttable_hot_cache_module_is_out_of_scope(tmp_path):
    """parallel/host_table.py is the ONE sanctioned home of
    master→device transfers — the same source that fires elsewhere is
    clean under its rel path."""
    src = ("import jax.numpy as jnp\n"
           "from hyperspace_tpu.parallel.host_table import HostEmbedTable\n"
           "def f(arr):\n"
           "    t = HostEmbedTable.from_array(arr)\n"
           "    return jnp.asarray(t.to_array())\n")
    p = tmp_path / "x.py"
    p.write_text(src)
    assert lint_file(str(p), rel="hyperspace_tpu/train/x.py",
                     rules=[FullTableMaterializationRule()]).findings
    assert lint_file(
        str(p), rel="hyperspace_tpu/parallel/host_table.py",
        rules=[FullTableMaterializationRule()]).findings == []


# --- frozen-table-mutation ----------------------------------------------------


def test_frozen_bad_fixture_fires_on_every_pattern():
    """Subscript pokes (plain, aug-assign, slice, tuple-hidden),
    delta-internal reach-ins, and foreign-attribute rebinds all
    fire."""
    report = _lint("bad_frozen.py", FrozenTableMutationRule)
    assert report.exit_code() == 1 and len(report.findings) == 9
    msgs = " ".join(f.message for f in report.findings)
    assert "'.table[...]'" in msgs
    assert "'._pen[...]'" in msgs
    assert "rebinding frozen array '.scan_table'" in msgs


def test_frozen_good_fixture_is_clean():
    """Own-slot construction, self-rebinds, reads, shadowing locals,
    dict keys, and the sanctioned upsert/delete/write_back API are all
    silent."""
    assert _lint("good_frozen.py", FrozenTableMutationRule).findings == []


def test_frozen_sanctioned_homes_are_out_of_scope(tmp_path):
    """serve/delta.py and parallel/host_table.py own the writes — the
    same source that fires elsewhere is clean under their rel
    paths."""
    src = ("def apply(self, slot, row):\n"
           "    self._rows[slot] = row\n"
           "    self._pen[slot] = 0.0\n")
    p = tmp_path / "x.py"
    p.write_text(src)
    assert lint_file(str(p), rel="hyperspace_tpu/serve/x.py",
                     rules=[FrozenTableMutationRule()]).findings
    for home in ("hyperspace_tpu/serve/delta.py",
                 "hyperspace_tpu/parallel/host_table.py"):
        assert lint_file(str(p), rel=home,
                         rules=[FrozenTableMutationRule()]).findings == []


def test_frozen_severity_is_error():
    """A stale-visibility hazard is never advisory."""
    report = _lint("bad_frozen.py", FrozenTableMutationRule)
    assert {f.severity for f in report.findings} == {"error"}


# --- precision-literal --------------------------------------------------------


def test_precision_bad_fixture_fires_under_package_rel():
    report = _lint("bad_precision.py", PrecisionLiteralRule,
                   rel="hyperspace_tpu/models/bad_precision.py")
    assert report.exit_code() == 1 and len(report.findings) >= 4
    whats = " ".join(f.message for f in report.findings)
    assert "q.bfloat16" in whats  # the aliased import the regex missed
    assert '"bfloat16" dtype string' in whats
    assert "from-import" in whats


def test_precision_good_fixture_is_clean_under_package_rel():
    report = _lint("good_precision.py", PrecisionLiteralRule,
                   rel="hyperspace_tpu/models/good_precision.py")
    assert report.findings == []


@pytest.mark.parametrize("rel", [
    "hyperspace_tpu/precision.py",          # the policy itself
    "hyperspace_tpu/kernels/bad.py",        # kernels are exempt
    "scripts/bad_precision.py",             # outside the package
])
def test_precision_scope_exemptions(rel):
    report = _lint("bad_precision.py", PrecisionLiteralRule, rel=rel)
    assert report.findings == []


def test_precision_hyperlint_suppression(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("import jax.numpy as jnp\n"
                 "DT = jnp.bfloat16  "
                 "# hyperlint: disable=precision-literal — fixture\n")
    report = lint_file(str(p), rel="hyperspace_tpu/models/m.py",
                       rules=[PrecisionLiteralRule()])
    assert report.findings == []


# --- packing-literal ----------------------------------------------------------


def test_packing_bad_fixture_fires_every_shape():
    report = _lint("bad_packing.py", PackingLiteralRule,
                   rel="hyperspace_tpu/serve/bad_packing.py")
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 5
    assert sum("`& 0xf`" in m for m in msgs) == 2   # hex AND decimal 15
    assert any("`& 0xf0`" in m for m in msgs)
    assert any("`>> 4`" in m for m in msgs)
    assert any("`<< 4`" in m for m in msgs)


def test_packing_good_fixture_is_clean():
    """Byte masks (`& 0xFF` — data/mnist.py's IDX header), pure-constant
    shifts (`1 << 4`), and non-4 shifts never fire."""
    report = _lint("good_packing.py", PackingLiteralRule,
                   rel="hyperspace_tpu/data/good_packing.py")
    assert report.findings == []


def test_packing_mnist_header_mask_is_clean():
    """The REAL data/mnist.py (`magic & 0xFF`) stays clean — the rule
    fences nibble masks, not byte masks."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "hyperspace_tpu", "data", "mnist.py")
    report = lint_file(path, rel="hyperspace_tpu/data/mnist.py",
                       rules=[PackingLiteralRule()])
    assert report.findings == []


@pytest.mark.parametrize("rel", [
    "hyperspace_tpu/serve/quant.py",        # the packing boundary itself
    "hyperspace_tpu/kernels/scan_topk.py",  # kernels unpack in-register
    "scripts/bad_packing.py",               # outside the package
])
def test_packing_scope_exemptions(rel):
    report = _lint("bad_packing.py", PackingLiteralRule, rel=rel)
    assert report.findings == []


# --- telemetry-catalog (project rule) ----------------------------------------


def _catalog_tree(tmp_path, doc_row):
    pkg = tmp_path / "hyperspace_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'from hyperspace_tpu.telemetry import registry as telem\n\n\n'
        'def f():\n    telem.inc("foo/undocumented")\n'
        '    return telem.default_registry().get("bar/read")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| name | kind |\n|---|---|\n" + doc_row)
    return tmp_path


def test_catalog_bad_tree_fires(tmp_path):
    root = _catalog_tree(tmp_path, "| `bar/read` | counter |\n")
    report = lint_paths([str(root / "hyperspace_tpu")], root=str(root),
                        rules=[TelemetryCatalogRule()])
    assert report.exit_code() == 1 and len(report.findings) == 1
    assert "foo/undocumented" in report.findings[0].message
    # suppression on the inc() line silences the project-rule finding too
    mod = root / "hyperspace_tpu" / "mod.py"
    lines = mod.read_text().splitlines()
    lines[report.findings[0].line - 1] += (
        "  # hyperlint: disable=telemetry-catalog — fixture")
    mod.write_text("\n".join(lines) + "\n")
    report = lint_paths([str(root / "hyperspace_tpu")], root=str(root),
                        rules=[TelemetryCatalogRule()])
    assert report.findings == []


def test_catalog_good_tree_is_clean(tmp_path):
    root = _catalog_tree(
        tmp_path, "| `bar/read` | counter |\n| `foo/undocumented` | c |\n")
    report = lint_paths([str(root / "hyperspace_tpu")], root=str(root),
                        rules=[TelemetryCatalogRule()])
    assert report.findings == []


def test_catalog_observe_writes_count_instance_observe_does_not(tmp_path):
    """PR 7: histogram writes — ``observe("name", v)`` — scan like
    inc/set_gauge; a ``Histogram().observe(value)`` instance call (no
    string first arg) stays out."""
    pkg = tmp_path / "hyperspace_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'from hyperspace_tpu.telemetry import registry as telem\n\n\n'
        'def f(h, v):\n'
        '    telem.observe("lat/undoc_ms", v)\n'
        '    h.observe(v)\n')
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("nothing\n")
    report = lint_paths([str(pkg)], root=str(tmp_path),
                        rules=[TelemetryCatalogRule()])
    assert [f for f in report.findings if "lat/undoc_ms" in f.message]
    assert len(report.findings) == 1  # the value-only call is silent
    # documenting the name clears it
    (tmp_path / "docs" / "observability.md").write_text(
        "| `lat/undoc_ms` | histogram |\n")
    report = lint_paths([str(pkg)], root=str(tmp_path),
                        rules=[TelemetryCatalogRule()])
    assert report.findings == []


def test_catalog_namespaced_read_counts_plain_get_does_not(tmp_path):
    pkg = tmp_path / "hyperspace_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'def f(d, reg):\n'
        '    d.get("plain_key")\n'          # no "/": a dict get, ignored
        '    return reg.get("ns/typo")\n')  # namespaced: must be documented
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("nothing\n")
    report = lint_paths([str(pkg)], root=str(tmp_path),
                        rules=[TelemetryCatalogRule()])
    assert [f for f in report.findings if "ns/typo" in f.message]
    assert not [f for f in report.findings if "plain_key" in f.message]


# --- flag-doc-drift (project rule) -------------------------------------------


def _flags_tree(tmp_path, readme):
    cli_dir = tmp_path / "hyperspace_tpu" / "cli"
    cli_dir.mkdir(parents=True)
    (cli_dir / "train.py").write_text(textwrap.dedent("""\
        import dataclasses


        @dataclasses.dataclass
        class RunConfig:
            steps: int = 500
            mystery_flag: bool = False
            _private: int = 0
    """))
    (tmp_path / "bench.py").write_text(
        "import argparse\np = argparse.ArgumentParser()\n"
        'p.add_argument("--repeats", type=int)\n'
        'p.add_argument("--wobble", action="store_true")\n')
    (tmp_path / "README.md").write_text(readme)
    return tmp_path


def test_flags_drift_fires(tmp_path):
    root = _flags_tree(tmp_path, "`steps=500` and `--repeats N`\n")
    report = lint_paths([str(root / "hyperspace_tpu"),
                         str(root / "bench.py")], root=str(root),
                        rules=[FlagDocDriftRule()])
    assert report.exit_code() == 1
    msgs = " ".join(f.message for f in report.findings)
    assert "mystery_flag=" in msgs and "--wobble" in msgs
    assert "steps" not in msgs and "_private" not in msgs
    # suppression on the defining lines silences the drift findings
    for f in report.findings:
        path = root / f.path
        lines = path.read_text().splitlines()
        lines[f.line - 1] += "  # hyperlint: disable=flag-doc-drift — fixture"
        path.write_text("\n".join(lines) + "\n")
    report = lint_paths([str(root / "hyperspace_tpu"),
                         str(root / "bench.py")], root=str(root),
                        rules=[FlagDocDriftRule()])
    assert report.findings == []


def test_flags_documented_tree_is_clean(tmp_path):
    root = _flags_tree(
        tmp_path,
        "`steps=500`, `mystery_flag=1`, `--repeats N`, `--wobble`\n")
    report = lint_paths([str(root / "hyperspace_tpu"),
                         str(root / "bench.py")], root=str(root),
                        rules=[FlagDocDriftRule()])
    assert report.findings == []


# --- review regressions ------------------------------------------------------


def test_donation_same_line_read_after_dispatch_fires(tmp_path):
    """The read can share the dispatch's LINE — `out = step(state);
    log(state)` and `return step(state), state` both touch invalidated
    buffers and must fire (line-granular filtering missed them)."""
    src = textwrap.dedent("""\
        import jax

        def f(step_fn, state, log):
            step = jax.jit(step_fn, donate_argnums=(0,))
            out = step(state); log(state)
            return out

        def g(step_fn, state):
            step = jax.jit(step_fn, donate_argnums=(0,))
            return step(state), state
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    report = lint_file(str(p), rules=[DonationHazardRule()])
    assert len(report.findings) == 2
    assert {f.line for f in report.findings} == {5, 10}


def test_donation_rebind_idiom_still_clean(tmp_path):
    src = textwrap.dedent("""\
        import jax

        def f(step_fn, state):
            step = jax.jit(step_fn, donate_argnums=(0,))
            state = step(state)
            return state
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    assert lint_file(str(p), rules=[DonationHazardRule()]).findings == []


def test_precision_scan_package_works_outside_repo(tmp_path):
    """scan_package on an arbitrary directory tree must still lint it
    (old-script contract) — only the package-shaped exemptions (root
    precision.py, kernels/, analysis/) are skipped."""
    from hyperspace_tpu.analysis.rules.precision import scan_package

    pkg = tmp_path / "otherpkg"
    (pkg / "kernels").mkdir(parents=True)
    (pkg / "sub").mkdir()
    (pkg / "sub" / "m.py").write_text(
        "import jax.numpy as jnp\nx = jnp.bfloat16\n")
    (pkg / "precision.py").write_text("y = jnp.bfloat16\n")
    (pkg / "kernels" / "k.py").write_text("z = jnp.bfloat16\n")
    offenders = scan_package(str(pkg))
    assert len(offenders) == 1 and offenders[0].startswith(
        "otherpkg/sub/m.py:2")


def test_catalog_shim_falls_back_on_unparseable_file(tmp_path):
    """A mid-refactor file with a syntax error must not silently drop
    its telemetry names from the shim scan — the regex fallback keeps
    them visible."""
    from hyperspace_tpu.analysis.rules.catalog import counters_in_code

    pkg = tmp_path / "hyperspace_tpu"
    pkg.mkdir()
    (pkg / "good.py").write_text('reg.inc("ns/good")\n')
    (pkg / "broken.py").write_text(
        'def f(:\n    reg.inc("ns/broken")\n    reg.get("ns/read")\n'
        '    reg.observe("ns/hist_ms", 1.0)\n')
    found = counters_in_code(str(pkg))
    assert {"ns/good", "ns/broken", "ns/read", "ns/hist_ms"} <= set(found)


# --- multiprocess-unsafe-io ---------------------------------------------------

_MPIO_REL = "hyperspace_tpu/parallel/bad_mpio.py"


def test_mpio_bad_fixture_fires_every_shape():
    report = _lint("bad_mpio.py", MultiprocessUnsafeIORule, rel=_MPIO_REL)
    msgs = [f.message for f in report.findings]
    assert report.exit_code() == 1 and len(report.findings) == 5
    assert all("multihost-reachable" in m for m in msgs)
    assert any("os.replace" in m for m in msgs)
    assert any("shutil.move" in m for m in msgs)
    assert any(".write_text()" in m for m in msgs)


def test_mpio_good_fixture_is_clean():
    assert _lint("good_mpio.py", MultiprocessUnsafeIORule,
                 rel="hyperspace_tpu/parallel/good_mpio.py").findings == []


@pytest.mark.parametrize("rel", [
    "hyperspace_tpu/serve/engine.py",   # serve plane: artifact.py only
    "hyperspace_tpu/models/hgcn.py",    # model code never does IO
    "scripts/bench_trend.py",           # driver-side, single process
    "bad_mpio.py",                      # bare rel: outside the package
])
def test_mpio_scope_is_multihost_reachable_modules_only(rel):
    assert _lint("bad_mpio.py", MultiprocessUnsafeIORule,
                 rel=rel).findings == []


def test_mpio_severity_is_warning():
    assert MultiprocessUnsafeIORule.severity == "warning"
