"""__graft_entry__ device acquisition: CPU-first, tunnel-proof.

The r05 failure mode: ``_ensure_devices`` probed ``jax.devices()`` —
initializing the real TPU backend over the tunnel — BEFORE its CPU
fallback, so a wedged chip/tunnel killed the CPU-only
``dryrun_multichip`` correctness check outright.  The contract now:

- ``JAX_PLATFORMS=cpu`` (or unset) → straight to virtual CPU devices,
  the default backend is never touched;
- the real backend is probed only when explicitly requested
  (``JAX_PLATFORMS=tpu`` / ``HYPERSPACE_DRYRUN_BACKEND=default``).
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_resolve_prefer_cpu(monkeypatch):
    import __graft_entry__ as g

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert g._resolve_prefer_cpu() is True
    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    assert g._resolve_prefer_cpu() is True  # cpu listed → honored
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    assert g._resolve_prefer_cpu() is False  # explicit non-cpu request
    monkeypatch.delenv("JAX_PLATFORMS")
    monkeypatch.delenv("HYPERSPACE_DRYRUN_BACKEND", raising=False)
    assert g._resolve_prefer_cpu() is True  # default: cpu
    monkeypatch.setenv("HYPERSPACE_DRYRUN_BACKEND", "default")
    assert g._resolve_prefer_cpu() is False  # explicit opt-in only


def test_ensure_devices_cpu_fresh_process():
    """A fresh process with JAX_PLATFORMS=cpu gets its n virtual CPU
    devices without XLA_FLAGS pre-set and without the default backend
    ever being probed (a TPU probe would crash on this host — the test
    passing IS the proof the probe never ran)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "HYPERSPACE_DRYRUN_BACKEND")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g\n"
         "d = g._ensure_devices(4)\n"
         "assert len(d) == 4 and d[0].platform == 'cpu', d\n"
         "print('CPU_OK', len(d))"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CPU_OK 4" in proc.stdout


def test_ensure_devices_in_process():
    """In the test process (8 virtual CPU devices already up) the CPU
    path serves from the existing backend — no clear_backends churn."""
    import jax

    import __graft_entry__ as g

    if len(jax.devices()) < 4:
        import pytest

        pytest.skip("needs 4 virtual devices")
    before = jax.devices()
    d = g._ensure_devices(4, prefer_cpu=True)
    assert len(d) == 4 and all(x.platform == "cpu" for x in d)
    assert jax.devices() == before  # backend untouched


def test_dryrun_bounded_timeout_emits_parseable_artifact(capsys):
    """The MULTICHIP r04/r05 fix: a dryrun that outruns its budget
    emits a parseable budget_exhausted record (bench.py's sentinel
    shape, so bench_trend and any tail parser read it) and returns
    False — never a silent rc=124 loss."""
    import json
    import time

    import __graft_entry__ as g

    exits = []
    ok = g.run_dryrun_bounded(4, 0.2, _dryrun=lambda n: time.sleep(5),
                              _exit=exits.append)
    assert ok is False
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "budget_exhausted"
    assert rec["detail"]["lane"] == "dryrun_multichip"
    assert rec["detail"]["budget_s"] == 0.2
    assert exits == []  # SIGALRM path won; the watchdog never fired


def test_dryrun_bounded_success_emits_nothing(capsys):
    """A run that finishes inside the budget is transparent: no
    sentinel line, True back, the alarm disarmed."""
    import signal

    import __graft_entry__ as g

    ran = []
    ok = g.run_dryrun_bounded(4, 30.0, _dryrun=ran.append)
    assert ok is True and ran == [4]
    assert "budget_exhausted" not in capsys.readouterr().out
    # the deadline alarm was restored (no timer left pending)
    assert signal.getitimer(signal.ITIMER_REAL) == (0.0, 0.0)


def test_dryrun_budget_env_and_escape_hatch(monkeypatch):
    """MULTICHIP_BUDGET_S feeds the default; <= 0 runs unbounded."""
    import __graft_entry__ as g

    ran = []
    monkeypatch.setenv("MULTICHIP_BUDGET_S", "0")
    assert g.run_dryrun_bounded(4, _dryrun=ran.append) is True
    assert ran == [4]  # unbounded escape hatch still runs the lane
