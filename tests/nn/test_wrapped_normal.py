"""WrappedNormal: normalization, consistency, reparameterized gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.manifolds.maps import ball_to_lorentz
from hyperspace_tpu.nn import WrappedNormal


def test_ball_density_integrates_to_one_2d():
    """∫ p(z) √|g(z)| dz over the 2-D ball must be 1 (Riemannian density)."""
    c = 1.0
    ball = PoincareBall(c)
    loc = ball.proj(jnp.asarray([0.25, -0.1], jnp.float64))
    scale = jnp.asarray([0.6, 0.6], jnp.float64)
    dist = WrappedNormal(ball, loc, scale)

    n = 400
    lim = 1.0 / np.sqrt(c) * (1 - 1e-4)
    xs = np.linspace(-lim, lim, n)
    X, Y = np.meshgrid(xs, xs)
    pts = jnp.asarray(np.stack([X.ravel(), Y.ravel()], -1))
    r2 = np.sum(np.asarray(pts) ** 2, -1)
    inside = r2 < lim**2
    logp = np.asarray(dist.log_prob(pts))
    lam = np.asarray(ball.lambda_x(pts, keepdims=False))
    dens = np.where(inside, np.exp(logp) * lam**2, 0.0)  # √|g| = λ^d, d=2
    integral = dens.sum() * (xs[1] - xs[0]) ** 2
    assert abs(integral - 1.0) < 2e-2


def test_lorentz_density_integrates_to_one_2d():
    """Same check on the hyperboloid, integrating in ball coordinates.

    Under the isometry the Riemannian densities agree pointwise, so
    ∫ p_L(φ(z)) λ² dz = 1 with φ = ball→Lorentz."""
    c = 0.8
    lor = Lorentz(c)
    ball = PoincareBall(c)
    loc_b = jnp.asarray([0.1, 0.2], jnp.float64)
    loc = ball_to_lorentz(loc_b, c)
    scale = jnp.asarray([0.7, 0.5], jnp.float64)
    dist = WrappedNormal(lor, loc, scale)

    n = 400
    lim = 1.0 / np.sqrt(c) * (1 - 1e-4)
    xs = np.linspace(-lim, lim, n)
    X, Y = np.meshgrid(xs, xs)
    pts = jnp.asarray(np.stack([X.ravel(), Y.ravel()], -1))
    inside = np.sum(np.asarray(pts) ** 2, -1) < lim**2
    zl = ball_to_lorentz(pts, c)
    logp = np.asarray(dist.log_prob(zl))
    lam = np.asarray(ball.lambda_x(pts, keepdims=False))
    dens = np.where(inside, np.exp(logp) * lam**2, 0.0)
    integral = dens.sum() * (xs[1] - xs[0]) ** 2
    assert abs(integral - 1.0) < 2e-2


@pytest.mark.parametrize("mk", [lambda: PoincareBall(1.0), lambda: Lorentz(1.0)])
def test_rsample_on_manifold_and_logprob_finite(mk):
    m = mk()
    d = 6
    D = m.ambient_dim(d)
    loc = m.random_normal(jax.random.PRNGKey(0), (D,), jnp.float64, std=0.4)
    scale = 0.3 * jnp.ones((d,), jnp.float64)
    dist = WrappedNormal(m, loc, scale)
    z = dist.rsample(jax.random.PRNGKey(1), (128,))
    assert z.shape == (128, D)
    assert float(jnp.max(m.check_point(z))) < 1e-9
    lp = dist.log_prob(z)
    assert np.isfinite(np.asarray(lp)).all()


def test_logprob_highest_at_loc_for_isotropic():
    m = PoincareBall(1.0)
    loc = jnp.asarray([0.3, 0.0, -0.2], jnp.float64)
    dist = WrappedNormal(m, m.proj(loc), 0.4 * jnp.ones((3,), jnp.float64))
    z = dist.rsample(jax.random.PRNGKey(2), (64,))
    lp_loc = dist.log_prob(m.proj(loc))
    assert float(lp_loc) >= float(jnp.max(dist.log_prob(z))) - 1e-9


@pytest.mark.slow
def test_reparameterized_gradients_flow_to_loc_and_scale():
    """∂/∂(loc,scale) of an expectation estimated with rsample is finite."""
    m = Lorentz(1.0)
    target = m.random_normal(jax.random.PRNGKey(3), (5,), jnp.float64)

    def objective(params):
        loc = m.proj(params["loc"])
        scale = jax.nn.softplus(params["raw_scale"])
        dist = WrappedNormal(m, loc, scale)
        z = dist.rsample(jax.random.PRNGKey(4), (32,))
        return jnp.mean(m.sqdist(z, target))

    params = {
        "loc": m.random_normal(jax.random.PRNGKey(5), (5,), jnp.float64),
        "raw_scale": jnp.zeros((4,), jnp.float64),
    }
    g = jax.grad(objective)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(jnp.linalg.norm(g["loc"])) > 0.0


def test_sample_and_log_prob_matches_log_prob():
    """The v-direct density path must equal the inverse-chain log_prob."""
    import jax

    from hyperspace_tpu.manifolds import Lorentz, PoincareBall, Product, Euclidean, Sphere
    from hyperspace_tpu.nn.wrapped_normal import WrappedNormal

    for m, d_amb, d_coord in [
        (PoincareBall(1.3), 3, 3),
        (Lorentz(0.7), 4, 3),
        (Product([Lorentz(1.0), PoincareBall(0.5), Euclidean()], [3, 2, 2]), 7, 6),
    ]:
        loc = m.random_normal(jax.random.PRNGKey(0), (5, d_amb), jnp.float64, std=0.3)
        scale = 0.5 * jnp.ones((5, d_coord), jnp.float64)
        dist = WrappedNormal(m, loc, scale)
        z, lp_fast = dist.sample_and_log_prob(jax.random.PRNGKey(1))
        lp_ref = dist.log_prob(z)
        np.testing.assert_allclose(
            np.asarray(lp_fast), np.asarray(lp_ref), rtol=1e-8, atol=1e-9)


def test_product_log_prob_factorizes():
    """Independent factors: product log-density = sum of factor densities."""
    import jax

    from hyperspace_tpu.manifolds import Lorentz, PoincareBall, Product
    from hyperspace_tpu.nn.wrapped_normal import WrappedNormal

    mL, mB = Lorentz(1.0), PoincareBall(1.0)
    mP = Product([mL, mB], [4, 3])
    locL = mL.random_normal(jax.random.PRNGKey(2), (6, 4), jnp.float64, std=0.2)
    locB = mB.random_normal(jax.random.PRNGKey(3), (6, 3), jnp.float64, std=0.2)
    loc = jnp.concatenate([locL, locB], axis=-1)
    sL = 0.4 * jnp.ones((6, 3), jnp.float64)
    sB = 0.6 * jnp.ones((6, 3), jnp.float64)
    dist = WrappedNormal(mP, loc, jnp.concatenate([sL, sB], axis=-1))
    z = dist.rsample(jax.random.PRNGKey(4))
    zL, zB = z[..., :4], z[..., 4:]
    lp = dist.log_prob(z)
    lp_sum = (WrappedNormal(mL, locL, sL).log_prob(zL)
              + WrappedNormal(mB, locB, sB).log_prob(zB))
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lp_sum), rtol=1e-8)
