"""Sorted symmetric aggregation (nn/scatter.py): forward and VJP must match
the naive unsorted segment formulation exactly (the reindexing identity is
exact, not approximate)."""

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.nn.scatter import sym_segment_aggregate
from hyperspace_tpu.nn.gcn import segment_softmax


def _graph(n=50, seed=0):
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=n, feat_dim=8, seed=seed)
    return G.prepare(edges, n, x, pad_multiple=64)


def test_prepare_sorted_and_involution():
    g = _graph()
    assert np.all(np.diff(g.receivers) >= 0)
    rp = g.rev_perm
    assert rp is not None
    # involution, and (s, r) -> (r, s)
    np.testing.assert_array_equal(rp[rp], np.arange(len(rp)))
    np.testing.assert_array_equal(g.senders[rp], g.receivers)
    np.testing.assert_array_equal(g.receivers[rp], g.senders)
    # padding maps to itself
    assert np.all(rp[~g.edge_mask] == np.arange(len(rp))[~g.edge_mask])


def test_forward_matches_naive():
    g = _graph()
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float64)
    w = jnp.asarray(rng.random(len(g.senders)) * g.edge_mask, jnp.float64)
    got = sym_segment_aggregate(h, w, jnp.asarray(g.senders), jnp.asarray(g.receivers),
                                jnp.asarray(g.rev_perm), None, None, None,
                                g.num_nodes)
    want = jax.ops.segment_sum(w[:, None] * h[jnp.asarray(g.senders)],
                               jnp.asarray(g.receivers), g.num_nodes)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_vjp_matches_naive():
    g = _graph()
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float64)
    w = jnp.asarray(rng.random(len(g.senders)) * g.edge_mask, jnp.float64)
    s, r, rp = map(jnp.asarray, (g.senders, g.receivers, g.rev_perm))
    t = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float64)

    def loss_sym(h, w):
        return jnp.sum(
            sym_segment_aggregate(h, w, s, r, rp, None, None, None,
                                  g.num_nodes) * t)

    def loss_naive(h, w):
        return jnp.sum(jax.ops.segment_sum(w[:, None] * h[s], r, g.num_nodes) * t)

    gh1, gw1 = jax.grad(loss_sym, argnums=(0, 1))(h, w)
    gh2, gw2 = jax.grad(loss_naive, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gh1, gh2, rtol=1e-12)
    # dw on padding edges is irrelevant (w is always masked to 0 upstream):
    # compare on real edges only
    m = jnp.asarray(g.edge_mask)
    np.testing.assert_allclose(gw1 * m, gw2 * m, rtol=1e-12)


def test_plan_path_fwd_and_vjp_match_xla(monkeypatch):
    """The production path: plan-carrying aggregation through the Pallas CSR
    kernel (interpret mode) must match the XLA path in forward AND backward —
    guards the pb/pc/pf plumbing through the custom_vjp."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    g = _graph()
    from hyperspace_tpu.kernels.segment import build_csr_plan

    plan = tuple(jnp.asarray(a) for a in build_csr_plan(g.receivers, g.num_nodes))
    rng = np.random.default_rng(4)
    h = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float32)
    w = jnp.asarray(rng.random(len(g.senders)) * g.edge_mask, jnp.float32)
    s, r, rp = map(jnp.asarray, (g.senders, g.receivers, g.rev_perm))
    t = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float32)

    def loss(h, w, pb, pc, pf):
        return jnp.sum(
            sym_segment_aggregate(h, w, s, r, rp, pb, pc, pf, g.num_nodes) * t)

    out_plan = sym_segment_aggregate(h, w, s, r, rp, *plan, g.num_nodes)
    out_xla = sym_segment_aggregate(h, w, s, r, rp, None, None, None, g.num_nodes)
    np.testing.assert_allclose(np.asarray(out_plan), np.asarray(out_xla),
                               rtol=1e-5, atol=1e-5)
    gh1, gw1 = jax.grad(loss, argnums=(0, 1))(h, w, *plan)
    gh2, gw2 = jax.grad(loss, argnums=(0, 1))(h, w, None, None, None)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2), rtol=1e-5, atol=1e-5)
    m = np.asarray(g.edge_mask)
    np.testing.assert_allclose(np.asarray(gw1) * m, np.asarray(gw2) * m,
                               rtol=1e-5, atol=1e-5)


def test_with_dw_false_zeroes_weight_grad():
    g = _graph()
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.standard_normal((g.num_nodes, 8)), jnp.float64)
    w = jnp.asarray(rng.random(len(g.senders)) * g.edge_mask, jnp.float64)
    s, r, rp = map(jnp.asarray, (g.senders, g.receivers, g.rev_perm))

    def loss(h, w):
        return jnp.sum(
            sym_segment_aggregate(h, w, s, r, rp, None, None, None,
                                  g.num_nodes, False))

    gh, gw = jax.grad(loss, argnums=(0, 1))(h, w)
    assert np.all(np.asarray(gw) == 0.0)
    assert np.all(np.isfinite(np.asarray(gh)))


def test_sorted_segment_softmax_matches():
    g = _graph()
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.standard_normal(len(g.senders)), jnp.float64)
    r = jnp.asarray(g.receivers)
    m = jnp.asarray(g.edge_mask)
    got = segment_softmax(logits, r, g.num_nodes, mask=m, indices_are_sorted=True)
    want = segment_softmax(logits, r, g.num_nodes, mask=m)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_pick_vjps_match_gather_autodiff():
    """pick_senders / pick_receivers: values equal plain gathers, grads
    equal autodiff of the gathers (planned-scatter VJP correctness,
    including the sender-side involution)."""
    from hyperspace_tpu.data.graphs import prepare
    from hyperspace_tpu.nn.scatter import pick_receivers, pick_senders

    rng = np.random.default_rng(11)
    n = 24
    edges = rng.integers(0, n, (40, 2)).astype(np.int32)
    g = prepare(edges, n, np.zeros((n, 3), np.float32))
    s, r, rp = map(jnp.asarray, (g.senders, g.receivers, g.rev_perm))
    pb, pc, pf = (jnp.asarray(a) for a in g.csr_plan)
    alpha = jnp.asarray(rng.normal(size=n), jnp.float64)
    t = jnp.asarray(rng.normal(size=len(g.senders)), jnp.float64)

    np.testing.assert_array_equal(
        np.asarray(pick_senders(alpha, s, r, rp, pb, pc, pf, n)),
        np.asarray(alpha[s]))
    np.testing.assert_array_equal(
        np.asarray(pick_receivers(alpha, r, pb, pc, pf, n)),
        np.asarray(alpha[r]))

    g1 = jax.grad(lambda a: jnp.sum(pick_senders(a, s, r, rp, pb, pc, pf, n) * t))(alpha)
    g2 = jax.grad(lambda a: jnp.sum(a[s] * t))(alpha)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-12)
    g3 = jax.grad(lambda a: jnp.sum(pick_receivers(a, r, pb, pc, pf, n) * t))(alpha)
    g4 = jax.grad(lambda a: jnp.sum(a[r] * t))(alpha)
    np.testing.assert_allclose(np.asarray(g3), np.asarray(g4), rtol=1e-12)


# --- fused planned attention aggregation (att_aggregate_planned) --------------


def _att_oracle(h, a_s, a_r, g, n, agg_dtype=None):
    """Unfused reference: bounded logits -> exp -> num/den via plain
    segment ops (mirrors the fused op's math exactly)."""
    from hyperspace_tpu.nn.gcn import bounded_att_logits

    snd = jnp.asarray(g.senders)
    rcv = jnp.asarray(g.receivers)
    mask = jnp.asarray(g.edge_mask)
    lm = bounded_att_logits(a_s[snd] + a_r[rcv], 0.2)
    w = jnp.where(mask, jnp.exp(lm), 0.0)
    h_in = h if agg_dtype is None else h.astype(agg_dtype)[snd].astype(
        agg_dtype)
    hs = h[snd] if agg_dtype is None else h.astype(jnp.float32)[snd].astype(
        agg_dtype)
    w_in = w if agg_dtype is None else w.astype(agg_dtype)
    num = jax.ops.segment_sum(
        (w_in[:, None] * hs).astype(jnp.float32), rcv, n,
        indices_are_sorted=True)
    den = jax.ops.segment_sum(w_in.astype(jnp.float32), rcv, n,
                              indices_are_sorted=True)
    return num / jnp.maximum(den, 1e-15)[:, None]


def test_att_aggregate_planned_matches_oracle():
    from hyperspace_tpu.nn.scatter import att_aggregate_planned

    g = _graph(n=120, seed=3)
    n = g.num_nodes
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    a_s = jnp.asarray(rng.standard_normal(n), jnp.float32)
    a_r = jnp.asarray(rng.standard_normal(n), jnp.float32)
    probe = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    plan = tuple(jnp.asarray(p) for p in g.csr_plan)

    def f_fused(h, a_s, a_r):
        out = att_aggregate_planned(
            h, a_s, a_r, jnp.asarray(g.senders), jnp.asarray(g.receivers),
            jnp.asarray(g.rev_perm), jnp.asarray(g.edge_mask), plan, n,
            None, 0.2)
        return jnp.sum(out * probe)

    def f_ref(h, a_s, a_r):
        return jnp.sum(_att_oracle(h, a_s, a_r, g, n) * probe)

    np.testing.assert_allclose(float(f_fused(h, a_s, a_r)),
                               float(f_ref(h, a_s, a_r)), rtol=1e-5)
    gf = jax.grad(f_fused, argnums=(0, 1, 2))(h, a_s, a_r)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(h, a_s, a_r)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_att_aggregate_planned_bf16_close_to_f32():
    from hyperspace_tpu.nn.scatter import att_aggregate_planned

    g = _graph(n=120, seed=4)
    n = g.num_nodes
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    a_s = jnp.asarray(rng.standard_normal(n), jnp.float32)
    a_r = jnp.asarray(rng.standard_normal(n), jnp.float32)
    plan = tuple(jnp.asarray(p) for p in g.csr_plan)
    args = (jnp.asarray(g.senders), jnp.asarray(g.receivers),
            jnp.asarray(g.rev_perm), jnp.asarray(g.edge_mask), plan, n)
    o32 = att_aggregate_planned(h, a_s, a_r, *args, None, 0.2)
    o16 = att_aggregate_planned(h, a_s, a_r, *args, jnp.bfloat16, 0.2)
    np.testing.assert_allclose(np.asarray(o16, np.float32),
                               np.asarray(o32, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_att_aggregate_planned_kernel_path(monkeypatch):
    """Same parity through the actual Pallas kernels (interpret mode):
    the fused forward CSR pass and the fused backward edge kernel
    (csr_att_bwd_edges) both execute."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    from hyperspace_tpu.nn.scatter import att_aggregate_planned

    g = _graph(n=120, seed=5)
    n = g.num_nodes
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    a_s = jnp.asarray(rng.standard_normal(n), jnp.float32)
    a_r = jnp.asarray(rng.standard_normal(n), jnp.float32)
    probe = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    plan = tuple(jnp.asarray(p) for p in g.csr_plan)

    def f_fused(h, a_s, a_r):
        out = att_aggregate_planned(
            h, a_s, a_r, jnp.asarray(g.senders), jnp.asarray(g.receivers),
            jnp.asarray(g.rev_perm), jnp.asarray(g.edge_mask), plan, n,
            None, 0.2)
        return jnp.sum(out * probe)

    def f_ref(h, a_s, a_r):
        return jnp.sum(_att_oracle(h, a_s, a_r, g, n) * probe)

    np.testing.assert_allclose(float(f_fused(h, a_s, a_r)),
                               float(f_ref(h, a_s, a_r)), rtol=1e-4)
    gf = jax.grad(f_fused, argnums=(0, 1, 2))(h, a_s, a_r)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(h, a_s, a_r)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
