"""Hyperbolic attention tests (SURVEY.md §4.4): tiled == dense (the kernel
oracle relation), outputs on-manifold, masking semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import (
    HypMultiHeadAttention,
    lorentz_attention,
    lorentz_attention_tiled,
    minkowski_gram,
)
from hyperspace_tpu.manifolds.lorentz import minkowski_dot


def _pts(key, m, shape):
    return m.random_normal(key, shape, jnp.float64)


def test_minkowski_gram_matches_pairwise():
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(0), m, (3, 5))
    k = _pts(jax.random.PRNGKey(1), m, (4, 5))
    g = minkowski_gram(q, k)
    want = minkowski_dot(q[:, None, :], k[None, :, :], keepdims=False)
    np.testing.assert_allclose(np.asarray(g), np.asarray(want), rtol=1e-10)


def test_attention_output_on_manifold():
    m = Lorentz(0.8)
    q = _pts(jax.random.PRNGKey(2), m, (2, 6, 5))
    o = lorentz_attention(q, q, q, m)
    assert float(jnp.max(m.check_point(o))) < 1e-8


def test_attention_uniform_weights_is_centroid():
    """With tau→∞ the scores are flat and attention = Lorentz centroid."""
    m = Lorentz(1.0)
    x = _pts(jax.random.PRNGKey(3), m, (7, 5))
    o = lorentz_attention(x, x, x, m, tau=1e9)
    want = m.centroid(x)
    np.testing.assert_allclose(
        np.asarray(o[0]), np.asarray(want), rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("nk", [8, 13, 128])
def test_tiled_matches_dense(nk):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(4), m, (2, 5, 7))
    k = _pts(jax.random.PRNGKey(5), m, (2, nk, 7))
    v = _pts(jax.random.PRNGKey(6), m, (2, nk, 7))
    dense = lorentz_attention(q, k, v, m, beta=0.3, tau=0.7)
    tiled = lorentz_attention_tiled(q, k, v, m, beta=0.3, tau=0.7, block_size=8)
    np.testing.assert_allclose(np.asarray(tiled), np.asarray(dense), rtol=1e-8, atol=1e-10)


def test_tiled_matches_dense_masked():
    m = Lorentz(1.0)
    rng = np.random.default_rng(0)
    q = _pts(jax.random.PRNGKey(7), m, (2, 5, 7))
    k = _pts(jax.random.PRNGKey(8), m, (2, 11, 7))
    v = _pts(jax.random.PRNGKey(9), m, (2, 11, 7))
    mask = jnp.asarray(rng.random((2, 5, 11)) > 0.3)
    mask = mask.at[:, :, 0].set(True)  # no fully-masked rows
    dense = lorentz_attention(q, k, v, m, mask=mask)
    tiled = lorentz_attention_tiled(q, k, v, m, mask=mask, block_size=4)
    np.testing.assert_allclose(np.asarray(tiled), np.asarray(dense), rtol=1e-8, atol=1e-10)


@pytest.mark.slow
def test_attention_mask_equals_dropped_keys():
    """Masking the tail keys == running attention on the truncated KV."""
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(10), m, (3, 6))
    k = _pts(jax.random.PRNGKey(11), m, (9, 6))
    v = _pts(jax.random.PRNGKey(12), m, (9, 6))
    mask = jnp.asarray(np.arange(9) < 5)[None, :].repeat(3, 0)
    full = lorentz_attention(q, k, v, m, mask=mask)
    trunc = lorentz_attention(q, k[:5], v[:5], m)
    np.testing.assert_allclose(np.asarray(full), np.asarray(trunc), rtol=1e-10)


@pytest.mark.parametrize("impl", ["flash", "scan"])
def test_mha_module_shapes_and_manifold(impl):
    m = Lorentz(1.0)
    x = _pts(jax.random.PRNGKey(13), m, (2, 6, 9))  # dim 8 manifold
    mha = HypMultiHeadAttention(dim=8, num_heads=2, manifold=m, impl=impl)
    params = mha.init(jax.random.PRNGKey(14), x)
    y = mha.apply(params, x)
    assert y.shape == (2, 6, 9)
    assert float(jnp.max(m.check_point(y))) < 1e-8


@pytest.mark.slow
def test_mha_grads_finite():
    m = Lorentz(1.0)
    x = _pts(jax.random.PRNGKey(15), m, (1, 4, 9))
    mha = HypMultiHeadAttention(dim=8, num_heads=2, manifold=m)
    params = mha.init(jax.random.PRNGKey(16), x)

    def loss(p):
        y = mha.apply(p, x)
        return jnp.sum(m.dist(y[:, :1], y[:, 1:]))

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(bool(jnp.isfinite(l).all()) for l in leaves)
