"""HGCConv unit tests (SURVEY.md §4.1/§4.4 style): segment ops, on-manifold
outputs, masked-padding invariance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.nn.gcn import (
    HGCConv,
    from_tangent0_coords,
    segment_softmax,
    tangent0_coords,
)


def test_segment_softmax_matches_dense():
    logits = jnp.asarray([0.1, 1.0, -0.5, 2.0, 0.0])
    seg = jnp.asarray([0, 0, 1, 1, 1])
    w = segment_softmax(logits, seg, 2)
    w0 = jax.nn.softmax(logits[:2])
    w1 = jax.nn.softmax(logits[2:])
    np.testing.assert_allclose(np.asarray(w), np.concatenate([w0, w1]), rtol=1e-6)


def test_segment_softmax_mask_and_empty_segment():
    logits = jnp.asarray([1.0, 2.0, 3.0])
    seg = jnp.asarray([0, 0, 2])
    mask = jnp.asarray([True, False, False])
    w = segment_softmax(logits, seg, 3, mask=mask)
    np.testing.assert_allclose(np.asarray(w), [1.0, 0.0, 0.0], atol=1e-12)


@pytest.mark.parametrize("kind", ["lorentz", "poincare"])
def test_tangent0_roundtrip(kind, rng):
    m = Lorentz(0.7) if kind == "lorentz" else PoincareBall(0.7)
    v = jnp.asarray(rng.normal(size=(5, 4)) * 0.3)
    x = from_tangent0_coords(m, v)
    assert float(jnp.max(m.check_point(x))) < 1e-8
    back = tangent0_coords(m, x)
    np.testing.assert_allclose(np.asarray(back), np.asarray(v), rtol=1e-6, atol=1e-8)


def _tiny_graph(n=6, e=10, seed=0):
    rng = np.random.default_rng(seed)
    senders = rng.integers(0, n, e).astype(np.int32)
    receivers = rng.integers(0, n, e).astype(np.int32)
    mask = np.ones(e, bool)
    return jnp.asarray(senders), jnp.asarray(receivers), jnp.asarray(mask)


def _dg(x, s, r, mask, n):
    from hyperspace_tpu.data.graphs import DeviceGraph

    return DeviceGraph(x=x, senders=s, receivers=r, edge_mask=mask, num_nodes=n)


@pytest.mark.parametrize("kind", ["lorentz", "poincare"])
@pytest.mark.parametrize("use_att", [False, True])
def test_hgcconv_on_manifold(kind, use_att, rng):
    n, d_out = 6, 8
    m_in = Lorentz(1.0) if kind == "lorentz" else PoincareBall(1.0)
    x = m_in.random_normal(jax.random.PRNGKey(0), (n, m_in.ambient_dim(4)), jnp.float64)
    s, r, mask = _tiny_graph(n)
    conv = HGCConv(features=d_out, kind=kind, c_in=1.0, c_out=0.5, use_att=use_att)
    g = _dg(x, s, r, mask, n)
    params = conv.init(jax.random.PRNGKey(1), x, g)
    y, m_out = conv.apply(params, x, g)
    assert y.shape == (n, m_out.ambient_dim(d_out))
    assert float(jnp.max(m_out.check_point(y))) < 1e-6
    assert abs(float(m_out.c) - 0.5) < 1e-12


def test_hgcconv_padding_invariance(rng):
    """Extra masked edges must not change the output at all."""
    n = 5
    m = Lorentz(1.0)
    x = m.random_normal(jax.random.PRNGKey(2), (n, 5), jnp.float64)
    s, r, mask = _tiny_graph(n, e=8, seed=3)
    conv = HGCConv(features=4, kind="lorentz", use_att=True)
    params = conv.init(jax.random.PRNGKey(3), x, _dg(x, s, r, mask, n))
    y1, _ = conv.apply(params, x, _dg(x, s, r, mask, n))
    # pad with junk edges, masked out
    pad = jnp.asarray(np.full(7, 2, np.int32))
    s2 = jnp.concatenate([s, pad])
    r2 = jnp.concatenate([r, pad])
    mask2 = jnp.concatenate([mask, jnp.zeros(7, bool)])
    y2, _ = conv.apply(params, x, _dg(x, s2, r2, mask2, n))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_hgcconv_learned_curvature_grad():
    """learn_c exposes a c_raw param that receives a gradient."""
    n = 4
    m = Lorentz(1.0)
    x = m.random_normal(jax.random.PRNGKey(4), (n, 5), jnp.float64)
    s, r, mask = _tiny_graph(n, e=6, seed=5)
    conv = HGCConv(features=4, kind="lorentz", learn_c=True)
    g = _dg(x, s, r, mask, n)
    params = conv.init(jax.random.PRNGKey(5), x, g)
    assert "c_raw" in params["params"]

    def loss(p):
        y, m_out = conv.apply(p, x, g)
        return jnp.sum(m_out.sqdist(y[:1], y[1:2]))

    g = jax.grad(loss)(params)
    assert np.isfinite(float(g["params"]["c_raw"]))


@pytest.mark.parametrize("layout", ["unsorted", "sorted_planned"])
def test_hgcconv_agg_dtype_bf16_close_to_f32(layout):
    """agg_dtype=bfloat16 changes only the message dtype (accumulation is
    >= f32 on every path), so outputs track the full-precision layer to
    bf16-rounding tolerance and stay on-manifold — on both the unsorted
    XLA fallback and the sorted/CSR-planned path used in training."""
    n = 32
    m = Lorentz(1.0)
    x = m.random_normal(jax.random.PRNGKey(7), (n, 9), jnp.float32, std=0.3)
    if layout == "sorted_planned":
        from hyperspace_tpu.data.graphs import prepare, to_device

        rng = np.random.default_rng(7)
        edges = rng.integers(0, n, (48, 2)).astype(np.int32)
        g = to_device(prepare(edges, n, np.asarray(x)))
        x_dev = g.x
    else:
        s, r, mask = _tiny_graph(n, e=96, seed=7)
        g = _dg(x, s, r, mask, n)
        x_dev = x
    conv32 = HGCConv(features=8, kind="lorentz")
    convbf = HGCConv(features=8, kind="lorentz", agg_dtype=jnp.bfloat16)
    params = conv32.init(jax.random.PRNGKey(8), x_dev, g)
    y32, m_out = conv32.apply(params, x_dev, g)
    ybf, _ = convbf.apply(params, x_dev, g)
    assert ybf.dtype == y32.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(ybf), np.asarray(y32),
                               rtol=0.0, atol=0.05)
    assert float(jnp.max(m_out.check_point(ybf))) < 1e-5
