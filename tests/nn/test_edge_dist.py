"""Planned edge-distance ops (nn/edge_dist.py): values and gradients —
including learned-curvature cotangents — must match the direct
``m.sqdist(z[a], z[b])`` formulation exactly (the reorganized scatter is
algebraically the same sum)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.nn.edge_dist import graph_edge_sqdist, pair_sqdist_semi_planned
from hyperspace_tpu.nn.gcn import make_manifold
from hyperspace_tpu.kernels.segment import build_csr_plan


def _graph(n=60, seed=0):
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=n, feat_dim=8, seed=seed)
    return G.prepare(edges, n, x, pad_multiple=64)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["lorentz", "poincare"])
def test_graph_edge_sqdist_matches_direct(kind, rng):
    g = _graph()
    m = make_manifold(kind, 1.0)
    z = m.random_normal(jax.random.PRNGKey(0), (g.num_nodes, m.ambient_dim(6)),
                        jnp.float64)
    s, r, rp = map(jnp.asarray, (g.senders, g.receivers, g.rev_perm))
    pb, pc, pf = (jnp.asarray(a) for a in g.csr_plan)
    wmask = jnp.asarray((g.edge_mask & (g.senders != g.receivers)), jnp.float64)
    t = jnp.asarray(rng.standard_normal(len(g.senders)), jnp.float64) * wmask

    def loss_planned(z, c):
        d2 = graph_edge_sqdist(z, c, s, r, rp, pb, pc, pf, kind)
        return jnp.sum(d2 * t)

    def loss_direct(z, c):
        d2 = make_manifold(kind, c).sqdist(z[s], z[r])
        return jnp.sum(d2 * t)

    c = jnp.asarray(1.0, jnp.float64)
    np.testing.assert_allclose(loss_planned(z, c), loss_direct(z, c), rtol=1e-12)
    (gz1, gc1) = jax.grad(loss_planned, argnums=(0, 1))(z, c)
    (gz2, gc2) = jax.grad(loss_direct, argnums=(0, 1))(z, c)
    np.testing.assert_allclose(np.asarray(gz1), np.asarray(gz2),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(float(gc1), float(gc2), rtol=1e-9)


@pytest.mark.slow
def test_pair_sqdist_semi_planned_matches_direct(rng):
    n, p = 50, 200
    m = make_manifold("lorentz", 0.7)
    z = m.random_normal(jax.random.PRNGKey(1), (n, 7), jnp.float64)
    u = np.sort(rng.integers(0, n, p)).astype(np.int32)
    v = rng.integers(0, n, p).astype(np.int32)
    plan = tuple(jnp.asarray(a) for a in build_csr_plan(u, n))
    uj, vj = jnp.asarray(u), jnp.asarray(v)
    t = jnp.asarray(rng.standard_normal(p), jnp.float64)

    def loss_planned(z, c):
        return jnp.sum(pair_sqdist_semi_planned(z, c, uj, vj, *plan, "lorentz") * t)

    def loss_direct(z, c):
        return jnp.sum(make_manifold("lorentz", c).sqdist(z[uj], z[vj]) * t)

    c = jnp.asarray(0.7, jnp.float64)
    np.testing.assert_allclose(loss_planned(z, c), loss_direct(z, c), rtol=1e-12)
    (gz1, gc1) = jax.grad(loss_planned, argnums=(0, 1))(z, c)
    (gz2, gc2) = jax.grad(loss_direct, argnums=(0, 1))(z, c)
    np.testing.assert_allclose(np.asarray(gz1), np.asarray(gz2),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(float(gc1), float(gc2), rtol=1e-9)


@pytest.mark.parametrize("kind", ["lorentz"])
def test_pair_sqdist_planned_matches_direct(kind, rng):
    """Fully-planned static pairs (both scatters CSR): values and grads —
    including curvature cotangents — match the direct formulation."""
    from hyperspace_tpu.models.hgcn import make_planned_pairs
    from hyperspace_tpu.nn.edge_dist import pair_sqdist_planned

    n = 80
    m = make_manifold(kind, 1.0)
    z = m.random_normal(jax.random.PRNGKey(1), (n, m.ambient_dim(6)),
                        jnp.float64)
    pairs = rng.integers(0, n, (300, 2))
    pp = make_planned_pairs(pairs, n)
    t = jnp.asarray(rng.standard_normal(300), jnp.float64)

    def loss_planned(z, c):
        d2 = pair_sqdist_planned(z, c, pp.u, pp.v, *pp.u_plan, pp.v_perm,
                                 pp.v_sorted, *pp.v_plan, kind)
        return jnp.sum(d2 * t)

    def loss_direct(z, c):
        d2 = make_manifold(kind, c).sqdist(z[pp.u], z[pp.v])
        return jnp.sum(d2 * t)

    c = jnp.asarray(1.0, jnp.float64)
    np.testing.assert_allclose(loss_planned(z, c), loss_direct(z, c),
                               rtol=1e-12)
    (gz1, gc1) = jax.grad(loss_planned, argnums=(0, 1))(z, c)
    (gz2, gc2) = jax.grad(loss_direct, argnums=(0, 1))(z, c)
    np.testing.assert_allclose(np.asarray(gz1), np.asarray(gz2),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(float(gc1), float(gc2), rtol=1e-9)
