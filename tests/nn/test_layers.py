"""Layer tests: outputs on-manifold, gradients finite, known reductions."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.nn import HypAct, HypLinear, HypMLR, LorentzLinear, LorentzMLR
from hyperspace_tpu.nn.mlr import hyp_mlr_logits


def test_hyp_linear_on_ball():
    ball = PoincareBall(1.0)
    layer = HypLinear(features=6, manifold=ball)
    x = ball.random_normal(jax.random.PRNGKey(0), (4, 3), jnp.float64, std=0.5)
    params = layer.init(jax.random.PRNGKey(1), x)
    y = layer.apply(params, x)
    assert y.shape == (4, 6)
    assert float(jnp.max(ball.check_point(y))) == 0.0
    # zero weights + zero bias → origin
    z = layer.apply(jax.tree_util.tree_map(jnp.zeros_like, params), x)
    np.testing.assert_allclose(np.asarray(z), 0.0, atol=1e-12)


def test_lorentz_linear_on_hyperboloid():
    lor = Lorentz(0.7)
    layer = LorentzLinear(dim=5, manifold=lor)
    x = lor.random_normal(jax.random.PRNGKey(0), (8, 4), jnp.float64, std=0.5)
    params = layer.init(jax.random.PRNGKey(1), x)
    y = layer.apply(params, x)
    assert y.shape == (8, 6)  # ambient dim+1
    assert float(jnp.max(lor.check_point(y))) < 1e-10
    # gradients finite
    g = jax.grad(lambda p: jnp.sum(layer.apply(p, x) ** 2))(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_hyp_act_curvature_transfer():
    b1, b2 = PoincareBall(1.0), PoincareBall(0.5)
    layer = HypAct(manifold_in=b1, manifold_out=b2, activation=jax.nn.relu)
    x = b1.random_normal(jax.random.PRNGKey(0), (5, 3), jnp.float64, std=0.5)
    y = layer.apply({}, x)
    assert float(jnp.max(b2.check_point(y))) == 0.0


def test_hyp_act_lorentz_keeps_manifold():
    l1, l2 = Lorentz(1.0), Lorentz(2.0)
    layer = HypAct(manifold_in=l1, manifold_out=l2, activation=jax.nn.relu)
    x = l1.random_normal(jax.random.PRNGKey(0), (5, 4), jnp.float64, std=0.5)
    y = layer.apply({}, x)
    assert float(jnp.max(l2.check_point(y))) < 1e-10


def test_mlr_sign_symmetry_and_origin():
    """At p = 0 the logit must be odd in x along a, and 0 at the origin."""
    c = 1.0
    d = 4
    a = jnp.zeros((1, d), jnp.float64).at[0, 0].set(1.5)
    p = jnp.zeros((1, d), jnp.float64)
    x = jnp.zeros((d,), jnp.float64).at[0].set(0.3)
    lp = hyp_mlr_logits(x, p, a, c)
    lm = hyp_mlr_logits(-x, p, a, c)
    np.testing.assert_allclose(np.asarray(lp), -np.asarray(lm), rtol=1e-12)
    l0 = hyp_mlr_logits(jnp.zeros((d,), jnp.float64), p, a, c)
    np.testing.assert_allclose(np.asarray(l0), 0.0, atol=1e-12)
    # positive side of the hyperplane → positive logit
    assert float(lp[0]) > 0.0


def test_mlr_flat_limit_matches_euclidean_logit():
    """As c → 0 the hyperbolic MLR approaches 4⟨x−p, a⟩ (Ganea 2018 §3.1:
    lim logit = 4⟨−p+x, a⟩ accounting for λ→2 and asinh(z)≈z)."""
    c = 1e-8
    d = 3
    key = jax.random.PRNGKey(2)
    a = jax.random.normal(key, (2, d), jnp.float64)
    p = 0.01 * jax.random.normal(jax.random.PRNGKey(3), (2, d), jnp.float64)
    x = 0.01 * jax.random.normal(jax.random.PRNGKey(4), (d,), jnp.float64)
    got = hyp_mlr_logits(x, p, a, c)
    want = 4.0 * jnp.sum((x - p) * a, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-3)


@pytest.mark.slow
def test_hyp_mlr_module_and_grads():
    ball = PoincareBall(1.0)
    head = HypMLR(num_classes=7, manifold=ball)
    x = ball.random_normal(jax.random.PRNGKey(0), (6, 4), jnp.float64, std=0.5)
    params = head.init(jax.random.PRNGKey(1), x)
    logits = head.apply(params, x)
    assert logits.shape == (6, 7)
    labels = jnp.arange(6) % 7
    loss = lambda p: jnp.mean(
        -jax.nn.log_softmax(head.apply(p, x))[jnp.arange(6), labels]
    )
    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_lorentz_mlr_matches_ball_mlr_through_isometry():
    """LorentzMLR on mapped points == HypMLR on ball points (same params)."""
    from hyperspace_tpu.manifolds.maps import ball_to_lorentz

    c = 0.8
    ball, lor = PoincareBall(c), Lorentz(c)
    xb = ball.random_normal(jax.random.PRNGKey(0), (5, 3), jnp.float64, std=0.5)
    xl = ball_to_lorentz(xb, c)
    head_b = HypMLR(num_classes=4, manifold=ball)
    params = head_b.init(jax.random.PRNGKey(1), xb)
    head_l = LorentzMLR(num_classes=4, manifold=lor)
    lb = head_b.apply(params, xb)
    ll = head_l.apply(params, xl)
    np.testing.assert_allclose(np.asarray(lb), np.asarray(ll), rtol=1e-8, atol=1e-10)
