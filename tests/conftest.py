"""Test configuration: CPU backend with 8 virtual devices (SURVEY.md §4.6).

Tests never require TPU hardware: manifold math runs in float64 on CPU,
Pallas kernels run in interpret mode, and distributed code runs on the
8 fake CPU devices created here.  Must run before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize may have imported jax already (registering a
# remote TPU backend), in which case the env var above is read too late — the
# config update is authoritative either way.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: most suite wall-time is XLA CPU compiles,
# which are identical run to run.  First (cold) run pays full price and
# populates the cache; warm reruns — the common CI/dev loop — skip them.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

# Hermetic tile sizing: the checked-in autotune table
# (configs/scan_topk_tiles.json) is tuned for device_kind "cpu" — the
# very backend the suite runs on — so without this, checking in a
# re-tuned table would silently change every engine's chunk sizing
# under test.  Tile choice is result-invisible (tested), but sizing
# assertions must see the static model; tests that exercise tuned
# lookups monkeypatch this env var to their own table.
os.environ.setdefault("HYPERSPACE_AUTOTUNE_TABLE", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def pytest_runtest_protocol(item, nextitem):
    """Strict single rerun for ``@pytest.mark.flaky`` tests.

    A test carrying the marker gets ONE retry when its first attempt
    fails (fresh setup/teardown both times); only the final attempt is
    reported.  Two consecutive failures fail the run exactly like an
    unmarked test — the marker absorbs a known stochastic threshold
    (e.g. the sampled-LP AUC-improvement assertion), it does not hide a
    real regression, which fails twice in a row.  Markers must cite the
    flake they cover in a comment at the use site."""
    if item.get_closest_marker("flaky") is None:
        return None
    from _pytest.runner import runtestprotocol

    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for report in reports:
        item.ihook.pytest_runtest_logreport(report=report)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True
