"""Test configuration: CPU backend with 8 virtual devices (SURVEY.md §4.6).

Tests never require TPU hardware: manifold math runs in float64 on CPU,
Pallas kernels run in interpret mode, and distributed code runs on the
8 fake CPU devices created here.  Must run before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize may have imported jax already (registering a
# remote TPU backend), in which case the env var above is read too late — the
# config update is authoritative either way.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: most suite wall-time is XLA CPU compiles,
# which are identical run to run.  First (cold) run pays full price and
# populates the cache; warm reruns — the common CI/dev loop — skip them.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
