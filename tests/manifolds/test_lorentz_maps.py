"""Lorentz specifics + ball↔hyperboloid isometry tests (SURVEY.md §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import (
    Lorentz,
    PoincareBall,
    ball_to_lorentz,
    lorentz_to_ball,
    minkowski_dot,
)


@pytest.fixture(params=[0.5, 1.0, 2.0])
def c(request):
    return request.param


def test_roundtrip(c):
    lor = Lorentz(c)
    x = lor.random_normal(jax.random.PRNGKey(0), (32, 7), jnp.float64)
    y = lorentz_to_ball(x, c)
    x2 = ball_to_lorentz(y, c)
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x), atol=1e-9)
    # and the image is inside the ball
    assert np.all(c * np.sum(np.asarray(y) ** 2, -1) < 1.0)


def test_isometry(c):
    """Distances agree between the two models (maps are isometries)."""
    lor, ball = Lorentz(c), PoincareBall(c)
    k = jax.random.split(jax.random.PRNGKey(1), 2)
    x = lor.random_normal(k[0], (32, 7), jnp.float64)
    y = lor.random_normal(k[1], (32, 7), jnp.float64)
    d_l = np.asarray(lor.dist(x, y))
    d_b = np.asarray(ball.dist(lorentz_to_ball(x, c), lorentz_to_ball(y, c)))
    np.testing.assert_allclose(d_b, d_l, rtol=1e-8, atol=1e-10)


def test_dist_golden(c):
    """d(o, exp_o(t e₁)) = t for any radial tangent step."""
    lor = Lorentz(c)
    o = lor.origin((1, 4), jnp.float64)
    t = 1.37
    v = jnp.zeros((1, 4), jnp.float64).at[..., 1].set(t)
    y = lor.expmap(o, v)
    np.testing.assert_allclose(np.asarray(lor.dist(o, y))[0], t, rtol=1e-10)


def test_centroid_on_manifold_and_symmetric(c):
    lor = Lorentz(c)
    x = lor.random_normal(jax.random.PRNGKey(2), (8, 5, 4), jnp.float64)
    mu = lor.centroid(x)
    np.testing.assert_allclose(
        np.asarray(minkowski_dot(mu, mu, keepdims=False)), -1.0 / c, rtol=1e-9
    )
    # centroid of {y, y} is y
    y = x[:, :1]
    mu2 = lor.centroid(jnp.concatenate([y, y], axis=-2))
    np.testing.assert_allclose(np.asarray(mu2), np.asarray(y[:, 0]), atol=1e-9)


def test_egrad2rgrad_tangency(c):
    lor = Lorentz(c)
    x = lor.random_normal(jax.random.PRNGKey(3), (16, 5), jnp.float64)
    g = jax.random.normal(jax.random.PRNGKey(4), x.shape, x.dtype)
    rg = lor.egrad2rgrad(x, g)
    np.testing.assert_allclose(
        np.asarray(minkowski_dot(x, rg, keepdims=False)), 0.0, atol=1e-9
    )
