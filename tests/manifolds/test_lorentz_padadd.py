"""The Lorentz lifts are pad+add, bitwise-equal to the concat forms.

jax 0.4.37's GSPMD partitioner miscompiles `concatenate` whose operands
are sharded over a subset of a multi-axis mesh's axes (minimal repro:
tests/parallel/test_node_sharded.py::test_gspmd_concat_constraint_
miscompile), so every Lorentz time-coordinate lift/split was rewritten
as pad(+add) (manifolds/lorentz._pad_last / with_time_coordinate).
These tests pin the rewrite to the old `jnp.concatenate` forms
BITWISE on a single device — the rewrite is a partitioner dodge, never
a numerics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, smath
from hyperspace_tpu.manifolds.lorentz import with_time_coordinate


def _bitwise(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape
    assert a.tobytes() == b.tobytes(), (
        f"bitwise mismatch: max abs diff {np.max(np.abs(a - b))}")


@pytest.fixture(params=[jnp.float32, jnp.float64])
def data(request):
    dt = request.param
    k = jax.random.PRNGKey(7)
    kx, kg, kv = jax.random.split(k, 3)
    x = jax.random.normal(kx, (17, 9), dt)
    g = jax.random.normal(kg, (17, 9), dt)
    v = jax.random.normal(kv, (17, 8), dt)
    return dt, x, g, v


@pytest.mark.parametrize("c", [1.0, 0.7])
def test_proj_matches_concat_form(data, c):
    dt, x, _, _ = data
    m = Lorentz(c)
    sp = x[..., 1:]
    cc = jnp.asarray(c, dt)
    t = smath.safe_sqrt(
        1.0 / smath.clamp_min(cc, smath.min_norm(dt)) + smath.sq_norm(sp))
    _bitwise(m.proj(x), jnp.concatenate([t, sp], axis=-1))


def test_with_time_coordinate_matches_concat_form(data):
    dt, x, _, _ = data
    sp = x  # any space block
    cc = jnp.asarray(0.9, dt)
    t = smath.safe_sqrt(
        1.0 / smath.clamp_min(cc, smath.min_norm(dt)) + smath.sq_norm(sp))
    _bitwise(with_time_coordinate(sp, cc),
             jnp.concatenate([t, sp], axis=-1))


def test_origin_matches_concat_form(data):
    dt, _, _, _ = data
    m = Lorentz(1.3)
    shape = (5, 9)
    o = jnp.zeros(shape, dt)
    t = jnp.ones(shape[:-1] + (1,), dt) / smath.sqrt_c(jnp.asarray(1.3, dt))
    _bitwise(m.origin(shape, dt), jnp.concatenate([t, o[..., 1:]], axis=-1))


def test_egrad2rgrad_matches_concat_form(data):
    dt, x, g, _ = data
    m = Lorentz(1.0)
    xp = m.proj(x)
    gl = jnp.concatenate([-g[..., :1], g[..., 1:]], axis=-1)
    _bitwise(m.egrad2rgrad(xp, g), m.proju(xp, gl))


def test_tangent_lift_matches_concat_form(data):
    dt, _, _, v = data
    m = Lorentz(1.0)
    _bitwise(m.tangent_from_origin_coords(v),
             jnp.concatenate([jnp.zeros_like(v[..., :1]), v], axis=-1))


def test_gcn_tangent_roundtrip_unchanged(data):
    """from_tangent0_coords routes through the pad lift — the chart
    round-trip (gcn.tangent0_coords ∘ from_tangent0_coords) stays
    exact and on-manifold."""
    from hyperspace_tpu.nn import gcn

    dt, _, _, v = data
    m = Lorentz(1.0)
    x = gcn.from_tangent0_coords(m, v)
    assert np.max(np.asarray(m.check_point(x))) < 1e-5
    old = m.expmap0(jnp.concatenate(
        [jnp.zeros_like(v[..., :1]), v], axis=-1))
    _bitwise(x, old)


def test_no_concatenate_left_in_lorentz_lifts():
    """Source-level pin: manifolds/lorentz.py must stay concatenate-free
    (the sharded-path rule — a re-grown concat would silently re-arm
    the GSPMD miscompile on multi-axis meshes)."""
    import ast
    import inspect

    from hyperspace_tpu.manifolds import lorentz as L

    calls = [n for n in ast.walk(ast.parse(inspect.getsource(L)))
             if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)
             and n.func.attr == "concatenate"]
    assert not calls, f"concatenate re-grew at lines {[c.lineno for c in calls]}"
