"""Manifold axiom tests (SURVEY.md §4.1): property checks in float64.

Each geometry must satisfy, on random batches of points/tangents:
exp∘log = id, symmetry of distance, triangle inequality, metric preservation
under parallel transport, and the on-manifold constraint after every op.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import (
    Euclidean,
    Lorentz,
    PoincareBall,
    Product,
    Sphere,
)

B, D = 64, 8
CURVS = [0.5, 1.0, 2.3]


def make_points(man, key, n=B, d=D, std=0.7):
    dim = man.ambient_dim(d) if man.name == "lorentz" else d
    if man.name == "product":
        dim = man.total_dim
    return man.random_normal(key, (n, dim), jnp.float64, std=std)


def make_tangent(man, key, x, scale=0.5):
    # logmap to a second random point gives a tangent whose *Riemannian* norm
    # is a typical inter-point distance — bounded on every geometry, unlike a
    # raw ambient Gaussian (whose metric norm explodes near the ball boundary).
    y = make_points(man, key, n=x.shape[0])
    return scale * man.logmap(x, y)


def manifolds():
    out = []
    for c in CURVS:
        out.append(PoincareBall(c))
        out.append(Lorentz(c))
        out.append(Sphere(c))
    out.append(Euclidean())
    out.append(
        Product([PoincareBall(1.0), Sphere(1.0), Euclidean()], [4, 4, 4])
    )
    return out


@pytest.mark.parametrize("man", manifolds(), ids=lambda m: f"{m.name}-{getattr(m, 'c', '')}")
class TestAxioms:
    def _xyv(self, man):
        k = jax.random.split(jax.random.PRNGKey(7), 4)
        x = make_points(man, k[0])
        y = make_points(man, k[1])
        v = make_tangent(man, k[2], x)
        return x, y, v

    def test_on_manifold(self, man):
        x, y, v = self._xyv(man)
        np.testing.assert_allclose(man.check_point(x), 0.0, atol=1e-8)
        np.testing.assert_allclose(man.check_point(man.expmap(x, v)), 0.0, atol=1e-7)

    def test_exp_log_inverse(self, man):
        x, y, _ = self._xyv(man)
        y2 = man.expmap(x, man.logmap(x, y))
        # atol 2e-5: near-boundary ball points lose ~2 digits to artanh's
        # conditioning even in f64; this is inherent, not an implementation bug.
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y), atol=2e-5)

    def test_log_exp_inverse(self, man):
        x, _, v = self._xyv(man)
        v2 = man.logmap(x, man.expmap(x, v))
        np.testing.assert_allclose(np.asarray(v2), np.asarray(v), atol=1e-6)

    def test_dist_symmetric_and_zero(self, man):
        x, y, _ = self._xyv(man)
        np.testing.assert_allclose(
            np.asarray(man.dist(x, y)), np.asarray(man.dist(y, x)), atol=1e-8
        )
        assert np.all(np.asarray(man.dist(x, x)) < 1e-6)
        assert np.all(np.asarray(man.dist(x, y)) >= 0.0)

    def test_triangle_inequality(self, man):
        k = jax.random.split(jax.random.PRNGKey(11), 3)
        x, y, z = (make_points(man, kk) for kk in k)
        dxz = np.asarray(man.dist(x, z))
        dxy = np.asarray(man.dist(x, y))
        dyz = np.asarray(man.dist(y, z))
        assert np.all(dxz <= dxy + dyz + 1e-7)

    def test_dist_matches_norm_of_log(self, man):
        x, y, _ = self._xyv(man)
        d = np.asarray(man.dist(x, y))
        nl = np.asarray(man.norm_t(x, man.logmap(x, y)))
        np.testing.assert_allclose(nl, d, atol=1e-6)

    def test_ptransp_preserves_inner(self, man):
        x, y, v = self._xyv(man)
        k = jax.random.PRNGKey(13)
        w = make_tangent(man, k, x)
        ip_x = np.asarray(man.inner(x, v, w))
        vt = man.ptransp(x, y, v)
        wt = man.ptransp(x, y, w)
        ip_y = np.asarray(man.inner(y, vt, wt))
        np.testing.assert_allclose(ip_y, ip_x, rtol=1e-5, atol=1e-7)

    def test_ptransp_lands_in_tangent(self, man):
        if man.name in ("poincare", "euclidean", "product"):
            pytest.skip("tangent space is the full ambient space")
        x, y, v = self._xyv(man)
        vt = man.ptransp(x, y, v)
        # residual of the tangency constraint at y
        res = np.asarray(man.inner(y, vt, vt) - man.inner(y, man.proju(y, vt), man.proju(y, vt)))
        np.testing.assert_allclose(res, 0.0, atol=1e-7)

    def test_expmap0_logmap0_roundtrip(self, man):
        _, y, _ = self._xyv(man)
        y2 = man.expmap0(man.logmap0(y))
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y), atol=1e-6)

    def test_jit_and_grad_clean(self, man):
        x, y, _ = self._xyv(man)

        @jax.jit
        def loss(x, y):
            return jnp.sum(man.sqdist(x, y))

        g = jax.grad(loss)(x, y)
        assert np.all(np.isfinite(np.asarray(g)))
        # gradient at coincident points must be finite (degenerate case §4.2)
        g2 = jax.grad(loss)(x, x)
        assert np.all(np.isfinite(np.asarray(g2)))
