"""Autodiff checks vs float64 finite differences (SURVEY.md §4.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall, Sphere


def fd_grad(f, x, eps=1e-6):
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (f(jnp.asarray(xp)) - f(jnp.asarray(xm))) / (2 * eps)
        it.iternext()
    return g


@pytest.mark.parametrize("man", [PoincareBall(1.3), Lorentz(0.8), Sphere(1.0)], ids=lambda m: m.name)
def test_dist_grad_matches_fd(man):
    k = jax.random.split(jax.random.PRNGKey(0), 2)
    x = man.random_normal(k[0], (3, 4), jnp.float64, std=0.5)
    y = man.random_normal(k[1], (3, 4), jnp.float64, std=0.5)

    def f(x_):
        if man.name in ("lorentz", "sphere"):
            x_ = man.proj(x_)  # constrain FD perturbations back to the manifold
        return float(jnp.sum(man.sqdist(x_, y)))

    def f_jax(x_):
        if man.name in ("lorentz", "sphere"):
            x_ = man.proj(x_)
        return jnp.sum(man.sqdist(x_, y))

    g = np.asarray(jax.grad(f_jax)(x))
    g_fd = fd_grad(f, x)
    np.testing.assert_allclose(g, g_fd, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("man", [PoincareBall(1.0), Lorentz(1.0)], ids=lambda m: m.name)
def test_expmap_grad_matches_fd(man):
    k = jax.random.split(jax.random.PRNGKey(1), 2)
    x = man.random_normal(k[0], (2, 3), jnp.float64, std=0.4)
    v = man.proju(x, 0.3 * jax.random.normal(k[1], x.shape, x.dtype))
    w = jax.random.normal(jax.random.PRNGKey(2), x.shape, x.dtype)

    def f(v_):
        if man.name == "lorentz":
            v_ = man.proju(x, v_)
        return float(jnp.sum(w * man.expmap(x, v_)))

    def f_jax(v_):
        if man.name == "lorentz":
            v_ = man.proju(x, v_)
        return jnp.sum(w * man.expmap(x, v_))

    g = np.asarray(jax.grad(f_jax)(v))
    g_fd = fd_grad(f, v)
    np.testing.assert_allclose(g, g_fd, rtol=1e-4, atol=1e-6)


def test_no_nan_at_degenerate_points():
    """Gradients at the origin / coincident points / near boundary are finite."""
    ball = PoincareBall(1.0)
    zero = jnp.zeros((2, 3), jnp.float64)

    for fn in (
        lambda x: jnp.sum(ball.expmap0(x)),
        lambda x: jnp.sum(ball.logmap0(x)),
        lambda x: jnp.sum(ball.dist0(x)),
        lambda x: jnp.sum(ball.mobius_scalar_mul(2.0, x)),
    ):
        g = jax.grad(fn)(zero)
        assert np.all(np.isfinite(np.asarray(g))), fn

    lor = Lorentz(1.0)
    o = lor.origin((2, 4), jnp.float64)
    g = jax.grad(lambda x: jnp.sum(lor.sqdist(lor.proj(x), o)))(o)
    assert np.all(np.isfinite(np.asarray(g)))


def test_curvature_is_differentiable():
    """d/dc of a distance must exist and be finite (learned curvature)."""

    def loss(c):
        ball = PoincareBall(c)
        x = jnp.array([[0.1, 0.2]], jnp.float64)
        y = jnp.array([[-0.3, 0.05]], jnp.float64)
        return jnp.sum(ball.dist(x, y))

    g = jax.grad(loss)(jnp.asarray(1.0, jnp.float64))
    assert np.isfinite(float(g)) and abs(float(g)) > 0

    def loss_l(c):
        lor = Lorentz(c)
        o = lor.origin((1, 3), jnp.float64)
        y = lor.expmap(o, jnp.array([[0.0, 0.5, 0.1]], jnp.float64))
        return jnp.sum(lor.dist(o, y))

    g = jax.grad(loss_l)(jnp.asarray(1.0, jnp.float64))
    assert np.isfinite(float(g))
