"""Poincaré-ball specifics: gyro identities, golden values, Möbius ops
(SURVEY.md §4.1, §4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall


@pytest.fixture(params=[0.7, 1.0, 1.8])
def ball(request):
    return PoincareBall(request.param)


def pts(ball, key, n=32, d=6, std=0.8):
    return ball.random_normal(key, (n, d), jnp.float64, std=std)


def test_mobius_left_identity(ball):
    x = pts(ball, jax.random.PRNGKey(0))
    z = jnp.zeros_like(x)
    np.testing.assert_allclose(np.asarray(ball.mobius_add(z, x)), np.asarray(x), atol=1e-10)
    np.testing.assert_allclose(np.asarray(ball.mobius_add(x, z)), np.asarray(x), atol=1e-10)


def test_mobius_left_inverse(ball):
    x = pts(ball, jax.random.PRNGKey(1))
    np.testing.assert_allclose(
        np.asarray(ball.mobius_add(-x, x)), 0.0, atol=1e-8
    )


def test_gyration_closed_form_matches_definition(ball):
    """gyr[u,v]w == -(u⊕v) ⊕ (u ⊕ (v ⊕ w))."""
    k = jax.random.split(jax.random.PRNGKey(2), 3)
    u, v, w = (pts(ball, kk, std=0.5) for kk in k)
    lhs = ball.gyration(u, v, w)
    rhs = ball.mobius_add(
        -ball.mobius_add(u, v), ball.mobius_add(u, ball.mobius_add(v, w))
    )
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs), atol=1e-7)


def test_gyro_associative_law(ball):
    """u ⊕ (v ⊕ w) == (u ⊕ v) ⊕ gyr[u,v]w (left gyroassociativity)."""
    k = jax.random.split(jax.random.PRNGKey(3), 3)
    u, v, w = (pts(ball, kk, std=0.5) for kk in k)
    lhs = ball.mobius_add(u, ball.mobius_add(v, w))
    rhs = ball.mobius_add(ball.mobius_add(u, v), ball.gyration(u, v, w))
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs), atol=1e-7)


def test_scalar_mul_distributes(ball):
    x = pts(ball, jax.random.PRNGKey(4))
    lhs = ball.mobius_scalar_mul(3.0, x)
    rhs = ball.mobius_add(x, ball.mobius_add(x, x))
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs), atol=1e-7)
    # (r1 r2) ⊗ x = r1 ⊗ (r2 ⊗ x)
    lhs = ball.mobius_scalar_mul(0.75, x)
    rhs = ball.mobius_scalar_mul(1.5, ball.mobius_scalar_mul(0.5, x))
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs), atol=1e-9)


def test_matvec_identity_and_compose(ball):
    x = pts(ball, jax.random.PRNGKey(5))
    d = x.shape[-1]
    eye = jnp.eye(d, dtype=x.dtype)
    np.testing.assert_allclose(
        np.asarray(ball.mobius_matvec(eye, x)), np.asarray(x), atol=1e-9
    )
    # r·I as matvec == scalar mul
    np.testing.assert_allclose(
        np.asarray(ball.mobius_matvec(0.3 * eye, x)),
        np.asarray(ball.mobius_scalar_mul(0.3, x)),
        atol=1e-9,
    )


def test_dist_golden_1d():
    """Golden value: c=1, x=0, y=0.5 ⇒ d = 2·artanh(0.5) = 1.0986122886681098."""
    ball = PoincareBall(1.0)
    x = jnp.zeros((1, 1), jnp.float64)
    y = jnp.full((1, 1), 0.5, jnp.float64)
    np.testing.assert_allclose(
        np.asarray(ball.dist(x, y))[0], 2.0 * np.arctanh(0.5), rtol=1e-12
    )


def test_dist_golden_curvature_scaling():
    """d_c(x,y) = d_1(√c x, √c y)/√c (homothety invariance)."""
    c = 2.3
    b1, bc = PoincareBall(1.0), PoincareBall(c)
    k = jax.random.split(jax.random.PRNGKey(6), 2)
    x = b1.random_normal(k[0], (16, 5), jnp.float64, std=0.6) / np.sqrt(c)
    y = b1.random_normal(k[1], (16, 5), jnp.float64, std=0.6) / np.sqrt(c)
    np.testing.assert_allclose(
        np.asarray(bc.dist(x, y)),
        np.asarray(b1.dist(np.sqrt(c) * x, np.sqrt(c) * y)) / np.sqrt(c),
        rtol=1e-9,
    )


def test_expmap_golden_radial():
    """c=1: exp_0(v) = tanh(‖v‖) v/‖v‖."""
    ball = PoincareBall(1.0)
    v = jnp.array([[0.3, 0.4]], jnp.float64)
    out = np.asarray(ball.expmap0(v))
    n = 0.5
    expect = np.tanh(n) * np.array([[0.3, 0.4]]) / n
    np.testing.assert_allclose(out, expect, rtol=1e-10)


def test_project_keeps_interior(ball):
    x = jnp.full((4, 3), 10.0, jnp.float64)
    p = np.asarray(ball.proj(x))
    c = float(ball.c)
    assert np.all(np.sum(p * p, -1) * c < 1.0)


def test_grad_near_boundary_finite(ball):
    c = float(ball.c)
    r = (1.0 - 1e-9) / np.sqrt(c)
    x = jnp.array([[r / np.sqrt(3.0)] * 3], jnp.float64)

    def f(x):
        return jnp.sum(ball.dist0(x))

    g = jax.grad(f)(x)
    assert np.all(np.isfinite(np.asarray(g)))


def test_gyromidpoint_of_pair_is_on_geodesic_midpoint(ball):
    k = jax.random.split(jax.random.PRNGKey(8), 2)
    x = pts(ball, k[0], n=8)
    y = pts(ball, k[1], n=8)
    mid = ball.gyromidpoint(jnp.stack([x, y], axis=-2))
    # geodesic midpoint via expmap of half the log
    mid2 = ball.expmap(x, 0.5 * ball.logmap(x, y))
    np.testing.assert_allclose(np.asarray(mid), np.asarray(mid2), atol=1e-6)
