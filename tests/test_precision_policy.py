"""Precision-policy unit contracts (hyperspace_tpu/precision.py) and the
no-ad-hoc-bf16 lint (scripts/check_precision_policy.py)."""

import importlib.util
import os

import jax.numpy as jnp
import pytest

from hyperspace_tpu import precision as P


def _lint_mod():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "scripts", "check_precision_policy.py")
    spec = importlib.util.spec_from_file_location("check_precision_policy",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_presets_and_lookup():
    assert P.get_policy(None) is P.F32
    assert P.get_policy("f32") is P.F32
    assert P.get_policy("bf16") is P.BF16
    assert P.get_policy(P.BF16) is P.BF16
    assert not P.F32.mixed
    assert P.BF16.mixed
    assert jnp.dtype(P.BF16.compute) == jnp.dtype(jnp.bfloat16)
    # every non-compute lane of the bf16 preset stays f32: params,
    # accumulation, and the boundary-sensitive manifold math
    for dt in (P.BF16.param, P.BF16.accum, P.BF16.boundary):
        assert jnp.dtype(dt) == jnp.dtype(jnp.float32)
    with pytest.raises(ValueError, match="unknown precision"):
        P.get_policy("fp8")


def test_f32_cast_helpers_are_identity():
    """The f32 preset must return the INPUT OBJECT — zero added ops, so
    precision=f32 is bit-identical to a pre-policy build."""
    x = jnp.ones((3,), jnp.float32)
    for fn in (P.F32.cast_compute, P.F32.cast_boundary, P.F32.cast_accum,
               P.F32.cast_param):
        assert fn(x) is x
    tree = {"a": x, "b": jnp.arange(3)}
    assert P.F32.cast_compute_tree(tree) is tree
    assert P.F32.module_dtype() is None


def test_bf16_casts_floats_only():
    x32 = jnp.ones((3,), jnp.float32)
    ints = jnp.arange(3, dtype=jnp.int32)
    mask = jnp.ones((3,), bool)
    assert P.BF16.cast_compute(x32).dtype == jnp.dtype(jnp.bfloat16)
    # ids/masks must never be cast (they'd stop being ids/masks)
    assert P.BF16.cast_compute(ints) is ints
    assert P.BF16.cast_compute(mask) is mask
    tree = P.BF16.cast_compute_tree({"x": x32, "i": ints})
    assert tree["x"].dtype == jnp.dtype(jnp.bfloat16)
    assert tree["i"] is ints
    # the boundary/accum/param casts bring a compute-dtype array BACK
    xc = P.BF16.cast_compute(x32)
    assert P.BF16.cast_boundary(xc).dtype == jnp.dtype(jnp.float32)
    assert P.BF16.cast_accum(xc).dtype == jnp.dtype(jnp.float32)
    assert P.BF16.cast_param(xc).dtype == jnp.dtype(jnp.float32)


def test_parse_dtype():
    assert jnp.dtype(P.parse_dtype("bfloat16")) == jnp.dtype(jnp.bfloat16)
    assert jnp.dtype(P.parse_dtype("float32")) == jnp.dtype(jnp.float32)
    assert P.parse_dtype(None) is None
    assert P.parse_dtype(None, default="x") == "x"
    assert P.parse_dtype(jnp.float32) is jnp.float32  # pass-through
    with pytest.raises(ValueError, match="unknown dtype"):
        P.parse_dtype("definitely-not-a-dtype")


def test_policy_is_hashable_config_material():
    """Policies ride in frozen dataclass configs used as jit statics."""
    assert hash(P.BF16) != hash(P.F32)
    assert P.get_policy("bf16") == P.BF16


# --- the lint ---------------------------------------------------------------


def test_lint_catches_adhoc_bf16():
    lint = _lint_mod()
    bad = "x = y.astype(jnp.bfloat16)\nz = h.astype('bfloat16')\n"
    hits = lint.violations_in_text(bad, "pkg/mod.py")
    assert len(hits) == 2 and "pkg/mod.py:1" in hits[0]
    # comments and the annotation escape do not trigger
    ok = ("# jnp.bfloat16 is discussed here only\n"
          'flag: str = "bfloat16"  # precision-policy: ok (CLI flag)\n')
    assert lint.violations_in_text(ok, "pkg/mod.py") == []


def test_package_is_lint_clean(capsys):
    """The shipped package carries no ad-hoc bf16 literal outside
    precision.py / kernels/ (run exactly as CI would)."""
    lint = _lint_mod()
    rc = lint.main()
    assert rc == 0, capsys.readouterr().out
