"""The strict-rerun ``flaky`` marker (tests/conftest.py) really retries:
this test FAILS its first attempt on purpose and passes the second — a
broken/removed hook surfaces immediately as a red test, not as a
silently-flaky tier-1 signal."""

import pytest

_attempts = {"n": 0}


@pytest.mark.flaky
def test_flaky_marker_gives_exactly_one_retry():
    _attempts["n"] += 1
    assert _attempts["n"] == 2, (
        "first attempt fails by design; the strict-rerun hook must run "
        "the test a second time and report only that attempt")
