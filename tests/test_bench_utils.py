"""Roofline / repeat-spread bench helpers (VERDICT r4 #6/#9/#10).

These fields ride in every BENCH_r*.json; a silent breakage would strip
the artifact of its MFU statement and contention markers, so the helper
contracts are pinned here (CPU — cost analysis works on any backend).
"""

import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.benchmarks.hgcn_bench import (
    V5E_HBM_BYTES_PER_S,
    roofline_fields,
    spread,
    step_cost,
    time_steps_all,
)


def _stepper(st):
    return st @ st, jnp.sum(st)


def test_step_cost_reports_flops_and_bounds():
    c = step_cost(_stepper, jnp.ones((128, 128), jnp.float32))
    # one 128^3 matmul fwd: flops >= 2*128^3; bytes >= the operand reads
    assert c["flops_per_step"] >= 2 * 128**3
    assert c["bytes_per_step"] >= 128 * 128 * 4
    assert c["hbm_bound_ms"] > 0
    np.testing.assert_allclose(
        c["hbm_bound_ms"],
        round(c["bytes_per_step"] / V5E_HBM_BYTES_PER_S * 1e3, 6))
    # the assumed-chip peaks vs the chip that actually ran must both be
    # in the artifact (ADVICE r5): CPU numbers read as "fraction of a
    # v5e", never as on-chip truth
    assert c["roofline_chip"] == "v5e"
    assert c["device_kind"]  # e.g. "cpu" here, "TPU v5e" on chip


def test_roofline_fields_fraction_and_bound():
    cost = {"flops_per_step": 1e9, "bytes_per_step": 8.19e6,
            "hbm_bound_ms": 0.01, "mxu_bound_ms": 0.005}
    r = roofline_fields(cost, 1e-3)          # measured 1 ms step
    assert r["frac_hbm_roofline"] == 0.01    # 0.01 ms bound / 1 ms step
    assert r["bound"] == "hbm"
    r2 = roofline_fields({**cost, "mxu_bound_ms": 0.02}, 1e-3)
    assert r2["bound"] == "mxu"
    assert roofline_fields({}, 1e-3) == {}   # cost-analysis failure: inert


def test_step_cost_failure_is_inert():
    assert step_cost(lambda st: 1 / 0, jnp.ones(3)) == {}


def test_time_steps_all_and_spread():
    times, st, loss = time_steps_all(_stepper, jnp.ones((16, 16)), 2, 3)
    assert len(times) == 3 and all(t > 0 for t in times)
    assert spread(times) >= 1.0
    assert spread([2.0, 1.0]) == 2.0
