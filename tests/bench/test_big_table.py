"""`bench_big_table` (r15/r16, docs/benchmarks.md): a miniature
end-to-end leg — sharded generation, host-streamed index build, the
five serve lanes (f32/bf16/int8/int4/pq) with recall-gated qps, the
host-vs-in-HBM train pair — plus the compact-line field wiring."""

import json

import numpy as np
import pytest

import bench


@pytest.fixture(scope="module")
def result():
    return bench.bench_big_table(repeats=1, rows=6000, dim=16, ncells=24,
                                 train_rows=2000, queries=16)


def test_record_shape_and_headline(result):
    assert result["metric"] == "big_table_qps_at_recall99"
    assert result["unit"] == "queries/s"
    d = result["detail"]
    assert d["rows"] == 6000 and d["ncells"] == 24
    assert d["build_s"] >= 0 and d["gen_s"] >= 0
    # the headline value IS the int8 lane's recall-gated qps
    assert result["value"] == d["lanes"]["int8"]["qps_at_recall99"]
    assert result["value"] > 0  # the ladder reached recall >= 0.99
    # the whole record serializes (the emit contract)
    json.dumps(result, default=bench._json_default)


def test_all_three_lanes_report_recall_gated_qps(result):
    lanes = result["detail"]["lanes"]
    for lane in ("f32", "bf16", "int8"):
        assert lane in lanes, lanes.keys()
        out = lanes[lane]
        assert out["qps_at_recall99"] > 0
        # the qualifying probe actually held the recall bar
        best = max(v["recall10"] for v in out["probes"].values()
                   if "recall10" in v)
        assert best >= 0.99


def test_table_bytes_order_is_the_capacity_story(result):
    mb = result["detail"]["table_mb"]
    # int8 (code + per-row scale) < bf16 < f32 — the 4× lever
    assert mb["int8"] < mb["bf16"] < mb["f32"]
    # the r16 quarter lanes keep shrinking (rounded to 0.1 MB, so the
    # sub-int8 steps are <= at this miniature size, never >)
    assert mb["int4"] <= mb["int8"]
    assert mb["pq"] <= mb["int4"]


def test_quarter_lanes_report(result):
    """int4 rides the same rescore contract as int8 (recall-gated qps >
    0); pq reports bytes + honest per-probe recall, qualifying or not."""
    lanes = result["detail"]["lanes"]
    assert lanes["int4"]["qps_at_recall99"] > 0
    best = max(v["recall10"] for v in lanes["int4"]["probes"].values())
    assert best >= 0.99
    pq = lanes["pq"]
    assert pq["table_mb"] <= lanes["int4"]["table_mb"]
    assert pq["probes"], "pq must walk the probe ladder"
    for v in pq["probes"].values():
        assert 0.0 <= v["recall10"] <= 1.0 and v["qps"] > 0


def test_train_pair_present_and_finite(result):
    tr = result["detail"].get("train")
    assert tr, result["detail"].get("train_error")
    assert tr["host_step_ms"] > 0 and tr["inhbm_step_ms"] > 0
    assert np.isfinite(tr["host_vs_inhbm"])
    # rows > train_rows: the full-size host-only reading rides along
    assert tr["host_step_ms_full"] > 0


def test_compact_fields_fire_in_both_modes(result):
    # headline mode (--metric big_table): flat detail paths
    line = bench.compact_headline(result)
    rec = json.loads(line)
    assert rec["detail"]["big_qps_r99_int8"] == result["value"]
    assert rec["detail"]["big_table_mb_int8"] == \
        result["detail"]["table_mb"]["int8"]
    assert "big_build_s" in rec["detail"]
    assert "big_host_step_ms" in rec["detail"]
    # auto mode: the nested leg paths
    nested = {"metric": "x", "value": 1, "unit": "", "vs_baseline": None,
              "detail": {"big_table": result["detail"]}}
    rec = json.loads(bench.compact_headline(nested))
    assert rec["detail"]["big_qps_r99_int8"] == result["value"]
