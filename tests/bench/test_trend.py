"""scripts/bench_trend.py: the cross-round trend report + regression
gate, run (1) against the repo's REAL checked-in BENCH_r01–r05
artifacts — which must tolerate the r04 ``parsed: null`` and the r05
rc=124 rows without crashing and still gate green — and (2) against
synthetic fixtures proving the gate's pass/fail contract."""

import glob
import json
import os
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
SCRIPT = os.path.join(ROOT, "scripts", "bench_trend.py")


def _run(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, SCRIPT, *args],
        capture_output=True, text=True, cwd=cwd, timeout=60)


def _stage_real_rounds(tmp_path) -> str:
    """Copy only the CHECKED-IN BENCH_r*.json wrappers into a tmp dir:
    the working tree's bench_full.json is machine-local (a slower box's
    fresh bench run must not turn this suite red)."""
    for p in glob.glob(os.path.join(ROOT, "BENCH_r*.json")):
        shutil.copy(p, tmp_path / os.path.basename(p))
    return str(tmp_path)


def _wrapper(n, value, metric="hgcn_samples_per_sec_per_chip", rc=0,
             detail=None):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "…",
            "parsed": {"metric": metric, "value": value,
                       "unit": "samples/s/chip", "vs_baseline": None,
                       "detail": detail or (
                           {"step_time_s": 1.0 / value} if value else {})}}


def _write_rounds(tmp_path, values, metric="hgcn_samples_per_sec_per_chip"):
    for i, v in enumerate(values, 1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_wrapper(i, v, metric=metric)))


# --- the checked-in artifacts ------------------------------------------------


def test_real_artifacts_emit_parseable_trend_json(tmp_path):
    res = _run("--dir", _stage_real_rounds(tmp_path), "--json")
    assert res.returncode == 0, res.stderr
    report = json.loads(res.stdout)
    rounds = {r["round"]: r for r in report["rounds"]}
    # r01–r05 all listed; the two lost rounds are rows, not crashes
    for r in ("r01", "r02", "r03", "r04", "r05"):
        assert r in rounds
    assert rounds["r01"]["parsed"] and rounds["r03"]["parsed"]
    assert not rounds["r04"]["parsed"]          # rc=0, parsed null
    assert not rounds["r05"]["parsed"]          # rc=124, no artifact
    assert rounds["r05"]["rc"] == 124
    # the headline series exists with the known best
    s = report["series"]["hgcn_samples_per_sec_per_chip"]
    assert s["direction"] == "higher"
    assert s["best"]["value"] == 1244134.8 and s["best"]["round"] == "r03"
    # workload-shape constants never appear as detail series
    for noise in ("detail.num_nodes", "detail.devices", "detail.steps"):
        assert noise not in report["series"], noise


def test_real_artifacts_gate_green(tmp_path):
    res = _run("--dir", _stage_real_rounds(tmp_path), "--gate")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "GATE: ok" in res.stderr


def test_real_artifacts_markdown_mode(tmp_path):
    md_out = str(tmp_path / "trend.md")
    res = _run("--dir", ROOT, "--out-md", md_out)
    assert res.returncode == 0, res.stderr
    md = open(md_out).read()
    assert "# Bench trend" in md and "r04" in md and "r05" in md
    assert md == res.stdout  # stdout default is the same markdown


# --- synthetic gate fixtures -------------------------------------------------


def test_gate_passes_on_improving_series(tmp_path):
    _write_rounds(tmp_path, [100.0, 110.0, 121.0])
    res = _run("--dir", str(tmp_path), "--gate")
    assert res.returncode == 0, res.stdout + res.stderr


def test_gate_fails_on_regression_past_threshold(tmp_path):
    # latest 95 vs best 110: 13.6% down on a higher-better metric
    _write_rounds(tmp_path, [100.0, 110.0, 95.0])
    res = _run("--dir", str(tmp_path), "--gate")
    assert res.returncode == 1
    assert "regressed" in res.stderr
    # a looser threshold lets the same series through
    res = _run("--dir", str(tmp_path), "--gate", "--threshold", "0.2")
    assert res.returncode == 0


def test_gate_respects_lower_better_direction(tmp_path):
    # epoch time growing 1.0 → 1.25 s is the regression direction
    _write_rounds(tmp_path, [1.0, 1.25],
                  metric="poincare_embed_epoch_time")
    res = _run("--dir", str(tmp_path), "--gate")
    assert res.returncode == 1
    _write_rounds(tmp_path, [1.25, 1.0],
                  metric="poincare_embed_epoch_time")
    assert _run("--dir", str(tmp_path), "--gate").returncode == 0


def test_gate_zero_best_still_gates(tmp_path):
    # a lower-better headline whose best round recorded exactly 0 must
    # not be exempt: any step away from 0 is an (unboundedly large)
    # regression — reported with regression_pct null, not skipped
    _write_rounds(tmp_path, [0.0, 50.0],
                  metric="poincare_embed_epoch_time")
    res = _run("--dir", str(tmp_path), "--gate", "--json")
    assert res.returncode == 1, res.stdout
    regs = json.loads(res.stdout)["gate"]["regressions"]
    assert [r["regression_pct"] for r in regs] == [None]
    # holding at 0 is not a regression
    _write_rounds(tmp_path, [0.0, 0.0],
                  metric="poincare_embed_epoch_time")
    assert _run("--dir", str(tmp_path), "--gate").returncode == 0


def test_nested_detail_ms_series_infer_lower_direction(tmp_path):
    # the dotted detail path ends in '.p99'/'.f32', but the unit lives
    # in the 'latency_ms'/'train_step_ms' segment — the series this PR
    # adds must get a direction, not the '—' column
    for i, (p99, step) in enumerate([(2.0, 700.0), (2.4, 650.0)], 1):
        detail = {"latency_ms": {"b8": {"n": 4, "p50": 1.0, "p99": p99}},
                  "precision": {"train_step_ms": {"f32": step}}}
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_wrapper(i, 100.0 + i, detail=detail)))
    res = _run("--dir", str(tmp_path), "--json")
    assert res.returncode == 0, res.stderr
    series = json.loads(res.stdout)["series"]
    for key in ("detail.latency_ms.b8.p99",
                "detail.precision.train_step_ms.f32"):
        assert series[key]["direction"] == "lower", key
        assert "best" in series[key]
    assert series["detail.latency_ms.b8.p99"]["best"]["value"] == 2.0
    # the sample-count leaf is basis size, not a measurement: never
    # ranked best-when-smallest
    assert series["detail.latency_ms.b8.n"]["direction"] is None


def test_gate_tolerates_lost_rounds_and_sentinels(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_wrapper(1, 100.0)))
    # the r04 loss mode: rc=0, parsed null
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "cmd": "python bench.py", "rc": 0, "tail": "garbage",
         "parsed": None}))
    # the r05 loss mode: driver timeout
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"n": 3, "cmd": "python bench.py", "rc": 124, "tail": "",
         "parsed": None}))
    # a watchdog sentinel in bench_full.json must not gate (value 0!)
    (tmp_path / "bench_full.json").write_text(json.dumps(
        {"metric": "budget_exhausted", "value": 0, "unit": "",
         "vs_baseline": None, "detail": {"budget_exhausted": True}}))
    res = _run("--dir", str(tmp_path), "--gate", "--json")
    assert res.returncode == 0, res.stdout + res.stderr
    report = json.loads(res.stdout)
    assert len(report["rounds"]) == 4
    assert "budget_exhausted" not in report["series"]
    # the one parseable measurement survives as the series
    assert report["series"]["hgcn_samples_per_sec_per_chip"][
        "latest"]["value"] == 100.0


def test_bench_full_participates_as_latest_round(tmp_path):
    _write_rounds(tmp_path, [100.0, 110.0])
    # a fresh local bench run regressing 20% must trip the gate even
    # before a driver round records it
    (tmp_path / "bench_full.json").write_text(json.dumps(
        _wrapper(0, 88.0)["parsed"]))
    res = _run("--dir", str(tmp_path), "--gate", "--json")
    assert res.returncode == 1
    report = json.loads(res.stdout)
    s = report["series"]["hgcn_samples_per_sec_per_chip"]
    assert s["latest"]["round"] == "full"
    assert report["gate"]["regressions"][0]["regression_pct"] > 10


def test_empty_dir_is_a_distinct_error(tmp_path):
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 2
    assert "no BENCH_r*" in res.stderr


def test_unreadable_round_is_a_row_not_a_crash(tmp_path):
    _write_rounds(tmp_path, [100.0])
    (tmp_path / "BENCH_r02.json").write_text("{not json")
    res = _run("--dir", str(tmp_path), "--json")
    assert res.returncode == 0, res.stderr
    rounds = {r["round"]: r for r in json.loads(res.stdout)["rounds"]}
    assert not rounds["r02"]["parsed"] and "error" in rounds["r02"]


def test_direction_quality_metrics_are_higher_better():
    """Names carrying recall / hit_rate / auc are higher-is-better —
    the r10 recall@10 contract (and any future quality series) must
    gate in the right direction, not fall into the `_s`-suffix
    lower-better bucket or the unknown `—` column."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("recall10", "detail.ivf.probes.np4.recall10",
                 "detail.serve.ivf.qps_at_recall99",
                 "detail.serve.cache.cache_hit_rate", "val_auc",
                 "detail.hgcn.roc_auc"):
        assert mod.direction(name) == "higher", name
    # and the lower-better inference stays undisturbed around them
    assert mod.direction("detail.serve.ivf.build_s") == "lower"
    assert mod.direction("detail.latency_ms.b8.p99") == "lower"


def test_direction_speedup_ratio_are_higher_better():
    """Names carrying speedup / ratio are higher-is-better — the r12
    serve_fused_speedup headline and the per-bucket fused/two_stage
    ratios must gate in the right direction from round one.  The one
    exception: a *waste* ratio stays lower-better (waste outranks the
    generic ratio token)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("serve_fused_speedup",
                 "detail.serve.fused_vs_unfused.serve_fused_speedup",
                 "detail.serve.fused_vs_unfused.buckets.b64.ratio",
                 "speedup_at_recall99"):
        assert mod.direction(name) == "higher", name
    assert mod.direction("detail.serve.cache.padded_waste_ratio") == "lower"


def test_direction_table_size_tokens_are_lower_better():
    """The r15 big-table leg's capacity metrics — bytes / mb / hbm
    word-tokens per dotted segment — gate lower-is-better: a table
    growing must never read as regressions-are-good.  Matching is
    word-boundary per segment, so substrings stay inert: every *embed*
    metric contains the letters "mb" and must keep its own direction."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("table_bytes", "detail.big_table.table_mb.int8",
                 "detail.big_table.table_mb.f32", "hbm_gb",
                 "detail.big_table.lanes.bf16.table_mb",
                 "detail.big_table.hbm_bytes",
                 # the r16 sub-int8 lanes' compact fields and nested
                 # paths gate the same way — smaller tables only
                 "big_table_mb_int4", "big_table_mb_pq",
                 "detail.big_table.table_mb.pq",
                 "detail.big_table.lanes.int4.table_mb",
                 "detail.big_table.lanes.pq.table_mb"):
        assert mod.direction(name) == "lower", name
    # …while the lanes' per-probe recall stays a quality reading
    assert mod.direction(
        "detail.big_table.lanes.pq.probes.np8.recall10") == "higher"
    # substring immunity: "embed" carries no mb *word*
    assert mod.direction("poincare_embed_epoch_time") == "lower"  # time
    assert mod.direction("detail.poincare.embed_samples_per_s") == "higher"
    # and the size tokens never capture unrelated neighbors — nor
    # demote explicit quality/throughput readings that carry a size
    # word: the roofline FRACTION stays higher-better
    assert mod.direction("detail.big_table.qps_at_recall99.int8") == "higher"
    assert mod.direction("frac_hbm_roofline") == "higher"
    assert mod.direction("detail.big_table.lanes.int8.n") is None


def test_direction_freshness_staleness_are_lower_better():
    """The r18 live-index leg's freshness/staleness family is a cost:
    time-to-visible after an upsert, stale answers served, tombstones
    outstanding — growing any of them is never an improvement.  The
    tokens outrank the generic higher-better list the same way shed /
    deadline do (a stale *rate* is still staleness)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("upsert_visible_ms",
                 "detail.live_index.freshness.upsert_visible_ms.p99",
                 "detail.live_index.stale_results", "stale_rate",
                 "detail.live_index.staleness_ms"):
        assert mod.direction(name) == "lower", name


def test_direction_fairness_starvation_are_lower_better():
    """The r20 multi-tenant leg's fairness family is a cost: the
    ``tenant_fairness`` ratio is starved-p99 over solo-p99 (contention
    damage — it must outrank the generic higher-better ratio token the
    same way waste_ratio does) and ``starved_p99_ms`` is the latency
    behind it.  The aggregate throughput at the tenant mix stays
    higher-better via the qps token."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("tenant_fairness", "detail.multitenant.fairness",
                 "fairness_ratio", "starved_p99_ms",
                 "detail.multitenant.starved_p99_ms"):
        assert mod.direction(name) == "lower", name
    assert mod.direction("multitenant_agg_qps") == "higher"
    assert mod.direction(
        "detail.multitenant.aggregate_qps") == "higher"


def test_direction_during_rollover_inherits_base_metric():
    """``*_during_rollover`` readings (r18) inherit the base metric's
    direction: the window qualifier carries none of its own.  A p99
    latency across the flip stays lower-better, a throughput measured
    across the flip would stay higher-better — and the bare qualifier
    resolves to no direction at all (shown, never gated)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("p99_during_rollover_ms",
                 "detail.live_index.p99_during_rollover_ms",
                 "recompiles_during_rollover"):
        assert mod.direction(name) == "lower", name
    assert mod.direction("qps_during_rollover") == "higher"
    assert mod.direction(
        "detail.live_index.recall_during_rollover") == "higher"
    assert mod.direction("during_rollover") is None


def test_direction_scaling_efficiency_is_higher_better():
    """The r19 pod-scaling leg: ``scaling_efficiency`` (2-proc fleet
    throughput over 2× 1-proc) gates higher-is-better — drifting away
    from linear scaling is the regression.  Its ``multihost_ok``
    verdict is a JSON bool, and bools are excluded at flatten time
    (flags are config, not measurements), so the verdict can never
    become a gated series."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("multihost_scaling_efficiency",
                 "detail.multihost.scaling_efficiency",
                 "scaling_efficiency"):
        assert mod.direction(name) == "higher", name
    # per-proc-count throughput rows keep their own directions
    assert mod.direction(
        "detail.multihost.procs.2.steps_per_s") == "higher"
    assert mod.direction(
        "detail.multihost.procs.2.step_time_s") == "lower"
    flat = mod._flatten_numeric(
        {"multihost_ok": True, "scaling_efficiency": 0.5})
    assert "scaling_efficiency" in flat and "multihost_ok" not in flat


def test_budget_exhausted_primary_never_gates(tmp_path):
    """A record whose metric is real but whose detail carries
    budget_exhausted (the watchdog's partial artifact — the checked-in
    1-second-budget bench_full.json class) is a rounds row, never a
    series point: it must not gate as the 'full' round nor set a
    phantom best."""
    _write_rounds(tmp_path, [100.0, 110.0])
    rec = _wrapper(0, 50.0)["parsed"]  # a 55% "regression"…
    rec["detail"]["budget_exhausted"] = True  # …from a cut-short run
    (tmp_path / "bench_full.json").write_text(json.dumps(rec))
    res = _run("--dir", str(tmp_path), "--gate", "--json")
    assert res.returncode == 0, res.stdout + res.stderr
    report = json.loads(res.stdout)
    rounds = {r["round"]: r for r in report["rounds"]}
    assert rounds["full"]["parsed"] and rounds["full"]["budget_exhausted"]
    s = report["series"]["hgcn_samples_per_sec_per_chip"]
    assert s["latest"]["round"] == "r02"  # the partial never entered
    # and a cut-short BEST is equally excluded: a lucky partial must
    # not raise the bar the honest rounds gate against
    rec["value"] = 500.0
    (tmp_path / "bench_full.json").write_text(json.dumps(rec))
    res = _run("--dir", str(tmp_path), "--gate", "--json")
    assert res.returncode == 0
    s = json.loads(res.stdout)["series"]["hgcn_samples_per_sec_per_chip"]
    assert s["best"]["value"] == 110.0


def test_direction_compile_and_ttfq_lower_better():
    """The r14 cold-start / compile-cache fields gate lower-is-better:
    cold_ttfq_ms at headline and nested paths, the compile counters,
    and every recompiles* token."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("cold_ttfq_ms", "detail.cold_start.cold_ttfq_ms",
                 "detail.cold_start.warm_cache.ttfq_ms",
                 "detail.cold_start.cache_off.ttfq_ms",
                 "cold_recompiles_steady",
                 "detail.cold_start.warm_prewarm.recompiles_first",
                 "compile_s", "detail.serve.recompiles_warmup",
                 "recompiles_steady", "serve_recompiles_steady"):
        assert mod.direction(name) == "lower", name
    # neighbors keep their directions
    assert mod.direction("detail.serve.ivf.qps_at_recall99") == "higher"
    assert mod.direction("detail.cold_start.warm_prewarm.n") is None


def test_direction_http_front_door_fields_are_lower_better():
    """The r13 HTTP front-door compact fields gate in the right
    direction: http_p99_ms (latency) and shed_rate / deadline_rate
    (failure fractions — the "shed"/"deadline" tokens outrank the
    generically-higher-better "rate") are all lower-is-better, at the
    headline and at every nested detail path."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name in ("http_p99_ms", "detail.serve_http.http_p99_ms",
                 "serve_http_p99_ms",
                 "shed_rate", "http_shed_rate",
                 "detail.serve_http.shed_rate",
                 "detail.serve_http.deadline_rate",
                 "detail.serve_http.latency_ms.b8.p99",
                 "detail.serve_http.aggregate_ms.p99",
                 "detail.resilience.overload.shed_rate"):
        assert mod.direction(name) == "lower", name
    # the rate/ratio families around them keep their directions
    assert mod.direction("detail.serve.cache.cache_hit_rate") == "higher"
    assert mod.direction("serve_fused_speedup") == "higher"
    assert mod.direction("detail.serve.ivf.qps_at_recall99") == "higher"
    # sample-count leaves stay direction-free
    assert mod.direction("detail.serve_http.latency_ms.b8.n") is None


def test_direction_observability_overhead_is_lower_better():
    """The r16 observability pair: overhead_ratio is a COST fraction —
    'overhead' outranks the generic higher-better ratio token — and the
    paired p99 leaves keep their _ms lower-better direction."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_trend", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.direction(
        "detail.serve_http.observability.overhead_ratio") == "lower"
    assert mod.direction(
        "detail.serve_http.observability.p99_on_ms") == "lower"
    assert mod.direction(
        "detail.serve_http.observability.p99_off_ms") == "lower"
    # the generic speedup ratio direction is untouched
    assert mod.direction(
        "detail.serve.fused_vs_unfused.buckets.b64.ratio") == "higher"
