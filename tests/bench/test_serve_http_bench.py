"""bench.py's r13 HTTP front-door leg: the open-loop arrival generator
and an end-to-end miniature run of ``bench_serve_http`` (in-process
server + asyncio client, scaled down for tier-1)."""

import numpy as np
import pytest


def _bench():
    import importlib
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, root)
    return importlib.import_module("bench")


# --- open-loop arrival generator ---------------------------------------------


def test_even_arrivals_are_exact():
    mod = _bench()
    off = mod.open_loop_arrivals(5, 100.0, "even")
    np.testing.assert_allclose(off, [0.0, 0.01, 0.02, 0.03, 0.04])


def test_poisson_arrivals_mean_rate_and_monotonicity():
    """Exponential gaps: monotone nondecreasing offsets whose mean gap
    converges on 1/qps (seeded — deterministic draw), and a different
    seed gives a different draw (the per-pass decorrelation)."""
    mod = _bench()
    off = mod.open_loop_arrivals(4000, 200.0, "poisson", seed=3)
    assert np.all(np.diff(off) >= 0)
    mean_gap = float(np.mean(np.diff(off)))
    assert 0.8 / 200.0 < mean_gap < 1.2 / 200.0
    off2 = mod.open_loop_arrivals(4000, 200.0, "poisson", seed=4)
    assert not np.array_equal(off, off2)


def test_arrivals_validation():
    mod = _bench()
    with pytest.raises(ValueError, match="qps"):
        mod.open_loop_arrivals(0, 10.0)
    with pytest.raises(ValueError, match="qps"):
        mod.open_loop_arrivals(5, 0.0)
    with pytest.raises(ValueError, match="mode"):
        mod.open_loop_arrivals(5, 10.0, "burst")


# --- the leg end-to-end (miniature) ------------------------------------------


@pytest.mark.flaky  # wall-clock leg: a starved CI host can wobble it
def test_bench_serve_http_miniature_run():
    """The whole leg at reduced scale: per-bucket + aggregate
    percentiles land, the compact headline value is the aggregate p99,
    recompiles stay FLAT across the open-loop passes (warmup covers
    the ladder), and the overload pass answers EVERY request with the
    excess shed as HTTP 429 — never unbounded queueing."""
    mod = _bench()
    r = mod.bench_serve_http(repeats=1, qps=60.0, duration_s=0.5,
                             table_rows=8192, overload_qps=1500.0,
                             overload_s=0.4)
    assert r["metric"] == "serve_http_p99_ms" and r["unit"] == "ms"
    d = r["detail"]
    assert r["value"] == d["http_p99_ms"] > 0
    # per-bucket rows: three distinct size classes, all-200 statuses
    assert set(d["latency_ms"]) == {"b8", "b16", "b64"}
    for row in d["latency_ms"].values():
        assert row["n"] > 0 and row["p50"] <= row["p99"]
        assert set(row["statuses"]) == {"200"}
    agg = d["aggregate_ms"]
    assert agg["n"] == sum(x["n"] for x in d["latency_ms"].values())
    # the recompile contract: the ladder warmup covers every shape the
    # collator can form — the timed passes never meet the compiler
    assert d["recompiles_warmup"] >= 1
    assert d["recompiles_steady"] == 0
    # overload: every request answered, the excess shed with 429
    ov = d["overload"]
    assert ov["answered"] == ov["offered"]
    assert ov["shed"] > 0 and d["shed_rate"] > 0
    assert set(ov["statuses"]) <= {"200", "429", "504"}


def test_serve_http_compact_fields():
    """The compact headline carries http_p99_ms / http_shed_rate both
    when serve_http IS the headline (flat detail) and when it rides
    auto mode's nested leg."""
    import json

    mod = _bench()
    flat = {"metric": "serve_http_p99_ms", "value": 12.3, "unit": "ms",
            "vs_baseline": None,
            "detail": {"http_p99_ms": 12.3, "shed_rate": 0.41}}
    line = json.loads(mod.compact_headline(flat))
    assert line["detail"]["http_p99_ms"] == 12.3
    assert line["detail"]["http_shed_rate"] == 0.41
    auto = {"metric": "hgcn_samples_per_sec_per_chip", "value": 1.0,
            "unit": "samples/s/chip", "vs_baseline": None,
            "detail": {"serve_http": {"http_p99_ms": 9.9,
                                      "shed_rate": 0.1}}}
    line = json.loads(mod.compact_headline(auto))
    assert line["detail"]["http_p99_ms"] == 9.9
    assert line["detail"]["http_shed_rate"] == 0.1
