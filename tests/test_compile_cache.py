"""Persistent compilation cache wiring (hyperspace_tpu/compile_cache.py).

The contract of ISSUE 13 pillar 1: run #2 of the same program shapes
with the same ``compile_cache_dir`` deserializes executables instead of
re-invoking XLA — proven HERE as a real subprocess pair through the
serve CLI (the telemetry summary carries ``ctr/jax/compile_cache_hit``
and the compile counters), with the cache-disabled path bit-identical
and a bad directory a clean usage error."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from hyperspace_tpu import compile_cache

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_activation_state():
    """The cache state is process-global and other suites legitimately
    activate it in-process (the bench CLI contract tests call
    bench.main()) — these tests assert on activation state, so they
    start and end deactivated (deactivate restores whatever config the
    prior activation replaced, so the harness's own cache survives)."""
    compile_cache.deactivate()
    yield
    compile_cache.deactivate()


# --- resolution rules (pure, no jax) -----------------------------------------


def test_resolution_precedence(monkeypatch):
    monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
    # default ON, under the repo's .cache
    d = compile_cache.resolve_dir(None)
    assert d is not None and d.endswith(os.path.join(".cache", "jax_compile"))
    # env overrides the default; flag overrides the env
    monkeypatch.setenv(compile_cache.ENV_VAR, "/env/dir")
    assert compile_cache.resolve_dir(None) == "/env/dir"
    assert compile_cache.resolve_dir("/flag/dir") == "/flag/dir"
    # 0 disables at either level
    assert compile_cache.resolve_dir("0") is None
    monkeypatch.setenv(compile_cache.ENV_VAR, "0")
    assert compile_cache.resolve_dir(None) is None
    # an explicit flag still wins over a disabling env
    assert compile_cache.resolve_dir("/flag/dir") == "/flag/dir"


def test_off_spellings():
    for v in ("0", "false", "no", "off", "OFF", " 0 "):
        assert compile_cache.resolve_dir(v) is None


def test_bad_dir_is_a_clean_error(tmp_path):
    f = tmp_path / "a_file"
    f.write_text("not a directory")
    with pytest.raises(ValueError, match="compile_cache_dir"):
        compile_cache.activate(str(f))
    assert not compile_cache.is_enabled()


def test_activate_points_jax_and_deactivate_unpoints(tmp_path):
    import jax

    prev = jax.config.jax_compilation_cache_dir  # the suite's own cache
    try:
        d = compile_cache.activate(str(tmp_path / "cc"))
        assert d == str(tmp_path / "cc") and os.path.isdir(d)
        assert compile_cache.is_enabled()
        assert jax.config.jax_compilation_cache_dir == d
        # a jitted call lands entries on disk (the cache-everything
        # policy: even a trivial sub-second executable persists)
        import jax.numpy as jnp

        jax.jit(lambda x: x * 3 + 1)(jnp.ones((4, 4))).block_until_ready()
        files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
        assert files, "no cache entries written"
    finally:
        compile_cache.deactivate()
    assert not compile_cache.is_enabled()
    # deactivate RESTORES the pre-activation config (the test harness
    # points the suite at its own cache — blanking it would slow every
    # test after this one), it does not blank it
    assert jax.config.jax_compilation_cache_dir == prev


# --- the subprocess pair (the ISSUE's acceptance shape) ----------------------


def _query(art: str, cache_dir: str, extra=()):
    """One serve-CLI query subprocess → (stdout record, telemetry ctrs)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(compile_cache.ENV_VAR, None)
    res = subprocess.run(
        [sys.executable, "-m", "hyperspace_tpu.cli.serve", "query",
         f"artifact={art}", "ids=0,1,2", "k=3", "telemetry=1",
         f"compile_cache_dir={cache_dir}", *extra],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=240)
    assert res.returncode == 0, res.stderr[-2000:]
    out = json.loads(res.stdout.strip().splitlines()[-1])
    summary = None
    for line in res.stderr.strip().splitlines():
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "telemetry_summary" in doc:
            summary = doc["telemetry_summary"]
    assert summary is not None, res.stderr[-2000:]
    return out, summary


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    from hyperspace_tpu.serve import export_artifact

    rng = np.random.default_rng(0)
    table = np.tanh(rng.standard_normal((96, 6)).astype(np.float32) * 0.3) * 0.7
    out = str(tmp_path_factory.mktemp("cc") / "artifact")
    export_artifact(out, table, ("poincare", 1.0), model_config={"c": 1.0})
    return out


def test_subprocess_pair_hits_and_disabled_bitwise(tmp_path, artifact):
    cache = str(tmp_path / "cc")
    out1, t1 = _query(artifact, cache)
    # run #1: a cold cache has nothing to hit, and every compile missed
    # into it (entries written)
    assert t1.get("ctr/jax/compile_cache_hit", 0) == 0
    assert t1.get("ctr/jax/compile_cache_miss", 0) > 0
    assert t1.get("ctr/jax/recompiles", 0) > 0

    out2, t2 = _query(artifact, cache)
    # run #2, same dir: executables deserialize — hits recorded, fewer
    # misses, and LOWER compile counters (this jax times the hit's
    # deserialization under the same backend_compile event, so
    # recompiles stays <= while compile_s collapses — the honest win)
    assert t2.get("ctr/jax/compile_cache_hit", 0) > 0
    assert (t2.get("ctr/jax/compile_cache_miss", 0)
            < t1["ctr/jax/compile_cache_miss"])
    assert t2.get("ctr/jax/recompiles", 0) <= t1["ctr/jax/recompiles"]
    assert t2.get("ctr/jax/compile_s", 0) < t1["ctr/jax/compile_s"]
    # cached answers are the same executables: identical results
    assert out2 == out1

    out3, t3 = _query(artifact, "0")
    # cache-disabled path: no cache counters at all, results
    # bit-identical to the cached runs (tolist round-trips f32 exactly)
    assert "ctr/jax/compile_cache_hit" not in t3
    assert "ctr/jax/compile_cache_miss" not in t3
    assert out3 == out1


def test_subprocess_bad_dir_clean_error(tmp_path, artifact):
    f = tmp_path / "occupied"
    f.write_text("file, not dir")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, "-m", "hyperspace_tpu.cli.serve", "query",
         f"artifact={artifact}", "ids=0", "k=1",
         f"compile_cache_dir={f}"],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=240)
    assert res.returncode != 0
    assert "compile_cache_dir" in res.stderr
    assert "Traceback" not in res.stderr
