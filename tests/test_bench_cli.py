"""bench.py headline-metric contract (VERDICT r2 weak #3 / next #7).

Under ``--metric auto`` a failing HGCN benchmark must surface as
``metric: "error"`` with the traceback — never silently fall through to a
green Poincaré line about a different metric.
"""

import json
import sys

import pytest


@pytest.fixture()
def bench_mod(monkeypatch):
    sys.path.insert(0, "/root/repo")
    import bench

    yield bench
    sys.path.remove("/root/repo")


def _stub_poincare(repeats=1):
    return {"metric": "poincare_embed_epoch_time", "value": 0.5, "unit": "s",
            "vs_baseline": None, "detail": {"num_nodes": 10}}


def _stub_sampled(repeats=1):
    return {"step_ms": 2.5, "supervised_samples_per_s": 2e5}


def test_auto_hgcn_failure_reports_error(bench_mod, monkeypatch, capsys):
    def boom(repeats=1, **kw):
        raise RuntimeError("synthetic hgcn failure")

    monkeypatch.setattr(bench_mod, "bench_hgcn", boom)
    monkeypatch.setattr(bench_mod, "bench_poincare", _stub_poincare)
    monkeypatch.setattr(bench_mod, "bench_sampled", _stub_sampled)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "auto"])
    with pytest.raises(SystemExit) as ei:
        bench_mod.main()
    assert ei.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "error"
    assert "synthetic hgcn failure" in out["detail"]["error"]
    assert "RuntimeError" in out["detail"]["traceback"]
    assert out["detail"]["failed_benchmark"] == "hgcn"
    # poincare still rides along in detail — available, just not headline
    assert out["detail"]["poincare_embed_epoch_time_s"] == 0.5


def test_auto_success_keeps_hgcn_headline(bench_mod, monkeypatch, capsys):
    def ok(repeats=1, **kw):
        return {"metric": "hgcn_samples_per_sec_per_chip", "value": 1e6,
                "unit": "samples/s/chip", "vs_baseline": None, "detail": {}}

    monkeypatch.setattr(bench_mod, "bench_hgcn", ok)
    monkeypatch.setattr(bench_mod, "bench_poincare", _stub_poincare)
    monkeypatch.setattr(bench_mod, "bench_sampled", _stub_sampled)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "auto"])
    bench_mod.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["detail"]["poincare_embed_epoch_time_s"] == 0.5
    assert out["detail"]["hgcn_sampled"]["supervised_samples_per_s"] == 2e5


def test_explicit_poincare_failure_is_error(bench_mod, monkeypatch, capsys):
    def boom(repeats=1):
        raise ValueError("poincare broke")

    monkeypatch.setattr(bench_mod, "bench_poincare", boom)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "poincare"])
    with pytest.raises(SystemExit) as ei:
        bench_mod.main()
    assert ei.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "error"
    assert out["detail"]["failed_benchmark"] == "poincare"
