"""bench.py headline-metric + tail-budget contracts.

Headline contract (VERDICT r2 weak #3): under ``--metric auto`` a failing
HGCN benchmark must surface as ``metric: "error"`` with the traceback —
never silently fall through to a green Poincaré line about a different
metric.

Tail contract (VERDICT r4 missing #1): the driver records only the final
2000 characters of stdout, so the LAST line printed must be a complete,
parseable JSON record carrying metric/value/unit no matter how large the
full detail grows.  BENCH_r04.json was lost to this (``parsed: null``).
"""

import json
import sys

import pytest


def _last_json(captured: str) -> dict:
    """Parse the final stdout line — the driver-facing compact record."""
    return json.loads(captured.strip().splitlines()[-1])


def _tail_json(captured: str, budget: int = 2000) -> dict:
    """Simulate the driver: keep only the last ``budget`` chars, then
    parse the last complete line found there."""
    tail = captured[-budget:]
    return json.loads(tail.strip().splitlines()[-1])


@pytest.fixture()
def bench_mod(monkeypatch):
    sys.path.insert(0, "/root/repo")
    import bench

    # stub the heavy auto-mode legs at their SOURCE modules (main()
    # imports them lazily from there, so patching the bench module alone
    # would not intercept): the contract tests here are about artifact
    # shape, and must never run real multi-minute benches in tier-1
    import hyperspace_tpu.benchmarks.hgcn_bench as hb
    import hyperspace_tpu.benchmarks.workloads_bench as wb

    monkeypatch.setattr(hb, "run_realistic_bench",
                        lambda repeats=1, **kw: {"mean_step_s": 0.1})
    monkeypatch.setattr(wb, "run_workloads_bench",
                        lambda **kw: {"backend": "stub"})
    yield bench
    sys.path.remove("/root/repo")


def _stub_poincare(repeats=1):
    return {"metric": "poincare_embed_epoch_time", "value": 0.5, "unit": "s",
            "vs_baseline": None, "detail": {"num_nodes": 10}}


def _stub_sampled(repeats=1):
    return {"step_ms": 2.5, "supervised_samples_per_s": 2e5}


def _stub_serve(repeats=1):
    return {"metric": "serve_qps", "value": 1234.5, "unit": "queries/s",
            "vs_baseline": None,
            "detail": {"recompiles_steady": 0,
                       "latency_ms": {"b8": {"n": 2, "p50": 1.2,
                                             "p95": 2.0, "p99": 2.2}},
                       "cache": {"cache_hit_rate": 0.9}}}


def _stub_precision(repeats=1):
    return {"metric": "precision_train_speedup", "value": 1.4, "unit": "x",
            "vs_baseline": None,
            "detail": {"train_step_ms": {"f32": 2.0, "bf16": 1.4},
                       "serve_scan_ms": {"f32": 3.0, "bf16": 2.0}}}


def _stub_resilience(repeats=1):
    return {"metric": "resilience_ok", "value": 1, "unit": "bool",
            "vs_baseline": None,
            "detail": {"chaos_train": {"rollbacks": 1, "recovered": True},
                       "overload": {"shed_rate": 0.1,
                                    "ladder_recovered": True}}}


def _stub_cold_start(repeats=1):
    # the real leg spawns serve-CLI subprocesses — never in tier-1
    return {"metric": "cold_ttfq_ms", "value": 850.0, "unit": "ms",
            "vs_baseline": None,
            "detail": {"cold_ttfq_ms": 850.0, "recompiles_steady": 0,
                       "warm_cache": {"ttfq_ms": 900.0}}}


def test_auto_hgcn_failure_reports_error(bench_mod, monkeypatch, capsys):
    def boom(repeats=1, **kw):
        raise RuntimeError("synthetic hgcn failure")

    monkeypatch.setattr(bench_mod, "bench_hgcn", boom)
    monkeypatch.setattr(bench_mod, "bench_poincare", _stub_poincare)
    monkeypatch.setattr(bench_mod, "bench_sampled", _stub_sampled)
    monkeypatch.setattr(bench_mod, "bench_serve", _stub_serve)
    monkeypatch.setattr(bench_mod, "bench_precision", _stub_precision)
    monkeypatch.setattr(bench_mod, "bench_resilience", _stub_resilience)
    monkeypatch.setattr(bench_mod, "bench_cold_start", _stub_cold_start)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "auto"])
    with pytest.raises(SystemExit) as ei:
        bench_mod.main()
    assert ei.value.code == 1
    captured = capsys.readouterr().out
    full = json.loads(captured.strip().splitlines()[0])
    assert full["metric"] == "error"
    assert "synthetic hgcn failure" in full["detail"]["error"]
    assert "RuntimeError" in full["detail"]["traceback"]
    assert full["detail"]["failed_benchmark"] == "hgcn"
    # poincare still rides along in detail — available, just not headline
    assert full["detail"]["poincare_embed_epoch_time_s"] == 0.5
    # the compact last line carries the error too
    out = _last_json(captured)
    assert out["metric"] == "error"
    assert "synthetic hgcn failure" in out["detail"]["error"]


def test_auto_success_keeps_hgcn_headline(bench_mod, monkeypatch, capsys):
    def ok(repeats=1, **kw):
        return {"metric": "hgcn_samples_per_sec_per_chip", "value": 1e6,
                "unit": "samples/s/chip", "vs_baseline": None, "detail": {}}

    monkeypatch.setattr(bench_mod, "bench_hgcn", ok)
    monkeypatch.setattr(bench_mod, "bench_poincare", _stub_poincare)
    monkeypatch.setattr(bench_mod, "bench_sampled", _stub_sampled)
    monkeypatch.setattr(bench_mod, "bench_serve", _stub_serve)
    monkeypatch.setattr(bench_mod, "bench_precision", _stub_precision)
    monkeypatch.setattr(bench_mod, "bench_resilience", _stub_resilience)
    monkeypatch.setattr(bench_mod, "bench_cold_start", _stub_cold_start)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "auto"])
    bench_mod.main()
    captured = capsys.readouterr().out
    full = json.loads(captured.strip().splitlines()[0])
    assert full["metric"] == "hgcn_samples_per_sec_per_chip"
    assert full["detail"]["poincare_embed_epoch_time_s"] == 0.5
    assert full["detail"]["hgcn_sampled"]["supervised_samples_per_s"] == 2e5
    # the serve leg rides along: headline value + recompile contract +
    # the cache-effectiveness gauges in one detail dict
    assert full["detail"]["serve"]["qps"] == 1234.5
    assert full["detail"]["serve"]["recompiles_steady"] == 0
    assert full["detail"]["serve"]["cache"]["cache_hit_rate"] == 0.9
    # the per-bucket SLO percentiles ride in detail (PR 7)
    assert full["detail"]["serve"]["latency_ms"]["b8"]["p99"] == 2.2
    # the precision leg: the f32/bf16 timing PAIRS land in the artifact
    assert full["detail"]["precision"]["train_step_ms"] == {
        "f32": 2.0, "bf16": 1.4}
    assert full["detail"]["precision"]["serve_scan_ms"] == {
        "f32": 3.0, "bf16": 2.0}
    # compact last line: same headline, key legs summarized
    out = _last_json(captured)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["value"] == 1e6
    assert out["detail"]["poincare_epoch_s"] == 0.5
    assert out["detail"]["sampled_samples_per_s"] == 2e5
    assert out["detail"]["serve_qps"] == 1234.5
    assert out["detail"]["serve_latency_ms"]["b8"] == {
        "n": 2, "p50": 1.2, "p95": 2.0, "p99": 2.2}
    assert out["detail"]["precision_train_ms"] == {"f32": 2.0, "bf16": 1.4}
    # the resilience leg (PR 9): the recovery verdict + shed-rate
    # column ride the artifact and the compact line
    assert full["detail"]["resilience"]["ok"] == 1
    assert full["detail"]["resilience"]["overload"]["shed_rate"] == 0.1
    assert out["detail"]["resilience_ok"] == 1
    assert out["detail"]["shed_rate"] == 0.1
    assert out["detail"]["chaos_rollbacks"] == 1
    # the cold-start leg (r14): restart TTFQ + recompile contract ride
    # the artifact and the compact line
    assert full["detail"]["cold_start"]["cold_ttfq_ms"] == 850.0
    assert out["detail"]["cold_ttfq_ms"] == 850.0
    assert out["detail"]["cold_recompiles_steady"] == 0


def test_explicit_poincare_failure_is_error(bench_mod, monkeypatch, capsys):
    def boom(repeats=1):
        raise ValueError("poincare broke")

    monkeypatch.setattr(bench_mod, "bench_poincare", boom)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "poincare"])
    with pytest.raises(SystemExit) as ei:
        bench_mod.main()
    assert ei.value.code == 1
    out = _last_json(capsys.readouterr().out)
    assert out["metric"] == "error"
    assert out["detail"]["failed_benchmark"] == "poincare"


# ---------------------------------------------------------------------------
# tail-budget contract (VERDICT r4 missing #1)


def _fat_result():
    """A result whose full-detail line far exceeds the 2000-char budget —
    the shape that truncated BENCH_r04.json."""
    return {
        "metric": "hgcn_samples_per_sec_per_chip", "value": 1.309e6,
        "unit": "samples/s/chip", "vs_baseline": None,
        "detail": {
            "step_time_s": 0.1293, "num_nodes": 169343, "devices": 1,
            "backend": "tpu", "use_att": False, "lr": 0.01, "loss": 0.31,
            "frac_clustered": 0.391, "reorder": "community",
            "source": "synthetic", "dtype": "float32", "step": "pairs",
            "poincare_embed_epoch_time_s": 0.174,
            "poincare": {("k%d" % i): float(i) for i in range(120)},
            "hgcn_sampled": {"supervised_samples_per_s": 2.7e5,
                             "sampling_inclusive_samples_per_s": 5.2e4,
                             **{("s%d" % i): i for i in range(80)}},
            "realistic": {"mean_step_s": 0.127, "att_step_s": 0.39,
                          "frac_clustered": 0.300,
                          **{("r%d" % i): i for i in range(80)}},
            "use_att_arm": {"step_time_s": 0.391,
                            "samples_per_s_per_chip": 4.33e5},
            "workloads": {("w%d" % i): float(i) for i in range(150)},
        },
    }


def test_serve_headline_compact_carries_flat_latency(bench_mod,
                                                     monkeypatch, capsys):
    """With --metric serve the bench_serve detail is FLAT (not nested
    under detail.serve) — the compact line must still carry the
    per-bucket percentiles via the latency_ms field."""
    monkeypatch.setattr(bench_mod, "bench_serve", _stub_serve)
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--metric", "serve", "--budget-s", "0"])
    bench_mod.main()
    out = _last_json(capsys.readouterr().out)
    assert out["metric"] == "serve_qps" and out["value"] == 1234.5
    assert out["detail"]["latency_ms"]["b8"]["p95"] == 2.0


def test_compact_headline_fits_budget(bench_mod):
    res = _fat_result()
    assert len(json.dumps(res)) > 4000  # the failure precondition is real
    line = bench_mod.compact_headline(res)
    assert len(line) <= bench_mod.COMPACT_LIMIT
    out = json.loads(line)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["value"] == 1.309e6
    assert out["unit"] == "samples/s/chip"
    # the highest-priority details survive
    assert out["detail"]["step_time_s"] == 0.1293
    assert out["detail"]["att_step_s"] == 0.391
    assert out["detail"]["sampled_incl_samples_per_s"] == 5.2e4
    assert out["detail"]["realistic_mean_step_s"] == 0.127


def test_compact_headline_drops_detail_before_overflow(bench_mod):
    # absurdly small limit: metric/value must still emit, detail gives way
    res = _fat_result()
    line = bench_mod.compact_headline(res, limit=180)
    assert len(line) <= 180
    out = json.loads(line)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["value"] == 1.309e6


# ---------------------------------------------------------------------------
# wall-clock budget: bench must emit a parseable artifact and exit 0
# instead of dying to the driver's hard timeout (BENCH_r05: rc=124,
# ``parsed: null``)


def test_budget_zero_skips_all_legs_but_emits(bench_mod, monkeypatch, capsys):
    def ok(repeats=1, **kw):
        return {"metric": "hgcn_samples_per_sec_per_chip", "value": 1e6,
                "unit": "samples/s/chip", "vs_baseline": None, "detail": {}}

    monkeypatch.setattr(bench_mod, "bench_hgcn", ok)
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--metric", "auto", "--budget-s", "0"])
    bench_mod.main()
    captured = capsys.readouterr().out
    full = json.loads(captured.strip().splitlines()[0])
    # headline survives; every optional leg is reported skipped, not lost
    assert full["metric"] == "hgcn_samples_per_sec_per_chip"
    assert set(full["detail"]["skipped_legs"]) == {
        "poincare", "hgcn_sampled", "serve_qps", "serve_http",
        "live_index", "cold_start", "big_table", "precision",
        "resilience", "multihost", "multitenant", "realistic",
        "workloads", "use_att_arm"}
    assert full["detail"]["budget_s"] == 0
    assert _last_json(captured)["metric"] == "hgcn_samples_per_sec_per_chip"


def test_budget_env_var_is_honored(bench_mod, monkeypatch, capsys):
    def ok(repeats=1, **kw):
        return {"metric": "hgcn_samples_per_sec_per_chip", "value": 1e6,
                "unit": "samples/s/chip", "vs_baseline": None, "detail": {}}

    monkeypatch.setattr(bench_mod, "bench_hgcn", ok)
    monkeypatch.setenv("BENCH_BUDGET_S", "0")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--metric", "auto"])
    bench_mod.main()
    full = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert full["detail"]["budget_s"] == 0
    assert "skipped_legs" in full["detail"]


def test_budget_watchdog_emits_partial_and_exits_zero(bench_mod, capsys):
    # the last resort: deadline passes mid-run → the timer emits whatever
    # completed and exits 0 (injected _exit; the real one is os._exit)
    import time

    guard = bench_mod._BudgetGuard(0.0)
    holder = {"result": {"metric": "hgcn_samples_per_sec_per_chip",
                         "value": 2.0, "unit": "samples/s/chip",
                         "vs_baseline": None, "detail": {"devices": 1}}}
    codes = []
    guard.arm(holder, _exit=codes.append)
    for _ in range(100):
        if codes:
            break
        time.sleep(0.02)
    assert codes == [0]
    out = _last_json(capsys.readouterr().out)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["detail"]["budget_exhausted"] is True
    # emit-once: a late main-path emit is suppressed, not duplicated
    assert guard.claim_emit() is False


def test_leg_deadline_interrupts_overrun(bench_mod):
    """The per-leg deadline interrupts a leg that blows straight past
    its floor estimate (BENCH_r05: the skip-before-start check alone let
    a slow leg ride into the driver's hard timeout) — and a leg that
    finishes in time leaves the alarm disarmed."""
    import time

    guard = bench_mod._BudgetGuard(1.0)
    with pytest.raises(bench_mod._LegTimeout):
        with bench_mod._deadline(guard.remaining()):
            time.sleep(60)
    assert guard.elapsed() < 30  # cut at ~1 s, nowhere near the sleep(60)

    with bench_mod._deadline(5.0):
        pass
    time.sleep(0.01)  # a stale alarm would fire here and kill the test


def test_primary_timeout_emits_budget_record(bench_mod, monkeypatch, capsys):
    """Even the headline benchmark is bounded: past the budget it yields
    a parseable budget_exhausted record and exit 0 — never rc=124 with
    nothing on stdout."""
    import time

    monkeypatch.setattr(bench_mod, "bench_hgcn",
                        lambda repeats=1, **kw: time.sleep(60))
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--metric", "auto", "--budget-s", "1"])
    t0 = time.perf_counter()
    bench_mod.main()  # no SystemExit: a budget timeout is not a failure
    assert time.perf_counter() - t0 < 30
    captured = capsys.readouterr().out
    out = _last_json(captured)
    assert out["metric"] == "budget_exhausted"
    assert out["detail"]["timed_out_legs"] == ["hgcn"]
    full = json.loads(captured.strip().splitlines()[0])
    assert full["detail"]["budget_exhausted"] is True


def test_emit_survives_numpy_detail(bench_mod, capsys, monkeypatch, tmp_path):
    """A leg dropping numpy scalars/arrays (or any non-JSON object) into
    detail must degrade those values, never swallow the emit — the
    ``parsed: null`` + rc=0 shape of BENCH_r04."""
    import numpy as np

    monkeypatch.setattr(bench_mod, "__file__", str(tmp_path / "bench.py"))
    result = {"metric": "hgcn_samples_per_sec_per_chip",
              "value": np.float32(1e6), "unit": "samples/s/chip",
              "vs_baseline": None,
              "detail": {"step_time_s": np.float64(0.25),
                         "loss_curve": np.arange(3),
                         "weird": object()}}
    bench_mod.emit(result)
    captured = capsys.readouterr().out
    out = _last_json(captured)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["value"] == 1e6
    assert out["detail"]["step_time_s"] == 0.25
    full = json.loads(captured.strip().splitlines()[0])
    assert full["detail"]["loss_curve"] == [0, 1, 2]


# flaky: real SIGALRM + watchdog-thread timing across a process
# boundary — where the 12 s deadline lands (Python bytecode vs a native
# XLA trace with the signal pending) varies run to run, and one run in
# ~10 has been seen missing the window.  The strict rerun absorbs that;
# a broken emit contract fails both attempts.
@pytest.mark.flaky
def test_tiny_budget_subprocess_last_line_parses(tmp_path):
    """The satellite regression: a REAL ``bench.py`` run with a tiny
    ``--budget-s`` must end with a parseable headline JSON line carrying
    a ``metric`` key and exit 0, without any in-process stubbing — the
    whole-pipeline guarantee the driver relies on.

    Budget 12, not 2: ≥10 arms the watchdog thread as well as the
    SIGALRM deadline, and this test needs BOTH layers live — the alarm
    handler pends while the main thread sits in a long native XLA
    trace/compile (no bytecode boundary), which is exactly when the
    watchdog is the layer that saves the artifact."""
    import os
    import subprocess

    bench_py = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench.py")
    # emit() writes bench_full.json next to bench.py by default — point
    # it into the tmp dir so this run never clobbers the checkout's
    # last genuine artifact
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_FULL_JSON=str(tmp_path / "bench_full.json"))
    proc = subprocess.run(
        [sys.executable, bench_py, "--budget-s", "12"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert lines, proc.stderr[-2000:]
    out = json.loads(lines[-1])
    assert "metric" in out
    assert out["metric"] in ("budget_exhausted",
                             "hgcn_samples_per_sec_per_chip")


def test_emit_tail_2000_is_parseable(bench_mod, capsys, monkeypatch, tmp_path):
    # the end-to-end driver simulation: full line + compact line, then
    # keep only the last 2000 chars — the headline must parse out of it
    monkeypatch.setattr(bench_mod, "__file__", str(tmp_path / "bench.py"))
    bench_mod.emit(_fat_result())
    captured = capsys.readouterr().out
    out = _tail_json(captured, budget=2000)
    assert out["metric"] == "hgcn_samples_per_sec_per_chip"
    assert out["value"] == 1.309e6
    assert out["detail"]["step_time_s"] == 0.1293
    # the full record was preserved to a file beside bench.py
    full = json.loads((tmp_path / "bench_full.json").read_text())
    assert full["detail"]["workloads"]["w42"] == 42.0
