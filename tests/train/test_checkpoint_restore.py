"""ISSUE 3 satellites on train/checkpoint.py: template-free
``restore_params_only`` and the ``dir_bytes`` mid-scan-race guard."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.train import checkpoint as C


def _save_tiny(tmp_path, step=5):
    from hyperspace_tpu.models import poincare_embed as pe

    cfg = pe.PoincareEmbedConfig(num_nodes=8, dim=3)
    state, _opt = pe.init_state(cfg, seed=0)
    d = str(tmp_path / "ckpt")
    with C.CheckpointManager(d) as ck:
        ck.save(step, state, force=True)
    return d, state


def test_restore_params_only_raw_tree(tmp_path):
    d, state = _save_tiny(tmp_path)
    tree, step = C.restore_params_only(d)
    assert step == 5
    # NamedTuple state comes back as a plain dict keyed by field name —
    # no TrainState / optimizer-state objects were constructed
    assert isinstance(tree, dict)
    assert set(tree) == {"table", "opt_state", "key", "step"}
    np.testing.assert_array_equal(
        np.asarray(tree["table"]), np.asarray(state.table))
    assert int(tree["step"]) == int(state.step)


def test_restore_params_only_skips_uncommitted(tmp_path):
    d, state = _save_tiny(tmp_path)
    # an interrupted save's empty all-digit dir must not be trusted
    os.makedirs(os.path.join(d, "99"))
    tree, step = C.restore_params_only(d)
    assert step == 5
    np.testing.assert_array_equal(
        np.asarray(tree["table"]), np.asarray(state.table))


def test_restore_params_only_explicit_step_and_missing(tmp_path):
    d, _state = _save_tiny(tmp_path)
    _tree, step = C.restore_params_only(d, step=5)
    assert step == 5
    with pytest.raises(FileNotFoundError):
        C.restore_params_only(str(tmp_path / "nope"))
    # the never-trust-uncommitted rule holds for PINNED steps too: an
    # interrupted save's dir must not restore into a serving artifact
    os.makedirs(os.path.join(d, "99"))
    with pytest.raises(FileNotFoundError, match="uncommitted"):
        C.restore_params_only(d, step=99)
    with pytest.raises(FileNotFoundError, match="uncommitted"):
        C.restore_params_only(d, step=7)  # never existed


def test_dir_bytes_tolerates_files_deleted_mid_scan(tmp_path, monkeypatch):
    """The async-save race: a file listed by os.walk is deleted before
    getsize stats it — dir_bytes must skip it, not raise."""
    d = tmp_path / "ck"
    d.mkdir()
    (d / "a.bin").write_bytes(b"x" * 100)
    (d / "b.bin").write_bytes(b"y" * 50)
    doomed = str(d / "a.bin")
    real = os.path.getsize

    def racy(path):
        if path == doomed:
            raise FileNotFoundError(path)  # deleted between walk and stat
        return real(path)

    monkeypatch.setattr(os.path, "getsize", racy)
    assert C.dir_bytes(str(d)) == 50


def test_dir_bytes_missing_directory_is_zero(tmp_path):
    assert C.dir_bytes(str(tmp_path / "never")) == 0
