"""Train-plane telemetry (ISSUE 17): StepPhases timers are monotone
and complete, the host trainer populates every phase histogram plus
the host-table hit-rate gauge, and the multihost aggregation reduces
per-process exports with identical series shapes for world_size=1 and
a simulated multi-process merge."""

import time

import numpy as np
import pytest

from hyperspace_tpu.data import wordnet
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.parallel import multihost
from hyperspace_tpu.telemetry import aggregate
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.exposition import render_export
from hyperspace_tpu.train import host_embed as he
from hyperspace_tpu.train.telemetry import PHASES, StepPhases


@pytest.fixture(scope="module")
def ds():
    return wordnet.synthetic_tree(depth=4, branching=3)


def _cfg(ds, **kw):
    kw.setdefault("dim", 8)
    kw.setdefault("batch_size", 32)
    kw.setdefault("neg_samples", 5)
    return pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, **kw)


# --- StepPhases --------------------------------------------------------------


def test_phase_timers_are_monotone_and_complete():
    """One simulated chunk through every phase: all four readings land,
    every duration is non-negative, and consecutive phases' bounds are
    monotone (a phase never starts before its predecessor closed)."""
    ph = StepPhases()
    reg = telem.default_registry()
    base = reg.mark()
    for name in PHASES:
        with ph.phase(name):
            time.sleep(0.001)
    assert set(ph.last) == set(PHASES)
    assert all(ms >= 1.0 for ms in ph.last.values())
    for a, b in zip(PHASES, PHASES[1:]):
        assert ph.last_bounds[a][1] <= ph.last_bounds[b][0], \
            f"{a} must close before {b} opens"
    snap = reg.snapshot(baseline=base)
    for name in PHASES:
        h = snap.get(f"hist/train/phase/{name}_ms")
        assert h and h["count"] == 1


def test_phase_records_even_when_the_body_raises():
    """A crashed chunk still stamps its phase — the post-mortem needs
    to know WHICH phase died, exactly when it matters most."""
    ph = StepPhases()
    with pytest.raises(RuntimeError):
        with ph.phase("device_step"):
            raise RuntimeError("boom")
    assert "device_step" in ph.last


def test_profile_mode_blocks_on_the_thunk_after_the_body():
    """The block thunk is called only in profile mode and AFTER the
    body — late-bound locals (the host trainer's ``out.packed``) are
    legal, and its wait lands inside the phase window."""
    calls = []
    box = {}

    ph = StepPhases(profile=True)
    with ph.phase("device_step", lambda: calls.append(box["v"])):
        box["v"] = np.ones(3)  # bound DURING the body
    assert len(calls) == 1  # thunk ran (and was blocked on)

    ph2 = StepPhases(profile=False)
    with ph2.phase("device_step", lambda: calls.append(None)):
        pass
    assert len(calls) == 1  # free-running mode never calls it


# --- host trainer integration ------------------------------------------------


def test_host_trainer_populates_phases_and_hit_rate(ds):
    cfg = _cfg(ds)
    state, opt = pe.init_state(cfg, 0)
    tr = he.HostPlannedTrainer.from_state(cfg, opt, state, chunk_steps=4,
                                          seed=7, profile=True)
    reg = telem.default_registry()
    base = reg.mark()
    tr.run(ds.pairs, 8)
    snap = reg.snapshot(baseline=base)
    for name in PHASES:
        h = snap.get(f"hist/train/phase/{name}_ms")
        assert h and h["count"] >= 2, f"phase {name} missing"
    # cache effectiveness surfaces as a gauge a scraper can read
    # directly (parallel/host_table.py keeps it current per lookup)
    rate = telem.default_registry().snapshot().get(
        "host_table/cache_hit_rate")
    assert rate is not None and 0.0 <= rate <= 1.0


# --- multihost aggregation ---------------------------------------------------


def _fresh_export(seed: int) -> tuple:
    reg = telem.Registry()
    reg.inc("serve/requests", 10 + seed)
    reg.set_gauge("serve/degrade_level", seed)
    for i in range(20):
        reg.observe("serve/e2e_ms", 1.0 + seed + i * 0.1)
    return reg.export()


def test_merge_of_one_export_is_shape_identical():
    e = _fresh_export(0)
    m = aggregate.merge_exports([e])
    assert set(m[0]) == set(e[0]) and m[0] == e[0]
    assert set(m[1]) == set(e[1]) and m[1] == e[1]
    assert set(m[2]) == set(e[2])
    assert m[2]["serve/e2e_ms"].fields() == e[2]["serve/e2e_ms"].fields()


def test_simulated_two_process_merge_reduces_correctly():
    """The ISSUE 17 acceptance shape contract: a 2-process merge holds
    the SAME series names/kinds as either process — counters summed,
    gauges max-reduced, histogram counts added — and renders through
    the identical exposition path."""
    e0, e1 = _fresh_export(0), _fresh_export(3)
    m = aggregate.merge_exports([e0, e1])
    assert set(m[0]) == set(e0[0])  # no invented/dropped families
    assert m[0]["serve/requests"] == 10 + 13
    assert m[1]["serve/degrade_level"] == 3  # max, not average
    f = m[2]["serve/e2e_ms"].fields()
    assert f["count"] == 40
    assert f["sum"] == pytest.approx(
        e0[2]["serve/e2e_ms"].fields()["sum"]
        + e1[2]["serve/e2e_ms"].fields()["sum"])
    # the merged export renders exactly like a single process's scrape
    text = render_export(*m, labels={"scope": "fleet"})
    assert "hyperspace_serve_requests" in text
    assert 'scope="fleet"' in text


def test_codec_roundtrips_exactly():
    e = _fresh_export(1)
    back = aggregate.decode_bytes(aggregate.encode_bytes(e))
    assert back[0] == e[0] and back[1] == e[1]
    assert back[2]["serve/e2e_ms"].fields() == e[2]["serve/e2e_ms"].fields()
    # re-merging decoded exports works (the allgather consumer's path)
    m = aggregate.merge_exports([back, back])
    assert m[2]["serve/e2e_ms"].fields()["count"] == 40


def test_gather_on_one_process_is_the_local_export():
    """world_size=1 short-circuits: no collective, one export, and the
    merged result is shape-identical to the local registry's — the
    wiring is the same for 1 process and N."""
    reg = telem.Registry()
    reg.inc("serve/requests", 5)
    reg.observe("serve/e2e_ms", 2.5)
    exports = multihost.gather_metric_exports(reg)
    assert len(exports) == 1
    local = reg.export()
    m = aggregate.merge_exports(exports)
    assert m[0] == local[0] and m[1] == local[1]
    assert set(m[2]) == set(local[2])
