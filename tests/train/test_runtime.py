"""Train-runtime tests: checkpoint round-trip with re-projection, JSONL
logging, benchmark harness, CLI override plumbing (SURVEY.md §5)."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.train.checkpoint import CheckpointManager, reproject_params
from hyperspace_tpu.train.logging import MetricsLogger, read_jsonl
from hyperspace_tpu.train.profiling import benchmark_step, compiled_cost


def test_checkpoint_roundtrip(tmp_path):
    state = {
        "params": {"table": jnp.linspace(0, 1, 12).reshape(3, 4)},
        "step": jnp.asarray(7, jnp.int32),
        "key": jax.random.PRNGKey(3),
    }
    with CheckpointManager(str(tmp_path / "ckpt"), async_save=False) as mgr:
        assert mgr.save(7, state)
        mgr.wait()
        zeros = jax.tree_util.tree_map(jnp.zeros_like, state)
        restored, step = mgr.restore(zeros)
    assert step == 7
    np.testing.assert_allclose(
        np.asarray(restored["params"]["table"]), np.asarray(state["params"]["table"]))
    assert int(restored["step"]) == 7


def test_checkpoint_restore_reprojects(tmp_path):
    ball = PoincareBall(1.0)
    params = {"emb": jnp.asarray([[0.999999, 0.0], [0.1, 0.2]]),
              "dense": jnp.ones((2, 2))}
    tags = {"emb": ball, "dense": None}
    with CheckpointManager(str(tmp_path / "c2"), async_save=False) as mgr:
        mgr.save(0, params)
        mgr.wait()
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        restored, _ = mgr.restore(zeros, project=reproject_params(tags, params))
    # on-ball leaf got clamped inside the boundary; Euclidean untouched
    assert float(jnp.linalg.norm(restored["emb"][0])) < 1.0
    np.testing.assert_allclose(np.asarray(restored["dense"]), 1.0)


def test_restore_skips_uncommitted_step_dir(tmp_path):
    """An interrupted save's leftover (empty) step dir must not become
    the restore target: restore(step=None) and peek_latest_step must
    agree on the newest COMMITTED step, or the stream resume offset
    desyncs from the restored state (ADVICE r5)."""
    from hyperspace_tpu.train.checkpoint import peek_latest_step

    d = tmp_path / "c4"
    with CheckpointManager(str(d), async_save=False) as mgr:
        mgr.save(5, {"x": jnp.asarray(5)})
        mgr.wait()
        (d / "9").mkdir()  # interrupted save: all-digit but uncommitted
        assert mgr.latest_committed_step() == 5
        restored, step = mgr.restore({"x": jnp.asarray(0)})
    assert step == 5 and int(restored["x"]) == 5
    assert peek_latest_step(str(d)) == 5  # the two accountings agree


def test_checkpoint_interval_and_retention(tmp_path):
    with CheckpointManager(str(tmp_path / "c3"), async_save=False,
                           max_to_keep=2, save_interval_steps=5) as mgr:
        for s in range(12):
            mgr.save(s, {"x": jnp.asarray(s)})
        mgr.wait()
        assert mgr.latest_step() == 10
        restored, step = mgr.restore({"x": jnp.asarray(0)})
    assert int(restored["x"]) == 10


@pytest.mark.slow
def test_metrics_logger_tensorboard_sink(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    tb_dir = str(tmp_path / "tb")
    with MetricsLogger(str(tmp_path / "m.jsonl"),
                       tensorboard_dir=tb_dir) as lg:
        lg.log(1, loss=0.5)
        lg.log(2, loss=0.25)
    assert any(f.startswith("events.") for f in os.listdir(tb_dir))


def test_metrics_logger(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with MetricsLogger(p) as log:
        log.log(1, loss=0.5)
        log.log(2, loss=0.25, roc_auc=0.9)
    recs = read_jsonl(p)
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[1]["roc_auc"] == 0.9
    assert all("ts" in r for r in recs)


def test_metrics_logger_event_records(tmp_path):
    p = str(tmp_path / "e.jsonl")
    with MetricsLogger(p) as log:
        log.event("run_manifest", config={"steps": 5}, backend="cpu")
        # one bad field reprs ONLY itself — siblings keep their structure
        log.event("weird", blob=object(), config={"steps": 7})
    recs = read_jsonl(p)
    assert recs[0]["event"] == "run_manifest"
    assert recs[0]["config"] == {"steps": 5}
    assert recs[1]["event"] == "weird" and "object" in recs[1]["blob"]
    assert recs[1]["config"] == {"steps": 7}


def test_benchmark_step_runs():
    f = jax.jit(lambda: jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    stats = benchmark_step(f, warmup=1, iters=3)
    assert stats["iters"] == 3
    assert stats["min_s"] <= stats["mean_s"] <= stats["max_s"]


def test_benchmark_step_warmup_zero_regression():
    # warmup=0 used to hit `out` unbound before block_until_ready
    # (NameError); an intentionally-cold timing run must just work
    f = jax.jit(lambda: jnp.ones((4, 4)) * 2)
    stats = benchmark_step(f, warmup=0, iters=2)
    assert stats["iters"] == 2 and stats["min_s"] > 0


def test_compiled_cost_reports_flops():
    cost = compiled_cost(lambda a, b: a @ b, jnp.ones((16, 16)), jnp.ones((16, 16)))
    if cost:  # backend-dependent; CPU provides it
        assert cost.get("flops", 0) > 0


def test_cost_analysis_dict_normalizes_every_backend_shape():
    # the ONE list-shape handler every consumer (bench step_cost, the
    # profiling scripts) now routes through
    from hyperspace_tpu.train.profiling import cost_analysis_dict

    class Fake:
        def __init__(self, ret=None, raise_=False):
            self._ret, self._raise = ret, raise_

        def cost_analysis(self):
            if self._raise:
                raise RuntimeError("no analysis on this backend")
            return self._ret

    assert cost_analysis_dict(Fake({"flops": 2.0})) == {"flops": 2.0}
    assert cost_analysis_dict(Fake([{"flops": 3.0}])) == {"flops": 3.0}
    assert cost_analysis_dict(Fake([])) == {}
    assert cost_analysis_dict(Fake(None)) == {}
    assert cost_analysis_dict(Fake(raise_=True)) == {}


def test_read_jsonl_tolerates_truncated_final_line(tmp_path):
    import pytest

    p = tmp_path / "crashed.jsonl"
    p.write_text('{"step": 1, "loss": 0.5}\n{"step": 2, "lo')  # hard kill
    recs = read_jsonl(str(p))
    assert [r["step"] for r in recs] == [1]
    # corruption in the MIDDLE is a real error, not a crash artifact
    p2 = tmp_path / "corrupt.jsonl"
    p2.write_text('{"step": 1}\nnot json at all\n{"step": 3}\n')
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(str(p2))


def test_cli_override_coercion():
    from hyperspace_tpu.cli.train import RunConfig, apply_overrides, split_overrides

    run, wl = split_overrides(["steps=12", "lr=0.5", "multihost=true"], RunConfig())
    assert run.steps == 12 and run.multihost is True
    assert wl == {"lr": "0.5"}

    from hyperspace_tpu.models.hgcn import HGCNConfig

    cfg = apply_overrides(HGCNConfig(), {"lr": "0.5", "hidden_dims": "[8, 4]",
                                         "use_att": "true"})
    assert cfg.lr == 0.5 and tuple(cfg.hidden_dims) == (8, 4) and cfg.use_att is True
    with pytest.raises(SystemExit):
        apply_overrides(HGCNConfig(), {"nope": "1"})


@pytest.mark.slow
def test_cli_end_to_end_poincare(tmp_path, capsys):
    from hyperspace_tpu.cli import train as cli

    rc = cli.main(["poincare", "steps=30", "dim=4", "batch_size=32",
                   f"log={tmp_path}/run.jsonl"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    res = json.loads(out)
    assert res["workload"] == "poincare" and "map" in res
    assert os.path.exists(tmp_path / "run.jsonl")


@pytest.mark.slow
def test_cli_checkpoint_resume_poincare(tmp_path, capsys):
    """Interrupted-and-resumed CLI run matches an uninterrupted one: the
    checkpoint carries table, RSGD count, and PRNG key, so steps
    [k, N) replay identically (restart-from-checkpoint recovery model)."""
    from hyperspace_tpu.cli import train as cli

    common = ["poincare", "dim=4", "batch_size=32", "neg_samples=4"]

    cli.main(common + ["steps=20", f"ckpt_dir={tmp_path}/full", "ckpt_every=1"])
    full = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    cli.main(common + ["steps=12", f"ckpt_dir={tmp_path}/ab", "ckpt_every=1"])
    capsys.readouterr()
    cli.main(common + ["steps=20", f"ckpt_dir={tmp_path}/ab", "ckpt_every=1",
                       "resume=true"])
    resumed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    assert resumed["map"] == pytest.approx(full["map"], abs=1e-9)
    assert resumed["mean_rank"] == pytest.approx(full["mean_rank"], abs=1e-9)


@pytest.mark.slow
def test_cli_scan_chunk_poincare(tmp_path, capsys):
    """scan_chunk trains through train_epoch_scan with the step budget
    rounded up to a chunk multiple, and checkpoint steps stay truthful."""
    from hyperspace_tpu.cli import train as cli
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    rc = cli.main(["poincare", "steps=20", "scan_chunk=8", "dim=4",
                   "batch_size=32", f"ckpt_dir={tmp_path}/ck"])
    assert rc == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["steps"] == 24  # 20 rounded up to a multiple of 8
    with CheckpointManager(f"{tmp_path}/ck") as ck:
        assert ck.latest_step() == 24
