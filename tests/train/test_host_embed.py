"""Host-resident planned-sparse training (train/host_embed.py): the
bitwise contract vs the in-HBM packed trainer, eviction-pressure and
sharded-master invariance, the gather_ahead overlap mode's bounded-
staleness behavior, and the CLI wiring."""

import numpy as np
import pytest

from hyperspace_tpu.data import wordnet
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.train import host_embed as he
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture(scope="module")
def ds():
    return wordnet.synthetic_tree(depth=4, branching=3)


def _cfg(ds, **kw):
    kw.setdefault("dim", 8)
    kw.setdefault("batch_size", 32)
    kw.setdefault("neg_samples", 5)
    return pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, **kw)


def _run_both(cfg, ds, steps, *, chunk_steps=4, seed=7, **trainer_kw):
    state, opt = pe.init_state(cfg, 0)
    tr = he.HostPlannedTrainer.from_state(cfg, opt, state,
                                          chunk_steps=chunk_steps,
                                          seed=seed, **trainer_kw)
    losses_h = tr.run(ds.pairs, steps)
    state2, opt2 = pe.init_state(cfg, 0)
    st_i, losses_i = he.run_planned_inhbm(cfg, opt2, state2, ds.pairs,
                                          steps, chunk_steps=chunk_steps,
                                          seed=seed)
    return tr, losses_h, st_i, losses_i


@pytest.mark.parametrize("optname", ["rsgd", "radam"])
def test_host_path_bitwise_matches_inhbm(ds, optname):
    """The headline contract: sharded master + hot-row cache + remap-
    to-slots + chunk write-back produce BITWISE the in-HBM packed
    trajectory — losses, table, and (radam) both moment tables —
    including a ragged tail chunk."""
    cfg = _cfg(ds, optimizer=optname)
    tr, lh, st_i, li = _run_both(cfg, ds, 19, shards=3)
    assert np.array_equal(lh, li)
    st_h = tr.to_state()
    assert np.array_equal(np.asarray(st_h.table), np.asarray(st_i.table))
    assert int(st_h.step) == int(st_i.step) == 19
    if optname == "radam":
        assert np.array_equal(np.asarray(st_h.opt_state.mu),
                              np.asarray(st_i.opt_state.mu))
        assert np.array_equal(np.asarray(st_h.opt_state.nu),
                              np.asarray(st_i.opt_state.nu))


def test_bitwise_survives_eviction_pressure():
    """A cache much smaller than the table forces evictions and slot
    reuse (unsorted remaps) — values must not move: the sync-gather
    write-back protocol keeps every read current."""
    big = wordnet.synthetic_tree(depth=5, branching=4)
    cfg = pe.PoincareEmbedConfig(num_nodes=big.num_nodes, dim=8,
                                 batch_size=16, neg_samples=5,
                                 optimizer="radam")
    reg = telem.default_registry()
    base = reg.mark()
    tr, lh, st_i, li = _run_both(cfg, big, 12, chunk_steps=2,
                                 seed=3, shards=2, hot_rows=300)
    d = reg.snapshot(baseline=base)
    assert d.get("host_table/cache_evictions", 0) > 0, \
        "the test must actually exercise eviction to prove anything"
    assert d.get("host_table/cache_hits", 0) > 0
    assert np.array_equal(lh, li)
    assert np.array_equal(np.asarray(tr.to_state().table),
                          np.asarray(st_i.table))


def test_gather_ahead_trains_and_is_exact_at_full_capacity(ds):
    """The overlap mode's contract: always finite and training; and at
    ``hot_rows >= N`` (nothing ever evicted — every cached row is
    current in place) it is EXACT again, prefetched gathers or not."""
    cfg = _cfg(ds)
    state, opt = pe.init_state(cfg, 0)
    tr = he.HostPlannedTrainer.from_state(
        cfg, opt, state, chunk_steps=4, seed=7,
        hot_rows=ds.num_nodes, gather_ahead=True)
    lh = tr.run(ds.pairs, 16)
    assert np.all(np.isfinite(lh))
    state2, opt2 = pe.init_state(cfg, 0)
    _, li = he.run_planned_inhbm(cfg, opt2, state2, ds.pairs, 16,
                                 chunk_steps=4, seed=7)
    assert np.array_equal(lh, li)


def test_chunk_plans_are_deterministic(ds):
    cfg = _cfg(ds)
    a = he.chunk_plan_np(cfg, np.asarray(ds.pairs), 4, seed=9,
                         chunk_index=2)
    b = he.chunk_plan_np(cfg, np.asarray(ds.pairs), 4, seed=9,
                         chunk_index=2)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
    c = he.chunk_plan_np(cfg, np.asarray(ds.pairs), 4, seed=9,
                         chunk_index=3)
    assert not np.array_equal(a[0], c[0])


def test_trainer_validates_config(ds):
    cfg = _cfg(ds)
    state, opt = pe.init_state(cfg, 0)
    with pytest.raises(ValueError, match="chunk_steps"):
        he.HostPlannedTrainer.from_state(cfg, opt, state, chunk_steps=0)
    bad = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes + 1, dim=8)
    master = None
    p = pe.pack_state(cfg, pe.init_state(cfg, 0)[0])
    from hyperspace_tpu.parallel.host_table import HostEmbedTable
    master = HostEmbedTable.from_array(np.asarray(p.packed))
    with pytest.raises(ValueError, match="num_nodes"):
        he.HostPlannedTrainer(bad, opt, master, p.aux, p.key)
    with pytest.raises(ValueError, match="mined"):
        he.HostPlannedTrainer.from_state(
            pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=8,
                                   neg_mode="mined"),
            opt, state)


def test_cli_host_table_branch(ds, tmp_path):
    """run_poincare's host branch: trains, evals, saves the sharded
    master under ckpt_dir, and rejects the incompatible flags."""
    from hyperspace_tpu.cli.train import RunConfig, run_poincare
    from hyperspace_tpu.parallel.host_table import HostEmbedTable

    run = RunConfig(steps=8, host_table=True, host_chunk_steps=4,
                    ckpt_dir=str(tmp_path / "ck"))
    res = run_poincare(run, {"dim": "8", "batch_size": "16"})
    assert res["host_table"] and res["steps"] == 8
    assert "map" in res and np.isfinite(res["map"])
    restored = HostEmbedTable.load_sharded(
        str(tmp_path / "ck" / "host_table"))
    assert restored.num_rows > 0 and restored.width == 8  # rsgd: table
    with pytest.raises(SystemExit, match="host_table"):
        run_poincare(RunConfig(steps=4, host_table=True, scan_chunk=2),
                     {"dim": "8"})
