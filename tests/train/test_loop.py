"""Chunked-dispatch training loop (train/loop.py).

The two contracts that make scan_chunk shippable: (1) chunked dispatch
is the SAME trajectory as single-step dispatch — bitwise, not approx —
and (2) checkpoint/resume accounting stays truthful when steps arrive K
at a time (restore mid-run, chunk-boundary saves, ceil-based stream
chunk resume)."""

import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.cli.train import RunConfig, _stream_stepper
from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.train import loop

_DS = synthetic_tree(depth=3, branching=3)


def _cfg(**kw):
    kw.setdefault("num_nodes", _DS.num_nodes)
    kw.setdefault("dim", 4)
    kw.setdefault("batch_size", 16)
    kw.setdefault("neg_samples", 4)
    return pe.PoincareEmbedConfig(**kw)


def _base_stepper(cfg, opt, pairs):
    step_fn = pe.make_train_step(cfg)
    return lambda st: step_fn(cfg, opt, st, pairs)


def test_chunked_stepper_matches_stepwise():
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    s1, opt = pe.init_state(cfg, 1)
    s2, _ = pe.init_state(cfg, 1)
    base = _base_stepper(cfg, opt, pairs)
    for _ in range(8):
        s1, _ = base(s1)
    chunk = loop.make_chunked_stepper(base, 8)
    s2, losses = chunk(s2)
    np.testing.assert_array_equal(np.asarray(s1.table), np.asarray(s2.table))
    assert losses.shape == (8,)
    assert int(s2.step) == 8


def test_chunked_stepper_k1_is_identity():
    base = _base_stepper(_cfg(), None, None)
    assert loop.make_chunked_stepper(base, 1) is base


def test_chunked_stepper_stacks_multi_output():
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    state, opt = pe.init_state(cfg, 2)
    base = _base_stepper(cfg, opt, pairs)

    def multi(st):  # hvae-shaped stepper: (state, loss, aux, aux)
        st, loss = base(st)
        return st, loss, loss * 2.0, loss + 1.0

    st, (loss, twice, plus) = loop.make_chunked_stepper(multi, 4)(state)
    assert loss.shape == twice.shape == plus.shape == (4,)
    np.testing.assert_allclose(np.asarray(twice), 2 * np.asarray(loss))


def test_run_loop_chunked_equals_single_step():
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    run = RunConfig(steps=12, eval_every=0)
    s1, opt = pe.init_state(cfg, 3)
    s2, _ = pe.init_state(cfg, 3)
    base = _base_stepper(cfg, opt, pairs)
    s1, l1 = loop.run_loop(run, s1, base)
    s2, l2 = loop.run_loop(run, s2, loop.make_chunked_stepper(base, 4),
                           steps_per_call=4)
    np.testing.assert_array_equal(np.asarray(s1.table), np.asarray(s2.table))
    assert float(l1) == float(l2)
    assert int(s1.step) == int(s2.step) == 12


def test_run_loop_resume_mid_run_chunked(tmp_path):
    """Interrupted-then-resumed chunked run == uninterrupted chunked run
    (checkpoints land on chunk boundaries; state carries the PRNG key)."""
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    base = None

    def fresh(seed=5):
        nonlocal base
        st, opt = pe.init_state(cfg, seed)
        base = _base_stepper(cfg, opt, pairs)
        return st

    full = loop.run_loop(RunConfig(steps=16), fresh(),
                         loop.make_chunked_stepper(base, 4),
                         steps_per_call=4)[0]

    d = str(tmp_path / "ck")
    loop.run_loop(RunConfig(steps=8, ckpt_dir=d, ckpt_every=4), fresh(),
                  loop.make_chunked_stepper(base, 4), steps_per_call=4)
    resumed = loop.run_loop(
        RunConfig(steps=16, ckpt_dir=d, ckpt_every=4, resume=True), fresh(),
        loop.make_chunked_stepper(base, 4), steps_per_call=4)[0]
    np.testing.assert_array_equal(np.asarray(full.table),
                                  np.asarray(resumed.table))
    assert int(resumed.step) == 16


def test_run_loop_restore_mid_chunk_boundary(tmp_path):
    """A checkpoint written at a NON-multiple of the new chunk size (a
    K=1 run resumed with K=4): the loop steps chunkwise from the restored
    step — same trajectory as stepping the plain loop to the same total,
    with the step budget legitimately overshot to the next boundary."""
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)

    def fresh(seed=7):
        st, opt = pe.init_state(cfg, seed)
        return st, _base_stepper(cfg, opt, pairs)

    st, base = fresh()
    ref, _ = fresh()
    for _ in range(14):  # 6 + two chunks of 4
        ref, _ = base(ref)

    d = str(tmp_path / "ck")
    st, _ = loop.run_loop(RunConfig(steps=6, ckpt_dir=d, ckpt_every=2), st,
                          base)
    st2, _ = fresh()
    resumed, _ = loop.run_loop(
        RunConfig(steps=12, ckpt_dir=d, ckpt_every=2, resume=True), st2,
        loop.make_chunked_stepper(base, 4), steps_per_call=4)
    assert int(resumed.step) == 14  # 6 restored + 2 full chunks
    np.testing.assert_array_equal(np.asarray(ref.table),
                                  np.asarray(resumed.table))


def test_stream_stepper_pulls_on_device_step_boundaries():
    class FakeStream:
        chunk_steps = 4

        def __init__(self):
            self.pulls = 0

        def next(self):
            self.pulls += 1
            return self.pulls

    stream = FakeStream()
    seen = []
    stepper = _stream_stepper(stream,
                              lambda st, b: (seen.append(b) or (st, 0.0)),
                              steps_per_call=2)
    st = 0
    for _ in range(4):  # 8 device steps = 2 stream chunks
        st, _ = stepper(st)
    assert stream.pulls == 2
    assert seen == [1, 1, 2, 2]


def test_chunk_metrics_accumulates_across_chunks():
    from hyperspace_tpu.optim.metrics import ChunkMetrics

    acc = ChunkMetrics()
    assert acc.flush() is None
    acc.add(jnp.asarray([1.0, 2.0, 3.0]))
    acc.add(jnp.asarray(6.0))  # scalar (K=1 shape) mixes in fine
    stats = acc.flush()  # ONE host fetch for all four statistics
    assert stats == {"loss_mean": 3.0, "loss_last": 6.0,
                     "loss_min": 1.0, "loss_max": 6.0}
    assert acc.flush() is None  # flush drains


def test_run_loop_logs_chunk_mean(tmp_path):
    from hyperspace_tpu.train.logging import read_jsonl

    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    state, opt = pe.init_state(cfg, 9)
    base = _base_stepper(cfg, opt, pairs)
    log = str(tmp_path / "m.jsonl")
    loop.run_loop(RunConfig(steps=8, eval_every=4, log=log), state,
                  loop.make_chunked_stepper(base, 4), steps_per_call=4)
    recs = read_jsonl(log)
    assert [r["step"] for r in recs] == [4, 8]
    for r in recs:
        assert np.isfinite(r["loss"]) and np.isfinite(r["loss_mean"])
        # the interval extremes ride along on the same host fetch
        assert r["loss_min"] <= r["loss_mean"] <= r["loss_max"]
        assert r["loss_last"] == r["loss"]


def test_round_steps_to_chunk():
    assert loop.round_steps_to_chunk(20, 8) == 24
    assert loop.round_steps_to_chunk(24, 8) == 24
    assert loop.round_steps_to_chunk(5, 1) == 5


def test_resume_chunk_is_ceil(tmp_path):
    d = tmp_path / "ck"
    step_dir = d / "100"
    step_dir.mkdir(parents=True)
    (step_dir / "_CHECKPOINT_METADATA").write_text("{}")
    assert loop.resume_chunk(str(d), True, 64) == 2   # ceil(100/64)
    assert loop.resume_chunk(str(d), True, 100) == 1  # exact boundary
    assert loop.resume_chunk(str(d), False, 64) == 0
    assert loop.resume_chunk(None, True, 64) == 0
