"""Training under the mixed-precision policy (docs/precision.md).

Contracts:

- ``make_chunked_stepper(policy=...)`` casts explicit batch args to the
  compute dtype ONCE per chunk (ids/masks untouched) and returns accum-
  dtype losses; the f32 policy is bit-identical to no policy at all;
- bf16 model runs track the f32 loss trajectory within the documented
  tolerance (rel 2e-2 over 5 steps — in practice ≤1e-3 on CPU);
- master params stay f32 under bf16 (optimizers never see half
  precision);
- the all-boundary embedding workloads are BITWISE identical under
  bf16 — the policy refuses to downcast manifold math by design;
- a bf16 run reports ZERO health-monitor boundary violations.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.train.loop import make_chunked_stepper

TRAJ_RTOL = 2e-2  # the documented bf16-vs-f32 loss tolerance (5 steps)


def test_chunked_stepper_applies_policy():
    seen = {}

    def step(st, x, idx):
        seen["x"] = x.dtype
        seen["idx"] = idx.dtype
        return st + 1.0, jnp.sum(x.astype(jnp.float32))

    chunk = make_chunked_stepper(step, 4, policy="bf16")
    state = jnp.zeros(())
    x = jnp.ones((8,), jnp.float32)
    idx = jnp.arange(8)
    state, losses = chunk(state, x, idx)
    assert seen["x"] == jnp.dtype(jnp.bfloat16)  # batch data cast once
    assert seen["idx"] == idx.dtype              # ids never cast
    assert losses.shape == (4,)
    assert losses.dtype == jnp.dtype(jnp.float32)  # accum dtype out
    assert float(state) == 4.0

    # k<=1 under a mixed policy: same cast via the thin wrapper
    seen.clear()
    one = make_chunked_stepper(step, 1, policy="bf16")
    one(jnp.zeros(()), x, idx)
    assert seen["x"] == jnp.dtype(jnp.bfloat16)


def test_chunked_stepper_f32_policy_is_identity():
    def step(st, x):
        return st + jnp.sum(x), jnp.sum(x)

    assert make_chunked_stepper(step, 1, policy="f32") is step
    assert make_chunked_stepper(step, 1, policy=None) is step
    x = jnp.linspace(0.0, 1.0, 16, dtype=jnp.float32)
    s0 = jnp.zeros(())
    sa, la = make_chunked_stepper(step, 4)(s0, x)
    sb, lb = make_chunked_stepper(step, 4, policy="f32")(jnp.zeros(()), x)
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


def _hvae_losses(precision, steps=5):
    from hyperspace_tpu.models import hvae

    rng = np.random.default_rng(0)
    imgs = rng.random((256, 28, 28)).astype(np.float32)
    cfg = hvae.HVAEConfig(precision=precision, batch_size=32, hidden=64,
                          conv_features=(8, 16))
    model, opt, state = hvae.init_model(cfg, seed=0)
    x_all = jnp.asarray(imgs, cfg.dtype)
    losses = []
    for _ in range(steps):
        state, loss, _r, _k = hvae.train_step_sampled(model, opt, state,
                                                      x_all)
        losses.append(float(loss))
    return np.asarray(losses), state


def test_hvae_bf16_trajectory_and_param_dtypes():
    l32, _ = _hvae_losses("f32")
    l16, s16 = _hvae_losses("bf16")
    assert np.isfinite(l16).all()
    np.testing.assert_allclose(l16, l32, rtol=TRAJ_RTOL)
    # master params (and Adam moments) stay f32 — the optimizer never
    # sees half precision
    for leaf in jax.tree_util.tree_leaves((s16.params, s16.opt_state)):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            assert jnp.asarray(leaf).dtype == jnp.dtype(jnp.float32)


def test_hybonet_bf16_trajectory():
    from hyperspace_tpu.models import hybonet

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 100, (128, 16))
    mask = np.ones((128, 16), bool)
    labels = rng.integers(0, 4, (128,))

    def run(precision, steps=5):
        cfg = hybonet.HyboNetConfig(
            vocab_size=100, num_classes=4, max_len=16, dim=16,
            num_layers=1, batch_size=32, attention_impl="scan",
            precision=precision)
        model, opt, state = hybonet.init_model(cfg, seed=0)
        t, m, l = jnp.asarray(toks), jnp.asarray(mask), jnp.asarray(labels)
        out = []
        for _ in range(steps):
            state, loss = hybonet.train_step_sampled(model, opt, state,
                                                     t, m, l)
            out.append(float(loss))
        return np.asarray(out), state

    l32, _ = run("f32")
    l16, s16 = run("bf16")
    assert np.isfinite(l16).all()
    np.testing.assert_allclose(l16, l32, rtol=TRAJ_RTOL)
    for leaf in jax.tree_util.tree_leaves(s16.params):
        assert jnp.asarray(leaf).dtype == jnp.dtype(jnp.float32)


def test_poincare_bf16_policy_is_bitwise_f32():
    """The all-boundary workload: bf16 policy must change NOTHING — the
    table is a master param, the distances are boundary math.  A drifted
    bit here means an ad-hoc cast crept into the step."""
    from hyperspace_tpu.models import poincare_embed as pe

    rng = np.random.default_rng(0)
    pairs = jnp.asarray(rng.integers(0, 50, (100, 2)))
    cfg32 = pe.PoincareEmbedConfig(num_nodes=50, dim=4, batch_size=16)
    cfg16 = dataclasses.replace(cfg32, precision="bf16")
    st32, opt32 = pe.init_state(cfg32, 0)
    st16, opt16 = pe.init_state(cfg16, 0)
    for _ in range(3):
        st32, l32 = pe.train_step(cfg32, opt32, st32, pairs)
        st16, l16 = pe.train_step(cfg16, opt16, st16, pairs)
    np.testing.assert_array_equal(np.asarray(st32.table),
                                  np.asarray(st16.table))
    assert float(l32) == float(l16)


def test_bad_precision_name_rejected_at_init():
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.models import product_embed as pme

    with pytest.raises(ValueError, match="unknown precision"):
        pe.init_state(pe.PoincareEmbedConfig(num_nodes=8, precision="fp8"))
    with pytest.raises(ValueError, match="unknown precision"):
        pme.init_state(
            pme.ProductEmbedConfig(num_nodes=8, precision="half"))


def test_bf16_run_zero_boundary_violations():
    """The acceptance safety net: a bf16-policy training run sampled by
    the health monitor reports zero boundary violations/warnings —
    manifold points never left the f32 constraint surface."""
    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.telemetry.health import HealthMonitor, health_stats

    rng = np.random.default_rng(0)
    pairs = jnp.asarray(rng.integers(0, 64, (200, 2)))
    cfg = pe.PoincareEmbedConfig(num_nodes=64, dim=4, batch_size=32,
                                 precision="bf16")
    state, opt = pe.init_state(cfg, 0)
    ball = PoincareBall(cfg.c)
    monitor = HealthMonitor(
        jax.jit(lambda st: health_stats(st.table, ball)))
    for step in range(4):
        state, _ = pe.train_step(cfg, opt, state, pairs)
        monitor.check(state, step)
    assert monitor.checks == 4
    assert monitor.warnings == 0

    # the HVAE bf16 stack too: params finite, zero warnings
    from hyperspace_tpu.telemetry.health import make_health_fn

    _, hstate = _hvae_losses("bf16", steps=3)
    hmon = HealthMonitor(make_health_fn())
    hmon.check(hstate, 0)
    assert hmon.warnings == 0
