"""run_loop telemetry integration (the ISSUE 2 acceptance contracts):
manifest-first JSONL, span/ctr fields on step records, counters that
match actual prefetch/prep-cache behavior, telemetry_summary at close,
and — the no-regression side — telemetry OFF adds nothing to the stream
and leaves the chunked dispatch count unchanged."""

import dataclasses
import json

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.cli.train import RunConfig
from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import trace
from hyperspace_tpu.train import loop
from hyperspace_tpu.train.logging import read_jsonl

_DS = synthetic_tree(depth=3, branching=3)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telem.default_registry().reset()
    t = trace.default_tracer()
    was = (t.enabled, t.keep_events)
    t.reset()
    yield
    telem.default_registry().reset()
    t.reset()
    t.enabled, t.keep_events = was


def _cfg():
    return pe.PoincareEmbedConfig(num_nodes=_DS.num_nodes, dim=4,
                                  batch_size=16, neg_samples=4)


def _stepper(seed=1):
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    state, opt = pe.init_state(cfg, seed)
    step_fn = pe.make_train_step(cfg)
    return state, (lambda st: step_fn(cfg, opt, st, pairs))


def test_manifest_is_first_record_with_shape(tmp_path):
    state, base = _stepper()
    log = str(tmp_path / "t.jsonl")
    run = RunConfig(steps=8, eval_every=4, log=log, telemetry=True)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    recs = read_jsonl(log)
    man = recs[0]
    assert man["event"] == "run_manifest"
    assert man["config"]["steps"] == 8 and man["config"]["telemetry"]
    for key in ("backend", "device_kind", "device_count", "process_index",
                "process_count", "version"):
        assert key in man, key
    assert man["config"] == {**dataclasses.asdict(RunConfig()),
                             **man["config"]}  # full RunConfig shape


def test_step_records_carry_spans_and_counters(tmp_path):
    state, base = _stepper()
    log = str(tmp_path / "t.jsonl")
    run = RunConfig(steps=12, eval_every=4, log=log, telemetry=True)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    recs = read_jsonl(log)
    steps = [r for r in recs if "loss" in r]
    assert [r["step"] for r in steps] == [4, 8, 12]
    for i, r in enumerate(steps):
        assert r["span/dispatch_s"] > 0
        assert r["ctr/train/dispatches"] == i + 1  # snapshot matches truth
        for k in ("loss_mean", "loss_last", "loss_min", "loss_max"):
            assert np.isfinite(r[k])
    summary = recs[-1]
    assert summary["event"] == "telemetry_summary"
    assert summary["ctr/train/dispatches"] == 3
    assert summary["span/dispatch_n"] == 3


def test_disabled_default_adds_nothing_and_same_dispatch_count(tmp_path):
    state, base = _stepper()
    log = str(tmp_path / "plain.jsonl")
    run = RunConfig(steps=12, eval_every=4, log=log)  # telemetry off
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    recs = read_jsonl(log)
    assert all("event" not in r for r in recs)
    assert not any(k.startswith(("ctr/", "span/", "health/"))
                   for r in recs for k in r)
    # the chunked dispatch count is IDENTICAL to the telemetry-on run of
    # the same shape (12 steps / K=4 = 3): enabling telemetry never adds
    # or removes dispatches, and disabling never skips the accounting
    assert telem.default_registry().get("train/dispatches") == 3
    assert not trace.default_tracer().enabled


def test_health_records_flag_clamped_embedding(tmp_path):
    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.telemetry.health import make_health_fn

    cfg = _cfg()
    ball = PoincareBall(cfg.c)
    state, base = _stepper()
    # artificially clamp one row onto the boundary shell before training
    bad_table = state.table.at[0].set(
        ball.proj(jnp.asarray([0.99999] + [0.0] * (cfg.dim - 1))))
    state = state._replace(table=bad_table)
    log = str(tmp_path / "h.jsonl")
    run = RunConfig(steps=8, eval_every=4, log=log, telemetry=True,
                    health_every=1)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4,
                  health_fn=make_health_fn(ball,
                                           params_of=lambda st: st.table))
    health = [r for r in read_jsonl(log) if "health/ok" in r]
    assert len(health) == 2  # every chunk
    assert health[0]["health/ok"] is False  # the clamped row flags
    assert health[0]["health/boundary_margin_min"] < 1e-2
    assert telem.default_registry().get("health/warnings") >= 1


def test_health_abort_stops_the_run(tmp_path):
    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.telemetry.health import make_health_fn

    cfg = _cfg()
    ball = PoincareBall(cfg.c)
    state, base = _stepper()
    state = state._replace(table=state.table.at[0, 0].set(jnp.nan))
    run = RunConfig(steps=8, telemetry=True, health_every=1,
                    health_abort=True)
    with pytest.raises(FloatingPointError):
        loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                      steps_per_call=4,
                      health_fn=make_health_fn(
                          ball, params_of=lambda st: st.table))


def test_second_in_process_run_reports_only_its_own_counts(tmp_path):
    # library use: two telemetry runs share the process-cumulative
    # registry/tracer; run 2's records and summary must report ITS
    # dispatches/spans, not inherit run 1's (per-run baseline + reset)
    for i, steps in enumerate((8, 12)):
        state, base = _stepper(seed=i)
        log = str(tmp_path / f"r{i}.jsonl")
        run = RunConfig(steps=steps, eval_every=4, log=log, telemetry=True)
        loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                      steps_per_call=4)
    recs = read_jsonl(str(tmp_path / "r1.jsonl"))
    summary = recs[-1]
    assert summary["ctr/train/dispatches"] == 3  # 12/4, not 2+3
    assert summary["span/dispatch_n"] == 3
    first_step = next(r for r in recs if "loss" in r)
    assert first_step["ctr/train/dispatches"] == 1


def test_ckpt_span_counts_only_started_saves(tmp_path):
    # interval-gated save() calls that orbax skips must be no-ops in
    # BOTH metrics: ckpt/saves and span/ckpt_save_n stay in agreement
    state, base = _stepper()
    log = str(tmp_path / "c.jsonl")
    run = RunConfig(steps=12, eval_every=4, log=log, telemetry=True,
                    ckpt_dir=str(tmp_path / "ck"), ckpt_every=8)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)  # 3 save() calls, gate passes 2
    summary = read_jsonl(log)[-1]
    assert summary["span/ckpt_save_n"] == summary["ctr/ckpt/saves"]


def test_library_run_dumps_trace_out(tmp_path):
    # a non-CLI caller setting trace_out must get the file at that path
    # (the CLI dumps later, in main, where the eval span exists)
    state, base = _stepper()
    out = str(tmp_path / "t.json")
    run = RunConfig(steps=8, telemetry=True, trace_out=out)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    doc = json.loads(open(out).read())
    assert any(e["name"] == "dispatch" for e in doc["traceEvents"])


def test_run_loop_restores_freshly_enabled_tracer():
    # a library caller's second run must not inherit span recording the
    # first run's telemetry=1 turned on (process-global tracer leak)
    state, base = _stepper()
    run = RunConfig(steps=8, telemetry=True)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    assert not trace.default_tracer().enabled
    assert trace.default_tracer().flush_fields() == {}  # nothing leftover


def test_health_tol_flag_plumbs_to_monitor():
    from hyperspace_tpu.cli.train import split_overrides

    run, _ = split_overrides(["health_tol=0.05", "health_every=2"],
                             RunConfig())
    assert run.health_tol == 0.05  # a real RunConfig field, not SystemExit
    mon, every = loop._health_monitor(run, lambda st: {})
    assert every == 2 and mon.violation_tol == 0.05


def test_trace_dumped_even_when_workload_fails(tmp_path, monkeypatch):
    # the trace exists to diagnose failures — a health_abort (or any
    # workload crash) must still produce the trace_out artifact
    from hyperspace_tpu.cli import train as cli

    def boom(run, overrides):
        with trace.span("dispatch"):
            pass
        raise FloatingPointError("health abort")

    monkeypatch.setitem(cli.WORKLOADS, "poincare", boom)
    out = str(tmp_path / "t.json")
    with pytest.raises(FloatingPointError):
        cli.main(["poincare", "telemetry=1", f"trace_out={out}"])
    doc = json.loads(open(out).read())
    assert any(e["name"] == "dispatch" for e in doc["traceEvents"])
    assert not trace.default_tracer().enabled  # main's finally disabled it


def test_prefetch_counters_match_behavior():
    from hyperspace_tpu.data.prefetch import HostPrefetcher

    reg = telem.default_registry()
    with HostPrefetcher(lambda i: i * 10, depth=2) as p:
        got = [p.next() for _ in range(4)]
    assert got == [0, 10, 20, 30]
    assert reg.get("prefetch/consumed") == 4
    assert reg.get("prefetch/produced") >= 4
    # the very first next() races a cold queue: stalls ≤ consumed
    assert 0 <= reg.get("prefetch/stalls") <= 4


def test_prep_cache_counters_match_behavior(tmp_path):
    from hyperspace_tpu.data.prep_cache import PrepCache

    reg = telem.default_registry()
    cache = PrepCache(root=str(tmp_path / "prep"))
    cache.get_or_build("k", (1,), lambda: np.arange(3))
    cache.get_or_build("k", (1,), lambda: np.arange(3))
    assert reg.get("prep_cache/miss") == 1
    assert reg.get("prep_cache/hit") == 1


def test_ckpt_counters_and_summary_bytes(tmp_path):
    state, base = _stepper()
    log = str(tmp_path / "c.jsonl")
    run = RunConfig(steps=8, eval_every=4, log=log, telemetry=True,
                    ckpt_dir=str(tmp_path / "ck"), ckpt_every=4)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    reg = telem.default_registry()
    assert reg.get("ckpt/saves") >= 2  # steps 4 and 8
    assert reg.get("ckpt/save_s") > 0
    summary = read_jsonl(log)[-1]
    assert summary["event"] == "telemetry_summary"
    assert summary["ctr/ckpt/bytes"] > 0  # async saves landed first
    assert summary["span/ckpt_save_n"] >= 2


def test_metrics_out_writes_prometheus_snapshots(tmp_path):
    """metrics_out= makes a training run scrapeable-by-file: the loop
    writes Prometheus text at the cadence (first chunk always lands)
    and forces a final write at run end, atomically (no temp debris)."""
    import os

    state, base = _stepper()
    prom = str(tmp_path / "m" / "metrics.prom")
    run = RunConfig(steps=8, eval_every=4, telemetry=True,
                    metrics_out=prom, metrics_every=3600.0)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
    text = open(prom).read()
    assert "# TYPE hyperspace_train_dispatches counter" in text
    # HELP carries the original registry name (the catalog join key)
    assert "# HELP hyperspace_train_dispatches train/dispatches" in text
    assert "# TYPE hyperspace_train_dispatch_ms histogram" in text
    assert os.listdir(tmp_path / "m") == ["metrics.prom"]
    # the final forced write carries the run's closing dispatch count
    line = [l for l in text.splitlines()
            if l.startswith("hyperspace_train_dispatches{")][0]
    assert float(line.rsplit(" ", 1)[1]) == 2.0  # 8 steps / chunk 4


def test_metrics_out_off_constructs_nothing(monkeypatch):
    """The default (no metrics_out) never constructs the writer — the
    zero-cost-when-off contract, proven by making construction fatal."""
    from hyperspace_tpu.telemetry import exposition

    def _boom(*_a, **_kw):
        raise AssertionError(
            "MetricsFileWriter constructed without metrics_out")

    monkeypatch.setattr(exposition, "MetricsFileWriter", _boom)
    state, base = _stepper()
    run = RunConfig(steps=4, eval_every=4, telemetry=False)
    loop.run_loop(run, state, loop.make_chunked_stepper(base, 4),
                  steps_per_call=4)
