"""The fault registry: determinism, grammar, counters, zero-cost off."""

import time

import pytest

from hyperspace_tpu.resilience import faults
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def test_disabled_is_inert():
    assert not faults.active()
    faults.hit("ckpt.save")          # no-op, no raise
    assert not faults.poison("train.step_nan")
    assert faults.due("anything") is None
    assert faults.stats() == {}


def test_window_scheduling_is_deterministic():
    spec = faults.FaultSpec(site="s", kind="ioerror", times=2, after=1)
    faults.install([spec])
    faults.hit("s")                  # call 0: before the window
    with pytest.raises(IOError):
        faults.hit("s")              # calls 1, 2: the window
    with pytest.raises(IOError):
        faults.hit("s")
    faults.hit("s")                  # call 3: past the window
    assert faults.stats()["fired"] == 2


def test_times_zero_fires_every_call():
    faults.install([faults.FaultSpec(site="s", kind="nan", times=0)])
    assert all(faults.poison("s") for _ in range(5))


def test_prob_stream_reproducible_per_seed():
    def draws(seed):
        faults.install(
            [faults.FaultSpec(site="s", kind="nan", prob=0.5)], seed=seed)
        return [faults.poison("s") for _ in range(40)]

    a, b, c = draws(7), draws(7), draws(8)
    assert a == b           # same seed = same schedule: a regression
    assert a != c           # test, not a dice roll
    assert any(a) and not all(a)


def test_latency_kind_sleeps():
    faults.install(
        [faults.FaultSpec(site="s", kind="latency", ms=30.0)])
    t0 = time.perf_counter()
    faults.hit("s")
    assert time.perf_counter() - t0 >= 0.025
    t0 = time.perf_counter()
    faults.hit("s")          # window consumed: no delay
    assert time.perf_counter() - t0 < 0.02


def test_counters_armed_and_fired():
    reg = telem.default_registry()
    base = reg.mark()
    faults.install([faults.FaultSpec(site="a", kind="ioerror"),
                    faults.FaultSpec(site="b", kind="nan")])
    with pytest.raises(IOError):
        faults.hit("a")
    assert faults.poison("b")
    delta = reg.snapshot(baseline=base)
    assert delta.get("fault/armed") == 2
    assert delta.get("fault/fired") == 2


def test_chaos_grammar_round_trip():
    specs = faults.parse_chaos(
        "ckpt.save:ioerror:times=2,"
        "serve.dispatch:latency:ms=50:times=3,"
        "train.step_nan:nan:after=4,"
        "data.next_batch:ioerror:prob=0.05")
    assert [s.site for s in specs] == [
        "ckpt.save", "serve.dispatch", "train.step_nan",
        "data.next_batch"]
    assert specs[0].times == 2
    assert specs[1].ms == 50.0 and specs[1].times == 3
    assert specs[2].after == 4
    assert specs[3].prob == 0.05


@pytest.mark.parametrize("bad", [
    "",                       # nothing parsed
    "siteonly",               # no kind
    "s:unknown_kind",         # bad kind
    "s:nan:times",            # key without value
    "s:nan:bogus=1",          # unknown key
    "s:latency:ms=-1",        # negative delay
    "s:nan:prob=2.0",         # prob out of range
])
def test_chaos_grammar_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_chaos(bad)


def test_install_chaos_cli_helper():
    assert not faults.install_chaos(None)
    assert not faults.install_chaos("")
    assert faults.install_chaos("s:nan")
    assert faults.active()


def test_crash_kind_is_not_an_oserror():
    # a crash simulation must NOT be absorbed by transient-IO retry
    # loops (checkpoint.save catches OSError only)
    assert not issubclass(faults.InjectedCrash, OSError)
    assert issubclass(faults.InjectedIOError, OSError)


def test_data_next_batch_site_in_prefetcher():
    from hyperspace_tpu.data.prefetch import HostPrefetcher

    faults.install([faults.FaultSpec(site="data.next_batch",
                                     kind="ioerror", after=1)])
    with HostPrefetcher(lambda i: i) as pf:
        assert pf.next() == 0
        with pytest.raises(IOError):
            pf.next()
        assert pf.next() == 1  # transient: the stream continues
