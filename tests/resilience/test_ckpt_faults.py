"""Checkpoint failure domain: crash-mid-save orphans, init cleanup,
transient-IO retry, and mid-run save integrity under donation."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.resilience import faults
from hyperspace_tpu.train.checkpoint import (CheckpointManager,
                                             peek_latest_step,
                                             restore_params_only)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _state(step: int):
    return {"table": jnp.full((4, 3), float(step)),
            "step": jnp.asarray(step, jnp.int32)}


def test_crash_mid_save_is_ignored_then_cleaned(tmp_path):
    """The satellite contract: kill a save between staging write and
    commit rename (via the ckpt.save fault site) — resume must ignore
    the partial step, restore the previous COMMITTED one, and the next
    manager init must clean the orphan."""
    d = str(tmp_path / "ck")
    with CheckpointManager(d) as ck:
        assert ck.save(5, _state(5), force=True)
    faults.install([faults.FaultSpec(site="ckpt.save",
                                     kind="crash_staged")])
    with CheckpointManager(d) as ck:
        with pytest.raises(faults.InjectedCrash):
            ck.save(10, _state(10), force=True)
        # the crash left the debris shape on disk...
        names = os.listdir(d)
        assert any("orbax-checkpoint-tmp" in n for n in names)
        assert "10" in names
        # ...which the commit test refuses: resume accounting and the
        # restore target both stay at the committed step
        assert ck.latest_committed_step() == 5
        assert peek_latest_step(d) == 5
        tree, step = restore_params_only(d)
        assert step == 5
        np.testing.assert_array_equal(np.asarray(tree["table"]),
                                      np.full((4, 3), 5.0))
    faults.clear()

    from hyperspace_tpu.telemetry import registry as telem

    reg = telem.default_registry()
    base = reg.mark()
    with CheckpointManager(d) as ck:  # init cleans the orphans
        names = os.listdir(d)
        assert not any("orbax-checkpoint-tmp" in n for n in names)
        assert "10" not in names and "5" in names
        assert ck.latest_committed_step() == 5
        delta = reg.snapshot(baseline=base)
        assert delta.get("ckpt/orphans_cleaned") == 2
        # a cleaned dir is save-able again
        assert ck.save(10, _state(10), force=True)
    assert peek_latest_step(d) == 10


def test_transient_ioerror_is_retried(tmp_path):
    """Two injected transient IOErrors at ckpt.save: the bounded retry
    loop absorbs them, the save lands, and ckpt/save_retries counts."""
    from hyperspace_tpu.telemetry import registry as telem

    d = str(tmp_path / "ck")
    faults.install([faults.FaultSpec(site="ckpt.save", kind="ioerror",
                                     times=2)])
    reg = telem.default_registry()
    base = reg.mark()
    with CheckpointManager(d, retry_backoff_s=0.01) as ck:
        assert ck.save(3, _state(3), force=True)
    assert peek_latest_step(d) == 3
    delta = reg.snapshot(baseline=base)
    assert delta.get("ckpt/save_retries") == 2
    assert delta.get("fault/fired") == 2


def test_retry_budget_is_bounded(tmp_path):
    """More transient faults than the retry budget: the last error
    propagates — no unbounded retry, no sleep-forever."""
    d = str(tmp_path / "ck")
    faults.install([faults.FaultSpec(site="ckpt.save", kind="ioerror",
                                     times=0)])
    with CheckpointManager(d, save_retries=2,
                           retry_backoff_s=0.01) as ck:
        with pytest.raises(IOError):
            ck.save(3, _state(3), force=True)
    assert faults.stats()["fired"] == 3  # 1 attempt + 2 retries


def test_injected_crash_is_not_retried(tmp_path):
    """crash_staged simulates a process death — the transient-IO retry
    loop must NOT absorb it (one firing, straight through)."""
    d = str(tmp_path / "ck")
    faults.install([faults.FaultSpec(site="ckpt.save",
                                     kind="crash_staged", times=0)])
    with CheckpointManager(d, save_retries=5,
                           retry_backoff_s=0.01) as ck:
        with pytest.raises(faults.InjectedCrash):
            ck.save(3, _state(3), force=True)
    assert faults.stats()["fired"] == 1


def test_orphan_cleanup_spares_committed_steps(tmp_path):
    """Cleanup must only take staging debris — committed steps and
    unrelated files survive."""
    d = str(tmp_path / "ck")
    with CheckpointManager(d) as ck:
        ck.save(2, _state(2), force=True)
        ck.save(4, _state(4), force=True)
    # hand-made debris: a staging dir and an uncommitted step dir
    os.makedirs(os.path.join(d, "6.orbax-checkpoint-tmp-123"))
    os.makedirs(os.path.join(d, "6"))
    with open(os.path.join(d, "notes.txt"), "w") as f:
        f.write("keep me")
    with CheckpointManager(d) as ck:
        assert ck.latest_committed_step() == 4
    names = set(os.listdir(d))
    assert "2" in names and "4" in names and "notes.txt" in names
    assert "6" not in names
    assert not any("orbax-checkpoint-tmp" in n for n in names)


def test_midrun_save_integrity_under_donation(tmp_path):
    """Regression: orbax's async device→host copy is not reliably
    complete when save() returns, so a donated stepper's next dispatch
    could recycle the buffers and a MID-RUN checkpoint silently held a
    LATER step's content (observed on this image: dir 4 holding step-8
    values).  The save-side snapshot copy must keep every mid-run dir
    holding exactly its own step."""
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def bump(s):
        return {"table": s["table"] + 1.0, "step": s["step"] + 1}

    d = str(tmp_path / "ck")
    state = {"table": jnp.zeros((64, 8)), "step": jnp.asarray(0, jnp.int32)}
    with CheckpointManager(d, save_interval_steps=4,
                           max_to_keep=10) as ck:
        for _ in range(8):
            state = bump(state)
            ck.save(int(state["step"]), state)
    for step in (4, 8):
        tree, _ = restore_params_only(d, step=step)
        assert int(tree["step"]) == step
        np.testing.assert_array_equal(np.asarray(tree["table"]),
                                      np.full((64, 8), float(step)))
