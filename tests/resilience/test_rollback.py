"""Divergence guard: chaos NaN → rollback → recovery (the acceptance
chaos suite's training leg), guard-idle bit-identity, budget caps."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.cli.train import RunConfig
from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.resilience import faults
from hyperspace_tpu.resilience.guard import (RollbackController,
                                             RollbackExhausted)
from hyperspace_tpu.train import loop
from hyperspace_tpu.train.logging import read_jsonl

_DS = synthetic_tree(depth=3, branching=3)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _cfg():
    return pe.PoincareEmbedConfig(num_nodes=_DS.num_nodes, dim=4,
                                  batch_size=16, neg_samples=4,
                                  burnin_steps=0)


def _setup(seed=5):
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    state, opt = pe.init_state(cfg, seed)
    step_fn = pe.make_train_step(cfg)
    return state, (lambda st: step_fn(cfg, opt, st, pairs))


def test_chaos_nan_rollback_recovers(tmp_path):
    """One poisoned chunk: the run rolls back to the last committed
    checkpoint EXACTLY ONCE (JSONL incident), completes its full step
    budget, and ends with a finite loss."""
    log = str(tmp_path / "log.jsonl")
    run = RunConfig(steps=16, eval_every=4, ckpt_dir=str(tmp_path / "ck"),
                    ckpt_every=4, rollback=2, log=log)
    state, stepper = _setup()
    faults.install([faults.FaultSpec(site="train.step_nan", kind="nan",
                                     after=5)])
    state, loss = loop.run_loop(run, state, stepper)
    assert math.isfinite(float(loss))
    assert int(state.step) == 16
    assert not bool(jnp.any(~jnp.isfinite(state.table)))
    incidents = [r for r in read_jsonl(log)
                 if r.get("event") == "rollback"]
    assert len(incidents) == 1
    inc = incidents[0]
    # poisoned at step 6, detected at the step-8 boundary, restored to
    # the last committed save (step 4); the lr backoff scale rides along
    assert inc["restored_step"] < inc["step"]
    assert inc["attempt"] == 1 and inc["lr_scale"] == 0.5
    assert "loss" in inc["reason"]
    from hyperspace_tpu.telemetry import registry as telem

    assert telem.default_registry().get("resilience/rollbacks") >= 1


def test_guard_idle_is_bit_identical(tmp_path):
    """Guard armed + no fault == unguarded run, bitwise (the chaos
    acceptance's faults-disabled contract)."""
    s1, st1 = _setup(seed=9)
    s2, st2 = _setup(seed=9)
    plain = RunConfig(steps=12, eval_every=4,
                      ckpt_dir=str(tmp_path / "a"), ckpt_every=4)
    guarded = RunConfig(steps=12, eval_every=4,
                        ckpt_dir=str(tmp_path / "b"), ckpt_every=4,
                        rollback=2)
    s1, l1 = loop.run_loop(plain, s1, st1)
    s2, l2 = loop.run_loop(guarded, s2, st2)
    np.testing.assert_array_equal(np.asarray(s1.table),
                                  np.asarray(s2.table))
    assert float(l1) == float(l2)


def test_rollback_budget_exhausted(tmp_path):
    """Persistent divergence (every chunk poisoned) must exhaust the
    capped budget and fail LOUDLY, not loop forever."""
    run = RunConfig(steps=8, eval_every=2, ckpt_dir=str(tmp_path / "ck"),
                    ckpt_every=2, rollback=1)
    state, stepper = _setup()
    faults.install([faults.FaultSpec(site="train.step_nan", kind="nan",
                                     times=0)])
    with pytest.raises(RollbackExhausted):
        loop.run_loop(run, state, stepper)


def test_rollback_requires_ckpt_dir():
    run = RunConfig(steps=4, rollback=1)  # no ckpt_dir
    state, stepper = _setup()
    with pytest.raises(ValueError, match="ckpt_dir"):
        loop.run_loop(run, state, stepper)


def test_on_rollback_hook_reseeds(tmp_path):
    """The hook receives (restored_step, attempt, lr_scale) — the
    stream re-seed + LR-backoff delivery point."""
    calls = []
    run = RunConfig(steps=12, eval_every=4, ckpt_dir=str(tmp_path / "ck"),
                    ckpt_every=4, rollback=3, rollback_lr_backoff=0.25)
    state, stepper = _setup()
    faults.install([faults.FaultSpec(site="train.step_nan", kind="nan",
                                     after=5)])
    state, loss = loop.run_loop(
        run, state, stepper,
        on_rollback=lambda *a: calls.append(a))
    assert math.isfinite(float(loss))
    assert calls == [(4, 1, 0.25)]


def test_health_violation_triggers_rollback(tmp_path):
    """The health-monitor path: a nonfinite state flags at the health
    cadence (BEFORE any log boundary) and rolls back instead of
    warn/abort."""
    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.telemetry.health import make_health_fn

    log = str(tmp_path / "log.jsonl")
    run = RunConfig(steps=12, eval_every=50, ckpt_dir=str(tmp_path / "ck"),
                    ckpt_every=2, rollback=2, health_every=1,
                    health_abort=True, log=log)
    state, stepper = _setup()
    health_fn = make_health_fn(PoincareBall(1.0),
                               params_of=lambda st: st.table)
    faults.install([faults.FaultSpec(site="train.step_nan", kind="nan",
                                     after=4)])
    state, loss = loop.run_loop(run, state, stepper, health_fn=health_fn)
    assert math.isfinite(float(loss))
    incidents = [r for r in read_jsonl(log)
                 if r.get("event") == "rollback"]
    assert len(incidents) == 1
    assert incidents[0]["reason"].startswith("health:")


def test_end_of_run_divergence_caught(tmp_path):
    """A poisoned FINAL chunk (past the last log/save boundary) must
    still be detected and rolled back — never returned as the result."""
    run = RunConfig(steps=8, eval_every=50, ckpt_dir=str(tmp_path / "ck"),
                    ckpt_every=4, rollback=2)
    state, stepper = _setup()
    faults.install([faults.FaultSpec(site="train.step_nan", kind="nan",
                                     after=7)])  # the last chunk
    state, loss = loop.run_loop(run, state, stepper)
    assert math.isfinite(float(loss))
    assert int(state.step) == 8


def test_controller_validates_inputs(tmp_path):
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    with CheckpointManager(str(tmp_path / "ck")) as ck:
        with pytest.raises(ValueError, match="max_rollbacks"):
            RollbackController(ck, max_rollbacks=0)
        with pytest.raises(ValueError, match="lr_backoff"):
            RollbackController(ck, lr_backoff=0.0)
