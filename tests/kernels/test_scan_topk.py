"""Fused scan-top-k parity suite (ISSUE 10 tentpole).

Chain of oracles: the Pallas kernel (interpret mode, SURVEY.md §4.4)
must match the XLA twin **bitwise** (the tightened twin contract: same
padded block schedule, same shared tile/merge functions), and the twin
must rank-match a numpy argsort over the masked distances.  Plus the
deterministic tile-sizing pins for ``fused_tile_rows`` (the
VMEM-budget-aware sizing satellite)."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels import scan_topk as F
from hyperspace_tpu.manifolds import Euclidean, Lorentz, PoincareBall

from .conftest import ball_points


def _table(rng, kind, n, d):
    if kind == "lorentz":
        man = Lorentz(0.8)
        v = jnp.asarray(rng.standard_normal((n, d + 1)) * 0.5, jnp.float32)
        v = v.at[:, 0].set(0.0)
        return np.asarray(man.expmap0(v)), ("lorentz", 0.8), man
    if kind == "euclidean":
        t = rng.standard_normal((n, d)).astype(np.float32)
        return t, ("euclidean", 0.0), Euclidean()
    t = np.asarray(ball_points(rng, (n, d), 1.3))
    return t, ("poincare", 1.3), PoincareBall(1.3)


def _ref_topk(man, table, qidx, k, exclude_self):
    d = np.array(jax.vmap(lambda x: man.dist(x, jnp.asarray(table)))(
        jnp.asarray(table)[qidx]))
    if exclude_self:
        d[np.arange(len(qidx)), qidx] = np.inf
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx


def _run_both(monkeypatch, fn):
    """fn() under the twin, then under the interpreter — returns both."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    twin = tuple(np.asarray(a) for a in fn())
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    kern = tuple(np.asarray(a) for a in fn())
    return twin, kern


@pytest.mark.parametrize("kind", ["poincare", "lorentz", "euclidean"])
@pytest.mark.parametrize("exclude_self", [True, False])
def test_twin_matches_interpreter_bitwise(rng, monkeypatch, kind,
                                          exclude_self):
    """The twin contract: XLA twin == Pallas interpreter, bit for bit
    (distances via uint32 view), on every supported family."""
    table, spec, man = _table(rng, kind, 300, 6)
    qidx = np.asarray([0, 3, 17, 150, 299], np.int32)
    q = table[qidx]
    k = 7

    def run():
        return F.scan_topk(jnp.asarray(table), jnp.asarray(q),
                           jnp.asarray(qidx), 0, spec=spec, k=k,
                           n=table.shape[0], exclude_self=exclude_self,
                           tile_rows=128)

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))
    # and both rank-match the manifold oracle
    assert np.array_equal(ti, _ref_topk(man, table, qidx, k, exclude_self))
    assert np.all(np.diff(td, axis=1) >= 0)


def test_k_drain_and_tile_boundaries(rng, monkeypatch):
    """k = N−1 (self excluded) and k = N (drain) across tile
    boundaries: every reachable row exactly once, ascending, and the
    twin/interpreter stay bitwise."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    table, spec, man = _table(rng, "poincare", 200, 5)
    qidx = np.asarray([0, 127, 128, 199], np.int32)
    q = table[qidx]
    for k, es in ((1, True), (199, True), (200, False)):
        d, i = (np.asarray(a) for a in F.scan_topk(
            jnp.asarray(table), jnp.asarray(q), jnp.asarray(qidx), 0,
            spec=spec, k=k, n=200, exclude_self=es, tile_rows=128))
        assert np.array_equal(i, _ref_topk(man, table, qidx, k, es))
        assert np.all(np.isfinite(d))
        for j, qi in enumerate(qidx):
            want = [r for r in range(200) if es is False or r != qi][:200]
            assert len(set(i[j].tolist())) == k
            assert set(i[j].tolist()) <= set(want)


def test_narrow_slab_pads_with_inf_minus_one(rng, monkeypatch):
    """A slab narrower than k (the sharded narrow-shard case) fills the
    tail with (+inf, −1) — never a duplicated or fabricated id."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    table, spec, _ = _table(rng, "poincare", 40, 5)
    qidx = np.asarray([0, 1], np.int32)
    d, i = (np.asarray(a) for a in F.scan_topk(
        jnp.asarray(table), jnp.asarray(table[qidx]), jnp.asarray(qidx),
        0, spec=spec, k=64, n=40, exclude_self=True, tile_rows=128))
    assert np.all(np.isinf(d[:, 39:]))
    assert np.all(i[:, 39:] == -1)
    assert np.all(np.isfinite(d[:, :39]))


def test_shard_local_col0_offsets(rng, monkeypatch):
    """A traced-style col0 offset shifts the returned GLOBAL ids but not
    the geometry — the _topk_sharded composition contract."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    table, spec, _ = _table(rng, "poincare", 150, 5)
    qidx = np.asarray([3, 70], np.int32)
    q = table[qidx]
    d0, i0 = (np.asarray(a) for a in F.scan_topk(
        jnp.asarray(table), jnp.asarray(q), jnp.asarray(qidx), 0,
        spec=spec, k=5, n=150, exclude_self=False, tile_rows=128))
    off = 1000
    d1, i1 = (np.asarray(a) for a in F.scan_topk(
        jnp.asarray(table), jnp.asarray(q), jnp.asarray(qidx + off),
        jnp.int32(off), spec=spec, k=5, n=150 + off,
        exclude_self=False, tile_rows=128))
    assert np.array_equal(i0 + off, i1)
    assert np.array_equal(d0.view(np.uint32), d1.view(np.uint32))


def test_bf16_slab_scans_in_f32_registers(rng, monkeypatch):
    """A bf16 slab streams at half the bytes but computes f32 distances
    in-register: results are f32 and rank-match the oracle over the
    QUANTIZED table (the quantization is the only bf16 effect)."""
    table, spec, man = _table(rng, "poincare", 300, 6)
    tb = jnp.asarray(table).astype(jnp.bfloat16)
    qidx = np.asarray([0, 50, 299], np.int32)
    qb = tb[jnp.asarray(qidx)]

    def run():
        return F.scan_topk(tb, qb, jnp.asarray(qidx), 0, spec=spec, k=6,
                           n=300, exclude_self=True, tile_rows=128)

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert td.dtype == np.float32
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))
    tq = np.asarray(tb.astype(jnp.float32))
    assert np.array_equal(ti, _ref_topk(man, tq, qidx, 6, True))


@pytest.mark.parametrize("kind", ["poincare", "lorentz", "euclidean"])
def test_int8_slab_dequantizes_in_register(rng, monkeypatch, kind):
    """An int8 slab + per-row scale (the serve int8 lane,
    serve/quant.py): twin == interpreter bitwise, and results are
    BITWISE those of scanning the pre-dequantized f32 table — the
    in-register ``astype(f32) * scale`` is the only int8 effect."""
    from hyperspace_tpu.serve.quant import dequantize_rows, quantize_rows

    table, spec, man = _table(rng, kind, 300, 6)
    q8, sc = quantize_rows(table)
    deq = dequantize_rows(q8, sc)
    qidx = np.asarray([0, 50, 299], np.int32)
    qf = jnp.asarray(deq[qidx])

    def run():
        return F.scan_topk(jnp.asarray(q8), qf, jnp.asarray(qidx), 0,
                           spec=spec, k=6, n=300, exclude_self=True,
                           tile_rows=128, scale=jnp.asarray(sc))

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert td.dtype == np.float32
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))

    def run_deq():
        return F.scan_topk(jnp.asarray(deq), qf, jnp.asarray(qidx), 0,
                           spec=spec, k=6, n=300, exclude_self=True,
                           tile_rows=128)

    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    dd, di = (np.asarray(a) for a in run_deq())
    assert np.array_equal(ti, di)
    assert np.array_equal(td.view(np.uint32), dd.view(np.uint32))


@pytest.mark.parametrize("kind", ["poincare", "lorentz", "euclidean"])
@pytest.mark.parametrize("exclude_self", [True, False])
def test_int4_packed_slab_unpacks_in_register(rng, monkeypatch, kind,
                                              exclude_self):
    """The int4 lane (ISSUE 16): a planar two-nibble slab + per-row f16
    scale — twin == interpreter bitwise, results RANK-identical to
    scanning the pre-dequantized f32 table with distances ULP-tight
    (the split-lane relayout reorders the coordinate reduction, so the
    sums can differ in the last bit), and bitwise-invariant across the
    double-buffered tile heights."""
    from hyperspace_tpu.serve.quant import (dequantize_int4_rows,
                                            pack_int4_rows)

    table, spec, man = _table(rng, kind, 300, 6)
    d_ = table.shape[1]
    pk, sc = pack_int4_rows(table)
    deq = dequantize_int4_rows(pk, sc, d_)
    qidx = np.asarray([0, 50, 299], np.int32)
    qf = jnp.asarray(deq[qidx])

    def run(bm=128):
        return F.scan_topk(jnp.asarray(pk), qf, jnp.asarray(qidx), 0,
                           spec=spec, k=6, n=300,
                           exclude_self=exclude_self, tile_rows=bm,
                           scale=jnp.asarray(sc), packed=True)

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert td.dtype == np.float32
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))

    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    dd, di = (np.asarray(a) for a in F.scan_topk(
        jnp.asarray(deq), qf, jnp.asarray(qidx), 0, spec=spec, k=6,
        n=300, exclude_self=exclude_self, tile_rows=128))
    assert np.array_equal(ti, di)
    assert np.allclose(td, dd, rtol=1e-6, atol=1e-7)
    # the pipelined tile loop is result-invisible across tile heights
    for bm in (256, 512):
        bd, bi = (np.asarray(a) for a in run(bm))
        assert np.array_equal(bi, ti), bm
        assert np.array_equal(bd.view(np.uint32), td.view(np.uint32)), bm


@pytest.mark.parametrize("kind", ["poincare", "lorentz", "euclidean"])
@pytest.mark.parametrize("exclude_self", [True, False])
def test_pq_coded_slab_scores_by_adc(rng, monkeypatch, kind,
                                     exclude_self):
    """The PQ lane (ISSUE 16): coded tiles scored via per-query LUTs —
    twin == interpreter bitwise, invariant across tile heights, and
    rank-matched against an argsort over the engine's decode-and-score
    closed form on the reconstructed lifted rows (the fallback path the
    ADC sum must agree with)."""
    from hyperspace_tpu.serve.engine import _pq_lift_dist
    from hyperspace_tpu.serve.index import _lift
    from hyperspace_tpu.serve.quant import build_pq, pq_decode

    table, spec, man = _table(rng, kind, 300, 6)
    codes, cb = build_pq(table, spec, seed=0)
    qidx = np.asarray([0, 50, 299], np.int32)
    q_lift = jnp.asarray(np.asarray(
        _lift(spec, jnp.asarray(table[qidx])), np.float32))
    m = cb.m
    assert F.supports_pq(spec, k=6, m=m)
    lut = F.pq_lut(q_lift, jnp.asarray(cb.codebooks), kind=spec[0])

    def run(bm=128):
        return F.scan_topk_pq(jnp.asarray(codes), lut,
                              jnp.asarray(qidx), 0, spec=spec, k=6, n=300,
                              exclude_self=exclude_self, tile_rows=bm)

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert td.dtype == np.float32
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))
    for bm in (256, 512):
        monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
        bd, bi = (np.asarray(a) for a in run(bm))
        assert np.array_equal(bi, ti), bm
        assert np.array_equal(bd.view(np.uint32), td.view(np.uint32)), bm
    # decode-and-score oracle: distances of the reconstructed rows
    recon = jnp.asarray(pq_decode(cb, codes)[:, :cb.lift_dim])
    ref = np.asarray(_pq_lift_dist(spec, q_lift, recon), np.float64)
    if exclude_self:
        ref[np.arange(len(qidx)), qidx] = np.inf
    order = np.argsort(ref, axis=1, kind="stable")[:, :6]
    assert np.array_equal(ti, order)


def test_int8_cand_variant_gathers_scales(rng, monkeypatch):
    """The candidate variant's int8 path: per-candidate scale gather,
    twin == interpreter bitwise == the dequantized-table run."""
    from hyperspace_tpu.serve.quant import dequantize_rows, quantize_rows

    table, spec, _ = _table(rng, "poincare", 400, 6)
    q8, sc = quantize_rows(table)
    deq = dequantize_rows(q8, sc)
    cand = rng.integers(0, 400, (5, 257)).astype(np.int32)
    cand[:, 250:] = -1  # in-range padding slots
    qidx = np.arange(5, dtype=np.int32)
    qf = jnp.asarray(deq[qidx])

    def run():
        return F.scan_topk_cand(jnp.asarray(q8), jnp.asarray(cand), qf,
                                jnp.asarray(qidx), spec=spec, k=6,
                                exclude_self=True,
                                scale=jnp.asarray(sc))

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    dd, di = (np.asarray(a) for a in F.scan_topk_cand(
        jnp.asarray(deq), jnp.asarray(cand), qf, jnp.asarray(qidx),
        spec=spec, k=6, exclude_self=True))
    assert np.array_equal(ti, di)
    assert np.array_equal(td.view(np.uint32), dd.view(np.uint32))


@pytest.mark.parametrize("kind", ["poincare", "lorentz", "euclidean"])
def test_cand_variant_matches_interpreter_and_oracle(rng, monkeypatch,
                                                     kind):
    """The per-query candidate variant (the IVF probing scorer): twin ==
    interpreter bitwise; ranks == argsort over each query's OWN masked
    candidate set; −1 padding and exclude_self never surface."""
    table, spec, man = _table(rng, kind, 120, 6)
    b, cc, k = 9, 40, 5
    cand = rng.integers(0, 120, size=(b, cc)).astype(np.int32)
    cand[:, -3:] = -1                                     # padding slots
    qidx = rng.integers(0, 120, size=b).astype(np.int32)
    cand[:, 0] = qidx                                     # self present
    q = table[qidx]

    def run():
        return F.scan_topk_cand(jnp.asarray(table), jnp.asarray(cand),
                                jnp.asarray(q), jnp.asarray(qidx),
                                spec=spec, k=k, exclude_self=True)

    (td, ti), (kd, ki) = _run_both(monkeypatch, run)
    assert np.array_equal(ti, ki)
    assert np.array_equal(td.view(np.uint32), kd.view(np.uint32))
    # per-query oracle over the candidate multiset
    t64 = jnp.asarray(table)
    for j in range(b):
        ids = [c for c in cand[j] if c >= 0 and c != qidx[j]]
        dd = np.asarray(man.dist(jnp.asarray(table[qidx[j]])[None, :],
                                 t64[np.asarray(ids)]))
        order = np.asarray(ids)[np.argsort(dd, kind="stable")]
        # candidate ids may repeat (random draw) — compare distance
        # ranks via the id multiset of the top-k prefix
        got = ti[j].tolist()
        assert got == [int(x) for x in order[:k]] or (
            sorted(got) == sorted(int(x) for x in order[:k]))
        assert qidx[j] not in got
        assert -1 not in got


def test_fused_tile_rows_pins():
    """The VMEM-footprint sizing is deterministic in dim × dtype × k —
    pinned values for known shapes (the auto_chunk_rows satellite)."""
    assert F.fused_tile_rows(16, jnp.float32, 10) == 512
    assert F.fused_tile_rows(256, jnp.float32, 10) == 512
    assert F.fused_tile_rows(256, jnp.float32, 256) == 256
    assert F.fused_tile_rows(1024, jnp.float32, 10) == 128
    assert F.fused_tile_rows(1024, jnp.bfloat16, 10) == 256
    assert F.fused_cand_tile_rows(16, jnp.float32, 10) == 256


def test_supports_and_validation(rng):
    """Capability gates: product / oversized k / oversized dim are
    unsupported (callers fall back); calling anyway is a loud error."""
    assert F.supports(("poincare", 1.0), k=1, dim=16)
    assert F.supports(("euclidean", 0.0), k=F.FUSED_MAX_K, dim=16)
    assert not F.supports(("product", ()), k=4, dim=16)
    assert not F.supports(("poincare", 1.0), k=F.FUSED_MAX_K + 1, dim=16)
    assert not F.supports(("poincare", 1.0), k=4, dim=F.FUSED_MAX_DIM + 1)
    table, spec, _ = _table(np.random.default_rng(0), "poincare", 20, 4)
    with pytest.raises(ValueError, match="unsupported"):
        F.scan_topk(jnp.asarray(table), jnp.asarray(table[:2]),
                    jnp.zeros((2,), jnp.int32), 0, spec=("product", ()),
                    k=2, n=20)
    with pytest.raises(ValueError, match="tile_rows"):
        F.scan_topk(jnp.asarray(table), jnp.asarray(table[:2]),
                    jnp.zeros((2,), jnp.int32), 0, spec=spec, k=2, n=20,
                    tile_rows=100)
