"""Parity for the fused pairwise-distance kernels: kernel == twin == the
per-pair manifold distance (vmapped), on both manifolds."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels import distmat
from hyperspace_tpu.manifolds import Lorentz, PoincareBall

from tests.kernels.conftest import ball_points as _ball_points



def _lorentz_points(rng, n, d, c):
    man = Lorentz(c)
    v = jnp.asarray(rng.standard_normal((n, d + 1)) * 0.5, jnp.float64)
    v = v.at[:, 0].set(0.0)
    return np.asarray(man.expmap0(v))


@pytest.mark.parametrize("n,m,d", [(10, 13, 5), (64, 200, 10), (257, 129, 3)])
def test_poincare_pdist_parity(interp, rng, n, m, d):
    c = 1.0
    x = _ball_points(rng, (n, d), c)
    y = _ball_points(rng, (m, d), c)
    out = distmat.poincare_pdist(x, y, c)
    assert out.shape == (n, m)

    ball = PoincareBall(c)
    x64, y64 = x.astype(jnp.float64), y.astype(jnp.float64)
    oracle = jax.vmap(lambda xi: ball.dist(xi, y64))(x64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle, np.float32),
                               rtol=1e-4, atol=1e-4)


def test_lorentz_pdist_parity(interp, rng):
    c = 0.8
    x = jnp.asarray(_lorentz_points(rng, 33, 6, c), jnp.float32)
    y = jnp.asarray(_lorentz_points(rng, 50, 6, c), jnp.float32)
    out = distmat.lorentz_pdist(x, y, c)

    man = Lorentz(c)
    x64, y64 = x.astype(jnp.float64), y.astype(jnp.float64)
    oracle = jax.vmap(lambda xi: man.dist(xi, y64))(x64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle, np.float32),
                               rtol=2e-3, atol=2e-3)


def test_twin_matches_manifold_dist(rng):
    """The closed-form twin == artanh-form PoincareBall.dist in f64."""
    c = 1.7
    x = jnp.asarray(_ball_points(rng, (20, 4), c), jnp.float64)
    y = jnp.asarray(_ball_points(rng, (30, 4), c), jnp.float64)
    twin = distmat._t_poincare_pdist(x, y, c)
    ball = PoincareBall(c)
    oracle = jax.vmap(lambda xi: ball.dist(xi, y))(x)
    np.testing.assert_allclose(np.asarray(twin), np.asarray(oracle),
                               rtol=1e-9, atol=1e-9)


def test_public_pdist_wrapper(rng):
    """The documented entry point dispatches to the same ops as the
    legacy names (which stay as aliases) and rejects unknown manifolds."""
    c = 1.0
    x = _ball_points(rng, (6, 4), c)
    y = _ball_points(rng, (9, 4), c)
    np.testing.assert_array_equal(
        np.asarray(distmat.pdist(x, y, c, manifold="poincare")),
        np.asarray(distmat.poincare_pdist(x, y, c)))
    lx = jnp.asarray(_lorentz_points(rng, 5, 4, c), jnp.float32)
    ly = jnp.asarray(_lorentz_points(rng, 7, 4, c), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(distmat.pdist(lx, ly, c, manifold="lorentz")),
        np.asarray(distmat.lorentz_pdist(lx, ly, c)))
    with pytest.raises(ValueError, match="unknown manifold"):
        distmat.pdist(x, y, c, manifold="sphere")


@pytest.mark.slow
def test_pdist_gradients(interp, rng):
    c = 1.0
    x = _ball_points(rng, (6, 4), c)
    y = _ball_points(rng, (8, 4), c)
    g_k = jax.grad(lambda xx: jnp.sum(distmat.poincare_pdist(xx, y, c)))(x)
    g_t = jax.grad(lambda xx: jnp.sum(distmat._t_poincare_pdist(xx, y, c)))(x)
    np.testing.assert_allclose(np.asarray(g_k), np.asarray(g_t), rtol=1e-5, atol=1e-5)
    assert np.all(np.isfinite(np.asarray(g_k)))
