"""Parity suite for the fused gyro-linear kernel (N5, SURVEY.md §4.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels import hyplinear as khl
from hyperspace_tpu.manifolds import PoincareBall

from .conftest import ball_points


def _case(rng, n, d_in, d_out, c, dtype=jnp.float32):
    x = ball_points(rng, (n, d_in), c).astype(dtype)
    m = jnp.asarray(rng.standard_normal((d_in, d_out)) * 0.3, dtype)
    b = ball_points(rng, (d_out,), c, scale=0.3).astype(dtype)
    return x, m, b


@pytest.mark.parametrize("c", [1.0, 0.5])
@pytest.mark.parametrize(
    "n,d_in,d_out", [(9, 10, 6), (64, 128, 128), (300, 33, 65)]
)  # (300, ...) forces a multi-row-block grid
def test_kernel_matches_twin(rng, interp, c, n, d_in, d_out):
    x, m, b = _case(rng, n, d_in, d_out, c)
    got = khl.hyp_linear(x, m, b, c)
    want = khl._t_hyp_linear(x, m, b, c)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_twin_is_manifold_composition(rng):
    c = 1.0
    x, m, b = _case(rng, 11, 7, 5, c, jnp.float64)
    ball = PoincareBall(c)
    want = ball.proj(ball.mobius_add(ball.mobius_matvec(m, x), b))
    np.testing.assert_allclose(khl._t_hyp_linear(x, m, b, c), want, rtol=1e-12)


def test_zero_bias_is_identity_of_matvec(rng, interp):
    c = 1.0
    x, m, _ = _case(rng, 8, 10, 10, c)
    got = khl.hyp_linear(x, m, jnp.zeros(10, jnp.float32), c)
    ball = PoincareBall(c)
    want = ball.proj(ball.mobius_matvec(m, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_zero_matvec_maps_to_bias(rng, interp):
    """M x = 0 → origin, so output is proj(0 ⊕ b) = b."""
    c = 1.0
    x = ball_points(rng, (8, 6), c)
    m = jnp.zeros((6, 4), jnp.float32)
    b = ball_points(rng, (4,), c, scale=0.3)
    got = khl.hyp_linear(x, m, b, c)
    np.testing.assert_allclose(got, jnp.broadcast_to(b, (8, 4)), rtol=1e-5, atol=1e-6)


def test_batched_leading_dims(rng, interp):
    c = 1.0
    x = ball_points(rng, (3, 5, 10), c)
    m = jnp.asarray(np.random.default_rng(1).standard_normal((10, 8)) * 0.3, jnp.float32)
    b = ball_points(rng, (8,), c, scale=0.3)
    got = khl.hyp_linear(x, m, b, c)
    want = khl._t_hyp_linear(x, m, b, c)
    assert got.shape == (3, 5, 8)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gradients_match_twin(rng):
    c = 1.0
    x = ball_points(rng, (9, 10), c).astype(jnp.float64)
    m = jnp.asarray(rng.standard_normal((10, 6)) * 0.3, jnp.float64)
    b = ball_points(rng, (6,), c, scale=0.3).astype(jnp.float64)

    def loss(fn, *args):
        return jnp.sum(jnp.tanh(fn(*args, c)))

    g1 = jax.grad(lambda *a: loss(khl.hyp_linear, *a), argnums=(0, 1, 2))(x, m, b)
    g2 = jax.grad(lambda *a: loss(khl._t_hyp_linear, *a), argnums=(0, 1, 2))(x, m, b)
    for a_, b_ in zip(g1, g2):
        np.testing.assert_allclose(a_, b_, rtol=1e-8, atol=1e-10)


def test_output_on_ball(rng, interp):
    c = 1.0
    x, m, b = _case(rng, 16, 12, 12, c)
    y = khl.hyp_linear(x, 10.0 * m, b, c)  # large weights push to the boundary
    assert float(jnp.max(jnp.linalg.norm(y, axis=-1))) < 1.0 / np.sqrt(c)
