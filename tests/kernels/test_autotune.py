"""Empirical tile autotuner (`kernels/autotune.py` — ISSUE 13 pillar 3).

Contracts: table round trip + lookup keyed by (variant, dim, dtype, k,
device_kind); `fused_tile_rows`/`fused_cand_tile_rows` consult a tuned
entry and fall back to the static model on ANY problem (no table,
version mismatch, foreign device kind, off-grid bm); and the tile
choice is **result-invisible** — bitwise identical scan results across
tile sizes, through the raw kernel AND a tuned engine."""

import json
import os

import numpy as np
import pytest

from hyperspace_tpu.kernels import autotune, scan_topk as K


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    autotune.reset_cache()
    yield
    autotune.reset_cache()


def _write_table(path, entries):
    autotune.save_table(entries, str(path))


def _entry(variant, dim, dtype, k, bm, kind=None):
    kind = kind or autotune.device_kind()
    return {autotune.entry_key(variant, dim, dtype, k, kind):
            {"variant": variant, "dim": dim, "dtype": dtype, "k": k,
             "device_kind": kind, "bm": bm, "ms": 1.0}}


def test_lookup_round_trip(tmp_path, monkeypatch):
    import jax.numpy as jnp

    p = tmp_path / "tiles.json"
    _write_table(p, _entry("slab", 16, "float32", 10, 256))
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    assert autotune.lookup("slab", 16, "float32", 10) == 256
    assert autotune.lookup("slab", 16, jnp.float32, 10) == 256  # dtype objs
    assert autotune.lookup("cand", 16, "float32", 10) is None  # variant keyed
    assert autotune.lookup("slab", 32, "float32", 10) is None


def test_fused_tile_rows_consults_tuned_then_falls_back(tmp_path,
                                                        monkeypatch):
    import jax.numpy as jnp

    static = K.fused_tile_rows(16, jnp.float32, 10, allow_tuned=False)
    assert K.fused_tile_rows(16, jnp.float32, 10) == static  # no table
    p = tmp_path / "tiles.json"
    _write_table(p, {**_entry("slab", 16, "float32", 10, 256),
                     **_entry("cand", 16, "float32", 10, 128)})
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    assert K.fused_tile_rows(16, jnp.float32, 10) == 256
    assert K.fused_cand_tile_rows(16, jnp.float32, 10) == 128
    # a non-default budget asks the MODEL a question the table never
    # measured: tuned entries are not consulted
    assert K.fused_tile_rows(16, jnp.float32, 10,
                             tile_budget=1 << 20) != 256 or static == 256
    # the untuned shape keeps the static answer
    assert (K.fused_tile_rows(16, jnp.bfloat16, 10)
            == K.fused_tile_rows(16, jnp.bfloat16, 10, allow_tuned=False))


@pytest.mark.parametrize("corrupt", [
    "not json", json.dumps({"version": 999, "entries": {}}),
    json.dumps({"entries": "nope"}), json.dumps([1, 2, 3])])
def test_bad_tables_fall_back_silently(tmp_path, monkeypatch, corrupt):
    import jax.numpy as jnp

    p = tmp_path / "tiles.json"
    p.write_text(corrupt)
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    assert autotune.lookup("slab", 16, "float32", 10) is None
    assert (K.fused_tile_rows(16, jnp.float32, 10)
            == K.fused_tile_rows(16, jnp.float32, 10, allow_tuned=False))


def test_invalid_bm_and_foreign_device_kind_ignored(tmp_path, monkeypatch):
    p = tmp_path / "tiles.json"
    _write_table(p, {
        # off the 128 grid / absurd / wrong type: all rejected
        **_entry("slab", 8, "float32", 4, 100),
        **_entry("slab", 8, "float32", 5, 128 * 1000),
        **_entry("slab", 8, "float32", 6, True),
        # a DIFFERENT device kind's tuning must never apply here
        **_entry("slab", 8, "float32", 7, 256, kind="TPU v9"),
    })
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    for k in (4, 5, 6, 7):
        assert autotune.lookup("slab", 8, "float32", k) is None, k


def test_tuned_bm_clamped_to_static_vmem_model(tmp_path, monkeypatch):
    """A stale table tuned under a looser footprint model must never
    hand the kernel a tile the CURRENT static model rejects — tuned
    values clamp to the model's answer (the 'stale table costs only
    speed, never correctness' guarantee; a real chip's Mosaic enforces
    the VMEM bound the model approximates)."""
    import jax.numpy as jnp

    static = K.fused_tile_rows(1024, jnp.float32, 256, allow_tuned=False)
    assert static < 1024  # the premise: this shape's cap is tight
    p = tmp_path / "tiles.json"
    _write_table(p, _entry("slab", 1024, "float32", 256, 1024))
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    assert K.fused_tile_rows(1024, jnp.float32, 256) == static


def test_env_zero_disables_lookups(tmp_path, monkeypatch):
    monkeypatch.setenv(autotune.ENV_TABLE, "0")
    autotune.reset_cache()
    assert autotune.table_path() is None
    assert autotune.lookup("slab", 16, "float32", 10) is None


def _bits(a):
    return np.asarray(a).view(np.uint32)


def test_tile_choice_is_result_invisible_raw_kernel():
    """The bitwise-twin contract extended across tuned tiles: every
    128-grid tile height gives bitwise identical (dists, ids) — the
    merge extracts exact copies with global-column tie-breaks, so the
    tiling can only reorder WORK, never results."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    tab = (np.tanh(rng.standard_normal((700, 8)) * 0.3) * 0.7).astype(
        np.float32)
    q = jnp.asarray(tab[:40])
    qi = jnp.arange(40, dtype=jnp.int32)
    base = None
    for bm in (128, 256, 512, 1024):
        d, i = K.scan_topk(jnp.asarray(tab), q, qi, 0,
                           spec=("poincare", 1.0), k=9, n=700,
                           exclude_self=True, tile_rows=bm)
        got = (_bits(d), np.asarray(i))
        if base is None:
            base = got
        else:
            assert np.array_equal(got[0], base[0]), bm
            assert np.array_equal(got[1], base[1]), bm


def test_tuned_engine_bitwise_matches_fallback_engine(tmp_path,
                                                      monkeypatch):
    """An engine built while a tuned table is active (different chunk =
    different tile height) answers bitwise like the static-model
    engine — tuning must be invisible to results end to end."""
    import jax.numpy as jnp

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.serve.engine import QueryEngine

    rng = np.random.default_rng(1)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((600, 8)) * 0.3, jnp.float32)))
    ref = QueryEngine(table, ("poincare", 1.0), scan_mode="fused")
    ids = np.asarray(rng.integers(0, 600, size=16), np.int32)
    ri, rd = ref.topk_neighbors(ids, 7)

    p = tmp_path / "tiles.json"
    # tune the engine's sizing key (k = FUSED_MAX_K) to a small tile
    _write_table(p, _entry("slab", 8, "float32", K.FUSED_MAX_K, 128))
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    tuned = QueryEngine(table, ("poincare", 1.0), scan_mode="fused")
    assert tuned.chunk_rows == 128 != ref.chunk_rows
    assert tuned.scan_signature == ref.scan_signature  # same identity
    ti, td = tuned.topk_neighbors(ids, 7)
    assert np.array_equal(np.asarray(ti), np.asarray(ri))
    assert np.array_equal(_bits(td), _bits(rd))


def test_measure_and_autotune_roundtrip(tmp_path, monkeypatch):
    """A miniature real tune: measure a tiny grid on this backend,
    persist, and watch the sizing functions pick the tuned answer up."""
    import jax.numpy as jnp

    m = autotune.measure("slab", 8, "float32", 4, rows=1024, batch=16,
                         repeats=1, candidates=(128, 256))
    assert m["bm"] in (128, 256) and set(m["timings"]) == {128, 256}
    entries = autotune.autotune(
        [8], ["float32"], [4], variants=("slab",), rows=1024, batch=16,
        repeats=1, log=lambda *_a: None)
    p = tmp_path / "tiles.json"
    autotune.save_table(entries, str(p))
    monkeypatch.setenv(autotune.ENV_TABLE, str(p))
    autotune.reset_cache()
    tuned = autotune.lookup("slab", 8, "float32", 4)
    assert tuned is not None
    assert K.fused_tile_rows(8, jnp.float32, 4) == tuned
    # additive merge: re-tuning preserves foreign entries
    entries2 = autotune.autotune(
        [8], ["float32"], [4], variants=("slab",), rows=1024, batch=16,
        repeats=1, base_entries={**entries,
                                 **_entry("slab", 64, "float32", 4, 512,
                                          kind="TPU v9")},
        log=lambda *_a: None)
    assert any("TPU v9" in k for k in entries2)


def test_checked_in_table_parses_and_validates():
    """The SHIPPED table (configs/scan_topk_tiles.json — tuned offline,
    checked in so a deployment checkout starts tuned) parses at the
    current schema version and every entry is self-consistent: the flat
    key reproduces from the entry's own fields, the tile is on the 128
    grid, and the timing is a non-negative number.  Guards the file
    against hand-edits and schema drift (ISSUE 16)."""
    path = autotune.default_table_path()
    assert os.path.exists(path), path
    doc = json.loads(open(path, encoding="utf-8").read())
    assert doc["version"] == autotune.TABLE_VERSION
    entries = doc["entries"]
    assert entries, "the checked-in table must not be empty"
    # load_table accepts it wholesale (no silent fallback-to-empty)
    assert autotune.load_table(path) == entries
    for key, e in entries.items():
        assert e["variant"] in autotune.VARIANTS, key
        assert autotune.entry_key(e["variant"], e["dim"], e["dtype"],
                                  e["k"], e["device_kind"]) == key
        assert autotune._valid_bm(e["bm"]) == e["bm"], key
        assert isinstance(e["ms"], (int, float)) and e["ms"] >= 0, key


def _load_script():
    import importlib.util

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "scripts",
        "autotune_scan_topk.py")
    spec = importlib.util.spec_from_file_location("autotune_script", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_autotune_script_dry_run(tmp_path, capsys):
    """--dry-run walks the grid and emits a schema-complete table
    without timing on a device: static-model tiles, ms=0.0, and the
    inert 'dry-run' device kind (a real lookup keyed by the actual
    backend can never match it)."""
    mod = _load_script()
    out = str(tmp_path / "dry.json")
    rc = mod.main(["--dry-run", "--dims", "8,16", "--ks", "4",
                   "--dtypes", "float32", "--variants", "slab,cand",
                   "--out", out])
    assert rc == 0
    doc = json.loads(open(out).read())
    assert doc["version"] == autotune.TABLE_VERSION
    assert len(doc["entries"]) == 4  # 2 dims x 1 k x 1 dtype x 2 variants
    for key, e in doc["entries"].items():
        assert e["device_kind"] == "dry-run" and e["ms"] == 0.0, key
        assert autotune._valid_bm(e["bm"]) == e["bm"], key
        assert autotune.entry_key(e["variant"], e["dim"], e["dtype"],
                                  e["k"], "dry-run") == key
    # dry entries are inert: the real device kind never matches them
    monkey_free_lookup = autotune.load_table(out)
    assert all("dry-run" in k for k in monkey_free_lookup)
    # without --out the doc goes to stdout and nothing is written
    capsys.readouterr()  # drain the first call's log line
    rc = mod.main(["--dry-run", "--dims", "8", "--ks", "4",
                   "--dtypes", "float32", "--variants", "slab"])
    assert rc == 0
    printed = json.loads(capsys.readouterr().out)
    assert printed["version"] == autotune.TABLE_VERSION
    assert len(printed["entries"]) == 1


def test_autotune_script_smoke(tmp_path):
    """The offline driver end-to-end on a tiny grid (in-process: jax is
    already loaded; the script is import-safe)."""
    import importlib.util

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "scripts",
        "autotune_scan_topk.py")
    spec = importlib.util.spec_from_file_location("autotune_script", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "tiles.json")
    rc = mod.main(["--dims", "8", "--ks", "4", "--dtypes", "float32",
                   "--variants", "slab", "--rows", "1024", "--batch", "16",
                   "--repeats", "1", "--out", out])
    assert rc == 0
    doc = json.loads(open(out).read())
    assert doc["version"] == autotune.TABLE_VERSION
    assert len(doc["entries"]) == 1
    (entry,) = doc["entries"].values()
    assert entry["bm"] % 128 == 0 and entry["device_kind"]
