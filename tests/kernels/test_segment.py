"""Block-CSR segment-sum kernel parity (SURVEY.md §4.4): the Pallas kernel
in interpret mode must match ``jax.ops.segment_sum`` exactly-ish, over
random sorted segment layouts including empty segments, hub nodes, and
padding tails."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels.segment import build_csr_plan, csr_segment_sum


def _run(receivers, vals, n):
    plan = tuple(jnp.asarray(a) for a in build_csr_plan(receivers, n))
    return csr_segment_sum(jnp.asarray(vals), jnp.asarray(receivers), plan, n)


@pytest.mark.parametrize(
    "n,e,f", [(300, 2000, 17), (50, 64, 128), (1000, 5000, 64), (7, 3, 5)]
)
def test_matches_segment_sum(n, e, f, rng, interp):
    r = np.sort(rng.integers(0, n, e)).astype(np.int32)
    vals = rng.standard_normal((e, f)).astype(np.float32)
    got = _run(r, vals, n)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(r), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_hub_node_and_empty_segments(rng, interp):
    # one node receives 90% of edges; most segments empty
    n, e, f = 500, 4000, 32
    r = np.where(rng.random(e) < 0.9, 137, rng.integers(0, n, e))
    r = np.sort(r).astype(np.int32)
    vals = rng.standard_normal((e, f)).astype(np.float32)
    got = _run(r, vals, n)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(r), n)
    # a ~3600-edge hub sums in a different order than segment_sum's chain:
    # tolerance scales with sqrt(deg)·eps
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=5e-4)


def test_zero_padding_tail_is_inert(rng, interp):
    # padding convention: receivers = n-1 with zero values
    n, e, f = 100, 700, 16
    r = np.sort(rng.integers(0, n, e)).astype(np.int32)
    vals = rng.standard_normal((e, f)).astype(np.float32)
    r_pad = np.concatenate([r, np.full(300, n - 1, np.int32)])
    vals_pad = np.concatenate([vals, np.zeros((300, f), np.float32)])
    got = _run(r_pad, vals_pad, n)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(r), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_plan_requires_sorted():
    with pytest.raises(ValueError):
        build_csr_plan(np.asarray([3, 1, 2], np.int32), 5)


def test_plan_chunks_in_range_for_empty_trailing_blocks(rng, interp):
    # E an exact multiple of bk with all receivers far below num_nodes:
    # trailing node blocks are empty and their mandatory zeroing item must
    # not index one chunk past the end of the padded edge array
    n, e, f = 300, 512, 8
    r = np.sort(rng.integers(0, 128, e)).astype(np.int32)
    plan = build_csr_plan(r, n)
    assert int(plan.chunk.max()) < max(e // 512, 1)
    vals = rng.standard_normal((e, f)).astype(np.float32)
    got = _run(r, vals, n)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(r), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("op", ["sum", "max"])
def test_csr_segment_reduce_1d_parity(op, monkeypatch):
    """Scalar per-segment sum/max kernel == jax.ops reference (interpret)."""
    from hyperspace_tpu.kernels.segment import (
        build_csr_plan,
        csr_segment_reduce_1d,
    )

    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    rng = np.random.default_rng(3)
    n, e = 300, 2048
    recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    vals = jnp.asarray(rng.normal(size=e).astype(np.float32))
    plan = tuple(jnp.asarray(a) for a in build_csr_plan(recv, n))
    got = csr_segment_reduce_1d(vals, jnp.asarray(recv), plan, n, op=op)
    ref_f = jax.ops.segment_sum if op == "sum" else jax.ops.segment_max
    ref = ref_f(vals, jnp.asarray(recv), n, indices_are_sorted=True)
    if op == "max":
        # empty segments: kernel yields the -inf stand-in, ref yields -inf
        got = np.where(np.asarray(got) < -1e37, -np.inf, np.asarray(got))
        ref = np.where(np.isinf(np.asarray(ref)) | (np.asarray(ref) < -1e37),
                       -np.inf, np.asarray(ref))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
