"""Parity suite for the fused hyperbolic-MLR kernel (N6).

Pins the algebraic expansion (two matmuls, kernels/mlr.py) to the naive
Möbius-form oracle (nn/mlr.py hyp_mlr_logits) — catching any drift in
either direction — and the Pallas kernel (interpret mode, SURVEY.md §4.4)
to the twin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels import mlr as kmlr
from hyperspace_tpu.nn.mlr import hyp_mlr_logits

from .conftest import ball_points


def _case(rng, n, k, d, c, dtype):
    x = ball_points(rng, (n, d), c).astype(dtype)
    p = ball_points(rng, (k, d), c, scale=0.5).astype(dtype)
    a = jnp.asarray(rng.standard_normal((k, d)), dtype)
    return x, p, a


@pytest.mark.parametrize("c", [1.0, 0.5, 2.0])
def test_twin_matches_naive_f64(rng, c):
    x, p, a = _case(rng, 33, 7, 10, c, jnp.float64)
    got = kmlr._t_hyp_mlr(x, p, a, c)
    want = hyp_mlr_logits(x, p, a, c)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


@pytest.mark.slow
def test_twin_matches_naive_batched(rng):
    c = 1.0
    x = ball_points(rng, (4, 5, 10), c).astype(jnp.float64)
    p = ball_points(rng, (6, 10), c, scale=0.5).astype(jnp.float64)
    a = jnp.asarray(rng.standard_normal((6, 10)), jnp.float64)
    got = kmlr._t_hyp_mlr(x, p, a, c)
    want = hyp_mlr_logits(x, p, a, c)
    assert got.shape == (4, 5, 6)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize(
    "n,k,d", [(17, 5, 10), (8, 128, 128), (200, 300, 33), (260, 520, 7)]
)  # (260, 520, 7) forces a multi-tile grid in both i and j
def test_kernel_matches_twin(rng, interp, n, k, d):
    c = 1.0
    x, p, a = _case(rng, n, k, d, c, jnp.float32)
    got = kmlr.hyp_mlr(x, p, a, c)
    want = kmlr._t_hyp_mlr(x, p, a, c)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gradients_match_naive(rng):
    c = 1.0
    x, p, a = _case(rng, 9, 4, 10, c, jnp.float64)

    def loss_kernel(x, p, a, cc):
        return jnp.sum(jnp.tanh(kmlr.hyp_mlr(x, p, a, cc)))

    def loss_naive(x, p, a, cc):
        return jnp.sum(jnp.tanh(hyp_mlr_logits(x, p, a, cc)))

    g1 = jax.grad(loss_kernel, argnums=(0, 1, 2, 3))(x, p, a, jnp.float64(c))
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2, 3))(x, p, a, jnp.float64(c))
    for a_, b_ in zip(g1, g2):
        np.testing.assert_allclose(a_, b_, rtol=1e-8, atol=1e-8)


def test_learned_curvature_grad_nonzero(rng):
    x, p, a = _case(rng, 9, 4, 10, 1.0, jnp.float64)
    g = jax.grad(lambda cc: jnp.sum(kmlr.hyp_mlr(x, p, a, cc) ** 2))(jnp.float64(0.7))
    assert np.isfinite(g) and abs(g) > 0
