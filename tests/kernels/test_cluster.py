"""Cluster-pair SpMM aggregation kernel parity (kernels/cluster.py).

The kernel must equal segment_sum of the gathered messages over any edge
geometry: dense block pairs, boundary-straddling chunks, empty receiver
blocks, padding edges, bf16 fast mode.  The split must cover every edge
exactly once and stay closed under edge reversal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels.cluster import (
    build_cluster_plan,
    build_cluster_split,
    cluster_aggregate,
)


def _sorted_by_pair(r, s, num_nodes, bn=None, bs=None):
    from hyperspace_tpu.kernels import cluster as C

    bn = bn or C._BN
    bs = bs or C._BS
    key = (r // bn).astype(np.int64) * (num_nodes // bs + 1) + s // bs
    o = np.argsort(key, kind="stable")
    return r[o], s[o]


@pytest.mark.parametrize("n,e,f,dtype", [
    (700, 4000, 32, np.float32),
    (700, 4000, 32, "bfloat16"),
    (300, 900, 130, np.float32),   # f > 128 lane padding
    (257, 513, 8, np.float32),     # odd sizes, boundary chunks
])
def test_cluster_aggregate_matches_segment_sum(n, e, f, dtype, rng, interp):
    r = rng.integers(0, n, e).astype(np.int32)
    s = rng.integers(0, n, e).astype(np.int32)
    r, s = _sorted_by_pair(r, s, n)
    w = rng.random(e).astype(np.float32)
    h = rng.standard_normal((n, f)).astype(np.float32)
    if dtype == "bfloat16":
        h = jnp.asarray(h, jnp.bfloat16)
    plan = tuple(jnp.asarray(a)
                 for a in build_cluster_plan(r, s, n))
    got = cluster_aggregate(jnp.asarray(h), jnp.asarray(w), jnp.asarray(r),
                            jnp.asarray(s), plan, n)
    want = jax.ops.segment_sum(
        (jnp.asarray(w)[:, None] * jnp.asarray(h, jnp.float32)[s]), jnp.asarray(r), n)
    tol = 2e-2 if dtype == "bfloat16" else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


def test_empty_receiver_blocks_zeroed(rng, interp):
    # all edges target one block; every other block's tile must come out 0
    n, e, f = 1500, 600, 16
    r = rng.integers(512, 768, e).astype(np.int32)
    s = rng.integers(0, n, e).astype(np.int32)
    r, s = _sorted_by_pair(r, s, n)
    w = np.ones(e, np.float32)
    h = rng.standard_normal((n, f)).astype(np.float32)
    plan = tuple(jnp.asarray(a) for a in build_cluster_plan(r, s, n))
    got = np.asarray(cluster_aggregate(
        jnp.asarray(h), jnp.asarray(w), jnp.asarray(r), jnp.asarray(s),
        plan, n))
    want = np.asarray(jax.ops.segment_sum(
        jnp.asarray(w)[:, None] * jnp.asarray(h)[s], jnp.asarray(r), n))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert np.all(got[:512] == 0) and np.all(got[768:] == 0)


def _toy_graph(n=600, seed=0):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=seed)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    # the production threshold (256) clusters nothing on a toy graph;
    # rebuild with a low threshold so BOTH paths carry edges here
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n, min_pair_edges=8)
    assert 0.1 < g.cluster_split.frac_clustered < 1.0
    return g


def test_split_covers_every_edge_once_and_is_symmetric():
    g = _toy_graph()
    sp = g.cluster_split
    mask = g.edge_mask
    want = sorted(zip(g.receivers[mask].tolist(), g.senders[mask].tolist()))
    got = sorted(list(zip(sp.c_recv.tolist(), sp.c_send.tolist()))
                 + list(zip(sp.s_recv[sp.s_wf > 0].tolist(),
                            sp.s_send[sp.s_wf > 0].tolist())))
    assert got == want
    # reversal-closed subsets: each straggler's reverse is a straggler
    strag = {(int(a), int(b)) for a, b in
             zip(sp.s_recv[sp.s_wf > 0], sp.s_send[sp.s_wf > 0])}
    assert all((b, a) in strag for a, b in strag)
    # weights match 1/deg of the right endpoints
    deg = np.maximum(g.deg, 1.0)
    np.testing.assert_allclose(sp.c_wf, 1.0 / deg[sp.c_recv], rtol=1e-6)
    np.testing.assert_allclose(sp.c_wb, 1.0 / deg[sp.c_send], rtol=1e-6)


def test_cluster_two_path_matches_plain_aggregation(rng):
    """cluster_sym_aggregate (XLA twin path) == the mean aggregation the
    layer would otherwise compute, values and gradient."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import cluster_sym_aggregate

    g = _toy_graph()
    dg = G.to_device(g)
    assert dg.cluster is not None
    n = g.num_nodes
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    probe = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))

    w = (g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]).astype(np.float32)

    def f_plain(h):
        msgs = jnp.asarray(w)[:, None] * h[jnp.asarray(g.senders)]
        return jnp.sum(jax.ops.segment_sum(
            msgs, jnp.asarray(g.receivers), n) * probe)

    def f_cluster(h):
        return jnp.sum(cluster_sym_aggregate(h, dg.cluster, n) * probe)

    np.testing.assert_allclose(float(f_cluster(h)), float(f_plain(h)),
                               rtol=1e-5)
    gc = jax.grad(f_cluster)(h)
    gp = jax.grad(f_plain)(h)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gp),
                               rtol=1e-4, atol=1e-6)


def test_hgcconv_cluster_path_matches_default(rng):
    """The same HGCConv params produce the same layer output whether the
    graph carries a cluster split or not."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.gcn import HGCConv
    from hyperspace_tpu.manifolds import Lorentz

    from hyperspace_tpu.data.graphs import synthetic_hierarchy

    n = 600
    edges, x, labels, ncls = synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=0)
    g_plain = G.prepare(edges, n, x, cluster=False, pad_multiple=256)
    g_clust = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    m = Lorentz(1.0)
    pts = m.expmap0(jnp.concatenate(
        [jnp.zeros((n, 1)),
         jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32) * 0.3)],
        axis=1))
    conv = HGCConv(features=8, kind="lorentz")
    params = conv.init(jax.random.PRNGKey(0), pts, G.to_device(g_plain))

    def run(dg):
        out, _ = conv.apply(params, pts, dg)
        return out

    o1 = run(G.to_device(g_plain))
    o2 = run(G.to_device(g_clust))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-5, atol=1e-6)


def test_empty_clustered_set_is_safe(rng, interp):
    """A split where nothing clusters still aggregates correctly: the
    kernel path must not index chunk 0 of a zero-length edge array (it
    returns zeros), and the straggler path carries everything."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import cluster_sym_aggregate
    from hyperspace_tpu.data.graphs import synthetic_hierarchy
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    n = 600
    edges, x, labels, ncls = synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=0)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    # production threshold on a toy graph: nothing reaches 10**6 edges
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n,
        min_pair_edges=10**6)
    assert len(g.cluster_split.c_recv) == 0
    dg = G.to_device(g)
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    out = cluster_sym_aggregate(h, dg.cluster, n)
    w = (g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]).astype(np.float32)
    want = jax.ops.segment_sum(
        jnp.asarray(w)[:, None] * h[jnp.asarray(g.senders)],
        jnp.asarray(g.receivers), n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# --- weighted (attention) path: SDDMM kernel + cluster_att_aggregate ----------


@pytest.mark.parametrize("n,e,f,dtype", [
    (700, 4000, 32, np.float32),
    (700, 4000, 32, "bfloat16"),
    (300, 900, 130, np.float32),   # f > 128 lane padding
    (257, 513, 8, np.float32),     # odd sizes, boundary chunks
])
def test_cluster_sddmm_matches_gather_dot(n, e, f, dtype, rng, interp):
    from hyperspace_tpu.kernels.cluster import cluster_sddmm

    r = rng.integers(0, n, e).astype(np.int32)
    s = rng.integers(0, n, e).astype(np.int32)
    r, s = _sorted_by_pair(r, s, n)
    g = rng.standard_normal((n, f)).astype(np.float32)
    h = rng.standard_normal((n, f)).astype(np.float32)
    if dtype == "bfloat16":
        g = jnp.asarray(g, jnp.bfloat16)
        h = jnp.asarray(h, jnp.bfloat16)
    plan = tuple(jnp.asarray(a) for a in build_cluster_plan(r, s, n))
    got = np.asarray(cluster_sddmm(jnp.asarray(g), jnp.asarray(h),
                                   jnp.asarray(r), jnp.asarray(s), plan, n))
    want = np.sum(np.asarray(g, np.float32)[r]
                  * np.asarray(h, np.float32)[s], axis=-1)
    tol = 3e-2 if dtype == "bfloat16" else 1e-4
    np.testing.assert_allclose(got[:e], want, rtol=tol, atol=tol)
    assert np.all(got[e:] == 0.0)  # padding lanes


def _toy_graph_weighted(n=600, seed=0):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=seed)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n, min_pair_edges=8,
        rev_perm=g.rev_perm)
    assert 0.1 < g.cluster_split.frac_clustered < 1.0
    return g


def test_cluster_att_aggregate_matches_sym_aggregate(rng):
    """Runtime-weighted cluster aggregation == sym_segment_aggregate on
    the same (h, w): values, dh, and dw (the SDDMM backward)."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import (cluster_att_aggregate,
                                           sym_segment_aggregate)

    g = _toy_graph_weighted()
    dg = G.to_device(g)
    dg.cluster.use_weighted = True  # toy frac may sit under the gate
    assert dg.cluster.weighted_ok
    n = g.num_nodes
    e = len(g.senders)
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    w = jnp.asarray((rng.random(e).astype(np.float32) + 0.1)
                    * g.edge_mask)
    probe = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    pb, pc, pf = dg.plan

    def f_att(h, w):
        return jnp.sum(cluster_att_aggregate(h, w, dg.cluster, n) * probe)

    def f_ref(h, w):
        return jnp.sum(sym_segment_aggregate(
            h, w, dg.senders, dg.receivers, dg.rev_perm, pb, pc, pf, n,
            True) * probe)

    np.testing.assert_allclose(float(f_att(h, w)), float(f_ref(h, w)),
                               rtol=1e-5)
    ga_h, ga_w = jax.grad(f_att, argnums=(0, 1))(h, w)
    gr_h, gr_w = jax.grad(f_ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(ga_h), np.asarray(gr_h),
                               rtol=1e-4, atol=1e-5)
    # dw on padding edges: both paths may differ there (w=0 either way);
    # compare on real edges only
    m = np.asarray(g.edge_mask)
    np.testing.assert_allclose(np.asarray(ga_w)[m], np.asarray(gr_w)[m],
                               rtol=1e-4, atol=1e-5)


def test_hgcconv_att_cluster_matches_plain(rng):
    """HGCConv(use_att=True) gives the same output + parameter gradients
    with and without the weighted cluster split."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.manifolds import Lorentz
    from hyperspace_tpu.nn.gcn import HGCConv

    g = _toy_graph_weighted()
    n = g.num_nodes
    dg_c = G.to_device(g)
    dg_c.cluster.use_weighted = True  # toy frac may sit under the gate
    dg_p = dg_c._replace(cluster=None)
    m = Lorentz(1.0)
    pts = m.expmap0(jnp.concatenate(
        [jnp.zeros((n, 1)),
         jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32) * 0.3)],
        axis=1))
    conv = HGCConv(features=8, kind="lorentz", use_att=True)
    params = conv.init(jax.random.PRNGKey(0), pts, dg_p)

    def loss(p, dg):
        out, _ = conv.apply(p, pts, dg)
        return jnp.sum(out * out)

    np.testing.assert_allclose(float(loss(params, dg_c)),
                               float(loss(params, dg_p)), rtol=1e-5)
    gc = jax.grad(loss)(params, dg_c)
    gp = jax.grad(loss)(params, dg_p)
    for kc, kp in zip(jax.tree_util.tree_leaves(gc),
                      jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(kc), np.asarray(kp),
                                   rtol=2e-4, atol=1e-5)
