"""Cluster-pair SpMM aggregation kernel parity (kernels/cluster.py).

The kernel must equal segment_sum of the gathered messages over any edge
geometry: dense block pairs, boundary-straddling chunks, empty receiver
blocks, padding edges, bf16 fast mode.  The split must cover every edge
exactly once and stay closed under edge reversal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels.cluster import (
    build_cluster_plan,
    build_cluster_split,
    cluster_aggregate,
)


def _sorted_by_pair(r, s, num_nodes, bn=None, bs=None):
    from hyperspace_tpu.kernels import cluster as C

    bn = bn or C._BN
    bs = bs or C._BS
    key = (r // bn).astype(np.int64) * (num_nodes // bs + 1) + s // bs
    o = np.argsort(key, kind="stable")
    return r[o], s[o]


@pytest.mark.parametrize("n,e,f,dtype", [
    (700, 4000, 32, np.float32),
    (700, 4000, 32, "bfloat16"),
    (300, 900, 130, np.float32),   # f > 128 lane padding
    (257, 513, 8, np.float32),     # odd sizes, boundary chunks
])
def test_cluster_aggregate_matches_segment_sum(n, e, f, dtype, rng, interp):
    r = rng.integers(0, n, e).astype(np.int32)
    s = rng.integers(0, n, e).astype(np.int32)
    r, s = _sorted_by_pair(r, s, n)
    w = rng.random(e).astype(np.float32)
    h = rng.standard_normal((n, f)).astype(np.float32)
    if dtype == "bfloat16":
        h = jnp.asarray(h, jnp.bfloat16)
    plan = tuple(jnp.asarray(a)
                 for a in build_cluster_plan(r, s, n))
    got = cluster_aggregate(jnp.asarray(h), jnp.asarray(w), jnp.asarray(r),
                            jnp.asarray(s), plan, n)
    want = jax.ops.segment_sum(
        (jnp.asarray(w)[:, None] * jnp.asarray(h, jnp.float32)[s]), jnp.asarray(r), n)
    tol = 2e-2 if dtype == "bfloat16" else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


def test_empty_receiver_blocks_zeroed(rng, interp):
    # all edges target one block; every other block's tile must come out 0
    n, e, f = 1500, 600, 16
    r = rng.integers(512, 768, e).astype(np.int32)
    s = rng.integers(0, n, e).astype(np.int32)
    r, s = _sorted_by_pair(r, s, n)
    w = np.ones(e, np.float32)
    h = rng.standard_normal((n, f)).astype(np.float32)
    plan = tuple(jnp.asarray(a) for a in build_cluster_plan(r, s, n))
    got = np.asarray(cluster_aggregate(
        jnp.asarray(h), jnp.asarray(w), jnp.asarray(r), jnp.asarray(s),
        plan, n))
    want = np.asarray(jax.ops.segment_sum(
        jnp.asarray(w)[:, None] * jnp.asarray(h)[s], jnp.asarray(r), n))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert np.all(got[:512] == 0) and np.all(got[768:] == 0)


def _toy_graph(n=600, seed=0):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=seed)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    # the production threshold (256) clusters nothing on a toy graph;
    # rebuild with a low threshold so BOTH paths carry edges here
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n, min_pair_edges=8)
    assert 0.1 < g.cluster_split.frac_clustered < 1.0
    return g


def test_mode_aware_cluster_threshold_plumbs_through():
    """graphs.cluster_min_pair_for is the ONE home of the r05 per-mode
    sweep; prepare/split_edges thread it to build_cluster_split (a
    lower threshold must cluster at least as many edges)."""
    from hyperspace_tpu.data import graphs as G

    assert G.cluster_min_pair_for(False) == 256
    assert G.cluster_min_pair_for(True) == 128
    n = 600
    edges, x, _, _ = G.synthetic_hierarchy(num_nodes=n, feat_dim=12, seed=0)
    fracs = {}
    for mp in (8, 64):
        g = G.prepare(edges, n, x, cluster=True, pad_multiple=256,
                      cluster_min_pair=mp)
        fracs[mp] = g.cluster_split.frac_clustered
    assert fracs[8] >= fracs[64]
    assert fracs[8] > 0  # the knob demonstrably reached the split


def test_split_covers_every_edge_once_and_is_symmetric():
    g = _toy_graph()
    sp = g.cluster_split
    mask = g.edge_mask
    want = sorted(zip(g.receivers[mask].tolist(), g.senders[mask].tolist()))
    got = sorted(list(zip(sp.c_recv.tolist(), sp.c_send.tolist()))
                 + list(zip(sp.s_recv[sp.s_wf > 0].tolist(),
                            sp.s_send[sp.s_wf > 0].tolist())))
    assert got == want
    # reversal-closed subsets: each straggler's reverse is a straggler
    strag = {(int(a), int(b)) for a, b in
             zip(sp.s_recv[sp.s_wf > 0], sp.s_send[sp.s_wf > 0])}
    assert all((b, a) in strag for a, b in strag)
    # weights match 1/deg of the right endpoints
    deg = np.maximum(g.deg, 1.0)
    np.testing.assert_allclose(sp.c_wf, 1.0 / deg[sp.c_recv], rtol=1e-6)
    np.testing.assert_allclose(sp.c_wb, 1.0 / deg[sp.c_send], rtol=1e-6)


def test_cluster_two_path_matches_plain_aggregation(rng):
    """cluster_sym_aggregate (XLA twin path) == the mean aggregation the
    layer would otherwise compute, values and gradient."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import cluster_sym_aggregate

    g = _toy_graph()
    dg = G.to_device(g)
    assert dg.cluster is not None
    n = g.num_nodes
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    probe = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))

    w = (g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]).astype(np.float32)

    def f_plain(h):
        msgs = jnp.asarray(w)[:, None] * h[jnp.asarray(g.senders)]
        return jnp.sum(jax.ops.segment_sum(
            msgs, jnp.asarray(g.receivers), n) * probe)

    def f_cluster(h):
        return jnp.sum(cluster_sym_aggregate(h, dg.cluster, n) * probe)

    np.testing.assert_allclose(float(f_cluster(h)), float(f_plain(h)),
                               rtol=1e-5)
    gc = jax.grad(f_cluster)(h)
    gp = jax.grad(f_plain)(h)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gp),
                               rtol=1e-4, atol=1e-6)


def test_hgcconv_cluster_path_matches_default(rng):
    """The same HGCConv params produce the same layer output whether the
    graph carries a cluster split or not."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.gcn import HGCConv
    from hyperspace_tpu.manifolds import Lorentz

    from hyperspace_tpu.data.graphs import synthetic_hierarchy

    n = 600
    edges, x, labels, ncls = synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=0)
    g_plain = G.prepare(edges, n, x, cluster=False, pad_multiple=256)
    g_clust = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    m = Lorentz(1.0)
    pts = m.expmap0(jnp.concatenate(
        [jnp.zeros((n, 1)),
         jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32) * 0.3)],
        axis=1))
    conv = HGCConv(features=8, kind="lorentz")
    params = conv.init(jax.random.PRNGKey(0), pts, G.to_device(g_plain))

    def run(dg):
        out, _ = conv.apply(params, pts, dg)
        return out

    o1 = run(G.to_device(g_plain))
    o2 = run(G.to_device(g_clust))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-5, atol=1e-6)


def test_empty_clustered_set_is_safe(rng, interp):
    """A split where nothing clusters still aggregates correctly: the
    kernel path must not index chunk 0 of a zero-length edge array (it
    returns zeros), and the straggler path carries everything."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import cluster_sym_aggregate
    from hyperspace_tpu.data.graphs import synthetic_hierarchy
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    n = 600
    edges, x, labels, ncls = synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=0)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    # production threshold on a toy graph: nothing reaches 10**6 edges
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n,
        min_pair_edges=10**6)
    assert len(g.cluster_split.c_recv) == 0
    dg = G.to_device(g)
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    out = cluster_sym_aggregate(h, dg.cluster, n)
    w = (g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]).astype(np.float32)
    want = jax.ops.segment_sum(
        jnp.asarray(w)[:, None] * h[jnp.asarray(g.senders)],
        jnp.asarray(g.receivers), n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# --- in-tile attention: cluster_att_fwd / cluster_att_bwd ---------------------


def _symmetric_pair_edges(rng, n, e_half):
    """A reversal-closed random edge set, (rb, sb)-pair-sorted — the
    closure the in-tile backward's involution identities require."""
    u = rng.integers(0, n, e_half).astype(np.int32)
    v = rng.integers(0, n, e_half).astype(np.int32)
    r = np.concatenate([u, v])
    s = np.concatenate([v, u])
    return _sorted_by_pair(r, s, n)


def _att_oracle(h, a_s, a_r, r, s, n, slope=0.2, bound=30.0):
    """Gathered exp/segsum chain — num|den, f32 (the kernel twin)."""
    pre = a_s[s] + a_r[r]
    lam = jnp.where(pre >= 0, pre, slope * pre)
    w = jnp.exp(bound * jnp.tanh(lam / bound))
    w = w.astype(h.dtype).astype(jnp.float32)  # match kernel rounding
    msgs = jnp.concatenate(
        [w[:, None] * h.astype(jnp.float32)[s], w[:, None]], axis=1)
    return jax.ops.segment_sum(msgs, jnp.asarray(r), n)


@pytest.mark.parametrize("n,e,f,dtype", [
    (700, 4000, 32, np.float32),
    (700, 4000, 32, "bfloat16"),
    (300, 900, 130, np.float32),   # f > 128 lane padding
    (257, 513, 8, np.float32),     # odd sizes, boundary chunks
    (300, 900, 128, np.float32),   # f == lane width: den in the ext tile
])
def test_cluster_att_fwd_matches_oracle(n, e, f, dtype, rng, interp):
    from hyperspace_tpu.kernels.cluster import cluster_att_fwd

    r, s = _symmetric_pair_edges(rng, n, e // 2)
    h = rng.standard_normal((n, f)).astype(np.float32)
    if dtype == "bfloat16":
        h = jnp.asarray(h, jnp.bfloat16)
    a_s = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    a_r = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    plan = tuple(jnp.asarray(a) for a in build_cluster_plan(r, s, n))
    got = cluster_att_fwd(jnp.asarray(h), a_s, a_r, jnp.asarray(r),
                          jnp.asarray(s), plan, n)
    want = _att_oracle(jnp.asarray(h), a_s, a_r, r, s, n)
    tol = 3e-2 if dtype == "bfloat16" else 1e-4
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("n,e,f,dtype", [
    (700, 4000, 32, np.float32),
    (700, 4000, 32, "bfloat16"),
    (300, 900, 128, np.float32),   # f == lane width: alpha lanes at 128/129
    (257, 513, 8, np.float32),
])
def test_cluster_att_bwd_matches_vjp_oracle(n, e, f, dtype, rng, interp):
    from hyperspace_tpu.kernels.cluster import cluster_att_bwd

    r, s = _symmetric_pair_edges(rng, n, e // 2)
    h32 = rng.standard_normal((n, f)).astype(np.float32)
    h = jnp.asarray(h32, jnp.bfloat16) if dtype == "bfloat16" \
        else jnp.asarray(h32)
    a_s = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    a_r = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    g_ext = jnp.asarray(rng.standard_normal((n, f + 1)).astype(np.float32))
    plan = tuple(jnp.asarray(a) for a in build_cluster_plan(r, s, n))
    dh, da_s, da_r = cluster_att_bwd(g_ext, h, a_s, a_r, jnp.asarray(r),
                                     jnp.asarray(s), plan, n)
    _, vjp = jax.vjp(
        lambda hh, as_, ar_: _att_oracle(hh, as_, ar_, r, s, n),
        jnp.asarray(h32), a_s, a_r)
    want_dh, want_das, want_dar = vjp(g_ext)
    # bf16 reference is the f32 chain: the kernel's bf16 weight/row-pick
    # rounding leaves ~0.01% of elements off by up to ~0.1 at values of
    # magnitude ~10 (bf16 eps ≈ 0.8%); exactness is proven by f32 cases
    tol = 2e-1 if dtype == "bfloat16" else 1e-3
    np.testing.assert_allclose(np.asarray(dh), np.asarray(want_dh),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(da_s), np.asarray(want_das),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(da_r), np.asarray(want_dar),
                               rtol=tol, atol=tol)


def _toy_graph_att(n=600, seed=0):
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=seed)
    g = G.prepare(edges, n, x, cluster=True, pad_multiple=256)
    g.cluster_split = build_cluster_split(
        g.senders, g.receivers, g.edge_mask, g.deg, n, min_pair_edges=8,
        rev_perm=g.rev_perm)
    assert 0.1 < g.cluster_split.frac_clustered < 1.0
    return g


def test_straggler_involution_is_closed():
    sp = _toy_graph_att().cluster_split
    rl = np.asarray(sp.s_rev_local)
    m = np.asarray(sp.s_mask)
    # an involution that stays inside the straggler set, pairing each
    # edge with its (recv, send)-swapped mirror; padding self-maps
    assert np.all(rl[rl] == np.arange(len(rl)))
    assert np.all(m[rl] == m)
    np.testing.assert_array_equal(sp.s_recv[rl[m]], sp.s_send[m])
    np.testing.assert_array_equal(sp.s_send[rl[m]], sp.s_recv[m])


def test_cluster_att_partial_matches_full_planned(rng):
    """cluster partial (in-tile) + straggler planned partial == the
    full-edge-list planned partial: values and (dh, dα_s, dα_r)."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.nn.scatter import (att_partial_planned,
                                           cluster_att_partial)

    g = _toy_graph_att()
    dg = G.to_device(g)
    dg.cluster.use_att_cluster = True  # toy frac may sit under the gate
    assert dg.cluster.att_ok
    n = g.num_nodes
    h = jnp.asarray(rng.standard_normal((n, 16)).astype(np.float32))
    a_s = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    a_r = jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.7)
    probe = jnp.asarray(rng.standard_normal((n, 17)).astype(np.float32))
    cl = dg.cluster

    def f_split(h, a_s, a_r):
        nd = cluster_att_partial(h, a_s, a_r, cl, n, 0.2)
        nd = nd + att_partial_planned(h, a_s, a_r, cl.s_send, cl.s_recv,
                                      cl.s_rev_local, cl.s_mask,
                                      cl.s_plan, n, None, 0.2)
        return jnp.sum(nd * probe)

    def f_full(h, a_s, a_r):
        return jnp.sum(att_partial_planned(
            h, a_s, a_r, dg.senders, dg.receivers, dg.rev_perm,
            dg.edge_mask, dg.plan, n, None, 0.2) * probe)

    np.testing.assert_allclose(float(f_split(h, a_s, a_r)),
                               float(f_full(h, a_s, a_r)), rtol=1e-5)
    gs = jax.grad(f_split, argnums=(0, 1, 2))(h, a_s, a_r)
    gf = jax.grad(f_full, argnums=(0, 1, 2))(h, a_s, a_r)
    for a, b in zip(gs, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_hgcconv_att_cluster_matches_plain(rng):
    """HGCConv(use_att=True) gives the same output + parameter gradients
    with and without the in-tile cluster attention split."""
    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.manifolds import Lorentz
    from hyperspace_tpu.nn.gcn import HGCConv

    g = _toy_graph_att()
    n = g.num_nodes
    dg_c = G.to_device(g)
    dg_c.cluster.use_att_cluster = True  # toy frac may sit under the gate
    dg_p = dg_c._replace(cluster=None)
    m = Lorentz(1.0)
    pts = m.expmap0(jnp.concatenate(
        [jnp.zeros((n, 1)),
         jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32) * 0.3)],
        axis=1))
    conv = HGCConv(features=8, kind="lorentz", use_att=True)
    params = conv.init(jax.random.PRNGKey(0), pts, dg_p)

    def loss(p, dg):
        out, _ = conv.apply(p, pts, dg)
        return jnp.sum(out * out)

    np.testing.assert_allclose(float(loss(params, dg_c)),
                               float(loss(params, dg_p)), rtol=1e-5)
    gc = jax.grad(loss)(params, dg_c)
    gp = jax.grad(loss)(params, dg_p)
    for kc, kp in zip(jax.tree_util.tree_leaves(gc),
                      jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(kc), np.asarray(kp),
                                   rtol=2e-4, atol=1e-5)
