"""Parity suite for the flash hyperbolic-attention kernel (N7).

Chain of oracles: Pallas kernel (interpret mode) == XLA dense twin ==
nn.attention.lorentz_attention (manifold form) == lorentz_attention_tiled
(the online-softmax scan the kernel implements).  SURVEY.md §4.4.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.kernels import attention as katt
from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import lorentz_attention, lorentz_attention_tiled


def hyperboloid_points(rng, shape, c=1.0, scale=1.0):
    sp = rng.standard_normal(shape) * scale
    t = np.sqrt(1.0 / c + np.sum(sp * sp, axis=-1, keepdims=True))
    return jnp.asarray(np.concatenate([t, sp], axis=-1), jnp.float32)


@pytest.mark.parametrize("c", [1.0, 0.5])
@pytest.mark.parametrize("nq,nk,d", [(16, 16, 8), (40, 72, 5), (300, 520, 9)])
def test_kernel_matches_dense(rng, interp, c, nq, nk, d):
    # (300, 520) forces multi-tile grids in both q and kv
    q = hyperboloid_points(rng, (2, nq, d), c)
    k = hyperboloid_points(rng, (2, nk, d), c)
    v = hyperboloid_points(rng, (2, nk, d), c)
    got = katt.flash_attention(q, k, v, c, beta=0.3, tau=1.5)
    want = lorentz_attention(q, k, v, Lorentz(c), beta=0.3, tau=1.5)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_kernel_matches_tiled_twin(rng, interp):
    c = 1.0
    q = hyperboloid_points(rng, (24, 7), c)
    k = hyperboloid_points(rng, (40, 7), c)
    v = hyperboloid_points(rng, (40, 7), c)
    got = katt.flash_attention(q, k, v, c)
    want = lorentz_attention_tiled(q, k, v, Lorentz(c), block_size=16)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_masked_matches_dense(rng, interp):
    c = 1.0
    q = hyperboloid_points(rng, (2, 24, 6), c)
    k = hyperboloid_points(rng, (2, 40, 6), c)
    v = hyperboloid_points(rng, (2, 40, 6), c)
    mask = jnp.asarray(rng.random((2, 24, 40)) > 0.4)
    got = katt.flash_attention(q, k, v, c, mask=mask)
    want = lorentz_attention(q, k, v, Lorentz(c), mask=mask)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_are_zero(rng, interp):
    c = 1.0
    q = hyperboloid_points(rng, (1, 9, 4), c)
    k = hyperboloid_points(rng, (1, 16, 4), c)
    v = hyperboloid_points(rng, (1, 16, 4), c)
    mask = jnp.ones((1, 9, 16), bool).at[0, 3].set(False)
    got = katt.flash_attention(q, k, v, c, mask=mask)
    want = lorentz_attention(q, k, v, Lorentz(c), mask=mask)
    np.testing.assert_allclose(got[0, 3], np.zeros(5), atol=1e-6)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_per_head_beta_tau(rng, interp):
    """β/τ shaped [h, 1, 1] over q [b, h, N, D] — the HypMultiHeadAttention case."""
    c = 1.0
    q = hyperboloid_points(rng, (2, 3, 16, 6), c)
    k = hyperboloid_points(rng, (2, 3, 16, 6), c)
    v = hyperboloid_points(rng, (2, 3, 16, 6), c)
    beta = jnp.asarray(rng.standard_normal((3, 1, 1)), jnp.float32)
    tau = jnp.asarray(1.0 + rng.random((3, 1, 1)), jnp.float32)
    got = katt.flash_attention(q, k, v, c, beta=beta, tau=tau)
    want = lorentz_attention(q, k, v, Lorentz(c), beta=beta, tau=tau)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_output_on_hyperboloid(rng, interp):
    c = 0.7
    q = hyperboloid_points(rng, (2, 24, 6), c, scale=2.0)
    k = hyperboloid_points(rng, (2, 40, 6), c, scale=2.0)
    v = hyperboloid_points(rng, (2, 40, 6), c, scale=2.0)
    o = katt.flash_attention(q, k, v, c)
    mink = np.sum(np.asarray(o[..., 1:]) ** 2, axis=-1) - np.asarray(o[..., 0]) ** 2
    np.testing.assert_allclose(mink, -1.0 / c, rtol=1e-4)


@pytest.mark.slow
def test_gradients_match_dense(rng):
    c = 1.0
    q = hyperboloid_points(rng, (1, 12, 5), c).astype(jnp.float64)
    k = hyperboloid_points(rng, (1, 20, 5), c).astype(jnp.float64)
    v = hyperboloid_points(rng, (1, 20, 5), c).astype(jnp.float64)

    def loss_kernel(q, k, v, beta, tau):
        return jnp.sum(jnp.tanh(katt.flash_attention(q, k, v, c, beta=beta, tau=tau)))

    def loss_dense(q, k, v, beta, tau):
        return jnp.sum(jnp.tanh(lorentz_attention(q, k, v, Lorentz(c), beta=beta, tau=tau)))

    args = (q, k, v, jnp.float64(0.2), jnp.float64(1.3))
    g1 = jax.grad(loss_kernel, argnums=(0, 1, 2, 3, 4))(*args)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(*args)
    for a_, b_ in zip(g1, g2):
        np.testing.assert_allclose(a_, b_, rtol=1e-8, atol=1e-8)


def test_bf16_inputs(rng, interp):
    c = 1.0
    q = hyperboloid_points(rng, (1, 16, 8), c).astype(jnp.bfloat16)
    k = hyperboloid_points(rng, (1, 32, 8), c).astype(jnp.bfloat16)
    v = hyperboloid_points(rng, (1, 32, 8), c).astype(jnp.bfloat16)
    got = katt.flash_attention(q, k, v, c)
    want = lorentz_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                             v.astype(jnp.float32), Lorentz(c))
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(got.astype(jnp.float32), want, rtol=0.02, atol=0.02)


# --- recomputing flash backward (r04; VERDICT r3 #4) --------------------------


def test_flash_backward_matches_twin(rng, interp):
    """Kernel-path gradients (interpret mode: the Pallas dq/dkv kernels
    actually run) == XLA dense twin, for q/k/v/c/τ, masked and unmasked.
    β is softmax-shift-invariant (dβ ≡ 0 mathematically) so it is
    checked against zero at the twin's own noise scale."""
    c = 1.3
    m = Lorentz(c)
    q = hyperboloid_points(rng, (2, 24, 6), c)
    k = hyperboloid_points(rng, (2, 40, 6), c)
    v = hyperboloid_points(rng, (2, 40, 6), c)
    mask = jnp.asarray(rng.random((2, 24, 40)) > 0.2)
    beta = jnp.asarray(rng.standard_normal((2, 1, 1)), jnp.float32) * 0.3
    tau = jnp.asarray(1.0 + rng.random((2, 1, 1)), jnp.float32)

    for msk in (mask, None):
        def loss_k(q, k, v, c, beta, tau):
            return jnp.sum(katt.flash_attention(
                q, k, v, c, beta=beta, tau=tau, mask=msk) ** 2)

        def loss_t(q, k, v, c, beta, tau):
            mf = None if msk is None else msk.astype(jnp.float32)
            return jnp.sum(katt._t_flash_attention(
                q, k, v, c, beta, tau, mf) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 5))(q, k, v, c, beta, tau)
        gt = jax.grad(loss_t, argnums=(0, 1, 2, 3, 5))(q, k, v, c, beta, tau)
        for a_, b_ in zip(gk, gt):
            a_, b_ = np.asarray(a_, np.float32), np.asarray(b_, np.float32)
            scale = max(float(np.max(np.abs(b_))), 1e-3)
            assert float(np.max(np.abs(a_ - b_))) / scale < 2e-3


def test_flash_backward_never_materializes_scores(monkeypatch):
    """The flash property must hold in BOTH directions: tracing the
    kernel-path gradient at L=4096 (pallas mode — tracing never executes
    TPU code) must produce no [Nq, Nk]-sized intermediate anywhere in
    the jaxpr.  The dense twin would carry a 4096x4096 score matrix."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "pallas")
    L, D = 4096, 8
    q = jax.ShapeDtypeStruct((1, L, D + 1), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(katt.flash_attention(q, k, v, 1.0) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)

    def sizes(jx):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    yield int(np.prod(aval.shape)) if aval.shape else 1
            for param in eqn.params.values():
                for sub in jax.tree_util.tree_leaves(
                        param, is_leaf=lambda x: isinstance(
                            x, jax.extend.core.ClosedJaxpr)):
                    if isinstance(sub, jax.extend.core.ClosedJaxpr):
                        yield from sizes(sub.jaxpr)

    biggest = max(sizes(jaxpr.jaxpr))
    assert biggest < L * L, biggest  # scores would be L*L = 16.8M
