"""Kernel↔twin parity for the rowwise Pallas kernels (SURVEY.md §4.4).

Kernels run through the Pallas interpreter on CPU (HYPERSPACE_KERNELS=
interpret); the oracle is the PoincareBall manifold method at matching f32
precision (same eps policy).  This is the CUDA-vs-CPU parity suite of the
reference family, re-targeted at Mosaic.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels import pointwise as pw
from hyperspace_tpu.manifolds import PoincareBall

from tests.kernels.conftest import ball_points as _ball_points


CURVATURES = [1.0, 0.5, 2.3]
SHAPES = [(4, 2), (40, 10), (130, 7), (9, 128), (17, 200)]



def _check(kernel_out, oracle_out, rtol=2e-4, atol=2e-5):
    # oracle runs the manifold method at the same f32 precision (identical
    # eps policy); tolerance covers log-form vs arctanh transcendentals.
    np.testing.assert_allclose(
        np.asarray(kernel_out), np.asarray(oracle_out, np.float32),
        rtol=rtol, atol=atol)


@pytest.mark.parametrize("c", CURVATURES)
@pytest.mark.parametrize("shape", SHAPES[:3])
def test_mobius_add_parity(interp, rng, c, shape):
    x = _ball_points(rng, shape, c)
    y = _ball_points(rng, shape, c)
    ball = PoincareBall(c)
    _check(pw.mobius_add(x, y, c),
           ball.mobius_add(x, y))


@pytest.mark.parametrize("shape", SHAPES)
def test_expmap_logmap_parity(interp, rng, shape):
    c = 1.3
    ball = PoincareBall(c)
    x = _ball_points(rng, shape, c)
    v = jnp.asarray(rng.standard_normal(shape) * 0.3, jnp.float32)
    y = _ball_points(rng, shape, c, scale=0.5)
    _check(pw.expmap(x, v, c), ball.expmap(x, v))
    _check(pw.logmap(x, y, c), ball.logmap(x, y))
    _check(pw.expmap0(v, c), ball.expmap0(v))
    _check(pw.logmap0(y, c), ball.logmap0(y))


def test_mobius_scalar_mul_parity(interp, rng):
    c = 0.7
    x = _ball_points(rng, (33, 6), c)
    for r in [-1.5, 0.0, 0.5, 3.0]:
        _check(pw.mobius_scalar_mul(r, x, c),
               PoincareBall(c).mobius_scalar_mul(r, x))


def test_ptransp_parity(interp, rng):
    c = 1.0
    x = _ball_points(rng, (21, 5), c)
    y = _ball_points(rng, (21, 5), c, scale=0.6)
    v = jnp.asarray(rng.standard_normal((21, 5)) * 0.4, jnp.float32)
    _check(pw.ptransp(x, y, v, c),
           PoincareBall(c).ptransp(x, y, v))


def test_broadcasting_and_batch_dims(interp, rng):
    c = 1.0
    x = _ball_points(rng, (3, 8, 6), c)
    b = _ball_points(rng, (6,), c, scale=0.2)
    out = pw.mobius_add(x, b, c)
    oracle = PoincareBall(c).mobius_add(x, jnp.broadcast_to(b, x.shape))
    _check(out, oracle)
    assert out.shape == x.shape


@pytest.mark.slow
def test_gradients_flow_through_twin(interp, rng):
    """custom_vjp backward == direct autodiff of the manifold method."""
    c = 1.0
    x = _ball_points(rng, (5, 4), c).astype(jnp.float32)
    v = jnp.asarray(rng.standard_normal((5, 4)) * 0.2, jnp.float32)

    g_kernel = jax.grad(lambda xx: jnp.sum(pw.expmap(xx, v, c) ** 2))(x)
    g_direct = jax.grad(lambda xx: jnp.sum(PoincareBall(c).expmap(xx, v) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_kernel), np.asarray(g_direct),
                               rtol=1e-5, atol=1e-5)

    # curvature gradient (learned-c path, workload 5) is finite and matches
    gc_kernel = jax.grad(lambda cc: jnp.sum(pw.expmap0(v, cc)))(jnp.float32(c))
    gc_direct = jax.grad(lambda cc: jnp.sum(PoincareBall(cc).expmap0(v)))(jnp.float32(c))
    np.testing.assert_allclose(gc_kernel, gc_direct, rtol=1e-5, atol=1e-5)


def test_xla_mode_is_twin(monkeypatch, rng):
    monkeypatch.setenv("HYPERSPACE_KERNELS", "xla")
    c = 1.0
    x = _ball_points(rng, (7, 3), c)
    y = _ball_points(rng, (7, 3), c)
    np.testing.assert_array_equal(
        np.asarray(pw.mobius_add(x, y, c)),
        np.asarray(PoincareBall(c).mobius_add(x, y)))


def test_bf16_inputs_compute_in_f32(interp, rng):
    c = 1.0
    x = _ball_points(rng, (16, 8), c).astype(jnp.bfloat16)
    y = _ball_points(rng, (16, 8), c).astype(jnp.bfloat16)
    out = pw.mobius_add(x, y, c)
    assert out.dtype == jnp.bfloat16
    oracle = PoincareBall(c).mobius_add(
        x.astype(jnp.float32), y.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(oracle), rtol=2e-2, atol=2e-2)
