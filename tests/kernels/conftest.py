"""Shared helpers for the kernel parity suite."""

import numpy as np
import jax.numpy as jnp
import pytest


def ball_points(rng, shape, c, scale=0.8):
    """Points strictly inside the ball of curvature -c (norm < scale/sqrt(c))."""
    v = rng.standard_normal(shape)
    v = v / (1.0 + np.linalg.norm(v, axis=-1, keepdims=True))
    return jnp.asarray(v * scale / np.sqrt(c), jnp.float32)


@pytest.fixture
def interp(monkeypatch):
    """Force Pallas interpreter mode for the test (SURVEY.md §4.4)."""
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
