"""SURVEY.md §4.6: a data-parallel GSPMD train step over the 8-fake-device
mesh must match the single-device run.  Uses product_embed's mesh-aware
step — its batch indices carry real (host, data) sharding constraints, so
XLA compiles an actual gradient all-reduce (unlike a replicated program,
where equality would hold vacuously).  Same PRNG stream both ways, so
only the reduction order differs — float tolerance, not bitwise."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import product_embed as pme
from hyperspace_tpu.parallel.mesh import make_mesh, replicated
from hyperspace_tpu.train.debug import nan_checks


def _cfg(n):
    return pme.ProductEmbedConfig(
        num_nodes=n, factors=(("poincare", 3), ("euclidean", 2)),
        batch_size=64, neg_samples=4, lr_table=0.2, burnin_steps=0)


@pytest.mark.slow
def test_dp_mesh_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    ds = synthetic_tree(depth=3, branching=3)
    cfg = _cfg(ds.num_nodes)
    pairs = jnp.asarray(ds.pairs)
    mesh = make_mesh({"host": 2, "data": 4})

    state1, curv_opt = pme.init_state(cfg, seed=0)
    for _ in range(15):
        state1, loss1 = pme.train_step(cfg, curv_opt, state1, pairs)

    state8, _ = pme.init_state(cfg, seed=0)
    state8 = jax.device_put(state8, replicated(mesh))
    step8 = pme.make_sharded_step(cfg, curv_opt, mesh)
    for _ in range(15):
        state8, loss8 = step8(state8, pairs)

    assert np.isfinite(float(loss1)) and np.isfinite(float(loss8))
    np.testing.assert_allclose(float(loss8), float(loss1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(state8.params.table),
                               np.asarray(state1.params.table),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(state8.params.c_raw),
                               np.asarray(state1.params.c_raw),
                               rtol=1e-5, atol=1e-7)


def test_nan_checks_traps():
    with nan_checks():
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x - 1.0))(jnp.zeros(4))
    # config restored
    assert not jax.config.jax_debug_nans
