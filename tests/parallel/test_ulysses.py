"""Ulysses (all-to-all) sequence parallelism on the 8-fake-device CPU mesh
(SURVEY.md §4.6, §5): must equal dense attention and the ring mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import lorentz_attention
from hyperspace_tpu.parallel.mesh import make_mesh
from hyperspace_tpu.parallel.ring import ring_attention_sharded
from hyperspace_tpu.parallel.ulysses import ulysses_attention_sharded


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"seq": 8})


def _pts(key, m, shape):
    return m.random_normal(key, shape, jnp.float64)


@pytest.mark.parametrize("L,H", [
    (32, 8), pytest.param(64, 16, marks=pytest.mark.slow)])
def test_ulysses_matches_dense(mesh8, L, H):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(0), m, (2, H, L, 7))
    k = _pts(jax.random.PRNGKey(1), m, (2, H, L, 7))
    v = _pts(jax.random.PRNGKey(2), m, (2, H, L, 7))
    dense = lorentz_attention(q, k, v, m, beta=0.2, tau=1.3)
    uly = ulysses_attention_sharded(q, k, v, m, mesh8, "seq", beta=0.2, tau=1.3)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_ulysses_matches_ring(mesh8):
    """The two SP modes are numerically interchangeable (same math)."""
    m = Lorentz(0.7)
    H, L = 8, 24
    q = _pts(jax.random.PRNGKey(3), m, (1, H, L, 5))
    k = _pts(jax.random.PRNGKey(4), m, (1, H, L, 5))
    v = _pts(jax.random.PRNGKey(5), m, (1, H, L, 5))
    uly = ulysses_attention_sharded(q, k, v, m, mesh8, "seq")
    ring = ring_attention_sharded(q, k, v, m, mesh8, "seq")
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                               rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_ulysses_jit_grads_and_manifold(mesh8):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(6), m, (1, 8, 16, 5))

    @jax.jit
    def f(q):
        return ulysses_attention_sharded(q, q, q, m, mesh8, "seq")

    out = f(q)
    assert out.shape == q.shape
    assert float(jnp.max(m.check_point(out))) < 1e-8
    g = jax.grad(lambda q: jnp.sum(f(q)[..., 1:] ** 2))(q)
    assert bool(jnp.isfinite(g).all())


def test_ulysses_rejects_indivisible_heads(mesh8):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(7), m, (1, 6, 16, 5))  # 6 heads, 8 devices
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, q, q, m, mesh8, "seq")


def test_ulysses_with_key_padding_mask_matches_dense(mesh8):
    m = Lorentz(1.0)
    B, H, L, D = 2, 8, 32, 7
    q = _pts(jax.random.PRNGKey(5), m, (B, H, L, D))
    rng = np.random.default_rng(1)
    k_mask = jnp.asarray(rng.random((B, L)) > 0.3)
    dense = lorentz_attention(q, q, q, m, mask=k_mask[:, None, None, :])
    uly = ulysses_attention_sharded(q, q, q, m, mesh8, "seq", k_mask=k_mask)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)
