"""HGCN multi-device training must match single-device (VERDICT r1 #2/#9).

The north-star workload (HGCN LP) trains through
`models/hgcn.make_sharded_step_lp` on dp-only, tp-only and dp×tp meshes
over the 8 virtual CPU devices; each must agree with the plain
`train_step_lp` run — same PRNG stream both ways, so only collective
reduction order differs (float tolerance, not bitwise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.models import hgcn
from hyperspace_tpu.parallel.mesh import make_mesh


def _setup(seed=0):
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=192, feat_dim=12, seed=seed)
    split = G.split_edges(edges, 192, x, seed=seed, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8))
    return cfg, split


def _run_single(cfg, split, steps, train_pos):
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    for _ in range(steps):
        state, loss = hgcn.train_step_lp(
            model, opt, split.graph.num_nodes, state, ga, train_pos)
    return state, loss


def _run_sharded(cfg, split, steps, axes, train_pos):
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    mesh = make_mesh(axes)
    ga = G.to_device(split.graph)
    step, state, ga = hgcn.make_sharded_step_lp(
        model, opt, split.graph.num_nodes, mesh, state, ga)
    for _ in range(steps):
        state, loss = step(state, ga, train_pos)
    return state, loss


@pytest.mark.parametrize("axes", [
    pytest.param({"data": 8}, marks=pytest.mark.slow),
    pytest.param({"data": 1, "model": 8}, marks=pytest.mark.slow),
    # dp×tp — the fast-suite representative.  Red from PR 3 to PR 8
    # under an (incorrect) "partitioner reduction-order drift"
    # diagnosis; PR 9 bisected the real op-level cause: jax 0.4.37
    # GSPMD MISCOMPILES `concatenate` whose operands/consumers are
    # sharded over a subset of a multi-axis mesh's axes — values
    # garbled, not reordered (minimal repro, KEPT xfailed as the bug's
    # documentation: tests/parallel/
    # test_node_sharded.py::test_gspmd_concat_constraint_miscompile).
    # The supervision-pair concat instance was fixed for every mesh by
    # hgcn.split_pair_logits; this legacy pair-sharded path additionally
    # hit the bug through the Lorentz time-coordinate concatenates when
    # tp column-sharding put the model axis on the feature dim — bisect
    # evidence: poincare/euclidean (no time-coord concat) were EXACT on
    # this config, lorentz alone returned garbage (~59 vs 0.54 loss at
    # identical params).  GREEN since every Lorentz lift was rewritten
    # as pad+add (manifolds/lorentz._pad_last / with_time_coordinate,
    # bitwise-pinned by tests/manifolds/test_lorentz_padadd.py) — the
    # xfail that sat here from PR 3 is retired.
    pytest.param({"data": 4, "model": 2}),
    pytest.param({"host": 2, "data": 4}, marks=pytest.mark.slow),
])
def test_sharded_lp_matches_single_device(axes):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, split = _setup()
    steps = 5
    mesh = make_mesh(axes)
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))
    state1, loss1 = _run_single(cfg, split, steps, train_pos)
    stateN, lossN = _run_sharded(cfg, split, steps, axes, train_pos)

    assert np.isfinite(float(loss1)) and np.isfinite(float(lossN))
    np.testing.assert_allclose(float(lossN), float(loss1), rtol=2e-5)
    p1 = jax.tree_util.tree_leaves(state1.params)
    pN = jax.tree_util.tree_leaves(stateN.params)
    for a, b in zip(p1, pN):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-6)


def test_tp_shards_kernels_and_colocates_moments():
    """The TP rule actually shards 2-D kernels over 'model' and gives Adam
    moments the same spec as their parameters."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.parallel.tp import state_shardings, tp_param_shardings

    cfg, split = _setup()
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    mesh = make_mesh({"data": 2, "model": 4})
    psh = tp_param_shardings(state.params, mesh)
    flat = jax.tree_util.tree_flatten_with_path(psh)[0]
    kernel_specs = [s.spec for p, s in flat
                    if "kernel" in str([getattr(e, "key", "") for e in p])]
    assert kernel_specs and all(sp[-1] == "model" for sp in kernel_specs)

    ssh = state_shardings(state, state.params, mesh)
    # moments mirror params: every param spec appears in the opt_state tree
    mu_specs = {str(s.spec) for s in jax.tree_util.tree_leaves(ssh.opt_state)}
    for s in jax.tree_util.tree_leaves(psh):
        assert str(s.spec) in mu_specs


def test_sharded_nc_matches_single_device():
    """NC twin of the LP equivalence: dp×tp sharded step == single device."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=192, feat_dim=12, num_classes=4, seed=0)
    tr, va, te = G.node_split_masks(192, seed=0)
    g = G.prepare(edges, 192, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8), num_classes=ncls)
    lab = jnp.asarray(g.labels)
    mask = jnp.asarray(g.train_mask)

    model, opt, state1 = hgcn.init_nc(cfg, g, seed=0)
    ga1 = G.to_device(g)
    for _ in range(5):
        state1, loss1 = hgcn.train_step_nc(model, opt, state1, ga1, lab, mask)

    model, opt, stateN = hgcn.init_nc(cfg, g, seed=0)
    mesh = make_mesh({"data": 4, "model": 2})
    step, stateN, gaN = hgcn.make_sharded_step_nc(
        model, opt, mesh, stateN, G.to_device(g))
    for _ in range(5):
        stateN, lossN = step(stateN, gaN, lab, mask)

    np.testing.assert_allclose(float(lossN), float(loss1), rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state1.params),
                    jax.tree_util.tree_leaves(stateN.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_sharded_training_checkpoint_resume(tmp_path):
    """Orbax checkpoint/resume of the dp×tp HGCN step: a run interrupted
    at step 3 and resumed must match the uninterrupted 6-step run (the
    sharded state round-trips through the checkpoint with its shardings)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    cfg, split = _setup()
    mesh = make_mesh({"data": 4, "model": 2})
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))

    def fresh():
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        ga = G.to_device(split.graph)
        return hgcn.make_sharded_step_lp(
            model, opt, split.graph.num_nodes, mesh, state, ga)

    # uninterrupted reference
    step, ref_state, ga = fresh()
    for _ in range(6):
        ref_state, loss_ref = step(ref_state, ga, train_pos)

    # interrupted: 3 steps, checkpoint, new process-equivalent restart
    step, state, ga = fresh()
    for _ in range(3):
        state, _ = step(state, ga, train_pos)
    with CheckpointManager(str(tmp_path), async_save=False) as ck:
        ck.save(3, state, force=True)

    step, state2, ga = fresh()
    with CheckpointManager(str(tmp_path), async_save=False) as ck:
        state2, start = ck.restore(state2)
    assert start == 3
    for _ in range(start, 6):
        state2, loss_res = step(state2, ga, train_pos)

    np.testing.assert_allclose(float(loss_res), float(loss_ref), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-6, atol=1e-8)
