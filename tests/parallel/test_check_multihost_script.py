"""The pod-loop smoke lint, run inside the suite: 2-process loopback
train → per-host checkpoint → restore-at-1-process → process-0 export →
serve query (scripts/check_multihost.py is the one implementation —
this test fails the build when it fails, mirroring
tests/serve/test_check_script.py)."""

import importlib.util
import os

import pytest


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_multihost.py")
    spec = importlib.util.spec_from_file_location("check_multihost", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.flaky  # a loaded CI host can starve the 2-process launch
def test_multihost_pod_loop_lint_passes(tmp_path, capsys):
    mod = _load_checker()
    rc = mod.main(str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 0, f"multihost pod-loop lint failed:\n{out}"
    assert "check_multihost OK" in out
    assert "restored at 1 process bitwise" in out
    assert "export parity" in out
