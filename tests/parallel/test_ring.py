"""Ring-attention tests on the 8-fake-device CPU mesh (SURVEY.md §4.6):
the sharded ring must equal dense attention over the gathered sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import lorentz_attention
from hyperspace_tpu.parallel.mesh import make_mesh
from hyperspace_tpu.parallel.ring import ring_attention_sharded


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"seq": 8})


def _pts(key, m, shape):
    return m.random_normal(key, shape, jnp.float64)


@pytest.mark.parametrize("L", [
    32, pytest.param(64, marks=pytest.mark.slow)])
def test_ring_matches_dense(mesh8, L):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(0), m, (2, L, 7))
    k = _pts(jax.random.PRNGKey(1), m, (2, L, 7))
    v = _pts(jax.random.PRNGKey(2), m, (2, L, 7))
    dense = lorentz_attention(q, k, v, m, beta=0.2, tau=1.3)
    ring = ring_attention_sharded(q, k, v, m, mesh8, "seq", beta=0.2, tau=1.3)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_ring_under_jit_compiles_collectives(mesh8):
    """The sharded ring must jit as one program (collectives inside XLA)."""
    m = Lorentz(0.5)
    q = _pts(jax.random.PRNGKey(3), m, (1, 16, 5))

    @jax.jit
    def f(q):
        return ring_attention_sharded(q, q, q, m, mesh8, "seq")

    out = f(q)
    assert out.shape == q.shape
    assert float(jnp.max(m.check_point(out))) < 1e-8
    # grads flow through ppermute
    g = jax.grad(lambda q: jnp.sum(f(q)[..., 1:] ** 2))(q)
    assert bool(jnp.isfinite(g).all())
