"""Ring-attention tests on the 8-fake-device CPU mesh (SURVEY.md §4.6):
the sharded ring must equal dense attention over the gathered sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import lorentz_attention
from hyperspace_tpu.parallel.mesh import make_mesh
from hyperspace_tpu.parallel.ring import ring_attention_sharded


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"seq": 8})


def _pts(key, m, shape):
    return m.random_normal(key, shape, jnp.float64)


@pytest.mark.parametrize("L", [
    32, pytest.param(64, marks=pytest.mark.slow)])
def test_ring_matches_dense(mesh8, L):
    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(0), m, (2, L, 7))
    k = _pts(jax.random.PRNGKey(1), m, (2, L, 7))
    v = _pts(jax.random.PRNGKey(2), m, (2, L, 7))
    dense = lorentz_attention(q, k, v, m, beta=0.2, tau=1.3)
    ring = ring_attention_sharded(q, k, v, m, mesh8, "seq", beta=0.2, tau=1.3)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_ring_under_jit_compiles_collectives(mesh8):
    """The sharded ring must jit as one program (collectives inside XLA)."""
    m = Lorentz(0.5)
    q = _pts(jax.random.PRNGKey(3), m, (1, 16, 5))

    @jax.jit
    def f(q):
        return ring_attention_sharded(q, q, q, m, mesh8, "seq")

    out = f(q)
    assert out.shape == q.shape
    assert float(jnp.max(m.check_point(out))) < 1e-8
    # grads flow through ppermute
    g = jax.grad(lambda q: jnp.sum(f(q)[..., 1:] ** 2))(q)
    assert bool(jnp.isfinite(g).all())


def test_ring_with_key_padding_mask_matches_dense(mesh8):
    """Masked ring == dense attention with the same key-padding mask (the
    long-context path must support padded batches, not just packed ones)."""
    m = Lorentz(1.0)
    L = 32
    q = _pts(jax.random.PRNGKey(4), m, (2, L, 7))
    rng = np.random.default_rng(0)
    k_mask = jnp.asarray(rng.random((2, L)) > 0.3)
    dense = lorentz_attention(q, q, q, m, mask=k_mask[:, None, :])
    ring = ring_attention_sharded(q, q, q, m, mesh8, "seq", k_mask=k_mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)


def test_ring_body_direct_shard_map_unmasked(mesh8):
    """ring_lorentz_attention with k_mask=None must work inside a caller's
    own shard_map (no mask in the loop carry — regression for the
    varying-type carry mismatch)."""
    from functools import partial as fpartial

    from hyperspace_tpu.parallel.mesh import shard_map
    from hyperspace_tpu.parallel.ring import ring_lorentz_attention
    from jax.sharding import PartitionSpec as P

    m = Lorentz(1.0)
    q = _pts(jax.random.PRNGKey(6), m, (2, 32, 7))
    spec = P(None, "seq", None)

    @fpartial(shard_map, mesh=mesh8, in_specs=(spec,), out_specs=spec)
    def run(q):
        return ring_lorentz_attention(q, q, q, m, "seq")

    dense = lorentz_attention(q, q, q, m)
    np.testing.assert_allclose(np.asarray(run(q)), np.asarray(dense),
                               rtol=1e-9, atol=1e-11)


def test_ring_backward_does_not_save_score_tiles(mesh8):
    """The ring loop remats each hop (r04): reverse-mode AD must not
    stack per-hop [Lq_loc, Lk_loc] score tiles across the n ring steps —
    the grad jaxpr may contain nothing of size >= n*Lq_loc*Lk_loc."""
    mesh = mesh8
    n = 8
    L, D = 1024, 8          # Lq_loc = Lk_loc = 128 per device
    m = Lorentz(1.0)
    rng = np.random.default_rng(0)
    sp = rng.standard_normal((1, L, D)).astype(np.float32) * 0.3
    t = np.sqrt(1.0 + np.sum(sp * sp, axis=-1, keepdims=True))
    q = jnp.asarray(np.concatenate([t, sp], axis=-1))

    def loss(q):
        out = ring_attention_sharded(q, q, q, m, mesh, axis="seq")
        return jnp.sum(out[..., 1:] ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss))(q)

    def sizes(jx):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    yield int(np.prod(aval.shape)) if aval.shape else 1
            for param in eqn.params.values():
                for sub in jax.tree_util.tree_leaves(
                        param, is_leaf=lambda x: isinstance(
                            x, jax.extend.core.ClosedJaxpr)):
                    if isinstance(sub, jax.extend.core.ClosedJaxpr):
                        yield from sizes(sub.jaxpr)

    lq = L // n
    biggest = max(sizes(jaxpr.jaxpr))
    assert biggest < n * lq * lq, biggest  # stacked tiles would be 8*128*128
