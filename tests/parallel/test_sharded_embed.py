"""Row-sharded embedding lookup on the 8-fake-device mesh: forward and
VJP must match dense ``table[idx]``, including duplicate indices, and the
gradient must come back in the table's own sharded layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.parallel.mesh import make_mesh
from hyperspace_tpu.parallel.sharded_embed import (
    shard_table,
    sharded_gather,
    table_sharding,
)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"model": 8})


def test_gather_matches_dense(mesh8, rng):
    v, d = 64, 16
    table = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
    ts = shard_table(table, mesh8)
    idx = jnp.asarray(rng.integers(0, v, 33), jnp.int32)
    got = sharded_gather(ts, idx, mesh8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[idx]),
                               rtol=1e-6)


def test_grad_matches_dense_with_duplicates(mesh8, rng):
    v, d = 32, 8
    table = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
    ts = shard_table(table, mesh8)
    # duplicates on purpose: grads must accumulate per row
    idx = jnp.asarray([0, 5, 5, 31, 17, 5, 0], jnp.int32)
    t = jnp.asarray(rng.standard_normal((len(idx), d)), jnp.float32)

    g_sh = jax.grad(lambda tb: jnp.sum(sharded_gather(tb, idx, mesh8) * t))(ts)
    g_dn = jax.grad(lambda tb: jnp.sum(tb[idx] * t))(table)
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_dn), rtol=1e-6)
    # the cotangent stays in the table's row-sharded layout (shard-local
    # optimizer updates, SURVEY.md §2 parallelism inventory)
    assert g_sh.sharding.is_equivalent_to(table_sharding(mesh8), g_sh.ndim)


def test_jit_train_step_updates_sharded_table(mesh8, rng):
    """One SGD step on a toy distance loss, entirely under jit, with the
    table sharded end to end."""
    v, d = 64, 8
    table = jnp.asarray(rng.standard_normal((v, d)) * 0.1, jnp.float32)
    ts = shard_table(table, mesh8)
    u = jnp.asarray(rng.integers(0, v, 16), jnp.int32)
    w = jnp.asarray(rng.integers(0, v, 16), jnp.int32)

    @jax.jit
    def step(tb):
        def loss(tb):
            eu = sharded_gather(tb, u, mesh8)
            ew = sharded_gather(tb, w, mesh8)
            return jnp.mean(jnp.sum((eu - ew) ** 2, -1))

        val, g = jax.value_and_grad(loss)(tb)
        return tb - 0.1 * g, val

    t1, l1 = step(ts)
    _, l2 = step(t1)
    assert float(l2) < float(l1)
    assert t1.sharding.is_equivalent_to(table_sharding(mesh8), t1.ndim)


def test_negative_and_oob_indices_match_dense(mesh8, rng):
    """Dense semantics: negatives wrap, out-of-range clamps to last row."""
    v, d = 64, 4
    table = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
    ts = shard_table(table, mesh8)
    idx = jnp.asarray([-1, -64, 63, 64, 1000], jnp.int32)
    got = sharded_gather(ts, idx, mesh8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[idx]),
                               rtol=1e-6)


def test_indivisible_rows_rejected(mesh8):
    with pytest.raises(ValueError, match="divisible"):
        shard_table(jnp.zeros((30, 4)), mesh8)
