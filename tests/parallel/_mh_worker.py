"""Loopback multi-host worker (SURVEY.md §4.6): one process of an
N-process DP training job over a ``host × data`` mesh.

Trains a tiny least-squares model with SGD, checkpointing every
``--ckpt-every`` steps; ``--crash-at S`` makes this process die abruptly
(os._exit) right after the step-S checkpoint commits — the fault half of
the restart-from-checkpoint drill.  Process 0 prints the final params as
one JSON line prefixed ``RESULT``.

Run by tests/parallel/test_multihost.py; also runnable by hand:

    python tests/parallel/_mh_worker.py --pid 0 --nprocs 2 --port 9731 \
        --workdir /tmp/mh &
    python tests/parallel/_mh_worker.py --pid 1 --nprocs 2 --port 9731 \
        --workdir /tmp/mh
"""

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--ckpt-every", type=int, default=2)
    ap.add_argument("--crash-at", type=int, default=0)  # 0 = never
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--hgcn", action="store_true",
                    help="train the sharded HGCN LP step instead of the "
                         "least-squares toy (north-star workload over DCN)")
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    from hyperspace_tpu.parallel import multihost as mh

    mh.initialize(f"127.0.0.1:{args.port}", args.nprocs, args.pid,
                  local_device_count=2)

    if args.hgcn:
        return run_hgcn(args, mh)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hyperspace_tpu.parallel.mesh import multihost_mesh
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    mesh = multihost_mesh({"data": 2})
    repl = NamedSharding(mesh, P())
    batch_spec = P(("host", "data"))

    # fixed global problem; each process feeds only its own row slice
    rng = np.random.default_rng(0)
    xh = rng.standard_normal((16, 4)).astype(np.float32)
    yh = (xh @ np.asarray([1.0, -2.0, 3.0, 0.5], np.float32)).astype(np.float32)
    rows = 16 // args.nprocs
    sl = slice(args.pid * rows, (args.pid + 1) * rows)
    xg = mh.host_local_to_global(xh[sl], mesh, batch_spec)
    yg = mh.host_local_to_global(yh[sl], mesh, batch_spec)

    opt = optax.sgd(0.2)
    params = jnp.zeros(4, jnp.float32)
    state = {"params": params, "opt": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    state = jax.device_put(state, repl)

    @jax.jit
    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(state["params"])
        updates, opt_state = opt.update(g, state["opt"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt": opt_state,
            "step": state["step"] + 1,
        }, loss

    mgr = CheckpointManager(os.path.join(args.workdir, "ckpt"),
                            async_save=False)
    start = 0
    if args.resume:
        latest = mgr.latest_step()
        if latest is not None:
            state, start = mgr.restore(state)

    loss = None
    for i in range(start, args.steps):
        state, loss = train_step(state, xg, yg)
        done = i + 1
        if done % args.ckpt_every == 0:
            mgr.save(done, state)
            mgr.wait()
            mh.sync(f"ckpt-{done}")
            if args.crash_at == done and args.pid == args.nprocs - 1:
                os._exit(7)  # simulated host failure, post-commit
    mgr.wait()
    mgr.close()

    final = mh.fetch_replicated(state["params"])
    if args.pid == 0:
        print("RESULT " + json.dumps({
            "params": [float(v) for v in final],
            "loss": float(jax.device_get(loss)) if loss is not None else None,
            "devices": jax.device_count(),
        }), flush=True)
    return 0


def run_hgcn(args, mh) -> int:
    """The north-star workload's library dp step over a real host×data
    mesh: every process builds the same graph deterministically, the
    supervision batch is sharded over (host, data), and the gradient
    all-reduce crosses the process boundary inside XLA (SURVEY.md §3.4:
    Python never communicates across hosts, only collectives do)."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn
    from hyperspace_tpu.parallel.mesh import multihost_mesh

    mesh = multihost_mesh({"data": 2})
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=128, feat_dim=8, seed=0)
    split = G.split_edges(edges, 128, x, seed=0, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=8, hidden_dims=(16, 8))
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))
    step, state, ga = hgcn.make_sharded_step_lp(
        model, opt, 128, mesh, state, ga)
    losses = []
    for _ in range(args.steps):
        state, loss = step(state, ga, train_pos)
        losses.append(float(jax.device_get(loss)))

    # node-sharded path across the same real processes: each process
    # device_puts its addressable shards of the partitioned graph, and
    # the encoder's all-gather crosses the host boundary inside XLA
    model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=1)
    nstep, state2, nsg = hgcn.make_node_sharded_step_lp(
        model2, opt2, 128, mesh, state2, split)
    # per-host data plane: the node-sharded step takes its supervision
    # batch SHARDED, so each host contributes only its own row slice
    # and the global [P, 2] batch is assembled across processes
    train_pos_g = mh.distribute_batch(train_pos, mesh)
    ns_losses = []
    for _ in range(args.steps):
        state2, nloss = nstep(state2, nsg, train_pos_g)
        ns_losses.append(float(jax.device_get(nloss)))
    if args.pid == 0:
        print("RESULT " + json.dumps({
            "losses": losses, "ns_losses": ns_losses,
            "devices": jax.device_count(),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
