"""Loopback multi-host worker (SURVEY.md §4.6): one process of an
N-process DP training job over a ``host × data`` mesh.

Trains a tiny least-squares model with SGD, checkpointing every
``--ckpt-every`` steps; ``--crash-at S`` makes this process die abruptly
(os._exit) right after the step-S checkpoint commits — the fault half of
the restart-from-checkpoint drill.  Process 0 prints the final params as
one JSON line prefixed ``RESULT``.

Run by tests/parallel/test_multihost.py; also runnable by hand:

    python tests/parallel/_mh_worker.py --pid 0 --nprocs 2 --port 9731 \
        --workdir /tmp/mh &
    python tests/parallel/_mh_worker.py --pid 1 --nprocs 2 --port 9731 \
        --workdir /tmp/mh
"""

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--ckpt-every", type=int, default=2)
    ap.add_argument("--crash-at", type=int, default=0)  # 0 = never
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    from hyperspace_tpu.parallel import multihost as mh

    mh.initialize(f"127.0.0.1:{args.port}", args.nprocs, args.pid,
                  local_device_count=2)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hyperspace_tpu.parallel.mesh import multihost_mesh
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    mesh = multihost_mesh({"data": 2})
    repl = NamedSharding(mesh, P())
    batch_spec = P(("host", "data"))

    # fixed global problem; each process feeds only its own row slice
    rng = np.random.default_rng(0)
    xh = rng.standard_normal((16, 4)).astype(np.float32)
    yh = (xh @ np.asarray([1.0, -2.0, 3.0, 0.5], np.float32)).astype(np.float32)
    rows = 16 // args.nprocs
    sl = slice(args.pid * rows, (args.pid + 1) * rows)
    xg = mh.host_local_to_global(xh[sl], mesh, batch_spec)
    yg = mh.host_local_to_global(yh[sl], mesh, batch_spec)

    opt = optax.sgd(0.2)
    params = jnp.zeros(4, jnp.float32)
    state = {"params": params, "opt": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    state = jax.device_put(state, repl)

    @jax.jit
    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(state["params"])
        updates, opt_state = opt.update(g, state["opt"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt": opt_state,
            "step": state["step"] + 1,
        }, loss

    mgr = CheckpointManager(os.path.join(args.workdir, "ckpt"),
                            async_save=False)
    start = 0
    if args.resume:
        latest = mgr.latest_step()
        if latest is not None:
            state, start = mgr.restore(state)

    loss = None
    for i in range(start, args.steps):
        state, loss = train_step(state, xg, yg)
        done = i + 1
        if done % args.ckpt_every == 0:
            mgr.save(done, state)
            mgr.wait()
            mh.sync(f"ckpt-{done}")
            if args.crash_at == done and args.pid == args.nprocs - 1:
                os._exit(7)  # simulated host failure, post-commit
    mgr.wait()
    mgr.close()

    final = mh.fetch_replicated(state["params"])
    if args.pid == 0:
        print("RESULT " + json.dumps({
            "params": [float(v) for v in final],
            "loss": float(jax.device_get(loss)) if loss is not None else None,
            "devices": jax.device_count(),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
