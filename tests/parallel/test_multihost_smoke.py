"""Tier-1 multi-process smoke: a REAL 2-process × 2-virtual-device
``jax.distributed`` group over loopback, fast enough for every CI run
(one bounded group launch; the long kill/restart fault drill stays in
``test_multihost.py`` behind the ``slow`` marker).

Runs ``hyperspace_tpu.benchmarks.mh_worker --task pipeline`` once and
asserts the full pod story against its RESULT: group formation, the
per-host data plane (each process's addressable shards of the
assembled global batch hold exactly its owned rows — verified inside
the workers), bit-identical replicas across processes (digest exchange
behind a coordination barrier), the per-host-owned table checkpoint
with its process-0 manifest commit, and the process-0-gated artifact
export — then restores the 2-host checkpoint and loads the artifact
in THIS single process, closing the elastic-restore loop.
"""

import hashlib
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER_MOD = "hyperspace_tpu.benchmarks.mh_worker"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    extra = env.get("PYTHONPATH")  # no empty entry (= cwd) when unset
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + (extra.split(os.pathsep) if extra else []))
    return env


def _launch(pid, nprocs, port, workdir, *extra):
    return subprocess.Popen(
        [sys.executable, "-m", _WORKER_MOD, "--pid", str(pid),
         "--nprocs", str(nprocs), "--port", str(port),
         "--workdir", str(workdir), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env())


def _run_group(nprocs, workdir, *extra, timeout=180):
    """Run an nprocs group to completion; return pid-0's RESULT dict."""
    port = _free_port()
    procs = [_launch(p, nprocs, port, workdir, *extra) for p in range(nprocs)]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
        raise AssertionError(
            "multihost group timed out\n" + "\n".join(outs))
    for pr, out in zip(procs, outs):
        assert pr.returncode == 0, f"worker failed:\n{out}"
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
    raise AssertionError("no RESULT line\n" + "\n".join(outs))


@pytest.fixture(scope="module")
def smoke(tmp_path_factory):
    """ONE 2-process pipeline run shared by every assertion below —
    the launch (not the checks) is the expensive part."""
    wd = tmp_path_factory.mktemp("mh_smoke")
    return _run_group(2, wd, "--task", "pipeline", "--steps", "3")


@pytest.mark.flaky  # a loaded CI host can starve the subprocess launch
def test_two_process_group_trains(smoke):
    assert smoke["processes"] == 2
    assert smoke["devices"] == 2  # per-process local devices
    losses = smoke["losses"]
    assert len(losses) == 3 and np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # descended


def test_data_plane_owns_disjoint_rows(smoke):
    """Each process assembled the global batch from only its own rows
    (asserted shard-by-shard inside the workers; the RESULT reports
    process 0's view)."""
    plane = smoke["data_plane"]
    assert plane["local_rows"] == [0, plane["batch_rows"] // 2]
    assert plane["local_shards"] == 2


def test_per_host_checkpoint_commits_and_restores_elastically(smoke):
    """The 2-host checkpoint (one shard item per host + process-0
    manifest) restores in THIS 1-process context, bit-identical to the
    table the workers trained."""
    from hyperspace_tpu.parallel import host_table as HT

    names = set(os.listdir(smoke["ckpt_dir"]))
    assert {"shard_00000.npy", "shard_00001.npy", HT.MANIFEST} <= names
    t = HT.HostEmbedTable.load_sharded(smoke["ckpt_dir"], shards=1)
    assert t.num_rows == smoke["num_rows"]
    sha = hashlib.sha256(
        np.ascontiguousarray(t.to_array()).tobytes()).hexdigest()
    assert sha == smoke["table_sha"]
    # per-host read path: process 0's owned range, read directly
    lo, hi = smoke["owned_rows_p0"]
    rows = HT.load_rows(smoke["ckpt_dir"], lo, hi)
    np.testing.assert_array_equal(rows, t.to_array()[lo:hi])


def test_export_is_single_committed_artifact(smoke):
    """Process-0-gated export: one committed artifact, loadable here,
    with the fingerprint every process agreed on."""
    from hyperspace_tpu.serve.artifact import is_committed, load_artifact

    assert is_committed(smoke["export_dir"])
    art = load_artifact(smoke["export_dir"])
    assert art.fingerprint == smoke["fingerprint"]
    assert art.table.shape[0] == smoke["num_rows"]
