"""Multi-host DP over loopback processes (SURVEY.md §4.6) and the
kill-one-host → restart-from-checkpoint fault drill (SURVEY.md §5
"Failure detection / elastic recovery").

Spawns real OS processes each running tests/parallel/_mh_worker.py with
``jax.distributed`` over 127.0.0.1 (2 processes × 2 virtual CPU devices
= a 2×2 host×data mesh), so the cross-process collective path — the
TPU-native stand-in for the reference's NCCL group — is exercised for
real, not simulated.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_mh_worker.py")

# real OS-process spawns + distributed init: inherently slow (>1 min total)
pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    extra = env.get("PYTHONPATH")  # no empty entry (= cwd) when unset
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + (extra.split(os.pathsep) if extra else []))
    return env


def _launch(pid, nprocs, port, workdir, *extra):
    return subprocess.Popen(
        [sys.executable, _WORKER, "--pid", str(pid), "--nprocs", str(nprocs),
         "--port", str(port), "--workdir", str(workdir), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env())


def _run_group(nprocs, workdir, *extra, timeout=240):
    """Run an nprocs group to completion; return pid-0's RESULT dict."""
    port = _free_port()
    procs = [_launch(p, nprocs, port, workdir, *extra) for p in range(nprocs)]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
        raise AssertionError(
            "multihost group timed out\n" + "\n".join(outs))
    for pr, out in zip(procs, outs):
        assert pr.returncode == 0, f"worker failed:\n{out}"
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
    raise AssertionError("no RESULT line\n" + "\n".join(outs))


@pytest.fixture(scope="module")
def ref_result(tmp_path_factory):
    """Uninterrupted 2-process run — the drill's ground truth."""
    wd = tmp_path_factory.mktemp("mh_ref")
    return _run_group(2, wd, "--steps", "6", "--ckpt-every", "2")


def test_two_process_dp_trains(ref_result):
    assert ref_result["devices"] == 4  # 2 procs × 2 virtual devices
    assert ref_result["loss"] < 1.0    # descended from ~14 at w=0
    assert np.all(np.isfinite(ref_result["params"]))


def test_single_process_matches_two_process(ref_result, tmp_path):
    res1 = _run_group(1, tmp_path, "--steps", "6", "--ckpt-every", "2")
    np.testing.assert_allclose(res1["params"], ref_result["params"],
                               rtol=1e-5, atol=1e-6)


def test_kill_one_host_restart_from_checkpoint(ref_result, tmp_path):
    """The SURVEY.md §5 recovery model, end to end: process 1 dies after
    the step-4 checkpoint commits; the survivor is torn down (the cluster
    manager's job); both restart with --resume and must reproduce the
    uninterrupted run exactly."""
    port = _free_port()
    procs = [_launch(p, 2, port, tmp_path, "--steps", "6", "--ckpt-every",
                     "2", "--crash-at", "4") for p in range(2)]
    try:
        out1, _ = procs[1].communicate(timeout=240)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
            pr.wait()
        raise AssertionError("victim hung instead of crashing")
    assert procs[1].returncode == 7, f"victim did not crash as planned:\n{out1}"
    # survivor hangs on the next collective — failure detection kills it
    try:
        procs[0].communicate(timeout=10)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].wait()

    resumed = _run_group(2, tmp_path, "--steps", "6", "--ckpt-every", "2",
                         "--resume")
    np.testing.assert_allclose(resumed["params"], ref_result["params"],
                               rtol=1e-6, atol=1e-7)


def test_two_process_hgcn_sharded_step(tmp_path):
    """The north-star workload's library dp step (make_sharded_step_lp)
    trains over a real 2-process host×data mesh — the gradient all-reduce
    crosses the process boundary inside XLA."""
    res = _run_group(2, tmp_path, "--steps", "5", "--hgcn")
    assert res["devices"] == 4
    losses = res["losses"]
    assert len(losses) == 5 and np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # the node-sharded encoder path over the same real processes
    ns = res["ns_losses"]
    assert len(ns) == 5 and np.all(np.isfinite(ns))
    assert ns[-1] < ns[0]
