"""Per-host row ownership of the host table (the pod data plane).

`multihost.process_row_range` carves the global row space into disjoint
near-equal per-process ranges (the SAME split convention as
`host_table._shard_bounds`); `host_table.save_owned_rows` has each
process write only its owned range (one flat .npy file per host — a
per-host-private codec, since Orbax's numpy handler only writes data on
global process 0) plus a process-0 manifest commit, keeping
`save_sharded`'s bounds contract — so a checkpoint written at ANY
process count restores at any other, bit-identically per row.  These tests exercise the whole surface in one process by
passing explicit (index, count) pairs — the real 2-process drill lives
in tests/parallel/test_multihost_smoke.py and scripts/check_multihost.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.parallel import host_table as HT
from hyperspace_tpu.parallel import multihost as mh
from hyperspace_tpu.parallel.host_table import HostEmbedTable


@pytest.mark.parametrize("num_rows,count", [
    (10, 1), (10, 3), (7, 7), (8, 3), (1000, 4), (5, 8)])
def test_process_row_range_disjoint_and_covering(num_rows, count):
    ranges = [mh.process_row_range(num_rows, i, count) for i in range(count)]
    # contiguous, ordered, disjoint, covering
    assert ranges[0][0] == 0 and ranges[-1][1] == num_rows
    for (alo, ahi), (blo, bhi) in zip(ranges, ranges[1:]):
        assert ahi == blo and alo <= ahi and blo <= bhi
    # near-equal: sizes differ by at most one row
    sizes = [hi - lo for lo, hi in ranges]
    assert max(sizes) - min(sizes) <= 1
    # same convention as the table's own shard split
    assert [lo for lo, _ in ranges] == list(
        HT._shard_bounds(num_rows, count)[:-1])


def test_process_row_range_rejects_bad_index():
    with pytest.raises(ValueError, match="out of range"):
        mh.process_row_range(10, 3, 3)


@pytest.mark.parametrize("writer_count,reader_shards", [
    (2, 1), (1, 2), (2, 3), (3, 2), (4, 1)])
def test_save_owned_restores_elastically(tmp_path, writer_count,
                                         reader_shards):
    """A checkpoint written cooperatively by N simulated processes is
    bit-identical when restored at ANY shard count — and identical to
    what save_sharded would have written."""
    rng = np.random.default_rng(3)
    arr = rng.standard_normal((37, 5)).astype(np.float32)
    table = HostEmbedTable.from_array(arr, shards=2)

    d = tmp_path / "owned"
    barriers = []
    for pi in range(writer_count):  # every "process" runs the same call
        HT.save_owned_rows(table, str(d), process_index=pi,
                           process_count=writer_count,
                           barrier=lambda: barriers.append(1))
    assert len(barriers) == 2 * writer_count  # pre-commit + post-commit

    back = HostEmbedTable.load_sharded(str(d), shards=reader_shards)
    assert back.num_shards == reader_shards
    assert back.to_array().tobytes() == arr.tobytes()


def test_save_owned_manifest_written_only_by_process_zero(tmp_path):
    d = tmp_path / "partial"
    rng = np.random.default_rng(4)
    table = HostEmbedTable.from_array(
        rng.standard_normal((12, 3)).astype(np.float32))
    # process 1 alone: shard file lands, NO manifest → not committed
    HT.save_owned_rows(table, str(d), process_index=1, process_count=2)
    assert (d / "shard_00001.npy").exists()
    assert not (d / HT.MANIFEST).exists()
    with pytest.raises(FileNotFoundError):
        HostEmbedTable.load_sharded(str(d))
    # process 0 joins: manifest appears, checkpoint is live
    HT.save_owned_rows(table, str(d), process_index=0, process_count=2)
    assert (d / HT.MANIFEST).exists()


def test_load_rows_reads_only_owned_range(tmp_path):
    rng = np.random.default_rng(5)
    arr = rng.standard_normal((31, 4)).astype(np.float32)
    d = tmp_path / "t"
    HostEmbedTable.from_array(arr, shards=3).save_sharded(str(d))

    for count in (1, 2, 4):
        for pi in range(count):
            lo, hi = mh.process_row_range(31, pi, count)
            got = HT.load_rows(str(d), lo, hi)
            assert got.tobytes() == arr[lo:hi].tobytes()
    with pytest.raises(ValueError, match="out of range"):
        HT.load_rows(str(d), 5, 40)


def test_local_batch_shards_cover_batch():
    """Simulated per-process batch shards are disjoint rows of the
    host-identical batch and re-concatenate to it exactly."""
    batch = {"x": np.arange(24).reshape(12, 2), "y": np.arange(12)}
    for count in (1, 2, 3, 4):
        parts = [jax.tree_util.tree_map(
            lambda a, i=i: mh.local_batch_rows(a, i, count), batch)
            for i in range(count)]
        for key in batch:
            cat = np.concatenate([p[key] for p in parts], axis=0)
            assert cat.tobytes() == batch[key].tobytes()


def test_distribute_batch_single_process_matches_device_put():
    from hyperspace_tpu.parallel.mesh import batch_sharding, make_mesh

    mesh = make_mesh({"data": -1})
    x = jnp.arange(32.0).reshape(8, 4)
    out = mh.distribute_batch({"x": x}, mesh)["x"]
    assert out.sharding == batch_sharding(mesh, 2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_distribute_batch_rejects_indivisible(monkeypatch):
    from hyperspace_tpu.parallel.mesh import make_mesh

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="pad the batch"):
        mh.local_batch_shards({"x": np.zeros((7, 3))})


def test_sharded_prefetcher_single_process_orders_and_shards():
    """ShardedHostPrefetcher at world size 1: same ordering contract as
    HostPrefetcher, leaves land batch-sharded on the mesh."""
    from hyperspace_tpu.data.prefetch import ShardedHostPrefetcher
    from hyperspace_tpu.parallel.mesh import batch_sharding, make_mesh

    mesh = make_mesh({"data": -1})

    def make(i):
        return {"x": np.full((8, 2), float(i), np.float32)}

    with ShardedHostPrefetcher(make, mesh, depth=2) as pf:
        for i in range(5):
            b = pf.next()
            assert b["x"].sharding == batch_sharding(mesh, 2)
            assert float(np.asarray(b["x"])[0, 0]) == float(i)


def test_sharded_prefetcher_propagates_worker_error():
    from hyperspace_tpu.data.prefetch import ShardedHostPrefetcher
    from hyperspace_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": -1})

    def boom(i):
        raise IOError("batch source died")

    with ShardedHostPrefetcher(boom, mesh, depth=1) as pf:
        with pytest.raises(RuntimeError, match="worker failed"):
            pf.next()
