"""Node-sharded HGCN training (VERDICT r2 next #1).

The point of this file is twofold: (a) the node-sharded step computes the
SAME training trajectory as the single-device step, and (b) — the part r2
showed was missing — the mesh actually *divides* the work: compiled
per-device FLOPs and HBM bytes at dp=8 must drop to a fraction of the
single-device step, not stay ~95% like the pair-sharded step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.models import hgcn
from hyperspace_tpu.parallel.mesh import make_mesh
from hyperspace_tpu.parallel import node_shard as NS


def _setup(num_nodes=256, seed=0):
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=num_nodes, feat_dim=12, num_classes=4, seed=seed)
    split = G.split_edges(edges, num_nodes, x, seed=seed, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8))
    return cfg, split, (edges, x, labels, ncls)


# --- host-side partition invariants ------------------------------------------


def test_partition_covers_every_edge_once():
    _, split, _ = _setup()
    g = split.graph
    ndev = 4
    # halo=False: this test checks the GLOBAL-id layout invariants (the
    # halo layout rewrites senders to extended-local ids)
    hp = NS.partition_graph(g, ndev, halo=False)
    # real (sender, receiver) multiset must be preserved exactly
    mask = g.edge_mask
    want = sorted(zip(g.receivers[mask].tolist(), g.senders[mask].tolist()))
    got = []
    for k in range(ndev):
        real = hp.w_fwd[k] > 0
        got += list(zip((hp.recv[k][real] + k * hp.n_shard).tolist(),
                        hp.senders[k][real].tolist()))
    assert sorted(got) == want


def test_partition_receivers_local_sorted_and_weights():
    _, split, _ = _setup()
    g = split.graph
    hp = NS.partition_graph(g, 4, halo=False)  # global-id layout
    deg = np.maximum(g.deg, 1.0)
    for k in range(4):
        r = hp.recv[k]
        assert np.all(np.diff(r) >= 0), "local receivers must stay sorted"
        assert np.all(r >= 0) and np.all(r < hp.n_shard)
        real = hp.w_fwd[k] > 0
        glob_r = r[real] + k * hp.n_shard
        np.testing.assert_allclose(hp.w_fwd[k][real], 1.0 / deg[glob_r],
                                   rtol=1e-6)
        np.testing.assert_allclose(hp.w_bwd[k][real],
                                   1.0 / deg[hp.senders[k][real]], rtol=1e-6)


def test_padded_plan_items_are_inert(interp_kernels):
    """The [ndev, T] plan rows are padded with (last block, last chunk,
    first=0) items; the Pallas kernel must treat them as exact no-ops."""
    _, split, _ = _setup()
    hp = NS.partition_graph(split.graph, 4)
    for k in range(4):
        vals = np.zeros((hp.recv.shape[1], 8), np.float32)
        real = hp.w_fwd[k] > 0
        vals[real] = np.random.default_rng(k).standard_normal(
            (int(real.sum()), 8)).astype(np.float32)
        plan = tuple(jnp.asarray(p[k]) for p in hp.plan)
        got = hgcn.graph_data  # noqa: F841  (keep import surface stable)
        from hyperspace_tpu.kernels.segment import csr_segment_sum

        out = csr_segment_sum(jnp.asarray(vals), jnp.asarray(hp.recv[k]),
                              plan, hp.n_shard)
        want = jax.ops.segment_sum(jnp.asarray(vals),
                                   jnp.asarray(hp.recv[k]), hp.n_shard)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


@pytest.fixture
def interp_kernels(monkeypatch):
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")


# --- aggregation equivalence --------------------------------------------------


def _mesh_or_skip(axes):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(axes)


@pytest.mark.parametrize("axes", [
    {"data": 8},
    pytest.param({"host": 2, "data": 4}, marks=pytest.mark.slow),
])
def test_aggregate_matches_segment_sum(axes):
    mesh = _mesh_or_skip(axes)
    _, split, _ = _setup()
    g = split.graph
    nsg = NS.shard_graph(g, mesh)
    n_pad = nsg.x.shape[0]
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((n_pad, 16)).astype(np.float32))

    out = node_agg = NS.node_sharded_aggregate(h, nsg)
    # oracle: plain masked mean aggregation on the unsharded layout
    w = g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]
    msgs = np.asarray(w)[:, None] * np.asarray(h)[g.senders]
    want = jax.ops.segment_sum(jnp.asarray(msgs, jnp.float32),
                               jnp.asarray(g.receivers), g.num_nodes)
    np.testing.assert_allclose(np.asarray(out)[: g.num_nodes],
                               np.asarray(want), rtol=1e-5, atol=1e-5)
    assert np.asarray(node_agg).shape == (n_pad, 16)


def test_aggregate_gradient_matches_dense(rng):
    """d/dh of a scalar of the sharded aggregation == the dense jacobian
    path computed on the unsharded layout (the involution backward)."""
    mesh = _mesh_or_skip({"data": 8})
    _, split, _ = _setup(num_nodes=192)
    g = split.graph
    nsg = NS.shard_graph(g, mesh)
    n_pad = nsg.x.shape[0]
    h0 = jnp.asarray(rng.standard_normal((n_pad, 8)).astype(np.float32))
    probe = jnp.asarray(rng.standard_normal((n_pad, 8)).astype(np.float32))

    def f_sharded(h):
        return jnp.sum(NS.node_sharded_aggregate(h, nsg) * probe)

    w = jnp.asarray(
        (g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]).astype(np.float32))
    recv = jnp.asarray(g.receivers)
    send = jnp.asarray(g.senders)

    def f_dense(h):
        msgs = w[:, None] * h[send]
        out = jax.ops.segment_sum(msgs, recv, g.num_nodes)
        return jnp.sum(out * probe[: g.num_nodes])

    gs = jax.grad(f_sharded)(h0)
    gd = jax.grad(f_dense)(h0)  # padded rows get zero grad naturally
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd),
                               rtol=1e-4, atol=1e-5)


# --- full train-step equivalence ----------------------------------------------


@pytest.mark.parametrize("axes", [
    {"data": 8},
    # dp×tp: red from PR 3 to PR 8 under an (incorrect) "partitioner
    # reduction-order drift" diagnosis.  PR 9 root-caused the real
    # op-level cause — jax 0.4.37 GSPMD miscompiles `concatenate` under
    # a subset-of-axes sharding constraint (see
    # test_gspmd_concat_constraint_miscompile below) — and the LP step
    # now avoids the pattern (hgcn.split_pair_logits), so dp×tp is
    # exact again and gates like every other mesh.
    {"data": 4, "model": 2},
])
def test_node_sharded_lp_matches_single_device(axes):
    mesh = _mesh_or_skip(axes)
    cfg, split, _ = _setup(num_nodes=192)
    n = split.graph.num_nodes
    steps = 3
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    for _ in range(steps):
        state, loss_single = hgcn.train_step_lp(
            model, opt, n, state, ga, train_pos)

    model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
    step, state2, nsg = hgcn.make_node_sharded_step_lp(
        model2, opt2, n, mesh, state2, split)
    for _ in range(steps):
        state2, loss_sharded = step(state2, nsg, train_pos)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=1e-4, atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        state.params, state2.params)


@pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.37 GSPMD miscompiles concatenate under a "
           "subset-of-axes sharding constraint on a multi-axis mesh — "
           "the minimal repro of the bug that held the dp×tp "
           "equivalence tests red from PR 3 to PR 8; expected to PASS "
           "(and this xfail to become an xpass) on a jax whose "
           "partitioner assembles the concat correctly")
def test_gspmd_concat_constraint_miscompile():
    """Reduced repro of the op-level root cause (PR 9 bisect): on a
    dp×tp mesh, `concatenate([with_sharding_constraint(a, P(("data",),
    None)), b])` returns GARBLED VALUES — the model-axis sub-shard read
    with full-width strides (got[i] == [want[2i][0], want[2i+1][0]]) —
    not a reduction reorder.  dp-only meshes compile the same program
    correctly.  The production LP step dodges the pattern entirely
    (hgcn.split_pair_logits); this test documents the jax bug so a
    fixed jax shows up as an xpass."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.parallel.mesh import batch_sharding, replicated

    mesh = make_mesh({"data": 4, "model": 2})
    bsh = batch_sharding(mesh, ndim=2)
    a = jnp.asarray(np.arange(480 * 2).reshape(480, 2))
    b = jnp.asarray(10_000 + np.arange(1920 * 2).reshape(1920, 2))
    want = np.concatenate([np.asarray(a), np.asarray(b)], axis=0)

    def f(a, b):
        a = jax.lax.with_sharding_constraint(a, bsh)
        return jnp.concatenate([a, b], axis=0)

    got = np.asarray(jax.jit(f, out_shardings=replicated(mesh))(a, b))
    np.testing.assert_array_equal(got, want)


def test_node_sharded_nc_matches_single_device():
    mesh = _mesh_or_skip({"data": 8})
    _, _, (edges, x, labels, ncls) = _setup(num_nodes=192)
    tr, va, te = G.node_split_masks(192, seed=0)
    g = G.prepare(edges, 192, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8), num_classes=ncls)
    steps = 3

    model, opt, state = hgcn.init_nc(cfg, g, seed=0)
    ga = G.to_device(g)
    lab, msk = jnp.asarray(g.labels), jnp.asarray(g.train_mask)
    for _ in range(steps):
        state, loss_single = hgcn.train_step_nc(model, opt, state, ga, lab, msk)

    model2, opt2, state2 = hgcn.init_nc(cfg, g, seed=0)
    step, state2, nsg, lab_p, msk_p = hgcn.make_node_sharded_step_nc(
        model2, opt2, mesh, state2, g)
    for _ in range(steps):
        state2, loss_sharded = step(state2, nsg, lab_p, msk_p)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=1e-4, atol=1e-5)


def test_node_sharded_attention_matches_single_device():
    """GAT-style attention through the node-sharded path: the receiver
    partition keeps the segment softmax shard-local, so the trajectory
    must match the single-device attention step."""
    mesh = _mesh_or_skip({"data": 8})
    _, split, _ = _setup(num_nodes=192)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8), use_att=True)
    n = split.graph.num_nodes
    steps = 3
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    for _ in range(steps):
        state, loss_single = hgcn.train_step_lp(
            model, opt, n, state, ga, train_pos)

    model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
    step, state2, nsg = hgcn.make_node_sharded_step_lp(
        model2, opt2, n, mesh, state2, split)
    for _ in range(steps):
        state2, loss_sharded = step(state2, nsg, train_pos)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=1e-4, atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5),
        state.params, state2.params)


# --- the scaling assertion (the r2 gap) ---------------------------------------


@pytest.mark.slow
def test_per_device_cost_scales_down():
    """dp=8 must leave ≤35% of the single-device FLOPs and bytes per
    device (r2's pair-sharded step left 95%/85% — the whole point of the
    node-sharded path is to fix this)."""
    mesh = _mesh_or_skip({"data": 8})
    cfg, split, _ = _setup(num_nodes=2048)
    n = split.graph.num_nodes

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))
    single = jax.jit(
        lambda st, g, p: hgcn._lp_step_impl(model, opt, n, st, g, p)
    ).lower(state, ga, train_pos).compile().cost_analysis()

    model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
    step, state2, nsg = hgcn.make_node_sharded_step_lp(
        model2, opt2, n, mesh, state2, split)
    sharded = step.lower(state2, nsg, train_pos).compile().cost_analysis()

    flops_ratio = sharded["flops"] / single["flops"]
    bytes_ratio = sharded["bytes accessed"] / single["bytes accessed"]
    assert flops_ratio <= 0.35, f"per-device flops ratio {flops_ratio:.2f}"
    assert bytes_ratio <= 0.35, f"per-device bytes ratio {bytes_ratio:.2f}"


def test_node_sharded_learned_curvature_and_bf16_messages():
    """The bench dtype policy (bf16 edge messages) and learned per-layer
    curvature both train through the node-sharded step and match the
    single-device trajectory."""
    mesh = _mesh_or_skip({"data": 8})
    _, split, _ = _setup(num_nodes=192)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8), learn_c=True,
                          agg_dtype=jnp.bfloat16)
    n = split.graph.num_nodes
    train_pos = jnp.asarray(hgcn.round_up_pairs(split.train_pos, mesh))

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    for _ in range(3):
        state, loss_single = hgcn.train_step_lp(
            model, opt, n, state, ga, train_pos)

    model2, opt2, state2 = hgcn.init_lp(cfg, split.graph, seed=0)
    step, state2, nsg = hgcn.make_node_sharded_step_lp(
        model2, opt2, n, mesh, state2, split)
    for _ in range(3):
        state2, loss_sharded = step(state2, nsg, train_pos)

    # bf16 messages accumulate f32 on both paths; small reassociation slack
    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=5e-3)
    c0 = state.params["encoder"]["conv0"]["c_raw"]
    c1 = state2.params["encoder"]["conv0"]["c_raw"]
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0), rtol=1e-2)


@pytest.mark.slow
def test_per_device_cost_scales_to_v5e16_shape():
    """The v5e-16 projection (BASELINE north star): on a 16-virtual-device
    mesh, compiled per-device cost of the node-sharded step must keep
    falling through dp=16 — <=20% of single-device FLOPs (ideal 6.25%,
    overhead is the per-layer [N, F] all-gather) and monotone in dp.
    Runs scripts/cost_scaling_probe.py in a subprocess because the
    conftest pins this process to 8 virtual devices."""
    import json
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # the probe sets its own device count
    extra = env.get("PYTHONPATH")  # no empty entry (= cwd) when unset
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + (extra.split(os.pathsep) if extra else []))
    out = subprocess.run(
        [sys.executable,
         os.path.join(repo_root, "scripts", "cost_scaling_probe.py"),
         "--ndev", "16", "--num-nodes", "4096", "--reorder", "community"],
        capture_output=True, text=True, env=env, timeout=900, check=True)
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    ratios = [(int(k), v["flops_ratio"], v["bytes_ratio"])
              for k, v in sorted(rec["dp"].items(), key=lambda kv: int(kv[0]))]
    assert ratios[0][0] == 1 and 0.9 <= ratios[0][1] <= 1.2  # sanity anchor
    flops = [f for _, f, _ in ratios]
    assert flops == sorted(flops, reverse=True), f"not monotone: {ratios}"
    dp16 = rec["dp"]["16"]
    assert dp16["flops_ratio"] <= 0.20, dp16
    # VERDICT r3 #6 / r4 #4: the community locality order cuts the
    # dp=16 byte floor (0.154 unordered r03 → 0.1105 here).  The r05
    # halo study (docs/benchmarks.md "Halo exchange") measured that in
    # the XLA compiled-cost metric NO exchange schedule beats the plain
    # all-gather at the scales this probe can compile — the auto gate
    # therefore only engages a halo when its need-rows win by
    # construction, and the floor below is the all-gather's.
    assert dp16["bytes_ratio"] <= 0.12, dp16


# --- halo exchange (VERDICT r3 #6) --------------------------------------------


def _ordered_setup(num_nodes=256, seed=0):
    """Community-ordered graph: the layout the halo path is built for."""
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=num_nodes, feat_dim=12, num_classes=4, seed=seed)
    edges, x, labels, _ = G.apply_locality_order(edges, x, labels,
                                                 method="community")
    split = G.split_edges(edges, num_nodes, x, seed=seed, pad_multiple=128)
    return split


@pytest.mark.parametrize("kind", ["a2a", "ppermute"])
def test_halo_aggregate_matches_allgather_and_dense(rng, kind):
    """halo aggregation (either schedule) == halo=False == the unsharded
    oracle, values AND gradients (involution backward over the
    collective)."""
    mesh = _mesh_or_skip({"data": 8})
    split = _ordered_setup()
    g = split.graph
    nsg_h = NS.to_device_sharded(NS.partition_graph(g, 8, halo=kind), mesh)
    nsg_a = NS.to_device_sharded(NS.partition_graph(g, 8, halo=False), mesh)
    assert nsg_h.halo and nsg_h.halo_kind == kind and not nsg_a.halo
    n_pad = nsg_h.x.shape[0]
    h = jnp.asarray(rng.standard_normal((n_pad, 16)).astype(np.float32))
    probe = jnp.asarray(rng.standard_normal((n_pad, 16)).astype(np.float32))

    f_h = lambda h: jnp.sum(NS.node_sharded_aggregate(h, nsg_h) * probe)
    f_a = lambda h: jnp.sum(NS.node_sharded_aggregate(h, nsg_a) * probe)
    np.testing.assert_allclose(float(f_h(h)), float(f_a(h)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(jax.grad(f_h)(h)),
                               np.asarray(jax.grad(f_a)(h)),
                               rtol=1e-4, atol=1e-6)
    # dense oracle for the values
    w = g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]
    msgs = np.asarray(w)[:, None] * np.asarray(h)[g.senders]
    want = jax.ops.segment_sum(jnp.asarray(msgs, jnp.float32),
                               jnp.asarray(g.receivers), g.num_nodes)
    out = NS.node_sharded_aggregate(h, nsg_h)
    np.testing.assert_allclose(np.asarray(out)[: g.num_nodes],
                               np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind", ["a2a", "ppermute"])
def test_halo_att_aggregate_matches_allgather(rng, kind):
    mesh = _mesh_or_skip({"data": 8})
    split = _ordered_setup(seed=1)
    g = split.graph
    nsg_h = NS.to_device_sharded(NS.partition_graph(g, 8, halo=kind), mesh)
    nsg_a = NS.to_device_sharded(NS.partition_graph(g, 8, halo=False), mesh)
    assert nsg_h.halo and nsg_h.halo_kind == kind
    n_pad = nsg_h.x.shape[0]
    h = jnp.asarray(rng.standard_normal((n_pad, 16)).astype(np.float32))
    a_s = jnp.asarray(rng.standard_normal(n_pad).astype(np.float32))
    a_r = jnp.asarray(rng.standard_normal(n_pad).astype(np.float32))
    probe = jnp.asarray(rng.standard_normal((n_pad, 16)).astype(np.float32))

    def f(nsg, h, a_s, a_r):
        return jnp.sum(
            NS.node_sharded_att_aggregate(h, a_s, a_r, nsg) * probe)

    np.testing.assert_allclose(float(f(nsg_h, h, a_s, a_r)),
                               float(f(nsg_a, h, a_s, a_r)), rtol=1e-5)
    gh = jax.grad(f, argnums=(1, 2, 3))(nsg_h, h, a_s, a_r)
    ga = jax.grad(f, argnums=(1, 2, 3))(nsg_a, h, a_s, a_r)
    for a, b in zip(gh, ga):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_halo_auto_engages_on_low_cut_graph():
    """'auto' must pick the halo exchange when the static exchange volume
    beats the all-gather — a ring of cliques aligned with the shard
    boundaries (the shape a locality ordering produces at scale)."""
    n, k = 512, 4
    blocks = []
    for b in range(k):
        base = b * (n // k)
        ids = np.arange(base, base + n // k)
        u = np.repeat(ids, 4)
        v = ids[(np.tile(np.arange(4), n // k) + u % 17) % (n // k)]
        blocks.append(np.stack([u, v], 1))
        # a handful of cross-shard edges to the next clique
        nxt = (b + 1) % k * (n // k)
        blocks.append(np.stack([ids[:8], nxt + np.arange(8)], 1))
    edges = np.concatenate(blocks)
    edges = edges[edges[:, 0] != edges[:, 1]]
    x = np.zeros((n, 4), np.float32)
    g = G.prepare(edges, n, x, pad_multiple=128)
    hp = NS.partition_graph(g, k, halo="auto")
    assert hp.halo and hp.send_idx is not None
    # and the picked schedule's estimated volume genuinely beats the
    # all-gather (the gate's own criterion)
    if hp.halo_kind == "a2a":
        assert hp.send_idx.ndim == 3
        assert 2 * k * hp.send_idx.shape[2] <= hp.n_shard * k
    else:
        total = sum(hp.halo_sizes)
        assert hp.send_idx.shape == (k, total)
        assert (2 + len(hp.halo_dists)) * total <= hp.n_shard * k
        assert all(1 <= d < k for d in hp.halo_dists)
    # the ppermute layout exists and is strictly smaller in rows than
    # the pair-max a2a on this shape (the r05 per-distance win)
    hp_p = NS.partition_graph(g, k, halo="ppermute")
    hp_a = NS.partition_graph(g, k, halo="a2a")
    assert hp_p.halo_kind == "ppermute" and hp_a.halo_kind == "a2a"
    assert sum(hp_p.halo_sizes) <= k * hp_a.send_idx.shape[2]


def test_no_cross_shard_edges_never_halos(rng):
    """A fully block-diagonal graph (no cross-shard edges) must not
    engage a halo — the zero-volume 'exchange' would otherwise win the
    auto gate trivially and crash on empty ppermute chains — and the
    aggregation still matches the dense oracle."""
    from hyperspace_tpu.parallel.mesh import make_mesh

    n, k = 256, 4
    blocks = []
    for b in range(k):
        ids = b * (n // k) + np.arange(n // k)
        u = np.repeat(ids, 3)
        v = ids[(np.tile(np.arange(3), n // k) + u % 11) % (n // k)]
        blocks.append(np.stack([u, v], 1))
    edges = np.concatenate(blocks)
    edges = edges[edges[:, 0] != edges[:, 1]]
    x = np.zeros((n, 4), np.float32)
    g = G.prepare(edges, n, x, pad_multiple=128)
    for mode in ("auto", True, "ppermute", "a2a"):
        hp = NS.partition_graph(g, k, halo=mode)
        assert not hp.halo, mode
    mesh = make_mesh({"data": k}, devices=jax.devices()[:k])
    nsg = NS.to_device_sharded(NS.partition_graph(g, k, halo="auto"), mesh)
    h = jnp.asarray(rng.standard_normal((nsg.x.shape[0], 8)).astype(np.float32))
    out = NS.node_sharded_aggregate(h, nsg)
    w = g.edge_mask / np.maximum(g.deg, 1.0)[g.receivers]
    want = jax.ops.segment_sum(
        jnp.asarray(np.asarray(w)[:, None] * np.asarray(h)[g.senders],
                    jnp.float32),
        jnp.asarray(g.receivers), n)
    np.testing.assert_allclose(np.asarray(out)[:n], np.asarray(want),
                               rtol=1e-5, atol=1e-5)
