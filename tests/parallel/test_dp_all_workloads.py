"""Every sampled-minibatch workload's dp step matches single-device
(hybonet, hvae — hgcn and product have their own equivalence suites).

Same PRNG stream both ways → identical sampled batches; only collective
reduction order differs (float tolerance, not bitwise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.parallel.mesh import make_mesh


def _assert_trees_close(a, b, rtol=2e-4, atol=1e-5):
    # atol dominates for near-zero params (Adam's eps floor turns
    # reduction-order noise into large *relative* error on tiny weights)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   rtol=rtol, atol=atol)


def test_hybonet_dp_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.data.text import synthetic_text
    from hyperspace_tpu.models import hybonet

    ds = synthetic_text(num_samples=96, seed=0)
    cfg = hybonet.HyboNetConfig(
        vocab_size=ds.vocab_size, num_classes=ds.num_classes,
        max_len=ds.tokens.shape[1], dim=16, num_heads=2, num_layers=1,
        batch_size=32)
    toks, mask, labels = (jnp.asarray(ds.tokens), jnp.asarray(ds.mask),
                          jnp.asarray(ds.labels))

    model, opt, s1 = hybonet.init_model(cfg, seed=0)
    for _ in range(4):
        s1, l1 = hybonet.train_step_sampled(model, opt, s1, toks, mask, labels)

    model, opt, sN = hybonet.init_model(cfg, seed=0)
    mesh = make_mesh({"data": 8})
    step, sN, (toks, mask, labels) = hybonet.make_sharded_step(
        model, opt, mesh, sN, toks, mask, labels)
    for _ in range(4):
        sN, lN = step(sN, toks, mask, labels)

    np.testing.assert_allclose(float(lN), float(l1), rtol=2e-5)
    _assert_trees_close(s1.params, sN.params)


def test_hvae_dp_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.data.mnist import synthetic_mnist
    from hyperspace_tpu.models import hvae

    ds = synthetic_mnist(num_samples=128, seed=0)
    cfg = hvae.HVAEConfig(image_size=ds.images.shape[1], latent_dim=4,
                          batch_size=32)
    x_all = jnp.asarray(ds.images, cfg.dtype)

    model, opt, s1 = hvae.init_model(cfg, seed=0)
    for _ in range(3):
        s1, l1, _, _ = hvae.train_step_sampled(model, opt, s1, x_all)

    model, opt, sN = hvae.init_model(cfg, seed=0)
    mesh = make_mesh({"host": 2, "data": 4})
    step, sN, x_all = hvae.make_sharded_step(model, opt, mesh, sN, x_all)
    for _ in range(3):
        sN, lN, _, _ = step(sN, x_all)

    np.testing.assert_allclose(float(lN), float(l1), rtol=5e-5)
    _assert_trees_close(s1.params, sN.params)


def test_sharded_step_rejects_indivisible_batch():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from hyperspace_tpu.data.text import synthetic_text
    from hyperspace_tpu.models import hybonet

    ds = synthetic_text(num_samples=24, seed=0)
    cfg = hybonet.HyboNetConfig(
        vocab_size=ds.vocab_size, num_classes=ds.num_classes,
        max_len=ds.tokens.shape[1], dim=16, num_heads=2, num_layers=1,
        batch_size=12)  # not divisible by 8
    model, opt, state = hybonet.init_model(cfg, seed=0)
    with pytest.raises(ValueError, match="not divisible"):
        hybonet.make_sharded_step(model, opt, make_mesh({"data": 8}), state,
                                  jnp.asarray(ds.tokens), jnp.asarray(ds.mask),
                                  jnp.asarray(ds.labels))
