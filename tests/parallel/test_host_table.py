"""Host-resident master table + device hot-row cache
(parallel/host_table.py): host access semantics, the sharded Orbax
round trip's no-full-materialization invariant, and the cache's
hit/evict/write-back protocol."""

import numpy as np
import pytest

from hyperspace_tpu.parallel import host_table as ht
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture
def arr():
    return np.random.default_rng(0).standard_normal(
        (1003, 7)).astype(np.float32)


def test_gather_write_back_match_dense_semantics(arr):
    t = ht.HostEmbedTable.from_array(arr.copy(), shards=4)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1003, 64)
    assert np.array_equal(t.gather(ids), arr[ids])
    rows = rng.standard_normal((64, 7)).astype(np.float32)
    t.write_back(ids, rows)
    ref = arr.copy()
    ref[ids] = rows  # duplicate ids: last write wins in both
    assert np.array_equal(t.to_array(), ref)


def test_iter_chunks_covers_in_order_without_shard_crossing(arr):
    t = ht.HostEmbedTable.from_array(arr.copy(), shards=3)
    blocks = list(t.iter_chunks(100))
    assert all(b.shape[0] <= 100 for _, b in blocks)
    assert np.array_equal(np.concatenate([b for _, b in blocks]), arr)
    starts = [s for s, _ in blocks]
    assert starts == sorted(starts)


def test_build_generates_shard_by_shard():
    fill = lambda start, rows: np.full((rows, 3), start, np.float32)
    t = ht.HostEmbedTable.build(1000, 3, fill, shard_rows=256)
    assert t.num_shards == 4 and t.num_rows == 1000
    # each row carries its shard's start offset — fill saw shard ranges
    assert t.gather([0])[0, 0] == 0.0
    assert t.gather([999])[0, 0] == t._starts[-2]


def test_gather_rejects_out_of_range(arr):
    t = ht.HostEmbedTable.from_array(arr.copy())
    with pytest.raises(ValueError, match="out of range"):
        t.gather([0, 1003])


# --- sharded Orbax round trip (the satellite contract) ------------------------


@pytest.mark.parametrize("save_shards,load_shards", [(4, 4), (4, 3),
                                                     (4, 7), (3, 1)])
def test_sharded_roundtrip_bounded_io(arr, tmp_path, save_shards,
                                      load_shards):
    """Save ``save_shards``-way, restore into ``load_shards`` ranges:
    content identical, and the LARGEST single array the I/O path ever
    touched stays <= N/min(shards) + pad — no full-table
    materialization on one host, whatever the two shard counts."""
    t = ht.HostEmbedTable.from_array(arr.copy(), shards=4)
    ht.reset_io_peak()
    t.save_sharded(str(tmp_path / "tab"), shards=save_shards)
    t2 = ht.HostEmbedTable.load_sharded(str(tmp_path / "tab"),
                                        shards=load_shards)
    assert t2.num_shards == load_shards
    assert np.array_equal(t2.to_array(), arr)
    bound = -(-1003 // min(save_shards, load_shards)) + 1
    assert 0 < ht.io_rows_peak() <= bound
    # the per-host invariant holds for the RESTORED layout too
    assert max(s.shape[0] for s in t2._shards) <= -(-1003 // load_shards)
    # and it is surfaced as the documented gauge
    assert telem.default_registry().snapshot()[
        "host_table/io_rows_peak"] == ht.io_rows_peak()


def test_load_rejects_unknown_format(arr, tmp_path):
    t = ht.HostEmbedTable.from_array(arr.copy())
    t.save_sharded(str(tmp_path / "tab"), shards=2)
    import json
    mpath = tmp_path / "tab" / ht.MANIFEST
    meta = json.loads(mpath.read_text())
    meta["version"] = 99
    mpath.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="format"):
        ht.HostEmbedTable.load_sharded(str(tmp_path / "tab"))


# --- device hot-row cache -----------------------------------------------------


def test_cache_hits_skip_upload_and_evictions_are_lru(arr):
    t = ht.HostEmbedTable.from_array(arr.copy(), shards=2)
    c = ht.DeviceHotCache(t, 128)
    reg = telem.default_registry()
    base = reg.mark()
    s1 = c.ensure(np.arange(100))
    assert np.array_equal(c.fetch(s1), arr[:100])
    d = reg.snapshot(baseline=base)
    assert d.get("host_table/cache_misses") == 100
    assert d.get("host_table/upload_rows") == 100
    # 50 hits, 60 misses, eviction of the least-recent non-requested
    base = reg.mark()
    s2 = c.ensure(np.arange(50, 160))
    assert np.array_equal(c.fetch(s2), arr[50:160])
    d = reg.snapshot(baseline=base)
    assert d.get("host_table/cache_hits") == 50
    assert d.get("host_table/cache_misses") == 60
    assert d.get("host_table/cache_evictions") == 32  # 128-cap overflow
    # the hit rows kept their slots
    assert np.array_equal(s1[50:], s2[:50])


def test_cache_rejects_oversized_working_set(arr):
    t = ht.HostEmbedTable.from_array(arr.copy())
    c = ht.DeviceHotCache(t, 16)
    with pytest.raises(ValueError, match="exceeds the hot-row cache"):
        c.ensure(np.arange(17))


def test_cache_ensure_with_rows_drops_stale_for_resident_ids(arr):
    """The gather_ahead staleness bound: a prefetched row whose id
    became resident since the gather must NOT overwrite the (at least
    as fresh) cached value."""
    t = ht.HostEmbedTable.from_array(arr.copy())
    c = ht.DeviceHotCache(t, 64)
    ids = np.arange(10)
    slots = c.ensure(ids)
    fresh = np.full((10, 7), 42.0, np.float32)
    # simulate the chunk program updating the cache in place
    c.array = c.array.at[np.asarray(slots)].set(fresh)
    stale = t.gather(ids)  # gathered BEFORE the update landed
    slots2 = c.ensure_with_rows(ids, stale, np.ones(10, bool))
    assert np.array_equal(slots, slots2)
    assert np.array_equal(c.fetch(slots2), fresh)  # stale rows dropped
