"""Workload-1 integration test (SURVEY.md §4.7): recover a small tree's
hierarchy with Poincaré embeddings to high MAP."""

import pytest
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.data.wordnet import synthetic_tree, transitive_closure
from hyperspace_tpu.models import poincare_embed as pe


def test_closure_of_chain():
    edges = np.asarray([[1, 0], [2, 1], [3, 2]], np.int32)
    pairs = transitive_closure(edges, 4)
    got = {(int(u), int(v)) for u, v in pairs}
    assert got == {(1, 0), (2, 1), (2, 0), (3, 2), (3, 1), (3, 0)}


def test_synthetic_tree_counts():
    ds = synthetic_tree(depth=2, branching=2)  # 1 + 2 + 4 nodes
    assert ds.num_nodes == 7
    # closure: each depth-1 node has 1 ancestor, each depth-2 node has 2
    assert ds.num_pairs == 2 * 1 + 4 * 2


@pytest.mark.slow
def test_poincare_embed_recovers_tree():
    ds = synthetic_tree(depth=3, branching=2)  # 15 nodes
    cfg = pe.PoincareEmbedConfig(
        num_nodes=ds.num_nodes,
        dim=5,
        lr=0.5,
        neg_samples=10,
        batch_size=64,
        burnin_steps=50,
    )
    state, opt = pe.init_state(cfg, seed=0)
    pairs = jnp.asarray(ds.pairs)
    for _ in range(2000):
        state, loss = pe.train_step(cfg, opt, state, pairs)
    assert bool(jnp.isfinite(state.table).all())
    metrics = pe.evaluate(state.table, ds.pairs, cfg.c)
    assert metrics["map"] >= 0.95, metrics
    assert metrics["mean_rank"] <= 1.5, metrics
