"""The live observability plane through the serve surfaces: access log
+ flight recorder (serve/access.py), request-id tracing (batcher,
collator, HTTP front door), /metrics over HTTP, the enriched /healthz
body, and the windowed SLO block in stats."""

import asyncio
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import hyperspace_tpu
from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.serve.access import AccessLog, FlightRecorder
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.errors import OverloadedError
from hyperspace_tpu.serve.server import HttpFrontDoor
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.window import SloWindow


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(3)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((200, 4)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    eng.topk_neighbors(np.zeros(8, np.int32), 4)
    return eng


def _records(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# --- access log through the sync batcher -------------------------------------


def test_topk_writes_one_access_record(engine, tmp_path):
    alog = AccessLog(str(tmp_path / "access.jsonl"))
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=64, access_sink=alog.emit)
    bat.topk([1, 2, 3], 4)
    recs = _records(tmp_path / "access.jsonl")
    assert len(recs) == 1
    r = recs[0]
    assert r["route"] == "topk" and r["outcome"] == "ok"
    assert r["request_id"]  # generated: never anonymous with a sink
    assert r["cache_misses"] == 3 and r["cache_hits"] == 0
    assert r["bucket"] == [8]
    assert r["e2e_ms"] > 0 and r["queue_wait_ms"] >= 0
    assert r["dispatch_ms"] > 0 and r["degrade_level"] == 0
    assert "ts" in r
    # warm repeat: hits recorded, caller id echoed into the record
    bat.topk([1, 2, 3], 4, request_id="my-id-1")
    alog.close()
    recs = _records(tmp_path / "access.jsonl")
    assert recs[1]["request_id"] == "my-id-1"
    assert recs[1]["cache_hits"] == 3 and recs[1]["cache_misses"] == 0


def test_failed_requests_carry_taxonomy_outcome(engine, tmp_path):
    alog = AccessLog(str(tmp_path / "a.jsonl"))
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=alog.emit)
    errs0 = telem.default_registry().get("serve/errors")
    with pytest.raises(ValueError):
        bat.topk([1.5], 4)  # float id: validation
    with pytest.raises(ValueError):
        bat.score([0], [1, 2])  # mismatched: validation
    alog.close()
    recs = _records(tmp_path / "a.jsonl")
    assert [r["outcome"] for r in recs] == ["validation", "validation"]
    assert [r["route"] for r in recs] == ["topk", "score"]
    # taxonomy errors tick serve/errors (shed/deadline keep their own)
    assert telem.default_registry().get("serve/errors") == errs0 + 2


def test_no_sink_means_no_records_and_no_ids(engine):
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0)
    assert bat.access_sink is None and bat.window is None
    bat.topk([0], 4)  # no sink: nothing to write, nothing raises


# --- flight recorder ----------------------------------------------------------


def test_error_burst_dumps_incident(tmp_path):
    rec = FlightRecorder(str(tmp_path / "inc"), capacity=16,
                         burst_n=3, burst_s=60.0, cooldown_s=0.0)
    inc0 = telem.default_registry().get("serve/incidents")
    for i in range(2):
        rec.record({"request_id": f"ok{i}", "outcome": "ok"})
    for i in range(3):
        rec.record({"request_id": f"bad{i}", "outcome": "overloaded"})
    rec.join()  # the write rides a background thread (event-loop safety)
    assert len(rec.dumps) == 1
    lines = _records(rec.dumps[0])
    assert lines[0]["event"] == "incident"
    assert lines[0]["reason"] == "error_burst_overloaded"
    assert "counters" in lines[0]  # the counter marks ride the header
    # the ring rides behind the header, oldest first, ok rows included
    assert [ln["request_id"] for ln in lines[1:]] == [
        "ok0", "ok1", "bad0", "bad1", "bad2"]
    assert telem.default_registry().get("serve/incidents") == inc0 + 1


def test_burst_cooldown_limits_dumps(tmp_path):
    rec = FlightRecorder(str(tmp_path / "inc"), burst_n=2,
                         burst_s=60.0, cooldown_s=3600.0)
    for i in range(10):
        rec.record({"outcome": "internal", "i": i})
    rec.join()
    assert len(rec.dumps) == 1  # one incident per storm, not per request


def test_ring_is_bounded(tmp_path):
    rec = FlightRecorder(str(tmp_path / "inc"), capacity=5,
                         cooldown_s=0.0)
    for i in range(100):
        rec.record({"outcome": "ok", "i": i})
    path = rec.dump("manual", wait=True)
    lines = _records(path)
    assert lines[0]["ring_len"] == 5
    assert [ln["i"] for ln in lines[1:]] == [95, 96, 97, 98, 99]


def test_degrade_transition_dumps(engine, tmp_path):
    rec = FlightRecorder(str(tmp_path / "inc"), cooldown_s=0.0)
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, queue_max=1,
                         ladder_down_after=1, recorder=rec)
    # force pressure: the second concurrent admit sheds → ladder down
    bat._admission.inflight = 1
    with pytest.raises(OverloadedError):
        bat.topk([0], 4)
    bat._admission.inflight = 0
    rec.join()
    assert any("degrade" in p for p in rec.dumps)


def test_validation(tmp_path):
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(str(tmp_path / "i"), capacity=0)
    with pytest.raises(ValueError, match="burst"):
        FlightRecorder(str(tmp_path / "i2"), burst_n=0)


# --- windowed SLOs through the batcher ---------------------------------------


def test_stats_carries_window_block(engine):
    w = SloWindow(30.0, registry=telem.default_registry())
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, window=w)
    for _ in range(3):
        bat.topk([1, 2], 4)
    stats = bat.stats()
    win = stats["window"]
    assert win is not None and win["e2e_ms"] is not None
    assert win["e2e_ms"]["count"] >= 3
    assert win["e2e_ms"]["p99"] > 0
    # no window armed → stats says so explicitly
    bat2 = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                          cache_size=0)
    assert bat2.stats()["window"] is None


# --- the HTTP surface ---------------------------------------------------------


async def _raw_request(host, port, method, path, payload=None,
                       headers=None):
    """(status, headers dict, body bytes) — header-aware variant of the
    test_server helper (the echo assertions need response headers)."""
    reader, writer = await asyncio.open_connection(host, port)
    body = b"" if payload is None else json.dumps(payload).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write((f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                  f"Content-Length: {len(body)}\r\n{extra}"
                  "Connection: close\r\n\r\n").encode() + body)
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    hdrs = {}
    clen = 0
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, val = h.decode().partition(":")
        hdrs[name.strip().lower()] = val.strip()
        if name.strip().lower() == "content-length":
            clen = int(val)
    data = await reader.readexactly(clen)
    writer.close()
    return status, hdrs, data


def _run_door(engine, coro_fn, **bat_kw):
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, **bat_kw)
    door = HttpFrontDoor(bat)

    async def main():
        await door.start()
        try:
            return await coro_fn(door)
        finally:
            await door.drain()

    return asyncio.run(main()), bat


def test_request_id_accept_and_generate(engine, tmp_path):
    alog = AccessLog(str(tmp_path / "http.jsonl"))

    async def go(door):
        h, p = door.host, door.port
        out = {}
        out["echo"] = await _raw_request(
            h, p, "POST", "/v1/topk", {"ids": [1], "k": 3},
            headers={"X-Request-Id": "trace-42"})
        out["gen"] = await _raw_request(h, p, "POST", "/v1/topk",
                                        {"ids": [2], "k": 3})
        # hostile id: header-injection runes are stripped, not echoed
        out["evil"] = await _raw_request(
            h, p, "POST", "/v1/topk", {"ids": [3], "k": 3},
            headers={"X-Request-Id": "a b\tc"})
        return out

    out, _bat = _run_door(engine, go, access_sink=alog.emit)
    alog.close()
    status, hdrs, _ = out["echo"]
    assert status == 200 and hdrs["x-request-id"] == "trace-42"
    status, hdrs, _ = out["gen"]
    assert status == 200 and len(hdrs["x-request-id"]) == 16
    status, hdrs, _ = out["evil"]
    assert status == 200 and hdrs["x-request-id"] == "abc"
    recs = _records(tmp_path / "http.jsonl")
    by_id = {r["request_id"]: r for r in recs}
    assert "trace-42" in by_id
    assert by_id["trace-42"]["flush_id"] is not None  # joined to a flush
    assert by_id["trace-42"]["outcome"] == "ok"


def test_parse_and_route_failures_are_logged(engine, tmp_path):
    alog = AccessLog(str(tmp_path / "err.jsonl"))

    async def go(door):
        h, p = door.host, door.port
        await _raw_request(h, p, "POST", "/v1/topk", None)  # empty body
        await _raw_request(h, p, "POST", "/no/route", {"x": 1})
        await _raw_request(h, p, "GET", "/healthz")  # scrape: not logged
        return None

    _out, _bat = _run_door(engine, go, access_sink=alog.emit)
    alog.close()
    recs = _records(tmp_path / "err.jsonl")
    assert [r["outcome"] for r in recs] == ["parse", "validation"]
    assert recs[0]["route"] == "topk" and recs[1]["route"] == "none"


def test_metrics_endpoint_over_http(engine):
    async def go(door):
        h, p = door.host, door.port
        await _raw_request(h, p, "POST", "/v1/topk",
                           {"ids": [1, 2], "k": 3})
        return await _raw_request(h, p, "GET", "/metrics")

    out, _bat = _run_door(engine, go)
    status, hdrs, body = out
    assert status == 200
    assert hdrs["content-type"].startswith("text/plain")
    text = body.decode()
    assert "# TYPE hyperspace_serve_requests counter" in text
    assert "# HELP hyperspace_serve_e2e_ms serve/e2e_ms" in text
    assert 'process_index="0"' in text
    # POST is not a scrape
    (_out2, _bat2) = _run_door(
        engine, lambda door: _raw_request(door.host, door.port, "POST",
                                          "/metrics", {}))
    assert _out2[0] == 405


def test_healthz_enriched_body(engine):
    async def go(door):
        return await _raw_request(door.host, door.port, "GET",
                                  "/healthz")

    out, bat = _run_door(engine, go)
    status, _hdrs, body = out
    health = json.loads(body)
    assert status == 200 and health["ok"] is True
    assert health["uptime_s"] >= 0
    assert health["version"] == hyperspace_tpu.__version__
    assert health["fingerprint"] == bat.engine.fingerprint
    assert health["scan_signature"] == list(bat.engine.scan_signature)
    assert health["precision"] == "f32"
    assert health["degrade_level"] == 0


def test_sigterm_drain_dumps_flight_recorder(engine, tmp_path):
    rec = FlightRecorder(str(tmp_path / "inc"), cooldown_s=0.0)

    async def go(door):
        await _raw_request(door.host, door.port, "POST", "/v1/topk",
                           {"ids": [1], "k": 3})
        return None

    _out, _bat = _run_door(engine, go, recorder=rec)
    # _run_door drains in its finally — the drain IS the trigger
    assert any("drain" in os.path.basename(p) for p in rec.dumps)


def test_http_framing_errors_feed_error_accounting(engine, tmp_path):
    """A storm of malformed HTTP (garbled request lines) must tick
    serve/errors and write access records — the framing level joins
    the same accounting as body-level failures, so the flight
    recorder's burst detector sees hostile traffic."""
    alog = AccessLog(str(tmp_path / "framing.jsonl"))
    errs0 = telem.default_registry().get("serve/errors")

    async def go(door):
        reader, writer = await asyncio.open_connection(door.host,
                                                       door.port)
        writer.write(b"utter garbage\r\n\r\n")
        await writer.drain()
        await reader.read()  # 400 + close
        writer.close()
        return None

    _out, _bat = _run_door(engine, go, access_sink=alog.emit)
    alog.close()
    recs = _records(tmp_path / "framing.jsonl")
    assert [r["outcome"] for r in recs] == ["parse"]
    assert recs[0]["route"] == "none" and recs[0]["request_id"]
    assert telem.default_registry().get("serve/errors") == errs0 + 1


def test_access_log_emit_after_close_is_safe(tmp_path):
    """The close/emit shutdown race: an emit landing after close()
    drops the line (and still feeds the recorder) instead of raising
    into a live request."""
    rec = FlightRecorder(str(tmp_path / "inc"), cooldown_s=0.0)
    alog = AccessLog(str(tmp_path / "late.jsonl"), recorder=rec)
    alog.emit({"request_id": "a", "outcome": "ok"})
    alog.close()
    alog.emit({"request_id": "b", "outcome": "ok"})  # must not raise
    assert alog.lines == 1
    assert len(rec._ring) == 2  # the ring still sees the late record


def test_cache_only_shed_counts_in_serve_shed(engine):
    """EVERY overloaded answer ticks serve/shed — counting only the
    admission-queue site left the window's shed_rate reading 0 during
    exactly the cache-only degradation state this plane must expose."""
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=64, queue_max=4)
    # force the terminal ladder level (cache-only)
    bat._ladder._level = len(bat._modes) - 1
    shed0 = telem.default_registry().get("serve/shed")
    errs0 = telem.default_registry().get("serve/errors")
    with pytest.raises(OverloadedError, match="cache-only"):
        bat.topk([7], 4)  # cold id under cache-only: shed
    with pytest.raises(OverloadedError, match="uncached"):
        bat.score([0], [1])  # scoring under cache-only: shed
    assert telem.default_registry().get("serve/shed") == shed0 + 2
    # sheds are NOT taxonomy errors: the window's rates never
    # double-count one refusal as both shed and error
    assert telem.default_registry().get("serve/errors") == errs0
