"""int8 quantized table-scan lane (serve/engine.py + serve/quant.py,
docs/serving.md "Quantized scan lane").

Acceptance contracts (ISSUE 14):

- **rank identity**: on all three manifold specs the int8-coarse-scan +
  f32-rescore engine returns EXACTLY the exact f32 engine's neighbors
  and f32-tight distances, checked against an f64 oracle — including
  the IVF, fused-kernel, and mesh-sharded compositions;
- **quarter bytes**: the resident scan copy is int8 + a per-row f32
  scale — the 4×-capacity lever the beyond-HBM ROADMAP item names;
- **lane isolation**: the scan signature and the batcher cache key
  carry the lane, so f32/bf16/int8 rows can never cross;
- **quant module**: per-row symmetric scaling round-trips within half a
  quantization step, zero rows stay exactly zero.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.quant import (QLEVELS, dequantize_rows,
                                        quantize_rows)

N, DIM, K, B = 600, 8, 7, 16


def _poincare_table(rng, n=N, dim=DIM, scale=0.5):
    return np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * scale, jnp.float32)))


def _lorentz_table(rng, n=N, dim=DIM, c=0.8):
    v = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.float32),
         jnp.asarray(rng.standard_normal((n, dim)) * 0.5, jnp.float32)],
        axis=1)
    return np.asarray(Lorentz(c).expmap0(v))


def _specs(rng):
    return [
        ("poincare", _poincare_table(rng), ("poincare", 1.0)),
        ("lorentz", _lorentz_table(rng), ("lorentz", 0.8)),
        ("product", _poincare_table(rng),
         ("product", (("poincare", 4, 1.0), ("euclidean", 4, 0.0)))),
    ]


def _f64_oracle(table, spec, q_idx, k):
    """Exact top-k in f64 via the live manifolds — the independent
    ranking the int8 lane must reproduce."""
    from hyperspace_tpu.serve.artifact import manifold_from_spec

    t64 = jnp.asarray(np.asarray(table, np.float64))
    m = manifold_from_spec(spec)
    d = np.array(m.dist(t64[q_idx][:, None, :], t64[None, :, :]))
    d[np.arange(len(q_idx)), q_idx] = np.inf  # exclude_self
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx, np.take_along_axis(d, idx, axis=1)


# --- quant module -------------------------------------------------------------


def test_quantize_rows_roundtrip_and_zero_rows(rng):
    t = rng.standard_normal((50, 6)).astype(np.float32)
    t[7] = 0.0
    q, s = quantize_rows(t)
    assert q.dtype == np.int8 and s.shape == (50, 1)
    assert np.abs(q).max() <= QLEVELS
    err = np.abs(dequantize_rows(q, s) - t)
    assert np.all(err <= s / 2 + 1e-9)
    assert s[7] == 0 and np.all(q[7] == 0)
    assert np.all(dequantize_rows(q, s)[7] == 0.0)
    with pytest.raises(ValueError, match=r"\[N, D\]"):
        quantize_rows(np.zeros(5))


# --- rank identity vs the f64 oracle -----------------------------------------


@pytest.mark.parametrize("scan_mode", ["two_stage", "carry", "fused"])
def test_int8_rank_identical_all_manifolds(rng, scan_mode):
    """All three specs × every scan mode: neighbors identical to the
    exact f32 engine AND the f64 oracle; distances f32-tight (they
    come from the f32 rescore, never the quantized pass)."""
    q = rng.integers(0, N, size=B)
    for name, table, spec in _specs(rng):
        e32 = QueryEngine(table, spec, chunk_rows=128)
        e8 = QueryEngine(table, spec, chunk_rows=128, precision="int8",
                         scan_mode=scan_mode)
        i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
        i8, d8 = (np.asarray(a) for a in e8.topk_neighbors(q, K))
        assert np.array_equal(i32, i8), (name, scan_mode)
        assert np.allclose(d32, d8, rtol=1e-6, atol=1e-7), name
        oi, od = _f64_oracle(table, spec, q, K)
        assert np.array_equal(i8, oi), (name, scan_mode)
        assert np.allclose(d8, od, rtol=2e-4, atol=1e-5), name


def test_int8_quarter_table_bytes(rng):
    table = _poincare_table(rng)
    e32 = QueryEngine(table, ("poincare", 1.0))
    e8 = QueryEngine(table, ("poincare", 1.0), precision="int8")
    assert e8.scan_table.dtype == jnp.int8
    assert e8.scan_scale is not None
    assert e8.scan_table.nbytes * 4 == e32.scan_table.nbytes
    # total lane bytes (code + scale) still well under half of f32
    lane = e8.scan_table.nbytes + e8.scan_scale.nbytes
    assert lane < e32.scan_table.nbytes / 2


def test_int8_ivf_rank_identical(rng):
    """IVF composition: probing through the int8 candidate scorer
    (per-candidate scale gather + f32 rescore) returns exactly the f32
    probing engine's rows, fused and two-stage."""
    from hyperspace_tpu.serve.index import build_index

    n = 4096
    table = _poincare_table(rng, n=n)
    idx = build_index(table, ("poincare", 1.0), 32, seed=0)
    q = rng.integers(0, n, size=B)
    for mode in ("two_stage", "fused"):
        e32 = QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=8,
                          scan_mode=mode)
        e8 = QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=8,
                         precision="int8", scan_mode=mode)
        assert e8.scan_strategy == "ivf"
        i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
        i8, d8 = (np.asarray(a) for a in e8.topk_neighbors(q, K))
        assert np.array_equal(i32, i8), mode
        assert np.allclose(d32, d8, rtol=1e-6, atol=1e-7), mode


def test_int8_sharded_rank_identical(rng):
    """4-way mesh sharding: int8 code + per-row scale shard
    P("model", None) beside the master; the per-shard scan + all-gather
    + f32 rescore matches the single-device f32 engine."""
    import jax

    from hyperspace_tpu.parallel.mesh import model_mesh

    if len(jax.local_devices()) < 4:
        pytest.skip("needs 4 local devices (tests/conftest.py forces them)")
    n = 4096
    table = _poincare_table(rng, n=n)
    q = rng.integers(0, n, size=B)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
    for mode in ("two_stage", "fused"):
        e8 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                         precision="int8", mesh=model_mesh(4),
                         scan_mode=mode)
        i8, d8 = (np.asarray(a) for a in e8.topk_neighbors(q, K))
        assert np.array_equal(i32, i8), mode
        assert np.allclose(d32, d8, rtol=1e-6, atol=1e-7), mode


# --- lane isolation -----------------------------------------------------------


def test_scan_signature_carries_the_lane(rng):
    table = _poincare_table(rng)
    assert QueryEngine(table, ("poincare", 1.0)).scan_signature == \
        ("exact",)
    e8 = QueryEngine(table, ("poincare", 1.0), precision="int8")
    assert e8.scan_signature == ("exact", "int8")
    ef = QueryEngine(table, ("poincare", 1.0), precision="int8",
                     scan_mode="fused")
    assert ef.scan_signature == ("exact", "fused", "int8")


def test_batcher_cache_never_crosses_lanes(rng):
    """The same ids through f32 / bf16 / int8 batchers over the SAME
    fingerprint: each lane computes its own rows (distinct cache keys —
    the serve counters are process-wide, so assert per-pass deltas),
    and stats reports the lane."""
    from hyperspace_tpu.telemetry import registry as telem

    table = _poincare_table(rng)
    ids = rng.integers(0, N, size=8).tolist()
    reg = telem.default_registry()
    batchers = {p: RequestBatcher(QueryEngine(table, ("poincare", 1.0),
                                              precision=p))
                for p in ("f32", "bf16", "int8")}
    for p, bat in batchers.items():
        base = reg.mark()
        bat.topk(ids, K)
        assert bat.stats()["precision"] == p
        d = reg.snapshot(baseline=base)
        assert d.get("serve/cache_hit", 0) == 0  # no cross-lane reuse
        base = reg.mark()
        bat.topk(ids, K)
        d = reg.snapshot(baseline=base)
        assert d.get("serve/cache_hit", 0) > 0  # same-lane reuse works


def test_int8_prewarm(rng):
    """Prewarm composes: the lane's executables warm without touching
    request/cache counters (process-wide — assert the pass's delta)."""
    from hyperspace_tpu.telemetry import registry as telem

    table = _poincare_table(rng)
    bat = RequestBatcher(QueryEngine(table, ("poincare", 1.0),
                                     precision="int8"),
                         min_bucket=8, max_bucket=16)
    reg = telem.default_registry()
    base = reg.mark()
    bat.prewarm([K])
    d = reg.snapshot(baseline=base)
    assert d.get("serve/prewarmed", 0) > 0
    assert d.get("serve/requests", 0) == 0


def test_bad_precision_rejected(rng):
    with pytest.raises(ValueError, match="precision"):
        QueryEngine(_poincare_table(rng), ("poincare", 1.0),
                    precision="int2")


def test_serve_cli_accepts_int8(tmp_path, rng):
    """ServeConfig precision=int8 reaches the engine (flag row:
    docs/serving.md)."""
    from hyperspace_tpu.cli.serve import ServeConfig, _build
    from hyperspace_tpu.serve.artifact import export_artifact

    table = _poincare_table(rng)
    art = str(tmp_path / "art")
    export_artifact(art, table, ("poincare", 1.0))
    cfg = ServeConfig(artifact=art, precision="int8")
    engine, batcher = _build(cfg)
    assert engine.precision == "int8"
    ids = rng.integers(0, N, size=4).tolist()
    e32, _ = _build(ServeConfig(artifact=art))
    i8, _ = batcher.topk(ids, 5)
    i32, _ = RequestBatcher(e32).topk(ids, 5)
    assert np.array_equal(np.asarray(i8), np.asarray(i32))
