"""Serving-artifact format: atomic export, commit marker, fingerprint,
manifold-spec round trips, checkpoint → artifact extraction."""

import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import (Euclidean, Lorentz, PoincareBall,
                                      Product, Sphere)
from hyperspace_tpu.serve import artifact as A


def _table(rng, n=20, d=4):
    return np.asarray(rng.standard_normal((n, d)) * 0.1, np.float32)


def test_export_load_round_trip(tmp_path, rng):
    t = _table(rng)
    out = str(tmp_path / "art")
    exported = A.export_artifact(out, t, ("poincare", 1.3),
                                 model_config={"c": 1.3}, step=7)
    loaded = A.load_artifact(out)
    assert loaded.fingerprint == exported.fingerprint
    assert np.array_equal(loaded.table, t)
    assert loaded.table.dtype == t.dtype
    assert loaded.manifold_spec == ("poincare", 1.3)
    assert loaded.model_config == {"c": 1.3}
    assert loaded.step == 7
    assert A.is_committed(out)


def test_missing_marker_is_uncommitted(tmp_path, rng):
    out = str(tmp_path / "art")
    A.export_artifact(out, _table(rng), ("poincare", 1.0))
    os.remove(os.path.join(out, A.COMMIT_MARKER))
    assert not A.is_committed(out)
    with pytest.raises(FileNotFoundError):
        A.load_artifact(out)


def test_fingerprint_mismatch_refuses_to_load(tmp_path, rng):
    out = str(tmp_path / "art")
    A.export_artifact(out, _table(rng), ("poincare", 1.0))
    # swap the table under the marker: a corrupted artifact must not serve
    np.save(os.path.join(out, A.TABLE_FILE), _table(rng) + 1.0)
    with pytest.raises(ValueError, match="fingerprint"):
        A.load_artifact(out)


def test_overwrite_semantics(tmp_path, rng):
    out = str(tmp_path / "art")
    t1, t2 = _table(rng), _table(rng)
    A.export_artifact(out, t1, ("poincare", 1.0))
    with pytest.raises(FileExistsError):
        A.export_artifact(out, t2, ("poincare", 1.0))
    A.export_artifact(out, t2, ("poincare", 1.0), overwrite=True)
    assert np.array_equal(A.load_artifact(out).table, t2)
    # no staging/backup leftovers beside the artifact
    assert os.listdir(tmp_path) == ["art"]


def test_fingerprint_covers_spec_and_bytes(rng):
    t = _table(rng)
    base = A.fingerprint_of(t, ("poincare", 1.0))
    assert A.fingerprint_of(t, ("poincare", 2.0)) != base
    assert A.fingerprint_of(t, ("lorentz", 1.0)) != base
    t2 = t.copy()
    t2[0, 0] += 1e-7
    assert A.fingerprint_of(t2, ("poincare", 1.0)) != base
    assert A.fingerprint_of(t.copy(), ("poincare", 1.0)) == base


@pytest.mark.parametrize("m,spec", [
    (PoincareBall(1.3), ("poincare", 1.3)),
    (Lorentz(0.8), ("lorentz", 0.8)),
    (Product([PoincareBall(1.1), Sphere(0.9), Euclidean()], [3, 3, 2]),
     ("product", (("poincare", 3, 1.1), ("sphere", 3, 0.9),
                  ("euclidean", 2, 0.0)))),
])
def test_spec_round_trips(m, spec):
    assert A.spec_from_manifold(m) == spec
    assert A.spec_from_json(A.spec_to_json(spec)) == spec
    rebuilt = A.manifold_from_spec(spec)
    assert A.spec_from_manifold(rebuilt) == spec
    # JSON path survives an actual serialize/parse
    assert A.spec_from_json(json.loads(json.dumps(A.spec_to_json(spec)))) == spec


def test_product_table_width_validated(tmp_path, rng):
    spec = ("product", (("poincare", 3, 1.0), ("euclidean", 2, 0.0)))
    with pytest.raises(ValueError, match="width"):
        A.export_artifact(str(tmp_path / "a"), _table(rng, d=4), spec)


def test_export_from_checkpoint_poincare(tmp_path):
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    cfg = pe.PoincareEmbedConfig(num_nodes=12, dim=3)
    state, _opt = pe.init_state(cfg, 0)
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(3, state, force=True)
    art = A.export_from_checkpoint(
        ckpt, str(tmp_path / "art"), workload="poincare",
        model_config={"c": cfg.c})
    assert art.step == 3
    assert art.manifold_spec == ("poincare", 1.0)
    assert np.array_equal(art.table, np.asarray(state.table))


def test_export_from_checkpoint_requires_curvature(tmp_path):
    """poincare/lorentz export must demand the trained c — a silent 1.0
    default would freeze the wrong metric into a valid-looking artifact."""
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    cfg = pe.PoincareEmbedConfig(num_nodes=8, dim=3)
    state, _opt = pe.init_state(cfg, 0)
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(1, state, force=True)
    with pytest.raises(ValueError, match="requires model_config\\['c'\\]"):
        A.export_from_checkpoint(ckpt, str(tmp_path / "art"),
                                 workload="poincare")


def test_export_from_checkpoint_product_factor_mismatch(tmp_path):
    """A factors= layout naming MORE curved factors than the checkpoint
    trained must fail with the diagnostic ValueError (not an IndexError
    from indexing past c_raw)."""
    from hyperspace_tpu.models import product_embed as pme
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    cfg = pme.ProductEmbedConfig(num_nodes=6)  # 2 curved factors
    state, _opt = pme.init_state(cfg, 0)
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(1, state, force=True)
    with pytest.raises(ValueError, match="learned"):
        A.export_from_checkpoint(
            ckpt, str(tmp_path / "art"), workload="product",
            model_config={"factors": [["poincare", 4], ["sphere", 4],
                                      ["poincare", 4]]})


def test_export_from_checkpoint_product(tmp_path):
    from hyperspace_tpu.models import product_embed as pme
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    cfg = pme.ProductEmbedConfig(num_nodes=10)
    state, _opt = pme.init_state(cfg, 0)
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(2, state, force=True)
    art = A.export_from_checkpoint(
        ckpt, str(tmp_path / "art"), workload="product")
    assert art.manifold_spec[0] == "product"
    kinds = [f[0] for f in art.manifold_spec[1]]
    assert kinds == ["poincare", "sphere", "euclidean"]
    # learned curvatures frozen as softplus(c_raw)
    want = np.asarray(jax.nn.softplus(
        jnp.asarray(state.params.c_raw, jnp.float64)))
    got = [c for k, _d, c in art.manifold_spec[1] if k != "euclidean"]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert np.array_equal(art.table, np.asarray(state.params.table))
