"""Query-engine goldens: the chunked jitted k-NN agrees with a pure-
`manifolds` O(N²) reference on every supported manifold — this is the
test coverage for the CPU/XLA fallback path of the distance kernels the
engine reuses (ISSUE 3 satellite)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import (Euclidean, Lorentz, PoincareBall,
                                      Product, Sphere)
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.engine import QueryEngine, auto_chunk_rows


def _poincare_table(rng, n, d, c):
    v = jnp.asarray(rng.standard_normal((n, d)) * 0.5, jnp.float32)
    return np.asarray(PoincareBall(c).expmap0(v)), PoincareBall(c)


def _lorentz_table(rng, n, d, c):
    man = Lorentz(c)
    v = jnp.asarray(rng.standard_normal((n, d + 1)) * 0.5, jnp.float32)
    v = v.at[:, 0].set(0.0)
    return np.asarray(man.expmap0(v)), man


def _product_table(rng, n):
    man = Product([PoincareBall(1.1), Sphere(0.9), Euclidean()], [3, 3, 2])
    v = jnp.asarray(rng.standard_normal((n, 8)) * 0.3, jnp.float32)
    pt = man.proj(man.expmap0(man.proju(man.origin((n, 8)), v)))
    return np.asarray(pt), man


def _reference_topk(man, table, q_idx, k):
    """O(N²) oracle: full f64 distance matrix through the manifold's own
    ``dist``, self excluded, argsorted."""
    t64 = jnp.asarray(table, jnp.float64)
    d = np.array(jax.vmap(lambda x: man.dist(x, t64))(t64[q_idx]))
    d[np.arange(len(q_idx)), q_idx] = np.inf
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx, np.take_along_axis(d, idx, axis=1)


@pytest.mark.parametrize("build", [_poincare_table, _lorentz_table],
                         ids=["poincare", "lorentz"])
def test_topk_matches_manifold_reference(rng, build):
    table, man = build(rng, 57, 6, 1.3)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128)
    q = np.asarray([0, 3, 17, 42, 56], np.int32)
    idx, dist = (np.asarray(a) for a in eng.topk_neighbors(q, 5))
    ref_idx, ref_dist = _reference_topk(man, table, q, 5)
    assert np.array_equal(idx, ref_idx)
    np.testing.assert_allclose(dist, ref_dist, rtol=2e-3, atol=2e-3)
    # ascending order, ids in range, self excluded
    assert np.all(np.diff(dist, axis=1) >= 0)
    assert idx.min() >= 0 and idx.max() < eng.num_nodes
    assert not np.any(idx == q[:, None])


def test_topk_matches_manifold_reference_product(rng):
    table, man = _product_table(rng, 41)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128)
    q = np.asarray([0, 7, 40], np.int32)
    idx, dist = (np.asarray(a) for a in eng.topk_neighbors(q, 6))
    ref_idx, ref_dist = _reference_topk(man, table, q, 6)
    assert np.array_equal(idx, ref_idx)
    np.testing.assert_allclose(dist, ref_dist, rtol=2e-3, atol=2e-3)


def test_chunking_is_value_invariant(rng):
    """The running top-k merge over 128-row chunks returns the same
    neighbors/distances as one chunk covering the whole (padded) table."""
    table, man = _poincare_table(rng, 300, 5, 1.0)
    spec = spec_from_manifold(man)
    q = np.asarray([1, 100, 299], np.int32)
    small = QueryEngine(table, spec, chunk_rows=128)
    big = QueryEngine(table, spec, chunk_rows=512)
    i1, d1 = (np.asarray(a) for a in small.topk_neighbors(q, 7))
    i2, d2 = (np.asarray(a) for a in big.topk_neighbors(q, 7))
    assert np.array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2, rtol=1e-5, atol=1e-6)


def test_padded_rows_never_surface(rng):
    """k = N−1 drains the whole table: every real row shows up exactly
    once, the zero-padded chunk tail never does."""
    table, man = _poincare_table(rng, 10, 3, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128)
    q = np.asarray([4], np.int32)
    idx, dist = (np.asarray(a) for a in eng.topk_neighbors(q, 9))
    assert sorted(idx[0].tolist()) == [i for i in range(10) if i != 4]
    assert np.all(np.isfinite(dist))


def test_k_equals_table_rows_without_self_exclusion(rng):
    """k = N with exclude_self=False drains EVERY row, self first at
    distance ~0 — the upper edge the IVF degenerate probe leans on."""
    table, man = _poincare_table(rng, 200, 4, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128)
    q = np.asarray([0, 99, 199], np.int32)
    idx, dist = (np.asarray(a) for a in
                 eng.topk_neighbors(q, 200, exclude_self=False))
    for j, qi in enumerate(q):
        assert sorted(idx[j].tolist()) == list(range(200))
        assert idx[j, 0] == qi
    assert np.all(np.isfinite(dist))
    assert np.all(np.diff(dist, axis=1) >= 0)
    # k past N must stay an error, not a silent clamp
    with pytest.raises(ValueError, match="k="):
        eng.topk_neighbors(q, 201, exclude_self=False)


def test_k_drains_table_across_chunk_boundaries(rng):
    """k = N−1 on a multi-chunk table: every row but self exactly once,
    with the drain crossing chunk boundaries (not the single-chunk case
    test_padded_rows_never_surface already covers)."""
    table, man = _poincare_table(rng, 300, 4, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128)
    q = np.asarray([7, 250], np.int32)
    idx, dist = (np.asarray(a) for a in eng.topk_neighbors(q, 299))
    for j, qi in enumerate(q):
        assert sorted(idx[j].tolist()) == [i for i in range(300) if i != qi]
    assert np.all(np.isfinite(dist))
    ref_idx, ref_dist = _reference_topk(man, table, q, 299)
    assert np.array_equal(idx, ref_idx)
    np.testing.assert_allclose(dist, ref_dist, rtol=2e-3, atol=2e-3)


def test_exclude_self_flag(rng):
    table, man = _poincare_table(rng, 12, 3, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man))
    q = np.asarray([5], np.int32)
    idx, dist = eng.topk_neighbors(q, 1, exclude_self=False)
    assert int(np.asarray(idx)[0, 0]) == 5  # nearest row to itself
    assert float(np.asarray(dist)[0, 0]) == pytest.approx(0.0, abs=1e-5)


def test_score_edges_matches_manifold_dist(rng):
    table, man = _lorentz_table(rng, 30, 5, 0.8)
    eng = QueryEngine(table, spec_from_manifold(man))
    u = np.asarray([0, 5, 9], np.int32)
    v = np.asarray([1, 7, 20], np.int32)
    d = np.asarray(eng.score_edges(u, v))
    ref = np.asarray(man.dist(jnp.asarray(table)[u], jnp.asarray(table)[v]))
    # same f32 math, but jitted-vs-eager fusion may round differently —
    # and identical-point pairs sit on arcosh's noise floor, so the pairs
    # above are all distinct rows
    np.testing.assert_allclose(d, ref, rtol=1e-5, atol=1e-5)
    # Fermi–Dirac probabilities: in (0, 1], monotone decreasing in d
    p = np.asarray(eng.score_edges(u, v, prob=True))
    assert np.all((p > 0) & (p <= 1))
    assert np.array_equal(np.argsort(-p), np.argsort(d))


def test_validation_errors(rng):
    table, man = _poincare_table(rng, 8, 3, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man))
    # a negative chunk would scan ZERO chunks and answer -1/inf silently
    with pytest.raises(ValueError, match="chunk_rows"):
        QueryEngine(table, spec_from_manifold(man), chunk_rows=-5)
    with pytest.raises(ValueError, match="k="):
        eng.topk_neighbors(np.asarray([0], np.int32), 8)  # k > N-1
    with pytest.raises(ValueError, match="out of range"):
        eng.topk_neighbors(np.asarray([8], np.int32), 2)
    with pytest.raises(ValueError, match="out of range"):
        eng.score_edges(np.asarray([-1], np.int32), np.asarray([0], np.int32))
    with pytest.raises(ValueError, match="must match"):
        eng.score_edges(np.asarray([0, 1], np.int32),
                        np.asarray([0], np.int32))
    with pytest.raises(ValueError, match="non-empty"):
        eng.topk_neighbors(np.asarray([], np.int32), 2)


@pytest.mark.parametrize("build", ["poincare", "lorentz", "product"])
def test_two_stage_matches_carry_and_oracle(rng, build):
    """The two-stage scan (per-chunk top-k + one post-scan merge, with
    the threshold-prune fast path) and the carry scan (running top-k,
    re-sort [B, chunk+k] per step) agree with each other exactly and
    with the f64 manifold oracle on every supported spec (ISSUE 4)."""
    if build == "product":
        table, man = _product_table(rng, 300)
        q = np.asarray([0, 7, 150, 299], np.int32)
    else:
        table, man = (_poincare_table if build == "poincare"
                      else _lorentz_table)(rng, 300, 6, 1.3)
        q = np.asarray([0, 3, 17, 150, 299], np.int32)
    spec = spec_from_manifold(man)
    # chunk 128 < N: the scan really runs multiple chunks + a merge
    two = QueryEngine(table, spec, chunk_rows=128, scan_mode="two_stage")
    car = QueryEngine(table, spec, chunk_rows=128, scan_mode="carry")
    i_two, d_two = (np.asarray(a) for a in two.topk_neighbors(q, 7))
    i_car, d_car = (np.asarray(a) for a in car.topk_neighbors(q, 7))
    assert np.array_equal(i_two, i_car)
    np.testing.assert_array_equal(d_two, d_car)
    ref_idx, ref_dist = _reference_topk(man, table, q, 7)
    assert np.array_equal(i_two, ref_idx)
    np.testing.assert_allclose(d_two, ref_dist, rtol=2e-3, atol=2e-3)


def test_two_stage_prune_layout_stays_correct(rng):
    """A norm-sorted table with near-origin queries makes every late
    chunk prunable (its row-min exceeds the running k-th bound) — the
    fast path must skip the sorts without changing a single answer."""
    table, man = _poincare_table(rng, 600, 5, 1.0)
    order = np.argsort(np.linalg.norm(table, axis=1))
    table = np.ascontiguousarray(table[order])
    spec = spec_from_manifold(man)
    q = np.asarray([0, 1, 5], np.int32)  # nearest-origin rows
    two = QueryEngine(table, spec, chunk_rows=128, scan_mode="two_stage")
    i, d = (np.asarray(a) for a in two.topk_neighbors(q, 6))
    ref_idx, ref_dist = _reference_topk(man, table, q, 6)
    assert np.array_equal(i, ref_idx)
    np.testing.assert_allclose(d, ref_dist, rtol=2e-3, atol=2e-3)


def test_bad_scan_mode_rejected(rng):
    table, man = _poincare_table(rng, 8, 3, 1.0)
    with pytest.raises(ValueError, match="scan_mode"):
        QueryEngine(table, spec_from_manifold(man), scan_mode="bogus")


def test_auto_chunk_rows_budget():
    # kernel path: rows independent of D; product path shrinks with D
    assert auto_chunk_rows(10, "poincare", 10_000_000) \
        == auto_chunk_rows(100, "poincare", 10_000_000)
    assert auto_chunk_rows(64, "product", 10_000_000) \
        < auto_chunk_rows(8, "product", 10_000_000)
    # tiny tables never over-allocate: chunk covers the table once
    assert auto_chunk_rows(4, "poincare", 40) == 128
