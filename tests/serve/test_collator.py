"""Continuous-batching collator: flush policy both ways (a full bucket
never waits, a lone request flushes within the max-wait deadline),
shared dispatch, deadline propagation through the queue, admission."""

import asyncio
import time

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.resilience import faults
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.collator import Collator
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.errors import (DeadlineExceededError,
                                         OverloadedError)
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(0)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((256, 4)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    # warm the one (bucket=8, k=4) executable so timing-sensitive tests
    # never race XLA
    eng.topk_neighbors(np.zeros(8, np.int32), 4)
    return eng


def _collator(engine, *, max_wait_us=50_000, **kw):
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=kw.pop("cache_size", 0), **kw)
    return Collator(bat, max_wait_us=max_wait_us), bat


def test_full_bucket_dispatches_without_waiting(engine):
    """min_bucket concurrent single-id requests EXACTLY fill the 8-rung
    — the flush fires on fill, long before the (deliberately huge)
    max-wait deadline, and all 8 share ONE dispatch."""
    col, bat = _collator(engine, max_wait_us=30_000_000)  # 30 s
    reg = telem.default_registry()
    base = reg.mark()

    async def run():
        t0 = time.perf_counter()
        outs = await asyncio.gather(
            *[col.topk([i], 4) for i in range(8)])
        return outs, time.perf_counter() - t0

    outs, elapsed = asyncio.run(run())
    col.close()
    assert elapsed < 5.0  # nowhere near the 30 s max-wait
    delta = reg.snapshot(baseline=base)
    # one shared dispatch: 8 slots total (zero padding), one flush —
    # NOT 8 dispatches of 8 padded slots each
    assert delta.get("serve/slots") == 8
    assert delta.get("serve/padded_waste", 0) == 0
    assert delta.get("serve/collator_flushes") == 1
    for i, (idx, dist) in enumerate(outs):
        ref_i, ref_d = (np.asarray(a) for a in engine.topk_neighbors(
            np.asarray([i], np.int32), 4))
        np.testing.assert_array_equal(np.asarray(idx), ref_i)
        np.testing.assert_array_equal(
            np.asarray(dist, np.float32).view(np.uint32),
            ref_d.astype(np.float32).view(np.uint32))


def test_lone_request_flushes_within_max_wait(engine):
    """A lone request is never held past T: it flushes at the deadline
    (padded) and answers."""
    col, _ = _collator(engine, max_wait_us=30_000)  # 30 ms
    reg = telem.default_registry()
    base = reg.mark()

    async def run():
        t0 = time.perf_counter()
        out = await col.topk([3, 4, 5], 4)
        return out, time.perf_counter() - t0

    (idx, _dist), elapsed = asyncio.run(run())
    col.close()
    assert idx.shape == (3, 4)
    assert elapsed < 5.0  # flushed at T, not at some larger horizon
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/slots") == 8  # padded 3 → 8
    assert delta.get("serve/padded_waste") == 5


def test_same_bucket_requests_share_one_dispatch(engine):
    """Several requests landing inside one max-wait window collate:
    one flush, one dispatch, correct per-request rows."""
    col, _ = _collator(engine, max_wait_us=150_000)
    reg = telem.default_registry()
    base = reg.mark()

    async def run():
        return await asyncio.gather(
            col.topk([1, 2], 4), col.topk([10], 4), col.topk([20, 21], 4))

    outs = asyncio.run(run())
    col.close()
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/collator_flushes") == 1
    assert delta.get("serve/slots") == 8  # 5 unique ids in one slab
    for ids, (idx, dist) in zip(([1, 2], [10], [20, 21]), outs):
        ref_i, _ = (np.asarray(a) for a in engine.topk_neighbors(
            np.asarray(ids, np.int32), 4))
        np.testing.assert_array_equal(np.asarray(idx), ref_i)


def test_collated_matches_sync_batcher_bitwise(engine):
    """The collated path answers exactly what the sync batcher does —
    same validation, same engine executable, same rows."""
    col, _ = _collator(engine, max_wait_us=1_000)
    sync_bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                              cache_size=0)
    ids = [7, 3, 7, 100, 42]  # duplicates included

    async def run():
        return await col.topk(ids, 6)

    idx, dist = asyncio.run(run())
    col.close()
    ref_i, ref_d = sync_bat.topk(ids, 6)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_array_equal(
        np.asarray(dist, np.float32).view(np.uint32),
        np.asarray(ref_d, np.float32).view(np.uint32))


def test_distinct_k_form_distinct_buckets(engine):
    """Different (k) requests never share a pending bucket — each key
    flushes its own batch (concurrent flushes, serialized dispatch)."""
    col, _ = _collator(engine, max_wait_us=50_000)
    reg = telem.default_registry()
    base = reg.mark()

    async def run():
        return await asyncio.gather(col.topk([1], 4), col.topk([2], 5))

    (i4, _), (i5, _) = asyncio.run(run())
    col.close()
    assert i4.shape == (1, 4) and i5.shape == (1, 5)
    assert telem.default_registry().snapshot(
        baseline=base).get("serve/collator_flushes") == 2


def test_deadline_expired_in_queue_is_never_dispatched(engine):
    """A request whose deadline expires while QUEUED in the collator
    answers deadline_exceeded and its ids never reach the engine —
    while a co-queued member with budget left is still served from the
    same flush (one member's expiry cannot fail the batch)."""
    col, _ = _collator(engine, max_wait_us=400_000)  # T = 400 ms
    reg = telem.default_registry()
    base = reg.mark()

    async def run():
        doomed = asyncio.ensure_future(
            col.topk([1], 4, deadline_ms=40.0))   # expires long before T
        healthy = asyncio.ensure_future(col.topk([2], 4))
        return await asyncio.gather(doomed, healthy,
                                    return_exceptions=True)

    doomed, healthy = asyncio.run(run())
    col.close()
    assert isinstance(doomed, DeadlineExceededError)
    assert "queued in the collator" in str(doomed)
    assert not isinstance(healthy, BaseException)
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/deadline_exceeded") == 1
    # the flush dispatched ONLY the healthy member's ids
    assert delta.get("serve/slots") == 8
    assert delta.get("serve/collator_flushes") == 1


def test_expired_mid_flight_answers_late_but_caches(engine):
    """A dispatch that outruns the member's remaining budget (injected
    latency) answers deadline_exceeded at completion — but the computed
    rows stay cached (the PR 9 batcher semantics, collated)."""
    col, bat = _collator(engine, max_wait_us=1_000, cache_size=1024)
    reg = telem.default_registry()
    faults.install([faults.FaultSpec(site="serve.dispatch",
                                     kind="latency", ms=150.0)])

    async def run():
        return await asyncio.gather(
            col.topk([5, 6], 4, deadline_ms=60.0),
            return_exceptions=True)

    base = reg.mark()
    (err,) = asyncio.run(run())
    faults.clear()
    assert isinstance(err, DeadlineExceededError)
    assert "at completion" in str(err)
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/slots") == 8  # it DID dispatch
    # the work was not wasted: the same ids answer from cache, sync path
    base = reg.mark()
    idx, _ = bat.topk([5, 6], 4)
    col.close()
    assert idx.shape == (2, 4)
    assert telem.default_registry().snapshot(
        baseline=base).get("serve/cache_hit") == 2


def test_admission_bounds_concurrent_collated_load(engine):
    """queue_max admits at arrival on the loop (not when the executor
    gets around to the flush): excess concurrent requests shed typed
    overloaded, every request gets exactly one outcome."""
    col, bat = _collator(engine, max_wait_us=5_000, queue_max=2,
                         ladder_down_after=100)

    async def run():
        return await asyncio.gather(
            *[col.topk([i], 4) for i in range(6)],
            return_exceptions=True)

    outs = asyncio.run(run())
    col.close()
    served = [o for o in outs if not isinstance(o, BaseException)]
    shed = [o for o in outs if isinstance(o, OverloadedError)]
    assert len(served) + len(shed) == 6
    assert served and shed  # bound of 2 under 6 concurrent: both occur
    assert bat._admission.inflight == 0  # every slot released


def test_cache_hits_skip_the_queue(engine):
    """An all-hit request never enqueues: answered immediately with
    zero dispatch (the collator path keeps per-id cache granularity)."""
    col, _ = _collator(engine, max_wait_us=200_000, cache_size=1024)
    reg = telem.default_registry()

    async def run():
        await col.topk([8, 9], 4)           # cold: computes + caches
        base = reg.mark()
        t0 = time.perf_counter()
        idx, _ = await col.topk([9, 8], 4)  # hot: pure cache
        return idx, time.perf_counter() - t0, base

    idx, elapsed, base = asyncio.run(run())
    col.close()
    assert idx.shape == (2, 4)
    assert elapsed < 0.19  # never waited out the 200 ms max-wait timer
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/cache_hit") == 2
    assert delta.get("serve/slots", 0) == 0


def test_score_through_collator_matches_sync(engine):
    col, _ = _collator(engine, max_wait_us=1_000)
    sync_bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                              cache_size=0)

    async def run():
        return await col.score([0, 1, 2], [3, 4, 5], prob=True)

    scores = asyncio.run(run())
    col.close()
    np.testing.assert_array_equal(
        scores, sync_bat.score([0, 1, 2], [3, 4, 5], prob=True))


def test_validation_errors_surface_before_queueing(engine):
    col, _ = _collator(engine)

    async def run():
        return await asyncio.gather(
            col.topk([0.5], 4), col.topk([1], "four"),
            col.score([0], [1, 2]), return_exceptions=True)

    bad_id, bad_k, bad_pair = asyncio.run(run())
    col.close()
    assert isinstance(bad_id, ValueError)
    assert isinstance(bad_k, ValueError)
    assert isinstance(bad_pair, ValueError)


def test_max_wait_validation(engine):
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64)
    with pytest.raises(ValueError, match="max_wait_us"):
        Collator(bat, max_wait_us=-1)
