"""Multi-tenant engine registry contracts (ISSUE 20): routing by name
or fingerprint with a 404-typed miss, whole-engine paging under a
device budget (LRU victims, in-use/queued protection, coalesced
admits, bitwise round trips), weighted-fair deficit-round-robin
dispatch, and per-tenant degradation independence."""

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.serve import (EngineRegistry, UnknownTenantError,
                                  engine_device_bytes)
from hyperspace_tpu.serve.artifact import export_artifact, load_artifact
from hyperspace_tpu.serve.collator import FairDispatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.telemetry import registry as telem

N, D, K = 96, 8, 4
QUERY_IDS = [0, 3, 11, 29]

_BATCHER_KW = dict(min_bucket=4, max_bucket=8, cache_size=0,
                   queue_max=4, ladder_down_after=1)


def _art(tmp_path, name, seed):
    rng = np.random.default_rng(seed)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((N, D)) * 0.3, jnp.float32)))
    export_artifact(str(tmp_path / name), table, ("poincare", 1.0))
    return str(tmp_path / name)


def _registry(tmp_path, names, *, budget_mb=0.0, **kw):
    reg = EngineRegistry(device_budget_mb=budget_mb, max_wait_us=500,
                         **kw)
    for i, name in enumerate(names):
        reg.add_tenant(name, _art(tmp_path, name, seed=i),
                       window_s=0.0, batcher_kw=dict(_BATCHER_KW))
    return reg


def _solo(path):
    return QueryEngine.from_artifact(load_artifact(path))


def _one_engine_budget_mb(tmp_path):
    """A budget that provably holds ONE of these engines but never two
    (1.25x one engine's measured device footprint — multiples of it
    stay strictly between N and N+1 engines for small N)."""
    eng = _solo(_art(tmp_path, "probe", seed=99))
    return engine_device_bytes(eng) * 1.25 / (1 << 20)


def _assert_bitwise(stack, solo):
    nbr, dist = stack.batcher.topk(QUERY_IDS, K)
    ref_n, ref_d = solo.topk_neighbors(
        np.asarray(QUERY_IDS, np.int32), K)
    np.testing.assert_array_equal(np.asarray(nbr), np.asarray(ref_n))
    np.testing.assert_array_equal(
        np.asarray(dist, np.float32).view(np.uint32),
        np.asarray(ref_d, np.float32).view(np.uint32))


# --- construction + routing ---------------------------------------------------


def test_negative_budget_rejected():
    with pytest.raises(ValueError, match="device_budget_mb"):
        EngineRegistry(device_budget_mb=-0.5)


def test_add_tenant_validation(tmp_path):
    reg = EngineRegistry()
    try:
        path = _art(tmp_path, "a", seed=0)
        with pytest.raises(ValueError, match="non-empty"):
            reg.add_tenant("", path)
        with pytest.raises(ValueError, match="weight"):
            reg.add_tenant("a", path, weight=0.0)
        reg.add_tenant("a", path, window_s=0.0)
        with pytest.raises(ValueError, match="duplicate"):
            reg.add_tenant("a", path, window_s=0.0)
    finally:
        reg.close()


def test_resolve_by_name_fingerprint_and_default(tmp_path):
    reg = _registry(tmp_path, ("a", "b"))
    try:
        a, b = reg.resolve("a"), reg.resolve("b")
        assert reg.resolve() is a        # first added tenant = default
        assert reg.resolve(None) is a
        assert reg.resolve(a.fingerprint) is a
        assert reg.resolve(b.fingerprint) is b
        assert a.fingerprint != b.fingerprint
        with pytest.raises(UnknownTenantError) as ei:
            reg.resolve("nobody")
        assert ei.value.tenant == "nobody"
        assert ei.value.kind == "unknown_tenant"
        for bad in (7, b"", ""):
            with pytest.raises(ValueError, match="non-empty string"):
                reg.resolve(bad)
    finally:
        reg.close()


def test_empty_registry_has_no_default():
    reg = EngineRegistry()
    try:
        with pytest.raises(UnknownTenantError):
            reg.default
    finally:
        reg.close()


# --- engine paging ------------------------------------------------------------


def test_budget_pages_out_idle_tenants_on_admission(tmp_path):
    budget = _one_engine_budget_mb(tmp_path)
    reg = _registry(tmp_path, ("a", "b", "c"), budget_mb=budget)
    try:
        a, b, c = (reg.resolve(n) for n in "abc")
        # each add_tenant keeps the newcomer and evicts the idle rest
        assert (a.resident, b.resident, c.resident) == (False, False,
                                                        True)
        assert a.evictions == 1 and b.evictions == 1
        assert reg.stats()["a"]["registry"]["device_bytes"] == 0
    finally:
        reg.close()


def test_eviction_picks_the_least_recently_used_victim(tmp_path):
    budget = 2.0 * _one_engine_budget_mb(tmp_path)  # holds two engines
    reg = _registry(tmp_path, ("a", "b", "c"), budget_mb=budget)
    try:
        a, b, c = (reg.resolve(n) for n in "abc")

        async def run():
            # admitting c evicted the LRU of {a, b} — a (built first)
            assert (a.resident, b.resident, c.resident) == (False, True,
                                                            True)
            async with reg.using(b):   # touch b: c becomes the LRU
                pass
            await reg.ensure_resident(a)
            assert (a.resident, b.resident, c.resident) == (True, True,
                                                            False)

        asyncio.run(run())
    finally:
        reg.close()


def test_inflight_tenant_is_never_a_victim(tmp_path):
    budget = _one_engine_budget_mb(tmp_path)
    reg = _registry(tmp_path, ("a", "b"), budget_mb=budget)
    try:
        a, b = reg.resolve("a"), reg.resolve("b")

        async def run():
            async with reg.using(b):
                await reg.ensure_resident(a)
                # no safe victim: the set stays over budget rather than
                # yanking the engine out from under b's request
                assert a.resident and b.resident
            reg._enforce_budget(keep=a)  # traffic passed: b pages out
            assert a.resident and not b.resident

        asyncio.run(run())
    finally:
        reg.close()


def test_concurrent_admits_coalesce_into_one_rebuild(tmp_path):
    reg = _registry(tmp_path, ("a", "b"))
    try:
        b = reg.resolve("b")
        reg._evict(b)

        async def run():
            await asyncio.gather(*(reg.ensure_resident(b)
                                   for _ in range(4)))

        asyncio.run(run())
        assert b.resident and b.admissions == 1
        assert b.admit_future is None
    finally:
        reg.close()


def test_paging_round_trip_is_bitwise(tmp_path):
    reg = _registry(tmp_path, ("a", "b"))
    try:
        b = reg.resolve("b")
        solo = _solo(b.artifact)
        _assert_bitwise(b, solo)
        reg._evict(b)
        assert b.batcher.engine is None

        async def run():
            await reg.ensure_resident(b)

        asyncio.run(run())
        # same artifact -> same fingerprint -> same bits; with the
        # persistent compile cache the rebuild is deserialization only
        assert b.fingerprint == solo.fingerprint
        _assert_bitwise(b, solo)
        assert (telem.default_registry().get(
            "serve/tenant_admissions@tenant=b") or 0) >= 1
    finally:
        reg.close()


def test_stats_shape_for_paged_out_tenants(tmp_path):
    budget = _one_engine_budget_mb(tmp_path)
    reg = _registry(tmp_path, ("a", "b"), budget_mb=budget)
    try:
        stats = reg.stats()
        # a was paged out by b's admission: registry block only (its
        # batcher stats would dereference the evicted engine)
        assert set(stats["a"]) == {"tenant", "registry"}
        assert stats["a"]["registry"]["resident"] is False
        assert stats["b"]["registry"]["resident"] is True
        assert "degrade_level" in stats["b"]  # full batcher stats
    finally:
        reg.close()


# --- weighted-fair dispatch ---------------------------------------------------


def _drive_drr(weights, jobs, *, cost, quantum=8):
    """Submit ``jobs`` [(tenant, fn-tag)] while the single worker is
    held busy, release it, and return the completion order of tags."""
    order = []

    async def run():
        loop = asyncio.get_running_loop()
        exec_ = ThreadPoolExecutor(max_workers=1)
        disp = FairDispatcher(exec_, weights=weights, quantum=quantum)
        gate = threading.Event()
        futs = [disp.submit(loop, jobs[0][0], 1, lambda: gate.wait(10))]
        for tenant, tag in jobs:
            futs.append(disp.submit(loop, tenant, cost,
                                    lambda t=tag: order.append(t)))
        assert sum(disp.pending().values()) == len(jobs)
        gate.set()
        await asyncio.gather(*futs)
        exec_.shutdown(wait=True)
        return disp

    disp = asyncio.run(run())
    return order, disp


def test_drr_grants_share_proportional_to_weight():
    jobs = ([("a", "a")] * 6) + [("b", "b")] * 6
    # cost 2x quantum: "a" (weight 2) affords every visit, "b" only
    # every second -> a drains at twice b's rate while both contend
    order, _ = _drive_drr({"a": 2.0, "b": 1.0}, jobs, cost=16)
    contended = order[:9]
    assert contended.count("a") == 6 and contended.count("b") == 3
    assert order[9:] == ["b", "b", "b"]


def test_drr_emptied_queue_forfeits_deficit():
    # a huge-weight tenant banks nothing while idle: after its queue
    # drains its deficit resets, so a later burst starts from zero
    order, disp = _drive_drr({"a": 100.0, "b": 1.0},
                             [("a", "a"), ("b", "b")], cost=8)
    assert sorted(order) == ["a", "b"]
    assert disp.pending() == {}
    assert all(d == 0.0 for d in disp._deficit.values())


def test_drr_skips_cancelled_jobs():
    ran = []

    async def run():
        loop = asyncio.get_running_loop()
        exec_ = ThreadPoolExecutor(max_workers=1)
        disp = FairDispatcher(exec_)
        gate = threading.Event()
        blocker = disp.submit(loop, "a", 1, lambda: gate.wait(10))
        doomed = disp.submit(loop, "a", 1, lambda: ran.append("doomed"))
        kept = disp.submit(loop, "b", 1, lambda: ran.append("kept"))
        doomed.cancel()
        gate.set()
        await asyncio.gather(blocker, kept)
        exec_.shutdown(wait=True)

    asyncio.run(run())
    assert ran == ["kept"]  # the cancelled job never reached the pool


def test_drr_misconfigured_zero_weight_throttles_not_halts():
    disp = FairDispatcher(ThreadPoolExecutor(max_workers=1),
                          weights={"z": 0.0})
    assert disp.weight("z") > 0.0
    with pytest.raises(ValueError, match="quantum"):
        FairDispatcher(ThreadPoolExecutor(max_workers=1), quantum=0)


# --- isolation ----------------------------------------------------------------


def test_tenant_answers_bitwise_match_solo_engines(tmp_path):
    reg = _registry(tmp_path, ("a", "b"))
    try:
        for name in ("a", "b"):
            stack = reg.resolve(name)
            _assert_bitwise(stack, _solo(stack.artifact))
    finally:
        reg.close()


def test_degradation_ladders_are_independent(tmp_path):
    """Satellite: one tenant walking its ladder down must not move a
    neighbor's level or its answers — the ladder, window, and cache
    live in the per-tenant stack, not in any shared middle."""
    reg = _registry(tmp_path, ("a", "b"))
    try:
        a, b = reg.resolve("a"), reg.resolve("b")
        solo_b = _solo(b.artifact)
        _assert_bitwise(b, solo_b)
        assert a.batcher.degrade_level == 0
        a.batcher._ladder.observe(1.0)  # sustained pressure on a only
        assert a.batcher.degrade_level >= 1
        assert b.batcher.degrade_level == 0
        _assert_bitwise(b, solo_b)  # b's answers untouched, bitwise
        summaries = {s["tenant"]: s
                     for s in (t.summary() for t in reg.tenants())}
        assert summaries["a"]["degrade_level"] >= 1
        assert summaries["b"]["degrade_level"] == 0
    finally:
        reg.close()
