"""IVF index (serve/index.py + the engine's probing path): builder
determinism, assignment totality, recall on a clustered table, the
degenerate-probe identity on all three manifold specs, cell-layout edge
cases (empty / single-row / capacity), fallback rules, artifact
round-trip, and batcher cache-key isolation (ISSUE 8)."""

import numpy as np
import pytest
import jax.numpy as jnp

from hyperspace_tpu.manifolds import (Euclidean, Lorentz, PoincareBall,
                                      Product, Sphere)
from hyperspace_tpu.serve import (QueryEngine, RequestBatcher, build_index,
                                  export_artifact, load_artifact)
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.engine import _topk_ivf
from hyperspace_tpu.serve.index import (IVF_MIN_TABLE_ROWS, ServingIndex,
                                        auto_ncells, index_fingerprint_of)


def _poincare_table(rng, n, d, c=1.0, scale=0.5):
    v = jnp.asarray(rng.standard_normal((n, d)) * scale, jnp.float32)
    return np.asarray(PoincareBall(c).expmap0(v)), PoincareBall(c)


def _lorentz_table(rng, n, d, c=0.8):
    man = Lorentz(c)
    v = jnp.asarray(rng.standard_normal((n, d + 1)) * 0.5, jnp.float32)
    v = v.at[:, 0].set(0.0)
    return np.asarray(man.expmap0(v)), man


def _product_table(rng, n):
    man = Product([PoincareBall(1.1), Sphere(0.9), Euclidean()], [3, 3, 2])
    v = jnp.asarray(rng.standard_normal((n, 8)) * 0.3, jnp.float32)
    pt = man.proj(man.expmap0(man.proju(man.origin((n, 8)), v)))
    return np.asarray(pt), man


def _clustered_poincare(rng, n, d, nclusters=64):
    """Cluster-structured ball table at f32-healthy radii — the regime
    real embedding tables (trees, communities) live in, and the one an
    IVF index is FOR."""
    centers = rng.standard_normal((nclusters, d)) * 0.25
    v = (centers[rng.integers(0, nclusters, size=n)]
         + rng.standard_normal((n, d)) * 0.05)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(v, jnp.float32)))
    return table, PoincareBall(1.0)


def _manual_index(centroids, cells, counts, n):
    cells = np.asarray(cells, np.int32)
    counts = np.asarray(counts, np.int32)
    centroids = np.asarray(centroids, np.float32)
    fp = index_fingerprint_of(centroids, cells, counts, num_nodes=n,
                              iters=0, seed=0)
    return ServingIndex(centroids=centroids, cells=cells, counts=counts,
                        num_nodes=n, iters=0, seed=0, fingerprint=fp)


# --- builder ------------------------------------------------------------------


def test_builder_deterministic_under_fixed_seed(rng):
    table, man = _poincare_table(rng, 500, 6)
    spec = spec_from_manifold(man)
    a = build_index(table, spec, 16, iters=5, seed=3)
    b = build_index(table, spec, 16, iters=5, seed=3)
    assert a.fingerprint == b.fingerprint
    assert np.array_equal(a.centroids.view(np.uint32),
                          b.centroids.view(np.uint32))
    assert np.array_equal(a.cells, b.cells)
    # a different seed is a different build (seeding really is seeded)
    c = build_index(table, spec, 16, iters=5, seed=4)
    assert c.fingerprint != a.fingerprint


@pytest.mark.parametrize("build", ["poincare", "lorentz", "product"])
def test_assignment_totality(rng, build):
    """Every table row lands in exactly one cell, on every manifold
    family — the invariant the degenerate-probe identity rests on."""
    if build == "product":
        table, man = _product_table(rng, 300)
    else:
        table, man = (_poincare_table if build == "poincare"
                      else _lorentz_table)(rng, 300, 6)
    idx = build_index(table, spec_from_manifold(man), 8, iters=4, seed=0)
    ids = np.sort(idx.cells[idx.cells >= 0])
    assert np.array_equal(ids, np.arange(300))
    assert int(idx.counts.sum()) == 300
    assert idx.max_cell == int(idx.counts.max())


def test_builder_validation(rng):
    table, man = _poincare_table(rng, 40, 4)
    spec = spec_from_manifold(man)
    with pytest.raises(ValueError, match="ncells"):
        build_index(table, spec, 1)
    with pytest.raises(ValueError, match="ncells"):
        build_index(table, spec, 41)
    with pytest.raises(ValueError, match=r"\[N, D\]"):
        build_index(table[0], spec, 4)
    # balance < 1 undershoots total capacity — the cap guarantee would
    # silently break, so it must refuse (0 stays the disable switch)
    with pytest.raises(ValueError, match="balance"):
        build_index(table, spec, 4, balance=0.5)
    build_index(table, spec, 4, balance=0)  # disabled: fine


def test_balance_caps_the_cell_pitch(rng):
    """A deliberately skewed table (one dense clump + a thin halo) must
    come out with max_cell <= ceil(balance*N/ncells) — the dense pitch
    is the probe's work unit, so one mega-cell taxes every query."""
    rng2 = np.random.default_rng(7)
    clump = rng2.standard_normal((900, 4)) * 0.02
    halo = rng2.standard_normal((100, 4)) * 0.9
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(np.concatenate([clump, halo]), jnp.float32)))
    idx = build_index(table, ("poincare", 1.0), 10, iters=4, seed=0,
                      balance=2.0)
    assert idx.max_cell <= -(-2 * 1000 // 10)  # ceil(balance*N/ncells)
    ids = np.sort(idx.cells[idx.cells >= 0])
    assert np.array_equal(ids, np.arange(1000))  # spill keeps totality


def test_auto_ncells_scales_like_sqrt():
    assert auto_ncells(4) == 2
    assert auto_ncells(10_000) == 100
    assert auto_ncells(50_000_000) == 4096  # clamped


# --- probe correctness --------------------------------------------------------


@pytest.mark.parametrize("build", ["poincare", "lorentz", "product"])
def test_full_coverage_probe_is_rank_identical(rng, build):
    """nprobe=ncells through the REAL probe program covers every row
    exactly once (totality), so it must return the exact engine's
    ranking on all three manifold specs — distances through the
    candidate scorer agree with the slab scan to f32 tolerance."""
    if build == "product":
        table, man = _product_table(rng, 300)
        q = np.asarray([0, 7, 150, 299], np.int32)
    else:
        table, man = (_poincare_table if build == "poincare"
                      else _lorentz_table)(rng, 300, 6)
        q = np.asarray([0, 3, 17, 150, 299], np.int32)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 8, iters=4, seed=0)
    exact = QueryEngine(table, spec, chunk_rows=128)
    ei, ed = (np.asarray(a) for a in exact.topk_neighbors(q, 7))
    ii, idd = (np.asarray(a) for a in _topk_ivf(
        exact.table, exact.scan_table, jnp.asarray(idx.centroids),
        jnp.asarray(idx.cells), jnp.asarray(q), spec=spec, k=7, k_scan=7,
        nprobe=idx.ncells, chunk=128, exclude_self=True, mixed=False))
    assert np.array_equal(ii, ei)
    np.testing.assert_allclose(idd, ed, rtol=1e-5, atol=1e-5)
    assert np.all(np.diff(idd, axis=1) >= 0)  # ascending


def test_engine_recall_on_clustered_table(rng):
    """The satellite contract: recall@10 >= 0.95 at nprobe=4/ncells=32
    on a 5k clustered Poincaré table, through the engine path."""
    n = 5000
    table, man = _clustered_poincare(rng, n, 8)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 32, iters=6, seed=0)
    exact = QueryEngine(table, spec)
    ivf = QueryEngine(table, spec, index=idx, nprobe=4)
    assert ivf.scan_strategy == "ivf"
    q = rng.integers(0, n, size=128).astype(np.int32)
    ei, _ = (np.asarray(a) for a in exact.topk_neighbors(q, 10))
    ii, dd = (np.asarray(a) for a in ivf.topk_neighbors(q, 10))
    recall = np.mean([len(set(ei[j]) & set(ii[j])) / 10
                      for j in range(len(q))])
    assert recall >= 0.95, f"recall@10 = {recall}"
    # probed results are well-formed: ascending, in range, no self
    assert np.all(np.diff(dd, axis=1) >= 0)
    assert ii.min() >= 0 and ii.max() < n
    assert not np.any(ii == q[:, None])


def test_exclude_self_across_cell_boundaries(rng):
    """exclude_self masks the query's own row wherever its cell lands —
    including when the probe reaches it through a non-nearest cell —
    and exclude_self=False returns it first at distance 0."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, nclusters=16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=5, seed=0)
    ivf = QueryEngine(table, spec, index=idx, nprobe=4)
    q = rng.integers(0, n, size=64).astype(np.int32)
    ii, _ = (np.asarray(a) for a in ivf.topk_neighbors(q, 5))
    assert not np.any(ii == q[:, None])
    ji, jd = (np.asarray(a) for a in
              ivf.topk_neighbors(q, 5, exclude_self=False))
    assert np.array_equal(ji[:, 0], q)  # own row is the nearest
    # the matmul-shaped closed form's self-distance sits on the f32
    # cancellation floor (~sqrt(eps)), not at exactly 0 — same floor
    # the exact engine's pdist tiles have
    np.testing.assert_allclose(jd[:, 0], 0.0, atol=2e-3)


def test_empty_cells_never_surface(rng):
    """A cell with zero rows (all -1) contributes nothing — probing it
    alongside the full cell still returns the exact answer."""
    table, man = _poincare_table(rng, 64, 4)
    spec = spec_from_manifold(man)
    # cell 0 holds every row; cells 1..3 are empty
    cells = np.full((4, 64), -1, np.int32)
    cells[0] = np.arange(64)
    idx = _manual_index(table[:4], cells, [64, 0, 0, 0], 64)
    exact = QueryEngine(table, spec)
    ei, ed = (np.asarray(a) for a in
              exact.topk_neighbors(np.arange(5, dtype=np.int32), 6))
    ii, idd = (np.asarray(a) for a in _topk_ivf(
        exact.table, exact.scan_table, jnp.asarray(idx.centroids),
        jnp.asarray(idx.cells), jnp.arange(5, dtype=jnp.int32), spec=spec,
        k=6, k_scan=6, nprobe=4, chunk=128, exclude_self=True,
        mixed=False))
    assert np.array_equal(ii, ei)
    np.testing.assert_allclose(idd, ed, rtol=1e-5, atol=1e-5)
    assert np.all(ii >= 0)


def test_single_row_cells(rng):
    """ncells == N degenerates to one row per cell: probing the p
    nearest cells IS a p-nearest-centroid search, so top-k over them
    matches the exact top-k for k <= p."""
    table, man = _poincare_table(rng, 16, 4, scale=1.2)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=3, seed=0)
    assert idx.max_cell == 1 and np.all(idx.counts == 1)
    exact = QueryEngine(table, spec)
    q = np.asarray([0, 9, 15], np.int32)
    ei, _ = (np.asarray(a) for a in exact.topk_neighbors(q, 3))
    ii, _ = (np.asarray(a) for a in _topk_ivf(
        exact.table, exact.scan_table, jnp.asarray(idx.centroids),
        jnp.asarray(idx.cells), jnp.asarray(q), spec=spec, k=3, k_scan=3,
        nprobe=4, chunk=128, exclude_self=True, mixed=False))
    assert np.array_equal(ii, ei)


def test_bf16_probe_rank_agreement(rng):
    """precision=bf16 composes with probing: same neighbors as the f32
    probe at ordinary point distributions (the precision contract —
    docs/precision.md), distances f32-accurate (the rescore ran).  Both
    engines probe the SAME cells (centroid scoring is f32 either way),
    so this isolates the in-cell scan dtype."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _poincare_table(rng, n, 8)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=5, seed=0)
    e32 = QueryEngine(table, spec, index=idx, nprobe=6)
    e16 = QueryEngine(table, spec, index=idx, nprobe=6, precision="bf16")
    q = rng.integers(0, n, size=64).astype(np.int32)
    i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, 5))
    i16, d16 = (np.asarray(a) for a in e16.topk_neighbors(q, 5))
    assert np.array_equal(i32, i16)
    np.testing.assert_allclose(d32, d16, rtol=1e-5, atol=1e-5)


# --- fallback rules and validation --------------------------------------------


def test_fallback_rules(rng):
    table, man = _poincare_table(rng, 300, 4)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 8, iters=3, seed=0)
    # nprobe=0: exact, index carried but dormant
    e = QueryEngine(table, spec, index=idx)
    assert e.scan_strategy == "exact" and e.scan_signature == ("exact",)
    # sub-threshold table: exact even with nprobe > 0
    e = QueryEngine(table, spec, index=idx, nprobe=2)
    assert 300 < IVF_MIN_TABLE_ROWS and e.scan_strategy == "exact"
    # nprobe >= ncells: the degenerate probe is served exactly
    big, _ = _poincare_table(rng, IVF_MIN_TABLE_ROWS, 4)
    bidx = build_index(big, spec, 8, iters=3, seed=0)
    e = QueryEngine(big, spec, index=bidx, nprobe=8)
    assert e.scan_strategy == "exact"
    e = QueryEngine(big, spec, index=bidx, nprobe=4)
    assert e.scan_strategy == "ivf"
    assert e.scan_signature == ("ivf", 4, bidx.fingerprint)


def test_validation_errors(rng):
    table, man = _poincare_table(rng, 300, 4)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 8, iters=3, seed=0)
    with pytest.raises(ValueError, match="nprobe"):
        QueryEngine(table, spec, nprobe=-1)
    with pytest.raises(ValueError, match="needs an IVF index"):
        QueryEngine(table, spec, nprobe=2)
    with pytest.raises(ValueError, match="built over"):
        QueryEngine(table[:200], spec, index=idx, nprobe=2)
    other, _ = _poincare_table(rng, 300, 6)
    with pytest.raises(ValueError, match="width"):
        QueryEngine(other, spec, index=idx, nprobe=2)


def test_k_beyond_probe_capacity_rejected(rng):
    """nprobe × max_cell bounds what a probe can ever see; a k past it
    must fail loudly, not return -1 rows — and an UNDER-FILLED probe
    (enough padded slots, too few reachable rows: sparse cells, or
    exclude_self masking one) must fail just as loudly, because -1/+inf
    filler is not an answer and +inf is not JSON."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _poincare_table(rng, n, 4)
    spec = spec_from_manifold(man)
    cells = np.arange(n, dtype=np.int32).reshape(n // 2, 2)
    idx = _manual_index(table[:n // 2], cells, np.full(n // 2, 2), n)
    e = QueryEngine(table, spec, index=idx, nprobe=1)
    with pytest.raises(ValueError, match="capacity"):
        e.topk_neighbors(np.asarray([0], np.int32), 3)
    # at capacity with the self row masked: only 1 reachable row for
    # k=2 — the under-fill check fires instead of returning a -1 slot
    with pytest.raises(ValueError, match="under-filled"):
        e.topk_neighbors(np.asarray([0], np.int32), 2)
    # keeping the self row fills the cell: both rows come back
    i, d = e.topk_neighbors(np.asarray([0], np.int32), 2,
                            exclude_self=False)
    assert np.asarray(i).shape == (1, 2)
    assert np.all(np.asarray(i) >= 0) and np.all(np.isfinite(np.asarray(d)))


# --- persistence and batcher integration --------------------------------------


def test_artifact_round_trip_with_index(rng, tmp_path):
    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, nclusters=16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=4, seed=0)
    bare = export_artifact(str(tmp_path / "bare"), table, spec)
    art = export_artifact(str(tmp_path / "ivf"), table, spec, index=idx)
    # the fingerprint COVERS the index: same table, different identity
    assert art.fingerprint != bare.fingerprint
    loaded = load_artifact(str(tmp_path / "ivf"))
    assert loaded.fingerprint == art.fingerprint
    assert loaded.index is not None
    assert loaded.index.fingerprint == idx.fingerprint
    assert np.array_equal(loaded.index.cells, idx.cells)
    assert np.array_equal(loaded.index.centroids.view(np.uint32),
                          idx.centroids.view(np.uint32))
    # engine from the loaded artifact probes bitwise like the live one
    live = QueryEngine(table, spec, index=idx, nprobe=4)
    served = QueryEngine.from_artifact(loaded, nprobe=4)
    q = rng.integers(0, n, size=32).astype(np.int32)
    li, ld = (np.asarray(a) for a in live.topk_neighbors(q, 5))
    si, sd = (np.asarray(a) for a in served.topk_neighbors(q, 5))
    assert np.array_equal(li, si)
    assert np.array_equal(ld.view(np.uint32), sd.view(np.uint32))
    # a bare artifact still loads with index=None and serves exactly
    loaded_bare = load_artifact(str(tmp_path / "bare"))
    assert loaded_bare.index is None
    e = QueryEngine.from_artifact(loaded_bare)
    assert e.scan_strategy == "exact"


def test_index_tamper_detected(rng, tmp_path):
    import os

    n = IVF_MIN_TABLE_ROWS
    table, man = _poincare_table(rng, n, 4)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 8, iters=3, seed=0)
    out = str(tmp_path / "art")
    export_artifact(out, table, spec, index=idx)
    # swap the index arrays under the marker: load must refuse
    np.savez(os.path.join(out, "index.npz"), centroids=idx.centroids,
             cells=np.roll(idx.cells, 1, axis=0), counts=idx.counts)
    with pytest.raises(ValueError, match="index fingerprint"):
        load_artifact(out)


def test_truncated_index_meta_is_a_value_error(rng, tmp_path):
    """A hand-edited/truncated index meta block answers the module's
    corrupt-artifact ValueError (clean CLI exit), not a raw KeyError."""
    import json
    import os

    n = IVF_MIN_TABLE_ROWS
    table, man = _poincare_table(rng, n, 4)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 8, iters=3, seed=0)
    out = str(tmp_path / "art")
    export_artifact(out, table, spec, index=idx)
    meta_path = os.path.join(out, "artifact.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["index"]["iters"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError, match="missing"):
        load_artifact(out)


def test_batcher_cache_isolates_exact_from_probed(rng):
    """The LRU key carries the scan signature: an approximate probed
    row must never answer an exact query over the SAME table (same
    artifact fingerprint), nor a probe at another nprobe."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, nclusters=16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=4, seed=0)
    exact = QueryEngine(table, spec)
    ivf = QueryEngine(table, spec, index=idx, nprobe=2)
    assert exact.fingerprint == ivf.fingerprint  # same table bytes
    b_exact = RequestBatcher(exact)
    b_ivf = RequestBatcher(ivf)
    ids = list(range(16))
    b_exact.topk(ids, 4)
    b_ivf.topk(ids, 4)
    assert not ({k for k in b_exact.cache._d}
                & {k for k in b_ivf.cache._d})
    assert b_exact.stats()["scan_strategy"] == "exact"
    assert b_ivf.stats()["scan_strategy"] == "ivf"
    assert b_ivf.stats()["nprobe"] == 2


def test_probe_telemetry_lands(rng):
    """The probing path observes serve/index_probe_ms and counts
    serve/recall_candidates (the catalog rows)."""
    from hyperspace_tpu.telemetry import registry as telem

    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, nclusters=16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=4, seed=0)
    ivf = QueryEngine(table, spec, index=idx, nprobe=2)
    reg = telem.default_registry()
    base = reg.mark()
    q = np.arange(8, dtype=np.int32)
    ivf.topk_neighbors(q, 4)
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/recall_candidates") == 8 * 2 * idx.max_cell
    hist = delta.get("hist/serve/index_probe_ms")
    assert hist and hist["count"] == 1


def test_lloyd_fused_assignment_matches_argmin(rng, monkeypatch):
    """On a kernel backend the Lloyd assignment runs the fused k=1
    scan-top-k (kernels/scan_topk.py) instead of the [chunk, ncells]
    argmin — the built index must come out the same (well-separated
    clusters: no boundary ties for ulp differences to flip)."""
    table, man = _clustered_poincare(rng, 600, 5, nclusters=8)
    spec = spec_from_manifold(man)
    import jax

    base = build_index(table, spec, 8, iters=2, seed=0)
    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    jax.clear_caches()  # _lloyd is jitted; the mode is read at trace time
    fused = build_index(table, spec, 8, iters=2, seed=0)
    assert np.array_equal(base.cells, fused.cells)
    assert np.array_equal(base.counts, fused.counts)
    np.testing.assert_allclose(base.centroids, fused.centroids,
                               rtol=1e-5, atol=1e-6)
