"""Host-streamed IVF builds (serve/index.py ``host_resident`` path +
sampled k-means++ seeding) — the beyond-HBM builder's regression
contract: bounded device residency, totality, and agreement with the
resident Lloyd loop from equal seeds."""

import numpy as np
import jax.numpy as jnp
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.parallel.host_table import HostEmbedTable
from hyperspace_tpu.serve import index as ix
from hyperspace_tpu.telemetry import registry as telem


def _ball_table(rng, n, d=8, scale=0.3):
    v = rng.standard_normal((n, d)).astype(np.float32) * scale
    nv = np.maximum(np.linalg.norm(v, axis=1, keepdims=True), 1e-12)
    return (np.tanh(nv) * v / nv).astype(np.float32)


def test_streamed_lloyd_matches_resident_from_equal_seeds(rng):
    """The equivalence contract: same cent0 through the jitted resident
    scan and the host-streamed chunk loop → IDENTICAL assignments,
    float-tolerance-equal centroids (same per-chunk math in the same
    fold order; XLA schedules the scan's accumulates differently, so
    bitwise is not promised)."""
    n, d, ncells, chunk = 20_000, 8, 32, 4096
    tab = _ball_table(rng, n, d)
    cent0 = jnp.asarray(tab[rng.choice(n, ncells, replace=False)])
    spec = ("poincare", 1.0)
    npad = -(-n // chunk) * chunk
    tpad = jnp.concatenate([jnp.asarray(tab),
                            jnp.zeros((npad - n, d), jnp.float32)])
    c1, a1 = ix._lloyd(tpad, cent0, jnp.int32(n), spec=spec, chunk=chunk,
                       iters=3, ncells=ncells)
    c2, a2 = ix._lloyd_stream(tab, cent0, spec=spec, chunk=chunk,
                              iters=3, ncells=ncells)
    assert np.array_equal(np.asarray(a1)[:n], np.asarray(a2))
    assert np.allclose(np.asarray(c1), np.asarray(c2),
                       rtol=1e-5, atol=1e-7)


def test_200k_streamed_build_time_and_peak_shape(rng):
    """The satellite regression (ISSUE 14): a ~200k build through the
    streamed path completes with bounded per-block device residency
    (the peak gauge reads the chunk height, never N), full assignment
    totality, and the balance cap intact."""
    n = 200_000
    tab = _ball_table(rng, n)
    idx = ix.build_index(tab, ("poincare", 1.0), 64, iters=2, seed=0,
                         seed_sample=8192, host_resident=True)
    peak = telem.default_registry().snapshot()[
        "index/build_device_rows_peak"]
    assert peak == ix._BUILD_CHUNK  # one [chunk, D] block at a time
    assert np.sum(idx.counts) == n  # totality
    ids = idx.cells[idx.cells >= 0]
    assert len(ids) == n and len(np.unique(ids)) == n
    assert idx.max_cell <= int(np.ceil(2.0 * n / 64))  # balance cap


def test_host_table_source_builds_identically_to_ndarray(rng):
    """A HostEmbedTable source streams by construction and produces the
    SAME cell layout as the streamed build over the equivalent ndarray
    (sharding moves the chunk boundaries — `iter_chunks` never crosses
    a shard — so centroid accumulates regroup and agree only to float
    tolerance; the ASSIGNMENTS are the behavioral contract)."""
    n = 12_000
    tab = _ball_table(rng, n)
    i_nd = ix.build_index(tab, ("poincare", 1.0), 24, iters=2, seed=0,
                          seed_sample=n, host_resident=True)
    ht = HostEmbedTable.from_array(tab.copy(), shards=3)
    i_ht = ix.build_index(ht, ("poincare", 1.0), 24, iters=2, seed=0,
                          seed_sample=n)
    assert np.array_equal(i_nd.cells, i_ht.cells)
    assert np.array_equal(i_nd.counts, i_ht.counts)
    assert np.allclose(np.asarray(i_nd.centroids),
                       np.asarray(i_ht.centroids), rtol=1e-5, atol=1e-7)


def test_streamed_index_serves_with_good_recall(rng):
    """The built index is not just well-shaped — probing through it
    recovers the exact engine's neighbors at production recall."""
    from hyperspace_tpu.serve.engine import QueryEngine

    n = 8192
    # cluster structure so the cells mean something
    centers = rng.standard_normal((64, 8)) * 0.25
    v = (centers[rng.integers(0, 64, n)]
         + rng.standard_normal((n, 8)) * 0.05).astype(np.float32)
    tab = np.asarray(PoincareBall(1.0).expmap0(jnp.asarray(v)))
    idx = ix.build_index(tab, ("poincare", 1.0), 32, iters=4, seed=0,
                         seed_sample=4096, host_resident=True)
    ids = rng.integers(0, n, 64)
    ex = QueryEngine(tab, ("poincare", 1.0))
    ei, _ = (np.asarray(a) for a in ex.topk_neighbors(ids, 10))
    ep = QueryEngine(tab, ("poincare", 1.0), index=idx, nprobe=8)
    pi, _ = (np.asarray(a) for a in ep.topk_neighbors(ids, 10))
    rec = np.mean([len(set(ei[j]) & set(pi[j])) / 10
                   for j in range(len(ids))])
    assert rec >= 0.95


def test_seed_sample_and_host_resident_validation(rng):
    tab = _ball_table(rng, 4096)
    with pytest.raises(ValueError, match="seed_sample"):
        ix.build_index(tab, ("poincare", 1.0), 64, seed_sample=32,
                       host_resident=True)
    ht = HostEmbedTable.from_array(tab.copy())
    with pytest.raises(ValueError, match="host-resident"):
        ix.build_index(ht, ("poincare", 1.0), 16, host_resident=False)
