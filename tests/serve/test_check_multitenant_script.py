"""The multi-tenant smoke lint, run inside the suite: two artifacts →
ONE ``serve-http tenants=`` subprocess → route by name + fingerprint
(bitwise vs solo engines) → unknown tenant 404 → paging round trip
under a device budget → SIGTERM drain (scripts/check_multitenant.py is
the one implementation — this test fails the build when it fails,
mirroring test_check_live_script.py)."""

import importlib.util
import os

import pytest


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_multitenant.py")
    spec = importlib.util.spec_from_file_location("check_multitenant",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.flaky  # a loaded CI host can starve the subprocess launch
def test_multitenant_front_door_lint_passes(tmp_path, capsys):
    mod = _load_checker()
    rc = mod.main(str(tmp_path / "tenants"))
    out = capsys.readouterr().out
    assert rc == 0, f"multi-tenant front-door lint failed:\n{out}"
    assert "multi-tenant front door OK" in out
    assert "paging round trip" in out
