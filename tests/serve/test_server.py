"""The asyncio HTTP front door: routing, status↔taxonomy mapping,
deadline propagation from socket-in (the PR 9 batcher deadline tests,
now through the socket path), 429 shedding, drain, recompile flatness."""

import asyncio
import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.resilience import faults
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.server import HttpFrontDoor
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(1)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((256, 4)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    eng.topk_neighbors(np.zeros(8, np.int32), 4)  # warm (8, 4)
    return eng


async def _request(host, port, method, path, payload=None, raw=None,
                   keep_alive=False, rw=None):
    """(status, parsed body[, (reader, writer)]): one HTTP round trip.
    ``rw`` reuses a keep-alive connection; ``keep_alive`` keeps it."""
    if rw is None:
        reader, writer = await asyncio.open_connection(host, port)
    else:
        reader, writer = rw
    body = (raw if raw is not None
            else b"" if payload is None
            else json.dumps(payload).encode())
    conn = "keep-alive" if keep_alive else "close"
    writer.write((f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                  f"Content-Length: {len(body)}\r\n"
                  f"Connection: {conn}\r\n\r\n").encode() + body)
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    clen = 0
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, val = h.decode().partition(":")
        if name.strip().lower() == "content-length":
            clen = int(val)
    resp = json.loads((await reader.readexactly(clen)).decode())
    if keep_alive:
        return status, resp, (reader, writer)
    writer.close()
    return status, resp


def _door(engine, **kw):
    bat_kw = {k: kw.pop(k) for k in ("queue_max", "deadline_ms",
                                     "cache_size", "ladder_down_after")
              if k in kw}
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=bat_kw.pop("cache_size", 0),
                         **bat_kw)
    return HttpFrontDoor(bat, **kw), bat


def _run(engine, coro_fn, **kw):
    """Start a door, run the test coroutine against it, drain."""
    door, bat = _door(engine, **kw)

    async def main():
        await door.start()
        try:
            return await coro_fn(door, bat)
        finally:
            await door.drain()

    return asyncio.run(main()), door


def test_topk_score_stats_healthz_round_trip(engine):
    async def go(door, bat):
        h, p = door.host, door.port
        out = {}
        out["topk"] = await _request(h, p, "POST", "/v1/topk",
                                     {"ids": [1, 2, 3], "k": 4})
        out["score"] = await _request(h, p, "POST", "/v1/score",
                                      {"u": [0, 1], "v": [2, 3],
                                       "prob": True})
        out["stats"] = await _request(h, p, "GET", "/v1/stats")
        out["health"] = await _request(h, p, "GET", "/healthz")
        return out

    out, door = _run(engine, go)
    status, r = out["topk"]
    assert status == 200
    ref_i, ref_d = (np.asarray(a) for a in engine.topk_neighbors(
        np.asarray([1, 2, 3], np.int32), 4))
    np.testing.assert_array_equal(np.asarray(r["neighbors"]), ref_i)
    np.testing.assert_array_equal(
        np.asarray(r["dists"], np.float32).view(np.uint32),
        ref_d.astype(np.float32).view(np.uint32))
    status, r = out["score"]
    assert status == 200 and len(r["scores"]) == 2
    assert all(0.0 <= s <= 1.0 for s in r["scores"])  # prob=True
    status, r = out["stats"]
    assert status == 200
    assert r["server"]["draining"] is False
    assert "recompiles" in r and "scan_strategy" in r
    status, r = out["health"]
    assert status == 200 and r["ok"] is True
    assert door.served == 4


def test_error_taxonomy_maps_to_status_codes(engine):
    """parse/validation → 400, unknown route → 404, wrong method →
    405, deadline → 504; every request answers exactly one typed
    response and the server keeps serving."""
    async def go(door, bat):
        h, p = door.host, door.port
        rows = [
            await _request(h, p, "POST", "/v1/topk",
                           raw=b"this is not json"),
            await _request(h, p, "POST", "/v1/topk",
                           {"ids": [0.5], "k": 4}),
            await _request(h, p, "POST", "/v1/topk",
                           {"ids": [1], "k": 4, "deadline_ms": "soon"}),
            await _request(h, p, "POST", "/v1/nope", {}),
            await _request(h, p, "GET", "/v1/topk"),
            await _request(h, p, "POST", "/v1/topk",
                           {"ids": [1], "k": 4, "deadline_ms": 1e-4}),
            await _request(h, p, "POST", "/v1/topk",
                           {"ids": [1], "k": 4}),  # still serving
        ]
        return rows

    rows, _ = _run(engine, go)
    (parse, bad_id, bad_dl, no_route, bad_method, expired, ok) = rows
    assert parse[0] == 400 and parse[1]["error"]["kind"] == "parse"
    assert bad_id[0] == 400 and bad_id[1]["error"]["kind"] == "validation"
    assert bad_dl[0] == 400 and bad_dl[1]["error"]["kind"] == "validation"
    assert no_route[0] == 404
    assert bad_method[0] == 405
    assert expired[0] == 504
    assert expired[1]["error"]["kind"] == "deadline_exceeded"
    assert ok[0] == 200 and "neighbors" in ok[1]


def test_deadline_expires_queued_in_collator_socket_path(engine):
    """Satellite contract, through the socket: a request whose deadline
    expires while queued in the collator is never dispatched and
    answers deadline_exceeded (HTTP 504) — queue time counts against
    the budget because t_enq is the socket-in stamp."""
    reg = telem.default_registry()

    async def go(door, bat):
        base = reg.mark()
        status, r = await _request(
            door.host, door.port, "POST", "/v1/topk",
            {"ids": [7], "k": 4, "deadline_ms": 30.0})
        return base, status, r

    (base, status, r), _ = _run(engine, go, max_wait_us=500_000)
    assert status == 504
    assert r["error"]["kind"] == "deadline_exceeded"
    assert "queued in the collator" in r["error"]["message"]
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/deadline_exceeded") == 1
    assert delta.get("serve/slots", 0) == 0  # never dispatched
    # failed requests observe no latency histograms
    assert "hist/serve/e2e_ms" not in delta


def test_deadline_expires_mid_flight_still_caches_socket_path(engine):
    """Satellite contract, through the socket: a request that expires
    MID-FLIGHT (injected dispatch latency) answers 504 — but its rows
    stay cached, so the same ids answer 200 from cache right after."""
    reg = telem.default_registry()
    faults.install([faults.FaultSpec(site="serve.dispatch",
                                     kind="latency", ms=150.0,
                                     times=1)])

    async def go(door, bat):
        h, p = door.host, door.port
        base = reg.mark()
        late = await _request(h, p, "POST", "/v1/topk",
                              {"ids": [5, 6], "k": 4,
                               "deadline_ms": 60.0})
        mid = reg.snapshot(baseline=base)
        base2 = reg.mark()
        hot = await _request(h, p, "POST", "/v1/topk",
                             {"ids": [5, 6], "k": 4,
                              "deadline_ms": 60.0})
        return late, mid, hot, reg.snapshot(baseline=base2)

    (late, mid, hot, delta2), _ = _run(engine, go, max_wait_us=1_000,
                                       cache_size=1024)
    assert late[0] == 504
    assert late[1]["error"]["kind"] == "deadline_exceeded"
    assert mid.get("serve/slots") == 8  # it DID dispatch (too late)
    assert hot[0] == 200 and "neighbors" in hot[1]
    assert delta2.get("serve/cache_hit") == 2  # served from cache
    assert delta2.get("serve/slots", 0) == 0


def test_sustained_overload_sheds_http_429(engine):
    """More concurrent requests than queue_max: the excess answers
    HTTP 429 / typed overloaded — never unbounded queueing — and every
    request gets exactly one response."""
    async def go(door, bat):
        h, p = door.host, door.port
        return await asyncio.gather(
            *[_request(h, p, "POST", "/v1/topk", {"ids": [i], "k": 4})
              for i in range(10)])

    rows, door = _run(engine, go, queue_max=2, ladder_down_after=100,
                      max_wait_us=5_000)
    assert len(rows) == 10  # one response per request, exactly
    ok = [r for s, r in rows if s == 200]
    shed = [(s, r) for s, r in rows if s == 429]
    assert len(ok) + len(shed) == 10
    assert ok and shed
    assert all(r["error"]["kind"] == "overloaded" for _, r in shed)


def test_keep_alive_connection_serves_sequentially(engine):
    """HTTP/1.1 keep-alive: several requests down one connection each
    get one response; recompiles stay FLAT across same-bucket requests
    (the compile-once-per-bucket contract through the socket path)."""
    telem.install_jax_monitoring_hook()
    reg = telem.default_registry()

    async def go(door, bat):
        h, p = door.host, door.port
        # warm the bucket once (first (8,4) compile may land here)
        await _request(h, p, "POST", "/v1/topk", {"ids": [0], "k": 4})
        c0 = reg.get("jax/recompiles")
        s, r, rw = await _request(h, p, "POST", "/v1/topk",
                                  {"ids": [1], "k": 4}, keep_alive=True)
        assert s == 200
        for i in (2, 3, 4):
            s, r, rw = await _request(h, p, "POST", "/v1/topk",
                                      {"ids": [i], "k": 4},
                                      keep_alive=True, rw=rw)
            assert s == 200 and len(r["neighbors"]) == 1
        rw[1].close()
        return reg.get("jax/recompiles") - c0

    steady_recompiles, door = _run(engine, go)
    assert steady_recompiles == 0
    assert door.served >= 5


def test_drain_answers_inflight_and_refuses_new(engine):
    """Drain: the in-flight request is answered, the listener refuses
    new connections, an IDLE keep-alive connection cannot block the
    drain, and healthz reports not-ok while draining."""
    faults.install([faults.FaultSpec(site="serve.dispatch",
                                     kind="latency", ms=120.0,
                                     times=1)])

    async def go_outer():
        door, bat = _door(engine, max_wait_us=1_000)
        await door.start()
        h, p = door.host, door.port
        # an idle keep-alive connection parks in the read/drain race
        _s, _r, idle_rw = await _request(h, p, "POST", "/v1/topk",
                                         {"ids": [0], "k": 4},
                                         keep_alive=True)
        # in-flight slow request, then drain while it runs
        inflight = asyncio.ensure_future(
            _request(h, p, "POST", "/v1/topk", {"ids": [9], "k": 4}))
        await asyncio.sleep(0.03)  # let it reach the dispatch
        t0 = time.perf_counter()
        await door.drain()
        drain_s = time.perf_counter() - t0
        status, r = await inflight
        refused = False
        try:
            await asyncio.open_connection(h, p)
        except OSError:
            refused = True
        idle_rw[1].close()
        return status, r, refused, drain_s, door

    status, r, refused, drain_s, door = asyncio.run(go_outer())
    assert status == 200 and "neighbors" in r  # in-flight answered
    assert refused  # listener closed: new connections refused
    assert drain_s < 10.0  # the idle keep-alive did not block drain
    assert door.draining


def test_draining_healthz_and_stats_report_it(engine):
    async def go_outer():
        door, bat = _door(engine, max_wait_us=1_000)
        await door.start()
        # drain with no traffic, then probe state objects directly (the
        # listener is closed, so HTTP probes can't reach it — the
        # stats/health payloads are what a load balancer saw LAST)
        await door.drain()
        return door

    door = asyncio.run(go_outer())
    assert door.draining
    stats = door._stats()
    assert stats["server"]["draining"] is True


def test_oversized_and_malformed_protocol_lines(engine):
    async def go(door, bat):
        h, p = door.host, door.port
        # malformed request line: answered 400 + close, server survives
        reader, writer = await asyncio.open_connection(h, p)
        writer.write(b"garbage\r\n\r\n")
        await writer.drain()
        first = await reader.readline()
        writer.close()
        # bad Content-Length
        reader, writer = await asyncio.open_connection(h, p)
        writer.write(b"POST /v1/topk HTTP/1.1\r\n"
                     b"Content-Length: banana\r\n\r\n")
        await writer.drain()
        second = await reader.readline()
        writer.close()
        # oversized body: 413, typed validation, BEFORE reading it
        reader, writer = await asyncio.open_connection(h, p)
        writer.write(b"POST /v1/topk HTTP/1.1\r\n"
                     b"Content-Length: 999999999\r\n\r\n")
        await writer.drain()
        third = await reader.readline()
        writer.close()
        ok = await _request(h, p, "POST", "/v1/topk",
                            {"ids": [1], "k": 4})
        return first, second, third, ok

    (first, second, third, ok), _ = _run(engine, go)
    assert b"400" in first
    assert b"400" in second
    assert b"413" in third
    assert ok[0] == 200


def test_cli_serve_http_bind_failure_is_clean_usage_error(engine,
                                                          tmp_path):
    """A port already in use answers a clean SystemExit, not an asyncio
    traceback (the CLI's usage-error contract)."""
    import socket

    from hyperspace_tpu.cli import serve as S

    # hold a port so the bind fails deterministically
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    try:
        cfg = S.ServeConfig(artifact="unused", port=port)

        def fake_build(_cfg):
            bat = RequestBatcher(engine, min_bucket=8, max_bucket=64)
            return engine, bat

        orig = S._build
        S._build = fake_build
        try:
            with pytest.raises(SystemExit, match="cannot bind"):
                S.run_serve_http(cfg)
        finally:
            S._build = orig
    finally:
        sock.close()
