"""Serve-startup bucket prewarm (`RequestBatcher.prewarm` — ISSUE 13
pillar 2, docs/serving.md "Warm starts").

The contracts: every ladder executable is compiled exactly once and
BEFORE traffic (zero recompiles on subsequent traffic, idempotent on a
second prewarm), prewarm respects the engine's scan-signature /
precision isolation (a prewarmed engine is warm for exactly what it
serves — a different mode still compiles fresh), the IVF degradation
ladder's narrowed widths are warmed too, and prewarm traffic never
masquerades as served requests."""

import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.telemetry import registry as telem


def _table(n=300, dim=6, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))


@pytest.fixture(autouse=True)
def _hook():
    telem.install_jax_monitoring_hook()


def _recompiles():
    return telem.default_registry().get("jax/recompiles")


def test_prewarm_covers_every_bucket_and_traffic_stays_flat():
    eng = QueryEngine(_table(), ("poincare", 1.0))
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=32, cache_size=0)
    info = bat.prewarm([5])
    assert info["buckets"] == [8, 16, 32] and info["ks"] == [5]
    # one executable per (ladder bucket × exclude_self flavor)
    assert info["programs"] == 6
    c0 = _recompiles()
    # traffic landing on EVERY rung, BOTH request flavors: all warm
    for n_ids in (3, 12, 30):
        bat.topk(list(range(n_ids)), 5)
        bat.topk(list(range(n_ids)), 5, exclude_self=False)
    assert _recompiles() == c0, "prewarmed traffic recompiled"


def test_prewarm_idempotent_second_pass_compiles_nothing():
    eng = QueryEngine(_table(seed=1), ("poincare", 1.0))
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=16)
    bat.prewarm([4])
    c0 = _recompiles()
    info = bat.prewarm([4])  # every ladder bucket compiled exactly once
    assert _recompiles() == c0
    assert info["programs"] == 4  # 2 buckets × 2 exclude_self flavors


def test_prewarm_counts_no_requests_or_cache_traffic():
    eng = QueryEngine(_table(seed=2), ("poincare", 1.0))
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=16)
    reg = telem.default_registry()
    base = reg.mark()
    bat.prewarm([3])
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/prewarmed", 0) == 4
    assert delta.get("serve/prewarm_s", 0) > 0
    for name in ("serve/requests", "serve/cache_hit", "serve/cache_miss",
                 "serve/slots", "serve/padded_waste"):
        assert delta.get(name, 0) == 0, name
    assert "hist/serve/e2e_ms" not in delta
    assert len(bat.cache) == 0  # no LRU writes
    # and stats surfaces the prewarm + compile counters
    s = bat.stats()
    assert s["prewarmed"] >= 4 and "recompiles" in s


def test_prewarm_precision_isolation():
    """A bf16 engine's prewarm warms the bf16 executables — its own
    traffic is flat, while a fresh f32 engine over the SAME table still
    compiles (prewarm never falsely covers another signature)."""
    # a shape no other test in this process compiles: the jit cache is
    # process-wide, so a shared (dim, k) would warm the control for free
    table = _table(n=280, dim=10, seed=3)
    bf = QueryEngine(table, ("poincare", 1.0), precision="bf16")
    bat_bf = RequestBatcher(bf, min_bucket=8, max_bucket=8, cache_size=0)
    bat_bf.prewarm([9])
    c0 = _recompiles()
    bat_bf.topk([0, 1, 2], 9)
    assert _recompiles() == c0, "bf16 prewarm did not cover bf16 traffic"
    f32 = QueryEngine(table, ("poincare", 1.0))
    bat_f32 = RequestBatcher(f32, min_bucket=8, max_bucket=8,
                             cache_size=0)
    bat_f32.topk([0, 1, 2], 9)
    assert _recompiles() > c0, (
        "an unprewarmed f32 engine answered with no compile — the "
        "isolation assertion proves nothing")


def test_prewarm_scan_mode_isolation():
    """Same for scan signatures: a two_stage prewarm leaves a carry
    engine cold (distinct executables; the batcher cache key already
    keeps their ROWS apart, prewarm keeps their warmth apart)."""
    table = _table(seed=4)
    two = QueryEngine(table, ("poincare", 1.0), scan_mode="two_stage")
    RequestBatcher(two, min_bucket=8, max_bucket=8).prewarm([4])
    c0 = _recompiles()
    carry = QueryEngine(table, ("poincare", 1.0), scan_mode="carry")
    RequestBatcher(carry, min_bucket=8, max_bucket=8,
                   cache_size=0).topk([0, 1], 4)
    assert _recompiles() > c0


def test_prewarm_ivf_ladder_widths_all_warm():
    """A probing engine with overload machinery warms the degradation
    ladder's narrowed nprobe widths too — stepping down under pressure
    must not hand the compiler a fresh program."""
    import jax.numpy as jnp

    from hyperspace_tpu.serve.index import IVF_MIN_TABLE_ROWS, build_index

    rng = np.random.default_rng(5)
    n = max(IVF_MIN_TABLE_ROWS, 2048)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, 6)) * 0.3, jnp.float32)))
    idx = build_index(table, ("poincare", 1.0), 16, iters=2, seed=0,
                      balance=3.0)
    eng = QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=8)
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=8, cache_size=0,
                         queue_max=4)
    widths = [m for m in bat._modes if isinstance(m, int)]
    assert widths, "ladder has no narrowed widths to prove anything"
    bat.prewarm([4])
    c0 = _recompiles()
    ids = list(range(8))
    eng.topk_neighbors(np.asarray(ids, np.int32), 4)  # full width
    for p in widths:  # every ladder override the batcher can serve
        eng.topk_neighbors(np.asarray(ids, np.int32), 4, nprobe=p)
    assert _recompiles() == c0, "a ladder width was left cold"


def test_prewarm_validates_k():
    eng = QueryEngine(_table(n=50, seed=6), ("poincare", 1.0))
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=8)
    with pytest.raises(ValueError, match="out of range"):
        bat.prewarm([50])  # k == N with exclude_self: one too many
    with pytest.raises(ValueError, match="out of range"):
        bat.prewarm([0])


def test_prewarm_cli_flag_parsing():
    from hyperspace_tpu.cli.serve import ServeConfig, _prewarm_ks

    assert _prewarm_ks(ServeConfig()) == []
    assert _prewarm_ks(ServeConfig(prewarm="1", k=7)) == [7]
    assert _prewarm_ks(ServeConfig(prewarm="true", k=3)) == [3]
    assert _prewarm_ks(ServeConfig(prewarm="5,10")) == [5, 10]
    with pytest.raises(SystemExit):
        _prewarm_ks(ServeConfig(prewarm="abc"))
    with pytest.raises(SystemExit):
        _prewarm_ks(ServeConfig(prewarm="0,-3"))
