"""The live-index smoke lint, run inside the suite: export →
``serve-http live=1`` subprocess → upsert/query/delete round trip over
the socket → SIGTERM drain (scripts/check_live_index.py is the one
implementation — this test fails the build when it fails, mirroring
test_check_http_script.py)."""

import importlib.util
import os

import pytest


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_live_index.py")
    spec = importlib.util.spec_from_file_location("check_live_index",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.flaky  # a loaded CI host can starve the subprocess launch
def test_live_index_round_trip_lint_passes(tmp_path, capsys):
    mod = _load_checker()
    rc = mod.main(str(tmp_path / "artifact"))
    out = capsys.readouterr().out
    assert rc == 0, f"live-index round-trip lint failed:\n{out}"
    assert "live index round trip OK" in out
    assert "recompiles flat" in out
