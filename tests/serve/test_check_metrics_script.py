"""The live-observability smoke lint, run inside the suite: export →
serve-http subprocess with access log + window → /metrics scraped
twice (catalog round trip both directions, counters monotone) →
request-id echo joined to its access-log line and collator flush →
SIGTERM drain (scripts/check_metrics_endpoint.py is the one
implementation — this test fails the build when it fails, mirroring
test_check_http_script.py)."""

import importlib.util
import os

import pytest


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_metrics_endpoint.py")
    spec = importlib.util.spec_from_file_location(
        "check_metrics_endpoint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.flaky  # a loaded CI host can starve the subprocess launch
def test_metrics_endpoint_lint_passes(tmp_path, capsys):
    mod = _load_checker()
    rc = mod.main(str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 0, f"metrics-endpoint lint failed:\n{out}"
    assert "metrics endpoint OK" in out
    assert "joined to flush" in out


def test_exposition_parser_rejects_garbage():
    """The script's parser is itself a contract: unparseable sample
    lines and orphan samples fail loudly (a silently-skipped line
    would let a malformed exposition 'pass' the round trip)."""
    mod = _load_checker()
    with pytest.raises(ValueError, match="unparseable"):
        mod.parse_exposition("# HELP x y\n# TYPE x counter\n{bad\n")
    with pytest.raises(ValueError, match="before any HELP"):
        mod.parse_exposition("orphan_sample 1\n")
    fams = mod.parse_exposition(
        "# HELP hyperspace_a a\n# TYPE hyperspace_a counter\n"
        'hyperspace_a{process_index="0"} 3\n')
    assert fams["hyperspace_a"]["type"] == "counter"
    assert list(fams["hyperspace_a"]["samples"].values()) == [3.0]
