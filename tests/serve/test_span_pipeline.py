"""Span-level pipeline tracing through the serve plane (ISSUE 17): the
per-stage decomposition sums to e2e exactly, stage histograms land on
the registry, spans survive the collator's batching boundary (N
requests → 1 flush → the shared subtree in N trees), slow requests hit
the slow-query log with their tree attached, and the flight recorder's
incident header carries the triggering request's tree."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.serve.access import FlightRecorder
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.collator import Collator
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans

STAGE_KEYS = ("queue_wait", "collate_wait", "dispatch", "serialize")


@pytest.fixture(autouse=True)
def _span_state():
    spans.disable()
    yield
    spans.disable()


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(0)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((256, 4)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    eng.topk_neighbors(np.zeros(8, np.int32), 4)  # warm the executable
    return eng


def _names(tree: dict) -> list:
    return [c["name"] for c in tree.get("children", ())]


def test_sync_topk_decomposes_and_fills_stage_histograms(engine):
    spans.enable()
    records = []
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=records.append)
    reg = telem.default_registry()
    base = reg.mark()
    bat.topk([1, 2, 3], 4, request_id="req-sync")
    (rec,) = records
    # the boundary decomposition sums to e2e EXACTLY (stages are
    # differences of consecutive stamps; only rounding separates them)
    assert set(rec["stages"]) == set(STAGE_KEYS)
    assert rec["stages"]["collate_wait"] == 0.0  # sync path never waits
    assert sum(rec["stages"].values()) == pytest.approx(
        rec["e2e_ms"], abs=0.01)
    # every stage feeds its per-stage histogram, plus the engine's
    # device_compute and the result-forcing rescore window
    snap = reg.snapshot(baseline=base)
    for name in ("queue_wait", "collate_wait", "dispatch", "serialize",
                 "device_compute", "rescore"):
        h = snap.get(f"hist/serve/stage/{name}_ms")
        assert h and h["count"] == 1, f"missing stage histogram {name}"


def test_disabled_spans_cost_no_stage_histograms(engine):
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64, cache_size=0)
    reg = telem.default_registry()
    base = reg.mark()
    bat.topk([1, 2], 4)
    snap = reg.snapshot(baseline=base)
    assert not any(k.startswith("hist/serve/stage/") for k in snap)
    assert snap.get("hist/serve/e2e_ms")  # the flat latency still lands


def test_spans_survive_the_batching_boundary(engine):
    """8 concurrent single-id requests exactly fill the 8-rung: ONE
    flush serves all — and every request's span tree holds the SAME
    shared flush subtree, with device_compute/rescore under it."""
    spans.enable()
    records = []
    # slo_ms microscopically low: every record breaches, so the span
    # tree rides every access record (the slow-evidence path)
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=records.append,
                         slo_ms=1e-6)
    col = Collator(bat, max_wait_us=30_000_000)  # flush on fill only

    async def run():
        return await asyncio.gather(
            *[col.topk([i], 4, request_id=f"req-{i}") for i in range(8)])

    asyncio.run(run())
    col.close()
    assert len(records) == 8
    flush_metas = []
    for rec in records:
        tree = rec["span"]
        assert tree["request_id"] == rec["request_id"]
        # boundary children + the adopted flush subtree
        kids = _names(tree)
        for k in STAGE_KEYS:
            assert k in kids
        (flush,) = [c for c in tree["children"] if c["name"] == "flush"]
        assert flush["meta"]["members"] == 8
        flush_metas.append(flush["meta"]["flush_id"])
        inner = [c["name"] for c in flush["children"]]
        assert "device_compute" in inner and "rescore" in inner
        # collated requests actually waited for their flush group
        assert rec["stages"]["collate_wait"] >= 0.0
        assert sum(rec["stages"].values()) == pytest.approx(
            rec["e2e_ms"], abs=0.01)
    # one flush, shared: every tree names the same flush id
    assert len(set(flush_metas)) == 1


def test_concurrent_collated_trees_do_not_cross_contaminate(engine):
    """Two flush groups (different k → different pending buckets):
    every request's tree references ITS flush only."""
    spans.enable()
    records = []
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=records.append,
                         slo_ms=1e-6)
    col = Collator(bat, max_wait_us=50_000)

    async def run():
        return await asyncio.gather(
            *[col.topk([i], 4, request_id=f"a{i}") for i in range(4)],
            *[col.topk([i], 6, request_id=f"b{i}") for i in range(4)])

    asyncio.run(run())
    col.close()
    by_id = {r["request_id"]: r for r in records}
    assert len(by_id) == 8
    flush_of = {}
    for rid, rec in by_id.items():
        (flush,) = [c for c in rec["span"]["children"]
                    if c["name"] == "flush"]
        flush_of[rid] = flush["meta"]["flush_id"]
        assert rec["flush_id"] == flush["meta"]["flush_id"]
    # k=4 members share one flush, k=6 members another — never mixed
    assert len({flush_of[f"a{i}"] for i in range(4)}) == 1
    assert len({flush_of[f"b{i}"] for i in range(4)}) == 1
    assert flush_of["a0"] != flush_of["b0"]


def test_slow_query_log_gets_breaching_records_with_trees(engine):
    spans.enable()
    slow = []
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, slow_sink=slow.append,
                         slo_ms=1e-6)
    reg = telem.default_registry()
    base = reg.mark()
    bat.topk([1], 4, request_id="slow-1")
    (rec,) = slow  # breached (slo is microscopic) → slow log, tree on
    assert rec["request_id"] == "slow-1" and "span" in rec
    assert reg.snapshot(baseline=base).get("serve/slow_queries") == 1


def test_fast_requests_skip_the_slow_log(engine):
    spans.enable()
    slow = []
    records = []
    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=records.append,
                         slow_sink=slow.append, slo_ms=60_000.0)
    bat.topk([1], 4)
    assert slow == []  # a minute of budget: nothing breaches
    (rec,) = records
    assert "span" in rec or rec["outcome"] == "ok"  # ok+fast: flat line
    assert "span" not in rec


def test_incident_header_carries_trigger_span(engine, tmp_path):
    """An error burst's incident dump names the triggering request AND
    its span tree — the ISSUE 17 flight-recorder satellite."""
    import json

    spans.enable()
    rec_dir = str(tmp_path / "incidents")
    recorder = FlightRecorder(rec_dir, burst_n=3, burst_s=60.0)
    sink_records = []

    def sink(rec):
        sink_records.append(rec)
        recorder.record(rec)

    bat = RequestBatcher(engine, min_bucket=8, max_bucket=64,
                         cache_size=0, access_sink=sink,
                         recorder=recorder)
    for i in range(3):  # three validation errors inside the window
        with pytest.raises(ValueError):
            bat.topk([10_000_000 + i], 4, request_id=f"boom-{i}")
    recorder.join(5.0)
    assert recorder.dumps, "an error burst must dump an incident"
    with open(recorder.dumps[0], encoding="utf-8") as f:
        header = json.loads(f.readline())
    assert header["event"] == "incident"
    assert header["trigger_request_id"] == "boom-2"
    tree = header["trigger_span"]
    assert tree["request_id"] == "boom-2"
    assert tree["name"] == "topk"
