"""``scan_mode="fused"`` engine contract (ISSUE 10): fused answers are
rank-identical to the default two-stage scan on every supported spec
(exact AND IVF), unsupported shapes/specs fall back bit-identically,
the bf16 scan-then-rescore composes, and the batcher's cache key
isolates fused rows from two-stage rows over the same table."""

import numpy as np
import pytest
import jax.numpy as jnp

from hyperspace_tpu.kernels import scan_topk as fused_kernel
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine, auto_chunk_rows
from hyperspace_tpu.serve.index import IVF_MIN_TABLE_ROWS, build_index

from .test_engine import (_lorentz_table, _poincare_table, _product_table,
                          _reference_topk)


def _pair(table, spec, **kw):
    return (QueryEngine(table, spec, chunk_rows=128, scan_mode="two_stage",
                        **kw),
            QueryEngine(table, spec, chunk_rows=128, scan_mode="fused",
                        **kw))


@pytest.mark.parametrize("build", ["poincare", "lorentz"])
@pytest.mark.parametrize("exclude_self", [True, False])
@pytest.mark.parametrize("k", [1, 199, 200])
def test_fused_matches_two_stage_and_oracle(rng, build, exclude_self, k):
    """Rank identity across the spec × exclude_self × k grid, k running
    from 1 through the N−1 / N drains ACROSS the 128-row tile boundary
    (N = 200 > chunk); distances agree to f32 tolerance and the f64
    oracle agrees with both."""
    table, man = (_poincare_table if build == "poincare"
                  else _lorentz_table)(rng, 200, 6, 1.3)
    if k == 200 and exclude_self:
        k = 199  # k = N needs exclude_self=False; fold the duplicate
    spec = spec_from_manifold(man)
    two, fus = _pair(table, spec)
    q = np.asarray([0, 17, 127, 128, 199], np.int32)
    i1, d1 = (np.asarray(a) for a in
              two.topk_neighbors(q, k, exclude_self=exclude_self))
    i2, d2 = (np.asarray(a) for a in
              fus.topk_neighbors(q, k, exclude_self=exclude_self))
    assert np.array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2, rtol=1e-5, atol=1e-6)
    assert np.all(np.diff(d2, axis=1) >= 0)
    ref_idx, ref_dist = _reference_topk(man, table, q, k)
    if exclude_self:
        assert np.array_equal(i2, ref_idx)
        np.testing.assert_allclose(d2, ref_dist, rtol=2e-3, atol=2e-3)


def test_product_spec_falls_back_bit_identically(rng):
    """Product manifolds are outside the fused kernel's closed forms —
    the engine must serve them through the UNCHANGED two-stage
    executable: indices and distance bits equal."""
    table, man = _product_table(rng, 300)
    spec = spec_from_manifold(man)
    two, fus = _pair(table, spec)
    assert not fus._fused_kind and fus.scan_signature == ("exact",)
    q = np.asarray([0, 7, 150, 299], np.int32)
    i1, d1 = (np.asarray(a) for a in two.topk_neighbors(q, 6))
    i2, d2 = (np.asarray(a) for a in fus.topk_neighbors(q, 6))
    assert np.array_equal(i1, i2)
    assert np.array_equal(np.asarray(d1).view(np.uint32),
                          np.asarray(d2).view(np.uint32))


@pytest.mark.parametrize("chunk", [100, 1024],
                         ids=["misaligned", "over-vmem-model"])
def test_bad_chunk_demotes_the_whole_engine(rng, chunk):
    """A fused engine whose user chunk_rows can never stream (off the
    128 grid, or past the kernel's VMEM footprint model — which only a
    real Mosaic compile would reject) is demoted AT BUILD: it must
    advertise itself as what it actually serves (no "fused" signature
    element) and dispatch two-stage EVERYWHERE — exact scan AND IVF
    probe — bitwise with the two_stage engine at the same chunk."""
    table, man = _poincare_table(rng, 300, 5, 1.0)
    spec = spec_from_manifold(man)
    fus = QueryEngine(table, spec, chunk_rows=chunk, scan_mode="fused")
    assert not fus._fused_kind and fus.scan_signature == ("exact",)
    assert fus._scan_mode_eff == "two_stage"
    two = QueryEngine(table, spec, chunk_rows=chunk, scan_mode="two_stage")
    q = np.asarray([0, 299], np.int32)
    i1, d1 = (np.asarray(a) for a in two.topk_neighbors(q, 5))
    i2, d2 = (np.asarray(a) for a in fus.topk_neighbors(q, 5))
    assert np.array_equal(i1, i2)
    assert np.array_equal(d1.view(np.uint32), d2.view(np.uint32))


def test_demoted_fused_engine_probes_two_stage_bitwise(rng):
    """The IVF side of the demotion: a demoted fused engine's probe
    must run the two-stage candidate scan (same signature ⇒ must be
    the same bits — the cache-isolation contract)."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, 16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=4, seed=0)
    two = QueryEngine(table, spec, index=idx, nprobe=2)
    dem = QueryEngine(table, spec, index=idx, nprobe=2,
                      scan_mode="fused", chunk_rows=100)
    assert not dem._fused_kind
    assert dem.scan_signature == two.scan_signature  # no "fused" marker
    q = rng.integers(0, n, size=16).astype(np.int32)
    i1, d1 = (np.asarray(a) for a in two.topk_neighbors(q, 4))
    i2, d2 = (np.asarray(a) for a in dem.topk_neighbors(q, 4))
    assert np.array_equal(i1, i2)
    assert np.array_equal(d1.view(np.uint32), d2.view(np.uint32))


def test_oversized_k_falls_back_bit_identically(rng):
    """k past FUSED_MAX_K is a per-call capability fallback: the fused
    engine answers through the two-stage path, bitwise."""
    table, man = _poincare_table(rng, 300, 5, 1.0)
    spec = spec_from_manifold(man)
    two, fus = _pair(table, spec)
    k = fused_kernel.FUSED_MAX_K + 10
    i1, d1 = (np.asarray(a) for a in two.topk_neighbors(
        np.asarray([1, 2], np.int32), k))
    i2, d2 = (np.asarray(a) for a in fus.topk_neighbors(
        np.asarray([1, 2], np.int32), k))
    assert np.array_equal(i1, i2)
    assert np.array_equal(d1.view(np.uint32), d2.view(np.uint32))


def test_bf16_fused_scan_rank_agreement(rng):
    """precision=bf16 + scan_mode=fused: the bf16 fused scan picks the
    candidates, the f32 rescore ranks them — final answers agree with
    the f32 default engine and distances come back f32."""
    table, man = _poincare_table(rng, 300, 6, 1.0)
    spec = spec_from_manifold(man)
    base = QueryEngine(table, spec, chunk_rows=128)
    bf = QueryEngine(table, spec, chunk_rows=128, scan_mode="fused",
                     precision="bf16")
    q = np.asarray([0, 3, 17, 150, 299], np.int32)
    i0, d0 = (np.asarray(a) for a in base.topk_neighbors(q, 7))
    i1, d1 = (np.asarray(a) for a in bf.topk_neighbors(q, 7))
    assert np.array_equal(i0, i1)
    assert d1.dtype == np.float32
    np.testing.assert_allclose(d0, d1, rtol=1e-5, atol=1e-6)


def test_sharded_bf16_fused_composes(rng):
    """The full stack at once: 4-way mesh × bf16 scan-then-rescore ×
    fused per-shard kernel — ranks agree with the plain f32 engine and
    distances come back f32 (the sharded fused-only case rides in
    test_sharded_engine's mode parametrization)."""
    from hyperspace_tpu.parallel.mesh import model_mesh

    table, man = _poincare_table(rng, 300, 6, 1.0)
    spec = spec_from_manifold(man)
    base = QueryEngine(table, spec, chunk_rows=128)
    sh = QueryEngine(table, spec, chunk_rows=128, scan_mode="fused",
                     precision="bf16", mesh=model_mesh(4))
    q = np.asarray([0, 10, 150, 299], np.int32)
    i0, _ = base.topk_neighbors(q, 7)
    i1, d1 = sh.topk_neighbors(q, 7)
    assert np.array_equal(np.asarray(i0), np.asarray(i1))
    assert np.asarray(d1).dtype == np.float32


def _clustered_poincare(rng, n, d, nclusters):
    from hyperspace_tpu.manifolds import PoincareBall

    centers = rng.standard_normal((nclusters, d)) * 0.25
    v = (centers[rng.integers(0, nclusters, size=n)]
         + rng.standard_normal((n, d)) * 0.05).astype(np.float32)
    man = PoincareBall(1.0)
    return np.asarray(man.expmap0(jnp.asarray(v))), man


def test_ivf_fused_matches_two_stage_probe(rng):
    """The fused candidate scan behind the IVF probe: same cells, same
    ranks as the two-stage probe, and the signature carries both the
    probe identity AND the fused marker."""
    n = IVF_MIN_TABLE_ROWS
    table, man = _clustered_poincare(rng, n, 6, 16)
    spec = spec_from_manifold(man)
    idx = build_index(table, spec, 16, iters=4, seed=0)
    two = QueryEngine(table, spec, index=idx, nprobe=4)
    fus = QueryEngine(table, spec, index=idx, nprobe=4, scan_mode="fused")
    assert fus.scan_signature == ("ivf", 4, idx.fingerprint, "fused")
    assert fus.scan_signature_for(2) == ("ivf", 2, idx.fingerprint, "fused")
    q = rng.integers(0, n, size=33).astype(np.int32)
    i1, d1 = (np.asarray(a) for a in two.topk_neighbors(q, 5))
    i2, d2 = (np.asarray(a) for a in fus.topk_neighbors(q, 5))
    assert np.array_equal(i1, i2)
    # the fused candidate Gram reduces in a different f32 order than
    # _cand_dist's einsum — ranks identical, values a few ulp apart
    np.testing.assert_allclose(d1, d2, rtol=1e-4, atol=1e-6)


def test_batcher_cache_isolates_fused_from_two_stage(rng):
    """Fused distances are only ulp-close to two-stage distances, so the
    LRU key must keep the two engines' rows apart over the SAME table
    (same fingerprint) — the new scan-signature element."""
    table, man = _poincare_table(rng, 300, 6, 1.0)
    spec = spec_from_manifold(man)
    two, fus = _pair(table, spec)
    assert two.fingerprint == fus.fingerprint
    assert two.scan_signature == ("exact",)
    assert fus.scan_signature == ("exact", "fused")
    b_two = RequestBatcher(two)
    b_fus = RequestBatcher(fus)
    ids = list(range(16))
    b_two.topk(ids, 4)
    b_fus.topk(ids, 4)
    assert not ({k for k in b_two.cache._d} & {k for k in b_fus.cache._d})
    assert b_fus.stats()["scan_mode"] == "fused"
    assert b_two.stats()["scan_mode"] == "two_stage"


def test_auto_chunk_rows_fused_sizing():
    """scan_mode=fused delegates chunk sizing to the kernel's VMEM
    footprint model; unsupported kinds keep the default sizing (the
    bit-identical-fallback contract); pinned values for known shapes."""
    assert auto_chunk_rows(16, "poincare", 10_000_000,
                           scan_mode="fused") == 512
    assert auto_chunk_rows(1024, "poincare", 10_000_000,
                           scan_mode="fused") == 128
    # dtype enters the footprint: a bf16 table halves the tile bytes
    assert auto_chunk_rows(256, "poincare", 10_000_000,
                           scan_mode="fused") == 256
    assert auto_chunk_rows(256, "poincare", 10_000_000,
                           scan_mode="fused", dtype=jnp.bfloat16) == 512
    # product: fused-unsupported — identical to the default sizing
    assert auto_chunk_rows(64, "product", 10_000_000, scan_mode="fused") \
        == auto_chunk_rows(64, "product", 10_000_000)
    # tiny tables never over-allocate
    assert auto_chunk_rows(4, "poincare", 40, scan_mode="fused") == 128
    # engines pick it up: a fused engine's chunk is the kernel tile
    rng = np.random.default_rng(0)
    table, man = _poincare_table(rng, 5000, 16, 1.0)
    e = QueryEngine(table, spec_from_manifold(man), scan_mode="fused")
    assert e.chunk_rows == 512 and e._fused_kind
