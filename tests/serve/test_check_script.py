"""The serving-artifact round-trip lint, run inside the suite: export →
load → 10 queries must match the live model bit-for-bit
(scripts/check_serve_artifact.py is the one implementation — this test
just fails the build when it fails, mirroring the telemetry-catalog
lint's test)."""

import importlib.util
import os


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_serve_artifact.py")
    spec = importlib.util.spec_from_file_location("check_serve_artifact",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_round_trip_lint_passes(tmp_path, capsys):
    mod = _load_checker()
    rc = mod.main(str(tmp_path / "artifact"))
    out = capsys.readouterr().out
    assert rc == 0, f"serve artifact round-trip lint failed:\n{out}"
    assert "bit-identical" in out


def test_lint_catches_a_poisoned_table(tmp_path, monkeypatch):
    """The checker itself must keep working: nudge one table entry in
    the loaded artifact (below any fingerprint re-check the script does
    on its own meta, but enough to move f32 distance bits) and the lint
    has to fail."""
    import numpy as np

    from hyperspace_tpu.serve import artifact as A

    mod = _load_checker()
    real = A.load_artifact

    def poisoned(directory):
        art = real(directory)
        t = art.table.copy()
        t[0, 0] += np.float32(1e-3)
        return A.ServingArtifact(
            table=t, manifold_spec=art.manifold_spec,
            model_config=art.model_config,
            fingerprint=art.fingerprint, step=art.step)

    # the script does `from hyperspace_tpu.serve import load_artifact`
    # inside main(), so the package attribute is the interception point
    monkeypatch.setattr("hyperspace_tpu.serve.load_artifact", poisoned)
    assert mod.main(str(tmp_path / "artifact")) == 1
