"""Sharded-serving equivalence (ISSUE 4 tentpole): the mesh-sharded
k-NN/scoring programs answer exactly what the single-device engine
answers — bitwise on a 1-device mesh (the fallback IS the single-device
program), and up to distance ties on a real multi-device mesh (the
merge concatenates per-shard candidates, not global column order).
All meshes here live on the conftest's 8 fake CPU devices."""

import numpy as np
import pytest
import jax

from hyperspace_tpu.parallel.mesh import model_mesh
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.engine import QueryEngine

from .test_engine import (_lorentz_table, _poincare_table, _product_table,
                          _reference_topk)


def test_one_device_mesh_is_bitwise_single_device(rng):
    """The documented fallback: a mesh whose model axis has ONE device
    runs the single-device executable — indices AND distance bits equal."""
    table, man = _poincare_table(rng, 300, 6, 1.3)
    spec = spec_from_manifold(man)
    q = np.asarray([0, 3, 17, 150, 299], np.int32)
    plain = QueryEngine(table, spec, chunk_rows=128)
    meshed = QueryEngine(table, spec, chunk_rows=128, mesh=model_mesh(1))
    assert meshed.shards == 1
    i1, d1 = (np.asarray(a) for a in plain.topk_neighbors(q, 7))
    i2, d2 = (np.asarray(a) for a in meshed.topk_neighbors(q, 7))
    assert np.array_equal(i1, i2)
    assert np.array_equal(np.asarray(d1).view(np.uint32),
                          np.asarray(d2).view(np.uint32))  # bitwise
    s1 = np.asarray(plain.score_edges(q[:-1], q[1:]))
    s2 = np.asarray(meshed.score_edges(q[:-1], q[1:]))
    assert np.array_equal(s1.view(np.uint64), s2.view(np.uint64))


@pytest.mark.parametrize("build", ["poincare", "lorentz", "product"])
@pytest.mark.parametrize("mode", ["two_stage", "carry", "fused"])
def test_sharded_matches_single_device(rng, build, mode):
    """4-way sharded scan + all-gather merge == single device, on every
    manifold spec and every scan mode — including ``fused``, whose
    per-shard scan runs the scan-top-k kernel with shard-local column
    offsets (product composes through its bit-identical two-stage
    fallback) — and == the f64 oracle."""
    if build == "product":
        table, man = _product_table(rng, 300)
        q = np.asarray([0, 7, 150, 299], np.int32)
    else:
        table, man = (_poincare_table if build == "poincare"
                      else _lorentz_table)(rng, 300, 6, 1.3)
        q = np.asarray([0, 3, 17, 150, 299], np.int32)
    spec = spec_from_manifold(man)
    single = QueryEngine(table, spec, chunk_rows=128, scan_mode=mode)
    shard = QueryEngine(table, spec, chunk_rows=128, scan_mode=mode,
                        mesh=model_mesh(4))
    assert shard.shards == 4
    # padded to a chunk-per-shard multiple: each device owns 128 rows
    assert shard.table.shape[0] == 512
    i1, d1 = (np.asarray(a) for a in single.topk_neighbors(q, 7))
    i2, d2 = (np.asarray(a) for a in shard.topk_neighbors(q, 7))
    # random tables have no distance ties: indices agree exactly; the
    # per-element distance math is identical tile math on both layouts
    assert np.array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2, rtol=1e-6, atol=1e-7)
    ref_idx, ref_dist = _reference_topk(man, table, q, 7)
    assert np.array_equal(i2, ref_idx)
    np.testing.assert_allclose(d2, ref_dist, rtol=2e-3, atol=2e-3)


def test_sharded_drains_table_and_hides_padding(rng):
    """k = N−1 across 4 shards: every real row surfaces exactly once,
    none of the 212 zero-padded rows ever does, self stays excluded."""
    table, man = _poincare_table(rng, 300, 5, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=128,
                      mesh=model_mesh(4))
    idx, dist = (np.asarray(a) for a in
                 eng.topk_neighbors(np.asarray([4], np.int32), 299))
    assert sorted(idx[0].tolist()) == [i for i in range(300) if i != 4]
    assert np.all(np.isfinite(dist))


def test_sharded_score_edges_matches(rng):
    table, man = _lorentz_table(rng, 60, 5, 0.8)
    spec = spec_from_manifold(man)
    single = QueryEngine(table, spec)
    shard = QueryEngine(table, spec, mesh=model_mesh(4))
    u = np.asarray([0, 5, 9, 33], np.int32)
    v = np.asarray([1, 7, 20, 59], np.int32)
    np.testing.assert_allclose(np.asarray(single.score_edges(u, v)),
                               np.asarray(shard.score_edges(u, v)),
                               rtol=1e-6, atol=1e-7)
    p1 = np.asarray(single.score_edges(u, v, prob=True, fd_r=1.5, fd_t=0.7))
    p2 = np.asarray(shard.score_edges(u, v, prob=True, fd_r=1.5, fd_t=0.7))
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-7)


def test_mesh_without_model_axis_rejected(rng):
    from hyperspace_tpu.parallel.mesh import make_mesh

    table, man = _poincare_table(rng, 16, 3, 1.0)
    data_mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="model"):
        QueryEngine(table, spec_from_manifold(man), mesh=data_mesh)


def test_model_mesh_validation():
    n = len(jax.devices())
    assert model_mesh(-1).shape["model"] == n
    assert model_mesh(2).shape["model"] == 2
    with pytest.raises(ValueError, match="out of range"):
        model_mesh(0)
    with pytest.raises(ValueError, match="out of range"):
        model_mesh(n + 1)
