"""Request batcher: bucket ladder, padding waste accounting, LRU result
cache semantics, and the one-compile-per-bucket contract."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.batcher import (RequestBatcher, bucket_for,
                                          bucket_sizes)
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.telemetry import registry as telem


def _engine(rng, n=64, d=4, c=1.0):
    v = jnp.asarray(rng.standard_normal((n, d)) * 0.5, jnp.float32)
    table = np.asarray(PoincareBall(c).expmap0(v))
    return QueryEngine(table, spec_from_manifold(PoincareBall(c)))


def test_bucket_ladder():
    assert bucket_sizes(8, 64) == (8, 16, 32, 64)
    assert bucket_sizes(1, 4) == (1, 2, 4)
    assert bucket_sizes(5, 48) == (8, 16, 32, 48)  # top bucket = max exactly
    assert bucket_for(3, (8, 16)) == 8
    assert bucket_for(9, (8, 16)) == 16
    assert bucket_for(99, (8, 16)) == 16  # callers slab-split first
    with pytest.raises(ValueError):
        bucket_sizes(16, 8)


def test_topk_results_and_padding_counters(rng):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    reg = telem.default_registry()
    req0, waste0 = reg.get("serve/requests"), reg.get("serve/padded_waste")
    idx, dist = b.topk([3, 1, 4], 5)
    assert idx.shape == (3, 5) and dist.shape == (3, 5)
    # the batcher's padded call returns exactly the engine's rows
    ref_i, ref_d = (np.asarray(a)
                    for a in eng.topk_neighbors(np.asarray([3, 1, 4]), 5))
    assert np.array_equal(idx, ref_i)
    assert np.array_equal(dist, ref_d)
    assert reg.get("serve/requests") == req0 + 1
    assert reg.get("serve/padded_waste") == waste0 + 5  # 3 -> bucket 8


def test_cache_hits_skip_the_engine(rng, monkeypatch):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    reg = telem.default_registry()
    first_i, first_d = b.topk([0, 1, 2], 4)
    calls = {"n": 0}
    real = eng.topk_neighbors

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(eng, "topk_neighbors", counting)
    hit0, miss0 = reg.get("serve/cache_hit"), reg.get("serve/cache_miss")
    again_i, again_d = b.topk([2, 0, 1], 4)  # same ids, new order
    assert calls["n"] == 0  # all rows served from cache
    assert reg.get("serve/cache_hit") == hit0 + 3
    assert reg.get("serve/cache_miss") == miss0
    assert np.array_equal(again_i[1], first_i[0])  # row for id 0
    # mixed hit/miss: only the cold id computes, rows stay request-ordered
    mix_i, mix_d = b.topk([5, 0], 4)
    assert calls["n"] == 1
    assert np.array_equal(mix_i[1], first_i[0])
    ref_i, _ = (np.asarray(a)
                for a in real(np.asarray([5], np.int32), 4))
    assert np.array_equal(mix_i[0], ref_i[0])


def test_duplicate_cold_ids_compute_once(rng, monkeypatch):
    """A request repeating a COLD id must compute it once and count one
    cache miss — not burn a padded slot (and a counter) per duplicate."""
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    reg = telem.default_registry()
    seen_batches = []
    real = eng.topk_neighbors

    def recording(q_idx, k, **kw):
        seen_batches.append(np.asarray(q_idx))
        return real(q_idx, k, **kw)

    monkeypatch.setattr(eng, "topk_neighbors", recording)
    hit0, miss0 = reg.get("serve/cache_hit"), reg.get("serve/cache_miss")
    idx, _dist = b.topk([7, 7, 9, 7], 3)
    assert idx.shape == (4, 3)
    assert np.array_equal(idx[0], idx[1]) and np.array_equal(idx[0], idx[3])
    # one dispatch, id 7 in exactly one slot of the padded batch's real
    # prefix (the pad repeats the last real id)
    assert len(seen_batches) == 1
    assert (seen_batches[0][:2] == 7).sum() == 1
    assert reg.get("serve/cache_miss") == miss0 + 2  # unique ids: 7, 9
    assert reg.get("serve/cache_hit") == hit0


def test_cache_keys_include_k_and_fingerprint(rng):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    i4, _ = b.topk([7], 4)
    i2, _ = b.topk([7], 2)  # different k: different cache entry
    assert i2.shape == (1, 2)
    assert np.array_equal(i2[0], i4[0, :2])
    # a different table (fingerprint) must not see this cache's rows
    eng2 = _engine(rng)  # rng advanced -> different table
    assert eng2.fingerprint != eng.fingerprint
    b2 = RequestBatcher(eng2, min_bucket=8, max_bucket=32)
    b2.cache = b.cache  # share the LRU on purpose
    reg = telem.default_registry()
    miss0 = reg.get("serve/cache_miss")
    b2.topk([7], 4)
    assert reg.get("serve/cache_miss") == miss0 + 1


def test_lru_eviction(rng):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32, cache_size=4)
    b.topk([0, 1, 2, 3], 3)
    b.topk([10], 3)  # evicts the oldest entry (id 0)
    assert len(b.cache) == 4
    reg = telem.default_registry()
    miss0 = reg.get("serve/cache_miss")
    b.topk([0], 3)
    assert reg.get("serve/cache_miss") == miss0 + 1


def test_large_request_slab_split(rng):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=4, max_bucket=8, cache_size=0)
    ids = list(range(20))  # 8 + 8 + 4-bucket slabs
    idx, dist = b.topk(ids, 3)
    assert idx.shape == (20, 3)
    ref_i, _ = (np.asarray(a)
                for a in eng.topk_neighbors(np.asarray(ids, np.int32), 3))
    assert np.array_equal(idx, ref_i)


def test_id_validation_happens_before_any_cast(rng):
    """Bad ids must fail the request — never silently truncate (floats)
    or wrap (ints past int32) into another node's answer."""
    eng = _engine(rng)  # 64 rows
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    with pytest.raises(ValueError, match="integer"):
        b.topk([1.9], 3)
    with pytest.raises(ValueError, match="out of range"):
        b.topk([2**32], 3)  # would wrap to id 0 through int32
    with pytest.raises(ValueError, match="out of range"):
        b.score([2**32], [1])
    with pytest.raises(ValueError, match="integer"):
        b.score([0.5], [1])
    with pytest.raises(ValueError, match="out of range"):
        b.topk([-1], 3)
    with pytest.raises(ValueError, match="non-empty"):
        b.topk([], 3)
    with pytest.raises(ValueError, match="bool"):
        b.topk([True], 3)  # would index-coerce to node 1
    with pytest.raises(ValueError, match="k must be"):
        b.topk([0], 2.9)  # float k: reject, don't truncate to 2
    with pytest.raises(ValueError, match="k must be"):
        b.topk([0], True)  # bool k: reject, don't coerce to 1


def test_score_bucketed(rng):
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    u, v = [0, 1, 2], [3, 4, 5]
    out = b.score(u, v)
    ref = np.asarray(eng.score_edges(np.asarray(u, np.int32),
                                     np.asarray(v, np.int32)))
    np.testing.assert_array_equal(out, ref.astype(np.float64))


def test_within_bucket_sizes_share_one_compile(rng):
    """THE serving contract: after one warmup per (bucket, k), requests
    of any size inside that bucket trigger zero XLA recompiles (asserted
    via the PR-2 ``jax/recompiles`` monitoring counter)."""
    telem.install_jax_monitoring_hook()
    eng = _engine(rng, n=80)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32, cache_size=0)
    reg = telem.default_registry()
    b.topk([0, 1, 2], 5)  # warmup: compiles the (8, 5) program
    before = reg.get("jax/recompiles")
    b.topk([10, 11], 5)
    b.topk([20, 21, 22, 23, 24], 5)
    b.topk(list(range(30, 38)), 5)  # exactly the bucket size
    assert reg.get("jax/recompiles") == before
    # crossing the bucket boundary MAY compile once; coming back doesn't
    b.topk(list(range(40, 49)), 5)  # bucket 16 warmup
    before = reg.get("jax/recompiles")
    b.topk(list(range(50, 60)), 5)
    assert reg.get("jax/recompiles") == before


def test_request_lifecycle_histograms(rng):
    """Each request observes serve/queue_wait_ms, serve/dispatch_ms and
    serve/e2e_ms with queue_wait ≤ e2e (the enqueue→batch-form stamp is
    inside the enqueue→complete window) and nonzero counts after a warm
    pass; all-cache-hit requests skip the dispatch histogram."""
    eng = _engine(rng)
    b = RequestBatcher(eng, min_bucket=8, max_bucket=32)
    reg = telem.default_registry()
    base = reg.mark()
    b.topk([0, 1, 2], 4)          # cold: one engine dispatch
    b.score([0, 1], [2, 3])       # score path observes too
    snap = reg.snapshot(baseline=base)
    qw, disp, e2e = (snap[f"hist/serve/{n}"]
                     for n in ("queue_wait_ms", "dispatch_ms", "e2e_ms"))
    assert qw["count"] == 2 and e2e["count"] == 2 and disp["count"] == 2
    assert qw["max"] <= e2e["max"]      # batch-form precedes complete
    assert disp["max"] <= e2e["max"]    # dispatch is inside the window
    assert e2e["p50"] is not None and e2e["p99"] is not None
    assert e2e["max"] > 0
    # a fully-cached request observes queue_wait/e2e but NO dispatch
    base = reg.mark()
    b.topk([2, 0, 1], 4)  # same ids → all hits
    snap = reg.snapshot(baseline=base)
    assert snap["hist/serve/e2e_ms"]["count"] == 1
    assert snap["hist/serve/queue_wait_ms"]["count"] == 1
    assert "hist/serve/dispatch_ms" not in snap
    # the stats() surface carries the cumulative e2e summary
    lat = b.stats()["latency_e2e_ms"]
    assert lat["count"] >= 3 and lat["p95"] >= lat["p50"]
