"""ISSUE 3 acceptance: train a tiny Poincaré embedding, export the
serving artifact, and (1) `topk_neighbors` from the LOADED artifact
matches brute-force hyperbolic distances computed from the LIVE params
— indices exactly, and bit-for-bit against the live-table engine; (2)
repeated queries at different batch sizes within one bucket trigger no
recompile (the PR-2 `jax/recompiles` counter stays flat)."""

import numpy as np
import jax.numpy as jnp

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.serve import (QueryEngine, RequestBatcher,
                                  export_from_checkpoint, load_artifact)
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.train.checkpoint import CheckpointManager


def _train_tiny(tmp_path, steps=12):
    from hyperspace_tpu.data import wordnet

    ds = wordnet.synthetic_tree(depth=3, branching=3)
    cfg = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=4,
                                 batch_size=32, neg_samples=4,
                                 burnin_steps=0)
    state, opt = pe.init_state(cfg, seed=0)
    pairs = jnp.asarray(ds.pairs)
    for _ in range(steps):
        state, _loss = pe.train_step(cfg, opt, state, pairs)
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(steps, state, force=True)
    return cfg, state, ckpt


def test_train_export_query_end_to_end(tmp_path):
    cfg, state, ckpt = _train_tiny(tmp_path)
    art_dir = str(tmp_path / "artifact")
    art = export_from_checkpoint(ckpt, art_dir, workload="poincare",
                                 model_config={"c": cfg.c})
    loaded = load_artifact(art_dir)
    assert loaded.fingerprint == art.fingerprint

    live_table = np.asarray(state.table)
    assert np.array_equal(loaded.table, live_table)  # params froze losslessly

    served = QueryEngine.from_artifact(loaded)
    live = QueryEngine(live_table, ("poincare", float(cfg.c)))
    q = np.asarray([0, 1, 5, 9, cfg.num_nodes - 1], np.int32)
    k = 5
    si, sd = (np.asarray(a) for a in served.topk_neighbors(q, k))
    li, ld = (np.asarray(a) for a in live.topk_neighbors(q, k))
    # served == live, bit for bit: same bytes, same executable
    assert np.array_equal(si, li)
    assert np.array_equal(sd.view(np.uint32), ld.view(np.uint32))

    # served == brute-force O(N²) hyperbolic distances from the live
    # params (the manifolds oracle, f64): exact on indices
    ball = PoincareBall(cfg.c)
    t64 = jnp.asarray(live_table, jnp.float64)
    d = np.array(jnp.stack([ball.dist(t64[i], t64) for i in q.tolist()]))
    d[np.arange(len(q)), q] = np.inf
    ref_idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    assert np.array_equal(si, ref_idx)
    np.testing.assert_allclose(
        sd, np.take_along_axis(d, ref_idx, axis=1), rtol=1e-4, atol=1e-4)


def test_no_recompile_within_bucket_after_export(tmp_path):
    cfg, _state, ckpt = _train_tiny(tmp_path, steps=4)
    art_dir = str(tmp_path / "artifact")
    export_from_checkpoint(ckpt, art_dir, workload="poincare",
                           model_config={"c": cfg.c})
    telem.install_jax_monitoring_hook()
    eng = QueryEngine.from_artifact(load_artifact(art_dir))
    batcher = RequestBatcher(eng, min_bucket=8, max_bucket=64, cache_size=0)
    reg = telem.default_registry()
    batcher.topk([0, 1, 2], 4)  # warmup compiles the (bucket=8, k=4) program
    before = reg.get("jax/recompiles")
    for ids in ([3], [4, 5], [6, 7, 8, 9], list(range(10, 18))):
        batcher.topk(ids, 4)
    assert reg.get("jax/recompiles") == before, (
        "batch sizes 1/2/4/8 inside the 8-bucket must share one compile")
