"""Overload-safe serving: deadlines, bounded admission, the degradation
ladder, the error taxonomy, and SIGTERM drain (docs/resilience.md)."""

import io
import json
import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.resilience import faults
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.errors import (DeadlineExceededError,
                                         OverloadedError, error_response)
from hyperspace_tpu.telemetry import registry as telem


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _engine(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
    return QueryEngine(table, ("poincare", 1.0))


# --- deadlines ----------------------------------------------------------------


def test_expired_request_is_never_dispatched():
    bat = RequestBatcher(_engine(), queue_max=8)
    base = telem.default_registry().mark()
    with pytest.raises(DeadlineExceededError):
        bat.topk([1, 2, 3], 4, deadline_ms=1e-4)  # expired on arrival
    delta = telem.default_registry().snapshot(baseline=base)
    assert delta.get("serve/deadline_exceeded") == 1
    # never dispatched late: no engine slots were spent on it
    assert delta.get("serve/slots", 0) == 0
    # failed requests observe no latency histograms — serve/e2e_ms
    # stays the distribution of honestly answered requests
    assert "hist/serve/e2e_ms" not in delta


def test_result_computed_past_deadline_is_not_answered():
    """A dispatch that overruns the deadline (injected 50 ms latency at
    serve.dispatch) must answer deadline_exceeded — never hand back the
    result as if it were on time.  The computed rows stay cached."""
    bat = RequestBatcher(_engine(), queue_max=8)
    faults.install([faults.FaultSpec(site="serve.dispatch",
                                     kind="latency", ms=50.0)])
    with pytest.raises(DeadlineExceededError, match="at completion"):
        bat.topk([1, 2], 4, deadline_ms=25.0)
    faults.clear()
    # the work was not wasted: the same ids now answer from cache
    base = telem.default_registry().mark()
    idx, dist = bat.topk([1, 2], 4, deadline_ms=25.0)
    assert idx.shape == (2, 4)
    delta = telem.default_registry().snapshot(baseline=base)
    assert delta.get("serve/cache_hit") == 2


def test_deadline_default_vs_override():
    bat = RequestBatcher(_engine(), queue_max=8, deadline_ms=1e-4)
    with pytest.raises(DeadlineExceededError):
        bat.topk([1], 4)  # server default applies
    idx, _ = bat.topk([1], 4, deadline_ms=10_000.0)  # override wins
    assert idx.shape == (1, 4)


def test_no_deadline_is_default():
    bat = RequestBatcher(_engine())
    idx, _ = bat.topk([1], 4)
    assert idx.shape == (1, 4)


# --- bounded admission --------------------------------------------------------


def test_full_queue_sheds_with_overloaded():
    # down_after=3 keeps the ladder out of this test: one shed alone
    # must not flip the mode (that interplay has its own test below)
    bat = RequestBatcher(_engine(), queue_max=2, ladder_down_after=3)
    # occupy the whole bound (as two in-flight concurrent callers would)
    assert bat._admission.try_admit() is not None
    assert bat._admission.try_admit() is not None
    base = telem.default_registry().mark()
    with pytest.raises(OverloadedError, match="queue_max=2"):
        bat.topk([1], 4)
    delta = telem.default_registry().snapshot(baseline=base)
    assert delta.get("serve/shed") == 1
    bat._admission.release()
    bat._admission.release()
    idx, _ = bat.topk([1], 4)  # room again: served
    assert idx.shape == (1, 4)


def test_concurrent_overload_sheds_some_serves_rest():
    """Genuine concurrency: more threads than queue_max — every request
    gets exactly one outcome (rows or a typed shed), none vanish."""
    import threading

    eng = _engine(n=256, dim=8)
    bat = RequestBatcher(eng, queue_max=2, cache_size=0)
    bat.topk([0], 8)  # warm the compile so in-flight spans overlap
    results, errors = [], []
    barrier = threading.Barrier(8)

    def worker(i):
        barrier.wait()
        try:
            results.append(bat.topk([i, i + 8, i + 16], 8))
        except OverloadedError as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) + len(errors) == 8
    assert results  # the bounded queue admitted at least one
    assert bat._admission.inflight == 0  # every slot released


# --- degradation ladder -------------------------------------------------------


def test_ladder_steps_down_and_recovers_with_hysteresis():
    eng = _engine()
    bat = RequestBatcher(eng, queue_max=4, ladder_up_after=2)
    bat.topk([1], 4)  # warm: id 1 is cache-servable while degraded
    reg = telem.default_registry()
    base = reg.mark()
    # 3 held slots: the next request admits at pressure 3/4 >= high
    tokens = [bat._admission.try_admit() for _ in range(3)]
    assert all(t is not None for t in tokens)
    bat.topk([1], 4)
    assert bat._ladder.level == 1  # exact engine: level 1 IS cache-only
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/degraded") == 1
    assert delta.get("serve/degrade_level") == 1
    # recovery needs ladder_up_after consecutive calm observations
    for _ in range(3):
        bat._admission.release()
    bat.topk([1], 4)              # calm 1 (id 1 is cached — servable
    assert bat._ladder.level == 1  # even in cache-only mode)
    bat.topk([1], 4)              # calm 2: recovered
    assert bat._ladder.level == 0
    delta = reg.snapshot(baseline=base)
    assert delta.get("serve/degrade_recovered") == 1
    assert delta.get("serve/degrade_level") == 0


def test_cache_only_serves_hits_sheds_cold():
    bat = RequestBatcher(_engine(), queue_max=4)
    idx_full, dist_full = bat.topk([3, 4], 5)      # warm the cache
    bat._ladder._level = len(bat._modes) - 1       # force terminal level
    idx, dist = bat.topk([3, 4], 5)                # hits: still served
    np.testing.assert_array_equal(idx, idx_full)
    with pytest.raises(OverloadedError, match="cache-only"):
        bat.topk([9, 10], 5)                       # cold: shed
    with pytest.raises(OverloadedError, match="uncached"):
        bat.score([0], [1])                        # score has no cache


def test_single_caller_exerts_no_pressure():
    """The blocking CLI loop (one request in flight, ever) must never
    degrade, whatever queue_max is: a lone caller's pressure is 0."""
    bat = RequestBatcher(_engine(), queue_max=1, ladder_down_after=1)
    for i in range(6):
        bat.topk([i], 4)
    assert bat._ladder.level == 0


def _clustered_ivf_engine(nprobe=4):
    from hyperspace_tpu.serve.index import IVF_MIN_TABLE_ROWS, build_index

    n = IVF_MIN_TABLE_ROWS  # smallest table the probe path serves
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((32, 4)) * 0.25
    vv = (centers[rng.integers(0, 32, size=n)]
          + rng.standard_normal((n, 4)) * 0.05)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(vv, jnp.float32)))
    idx = build_index(table, ("poincare", 1.0), 16, iters=4, seed=0,
                      balance=3.0)
    return QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=nprobe)


def test_ivf_ladder_narrows_nprobe_before_cache_only():
    """On a probing engine the ladder steps the probe width down toward
    1 before giving up quality entirely — and degraded-width rows never
    cross-contaminate the full-width cache."""
    eng = _clustered_ivf_engine(nprobe=4)
    bat = RequestBatcher(eng, queue_max=4)
    assert bat._modes == [None, 2, 1, "cache_only"]
    ids = [5, 17, 40]
    idx_full, _ = bat.topk(ids, 4)
    bat._ladder._level = 1  # degraded: effective nprobe 2
    idx_deg, dist_deg = bat.topk(ids, 4)
    ref_i, ref_d = (np.asarray(a) for a in
                    eng.topk_neighbors(np.asarray(ids, np.int32), 4,
                                       nprobe=2))
    np.testing.assert_array_equal(idx_deg, ref_i)
    np.testing.assert_allclose(dist_deg, ref_d)
    # back at full quality the full-width rows come back — the degraded
    # rows were cached under their own scan signature
    bat._ladder._level = 0
    base = telem.default_registry().mark()
    idx_back, _ = bat.topk(ids, 4)
    np.testing.assert_array_equal(idx_back, idx_full)
    delta = telem.default_registry().snapshot(baseline=base)
    assert delta.get("serve/cache_hit") == len(ids)  # full rows cached


def test_nprobe_override_rejected_on_exact_engine():
    eng = _engine()
    with pytest.raises(ValueError, match="exact"):
        eng.topk_neighbors(np.asarray([0], np.int32), 4, nprobe=2)
    probing = _clustered_ivf_engine(nprobe=4)
    with pytest.raises(ValueError, match="out of range"):
        probing.topk_neighbors(np.asarray([0], np.int32), 4, nprobe=9)


# --- error taxonomy + CLI ----------------------------------------------------


def test_error_response_mapping():
    assert error_response(OverloadedError("x"))["error"]["kind"] == \
        "overloaded"
    assert error_response(DeadlineExceededError("x"))["error"]["kind"] \
        == "deadline_exceeded"
    assert error_response(ValueError("x"))["error"]["kind"] == \
        "validation"
    assert error_response(RuntimeError("x"))["error"]["kind"] == \
        "internal"


@pytest.fixture(scope="module")
def cli_artifact(tmp_path_factory):
    from hyperspace_tpu.cli import serve as S
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    tmp = tmp_path_factory.mktemp("overload_cli")
    cfg = pe.PoincareEmbedConfig(num_nodes=30, dim=3, batch_size=16,
                                 neg_samples=4, burnin_steps=0)
    state, opt = pe.init_state(cfg, seed=0)
    pairs = jnp.asarray(
        np.random.default_rng(0).integers(0, 30, (60, 2), np.int64))
    state, _ = pe.train_step(cfg, opt, state, pairs)
    ckpt = str(tmp / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(1, state, force=True)
    art = str(tmp / "artifact")
    assert S.main(["export", f"ckpt={ckpt}", f"out={art}",
                   "workload=poincare", "c=1.0"]) == 0
    return art


def test_serve_loop_error_kinds(cli_artifact):
    """Every failed line answers a typed error.kind; every line gets
    exactly one response — nothing silently dropped."""
    from hyperspace_tpu.cli import serve as S

    cfg = S.apply_overrides(S.ServeConfig(),
                            {"artifact": cli_artifact, "queue_max": "4"})
    lines = "\n".join([
        "this is not json",
        json.dumps({"op": "nope"}),
        json.dumps({"op": "topk", "ids": [0.7], "k": 2}),
        json.dumps({"op": "topk", "ids": [0], "k": 2,
                    "deadline_ms": 1e-4}),
        json.dumps({"op": "topk", "ids": [0], "k": 2,
                    "deadline_ms": "soon"}),
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2}),
    ]) + "\n"
    out = io.StringIO()
    result = S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 6  # one response per line, exactly
    kinds = [r["error"]["kind"] for r in resp[:5]]
    assert kinds == ["parse", "validation", "validation",
                     "deadline_exceeded", "validation"]
    assert "neighbors" in resp[5]
    assert result["served"] == 1
    assert result["queue_max"] == 4 and result["degrade_mode"] == "full"


def test_serve_loop_overloaded_kind(cli_artifact, monkeypatch):
    from hyperspace_tpu.cli import serve as S

    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": cli_artifact})
    monkeypatch.setattr(
        S, "_handle",
        lambda *_a: (_ for _ in ()).throw(OverloadedError("queue full")))
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(
        json.dumps({"op": "topk", "ids": [0], "k": 2}) + "\n"),
        stdout=out)
    resp = json.loads(out.getvalue().strip())
    assert resp["error"]["kind"] == "overloaded"


def test_serve_loop_ioerror_answers_internal(cli_artifact):
    """A per-request IO failure (the injected serve.dispatch ioerror
    chaos fault) answers error.kind=internal and the loop KEEPS
    serving — one request's IO trouble must not kill the server."""
    from hyperspace_tpu.cli import serve as S

    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": cli_artifact})
    faults.install([faults.FaultSpec(site="serve.dispatch",
                                     kind="ioerror")])
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [5], "k": 2}),   # fault fires
        json.dumps({"op": "topk", "ids": [6], "k": 2}),   # loop survives
    ]) + "\n"
    out = io.StringIO()
    result = S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 2
    assert resp[0]["error"]["kind"] == "internal"
    assert "neighbors" in resp[1]
    assert result["served"] == 1


def test_degraded_underfill_is_overloaded(monkeypatch):
    """An under-filled probe at a SERVER-narrowed width is an overload
    symptom, not the client's validation error (the taxonomy's whole
    point: clients branch on kind)."""
    eng = _clustered_ivf_engine(nprobe=4)
    bat = RequestBatcher(eng, queue_max=4, cache_size=0)
    bat._ladder._level = 2  # degraded: effective nprobe 1

    def underfilled(*a, **kw):
        raise ValueError(
            "IVF probe under-filled: some query's 1 nearest cell(s) "
            "hold fewer than k=4 reachable rows")

    monkeypatch.setattr(eng, "topk_neighbors", underfilled)
    with pytest.raises(OverloadedError, match="degraded probe width"):
        bat.topk([5, 17], 4)


def test_sigterm_drains_idle_server(cli_artifact, capsys):
    """SIGTERM to a server blocked on a SILENT (but open) stdin pipe
    must still drain within the poll interval — the select-polling
    reader exists exactly for this; a plain readline would block until
    the client's next line (PEP 475 retries the interrupted read)."""
    import threading

    from hyperspace_tpu.cli import serve as S

    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": cli_artifact})
    r_fd, w_fd = os.pipe()
    try:
        with open(w_fd, "w") as w:
            w.write(json.dumps({"op": "topk", "ids": [0], "k": 2}) + "\n")
            w.flush()
            # the write end STAYS OPEN and silent: no EOF, no next line
            timer = threading.Timer(
                1.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
            timer.start()
            out = io.StringIO()
            with open(r_fd, closefd=False) as r:
                result = S.run_serve(cfg, stdin=r, stdout=out)
            timer.cancel()
    finally:
        os.close(r_fd)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 1 and "neighbors" in resp[0]
    assert result["drained"] is True and result["served"] == 1
    assert "[serve] drained" in capsys.readouterr().err


def test_sigterm_drains_gracefully(cli_artifact, capsys):
    """SIGTERM mid-stream: the in-flight request answers, admission
    stops (later lines unread), the drain notice hits stderr, and the
    closing stats return normally."""
    from hyperspace_tpu.cli import serve as S

    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": cli_artifact})

    def lines():
        yield json.dumps({"op": "topk", "ids": [0], "k": 2}) + "\n"
        os.kill(os.getpid(), signal.SIGTERM)
        yield json.dumps({"op": "topk", "ids": [1], "k": 2}) + "\n"
        yield json.dumps({"op": "topk", "ids": [2], "k": 2}) + "\n"

    out = io.StringIO()
    result = S.run_serve(cfg, stdin=lines(), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 1 and "neighbors" in resp[0]
    assert result["served"] == 1 and result["drained"] is True
    assert "[serve] drained" in capsys.readouterr().err


def test_cli_flag_validation(cli_artifact):
    from hyperspace_tpu.cli import serve as S

    with pytest.raises(SystemExit, match="queue_max"):
        S.main(["query", f"artifact={cli_artifact}", "ids=0", "k=2",
                "queue_max=-1"])
    with pytest.raises(SystemExit, match="chaos"):
        S.main(["query", f"artifact={cli_artifact}", "ids=0", "k=2",
                "chaos=bogus"])


def test_cli_chaos_latency_roundtrip(cli_artifact, capsys):
    """chaos= on the serve CLI arms the dispatch site; the run reports
    fired faults and still answers (latency only delays)."""
    from hyperspace_tpu.cli import serve as S

    rc = S.main(["query", f"artifact={cli_artifact}", "ids=0,1", "k=2",
                 "chaos=serve.dispatch:latency:ms=5"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["chaos"]["fired"] == 1
    assert not faults.active()  # cleared on the way out
