"""scripts/trace_report.py smoke: the per-stage table and span rollup
render from a RECORDED access-log fixture (captured from the real
collated pipeline with spans + a microscopic SLO, so every record
carries both ``stages`` and a ``span`` tree), and the edge contracts
(empty input, garbage lines) hold."""

import importlib.util
import os

import pytest


def _load():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "trace_access.jsonl")


def test_report_renders_stage_table_and_rollup(capsys):
    mod = _load()
    rc = mod.main([FIXTURE])
    out = capsys.readouterr().out
    assert rc == 0
    assert "stage breakdown" in out and "span rollup" in out
    # the four boundary stages in pipeline order, then the span paths
    pos = [out.index(s) for s in
           ("queue_wait", "collate_wait", "dispatch", "serialize")]
    assert pos == sorted(pos)
    assert "device_compute" in out and "flush" in out


def test_stage_table_aggregates_correctly():
    mod = _load()
    records = mod.read_records([FIXTURE])
    assert len(records) == 9  # 8 collated + 1 sync, as recorded
    table = {row[0]: row for row in mod.stage_table(records)}
    for name in ("queue_wait", "collate_wait", "dispatch", "serialize"):
        _, n, mean, p99, share = table[name]
        assert n == 9 and mean >= 0 and p99 >= mean >= 0
    assert sum(row[4] for row in table.values()) == pytest.approx(1.0)
    # the rollup walks nested stages the boundary table can't carry
    paths = {p for p, *_ in mod.span_rollup(records)}
    assert "topk/flush/device_compute" in paths
    assert "topk/flush/rescore" in paths


def test_empty_and_garbage_inputs(tmp_path, capsys):
    mod = _load()
    p = tmp_path / "junk.jsonl"
    p.write_text("not json\n{\"event\": \"incident\"}\n\n")
    assert mod.main([str(p)]) == 1  # nothing summarizable: loud exit
    err = capsys.readouterr().err
    assert "no stage/span records" in err
