"""Blue-green rollover contracts (ISSUE 18): the gate refuses standbys
whose identity is incomplete or degraded (and a refusal leaves the old
stack serving untouched), the flip atomically swaps batcher + collator
and drains the old stack, and a coordinator runs one rollover at a
time."""

import asyncio
import time

import numpy as np
import pytest

from hyperspace_tpu.parallel.host_table import HostEmbedTable
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.delta import LiveQueryEngine
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.rollover import (GATE_FIELDS,
                                           RolloverCoordinator, gate_flip,
                                           standby_health)
from hyperspace_tpu.serve.server import HttpFrontDoor

from .test_engine import _poincare_table


def _batcher(rng, n=40, seed_shift=0.0):
    table, man = _poincare_table(rng, n, 5, 1.0)
    if seed_shift:
        table = np.asarray(table) * (1.0 - seed_shift)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=32)
    return RequestBatcher(eng, min_bucket=4, max_bucket=8, cache_size=64)


def _door(batcher):
    # construction binds nothing — the door is drivable without a
    # socket (the collator + attribute surface is what flip touches)
    return HttpFrontDoor(batcher, max_wait_us=500)


# --- the gate -----------------------------------------------------------------


def test_standby_health_carries_every_gate_field(rng):
    body = standby_health(_batcher(rng))
    assert all(body.get(f) is not None for f in GATE_FIELDS)
    gate_flip(body)  # a healthy standby passes


def test_gate_refuses_missing_identity_fields(rng):
    body = standby_health(_batcher(rng))
    for field in GATE_FIELDS:
        broken = dict(body)
        del broken[field]
        with pytest.raises(ValueError, match="missing"):
            gate_flip(broken)


def test_gate_refuses_not_ok_and_degraded(rng):
    body = standby_health(_batcher(rng))
    with pytest.raises(ValueError, match="ok=false"):
        gate_flip(dict(body, ok=False))
    with pytest.raises(ValueError, match="degraded"):
        gate_flip(dict(body, degrade_level=2))


def test_gate_refusal_leaves_old_stack_serving(rng, monkeypatch):
    """A standby that gates red is discarded WITHOUT touching the live
    stack: same batcher, same collator, zero flips recorded."""
    old = _batcher(rng)
    door = _door(old)
    coord = RolloverCoordinator(door, lambda t: _batcher(rng, 40, 0.1),
                                prewarm_ks=(3,))
    monkeypatch.setattr("hyperspace_tpu.serve.rollover.standby_health",
                        lambda b: dict(standby_health(b),
                                       degrade_level=1))
    old_collator = door.collator
    with pytest.raises(ValueError, match="degraded"):
        asyncio.run(coord.rollover("v2"))
    assert door.batcher is old and door.collator is old_collator
    assert coord.flips == 0 and not old_collator._closed
    assert coord._busy is False  # a refused rollover releases the slot


# --- the flip -----------------------------------------------------------------


def test_rollover_flips_atomically_and_drains_old_stack(rng):
    """The full prepare → gate → flip → drain path: the door serves
    the standby afterwards (answers match the new engine directly),
    the old collator is flushed + closed, and the report names both
    fingerprints and the prewarm count."""
    old = _batcher(rng)
    door = _door(old)
    standby_box = {}

    def builder(target):
        assert target == "v2"
        standby_box["b"] = _batcher(rng, 40, 0.1)
        return standby_box["b"]

    coord = RolloverCoordinator(door, builder, prewarm_ks=(3,))
    old_collator = door.collator

    async def drive():
        report = await coord.rollover("v2")
        # post-flip traffic answers from the NEW stack, via the new
        # collator — compare against the standby engine directly
        idx, _ = await door.collator.topk([2, 7], 3)
        return report, np.asarray(idx)

    report, idx = asyncio.run(drive())
    standby = standby_box["b"]
    assert door.batcher is standby and door.collator is not old_collator
    assert old_collator._closed  # drained: flushed, executor released
    assert coord.flips == 1 and report["flipped"] is True
    assert report["old_fingerprint"] == old.engine.fingerprint
    assert report["new_fingerprint"] == standby.engine.fingerprint
    assert report["old_fingerprint"] != report["new_fingerprint"]
    assert report["prewarmed_programs"] > 0
    want, _ = standby.engine.topk_neighbors(
        np.asarray([2, 7], np.int32), 3)
    np.testing.assert_array_equal(idx, np.asarray(want))


def test_flip_onto_live_engine_rolls_the_scan_signature(rng):
    """A rollover onto a LiveQueryEngine standby (the bench's shape):
    the new collator serves the generation-folded signature, so no
    cache key can bridge the flip."""
    old = _batcher(rng)
    door = _door(old)
    table, man = _poincare_table(rng, 40, 5, 1.0)
    live = LiveQueryEngine(
        QueryEngine(table, spec_from_manifold(man), chunk_rows=32),
        HostEmbedTable.from_array(table), capacity=8,
        auto_compact=False)
    standby = RequestBatcher(live, min_bucket=4, max_bucket=8,
                             cache_size=64)
    coord = RolloverCoordinator(door, lambda t: standby,
                                prewarm_ks=(3,))
    report = asyncio.run(coord.rollover("live"))
    assert ("gen" in report["scan_signature"]
            and door.batcher.engine is live)


def test_one_rollover_at_a_time(rng):
    """A second rollover launched while the first is still preparing
    is refused immediately — the standby build owns the build
    bandwidth; the first completes unaffected."""
    door = _door(_batcher(rng))

    def slow_builder(target):
        time.sleep(0.2)  # keep the first rollover in its prepare phase
        return _batcher(rng, 40, 0.1)

    coord = RolloverCoordinator(door, slow_builder, prewarm_ks=(3,))

    async def drive():
        first = asyncio.ensure_future(coord.rollover("a"))
        await asyncio.sleep(0.05)  # first is now blocking in prepare
        with pytest.raises(ValueError, match="already in progress"):
            await coord.rollover("b")
        return await first

    report = asyncio.run(drive())
    assert report["flipped"] is True and coord.flips == 1
