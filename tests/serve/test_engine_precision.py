"""bf16 table-scan precision mode (serve/engine.py, docs/precision.md).

Acceptance contracts (ISSUE 5):

- **rank agreement**: on all three manifold specs the bf16-scan engine's
  top-k SET matches the f32 engine's (the over-fetched candidates are
  rescored in f32, which also fixes the within-set order);
- **f32 distances**: returned distances are f32-accurate (rescored), not
  bf16 approximations — tight allclose vs the f32 engine;
- **boundary stress**: a table of points pinned near the ball boundary —
  where bf16's 8-bit mantissa destroys 1 − c‖x‖² — still answers with
  f32-accurate distances, proving the boundary-sensitive math never runs
  in bf16 on anything returned;
- **default = f32 = bitwise**: precision="f32" is the same executable as
  an engine built before the policy existed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine

N, DIM, K, B = 400, 8, 7, 16


def _poincare_table(rng, n=N, dim=DIM, scale=0.5):
    return np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * scale, jnp.float32)))


def _lorentz_table(rng, n=N, dim=DIM, c=0.8):
    v = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.float32),
         jnp.asarray(rng.standard_normal((n, dim)) * 0.5, jnp.float32)],
        axis=1)
    return np.asarray(Lorentz(c).expmap0(v))


def _specs(rng):
    return [
        ("poincare", _poincare_table(rng), ("poincare", 1.0)),
        ("lorentz", _lorentz_table(rng), ("lorentz", 0.8)),
        ("product", _poincare_table(rng),
         ("product", (("poincare", 4, 1.0), ("euclidean", 4, 0.0)))),
    ]


@pytest.mark.parametrize("scan_mode", ["two_stage", "carry"])
def test_bf16_rank_agreement_all_manifolds(rng, scan_mode):
    """Top-k sets AND order match the f32 oracle after f32 rescoring,
    and the returned distances are f32-tight, on every manifold kind."""
    q = rng.integers(0, N, size=B)
    for name, table, spec in _specs(rng):
        e32 = QueryEngine(table, spec, chunk_rows=128)
        e16 = QueryEngine(table, spec, chunk_rows=128, precision="bf16",
                          scan_mode=scan_mode)
        i32, d32 = map(np.asarray, e32.topk_neighbors(q, K))
        i16, d16 = map(np.asarray, e16.topk_neighbors(q, K))
        assert d16.dtype == np.float32, name  # rescored, not bf16
        for a, b in zip(i32, i16):
            assert set(a.tolist()) == set(b.tolist()), name
        np.testing.assert_array_equal(i16, i32, err_msg=name)
        np.testing.assert_allclose(d16, d32, rtol=1e-5, atol=1e-6,
                                   err_msg=name)


def test_bf16_boundary_stress():
    """Points pinned near the ball edge: proj clamps f32 points to a
    margin near ball_eps(f32)=4e-3, exactly where bf16's 8-bit mantissa
    loses 1 − c‖x‖² entirely (a bf16 DISTANCE here is off by ~4e-2
    relative).  The mode's contract under this stress:

    - returned distances are f32-accurate — they match an f64 oracle
      over the returned (query, id) pairs to f32-level error, proving
      every distance that reaches the caller came from the f32 rescore,
      never the bf16 scan;
    - candidate recall stays high (the over-fetch absorbs most of the
      bf16 rank scrambling; exact-set agreement is NOT promised on a
      table built to break bf16 — that is what the f32 mode is for).
    """
    rng = np.random.default_rng(7)
    ball = PoincareBall(1.0)
    # unit directions scaled to radius ~0.99-1.0, then proj-clamped
    v = rng.standard_normal((N, DIM)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    radii = (0.99 + 0.01 * rng.random((N, 1))).astype(np.float32)
    table = np.asarray(ball.proj(jnp.asarray(v * radii)))
    margins = 1.0 - np.linalg.norm(table, axis=1)
    assert margins.max() < 2e-2, "stress table must hug the boundary"

    q = rng.integers(0, N, size=B)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    e16 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                      precision="bf16")
    i32, _ = map(np.asarray, e32.topk_neighbors(q, K))
    i16, d16 = map(np.asarray, e16.topk_neighbors(q, K))

    recall = np.mean([len(set(a.tolist()) & set(b.tolist())) / K
                      for a, b in zip(i32, i16)])
    assert recall >= 0.9, f"boundary-stress recall {recall:.3f}"

    # f64 oracle distances for the PAIRS ACTUALLY RETURNED: f32-level
    # agreement (~1e-4 relative — artanh amplification of f32 rounding)
    # vs the ~4e-2 relative error a bf16 distance carries here
    t64 = jnp.asarray(table, jnp.float64)
    oracle = np.asarray(PoincareBall(1.0).dist(
        t64[jnp.asarray(q)][:, None, :], t64[jnp.asarray(i16)]))
    rel = np.abs(d16 - oracle) / oracle
    assert rel.max() < 2e-3, f"returned distances not f32-grade: {rel.max()}"

    # contrast check: distances computed FROM bf16-rounded points are
    # grossly wrong here — proving the stress is real and the rescore
    # is what saves the answers
    tb = np.asarray(jnp.asarray(table).astype(jnp.bfloat16).astype(
        jnp.float64))
    bf16_dist = np.asarray(PoincareBall(1.0).dist(
        jnp.asarray(tb)[jnp.asarray(q)][:, None, :],
        jnp.asarray(tb)[jnp.asarray(i16)]))
    bf16_rel = np.abs(bf16_dist - oracle) / oracle
    assert bf16_rel.max() > 1e-2, "stress table failed to stress bf16"


def test_f32_default_is_same_program_and_table():
    """precision='f32' must add nothing: no scan copy (the attribute
    aliases the table) and bitwise-identical answers to a default-built
    engine."""
    rng = np.random.default_rng(3)
    table = _poincare_table(rng)
    q = rng.integers(0, N, size=B)
    e_default = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    e_f32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                        precision="f32")
    assert e_f32.scan_table is e_f32.table
    i1, d1 = map(np.asarray, e_default.topk_neighbors(q, K))
    i2, d2 = map(np.asarray, e_f32.topk_neighbors(q, K))
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(d1, d2)


def test_bad_precision_rejected():
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="precision"):
        QueryEngine(_poincare_table(rng), ("poincare", 1.0),
                    precision="fp8")


def test_sharded_bf16_matches_f32_oracle(rng):
    """4-way row-sharded bf16 scan == the single-device f32 answer
    (sets exact, distances f32-tight) — the rescore runs inside the
    shard_map program on the f32 shards."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from hyperspace_tpu.parallel.mesh import model_mesh

    table = _poincare_table(rng, n=500)
    q = rng.integers(0, 500, size=B)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=64)
    es = QueryEngine(table, ("poincare", 1.0), chunk_rows=64,
                     mesh=model_mesh(4), precision="bf16")
    i32, d32 = map(np.asarray, e32.topk_neighbors(q, K))
    i16, d16 = map(np.asarray, es.topk_neighbors(q, K))
    for a, b in zip(i32, i16):
        assert set(a.tolist()) == set(b.tolist())
    np.testing.assert_allclose(d16, d32, rtol=1e-5, atol=1e-6)


def test_batcher_cache_key_carries_precision(rng):
    """Two engines over the SAME table share a fingerprint, so the
    result-cache key must also carry the precision mode — an f32
    engine's cached rows must never answer for a bf16 engine or vice
    versa, and stats() must say which mode a batcher serves."""
    table = _poincare_table(rng)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    e16 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                      precision="bf16")
    assert e32.fingerprint == e16.fingerprint  # content-keyed: same table
    b32 = RequestBatcher(e32, min_bucket=8, max_bucket=32)
    b16 = RequestBatcher(e16, min_bucket=8, max_bucket=32)
    ids = rng.integers(0, N, size=8).tolist()
    b32.topk(ids, K)
    b16.topk(ids, K)
    keys32 = {key for key in b32.cache._d}
    keys16 = {key for key in b16.cache._d}
    # key layout: (fp, qid, k, exclude_self, precision, scan signature)
    assert all(key[-2] == "f32" for key in keys32)
    assert all(key[-2] == "bf16" for key in keys16)
    assert keys32.isdisjoint(keys16)
    assert b32.stats()["precision"] == "f32"
    assert b16.stats()["precision"] == "bf16"


def test_serve_cli_precision_flag(tmp_path, rng):
    """End-to-end through the CLI: precision=bf16 answers match the
    default engine's ranking, and a bad value is a clean usage error."""
    from hyperspace_tpu.cli import serve as cli
    from hyperspace_tpu.serve.artifact import export_artifact

    table = _poincare_table(rng, n=128)
    art_dir = str(tmp_path / "art")
    export_artifact(art_dir, table, ("poincare", 1.0), step=0)

    cfg = cli.apply_overrides(
        cli.ServeConfig(),
        {"artifact": art_dir, "ids": "0,1,2", "k": "3",
         "precision": "bf16"})
    out = cli.run_query(cfg)
    cfg32 = cli.apply_overrides(
        cli.ServeConfig(), {"artifact": art_dir, "ids": "0,1,2", "k": "3"})
    out32 = cli.run_query(cfg32)
    assert out["neighbors"] == out32["neighbors"]
    np.testing.assert_allclose(out["dists"], out32["dists"],
                               rtol=1e-5, atol=1e-6)

    bad = cli.apply_overrides(
        cli.ServeConfig(),
        {"artifact": art_dir, "ids": "0", "precision": "fp8"})
    with pytest.raises(SystemExit, match="precision"):
        cli.run_query(bad)
