"""Quarter-precision scan lanes: int4 packed nibbles and PQ codes
(serve/engine.py + serve/quant.py, docs/serving.md "Sub-int8 lanes").

Acceptance contracts (ISSUE 16):

- **rank identity**: on all three manifold specs the int4 and PQ
  coarse-scan + f32-rescore engines return EXACTLY the exact f32
  engine's neighbors and f32-tight distances, checked against an f64
  oracle — including the IVF, fused-kernel, and mesh-sharded
  compositions, and on a boundary-stress table hugging the Poincaré
  ball edge;
- **eighth/sub-eighth bytes**: the resident int4 copy is two nibbles
  per byte + a per-row f16 scale (~8× under f32); PQ is one byte per
  subspace + KB-scale codebooks (under int4 at serve sizes);
- **lane isolation**: the scan signature carries the lane (PQ includes
  the codebook fingerprint) and the batcher cache never crosses any of
  the five lanes;
- **quant module**: int4 pack/unpack round-trips bit-exactly through
  the host twin; PQ codebooks train deterministically with a content
  fingerprint.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.quant import (QLEVELS4, build_pq,
                                        default_pq_m,
                                        dequantize_int4_rows,
                                        int4_packed_width, pack_int4_rows,
                                        pq_decode, unpack_int4_rows)

N, DIM, K, B = 600, 8, 7, 16


def _poincare_table(rng, n=N, dim=DIM, scale=0.5):
    return np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * scale, jnp.float32)))


def _edge_table(rng, n=N, dim=DIM):
    """Boundary stress: points pushed out near the Poincaré ball edge
    (tangent norms 2–3 → radii up to ~0.995) — where the conformal
    factor blows up and a quantization step costs the most."""
    v = rng.standard_normal((n, dim))
    v = v / np.linalg.norm(v, axis=1, keepdims=True) * \
        (2.0 + rng.random((n, 1)))
    return np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(v, jnp.float32)))


def _lorentz_table(rng, n=N, dim=DIM, c=0.8):
    v = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.float32),
         jnp.asarray(rng.standard_normal((n, dim)) * 0.5, jnp.float32)],
        axis=1)
    return np.asarray(Lorentz(c).expmap0(v))


def _specs(rng):
    return [
        ("poincare", _poincare_table(rng), ("poincare", 1.0)),
        ("lorentz", _lorentz_table(rng), ("lorentz", 0.8)),
        ("product", _poincare_table(rng),
         ("product", (("poincare", 4, 1.0), ("euclidean", 4, 0.0)))),
    ]


def _f64_all_pairs(table, spec, q_idx):
    """f64 query-to-table distance matrix via the live manifolds."""
    from hyperspace_tpu.serve.artifact import manifold_from_spec

    t64 = jnp.asarray(np.asarray(table, np.float64))
    m = manifold_from_spec(spec)
    d = np.array(m.dist(t64[q_idx][:, None, :], t64[None, :, :]))
    d[np.arange(len(q_idx)), q_idx] = np.inf  # exclude_self
    return d


def _f64_oracle(table, spec, q_idx, k):
    """Exact top-k in f64 via the live manifolds — the independent
    ranking both quarter lanes must reproduce."""
    d = _f64_all_pairs(table, spec, q_idx)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx, np.take_along_axis(d, idx, axis=1)


# --- quant module -------------------------------------------------------------


def test_pack_int4_rows_roundtrip_and_zero_rows(rng):
    t = rng.standard_normal((50, 7)).astype(np.float32)  # odd dim
    t[7] = 0.0
    pk, s = pack_int4_rows(t)
    assert pk.dtype == np.uint8 and pk.shape == (50, int4_packed_width(7))
    assert s.dtype == np.float16 and s.shape == (50, 1)
    codes = unpack_int4_rows(pk, 7)
    assert codes.shape == (50, 7) and np.abs(codes).max() <= QLEVELS4
    # reconstruction within half a (coarse) step of the stored scale
    err = np.abs(dequantize_int4_rows(pk, s, 7) - t)
    assert np.all(err <= s.astype(np.float32) / 2 + 1e-6)
    assert s[7] == 0 and np.all(codes[7] == 0)
    assert np.all(dequantize_int4_rows(pk, s, 7)[7] == 0.0)
    with pytest.raises(ValueError, match=r"\[N, D\]"):
        pack_int4_rows(np.zeros(5))


def test_build_pq_deterministic_with_fingerprint(rng):
    table = _poincare_table(rng, n=200)
    codes, cb = build_pq(table, ("poincare", 1.0), seed=3)
    codes2, cb2 = build_pq(table, ("poincare", 1.0), seed=3)
    assert codes.dtype == np.uint8
    assert cb.m == default_pq_m(cb.lift_dim)
    assert np.array_equal(codes, codes2)
    assert cb.fingerprint == cb2.fingerprint
    # a different seed trains different centroids → different identity
    _, cb3 = build_pq(table, ("poincare", 1.0), seed=4)
    assert cb3.fingerprint != cb.fingerprint
    # decode reconstructs the padded lift width
    rec = pq_decode(cb, codes)
    assert rec.shape == (200, cb.m * cb.ds)


# --- rank identity vs the f64 oracle -----------------------------------------


@pytest.mark.parametrize("scan_mode", ["two_stage", "carry", "fused"])
@pytest.mark.parametrize("precision", ["int4", "pq"])
def test_quarter_rank_identical_all_manifolds(rng, precision, scan_mode):
    """All three specs × every scan mode × both quarter lanes:
    neighbors identical to the exact f32 engine AND the f64 oracle;
    distances f32-tight (they come from the f32 rescore, never the
    coarse pass)."""
    q = rng.integers(0, N, size=B)
    for name, table, spec in _specs(rng):
        e32 = QueryEngine(table, spec, chunk_rows=128)
        eq = QueryEngine(table, spec, chunk_rows=128, precision=precision,
                         scan_mode=scan_mode)
        i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
        iq, dq = (np.asarray(a) for a in eq.topk_neighbors(q, K))
        assert np.array_equal(i32, iq), (name, precision, scan_mode)
        assert np.allclose(d32, dq, rtol=5e-6, atol=1e-7), (name, precision)
        oi, od = _f64_oracle(table, spec, q, K)
        assert np.array_equal(iq, oi), (name, precision, scan_mode)
        assert np.allclose(dq, od, rtol=2e-4, atol=1e-5), (name, precision)


def test_boundary_stress_near_ball_edge(rng):
    """Boundary stress (radii up to ~0.995): the conformal factor
    blows up, so tiny radial differences — far below an int4 step —
    decide distances.  The hyperbolic-aware lane holds up: PQ trains
    its codebooks in the tangent LIFT, where ``atanh`` spreads the edge
    out, and keeps the f32 engine's neighbor SET exactly (ordering may
    flip only across genuine f32 near-ties; distances agree to the
    ~1e-4 relative stability f32 edge math has at all).  Raw-coordinate
    int4 honestly degrades to a recall probe there — well above chance
    (7/600), and every distance it returns is still the TRUE f32
    rescore for the id it returns (truthfulness: checked against the
    f64 oracle)."""
    table = _edge_table(rng)
    q = rng.integers(0, N, size=B)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
    d64 = _f64_all_pairs(table, ("poincare", 1.0), q)

    epq = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                      precision="pq", scan_mode="fused")
    ipq, dpq = (np.asarray(a) for a in epq.topk_neighbors(q, K))
    for r in range(B):
        assert set(i32[r]) == set(ipq[r]), r
    assert np.allclose(d32, dpq, rtol=1e-4, atol=1e-6)

    e4 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                     precision="int4", scan_mode="fused")
    i4, dd4 = (np.asarray(a) for a in e4.topk_neighbors(q, K))
    recall = np.mean([len(set(i32[r]) & set(i4[r])) / K for r in range(B)])
    assert recall >= 0.5, recall
    true_d = np.take_along_axis(d64, i4, axis=1)
    assert np.allclose(dd4, true_d, rtol=2e-4, atol=1e-5)
    assert np.all(np.diff(dd4, axis=1) >= 0)  # still sorted ascending


def test_quarter_table_bytes(rng):
    """The capacity ladder: int4 = packed codes (8× under the f32 scan
    copy) + f16 scales; pq = one byte per subspace + KB-scale
    codebooks, under the int4 lane at equal rows."""
    table = _poincare_table(rng)
    e32 = QueryEngine(table, ("poincare", 1.0))
    e4 = QueryEngine(table, ("poincare", 1.0), precision="int4")
    assert e4.scan_table.dtype == jnp.uint8
    assert e4.scan_table.shape[1] == int4_packed_width(DIM)
    assert e4.scan_table.nbytes * 8 == e32.scan_table.nbytes
    assert e4.scan_scale.dtype == jnp.float16
    lane4 = e4.scan_table.nbytes + e4.scan_scale.nbytes
    assert lane4 < e32.scan_table.nbytes / 4
    epq = QueryEngine(table, ("poincare", 1.0), precision="pq")
    assert epq.scan_table.dtype == jnp.uint8
    assert epq.pq_codebooks is not None and epq.scan_scale is None
    assert epq.scan_table.nbytes < e4.scan_table.nbytes
    # codebooks are the (row-count-independent) fixed cost


def test_quarter_ivf_rank_identical(rng):
    """IVF composition: probing through the packed candidate scorers
    (per-candidate scale gather / ADC + f32 rescore) returns exactly
    the f32 probing engine's rows, fused and two-stage."""
    from hyperspace_tpu.serve.index import build_index

    n = 4096
    table = _poincare_table(rng, n=n)
    idx = build_index(table, ("poincare", 1.0), 32, seed=0)
    q = rng.integers(0, n, size=B)
    for mode in ("two_stage", "fused"):
        e32 = QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=8,
                          scan_mode=mode)
        i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
        for precision, kw in (("int4", {}), ("pq", {"pq_m": 8})):
            eq = QueryEngine(table, ("poincare", 1.0), index=idx, nprobe=8,
                             precision=precision, scan_mode=mode, **kw)
            assert eq.scan_strategy == "ivf"
            iq, dq = (np.asarray(a) for a in eq.topk_neighbors(q, K))
            assert np.array_equal(i32, iq), (mode, precision)
            assert np.allclose(d32, dq, rtol=5e-6, atol=1e-7), \
                (mode, precision)


def test_quarter_sharded_rank_identical(rng):
    """4-way mesh sharding: packed codes + per-row scales shard
    P("model", None) beside the master (PQ codebooks replicate); the
    per-shard scan + all-gather + f32 rescore matches the
    single-device f32 engine."""
    import jax

    from hyperspace_tpu.parallel.mesh import model_mesh

    if len(jax.local_devices()) < 4:
        pytest.skip("needs 4 local devices (tests/conftest.py forces them)")
    n = 4096
    table = _poincare_table(rng, n=n)
    q = rng.integers(0, n, size=B)
    e32 = QueryEngine(table, ("poincare", 1.0), chunk_rows=128)
    i32, d32 = (np.asarray(a) for a in e32.topk_neighbors(q, K))
    for mode in ("two_stage", "fused"):
        for precision, kw in (("int4", {}), ("pq", {"pq_m": 8})):
            eq = QueryEngine(table, ("poincare", 1.0), chunk_rows=128,
                             precision=precision, mesh=model_mesh(4),
                             scan_mode=mode, **kw)
            iq, dq = (np.asarray(a) for a in eq.topk_neighbors(q, K))
            assert np.array_equal(i32, iq), (mode, precision)
            assert np.allclose(d32, dq, rtol=5e-6, atol=1e-7), \
                (mode, precision)


# --- lane isolation -----------------------------------------------------------


def test_scan_signature_distinguishes_every_lane(rng):
    table = _poincare_table(rng)
    sigs = {p: QueryEngine(table, ("poincare", 1.0),
                           precision=p).scan_signature
            for p in ("f32", "bf16", "int8", "int4", "pq")}
    # f32 and bf16 share the dense lane marker (the slab dtype keys the
    # program); every QUANTIZED lane is distinct from them and each other
    assert sigs["int4"] == ("exact", "int4")
    # pq carries the codebook fingerprint: ("exact", "pq", <sha256>)
    assert sigs["pq"][:2] == ("exact", "pq") and len(sigs["pq"]) == 3
    assert len({sigs["int8"], sigs["int4"], sigs["pq"],
                sigs["f32"]}) == 4
    # two PQ engines over DIFFERENT codebooks must not share a signature
    e_m8 = QueryEngine(table, ("poincare", 1.0), precision="pq", pq_m=8)
    assert e_m8.scan_signature != sigs["pq"]
    # fused marker composes with the lane
    ef = QueryEngine(table, ("poincare", 1.0), precision="int4",
                     scan_mode="fused")
    assert ef.scan_signature == ("exact", "fused", "int4")


def test_batcher_cache_never_crosses_lanes(rng):
    """The same ids through all five lanes over the SAME fingerprint:
    each lane computes its own rows (distinct cache keys — the serve
    counters are process-wide, so assert per-pass deltas), and stats
    reports the lane."""
    from hyperspace_tpu.telemetry import registry as telem

    table = _poincare_table(rng)
    ids = rng.integers(0, N, size=8).tolist()
    reg = telem.default_registry()
    batchers = {p: RequestBatcher(QueryEngine(table, ("poincare", 1.0),
                                              precision=p))
                for p in ("f32", "bf16", "int8", "int4", "pq")}
    for p, bat in batchers.items():
        base = reg.mark()
        bat.topk(ids, K)
        assert bat.stats()["precision"] == p
        d = reg.snapshot(baseline=base)
        assert d.get("serve/cache_hit", 0) == 0, p  # no cross-lane reuse
        base = reg.mark()
        bat.topk(ids, K)
        d = reg.snapshot(baseline=base)
        assert d.get("serve/cache_hit", 0) > 0, p  # same-lane reuse works


# --- artifact + CLI plumbing --------------------------------------------------


def test_artifact_payload_engine_matches_fresh_engine(tmp_path, rng):
    """An engine built from an exported quant payload answers bitwise
    like one that trained the same lane fresh (same table, same seed
    defaults) — the payload IS the trained state, not a summary."""
    from hyperspace_tpu.serve import (build_quant_payload, export_artifact,
                                      load_artifact)

    table = _poincare_table(rng)
    q = rng.integers(0, N, size=B)
    for lane in ("int4", "pq"):
        d = str(tmp_path / f"art-{lane}")
        payload = build_quant_payload(table, ("poincare", 1.0), lane)
        export_artifact(d, table, ("poincare", 1.0), quant=payload)
        loaded = load_artifact(d)
        served = QueryEngine.from_artifact(loaded, precision=lane)
        fresh = QueryEngine(table, ("poincare", 1.0), precision=lane)
        assert served.scan_signature == fresh.scan_signature, lane
        si, sd = (np.asarray(a) for a in served.topk_neighbors(q, K))
        fi, fd = (np.asarray(a) for a in fresh.topk_neighbors(q, K))
        assert np.array_equal(si, fi), lane
        assert np.array_equal(sd.view(np.uint32), fd.view(np.uint32)), lane


def test_serve_cli_accepts_quarter_lanes(tmp_path, rng):
    """ServeConfig precision=int4|pq reaches the engine (flag rows:
    docs/serving.md)."""
    from hyperspace_tpu.cli.serve import ServeConfig, _build
    from hyperspace_tpu.serve.artifact import export_artifact

    table = _poincare_table(rng)
    art = str(tmp_path / "art")
    export_artifact(art, table, ("poincare", 1.0))
    ids = rng.integers(0, N, size=4).tolist()
    e32, _ = _build(ServeConfig(artifact=art))
    i32, _ = RequestBatcher(e32).topk(ids, 5)
    for lane in ("int4", "pq"):
        engine, batcher = _build(ServeConfig(artifact=art, precision=lane))
        assert engine.precision == lane
        iq, _ = batcher.topk(ids, 5)
        assert np.array_equal(np.asarray(iq), np.asarray(i32)), lane
