"""Live-index delta-segment edge cases (ISSUE 18 satellite): the LSM
semantics that are easy to get subtly wrong — re-upsert last-write-wins,
delete-then-reinsert across a compaction boundary, tombstones under
``exclude_self`` and sharded meshes, and the under-filled error when
``k`` exceeds the live-row count.  Every top-k is cross-checked against
an f64 oracle over the mutable master with tombstones masked out."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.parallel.host_table import HostEmbedTable
from hyperspace_tpu.parallel.mesh import model_mesh
from hyperspace_tpu.serve.artifact import spec_from_manifold
from hyperspace_tpu.serve.delta import LiveQueryEngine
from hyperspace_tpu.serve.engine import QueryEngine

from .test_engine import _poincare_table


def _live(rng, n=60, d=5, c=1.0, cap=8, mesh=None, **kw):
    table, man = _poincare_table(rng, n, d, c)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=32,
                      mesh=mesh)
    live = LiveQueryEngine(eng, HostEmbedTable.from_array(table),
                           capacity=cap, auto_compact=False, **kw)
    return live, man


def _near(master_row, rng, eps=1e-4):
    return np.asarray(master_row, np.float32) + eps * rng.standard_normal(
        master_row.shape[-1]).astype(np.float32)


def _oracle_topk(live, man, q_idx, k, *, exclude_self=True):
    """f64 exact top-k over the CURRENT master with tombstones +inf."""
    arr = jnp.asarray(live.master.to_array(), jnp.float64)
    d = np.array(jax.vmap(lambda x: man.dist(x, arr))(arr[np.asarray(
        q_idx)]))
    for t in live._deleted:
        d[:, t] = np.inf
    if exclude_self:
        d[np.arange(len(q_idx)), q_idx] = np.inf
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx


# --- re-upsert: last write wins ----------------------------------------------


def test_reupsert_of_delta_resident_id_last_write_wins(rng):
    """Upserting an id ALREADY in the delta overwrites its slot in
    place (no second slot, no ghost of the first write): the query
    answers the newest vector, and segment occupancy stays flat."""
    live, man = _live(rng)
    master = live.master.to_array()
    vid, a1, a2 = 5, 40, 41
    live.upsert([vid], [_near(master[a1], rng)])
    assert live.segment_rows == 1
    idx, _ = live.topk_neighbors([vid], 3)
    assert idx[0][0] == a1
    g1 = live.generation
    live.upsert([vid], [_near(master[a2], rng)])
    assert live.segment_rows == 1  # same slot, not a second one
    assert live.generation == g1 + 1
    idx, _ = live.topk_neighbors([vid], 3)
    assert idx[0][0] == a2 and a1 not in idx[0][:1]
    np.testing.assert_array_equal(
        idx, _oracle_topk(live, man, [vid], 3))


def test_duplicate_ids_within_one_batch_last_write_wins(rng):
    """Duplicates inside ONE upsert batch resolve like sequential
    re-upserts: the final occurrence is the one that lands."""
    live, man = _live(rng)
    master = live.master.to_array()
    vid, a1, a2 = 9, 30, 31
    out = live.upsert([vid, 12, vid],
                      [_near(master[a1], rng),
                       _near(master[22], rng),
                       _near(master[a2], rng)])
    assert out["upserted"] == 2 and out["inserted"] == 0
    idx, _ = live.topk_neighbors([vid], 2)
    assert idx[0][0] == a2


# --- delete-then-reinsert across a compaction boundary ------------------------


def test_delete_then_reinsert_across_compaction(rng):
    """A tombstone survives compaction (rows are never renumbered, so
    the dead row rides into the rebuilt base and must stay masked);
    a later re-upsert of the same id revives it, and THAT survives the
    next compaction too."""
    live, man = _live(rng)
    master = live.master.to_array()
    victim, anchor = 7, 33
    live.delete([victim])
    fp0 = live.fingerprint
    gen0 = live.generation
    rep = live.compact()
    # delete-only compaction rebuilds from IDENTICAL master bytes, so
    # the content-derived fingerprint may not move — the generation is
    # what rolls the cache key
    assert rep["segment_rows"] == 0 and live.generation > gen0
    # still dead after the rebuild: refused as an anchor, never a
    # neighbor, and absent from a table-draining query
    with pytest.raises(ValueError, match="deleted"):
        live.topk_neighbors([victim], 3)
    idx, _ = live.topk_neighbors([anchor], live.num_live - 1)
    assert victim not in idx[0]
    np.testing.assert_array_equal(
        idx, _oracle_topk(live, man, [anchor], live.num_live - 1))
    # reinsert: the id comes back to life with its NEW vector
    live.upsert([victim], [_near(master[anchor], rng)])
    idx, _ = live.topk_neighbors([victim], 3)
    assert idx[0][0] == anchor
    live.compact()
    assert live.fingerprint != fp0  # the folded WRITE moves the bytes
    idx, _ = live.topk_neighbors([victim], 3)
    assert idx[0][0] == anchor  # revival survives the next rebuild
    idx, _ = live.topk_neighbors([anchor], 3)
    assert victim in idx[0]


# --- tombstones under exclude_self and sharded meshes -------------------------


@pytest.mark.parametrize("exclude_self", [True, False])
def test_tombstoned_row_never_surfaces(rng, exclude_self):
    """Delete the anchor's nearest neighbor: it must vanish from the
    anchor's top-k under BOTH self-exclusion settings (the drop
    penalty and the self mask are independent lanes)."""
    live, man = _live(rng)
    anchor = 11
    idx, _ = live.topk_neighbors([anchor], 1)
    victim = int(idx[0][0])
    live.delete([victim])
    k = live.num_live - (1 if exclude_self else 0)
    idx, dist = live.topk_neighbors([anchor], k,
                                    exclude_self=exclude_self)
    assert victim not in idx[0]
    assert np.isfinite(dist).all()
    if not exclude_self:
        assert idx[0][0] == anchor  # self at distance ~0 still wins
    np.testing.assert_array_equal(
        idx, _oracle_topk(live, man, [anchor], k,
                          exclude_self=exclude_self))


def test_tombstoned_row_excluded_on_sharded_mesh(rng):
    """The same contract on a 4-way model-sharded base: the drop
    penalty rides the per-shard scans and the merge, so a tombstone
    can never win on ANY shard (conftest's 8 fake CPU devices)."""
    live, man = _live(rng, n=120, mesh=model_mesh(4))
    assert live.base.shards == 4
    anchor = 17
    idx, _ = live.topk_neighbors([anchor], 2)
    victims = [int(i) for i in idx[0]]
    live.delete(victims)
    idx, dist = live.topk_neighbors([anchor], live.num_live - 1)
    assert not set(victims) & set(idx[0].tolist())
    assert np.isfinite(dist).all()
    np.testing.assert_array_equal(
        idx, _oracle_topk(live, man, [anchor], live.num_live - 1))


# --- under-filled: k beyond the live rows -------------------------------------


def test_k_beyond_live_rows_raises_underfilled(rng):
    """Tombstones are never served as filler: once deletes shrink the
    live set below ``k``, the existing under-filled ``ValueError``
    fires instead of padding with +inf rows."""
    live, _ = _live(rng, n=12)
    live.delete([2, 3, 4, 5])
    assert live.num_live == 8
    # k == live-1 still fills (anchor 0 excluded from its own answer)
    idx, dist = live.topk_neighbors([0], 7)
    assert np.isfinite(dist).all() and len(set(idx[0].tolist())) == 7
    with pytest.raises(ValueError, match="under-filled"):
        live.topk_neighbors([0], 8)  # 8 > the 7 reachable live rows


def test_k_beyond_id_space_is_still_a_range_error(rng):
    """The pre-existing k-range validation is unchanged: k past the
    whole id space fails fast, before any scan."""
    live, _ = _live(rng, n=12)
    with pytest.raises(ValueError, match="out of range"):
        live.topk_neighbors([0], 12)


# --- invariants ---------------------------------------------------------------


def test_generation_folds_into_scan_signature(rng):
    """Every mutation (upsert, delete, compact) bumps the generation
    the batcher's cache key folds in — staleness is structural."""
    live, _ = _live(rng)
    sigs = {live.scan_signature}
    master = live.master.to_array()
    live.upsert([3], [_near(master[20], rng)])
    sigs.add(live.scan_signature)
    live.delete([3])
    sigs.add(live.scan_signature)
    live.compact()
    sigs.add(live.scan_signature)
    assert len(sigs) == 4  # four distinct cache-key suffixes
    assert ("gen", live.generation) == live.scan_signature[-2:]


def test_queries_score_fresh_post_upsert_vectors(rng):
    """A query BY an updated id ranks against its post-upsert vector
    (q_rows gathers from the mutable master, not the frozen table)."""
    live, _ = _live(rng)
    master = live.master.to_array()
    moved, anchor = 2, 50
    live.upsert([moved], [_near(master[anchor], rng)])
    idx, dist = live.topk_neighbors([moved], 1)
    assert idx[0][0] == anchor and dist[0][0] < 0.01


def test_inserts_must_be_contiguous(rng):
    """Ids are row indices: a gapped insert would be an unaddressable
    hole forever, so it is refused up front."""
    live, _ = _live(rng, n=12)
    with pytest.raises(ValueError, match="contiguous"):
        live.upsert([14], [np.zeros(5, np.float32)])


def test_fused_base_rejected(rng):
    """The fused kernel has no tombstone lane — a LiveQueryEngine over
    it would silently serve the two-stage fallback under a signature
    that says 'fused'."""
    table, man = _poincare_table(rng, 40, 5, 1.0)
    eng = QueryEngine(table, spec_from_manifold(man), chunk_rows=32,
                      scan_mode="fused")
    with pytest.raises(ValueError, match="fused"):
        LiveQueryEngine(eng, HostEmbedTable.from_array(table),
                        capacity=4)
