"""The serve CLI: export / one-shot query / JSONL loop."""

import io
import json

import numpy as np
import jax.numpy as jnp
import pytest

from hyperspace_tpu.cli import serve as S


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """(cfg, state, ckpt_dir, artifact_dir) — one tiny trained+exported
    poincare run shared by the CLI tests (module-scoped: the CLI paths
    under test are read-only against it)."""
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.train.checkpoint import CheckpointManager

    tmp = tmp_path_factory.mktemp("serve_cli")
    cfg = pe.PoincareEmbedConfig(num_nodes=30, dim=3, batch_size=16,
                                 neg_samples=4, burnin_steps=0)
    state, opt = pe.init_state(cfg, seed=0)
    pairs = jnp.asarray(
        np.random.default_rng(0).integers(0, 30, (60, 2), np.int64))
    for _ in range(3):
        state, _ = pe.train_step(cfg, opt, state, pairs)
    ckpt = str(tmp / "ckpt")
    with CheckpointManager(ckpt) as ck:
        ck.save(3, state, force=True)
    art = str(tmp / "artifact")
    rc = S.main(["export", f"ckpt={ckpt}", f"out={art}",
                 "workload=poincare", "c=1.0"])
    assert rc == 0
    return cfg, state, ckpt, art


def test_export_wrote_a_committed_artifact(trained, capsys):
    from hyperspace_tpu.serve import is_committed, load_artifact

    cfg, state, _ckpt, art = trained
    assert is_committed(art)
    loaded = load_artifact(art)
    assert loaded.num_nodes == cfg.num_nodes
    assert loaded.step == 3
    assert np.array_equal(loaded.table, np.asarray(state.table))


def test_one_shot_topk_query(trained, capsys):
    _cfg, _state, _ckpt, art = trained
    rc = S.main(["query", f"artifact={art}", "ids=0,1,2", "k=3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["mode"] == "query"
    assert np.asarray(out["neighbors"]).shape == (3, 3)
    assert np.asarray(out["dists"]).shape == (3, 3)


def test_one_shot_score_query(trained, capsys):
    _cfg, _state, _ckpt, art = trained
    rc = S.main(["query", f"artifact={art}", "u=0,1", "v=2,3", "prob=1"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out["scores"]) == 2
    assert all(0 < s <= 1 for s in out["scores"])


def test_jsonl_loop(trained):
    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2}),
        json.dumps({"op": "score", "u": [0], "v": [1]}),
        json.dumps({"op": "nope"}),
        json.dumps([1, 2]),  # valid JSON, not an object
        json.dumps({"op": "topk", "ids": [2**33], "k": 2}),  # > int32
        json.dumps({"op": "topk", "ids": [0.7], "k": 2}),    # float id
        json.dumps({"op": "score", "u": [0], "v": [1],
                    "prob": "false"}),  # string boolean
        json.dumps({"op": "stats"}),
    ]) + "\n"
    out = io.StringIO()
    result = S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert np.asarray(resp[0]["neighbors"]).shape == (2, 2)
    assert len(resp[1]["scores"]) == 1
    assert "error" in resp[2]  # bad op answers in-line, loop survives
    assert "error" in resp[3]  # non-object line too
    assert "error" in resp[4]  # id past int32: error, not a wrapped id
    assert "error" in resp[5]  # float id: error, not a truncated id
    assert "error" in resp[6]  # "false" is not a JSON boolean
    assert resp[7]["fingerprint"]
    assert result["served"] == 3  # the bad lines don't count


def test_serve_mode_stdout_is_responses_only(trained, capsys, monkeypatch):
    """main() in serve mode must keep stdout a strict one-line-per-request
    stream: the closing stats dict goes to stderr."""
    import io as _io
    import sys as _sys

    _cfg, _state, _ckpt, art = trained
    monkeypatch.setattr(
        _sys, "stdin",
        _io.StringIO(json.dumps({"op": "topk", "ids": [0], "k": 2}) + "\n"))
    rc = S.main(["serve", f"artifact={art}"])
    assert rc == 0
    cap = capsys.readouterr()
    out_lines = cap.out.strip().splitlines()
    assert len(out_lines) == 1  # exactly the one response
    assert "neighbors" in json.loads(out_lines[0])
    closing = json.loads(cap.err.strip().splitlines()[-1])
    assert closing["mode"] == "serve" and closing["served"] == 1


def test_latency_summary_rides_stderr(trained, capsys):
    """The one-line serve/e2e_ms summary prints to STDERR on loop exit
    AND beside every stats response; stdout stays strictly responses,
    and the stats response itself carries the latency distribution."""
    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2}),
        json.dumps({"op": "stats"}),
    ]) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    err = capsys.readouterr().err
    # one line per stats request + one on exit
    summaries = [l for l in err.splitlines()
                 if l.startswith("[serve] latency e2e_ms")]
    assert len(summaries) == 2
    assert "p50=" in summaries[0] and "p99=" in summaries[0]
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 2  # stdout: exactly the two responses
    lat = resp[1]["latency_e2e_ms"]
    assert lat["count"] >= 1 and lat["p95"] >= lat["p50"]


def test_broken_stderr_never_kills_the_serve_loop(trained, monkeypatch):
    """A consumer closing our stderr mid-serve loses the latency
    one-liner, not the server: the stats response still lands on
    stdout and the loop keeps serving subsequent requests."""
    import sys as _sys

    class _Broken:
        def write(self, *_a):
            raise BrokenPipeError("consumer went away")

        def flush(self):
            raise BrokenPipeError("consumer went away")

    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    lines = "\n".join([
        json.dumps({"op": "stats"}),
        json.dumps({"op": "topk", "ids": [0], "k": 2}),
    ]) + "\n"
    out = io.StringIO()
    monkeypatch.setattr(_sys, "stderr", _Broken())
    result = S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert len(resp) == 2 and result["served"] == 2
    assert "requests" in resp[0]        # the stats answer, not an error
    assert "neighbors" in resp[1]       # the loop survived past it


def test_crash_still_prints_latency_summary(trained, capsys, monkeypatch):
    """An engine-level crash (outside the per-line error envelope) must
    not lose the closing latency one-liner: the accumulated
    distribution matters most in exactly that post-mortem."""
    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    real_handle = S._handle
    calls = []

    def _dying_handle(batcher, req, entered=None):
        if len(calls) >= 1:
            raise RuntimeError("device fell over")
        calls.append(req)
        return real_handle(batcher, req, entered)

    monkeypatch.setattr(S, "_handle", _dying_handle)
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2}),
        json.dumps({"op": "topk", "ids": [2], "k": 2}),
    ]) + "\n"
    out = io.StringIO()
    with pytest.raises(RuntimeError):
        S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    err = capsys.readouterr().err
    summaries = [l for l in err.splitlines()
                 if l.startswith("[serve] latency e2e_ms")]
    assert summaries and "count=1" in summaries[-1]


def test_bad_overrides_fail_loudly(trained):
    _cfg, _state, _ckpt, art = trained
    with pytest.raises(SystemExit):
        S.main(["query", f"artifact={art}", "ids=a,b", "k=3"])
    with pytest.raises(SystemExit):
        S.main(["query", f"artifact={art}"])  # neither ids nor u/v
    with pytest.raises(SystemExit):
        S.main(["export", "workload=poincare"])  # missing ckpt/out
    with pytest.raises(SystemExit):
        S.main(["query", "bogus_flag=1", f"artifact={art}", "ids=0"])


def test_export_with_index_and_probed_query(trained, tmp_path, capsys):
    """CLI end-to-end for the IVF flags: export index=1 ncells=K ships
    an index (reported in the export JSON), and query nprobe=P answers
    through the loaded artifact — on this sub-threshold 30-row table
    the engine falls back to the exact program (docs/serving.md
    "Approximate retrieval"), so answers match the bare artifact's
    bitwise."""
    from hyperspace_tpu.serve import load_artifact

    _cfg, _state, ckpt, bare_art = trained
    art = str(tmp_path / "ivf_art")
    rc = S.main(["export", f"ckpt={ckpt}", f"out={art}",
                 "workload=poincare", "c=1.0", "index=1", "ncells=8"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["index"]["ncells"] == 8
    loaded = load_artifact(art)
    assert loaded.index is not None and loaded.index.ncells == 8
    assert out["index"]["fingerprint"] == loaded.index.fingerprint

    rc = S.main(["query", f"artifact={art}", "ids=0,1,2", "k=3",
                 "nprobe=2"])
    assert rc == 0
    probed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rc = S.main(["query", f"artifact={bare_art}", "ids=0,1,2", "k=3"])
    assert rc == 0
    exact = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert probed["neighbors"] == exact["neighbors"]
    assert probed["dists"] == exact["dists"]
    # bad values are usage errors, not tracebacks
    with pytest.raises(SystemExit, match="ncells"):
        S.main(["export", f"ckpt={ckpt}", f"out={tmp_path / 'b'}",
                "workload=poincare", "c=1.0", "index=1", "ncells=-3"])
    with pytest.raises(SystemExit, match="nprobe"):
        S.main(["query", f"artifact={art}", "ids=0", "k=3", "nprobe=-1"])
    # data-dependent query-time ValueErrors (k out of range here; the
    # IVF capacity/under-fill errors take the same path) exit clean too
    with pytest.raises(SystemExit, match="k="):
        S.main(["query", f"artifact={art}", "ids=0", "k=999"])


def test_export_requires_explicit_curvature(trained, tmp_path):
    """CLI export of poincare/lorentz without c= must refuse — the
    trained curvature is not in the checkpoint and must not default."""
    _cfg, _state, ckpt, _art = trained
    with pytest.raises(SystemExit, match="requires c="):
        S.main(["export", f"ckpt={ckpt}", f"out={tmp_path / 'a'}",
                "workload=poincare"])
    with pytest.raises(SystemExit, match="want a float"):
        S.main(["export", f"ckpt={ckpt}", f"out={tmp_path / 'a'}",
                "workload=poincare", "c=abc"])
    with pytest.raises(SystemExit, match="want JSON"):
        S.main(["export", f"ckpt={ckpt}", f"out={tmp_path / 'a'}",
                "workload=product", "factors=[[poincare,5]]"])


def test_serve_log_parity_with_train_records(trained, tmp_path):
    """log= on the serve loop writes the TRAIN CLI's record shapes:
    a run_manifest FIRST record (full ServeConfig + device identity)
    and a closing telemetry_summary — read_jsonl reads both."""
    from hyperspace_tpu.train.logging import read_jsonl

    _cfg, _state, _ckpt, art = trained
    log = str(tmp_path / "serve.jsonl")
    cfg = S.apply_overrides(S.ServeConfig(),
                            {"artifact": art, "log": log})
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2}),
        json.dumps({"op": "stats"}),
    ]) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    recs = read_jsonl(log)
    assert recs[0]["event"] == "run_manifest"
    assert recs[0]["config"]["artifact"] == art
    for key in ("backend", "device_kind", "version", "process_index"):
        assert key in recs[0], key
    assert recs[-1]["event"] == "telemetry_summary"
    # session-scoped counters: this loop served one topk request
    assert recs[-1]["ctr/serve/requests"] >= 1


def test_serve_loop_request_id_echo_and_access_log(trained, tmp_path):
    """A stdin request carrying request_id gets it echoed in the
    response line and stamped on its access-log record; anonymous
    requests stay echo-free (schema-stable)."""
    _cfg, _state, _ckpt, art = trained
    access = str(tmp_path / "access.jsonl")
    cfg = S.apply_overrides(S.ServeConfig(),
                            {"artifact": art, "access_log": access})
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1], "k": 2,
                    "request_id": "cli-req-7"}),
        json.dumps({"op": "topk", "ids": [2], "k": 2}),
        json.dumps({"op": "topk", "ids": [0.5], "k": 2,
                    "request_id": "cli-bad-1"}),  # validation error
    ]) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert resp[0]["request_id"] == "cli-req-7"
    assert "request_id" not in resp[1]
    assert "error" in resp[2]
    recs = [json.loads(l) for l in open(access) if l.strip()]
    by_id = {r["request_id"]: r for r in recs}
    assert by_id["cli-req-7"]["outcome"] == "ok"
    assert by_id["cli-req-7"]["route"] == "topk"
    assert by_id["cli-bad-1"]["outcome"] == "validation"
    # the anonymous request got a generated id — never a null line
    assert all(r["request_id"] for r in recs)


def test_serve_loop_trace_and_slow_log_flags(trained, tmp_path):
    """trace=1 arms the span layer for the session: access records
    carry the per-stage decomposition, breaching requests (slo_ms
    microscopic here) get their span tree attached AND teed to the
    slow_log= file, and the session bracket disarms the process-global
    span state on the way out (ISSUE 17 flags)."""
    from hyperspace_tpu.telemetry import spans

    _cfg, _state, _ckpt, art = trained
    access = str(tmp_path / "tr_access.jsonl")
    slow = str(tmp_path / "tr_slow.jsonl")
    cfg = S.apply_overrides(S.ServeConfig(), {
        "artifact": art, "access_log": access, "slow_log": slow,
        "trace": "1", "slo_ms": "0.000001"})
    lines = json.dumps({"op": "topk", "ids": [0, 1], "k": 2,
                        "request_id": "tr-1"}) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    assert not spans.enabled()  # the session's finally disarmed it
    recs = [json.loads(l) for l in open(access) if l.strip()]
    rec = {r["request_id"]: r for r in recs}["tr-1"]
    assert set(rec["stages"]) == {"queue_wait", "collate_wait",
                                 "dispatch", "serialize"}
    assert sum(rec["stages"].values()) == pytest.approx(
        rec["e2e_ms"], abs=0.01)
    assert rec["span"]["request_id"] == "tr-1"  # breached: tree rides
    slows = [json.loads(l) for l in open(slow) if l.strip()]
    assert [r["request_id"] for r in slows] == ["tr-1"]
    assert "span" in slows[0]
    # without trace/slow_log no tree rides (the boundary ``stages``
    # block is stamp arithmetic and stays on every record regardless)
    cfg_off = S.apply_overrides(S.ServeConfig(),
                                {"artifact": art, "access_log": access})
    S.run_serve(cfg_off, stdin=io.StringIO(lines), stdout=io.StringIO())
    flat = [json.loads(l) for l in open(access) if l.strip()][-1]
    assert "span" not in flat and "stages" in flat


def test_serve_stats_op_carries_window_block(trained):
    """window_s= (the default) surfaces the rolling SLO block in the
    stdin loop's stats response — the /v1/stats parity."""
    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    lines = "\n".join([
        json.dumps({"op": "topk", "ids": [0, 1, 2], "k": 2}),
        json.dumps({"op": "stats"}),
    ]) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    win = resp[1]["window"]
    assert win is not None and win["e2e_ms"] is not None
    assert win["e2e_ms"]["count"] >= 1
    # window_s=0 disables: stats says so explicitly
    cfg0 = S.apply_overrides(S.ServeConfig(),
                             {"artifact": art, "window_s": "0"})
    out0 = io.StringIO()
    S.run_serve(cfg0, stdin=io.StringIO(
        json.dumps({"op": "stats"}) + "\n"), stdout=out0)
    assert json.loads(out0.getvalue().strip())["window"] is None


def test_serve_loop_pre_batcher_failures_are_logged(trained, tmp_path):
    """Failures that never reach the batcher (parse, non-object line,
    unknown op, missing ids) still write access records and echo the
    request_id on the error response — the HTTP _serve_access parity."""
    _cfg, _state, _ckpt, art = trained
    access = str(tmp_path / "pre.jsonl")
    cfg = S.apply_overrides(S.ServeConfig(),
                            {"artifact": art, "access_log": access})
    lines = "\n".join([
        "this is not json",
        json.dumps([1, 2]),                      # non-object line
        json.dumps({"op": "nope", "request_id": "pre-1"}),
        json.dumps({"op": "topk", "k": 2, "request_id": "pre-2"}),
    ]) + "\n"
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(lines), stdout=out)
    resp = [json.loads(l) for l in out.getvalue().strip().splitlines()]
    assert all("error" in r for r in resp)
    # error responses echo a well-formed request_id (join-ability)
    assert resp[2]["request_id"] == "pre-1"
    assert resp[3]["request_id"] == "pre-2"
    assert "request_id" not in resp[0]  # unparseable: no id to echo
    recs = [json.loads(l) for l in open(access) if l.strip()]
    assert [r["outcome"] for r in recs] == [
        "parse", "validation", "validation", "validation"]
    by_id = {r["request_id"]: r for r in recs if r["request_id"]}
    assert by_id["pre-1"]["route"] == "nope"
    assert by_id["pre-2"]["route"] == "topk"
    assert all(r["request_id"] for r in recs)  # parse line: generated


def test_serve_stats_op_echoes_request_id(trained):
    """Every answered line is joinable — the stats op echoes too."""
    _cfg, _state, _ckpt, art = trained
    cfg = S.apply_overrides(S.ServeConfig(), {"artifact": art})
    out = io.StringIO()
    S.run_serve(cfg, stdin=io.StringIO(
        json.dumps({"op": "stats", "request_id": "st-1"}) + "\n"),
        stdout=out)
    assert json.loads(out.getvalue().strip())["request_id"] == "st-1"
