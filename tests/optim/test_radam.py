"""Riemannian Adam tests (SURVEY.md §4.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from hyperspace_tpu.manifolds import Lorentz, PoincareBall, Sphere
from hyperspace_tpu.optim.radam import riemannian_adam


def test_euclidean_leaf_matches_optax_adam():
    """With tag None, riemannian_adam must reduce to standard Adam."""
    params = {"w": jnp.asarray([1.0, -2.0, 3.0], jnp.float64)}
    tags = {"w": None}
    opt_r = riemannian_adam(0.05, tags)
    opt_e = optax.adam(0.05)
    sr, se = opt_r.init(params), opt_e.init(params)
    pr, pe_ = params, params
    key = jax.random.PRNGKey(0)
    for _ in range(25):
        key, k = jax.random.split(key)
        g = {"w": jax.random.normal(k, (3,), jnp.float64)}
        ur, sr = opt_r.update(g, sr, pr)
        ue, se = opt_e.update(g, se, pe_)
        pr = optax.apply_updates(pr, ur)
        pe_ = optax.apply_updates(pe_, ue)
    np.testing.assert_allclose(pr["w"], pe_["w"], rtol=1e-9)


@pytest.mark.parametrize("manifold", [PoincareBall(1.0), Lorentz(1.0), Sphere(1.0)])
def test_converges_to_target_on_manifold(manifold):
    """Minimize d(x, target)²: RAdam must converge and stay on-manifold."""
    key = jax.random.PRNGKey(1)
    d = 5
    D = manifold.ambient_dim(d)
    target = manifold.random_normal(key, (D,), jnp.float64, std=0.5)
    x = manifold.random_normal(jax.random.PRNGKey(2), (D,), jnp.float64, std=0.5)

    opt = riemannian_adam(0.05, tags=manifold)
    state = opt.init(x)

    @jax.jit
    def step(x, state):
        loss, g = jax.value_and_grad(lambda p: manifold.sqdist(p, target))(x)
        upd, state = opt.update(g, state, x)
        return optax.apply_updates(x, upd), state, loss

    for _ in range(400):
        x, state, loss = step(x, state)
    assert float(manifold.dist(x, target)) < 1e-2
    assert float(manifold.check_point(x)) < 1e-6


def test_moments_are_transported_tangent_vectors():
    """After updates the first moment must lie in the tangent space at x."""
    m = Lorentz(1.0)
    x = m.random_normal(jax.random.PRNGKey(3), (4,), jnp.float64)
    target = m.random_normal(jax.random.PRNGKey(4), (4,), jnp.float64)
    opt = riemannian_adam(0.1, tags=m)
    state = opt.init(x)
    for _ in range(10):
        g = jax.grad(lambda p: m.sqdist(p, target))(x)
        upd, state = opt.update(g, state, x)
        x = optax.apply_updates(x, upd)
    from hyperspace_tpu.manifolds.lorentz import minkowski_dot

    # ⟨x, mu⟩_L == 0 for tangent vectors at x
    assert abs(float(minkowski_dot(x, state[1], keepdims=False))) < 1e-8


def test_mixed_tree_and_jit():
    """Manifold + Euclidean leaves in one tree, under one jitted step."""
    ball = PoincareBall(1.0)
    params = {
        "emb": ball.random_normal(jax.random.PRNGKey(5), (7, 3), jnp.float64, std=0.3),
        "w": jnp.ones((3, 2), jnp.float64),
    }
    tags = {"emb": ball, "w": None}
    opt = riemannian_adam(0.02, tags)
    state = opt.init(params)

    def loss_fn(p):
        h = ball.logmap0(p["emb"]) @ p["w"]
        return jnp.sum(h**2) + jnp.sum(ball.dist0(p["emb"]) ** 2)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, state = opt.update(g, state, params)
        return optax.apply_updates(params, upd), state, loss

    l0 = None
    for i in range(100):
        params, state, loss = step(params, state)
        if i == 0:
            l0 = float(loss)
    assert float(loss) < l0
    assert np.isfinite(np.asarray(params["emb"])).all()
    assert float(jnp.max(ball.check_point(params["emb"]))) == 0.0


@pytest.mark.slow
def test_retraction_mode():
    m = PoincareBall(1.0)
    x = m.random_normal(jax.random.PRNGKey(6), (3,), jnp.float64, std=0.3)
    target = m.random_normal(jax.random.PRNGKey(7), (3,), jnp.float64, std=0.3)
    opt = riemannian_adam(0.05, tags=m, use_expmap=False)
    state = opt.init(x)
    for _ in range(300):
        g = jax.grad(lambda p: m.sqdist(p, target))(x)
        upd, state = opt.update(g, state, x)
        x = optax.apply_updates(x, upd)
    assert float(m.dist(x, target)) < 5e-2


@pytest.mark.slow
def test_stabilize_cadence():
    """stabilize_every: params stay exactly on-manifold and the first moment
    is exactly re-tangentialized on stabilize steps; convergence matches the
    un-stabilized run to tight tolerance (projection is a no-op drift fix)."""
    m = Lorentz(1.0)
    x0 = m.random_normal(jax.random.PRNGKey(8), (6, 4), jnp.float64, std=0.4)
    target = m.random_normal(jax.random.PRNGKey(9), (6, 4), jnp.float64, std=0.4)

    def run(stabilize_every):
        opt = riemannian_adam(0.05, tags=m, stabilize_every=stabilize_every)
        state = opt.init(x0)
        x = x0
        for _ in range(50):
            g = jax.grad(lambda p: jnp.sum(m.sqdist(p, target)))(x)
            upd, state = opt.update(g, state, x)
            x = optax.apply_updates(x, upd)
        return x, state

    x_plain, _ = run(0)
    x_stab, state = run(5)
    np.testing.assert_allclose(np.asarray(x_stab), np.asarray(x_plain),
                               rtol=1e-6, atol=1e-8)
    assert float(jnp.max(m.check_point(x_stab))) < 1e-9
    from hyperspace_tpu.manifolds.lorentz import minkowski_dot

    # stabilized moment is tangent at x (|⟨x, mu⟩_L| ~ 0)
    tang_err = jnp.abs(minkowski_dot(x_stab, state[1], keepdims=False))
    assert float(jnp.max(tang_err)) < 1e-8
