"""Gradient accumulation (optim/accum.py): wiring + semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu.optim.accum import with_grad_accumulation


def test_accum_semantics_identity_on_repeated_grads():
    """MultiSteps(2) fed the same gradient twice == one inner update with
    that gradient; the intermediate microstep must not move params."""
    params = jnp.asarray([1.0, -2.0, 0.5])
    g = jnp.asarray([0.3, -0.1, 0.2])
    inner = optax.adamw(1e-2)

    opt, st = with_grad_accumulation(inner, params, 2)
    p = params
    up, st = opt.update(g, st, p)
    p_mid = optax.apply_updates(p, up)
    np.testing.assert_array_equal(np.asarray(p_mid), np.asarray(params))
    up, st = opt.update(g, st, p_mid)
    p_end = optax.apply_updates(p_mid, up)

    st1 = inner.init(params)
    up1, _ = inner.update(g, st1, params)
    p_ref = optax.apply_updates(params, up1)
    np.testing.assert_allclose(np.asarray(p_end), np.asarray(p_ref),
                               rtol=1e-6)


def test_accum_k1_is_inner_transform():
    params = {"w": jnp.ones((2,))}
    inner = optax.sgd(0.1)
    opt, st = with_grad_accumulation(inner, params, 1)
    assert opt is inner
    up, _ = opt.update({"w": jnp.ones((2,))}, st, params)
    np.testing.assert_allclose(np.asarray(up["w"]), -0.1 * np.ones(2))


def test_cli_hybonet_accum_runs(tmp_path, capsys):
    import json

    from hyperspace_tpu.cli import train as cli

    rc = cli.main(["hybonet", "steps=4", "accum=2", "dim=16", "num_layers=1",
                   "num_heads=2", "batch_size=8"])
    assert rc == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["workload"] == "hybonet" and np.isfinite(res["loss"])
