"""Optimizer tests (SURVEY.md §4.5): RSGD decreases an on-manifold objective
and stays on the manifold; mixed Euclidean/manifold trees work via tags."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.optim.rsgd import riemannian_sgd


def test_rsgd_converges_to_target_on_ball():
    ball = PoincareBall(1.0)
    target = jnp.asarray([[0.3, -0.4, 0.1]], jnp.float64)
    x = jnp.zeros((1, 3), jnp.float64)
    opt = riemannian_sgd(0.1, tags=ball)
    state = opt.init(x)

    @jax.jit
    def step(x, state):
        loss, g = jax.value_and_grad(lambda p: jnp.sum(ball.sqdist(p, target)))(x)
        upd, state = opt.update(g, state, x)
        return optax.apply_updates(x, upd), state, loss

    losses = []
    for _ in range(200):
        x, state, loss = step(x, state)
        losses.append(float(loss))
    assert losses[-1] < 1e-8
    np.testing.assert_allclose(np.asarray(x), np.asarray(target), atol=1e-4)
    # monotone decrease over the trajectory tail
    assert losses[50] < losses[0] and losses[-1] < losses[50]


@pytest.mark.slow
def test_rsgd_stays_on_hyperboloid():
    lor = Lorentz(1.0)
    o = lor.origin((4, 5), jnp.float64)
    target = lor.random_normal(jax.random.PRNGKey(0), (4, 5), jnp.float64)
    x = o
    opt = riemannian_sgd(0.2, tags=lor)
    state = opt.init(x)
    for _ in range(300):
        g = jax.grad(lambda p: jnp.sum(lor.sqdist(p, target)))(x)
        upd, state = opt.update(g, state, x)
        x = optax.apply_updates(x, upd)
    np.testing.assert_allclose(np.asarray(lor.check_point(x)), 0.0, atol=1e-9)
    assert float(jnp.max(lor.dist(x, target))) < 1e-3


@pytest.mark.slow
def test_rsgd_mixed_tree_euclidean_and_manifold():
    ball = PoincareBall(1.0)
    params = {
        "emb": jnp.asarray([[0.1, 0.1]], jnp.float64),
        "w": jnp.ones((2,), jnp.float64),
    }
    tags = {"emb": ball, "w": None}
    tgt = jnp.asarray([[-0.2, 0.25]], jnp.float64)
    opt = riemannian_sgd(0.1, tags=tags)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.sum(ball.sqdist(p["emb"], tgt)) + jnp.sum((p["w"] - 3.0) ** 2)

    for _ in range(300):
        g = jax.grad(loss_fn)(params)
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
    np.testing.assert_allclose(np.asarray(params["emb"]), np.asarray(tgt), atol=1e-4)
    np.testing.assert_allclose(np.asarray(params["w"]), 3.0, atol=1e-4)


def test_burnin_reduces_early_lr():
    ball = PoincareBall(1.0)
    x = jnp.asarray([[0.1, 0.0]], jnp.float64)
    g = jnp.asarray([[1.0, 0.0]], jnp.float64)
    opt_b = riemannian_sgd(0.5, tags=ball, burnin_steps=5, burnin_factor=0.1)
    opt_n = riemannian_sgd(0.5, tags=ball)
    sb, sn = opt_b.init(x), opt_n.init(x)
    ub, _ = opt_b.update(g, sb, x)
    un, _ = opt_n.update(g, sn, x)
    assert float(jnp.linalg.norm(ub)) < float(jnp.linalg.norm(un)) / 5.0
