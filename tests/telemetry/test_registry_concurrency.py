"""Registry concurrency: multi-threaded observe/inc racing snapshot/
mark/reset/export never tears a histogram snapshot — the bucket-count
sum ALWAYS equals the snapshot's count, and counters never go
backwards within one run epoch (the exposition endpoint scrapes a
live registry from the asyncio thread while the dispatch executor
observes — this is the exact race)."""

import threading

from hyperspace_tpu.telemetry.registry import Registry

N_THREADS = 8
N_OPS = 400


def _consistent(snap):
    assert sum(snap.counts) == snap.count, (
        f"torn histogram snapshot: bucket sum {sum(snap.counts)} != "
        f"count {snap.count}")
    if snap.count:
        assert snap.vmin is not None and snap.vmax is not None


def test_observe_inc_race_snapshot_mark_export():
    reg = Registry()
    stop = threading.Event()
    errors: list = []

    def writer(i):
        try:
            for j in range(N_OPS):
                reg.observe("serve/e2e_ms", 0.1 + (i * N_OPS + j) % 50)
                reg.inc("serve/requests")
                reg.set_gauge("serve/degrade_level", i)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                for snap_source in (reg.mark()["hists"].values(),
                                    reg.export()[2].values()):
                    for snap in snap_source:
                        _consistent(snap)
                full = reg.snapshot()
                h = full.get("hist/serve/e2e_ms")
                if h is not None:
                    assert h["count"] >= 0
        except Exception as e:  # pragma: no cover
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(N_THREADS)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors, errors
    # quiescent totals are exact: no observe was lost to a race
    counters, _gauges, hists = reg.export()
    assert counters["serve/requests"] == N_THREADS * N_OPS
    final = hists["serve/e2e_ms"]  # export() returns snapshots
    _consistent(final)
    assert final.count == N_THREADS * N_OPS


def test_observe_racing_reset_never_tears():
    """A reset mid-storm may drop in-flight observes (the documented
    trade) but every snapshot taken around it is internally
    consistent and the post-reset epoch converges."""
    reg = Registry()
    stop = threading.Event()
    errors: list = []

    def writer():
        try:
            while not stop.is_set():
                reg.observe("x_ms", 1.0)
                reg.inc("n")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def resetter():
        try:
            for _ in range(200):
                for snap in reg.export()[2].values():
                    _consistent(snap)
                reg.reset()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ws = [threading.Thread(target=writer) for _ in range(4)]
    r = threading.Thread(target=resetter)
    for t in ws + [r]:
        t.start()
    r.join()
    stop.set()
    for t in ws:
        t.join()
    assert not errors, errors
    for snap in reg.export()[2].values():
        _consistent(snap)
