"""Windowed SLOs: ring-delta percentiles (never run-cumulative), the
4.9% accuracy contract against exact percentiles, rates, and the
latency-pressure ladder signal."""

import numpy as np
import pytest

from hyperspace_tpu.telemetry.registry import Registry
from hyperspace_tpu.telemetry.window import SloWindow


def _mk(window_s=60.0, slots=12, reg=None, now=0.0):
    """Window primed at a pinned fake clock (the ring's baseline is
    the construction-time capture — traffic in the first slot is
    already a delta against it)."""
    reg = reg or Registry()
    return reg, SloWindow(window_s, slots=slots, registry=reg, now=now)


def test_validation():
    with pytest.raises(ValueError, match="window_s"):
        SloWindow(0.0)
    with pytest.raises(ValueError, match="slots"):
        SloWindow(10.0, slots=1)


def test_empty_window_reports_none_distribution():
    _reg, w = _mk()
    rep = w.report(now=100.0)
    assert rep["e2e_ms"] is None
    assert rep["rate_qps"] == 0.0 and rep["shed_rate"] == 0.0


def test_percentiles_from_ring_deltas_not_cumulative():
    """A pre-window burst of HUGE latencies must not drag the window's
    percentiles: the report subtracts the ring baseline, so only
    in-window observations count — the acceptance contract."""
    reg = Registry()
    for _ in range(500):
        reg.observe("serve/e2e_ms", 5000.0)  # ancient horror
    # the window opens AFTER the burst: its construction-time capture
    # is the baseline every report subtracts
    w = SloWindow(60.0, slots=12, registry=reg, now=0.0)
    rng = np.random.default_rng(0)
    recent = np.exp(rng.uniform(np.log(0.5), np.log(50.0), size=4000))
    for v in recent:
        reg.observe("serve/e2e_ms", float(v))
    rep = w.report(now=20.0)
    e = rep["e2e_ms"]
    assert e is not None and e["count"] == len(recent)
    # ring-delta percentiles track the EXACT percentiles of the recent
    # sample within the histogram's ~4.9% bound (+ tiny sampling slack)
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        exact = float(np.percentile(recent, q))
        assert e[key] == pytest.approx(exact, rel=0.06), (key, exact)
    # cumulative would have been dominated by the 5000 ms burst
    assert e["p99"] < 100.0


def test_rates_are_per_second_deltas():
    reg = Registry()
    reg.inc("serve/requests", 100)   # pre-window traffic
    reg.inc("serve/shed", 7)
    w = SloWindow(10.0, slots=5, registry=reg, now=0.0)
    reg.inc("serve/requests", 50)
    reg.inc("serve/shed", 5)
    reg.inc("serve/deadline_exceeded", 2)
    reg.inc("serve/errors", 1)
    rep = w.report(now=10.0)
    assert rep["rate_qps"] == pytest.approx(5.0)
    assert rep["shed_rate"] == pytest.approx(0.5)
    assert rep["deadline_rate"] == pytest.approx(0.2)
    assert rep["error_rate"] == pytest.approx(0.1)


def test_ring_is_bounded_and_old_entries_age_out():
    reg, w = _mk(window_s=10.0, slots=5)
    for t in range(0, 100, 2):
        w.tick(now=float(t))
    # deque maxlen = slots+1: memory bounded however long the run
    assert len(w._ring) <= 6
    reg.inc("serve/requests", 10)
    rep = w.report(now=100.0)
    # baseline is at most window+slot old: the span can never grow
    # unboundedly even after a long quiet stretch
    assert rep["window_s"] <= 10.0 + w.slot_s + 1e-6


def test_latency_pressure_signal():
    reg, w = _mk(window_s=10.0, slots=5)
    assert w.latency_pressure(50.0, now=0.0) == 0.0  # empty = calm
    w.tick(now=0.0)
    for _ in range(50):
        reg.observe("serve/e2e_ms", 500.0)  # way past the SLO
    # cache holds one slot: advance past it
    assert w.latency_pressure(50.0, now=5.0) == 1.0
    assert w.latency_pressure(0.0, now=5.0) == 0.0  # slo_ms=0 = off


def test_tick_is_slot_gated():
    reg, w = _mk(window_s=60.0, slots=12)  # slot = 5s
    w.tick(now=0.0)
    w.tick(now=1.0)
    w.tick(now=2.0)
    assert len(w._ring) == 1  # inside one slot: one capture
    w.tick(now=5.1)
    assert len(w._ring) == 2
