"""Trace spans (telemetry/trace.py): nesting, disabled-mode zero cost,
boundary flush vs cumulative totals, Chrome dump shape."""

import json
import time

from hyperspace_tpu.telemetry import trace


def _fresh(**kw):
    return trace.Tracer(enabled=True, **kw)


def test_disabled_span_is_shared_nullcontext():
    # the zero-cost contract: disabled (the default) the module-level
    # span() returns ONE shared stateless context manager — no
    # allocation, no recording
    t = trace.default_tracer()
    was = t.enabled
    t.enabled = False
    try:
        before = t.total_fields()
        a = trace.span("x")
        b = trace.span("y")
        assert a is b is trace._NULL
        with a:
            pass
        assert t.total_fields() == before  # nothing recorded
    finally:
        t.enabled = was


def test_span_nesting_records_both_levels():
    t = _fresh(keep_events=True)
    with t.span("outer"):
        with t.span("inner"):
            time.sleep(0.01)
    fields = t.total_fields()
    assert fields["span/outer_n"] == 1 and fields["span/inner_n"] == 1
    # containment: the outer span covers the inner one
    assert fields["span/outer_s"] >= fields["span/inner_s"] > 0
    (n1, t1a, t1b, _, _), (n2, t2a, t2b, _, _) = sorted(
        t._events, key=lambda e: e[1])
    assert (n1, n2) == ("outer", "inner")
    assert t1a <= t2a and t2b <= t1b


def test_flush_fields_resets_boundary_but_not_totals():
    t = _fresh()
    with t.span("a"):
        pass
    first = t.flush_fields()
    assert "span/a_s" in first
    assert t.flush_fields() == {}  # boundary aggregate drained
    with t.span("a"):
        pass
    assert "span/a_s" in t.flush_fields()
    assert t.total_fields()["span/a_n"] == 2  # cumulative survives


def test_chrome_dump_is_perfetto_loadable_shape(tmp_path):
    t = _fresh(keep_events=True)
    with t.span("dispatch"):
        with t.span("metrics_flush"):
            pass
    path = str(tmp_path / "trace.json")
    n = t.dump_chrome_trace(path)
    assert n == 2
    doc = json.loads(open(path).read())
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"
        assert set(ev) >= {"name", "pid", "tid", "ts", "dur"}
        assert ev["dur"] >= 0
    # dump DRAINS: a second run's dump starts from a clean timeline
    assert t.dump_chrome_trace(str(tmp_path / "t2.json")) == 0


def test_span_args_land_in_chrome_dump_and_late_fills_count(tmp_path):
    """The optional args dict rides into the trace event; it is held by
    REFERENCE so a call site can fill in late-known metadata (cache
    hits) before the span exits.  Spans without args stay bare."""
    t = _fresh(keep_events=True)
    meta = {"batch": 32}
    with t.span("query", args=meta):
        meta["cache_hits"] = 7  # filled in mid-span, batcher-style
    with t.span("plain"):
        pass
    t.record_span("ckpt_save", 1.0, 2.0, args={"step": 64})
    path = str(tmp_path / "trace.json")
    assert t.dump_chrome_trace(path) == 3
    evs = {e["name"]: e for e in json.loads(open(path).read())["traceEvents"]}
    assert evs["query"]["args"] == {"batch": 32, "cache_hits": 7}
    assert evs["ckpt_save"]["args"] == {"step": 64}
    assert "args" not in evs["plain"]


def test_keep_events_off_aggregates_without_retaining():
    t = _fresh(keep_events=False)
    for _ in range(10):
        with t.span("s"):
            pass
    assert len(t._events) == 0
    assert t.total_fields()["span/s_n"] == 10


def test_retention_ring_keeps_the_newest_events(monkeypatch):
    # the dump's crash-diagnosis job needs the timeline's TAIL: at the
    # cap, the OLDEST events evict (ring), and the drop count is honest
    import collections

    t = _fresh(keep_events=True)
    t._events = collections.deque(maxlen=3)
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert [e[0] for e in t._events] == ["s2", "s3", "s4"]
    assert t._dropped == 2


def test_enable_disable_roundtrip():
    t = trace.default_tracer()
    was_enabled, was_keep = t.enabled, t.keep_events
    try:
        got = trace.enable(keep_events=True)
        assert got is t and t.enabled and t.keep_events
        with trace.span("roundtrip"):
            pass
        assert t.total_fields().get("span/roundtrip_n") == 1
        trace.disable()
        assert not t.enabled
    finally:
        t.enabled, t.keep_events = was_enabled, was_keep
