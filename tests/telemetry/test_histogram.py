"""Streaming histograms (telemetry/histogram.py): quantile error bound,
merge associativity, thread-safety, empty-snapshot shape, and the
registry's ``hist/<name>`` surfacing + baseline-delta mechanics."""

import threading

import numpy as np
import pytest

from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.histogram import (
    Histogram,
    HistogramSnapshot,
    observe,
)
from hyperspace_tpu.telemetry.registry import Registry


def test_empty_histogram_snapshot_shape():
    s = Histogram().snapshot()
    assert s.count == 0 and s.sum == 0.0
    assert s.quantile(0.5) is None
    assert s.fields() == {"count": 0, "sum": 0.0, "min": None,
                          "max": None, "p50": None, "p90": None,
                          "p95": None, "p99": None}


def test_single_value_quantiles_are_exact():
    h = Histogram()
    h.observe(3.7)
    s = h.snapshot()
    # the estimate clamps to observed min/max, so one value is exact
    for q in (0.0, 0.5, 0.99, 1.0):
        assert s.quantile(q) == pytest.approx(3.7)
    f = s.fields()
    assert f["count"] == 1 and f["min"] == f["max"] == pytest.approx(3.7)


def test_quantile_error_bound_vs_numpy_on_log_uniform():
    """The ~5% relative-error contract (geometric bucket midpoint at
    growth 1.1 → sqrt(1.1)-1 ≈ 4.9%) against numpy's exact quantiles on
    log-uniform samples spanning 6 decades."""
    rng = np.random.default_rng(0)
    vals = np.exp(rng.uniform(np.log(1e-2), np.log(1e4), 50_000))
    h = Histogram()
    for v in vals:
        h.observe(float(v))
    s = h.snapshot()
    for q in (0.5, 0.9, 0.95, 0.99):
        ref = float(np.quantile(vals, q))
        est = s.quantile(q)
        assert abs(est - ref) / ref <= 0.05, (q, est, ref)


def test_out_of_range_values_clamp_to_observed_extremes():
    h = Histogram()
    h.observe(1e-7)   # under LO → underflow bucket
    h.observe(1e7)    # past HI → overflow bucket
    s = h.snapshot()
    assert s.quantile(0.01) == pytest.approx(1e-7)
    assert s.quantile(0.99) == pytest.approx(1e7)
    assert s.count == 2


def test_nan_observations_are_dropped():
    h = Histogram()
    h.observe(float("nan"))
    assert h.snapshot().count == 0
    h.observe(2.0)
    assert h.snapshot().count == 1


def test_merge_is_associative_and_matches_concatenation():
    rng = np.random.default_rng(1)
    chunks = [np.exp(rng.uniform(-2, 6, 500)) for _ in range(3)]
    hists = []
    for c in chunks:
        h = Histogram()
        for v in c:
            h.observe(float(v))
        hists.append(h.snapshot())
    a, b, c = hists
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.counts == right.counts
    assert left.count == right.count == sum(len(x) for x in chunks)
    assert left.sum == pytest.approx(right.sum)
    assert left.vmin == right.vmin and left.vmax == right.vmax
    # merged == one histogram over the concatenated stream
    whole = Histogram()
    for v in np.concatenate(chunks):
        whole.observe(float(v))
    ws = whole.snapshot()
    assert ws.counts == left.counts and ws.count == left.count
    for q in (0.5, 0.95):
        assert left.quantile(q) == pytest.approx(ws.quantile(q))


def test_merge_rejects_scheme_mismatch():
    a = Histogram().snapshot()
    b = Histogram(lo=1e-2, hi=1e2, growth=1.5).snapshot()
    with pytest.raises(ValueError, match="scheme mismatch"):
        a.merge(b)


def test_since_subtracts_a_baseline():
    h = Histogram()
    for v in (1.0, 2.0, 4.0):
        h.observe(v)
    base = h.snapshot()
    for v in (8.0, 16.0):
        h.observe(v)
    delta = h.snapshot().since(base)
    assert delta.count == 2
    assert delta.sum == pytest.approx(24.0)
    # only the two post-baseline buckets remain populated
    assert sum(delta.counts) == 2


def test_since_window_extremes_exclude_premark_spike():
    # a pre-mark 1500 ms spike must not surface as every later
    # interval's min/max: the delta tightens to its bucket envelope
    h = Histogram()
    h.observe(1500.0)
    h.observe(0.5)
    base = h.snapshot()
    for v in (3.0, 9.0):
        h.observe(v)
    delta = h.snapshot().since(base)
    # bounds come from the window's buckets (≤ ~10% wide), not lifetime
    assert delta.vmin is not None and 2.0 <= delta.vmin <= 3.0
    assert delta.vmax is not None and 9.0 <= delta.vmax <= 10.0
    # and the window quantiles stay inside the envelope
    assert delta.quantile(0.99) <= delta.vmax
    # lifetime extremes still intersect when they fall in the window's
    # edge buckets: an empty window reports no extremes at all
    empty = h.snapshot().since(h.snapshot())
    assert empty.count == 0 and empty.vmin is None and empty.vmax is None


def test_since_stale_baseline_never_goes_negative():
    # library misuse across runs: mark() taken, histograms reset, then
    # smaller fresh values under the same name — the delta must degrade
    # to clamped zeros, never emit count > 0 beside a negative sum
    h = Histogram()
    for _ in range(5):
        h.observe(1000.0)
    stale = h.snapshot()
    h.reset()
    for _ in range(6):
        h.observe(5.0)
    delta = h.snapshot().since(stale)
    assert delta.sum >= 0.0
    for q in (0.5, 0.99):
        est = delta.quantile(q)
        assert est is None or est >= 0.0
    assert all(c >= 0 for c in delta.counts)


def test_concurrent_observe_loses_nothing():
    h = Histogram()
    n_threads, per = 8, 5_000

    def work(seed):
        rng = np.random.default_rng(seed)
        for v in rng.uniform(0.1, 100.0, per):
            h.observe(float(v))

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = h.snapshot()
    assert s.count == n_threads * per
    assert sum(s.counts) == n_threads * per
    assert 0.1 <= s.vmin and s.vmax <= 100.0


def test_bad_scheme_rejected():
    with pytest.raises(ValueError):
        Histogram(lo=0.0)
    with pytest.raises(ValueError):
        Histogram(growth=1.0)


# --- registry integration ----------------------------------------------------


def test_registry_surfaces_hist_entries_with_fixed_prefix():
    reg = Registry()
    reg.observe("lat/e2e_ms", 5.0)
    reg.observe("lat/e2e_ms", 7.0)
    reg.inc("reqs")
    snap = reg.snapshot("ctr/")
    # counters take the prefix; histograms keep the fixed hist/ space
    assert snap["ctr/reqs"] == 1
    ent = snap["hist/lat/e2e_ms"]
    assert ent["count"] == 2 and ent["sum"] == pytest.approx(12.0)
    assert ent["min"] == pytest.approx(5.0)
    assert ent["max"] == pytest.approx(7.0)


def test_registry_baseline_reports_delta_and_omits_idle_hists():
    reg = Registry()
    reg.observe("busy_ms", 1.0)
    reg.observe("idle_ms", 1.0)
    base = reg.mark()
    reg.observe("busy_ms", 9.0)
    snap = reg.snapshot(baseline=base)
    assert snap["hist/busy_ms"]["count"] == 1  # delta, not cumulative
    assert snap["hist/busy_ms"]["max"] == pytest.approx(9.0)
    # nothing observed since the mark → omitted (the gauge contract)
    assert "hist/idle_ms" not in snap


def test_registry_reset_drops_hists():
    reg = Registry()
    reg.observe("x_ms", 1.0)
    reg.reset()
    assert reg.snapshot() == {}


def test_module_level_observe_reaches_default_registry():
    reg = telem.default_registry()
    base = reg.mark()
    observe("testonly/obs_ms", 2.5)          # histogram.observe
    telem.observe("testonly/obs_ms", 3.5)    # registry re-export
    snap = reg.snapshot(baseline=base)
    ent = snap["hist/testonly/obs_ms"]
    assert ent["count"] == 2 and ent["sum"] == pytest.approx(6.0)


def test_snapshot_fields_are_json_safe():
    import json

    h = Histogram()
    h.observe(1.25)
    assert json.loads(json.dumps(h.snapshot().fields()))["count"] == 1
    assert isinstance(h.snapshot(), HistogramSnapshot)
