"""Prometheus exposition: name sanitation, escaping, format goldens,
cumulative-bucket correctness, and the file snapshotter."""

import math
import os

import pytest

from hyperspace_tpu.telemetry.exposition import (MetricsFileWriter,
                                                 escape_help,
                                                 escape_label_value,
                                                 render_prometheus,
                                                 sanitize_name)
from hyperspace_tpu.telemetry.registry import Registry


def test_sanitize_name_golden():
    # the ISSUE's canonical example, pinned
    assert sanitize_name("serve/e2e_ms") == "hyperspace_serve_e2e_ms"
    assert sanitize_name("jax/recompiles") == "hyperspace_jax_recompiles"
    assert sanitize_name("a.b-c/d e") == "hyperspace_a_b_c_d_e"
    # already-valid runes (incl. colon) pass through
    assert sanitize_name("ok_name:x9") == "hyperspace_ok_name:x9"


def test_escaping_golden():
    assert escape_help("a\\b\nc") == "a\\\\b\\nc"
    assert escape_label_value('say "hi"\n\\') == 'say \\"hi\\"\\n\\\\'


def test_render_counters_gauges_golden():
    reg = Registry()
    reg.inc("serve/requests", 3)
    reg.inc("jax/compile_s", 1.5)
    reg.set_gauge("serve/degrade_level", 2)
    text = render_prometheus(reg, labels={"process_index": 0})
    lines = text.splitlines()
    # families sorted, HELP carries the ORIGINAL registry name (the
    # catalog round-trip key), TYPE is right, samples labeled
    assert lines[0] == ("# HELP hyperspace_jax_compile_s jax/compile_s")
    assert lines[1] == "# TYPE hyperspace_jax_compile_s counter"
    assert lines[2] == 'hyperspace_jax_compile_s{process_index="0"} 1.5'
    assert ("# TYPE hyperspace_serve_requests counter" in lines)
    assert ('hyperspace_serve_requests{process_index="0"} 3' in lines)
    assert ("# TYPE hyperspace_serve_degrade_level gauge" in lines)
    assert ('hyperspace_serve_degrade_level{process_index="0"} 2'
            in lines)
    assert text.endswith("\n")


def test_render_histogram_cumulative_buckets():
    reg = Registry()
    values = [0.5, 0.5, 2.0, 40.0, 40.0, 40.0, 1e9]  # 1e9 overflows
    for v in values:
        reg.observe("serve/e2e_ms", v)
    text = render_prometheus(reg, labels={"process_index": 0})
    bucket_lines = [ln for ln in text.splitlines()
                    if ln.startswith("hyperspace_serve_e2e_ms_bucket")]
    # cumulative counts are monotone and end at the full count
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts)
    assert 'le="+Inf"} 7' in bucket_lines[-1]
    # every finite le covers exactly the values at/below it
    for ln in bucket_lines[:-1]:
        le = float(ln.split('le="')[1].split('"')[0])
        cum = float(ln.rsplit(" ", 1)[1])
        expect = sum(1 for v in values if v < le)
        # bucket edges are geometric; the le reported is an upper bound
        # so the cumulative count can never undercount values below it
        assert cum >= expect - 1  # one-bucket boundary slack
    # sum and count samples present and correct
    assert f"hyperspace_serve_e2e_ms_count{{process_index=\"0\"}} 7" in text
    sum_line = [ln for ln in text.splitlines()
                if ln.startswith("hyperspace_serve_e2e_ms_sum")][0]
    assert math.isclose(float(sum_line.rsplit(" ", 1)[1]), sum(values),
                        rel_tol=1e-9)
    assert "# TYPE hyperspace_serve_e2e_ms histogram" in text


def test_render_compresses_edges_but_keeps_lower_bounds():
    """The ~283-edge scheme compresses unchanged runs — a one-value
    histogram is a handful of lines, not hundreds — but every
    populated bucket keeps its TRUE lower-bound edge: PromQL's
    histogram_quantile interpolates linearly inside a bucket, and a
    missing lower bound would stretch the bucket down to the last
    emitted edge and wreck the quantile estimate."""
    reg = Registry()
    reg.observe("serve/e2e_ms", 3.0)
    text = render_prometheus(reg)
    bucket_lines = [ln for ln in text.splitlines() if "_bucket" in ln]
    # lower-bound edge (cum 0) + populated edge (cum 1) + +Inf
    assert len(bucket_lines) == 3
    les = [ln.split('le="')[1].split('"')[0] for ln in bucket_lines]
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert cums == [0, 1, 1]
    lo_edge, hi_edge = float(les[0]), float(les[1])
    # adjacent scheme edges: the populated bucket is ONE bucket wide,
    # and the value sits inside it — linear interpolation inside
    # [lo_edge, hi_edge] stays within the scheme's ~5% error bound
    assert hi_edge / lo_edge == pytest.approx(1.1, rel=1e-4)  # %.6g edges
    assert lo_edge < 3.0 <= hi_edge * 1.1


def test_label_injection_is_escaped():
    reg = Registry()
    reg.inc("x", 1)
    text = render_prometheus(reg, labels={"job": 'a"b\nc'})
    assert 'job="a\\"b\\nc"' in text
    assert "\nc\"" not in text.split("hyperspace_x", 1)[1].split("\n")[0]


def test_file_writer_atomic_and_cadenced(tmp_path):
    reg = Registry()
    reg.inc("serve/requests", 1)
    path = str(tmp_path / "metrics.prom")
    w = MetricsFileWriter(path, 3600.0, registry=reg)
    assert w.maybe_write() is True  # first call always lands
    assert w.maybe_write() is False  # inside the cadence: no write
    assert w.writes == 1
    text = open(path).read()
    assert "hyperspace_serve_requests" in text
    reg.inc("serve/requests", 41)
    w.write()  # forced (the run-end path)
    assert "} 42" in open(path).read()
    # no temp debris left behind
    assert os.listdir(tmp_path) == ["metrics.prom"]


def test_file_writer_rejects_bad_cadence(tmp_path):
    with pytest.raises(ValueError, match="metrics_every"):
        MetricsFileWriter(str(tmp_path / "m.prom"), 0.0)


def test_non_finite_values_render_as_format_literals():
    """One poisoned gauge (or an inf observation's histogram sum) must
    not take down every future scrape: non-finite samples render as
    the text format's NaN/+Inf/-Inf literals."""
    reg = Registry()
    reg.set_gauge("poisoned", float("nan"))
    reg.set_gauge("hot", float("inf"))
    reg.inc("cold", float("-inf"))
    reg.observe("x_ms", float("inf"))  # poisons the histogram sum
    text = render_prometheus(reg)
    assert "hyperspace_poisoned{" in text and "} NaN" in text
    assert "hyperspace_hot{" in text and "} +Inf" in text
    assert "} -Inf" in text
    assert "hyperspace_x_ms_sum" in text  # histogram still renders
