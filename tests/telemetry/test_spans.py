"""Span layer contracts (telemetry/spans.py): the disabled path is a
shared no-op, trees nest and serialize, concurrent asyncio tasks never
cross-contaminate, and ``use`` carries a span across a thread hop —
the exact propagation surfaces the serve pipeline leans on."""

import asyncio
import concurrent.futures
import time

import pytest

from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans


@pytest.fixture(autouse=True)
def _span_state():
    """Span enablement is process-global: every test starts and leaves
    disabled, whatever it does in between."""
    spans.disable()
    yield
    spans.disable()


def test_disabled_is_a_shared_noop():
    assert spans.root("x") is None
    assert not spans.active()
    # the stage fast path returns ONE shared null context (zero
    # allocation on the serving hot path) and records nothing
    a, b = spans.stage("s"), spans.stage("t", metric="serve/e2e_ms")
    assert a is b is spans._NULL
    with spans.request("query") as env:
        assert env is None
        assert spans.current() is None


def test_tree_nests_and_serializes():
    spans.enable()
    with spans.request("query", request_id="r1") as env:
        assert spans.current() is env
        with spans.stage("outer") as outer:
            assert spans.current() is outer  # stages re-scope
            with spans.stage("inner", meta={"k": 4}):
                time.sleep(0.001)
        assert spans.current() is env  # scope restored
    d = env.to_dict()
    assert d["name"] == "query" and d["request_id"] == "r1"
    (o,) = d["children"]
    assert o["name"] == "outer"
    (i,) = o["children"]
    assert i["name"] == "inner" and i["meta"] == {"k": 4}
    # offsets are relative to the TREE root and nested stages sit
    # inside their parents' extent
    assert 0 <= o["t_off_ms"] <= i["t_off_ms"]
    assert i["dur_ms"] >= 1.0  # the sleep is in there
    assert o["dur_ms"] >= i["dur_ms"]
    assert d["dur_ms"] >= o["dur_ms"]


def test_stage_observes_metric_histogram():
    spans.enable()
    reg = telem.default_registry()
    base = reg.mark()
    with spans.request("query"):
        with spans.stage("dev", metric="serve/stage/device_compute_ms"):
            time.sleep(0.001)
    h = reg.snapshot(baseline=base).get("hist/serve/stage/device_compute_ms")
    assert h and h["count"] == 1 and h["p50"] >= 1.0


def test_stage_outside_any_scope_is_noop():
    spans.enable()
    reg = telem.default_registry()
    base = reg.mark()
    with spans.stage("dev", metric="serve/stage/device_compute_ms"):
        pass  # no current span (prewarm / direct engine call): no-op
    snap = reg.snapshot(baseline=base)
    assert "hist/serve/stage/device_compute_ms" not in snap


def test_concurrent_tasks_never_cross_contaminate():
    """N interleaved coroutines on ONE event loop, each opening its own
    request envelope and stages with forced interleaving points: every
    tree must hold exactly its own stages (the contextvar contract the
    per-thread tracer cannot give)."""
    spans.enable()

    async def one(i):
        with spans.request("query", request_id=f"r{i}") as env:
            await asyncio.sleep(0.001 * (i % 3))  # interleave
            with spans.stage(f"stage_a_{i}"):
                await asyncio.sleep(0.001)
                assert spans.current().name == f"stage_a_{i}"
            with spans.stage(f"stage_b_{i}"):
                await asyncio.sleep(0.001 * ((i + 1) % 3))
        return env

    async def run():
        return await asyncio.gather(*[one(i) for i in range(16)])

    envs = asyncio.run(run())
    for i, env in enumerate(envs):
        assert env.request_id == f"r{i}"
        assert [c.name for c in env.children] == [
            f"stage_a_{i}", f"stage_b_{i}"]


def test_use_carries_span_across_thread_hop():
    """run_in_executor does NOT propagate contextvars — ``use`` is the
    explicit hand-off: a stage opened inside the worker thread lands in
    the handed span, and the submitting task's own scope is intact."""
    spans.enable()
    flush = spans.Span("flush")

    def worker():
        assert spans.current() is None  # fresh thread: no inherited scope
        with spans.use(flush):
            with spans.stage("device_compute"):
                time.sleep(0.001)
        assert spans.current() is None

    with spans.request("query") as env:
        with concurrent.futures.ThreadPoolExecutor(1) as pool:
            pool.submit(worker).result()
        assert spans.current() is env  # the hop never touched this task
    flush.close()
    assert [c.name for c in flush.children] == ["device_compute"]


def test_adopt_shares_one_child_across_parents():
    """The batching boundary: one flush span adopted into N parents —
    every tree serializes the SAME shared subtree."""
    spans.enable()
    parents = [spans.Span("query", request_id=f"r{i}") for i in range(3)]
    flush = spans.Span("flush", meta={"members": 3})
    for p in parents:
        p.adopt(flush)
    flush.add("device_compute", flush.t0, flush.t0 + 0.002)
    flush.close()
    for p in parents:
        p.close()
        (f,) = p.to_dict()["children"]
        assert f["name"] == "flush" and f["meta"] == {"members": 3}
        assert [c["name"] for c in f["children"]] == ["device_compute"]


def test_unclosed_span_serializes_with_null_duration():
    spans.enable()
    s = spans.Span("query")
    assert s.to_dict()["dur_ms"] is None  # evidence, not a crash
    s.close()
    t1 = s.t1
    s.close()
    assert s.t1 == t1  # idempotent: first close wins
