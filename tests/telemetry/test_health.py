"""Hyperbolic health monitor (telemetry/health.py + the manifolds'
``health_stats``): hand-built near-boundary ball points, off-hyperboloid
Lorentz points, product merging, nonfinite detection, thresholds/abort."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.manifolds import (
    Euclidean,
    Lorentz,
    PoincareBall,
    Product,
)
from hyperspace_tpu.telemetry.health import (
    HealthMonitor,
    health_stats,
    make_health_fn,
)


def _floats(stats):
    return {k: float(v) for k, v in jax.device_get(stats).items()}


def test_poincare_stats_on_hand_built_points():
    ball = PoincareBall(1.0)
    x = jnp.asarray([[0.3, 0.0], [0.0, 0.5]], jnp.float32)
    s = _floats(ball.health_stats(x))
    assert s["norm_max"] == pytest.approx(0.5, abs=1e-6)
    assert s["norm_mean"] == pytest.approx(0.4, abs=1e-6)
    assert s["boundary_margin_min"] == pytest.approx(0.5, abs=1e-6)


def test_poincare_clamped_point_flags_below_default_eps():
    # an artificially boundary-clamped embedding (what proj does to a
    # diverging row) must read as margin ≈ ball_eps(f32) = 4e-3 < the
    # monitor's default 1e-2 — the acceptance-criterion scenario
    ball = PoincareBall(1.0)
    x = ball.proj(jnp.asarray([[0.9999, 0.0], [0.1, 0.2]], jnp.float32))
    s = _floats(health_stats(x, ball))
    assert s["boundary_margin_min"] < 1e-2
    mon = HealthMonitor(make_health_fn(ball))
    mon.check(x, step=0)
    assert mon.warnings == 1


def test_poincare_curvature_scales_radius():
    # c=4 halves the ball radius: ‖x‖=0.4 is √c‖x‖=0.8 of the way out
    ball = PoincareBall(4.0)
    s = _floats(ball.health_stats(jnp.asarray([[0.4, 0.0]], jnp.float32)))
    assert s["norm_max"] == pytest.approx(0.8, abs=1e-5)


def test_lorentz_stats_on_and_off_hyperboloid():
    L = Lorentz(1.0)
    on = L.proj(jnp.asarray([[0.0, 0.3, -0.2], [0.0, 1.5, 2.0]],
                            jnp.float32))
    s_on = _floats(L.health_stats(on))
    assert s_on["violation_max"] < 1e-5
    assert s_on["time_coord_max"] >= 1.0  # cosh ≥ 1 on the sheet
    off = on.at[0, 0].add(0.5)  # perturb the time coordinate
    s_off = _floats(L.health_stats(off))
    assert s_off["violation_max"] > 1e-2


def test_product_merges_factors_with_aggregates():
    ball = PoincareBall(1.0)
    P = Product([ball, Euclidean()], [2, 3])
    x = jnp.concatenate(
        [ball.proj(jnp.asarray([[0.999, 0.0]], jnp.float32)),
         jnp.ones((1, 3), jnp.float32)], axis=-1)
    s = _floats(P.health_stats(x))
    assert "f0_poincare/boundary_margin_min" in s
    assert "f1_euclidean/violation_max" in s
    # unprefixed worst-case aggregate drives the monitor's thresholds
    assert s["boundary_margin_min"] == pytest.approx(
        s["f0_poincare/boundary_margin_min"])


def test_nonfinite_counts_across_tree_and_warns():
    params = {"w": jnp.asarray([1.0, jnp.nan]),
              "b": jnp.asarray([jnp.inf]),
              "step": jnp.asarray(3, jnp.int32)}  # ints don't count
    s = _floats(health_stats(params))
    assert s["nonfinite"] == 2
    mon = HealthMonitor(make_health_fn(), abort=False)
    mon.check(params, step=1)
    assert mon.warnings == 1


def test_grads_tree_adds_named_global_norm():
    s = _floats(health_stats(
        {"w": jnp.ones((2, 2))}, grads={"w": 3.0 * jnp.ones((4,))},
        grads_name="grad_ema_norm"))
    assert s["grad_ema_norm"] == pytest.approx(6.0)


def test_tag_tree_merges_manifold_leaves():
    ball = PoincareBall(1.0)
    params = {"emb": ball.proj(jnp.asarray([[0.999, 0.0]], jnp.float32)),
              "dense": jnp.ones((2, 2))}
    s = _floats(health_stats(params, {"emb": ball, "dense": None}))
    assert s["boundary_margin_min"] < 1e-2
    assert s["nonfinite"] == 0


def test_monitor_logs_health_record_and_abort(tmp_path):
    from hyperspace_tpu.train.logging import MetricsLogger, read_jsonl

    ball = PoincareBall(1.0)
    bad = ball.proj(jnp.asarray([[0.99999, 0.0]], jnp.float32))
    path = str(tmp_path / "h.jsonl")
    with MetricsLogger(path) as log:
        mon = HealthMonitor(make_health_fn(ball))
        mon.check(bad, step=8, log=log)
    (rec,) = read_jsonl(path)
    assert rec["step"] == 8
    assert rec["health/ok"] is False
    assert rec["health/boundary_margin_min"] < 1e-2
    with pytest.raises(FloatingPointError):
        HealthMonitor(make_health_fn(ball), abort=True).check(bad, step=9)


def test_healthy_state_stays_quiet():
    ball = PoincareBall(1.0)
    ok = jnp.asarray(np.full((16, 4), 0.05, np.float32))
    mon = HealthMonitor(make_health_fn(ball))
    vals = mon.check(ok, step=0)
    assert mon.warnings == 0
    assert vals["boundary_margin_min"] > 0.5
