"""Counter registry (telemetry/registry.py): increments, gauges,
snapshot/reset, thread-safety, the jax.monitoring recompile hook."""

import threading

import pytest

from hyperspace_tpu.telemetry.registry import (
    Registry,
    default_registry,
    install_jax_monitoring_hook,
)


@pytest.fixture()
def reg():
    return Registry()


def test_inc_get_and_float_accumulation(reg):
    reg.inc("a")
    reg.inc("a", 2)
    reg.inc("secs", 0.25)
    reg.inc("secs", 0.5)
    assert reg.get("a") == 3
    assert reg.get("secs") == pytest.approx(0.75)
    assert reg.get("never") == 0


def test_snapshot_prefix_and_gauges(reg):
    reg.inc("hits", 4)
    reg.set_gauge("depth", 2)
    reg.set_gauge("depth", 1)  # last write wins
    snap = reg.snapshot("ctr/")
    assert snap == {"ctr/hits": 4, "ctr/depth": 1}
    # snapshot is a copy — mutating it never leaks back
    snap["ctr/hits"] = 999
    assert reg.get("hits") == 4


def test_mark_baseline_deltas_counters_and_excludes_stale_gauges(reg):
    # the per-run baseline contract run_loop relies on in library use:
    # counters report as deltas, and a gauge set BEFORE the mark (a
    # previous run's level, e.g. its ckpt/bytes) is excluded entirely
    reg.inc("a", 5)
    reg.set_gauge("stale", 7)
    base = reg.mark()
    reg.inc("a", 2)
    reg.set_gauge("fresh", 1)
    snap = reg.snapshot("ctr/", baseline=base)
    assert snap["ctr/a"] == 2
    assert "ctr/stale" not in snap
    assert snap["ctr/fresh"] == 1


def test_reset_drops_everything(reg):
    reg.inc("x")
    reg.set_gauge("g", 7)
    reg.reset()
    assert reg.snapshot() == {}


def test_concurrent_increments_do_not_lose_counts(reg):
    n, per = 8, 500

    def work():
        for _ in range(per):
            reg.inc("shared")

    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get("shared") == n * per


def test_default_registry_is_process_wide():
    assert default_registry() is default_registry()


def test_jax_monitoring_hook_counts_backend_compiles():
    import jax
    import jax.numpy as jnp

    install_jax_monitoring_hook()
    reg = default_registry()
    before = reg.get("jax/recompiles")

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.arange(7))  # fresh shape+program → one backend compile
    assert reg.get("jax/recompiles") >= before + 1
    assert reg.get("jax/compile_s") > 0
    # cached second call must NOT count
    mid = reg.get("jax/recompiles")
    f(jnp.arange(7))
    assert reg.get("jax/recompiles") == mid
