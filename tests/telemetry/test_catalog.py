"""The telemetry counter catalog lint, run inside the suite: every
counter incremented in code must be documented in docs/observability.md
(scripts/check_telemetry_catalog.py is the one implementation — this
test just fails the build when it fails)."""

import importlib.util
import os


def _load_checker():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "check_telemetry_catalog.py")
    spec = importlib.util.spec_from_file_location("check_telemetry_catalog",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_counter_in_code_is_documented(capsys):
    mod = _load_checker()
    rc = mod.main()
    out = capsys.readouterr().out
    assert rc == 0, f"undocumented telemetry counters:\n{out}"


def test_checker_finds_the_known_counters():
    # the scanner itself must keep working: it should at minimum see the
    # core counters the loop/cache/prefetcher increment and (PR 7) the
    # histogram observes on the serve/train/checkpoint paths
    mod = _load_checker()
    pkg = os.path.join(mod.repo_root(), "hyperspace_tpu")
    found = mod.counters_in_code(pkg)
    for name in ("prep_cache/hit", "prefetch/stalls", "train/dispatches",
                 "ckpt/saves", "jax/recompiles", "health/warnings",
                 "serve/e2e_ms", "serve/queue_wait_ms",
                 "train/dispatch_ms", "ckpt/save_ms"):
        assert name in found, (name, sorted(found))
