"""Neighbor-sampled minibatch HGCN (models/hgcn_sampled.py).

Four claims: (1) the index pyramid and adjacency are built right,
(2) the sampled layer is exact where sampling is deterministic
(degree <= 1), (3) parameters are tree-compatible with the full-graph
model, and (4) sampled training actually trains — evaluated by the
FULL-GRAPH model on the sampled-trained parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.models import hgcn
from hyperspace_tpu.models import hgcn_sampled as HS


def _cfg(**kw):
    base = dict(feat_dim=8, hidden_dims=(12, 6), num_classes=3, lr=5e-3)
    base.update(kw.pop("base_kw", {}))
    kw.setdefault("fanouts", (4, 4))
    kw.setdefault("batch_size", 16)
    return HS.SampledConfig(base=hgcn.HGCNConfig(**base), **kw)


def test_config_validation():
    with pytest.raises(ValueError, match="one fanout per conv"):
        _cfg(fanouts=(4,))
    with pytest.raises(ValueError, match="mean-aggregation only"):
        _cfg(base_kw=dict(use_att=True))


def test_adjacency_and_plan_shapes():
    edges = np.asarray([[0, 1], [1, 2], [2, 2], [3, 0]])  # one self-loop
    indptr, indices = HS.build_adjacency(edges, 5)
    assert indptr.shape == (6,)
    # self-loop dropped; node 4 isolated
    assert indptr[5] == indptr[4] == 6  # 3 undirected edges doubled
    deg = indptr[1:] - indptr[:-1]
    assert deg.tolist() == [2, 2, 1, 1, 0]

    cfg = _cfg(fanouts=(3, 2), batch_size=8)
    labels = np.arange(5) % 3
    mask = np.ones(5, bool)
    batches, dega = HS.plan_batches(cfg, edges, labels, mask, 5, steps=4,
                                    seed=1)
    assert batches.ids[0].shape == (4, 8)
    assert batches.ids[1].shape == (4, 8, 3)
    assert batches.ids[2].shape == (4, 8, 3, 2)
    assert batches.labels.shape == (4, 8)
    np.testing.assert_array_equal(np.asarray(dega), deg.astype(np.float32))
    # isolated node samples itself at every level
    lvl1 = np.asarray(batches.ids[1])
    seeds = np.asarray(batches.ids[0])
    assert np.all(lvl1[seeds == 4] == 4)


def test_param_tree_matches_full_graph_model():
    cfg = _cfg()
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=64, feat_dim=8, num_classes=3, seed=0)
    tr, va, te = G.node_split_masks(64, seed=0)
    g = G.prepare(edges, 64, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    _, _, st_s = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    _, _, st_f = hgcn.init_nc(cfg.base, g, seed=0)
    shp = lambda t: jax.tree_util.tree_map(lambda a: a.shape, t)
    assert shp(st_s.params) == shp(st_f.params)


def test_sampled_layer_exact_on_degree_one_graph():
    """Every node has exactly one neighbor -> the unbiased estimator is
    deterministic and must equal the full-graph conv output exactly:
    (h_self + (1/f)*f*h_nbr)/2 == (h_self + h_nbr)/2."""
    n = 16
    edges = np.stack([np.arange(0, n, 2), np.arange(1, n, 2)], axis=1)
    x = np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32)
    labels = np.arange(n) % 3
    tr = np.ones(n, bool)
    cfg = _cfg(fanouts=(3, 3), batch_size=n)
    g = G.prepare(edges, n, x, labels=labels, num_classes=3,
                  train_mask=tr, val_mask=tr, test_mask=tr)
    model_s, _, state = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    model_f = hgcn.HGCNNodeClf(cfg.base)

    full_logits = model_f.apply({"params": state.params}, G.to_device(g))

    batches, deg = HS.plan_batches(cfg, edges, labels, tr, n, steps=1, seed=0)
    ids = [a[0] for a in batches.ids]
    levels = [jnp.asarray(x)[a] for a in ids]
    n_nbrs = [deg[a] for a in ids[:-1]]
    samp_logits = model_s.apply({"params": state.params}, levels, n_nbrs)

    seeds = np.asarray(ids[0])
    np.testing.assert_allclose(np.asarray(samp_logits),
                               np.asarray(full_logits)[seeds],
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_sampled_training_improves_full_graph_eval():
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=512, feat_dim=16, num_classes=5, seed=0)
    tr, va, te = G.node_split_masks(512, seed=0)
    cfg = HS.SampledConfig(
        base=hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 16),
                             num_classes=5, lr=5e-3),
        fanouts=(5, 5), batch_size=64)
    g = G.prepare(edges, 512, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    model, opt, state = HS.init_sampled_nc(cfg, feat_dim=16, seed=0)
    full_model = hgcn.HGCNNodeClf(cfg.base)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 512, steps=40,
                                   seed=0)
    xt = jnp.asarray(x)
    acc0 = hgcn.evaluate_nc(full_model, state.params, g)["val_acc"]
    for _ in range(120):
        state, loss = HS.train_step_sampled_nc(model, opt, state, xt, deg,
                                               batches)
    acc1 = hgcn.evaluate_nc(full_model, state.params, g)["val_acc"]
    assert np.isfinite(float(loss))
    assert acc1 > max(0.8, acc0 + 0.3), (acc0, acc1)


def test_learned_curvature_trains_through_sampled_step():
    cfg = _cfg(base_kw=dict(learn_c=True))
    edges, x, labels, _ = G.synthetic_hierarchy(
        num_nodes=64, feat_dim=8, num_classes=3, seed=1)
    tr = np.ones(64, bool)
    model, opt, state = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 64, steps=3,
                                   seed=0)
    c0 = float(state.params["encoder"]["conv0"]["c_raw"])
    for _ in range(6):
        state, loss = HS.train_step_sampled_nc(
            model, opt, state, jnp.asarray(x), deg, batches)
    assert np.isfinite(float(loss))
    assert float(state.params["encoder"]["conv0"]["c_raw"]) != c0


def test_epoch_scan_matches_stepwise_sampled():
    """Scanned plan consumption == step%S consumption from step 0."""
    cfg = _cfg()
    edges, x, labels, _ = G.synthetic_hierarchy(
        num_nodes=64, feat_dim=8, num_classes=3, seed=2)
    tr = np.ones(64, bool)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 64, steps=3,
                                   seed=0)
    xt = jnp.asarray(x)
    model, opt, s1 = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    _, _, s2 = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    for _ in range(3):
        s1, _ = HS.train_step_sampled_nc(model, opt, s1, xt, deg, batches)
    s2, losses = HS.train_epoch_sampled_nc(model, opt, s2, xt, deg, batches)
    # two separately compiled XLA programs: tolerance, not bitwise
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=2e-5),
        s1.params, s2.params)
    assert losses.shape == (3,)


def test_sharded_sampled_step_matches_single_device():
    """DP over the batch axis: same trajectory as the single-device step
    to float tolerance (the gradient all-reduce is the only difference)."""
    from hyperspace_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 8})
    cfg = _cfg(batch_size=16, base_kw=dict(dropout=0.0))
    edges, x, labels, _ = G.synthetic_hierarchy(
        num_nodes=64, feat_dim=8, num_classes=3, seed=3)
    tr = np.ones(64, bool)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 64, steps=4,
                                   seed=0)
    xt = jnp.asarray(x)
    model, opt, s1 = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    _, _, s2 = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    for _ in range(4):
        s1, loss1 = HS.train_step_sampled_nc(model, opt, s1, xt, deg,
                                             batches)
    step, s2, data = HS.make_sharded_step(model, opt, mesh, s2, xt, deg,
                                          batches)
    for _ in range(4):
        s2, loss2 = step(s2, *data)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=2e-5),
        s1.params, jax.device_get(s2.params))


# flaky: the `auc1 > auc0 + 0.03` improvement assertion is a stochastic
# threshold — the early AUC trajectory differs across platform/blas
# combinations (this image's CPU jax sat in a dip at the original 120
# steps: delta −0.006 at 120, +0.064 by 360 — deterministic per
# platform, flaky across them; red since PR 3, CHANGES.md).  Trained to
# 360 steps the margin is comfortable everywhere measured; the strict
# single rerun (tests/conftest.py) absorbs a platform landing near the
# threshold.  A REAL training regression fails both attempts.
@pytest.mark.flaky
def test_sampled_lp_tree_and_training():
    """LP pyramids: param tree matches hgcn.init_lp (encoder + decoder),
    training improves the full-graph-evaluated val AUC, and the scanned
    epoch reproduces the stepwise trajectory."""
    n = 256
    edges, x, labels, _ = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=12, seed=4)
    split = G.split_edges(edges, n, x, seed=0, pad_multiple=128)
    cfg = HS.SampledConfig(
        base=hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8), lr=3e-3),
        fanouts=(4, 4), batch_size=64)
    model, opt, state = HS.init_sampled_lp(cfg, feat_dim=12, seed=0)
    fm, _, fs = hgcn.init_lp(cfg.base, split.graph, seed=0)
    shp = lambda t: jax.tree_util.tree_map(lambda a: a.shape, t)
    assert shp(state.params) == shp(fs.params)

    batches, deg = HS.plan_lp_batches(cfg, split.train_pos, n,
                                      steps=16, seed=0)
    xt = jnp.asarray(x)
    auc0 = hgcn.evaluate_lp(fm, state.params, split, "val")["roc_auc"]
    for _ in range(360):
        state, loss = HS.train_step_sampled_lp(model, opt, state, xt, deg,
                                               batches)
    auc1 = hgcn.evaluate_lp(fm, state.params, split, "val")["roc_auc"]
    assert np.isfinite(float(loss))
    assert auc1 > auc0 + 0.03, (auc0, auc1)

    _, _, s1 = HS.init_sampled_lp(cfg, feat_dim=12, seed=1)
    _, _, s2 = HS.init_sampled_lp(cfg, feat_dim=12, seed=1)
    b3, deg3 = HS.plan_lp_batches(cfg, split.train_pos, n, steps=3,
                                  seed=2)
    for _ in range(3):
        s1, _ = HS.train_step_sampled_lp(model, opt, s1, xt, deg3, b3)
    s2, losses = HS.train_epoch_sampled_lp(model, opt, s2, xt, deg3, b3)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=2e-5),
        s1.params, s2.params)
    assert losses.shape == (3,)


def test_three_layer_pyramid_trains():
    """The pyramid generalizes past the 2-layer default: 3 convs, 3
    fanout levels ([B], [B,3], [B,3,3], [B,3,3,2]) — tree still matches
    the full-graph model and the step trains."""
    cfg = _cfg(base_kw=dict(hidden_dims=(12, 8, 6)), fanouts=(3, 3, 2))
    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=64, feat_dim=8, num_classes=3, seed=5)
    tr, va, te = G.node_split_masks(64, seed=0)
    g = G.prepare(edges, 64, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te)
    model, opt, state = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    _, _, st_f = hgcn.init_nc(cfg.base, g, seed=0)
    shp = lambda t: jax.tree_util.tree_map(lambda a: a.shape, t)
    assert shp(state.params) == shp(st_f.params)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 64, steps=2,
                                   seed=0)
    assert batches.ids[3].shape == (2, 16, 3, 3, 2)
    for _ in range(4):
        state, loss = HS.train_step_sampled_nc(
            model, opt, state, jnp.asarray(x), deg, batches)
    assert np.isfinite(float(loss))


def test_sharded_sampled_lp_step_matches_single_device():
    """LP DP over the (4P) endpoint axis: same trajectory as the
    single-device LP step to float tolerance."""
    from hyperspace_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 8})
    n = 64
    edges, x, labels, _ = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=8, num_classes=3, seed=6)
    split = G.split_edges(edges, n, x, seed=0, pad_multiple=64)
    cfg = _cfg(batch_size=16, base_kw=dict(dropout=0.0, num_classes=0))
    batches, deg = HS.plan_lp_batches(cfg, split.train_pos, n, steps=4,
                                      seed=0)
    xt = jnp.asarray(x)
    model, opt, s1 = HS.init_sampled_lp(cfg, feat_dim=8, seed=0)
    _, _, s2 = HS.init_sampled_lp(cfg, feat_dim=8, seed=0)
    for _ in range(4):
        s1, loss1 = HS.train_step_sampled_lp(model, opt, s1, xt, deg,
                                             batches)
    step, s2, data = HS.make_sharded_lp_step(model, opt, mesh, s2, xt, deg,
                                             batches)
    for _ in range(4):
        s2, loss2 = step(s2, *data)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=2e-5),
        s1.params, jax.device_get(s2.params))


# --- overlapped sampling pipeline (VERDICT r3 #5) -----------------------------


def _stream_setup(n=200, seed=0):
    edges, x, labels, k = G.synthetic_hierarchy(
        num_nodes=n, feat_dim=8, num_classes=4, seed=seed)
    tr, va, te = G.node_split_masks(n, seed=seed)
    cfg = HS.SampledConfig(
        base=hgcn.HGCNConfig(feat_dim=8, hidden_dims=(16, 8), num_classes=4,
                             lr=3e-3),
        fanouts=(4, 4), batch_size=32)
    return edges, x, labels, tr, cfg


def test_stream_yields_fresh_deterministic_chunks():
    edges, x, labels, tr, cfg = _stream_setup()
    with HS.SampledBatchStream(cfg, "nc", num_nodes=200, edges=edges,
                               labels=labels, train_mask=tr,
                               chunk_steps=4, seed=7) as s1:
        a1, a2 = s1.next(), s1.next()
    with HS.SampledBatchStream(cfg, "nc", num_nodes=200, edges=edges,
                               labels=labels, train_mask=tr,
                               chunk_steps=4, seed=7) as s2:
        b1 = s2.next()
    # no recycling: consecutive chunks draw different seed batches
    assert not np.array_equal(np.asarray(a1.ids[0]), np.asarray(a2.ids[0]))
    # deterministic: same stream seed -> same chunk sequence
    for l1, l2 in zip(a1.ids, b1.ids):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    # shapes match the one-shot planner's
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, 200, steps=4,
                                   seed=7)
    for l1, l2 in zip(a1.ids, batches.ids):
        assert l1.shape == l2.shape


def test_stream_start_chunk_continues_sequence():
    """A resumed stream (start_chunk=k) yields exactly the chunks a
    fresh stream yields from position k on — no replay (ADVICE r04)."""
    edges, x, labels, tr, cfg = _stream_setup()
    kw = dict(num_nodes=200, edges=edges, labels=labels, train_mask=tr,
              chunk_steps=4, seed=7)
    with HS.SampledBatchStream(cfg, "nc", **kw) as fresh:
        _, c1, c2 = fresh.next(), fresh.next(), fresh.next()
    with HS.SampledBatchStream(cfg, "nc", start_chunk=1, **kw) as resumed:
        r1, r2 = resumed.next(), resumed.next()
    for a, b in zip(c1.ids + c2.ids, r1.ids + r2.ids):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_chunk_derivation(tmp_path):
    """CLI resume offset: latest checkpoint step // chunk_steps, without
    opening a checkpoint manager."""
    from hyperspace_tpu.cli.train import RunConfig, _resume_chunk
    from hyperspace_tpu.train.checkpoint import peek_latest_step

    def commit(p):  # a committed step dir is non-empty (orbax layout)
        p.mkdir(parents=True)
        (p / "_CHECKPOINT_METADATA").write_text("{}")

    d = tmp_path / "ck"
    assert peek_latest_step(str(d)) == 0           # nothing there yet
    commit(d / "64")
    commit(d / "128")
    (d / "128.orbax-checkpoint-tmp-x").mkdir()     # in-flight: ignored
    assert peek_latest_step(str(d)) == 128
    (d / "192").mkdir()    # interrupted save: empty dir = uncommitted,
    assert peek_latest_step(str(d)) == 128  # fall back to the committed one
    run = RunConfig(steps=256, ckpt_dir=str(d), resume=True)
    assert _resume_chunk(run, 64) == 2      # exact boundary: continue
    assert _resume_chunk(run, 100) == 2     # mid-chunk: skip the partial
    assert _resume_chunk(RunConfig(steps=256), 64) == 0


def test_stream_trains_nc_across_chunks():
    edges, x, labels, tr, cfg = _stream_setup()
    model, opt, state = HS.init_sampled_nc(cfg, feat_dim=8, seed=0)
    xt = jnp.asarray(np.asarray(x, np.float32))
    with HS.SampledBatchStream(cfg, "nc", num_nodes=200, edges=edges,
                               labels=labels, train_mask=tr,
                               chunk_steps=4, seed=0) as stream:
        losses = []
        for _ in range(3):                  # 3 fresh chunks, no recycling
            b = stream.next()
            for _ in range(4):
                state, loss = HS.train_step_sampled_nc(
                    model, opt, state, xt, stream.deg, b)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert int(state.step) == 12


def test_stream_lp_mode():
    edges, x, labels, tr, cfg = _stream_setup()
    split = G.split_edges(edges, 200, x, seed=0, pad_multiple=128)
    model, opt, state = HS.init_sampled_lp(cfg, feat_dim=8, seed=0)
    xt = jnp.asarray(np.asarray(x, np.float32))
    with HS.SampledBatchStream(cfg, "lp", num_nodes=200,
                               train_pos=split.train_pos,
                               chunk_steps=3, seed=0) as stream:
        b1 = stream.next()
        b2 = stream.next()
        assert b1.labels is None
        assert not np.array_equal(np.asarray(b1.ids[0]),
                                  np.asarray(b2.ids[0]))
        for _ in range(3):
            state, loss = HS.train_step_sampled_lp(
                model, opt, state, xt, stream.deg, b1)
        assert np.isfinite(float(loss))
