"""Sampled hard-negative mining (ISSUE 10: the fused scan-top-k wired
into the training-side negative path).  ``neg_mode="mined"`` keeps each
row's K nearest pool candidates; the default stays uniform and
untouched."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.models import poincare_embed as pe


def _cfg(ds, **kw):
    return pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=8,
                                  batch_size=32, neg_samples=5,
                                  burnin_steps=0, **kw)


def test_mined_negatives_are_the_nearest_pool_members(rng):
    """_mine_negatives == numpy argsort of ball distances over the pool
    (ties none at random init scales)."""
    ds = synthetic_tree(depth=4, branching=3)
    cfg = _cfg(ds, neg_mode="mined", mine_pool=64)
    table = jnp.asarray(
        np.asarray(PoincareBall(1.0).expmap0(jnp.asarray(
            rng.standard_normal((ds.num_nodes, 8)) * 0.3, jnp.float32))))
    u_idx = jnp.asarray(rng.integers(0, ds.num_nodes, 16), jnp.int32)
    key = jax.random.PRNGKey(7)
    neg = np.asarray(pe._mine_negatives(cfg, table, u_idx, key))
    assert neg.shape == (16, cfg.neg_samples)
    pool = np.asarray(jax.random.randint(key, (64,), 0, cfg.num_nodes))
    ball = PoincareBall(1.0)
    d = np.asarray(ball.dist(jnp.asarray(table)[u_idx][:, None, :],
                             jnp.asarray(table)[jnp.asarray(pool)][None]))
    want = pool[np.argsort(d, axis=1, kind="stable")[:, :cfg.neg_samples]]
    assert np.array_equal(neg, want)


def test_mined_step_trains_and_is_jittable(rng):
    ds = synthetic_tree(depth=4, branching=3)
    cfg = _cfg(ds, neg_mode="mined")
    state, opt = pe.init_state(cfg, seed=0)
    step = pe.make_train_step(cfg)
    pairs = jnp.asarray(ds.pairs)
    l0 = None
    for _ in range(10):
        state, loss = step(cfg, opt, state, pairs)
        l0 = l0 if l0 is not None else float(loss)
    assert np.isfinite(float(loss))
    assert int(state.step) == 10
    # and the epoch-scan path shares the same body
    state2, losses = pe.train_epoch_scan(cfg, opt, state, pairs, 3)
    assert np.all(np.isfinite(np.asarray(losses)))


def test_default_uniform_path_is_unchanged(rng):
    """neg_mode's default draws the identical PRNG stream as the
    pre-mining build: one explicit-uniform step == one default step,
    bitwise on the table."""
    ds = synthetic_tree(depth=3, branching=3)
    a, b = _cfg(ds), _cfg(ds, neg_mode="uniform")
    pairs = jnp.asarray(ds.pairs)
    sa, opt = pe.init_state(a, seed=0)
    sb, _ = pe.init_state(b, seed=0)
    sa, la = pe.train_step(a, opt, sa, pairs)
    sb, lb = pe.train_step(b, opt, sb, pairs)
    assert np.array_equal(np.asarray(sa.table).view(np.uint32),
                          np.asarray(sb.table).view(np.uint32))


def test_mined_mode_validation():
    ds = synthetic_tree(depth=3, branching=2)
    with pytest.raises(ValueError, match="dense"):
        pe.make_train_step(_cfg(ds, neg_mode="mined", sparse=True))
    with pytest.raises(ValueError, match="neg_mode"):
        pe.make_train_step(_cfg(ds, neg_mode="hardest"))
    with pytest.raises(ValueError, match="mine_pool"):
        pe.make_train_step(_cfg(ds, neg_mode="mined", mine_pool=2))
    # the fused kernel's caps fail at CONFIG time, not mid-training
    with pytest.raises(ValueError, match="caps neg_samples"):
        big = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=8,
                                     batch_size=8, neg_samples=300,
                                     mine_pool=1200, neg_mode="mined")
        pe.make_train_step(big)
    with pytest.raises(ValueError, match="dim"):
        wide = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=2000,
                                      batch_size=8, neg_samples=5,
                                      neg_mode="mined")
        pe.make_train_step(wide)
    with pytest.raises(ValueError, match="dense"):
        pe.plan_sparse_steps(_cfg(ds, neg_mode="mined"),
                             np.zeros((4, 2), np.int64), 2)