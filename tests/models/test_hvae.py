"""Workload-4 integration tests: HVAE ELBO improves; IWAE ≥ ELBO; both
latent geometries train (SURVEY.md §4.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import mnist as M
from hyperspace_tpu.models import hvae


def test_synthetic_mnist_shapes():
    ds = M.synthetic_mnist(num_samples=32, size=28)
    assert ds.images.shape == (32, 28, 28)
    assert ds.images.min() >= 0.0 and ds.images.max() <= 1.0
    tr, te = ds.split(0.75)
    assert len(tr.labels) == 24


def test_idx_roundtrip(tmp_path):
    import struct

    imgs = (np.arange(2 * 4 * 4) % 256).astype(np.uint8).reshape(2, 4, 4)
    p = tmp_path / "train-images-idx3-ubyte"
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 2, 4, 4))
        f.write(imgs.tobytes())
    labs = np.asarray([3, 7], np.uint8)
    q = tmp_path / "train-labels-idx1-ubyte"
    with open(q, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 2))
        f.write(labs.tobytes())
    ds = M.load_idx_dir(str(tmp_path))
    np.testing.assert_allclose(ds.images, imgs / 255.0)
    assert list(ds.labels) == [3, 7]


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["poincare", "lorentz"])
def test_hvae_forward_and_latents_on_manifold(kind):
    cfg = hvae.HVAEConfig(image_size=16, latent_dim=3, hidden=32,
                          conv_features=(8,), kind=kind)
    model, opt, state = hvae.init_model(cfg, seed=0)
    x = jnp.asarray(M.synthetic_mnist(num_samples=4, size=16).images)
    q, z, logits = model.apply({"params": state.params}, x, jax.random.PRNGKey(1))
    m = q.manifold
    assert float(jnp.max(m.check_point(z))) < 1e-5
    assert logits.shape == (4, 16, 16)
    lp = q.log_prob(z)
    assert bool(jnp.isfinite(lp).all())


@pytest.mark.slow
def test_hvae_elbo_improves():
    ds = M.synthetic_mnist(num_samples=512, size=16, seed=0)
    cfg = hvae.HVAEConfig(image_size=16, latent_dim=2, hidden=64,
                          conv_features=(8, 16), lr=2e-3, batch_size=64)
    model, opt, state = hvae.init_model(cfg, seed=0)
    x = jnp.asarray(ds.images)
    # loss at init vs after training
    _, loss0, _, _ = hvae.train_step(model, opt, state, x[:64])
    model, state, metrics = hvae.train(cfg, ds.images, steps=150, seed=0)
    assert np.isfinite(metrics["loss"])
    assert metrics["loss"] < float(loss0) - 5.0, (metrics, float(loss0))
    assert metrics["kl"] > 0.0  # posterior differs from prior


@pytest.mark.slow
def test_hvae_iwae_at_least_elbo():
    ds = M.synthetic_mnist(num_samples=128, size=16, seed=1)
    cfg = hvae.HVAEConfig(image_size=16, latent_dim=2, hidden=32,
                          conv_features=(8,), lr=2e-3, batch_size=64)
    model, state, _ = hvae.train(cfg, ds.images, steps=50, seed=0)
    x = jnp.asarray(ds.images[:32])
    key = jax.random.PRNGKey(7)
    prior = model.prior()
    out = model.apply({"params": state.params}, x, key)
    recon, kl = hvae.elbo_terms(out, prior, x)
    elbo = float(jnp.mean(recon - kl))
    iwae = float(hvae.iwae_bound(model, state.params, x, key, k=8))
    assert iwae >= elbo - 1.0  # IWAE ≥ ELBO up to MC noise
