"""Poincaré embeddings with Riemannian Adam + sparse-row updates
(VERDICT r1 #3/#8).

- radam trains the workload end to end through the single jitted step
  (BASELINE north star: "Riemannian SGD/Adam ... single XLA-compiled
  train step" — Adam half).
- The sparse-row step is mathematically identical to the dense step for
  rsgd (untouched rows: expmap(x, 0) = x), checked to float tolerance.
- The sparse radam step converges (lazy-moment semantics differ from the
  dense step by design, so equivalence is convergence, not equality).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.models import poincare_embed as pe


def _train(cfg, steps, seed=0):
    state, opt = pe.init_state(cfg, seed)
    ds_pairs = _DS.pairs
    pairs = jnp.asarray(ds_pairs)
    step_fn = pe.make_train_step(cfg)
    for _ in range(steps):
        state, loss = step_fn(cfg, opt, state, pairs)
    return state, float(loss)


_DS = synthetic_tree(depth=3, branching=3)


def _cfg(**kw):
    base = dict(num_nodes=_DS.num_nodes, dim=5, lr=0.5, neg_samples=10,
                batch_size=128, burnin_steps=50)
    base.update(kw)
    return pe.PoincareEmbedConfig(**base)


@pytest.mark.slow
def test_radam_dense_converges():
    cfg = _cfg(optimizer="radam", lr=0.05)
    state, loss = _train(cfg, 1500)
    res = pe.evaluate(state.table, _DS.pairs, cfg.c)
    assert np.isfinite(loss)
    assert res["map"] >= 0.85, res
    # still on the ball
    r = np.linalg.norm(np.asarray(state.table), axis=-1).max()
    assert r < 1.0


@pytest.mark.slow
def test_radam_sparse_converges():
    cfg = _cfg(optimizer="radam", lr=0.05, sparse=True)
    state, loss = _train(cfg, 1500)
    res = pe.evaluate(state.table, _DS.pairs, cfg.c)
    assert np.isfinite(loss)
    assert res["map"] >= 0.85, res


@pytest.mark.slow
def test_sparse_rsgd_matches_dense():
    """Same seed, same PRNG stream → identical batches; sparse and dense
    rsgd must produce the same table to float tolerance."""
    cfg_d = _cfg()
    cfg_s = _cfg(sparse=True)
    sd, _ = _train(cfg_d, 60)
    ss, _ = _train(cfg_s, 60)
    np.testing.assert_allclose(
        np.asarray(ss.table), np.asarray(sd.table), rtol=1e-5, atol=1e-7)


def test_sparse_handles_duplicate_rows_in_batch():
    """A batch where u appears many times accumulates tangents per unique
    row; result stays finite and on-manifold."""
    cfg = _cfg(sparse=True, batch_size=64)
    state, opt = pe.init_state(cfg, 0)
    # pairs all sharing one ancestor → heavy duplication in every batch
    pairs = jnp.asarray(
        np.stack([np.zeros(200, np.int64),
                  np.arange(1, 201) % _DS.num_nodes], 1))
    step_fn = pe.make_train_step(cfg)
    for _ in range(30):
        state, loss = step_fn(cfg, opt, state, pairs)
    t = np.asarray(state.table)
    assert np.isfinite(t).all()
    assert np.linalg.norm(t, axis=-1).max() < 1.0
    assert np.isfinite(float(loss))


def test_epoch_scan_matches_stepwise_dense():
    """train_epoch_scan is the same computation as N train_step calls —
    same body, same PRNG stream, so the trajectories agree bitwise."""
    cfg = _cfg()
    pairs = jnp.asarray(_DS.pairs)
    s1, opt = pe.init_state(cfg, 3)
    s2, _ = pe.init_state(cfg, 3)
    for _ in range(4):
        s1, _ = pe.train_step(cfg, opt, s1, pairs)
    s2, losses = pe.train_epoch_scan(cfg, opt, s2, pairs, 4)
    np.testing.assert_array_equal(np.asarray(s1.table), np.asarray(s2.table))
    assert losses.shape == (4,)
    assert int(s2.step) == 4


def test_epoch_scan_matches_stepwise_planned_packed():
    """Scanned plan consumption == step%S consumption from step 0 (radam
    moments ride along in the packed rows)."""
    cfg = _cfg(optimizer="radam", lr=0.05, burnin_steps=0)
    plan = pe.plan_sparse_steps(cfg, _DS.pairs, 4, seed=2)
    st1, opt = pe.init_state(cfg, 5)
    st2, _ = pe.init_state(cfg, 5)
    p1, p2 = pe.pack_state(cfg, st1), pe.pack_state(cfg, st2)
    for _ in range(4):
        p1, _ = pe.train_step_planned_packed(cfg, opt, p1, plan)
    p2, losses = pe.train_epoch_planned_packed(cfg, opt, p2, plan)
    np.testing.assert_array_equal(np.asarray(p1.packed), np.asarray(p2.packed))
    assert losses.shape == (4,)


def test_rank_chunk_uses_pdist_and_matches_ball_dist():
    """VERDICT r3 #7: eval ranking flows through the fused distmat kernel;
    its ranks must equal the direct ball.dist formulation."""
    import numpy as np
    import jax.numpy as jnp
    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.models import poincare_embed as pe

    rng = np.random.default_rng(0)
    c = 1.0
    n, d, b = 64, 5, 16
    v = rng.standard_normal((n, d)) * 0.3
    table = jnp.asarray(v / (1.0 + np.linalg.norm(v, axis=1, keepdims=True)),
                        jnp.float32)
    u_idx = jnp.asarray(rng.integers(0, n, b), jnp.int32)
    v_idx = jnp.asarray(rng.integers(0, n, b), jnp.int32)
    got = pe._rank_chunk(table, u_idx, v_idx, c)

    ball = PoincareBall(c)
    u = table[u_idx]
    d_all = ball.dist(u[:, None, :], table[None, :, :])
    d_pos = jnp.take_along_axis(d_all, v_idx[:, None], axis=1)
    closer = (d_all < d_pos).astype(jnp.int32)
    closer = closer.at[jnp.arange(b), u_idx].set(0)
    closer = closer.at[jnp.arange(b), v_idx].set(0)
    want = jnp.sum(closer, axis=1) + 1
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
