"""Regression tests for the measured failure modes the CLI defaults must
not ship (VERDICT r3 "what's weak" #1–2).

docs/benchmarks.md measured two cliffs at the shared full-graph default
lr=1e-2: the sampled minibatch arm oscillates (val acc 0.3–0.76 swings)
and the attention arm collapses 2-of-3 seeds to the degenerate logits-0
solution.  The fix is mode-aware defaults (lr 3e-3 for both modes,
grad-norm clip 1.0 for attention) built in ``cli.train.hgcn_mode_defaults``
— these tests pin (a) the defaults themselves and (b) that training with
them neither collapses nor oscillates on small-scale proxies.
"""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from hyperspace_tpu.cli.train import hgcn_mode_defaults
from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.models import hgcn


def test_mode_defaults_sampled_and_attention():
    base = hgcn.HGCNConfig(feat_dim=8)
    # full-graph mean mode keeps the plain defaults
    c = hgcn_mode_defaults(base, {}, sampled=False)
    assert c.lr == base.lr and c.clip_norm == 0.0
    # sampled → lr 3e-3, no clip
    c = hgcn_mode_defaults(base, {}, sampled=True)
    assert c.lr == 3e-3 and c.clip_norm == 0.0
    # attention → lr 3e-3 + clip 1.0
    c = hgcn_mode_defaults(base, {"use_att": "true"}, sampled=False)
    assert c.lr == 3e-3 and c.clip_norm == 1.0
    # explicit user overrides always win (apply_overrides runs after
    # hgcn_mode_defaults, so the base value it sets must defer)
    c = hgcn_mode_defaults(base, {"use_att": "true", "lr": "0.02",
                                  "clip_norm": "0"}, sampled=False)
    assert c.lr == base.lr and c.clip_norm == 0.0  # untouched base


def test_clip_norm_clips_global_gradient():
    cfg = hgcn.HGCNConfig(feat_dim=8, clip_norm=1.0, weight_decay=0.0)
    opt = hgcn.make_optimizer(cfg)
    params = {"w": jnp.zeros((4,))}
    state = opt.init(params)
    huge = {"w": jnp.full((4,), 1e6)}
    updates, _ = opt.update(huge, state, params)
    # adam normalizes per-coordinate; the clip must have run first, which
    # we observe via optax.clip_by_global_norm on its own
    clip = optax.clip_by_global_norm(cfg.clip_norm)
    clipped, _ = clip.update(huge, clip.init(params), params)
    assert float(optax.global_norm(clipped)) <= cfg.clip_norm + 1e-6
    assert all(bool(jnp.all(jnp.isfinite(u))) for u in updates.values())


@pytest.mark.slow
def test_attention_defaults_do_not_collapse():
    """With the shipped attention defaults (lr 3e-3 + clip 1.0) a
    multi-seed small-scale LP run must train to a real plateau — no seed
    may end at the degenerate solution (AUC ≈ 0.5, the measured collapse
    signature)."""
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=256, feat_dim=16,
                                                seed=0)
    split = G.split_edges(edges, 256, x, seed=0, pad_multiple=256)
    base = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 8), use_att=True)
    cfg = hgcn_mode_defaults(base, {"use_att": "true"}, sampled=False)
    assert cfg.lr == 3e-3 and cfg.clip_norm == 1.0
    for seed in (0, 1, 2):
        model, params, _ = hgcn.train_lp(cfg, split, steps=300, seed=seed)
        res = hgcn.evaluate_lp(model, params, split, "val")
        assert res["roc_auc"] > 0.75, (seed, res)


@pytest.mark.slow
def test_sampled_defaults_do_not_oscillate():
    """With the shipped sampled default (lr 3e-3) the tail of a sampled-NC
    run must sit near its best — the lr=1e-2 failure signature was
    train-quality swinging by >0.4 between adjacent evals."""
    from hyperspace_tpu.models import hgcn_sampled as HS

    n, k = 512, 4
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=n, feat_dim=16,
                                                num_classes=k, seed=0)
    tr, va, te = G.node_split_masks(n, seed=0)
    base = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 16), num_classes=k)
    cfg = hgcn_mode_defaults(base, {}, sampled=True)
    assert cfg.lr == 3e-3
    scfg = HS.SampledConfig(base=cfg, fanouts=(5, 5), batch_size=64)
    model, opt, state = HS.init_sampled_nc(scfg, feat_dim=16, seed=0)
    batches, deg = HS.plan_batches(scfg, edges, labels, tr, n, steps=64,
                                   seed=0)
    xt = jnp.asarray(np.asarray(x, np.float32))
    g = G.prepare(edges, n, x, labels=labels, num_classes=k,
                  train_mask=tr, val_mask=va, test_mask=te)
    full = hgcn.HGCNNodeClf(cfg)
    accs = []
    for step in range(320):
        state, loss = HS.train_step_sampled_nc(model, opt, state, xt, deg,
                                               batches)
        if step >= 160 and step % 32 == 31:  # tail evals only
            accs.append(hgcn.evaluate_nc(full, state.params, g)["val_acc"])
    accs = np.asarray(accs)
    assert accs.max() - accs.min() < 0.25, accs  # 1e-2 swung by >0.4
    assert accs[-1] > 0.5, accs  # and it actually learned (chance 0.25)
