"""Workload-2 integration tests (SURVEY.md §4.7): HGCN link prediction on a
synthetic hierarchy reaches high ROC-AUC; node classification beats chance
by a wide margin; graph prep invariants hold."""

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.models import hgcn
from hyperspace_tpu.utils.metrics import roc_auc


def test_roc_auc_known_values():
    assert roc_auc(np.asarray([2.0, 3.0]), np.asarray([0.0, 1.0])) == 1.0
    assert roc_auc(np.asarray([0.0, 1.0]), np.asarray([2.0, 3.0])) == 0.0
    # ties count half
    assert roc_auc(np.asarray([1.0]), np.asarray([1.0])) == 0.5
    # matches a hand computation with mixed ranks
    a = roc_auc(np.asarray([0.9, 0.4]), np.asarray([0.5, 0.1]))
    assert abs(a - 0.75) < 1e-12


def test_prepare_pads_and_symmetrizes():
    edges = np.asarray([[0, 1], [1, 2]])
    x = np.zeros((4, 3), np.float32)
    g = G.prepare(edges, 4, x, pad_multiple=16)
    assert g.senders.shape == (16,)
    es = {(int(u), int(v)) for u, v, m in zip(g.senders, g.receivers, g.edge_mask) if m}
    # symmetrized + self loops
    assert (1, 0) in es and (0, 1) in es and (2, 2) in es
    assert g.num_edges == 4 + 4  # 4 directed edges + 4 self loops


def test_split_edges_no_leak():
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=200, seed=1)
    split = G.split_edges(edges, 200, x, seed=1, pad_multiple=64)
    held = {tuple(e) for e in np.vstack([split.val_pos, split.test_pos])}
    train_dir = {
        (int(u), int(v))
        for u, v, m in zip(split.graph.senders, split.graph.receivers, split.graph.edge_mask)
        if m and u != v
    }
    for u, v in held:
        assert (u, v) not in train_dir and (v, u) not in train_dir
    # negatives are non-edges
    es = {tuple(sorted(e)) for e in edges}
    for u, v in split.test_neg:
        assert tuple(sorted((int(u), int(v)))) not in es


@pytest.mark.slow
def test_hyperbolic_not_worse_than_euclidean_control_on_hierarchy():
    """VERDICT r1 #4a: the same HGCConv stack with kind="euclidean" is a
    plain GCN; on hierarchical data the hyperbolic model must not lose
    (scripts/euclidean_control.py measured +0.012 mean AUC over 3 seeds
    at 4k nodes — this pins one smaller config with slack for noise)."""
    aucs = {}
    for kind in ("lorentz", "euclidean"):
        edges, x, labels, k = G.synthetic_hierarchy(
            num_nodes=1024, feat_dim=16, ancestor_hops=4, seed=1)
        split = G.split_edges(edges, 1024, x, seed=1)
        cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(64, 16), kind=kind)
        model, params, _ = hgcn.train_lp(cfg, split, steps=300, seed=1)
        aucs[kind] = hgcn.evaluate_lp(model, params, split, "test")["roc_auc"]
    assert aucs["lorentz"] >= aucs["euclidean"] - 0.01, aucs


@pytest.mark.slow
def test_hgcn_link_prediction_converges():
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=256, feat_dim=16, seed=0)
    split = G.split_edges(edges, 256, x, seed=0, pad_multiple=256)
    cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 8), lr=5e-3, neg_per_pos=1)
    model, params, _ = hgcn.train_lp(cfg, split, steps=300, seed=0)
    res = hgcn.evaluate_lp(model, params, split, "test")
    assert res["roc_auc"] > 0.85, res


@pytest.mark.slow
def test_hgcn_planned_lp_step_converges_to_same_quality():
    """The planned fast path (graph-edge positives + corrupt-v negatives,
    train_step_lp_planned) must reach the same test ROC-AUC band as the
    standard step — it changes the scatter layout and the negative
    sampler, not the learning problem."""
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=256, feat_dim=16, seed=0)
    split = G.split_edges(edges, 256, x, seed=0, pad_multiple=256)
    cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 8), lr=5e-3)
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    dg = G.to_device(split.graph)
    n_neg = split.train_pos.shape[0]
    neg_u, neg_plan = hgcn.make_static_negatives(256, n_neg, seed=0)
    for _ in range(300):
        state, loss = hgcn.train_step_lp_planned(
            model, opt, 256, state, dg, neg_u, neg_plan)
    assert bool(jnp.isfinite(loss))
    res = hgcn.evaluate_lp(model, state.params, split, "test")
    assert res["roc_auc"] > 0.85, res


@pytest.mark.slow
def test_hgcn_node_classification_converges():
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=256, feat_dim=16, num_classes=4, seed=0)
    tr, va, te = G.node_split_masks(256, seed=0)
    g = G.prepare(edges, 256, x, pad_multiple=256,
                  labels=labels, num_classes=k,
                  train_mask=tr, val_mask=va, test_mask=te)
    cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 16), num_classes=k, lr=1e-2)
    model, params, res = hgcn.train_nc(cfg, g, steps=200, seed=0)
    assert res["test_acc"] > 0.7, res  # 4 classes → chance = 0.25


@pytest.mark.slow
def test_hgcn_learned_curvature_trains():
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=128, feat_dim=8, seed=2)
    split = G.split_edges(edges, 128, x, seed=2, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=8, hidden_dims=(16, 8), learn_c=True, use_att=True)
    model, params, _ = hgcn.train_lp(cfg, split, steps=60, seed=0)
    res = hgcn.evaluate_lp(model, params, split, "val")
    assert np.isfinite(res["roc_auc"])
    # curvature moved off its init
    c_raw = float(params["encoder"]["conv0"]["c_raw"])
    assert np.isfinite(c_raw)


def test_train_step_lp_pairs_smoke():
    """Fully-planned-pairs step (VERDICT r1 #6) runs and reduces loss."""
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=192, feat_dim=12,
                                                seed=0)
    split = G.split_edges(edges, 192, x, seed=0, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8))
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = hgcn._device_graph(split.graph)
    pos = hgcn.make_planned_pairs(split.train_pos, 192)
    neg_u, neg_plan = hgcn.make_static_negatives(192, pos.u.shape[0], seed=0)
    losses = []
    for _ in range(25):
        state, loss = hgcn.train_step_lp_pairs(
            model, opt, 192, state, ga, pos, neg_u, neg_plan)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_train_step_lp_pairs_reaches_auc():
    edges, x, labels, k = G.synthetic_hierarchy(num_nodes=512, feat_dim=16,
                                                seed=0)
    split = G.split_edges(edges, 512, x, seed=0, pad_multiple=512)
    cfg = hgcn.HGCNConfig(feat_dim=16, hidden_dims=(32, 8))
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = hgcn._device_graph(split.graph)
    pos = hgcn.make_planned_pairs(split.train_pos, 512)
    neg_u, neg_plan = hgcn.make_static_negatives(512, pos.u.shape[0], seed=0)
    for _ in range(300):
        state, loss = hgcn.train_step_lp_pairs(
            model, opt, 512, state, ga, pos, neg_u, neg_plan)
    res = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
    assert res["roc_auc"] > 0.85, res


def test_remat_matches_default():
    """cfg.remat re-runs each conv in the backward; losses and gradients
    must match the default step exactly (same math, less live memory)."""
    import dataclasses

    from hyperspace_tpu.data import graphs as G

    edges, x, labels, ncls = G.synthetic_hierarchy(num_nodes=192, feat_dim=12,
                                                   seed=0)
    split = G.split_edges(edges, 192, x, seed=0, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=12, hidden_dims=(16, 8))
    ga = G.to_device(split.graph)
    pos = jnp.asarray(split.train_pos)

    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    for _ in range(2):
        state, loss = hgcn.train_step_lp(model, opt, 192, state, ga, pos)

    cfg_r = dataclasses.replace(cfg, remat=True)
    model_r = hgcn.HGCNLinkPred(cfg_r)
    _, _, state_r = hgcn.init_lp(cfg_r, split.graph, seed=0)
    for _ in range(2):
        state_r, loss_r = hgcn.train_step_lp(model_r, opt, 192, state_r, ga,
                                             pos)
    import jax

    np.testing.assert_allclose(float(loss_r), float(loss), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        state.params, state_r.params)


def test_remat_rejects_learned_curvature():
    from hyperspace_tpu.data import graphs as G

    edges, x, *_ = G.synthetic_hierarchy(num_nodes=128, feat_dim=8, seed=0)
    split = G.split_edges(edges, 128, x, seed=0, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=8, hidden_dims=(8,), remat=True,
                          learn_c=True)
    with pytest.raises(ValueError, match="remat"):
        hgcn.init_lp(cfg, split.graph, seed=0)
