"""Workload-5 integration tests: mixed-curvature embeddings with learned
curvature train (single-device and on a host×data mesh), curvatures move,
points stay on-manifold (SURVEY.md §4.6/§4.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import product_embed as pme
from hyperspace_tpu.parallel.mesh import make_mesh


def _cfg(n, **kw):
    return pme.ProductEmbedConfig(
        num_nodes=n,
        factors=(("poincare", 4), ("sphere", 3), ("euclidean", 2)),
        batch_size=64, neg_samples=8, burnin_steps=20, **kw)


@pytest.mark.slow
def test_build_manifold_curvature_grad():
    cfg = _cfg(8)
    c_raw = jnp.zeros((2,))

    def f(c_raw):
        m = pme.build_manifold(cfg, c_raw)
        x = m.random_normal(jax.random.PRNGKey(0), (4, cfg.total_dim), jnp.float64)
        return jnp.sum(m.dist(x[:2], x[2:]))

    g = jax.grad(f)(c_raw)
    assert g.shape == (2,)
    assert bool(jnp.isfinite(g).all()) and float(jnp.abs(g).sum()) > 0


def test_init_on_manifold():
    cfg = _cfg(32)
    state, _ = pme.init_state(cfg, seed=0)
    m = pme.build_manifold(cfg, state.params.c_raw)
    assert float(jnp.max(m.check_point(state.params.table))) < 1e-5


@pytest.mark.slow
def test_product_embed_trains_and_curvature_moves():
    ds = synthetic_tree(depth=3, branching=2)
    cfg = _cfg(ds.num_nodes, lr_table=0.5, lr_curv=5e-3)
    state, curv_opt = pme.init_state(cfg, seed=0)
    pairs = jnp.asarray(ds.pairs)
    c0 = pme.curvatures(cfg, state.params)
    loss0 = None
    for i in range(800):
        state, loss = pme.train_step(cfg, curv_opt, state, pairs)
        if loss0 is None:
            loss0 = float(loss)
    m = pme.build_manifold(cfg, state.params.c_raw)
    assert float(jnp.max(m.check_point(state.params.table))) < 1e-3
    assert float(loss) < loss0
    c1 = pme.curvatures(cfg, state.params)
    assert any(abs(a - b) > 1e-4 for a, b in zip(c0, c1)), (c0, c1)
    res = pme.evaluate(cfg, state.params, ds.pairs)
    assert res["map"] > 0.8, res


@pytest.mark.slow
def test_product_embed_sharded_matches_axes():
    """host×data mesh (DCN axis modeled by the leading axis): step runs,
    loss finite, state stays replicated."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"host": 2, "data": 4})
    ds = synthetic_tree(depth=3, branching=2)
    cfg = _cfg(ds.num_nodes)
    state, curv_opt = pme.init_state(cfg, seed=0)
    step = pme.make_sharded_step(cfg, curv_opt, mesh)
    pairs = jnp.asarray(ds.pairs)
    for _ in range(5):
        state, loss = step(state, pairs)
    assert bool(jnp.isfinite(loss))
    m = pme.build_manifold(cfg, state.params.c_raw)
    assert float(jnp.max(m.check_point(state.params.table))) < 1e-4
