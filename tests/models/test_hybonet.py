"""Workload-3 integration tests: HyboNet learns a synthetic text-clf task."""

import numpy as np
import pytest

from hyperspace_tpu.data import text as T
from hyperspace_tpu.models import hybonet


def test_synthetic_text_shapes():
    ds = T.synthetic_text(num_samples=64, vocab_size=128, max_len=16)
    assert ds.tokens.shape == (64, 16)
    assert ds.mask.shape == (64, 16)
    assert ds.tokens.max() < 128
    assert (ds.tokens[~ds.mask] == T.PAD_ID).all()
    tr, te = ds.split(0.75)
    assert len(tr.labels) == 48 and len(te.labels) == 16


def test_tsv_loader(tmp_path):
    p = tmp_path / "toy.tsv"
    p.write_text("pos\tgood great fine\nneg\tbad awful bad\npos\tgood\n")
    ds = T.load_tsv(str(p), max_len=4)
    assert ds.num_classes == 2
    assert ds.tokens.shape == (3, 4)
    # 'bad' appears twice → in vocab; both 'bad' tokens share an id ≥ 2
    assert ds.tokens[1][0] == ds.tokens[1][2] >= 2


@pytest.mark.slow
def test_hybonet_learns_classification():
    ds = T.synthetic_text(num_samples=512, vocab_size=128, num_classes=3,
                          max_len=16, seed=0)
    tr, te = ds.split(0.8, seed=0)
    cfg = hybonet.HyboNetConfig(
        vocab_size=128, num_classes=3, max_len=16, dim=16,
        num_heads=2, num_layers=1, lr=3e-3, batch_size=64)
    model, params, loss = hybonet.train(cfg, tr, steps=150, seed=0)
    assert np.isfinite(loss)
    res = hybonet.evaluate(model, params, te)
    assert res["accuracy"] > 0.7, res  # 3 classes → chance 0.33


@pytest.mark.slow
def test_hybonet_tiled_attention_parity():
    """Same params, tiled vs dense attention → identical logits."""
    import dataclasses
    import jax.numpy as jnp

    ds = T.synthetic_text(num_samples=8, vocab_size=64, max_len=12, seed=1)
    cfg = hybonet.HyboNetConfig(vocab_size=64, num_classes=4, max_len=12,
                                dim=8, num_heads=2, num_layers=1)
    model, _, state = hybonet.init_model(cfg, seed=0)
    logits_dense = hybonet.eval_logits(
        model, state.params, jnp.asarray(ds.tokens), jnp.asarray(ds.mask))
    cfg_t = dataclasses.replace(cfg, attention_impl="scan")
    model_t = hybonet.HyboNetClassifier(cfg_t)
    logits_tiled = hybonet.eval_logits(
        model_t, state.params, jnp.asarray(ds.tokens), jnp.asarray(ds.mask))
    # f32 forward: online-softmax reassociation costs a few ulp
    np.testing.assert_allclose(
        np.asarray(logits_tiled), np.asarray(logits_dense), rtol=1e-5, atol=1e-6)


def test_default_config_executes_n7_kernel(monkeypatch):
    """The DEFAULT HyboNet config must route through the N7 flash-attention
    kernel (VERDICT r2 next #5): with kernels forced to interpret mode the
    Pallas launch is spied on and must fire once per block per step."""
    import jax.numpy as jnp

    import hyperspace_tpu.kernels.attention as KA

    monkeypatch.setenv("HYPERSPACE_KERNELS", "interpret")
    calls = []
    real_launch = KA._launch

    def spy(*args, **kw):
        calls.append(1)
        return real_launch(*args, **kw)

    monkeypatch.setattr(KA, "_launch", spy)

    ds = T.synthetic_text(num_samples=16, vocab_size=64, max_len=8, seed=0)
    cfg = hybonet.HyboNetConfig(vocab_size=64, num_classes=4, max_len=8,
                                dim=8, num_heads=2, num_layers=2,
                                batch_size=8)
    assert cfg.attention_impl == "flash"  # the default IS the kernel path
    model, opt, state = hybonet.init_model(cfg, seed=0)
    calls.clear()  # init traced the forward too; count the train step only
    state, loss = hybonet.train_step_sampled(
        model, opt, state, jnp.asarray(ds.tokens), jnp.asarray(ds.mask),
        jnp.asarray(ds.labels))
    assert np.isfinite(float(loss))
    # one Pallas launch per transformer block in the forward trace
    # (backward uses the XLA twin by design — kernels/attention.py VJP)
    assert len(calls) == cfg.num_layers
