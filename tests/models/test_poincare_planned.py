"""Host-planned sparse Poincaré updates (VERDICT r2 next #2).

`train_step_sparse_planned` must be mathematically identical to the dense
update on the same batch — duplicate occurrences of a row sum their
cotangents before the single expmap — while containing no device sort, no
searchsorted, and no unsorted scatter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from hyperspace_tpu.data.wordnet import synthetic_tree
from hyperspace_tpu.models import poincare_embed as pe

_DS = synthetic_tree(depth=3, branching=3)


def _cfg(**kw):
    base = dict(num_nodes=_DS.num_nodes, dim=5, lr=0.5, neg_samples=4,
                batch_size=16, burnin_steps=0)
    base.update(kw)
    return pe.PoincareEmbedConfig(**base)


def _indices_with_duplicates(cfg, seed=0):
    """A batch that deliberately repeats rows (as u, as v, as negatives)."""
    rng = np.random.default_rng(seed)
    b, k = cfg.batch_size, cfg.neg_samples
    u = rng.integers(0, cfg.num_nodes, (1, b))
    u[0, 1] = u[0, 0]  # duplicate query
    v = rng.integers(0, cfg.num_nodes, (1, b))
    v[0, 2] = u[0, 0]  # row appears as both u and v
    neg = rng.integers(0, cfg.num_nodes, (1, b, k))
    neg[0, 0, 0] = u[0, 0]  # and as a negative (collision-masked in loss)
    neg[0, 3, 1] = neg[0, 3, 0]  # duplicate negative within a row
    return u, v, neg


def test_plan_invariants():
    cfg = _cfg()
    u, v, neg = _indices_with_duplicates(cfg)
    plan = pe.plan_from_indices(cfg, u, v, neg)
    uniq = np.asarray(plan.uniq[0])
    inv = np.asarray(plan.inv_map[0])
    order = np.asarray(plan.order[0])
    seg = np.asarray(plan.seg_sorted[0])
    flat = np.concatenate([u[0], v[0], neg[0].reshape(-1)])
    # uniq: ascending, sentinel-padded with num_nodes
    n_real = len(np.unique(flat))
    assert np.all(np.diff(uniq[:n_real]) > 0)
    assert np.all(uniq[n_real:] == cfg.num_nodes)
    # inv_map reconstructs the flat ids through uniq
    np.testing.assert_array_equal(uniq[inv], flat)
    # seg_sorted = inv_map[order], ascending
    np.testing.assert_array_equal(seg, inv[order])
    assert np.all(np.diff(seg) >= 0)


@pytest.mark.parametrize("optimizer", ["rsgd", "radam"])
def test_planned_step_matches_dense_update(optimizer):
    """One planned step == the dense update on the identical batch.

    For radam this holds exactly from a fresh state (zero moments: rows
    with zero grad get zero update, so dense touches only batch rows too).
    """
    cfg = _cfg(optimizer=optimizer, lr=0.1)
    u, v, neg = _indices_with_duplicates(cfg)
    plan = pe.plan_from_indices(cfg, u, v, neg)
    state, opt = pe.init_state(cfg, seed=0)

    # dense reference on the same indices
    loss_d, grads = jax.value_and_grad(pe.loss_fn)(
        state.table, jnp.asarray(u[0]), jnp.asarray(v[0]), jnp.asarray(neg[0]),
        cfg.c)
    updates, _ = opt.update(grads, state.opt_state, state.table)
    table_dense = optax.apply_updates(state.table, updates)

    state2, _ = pe.init_state(cfg, seed=0)
    state2, loss_p = pe.train_step_sparse_planned(cfg, opt, state2, plan)

    np.testing.assert_allclose(float(loss_p), float(loss_d), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(state2.table),
                               np.asarray(table_dense), rtol=1e-5, atol=1e-6)


def test_planned_multi_step_matches_dense_rsgd():
    """S planned steps == S dense updates on the same planned batches
    (rsgd: sparse and dense are mathematically identical row-wise)."""
    cfg = _cfg(optimizer="rsgd", lr=0.3, burnin_steps=2)
    plan = pe.plan_sparse_steps(cfg, _DS.pairs, steps=4, seed=7)
    state, opt = pe.init_state(cfg, seed=1)
    table = state.table
    opt_state = state.opt_state
    for i in range(4):
        loss, grads = jax.value_and_grad(pe.loss_fn)(
            table, plan.u_idx[i], plan.v_idx[i], plan.neg_idx[i], cfg.c)
        updates, opt_state = opt.update(grads, opt_state, table)
        table = optax.apply_updates(table, updates)

    state2, _ = pe.init_state(cfg, seed=1)
    for _ in range(4):
        state2, loss_p = pe.train_step_sparse_planned(cfg, opt, state2, plan)

    np.testing.assert_allclose(np.asarray(state2.table), np.asarray(table),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_planned_radam_converges():
    cfg = _cfg(optimizer="radam", lr=0.05, batch_size=128, neg_samples=10)
    plan = pe.plan_sparse_steps(cfg, _DS.pairs, steps=250, seed=0)
    state, opt = pe.init_state(cfg, seed=0)
    for _ in range(1500):  # cycles through the 250 planned batches
        state, loss = pe.train_step_sparse_planned(cfg, opt, state, plan)
    res = pe.evaluate(state.table, _DS.pairs, cfg.c)
    assert np.isfinite(float(loss))
    assert res["map"] >= 0.85, res
    assert np.linalg.norm(np.asarray(state.table), axis=-1).max() < 1.0


@pytest.mark.parametrize("optimizer", ["rsgd", "radam"])
def test_packed_step_matches_planned(optimizer):
    """The one-scatter packed variant is the same math as the planned
    step (and therefore as the dense update) on identical batches."""
    cfg = _cfg(optimizer=optimizer, lr=0.1)
    plan = pe.plan_sparse_steps(cfg, _DS.pairs, steps=3, seed=3)
    # independent states: the steps donate their inputs, and pack_state
    # aliases the table buffer for stateless-row optimizers
    ref, opt = pe.init_state(cfg, seed=0)
    state, _ = pe.init_state(cfg, seed=0)
    pstate = pe.pack_state(cfg, state)
    for _ in range(3):
        ref, loss_ref = pe.train_step_sparse_planned(cfg, opt, ref, plan)
        pstate, loss_p = pe.train_step_planned_packed(cfg, opt, pstate, plan)
    np.testing.assert_allclose(float(loss_p), float(loss_ref), rtol=1e-6)
    got = pe.unpack_state(cfg, pstate)
    np.testing.assert_allclose(np.asarray(got.table), np.asarray(ref.table),
                               rtol=1e-6, atol=1e-7)
    if optimizer == "radam":
        np.testing.assert_allclose(np.asarray(got.opt_state.mu),
                                   np.asarray(ref.opt_state.mu),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(got.opt_state.nu),
                                   np.asarray(ref.opt_state.nu),
                                   rtol=1e-6, atol=1e-7)
    # pack/unpack round-trips a fresh state exactly
    fresh, _ = pe.init_state(cfg, seed=5)
    rt = pe.unpack_state(cfg, pe.pack_state(cfg, fresh))
    np.testing.assert_array_equal(np.asarray(rt.table),
                                  np.asarray(fresh.table))
